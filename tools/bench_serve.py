"""Serving throughput/latency microbench (rows/s, p50/p99 ms).

Trains a small model, stands up the in-process serve stack
(``serve.Server``: micro-batcher + bucketed predictor engine) and
hammers it from concurrent client threads for a fixed duration,
measuring client-observed request latency.  The numbers fold into
``bench.py`` extras as ``serve_rows_per_s`` / ``serve_p99_ms``
(docs/Serving.md records the capture discipline).

Run standalone::

    python tools/bench_serve.py [key=value ...]
      duration_s=3 clients=4 rows_per_request=64 serve_max_batch=1024
      http=0 n_train=20000 n_feat=28 device=0

``device=1`` measures the fused device-resident path
(``serve_device_binning``; bench.py folds it in as
``serve_device_rows_per_s`` / ``serve_device_p99_ms``).  Prints one
JSON line with the measured point.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def build_model(n_train: int = 20000, n_feat: int = 28, seed: int = 0,
                num_leaves: int = 31, rounds: int = 50):
    """HIGGS-shaped binary model (bench.py's data family)."""
    import lightgbm_tpu as lgb
    rs = np.random.RandomState(seed)
    x = rs.randn(n_train, n_feat).astype(np.float32)
    y = ((1.2 * x[:, 0] - 0.8 * x[:, 1] + 0.6 * x[:, 2] * x[:, 3]
          + 0.5 * rs.randn(n_train)) > 0).astype(np.float32)
    ds = lgb.Dataset(x, label=y)
    return lgb.train({"objective": "binary", "num_leaves": num_leaves,
                      "verbosity": -1}, ds, num_boost_round=rounds)


def run_bench(booster=None, duration_s: float = 3.0, clients: int = 4,
              rows_per_request: int = 64, http: bool = False,
              params: dict | None = None, n_train: int = 20000,
              n_feat: int = 28, device_binning: bool = False) -> dict:
    """Drive the serve stack; returns the measured point as a dict.

    ``device_binning=True`` measures the FUSED device-resident path
    (``serve_device_binning``: one jit, one sync per batch) — reported
    by bench.py as ``serve_device_rows_per_s`` / ``serve_device_p99_ms``
    next to the host-accumulation numbers."""
    from lightgbm_tpu.serve import Server, start_http
    if booster is None:
        booster = build_model(n_train=n_train, n_feat=n_feat)
    nf = booster.num_feature()
    srv_params = dict(params or {})
    if device_binning:
        srv_params.setdefault("serve_device_binning", True)
    srv = Server(srv_params, booster=booster)
    fe = start_http(srv, port=0) if http else None
    rs = np.random.RandomState(1)
    pool = rs.randn(4096, nf)

    lat: list = []
    rows_done = [0]
    lock = threading.Lock()
    stop = threading.Event()

    def _client(cid: int):
        local_lat, local_rows = [], 0
        url = (f"http://127.0.0.1:{fe.port}/predict" if http else None)
        while not stop.is_set():
            lo = (cid * 131 + len(local_lat) * rows_per_request) % \
                (len(pool) - rows_per_request)
            rows = pool[lo:lo + rows_per_request]
            t0 = time.perf_counter()
            if http:
                import urllib.request
                req = urllib.request.Request(
                    url, data=json.dumps({"rows": rows.tolist()}).encode(),
                    headers={"Content-Type": "application/json"})
                json.loads(urllib.request.urlopen(req).read())
            else:
                srv.predict(rows, timeout=30)
            local_lat.append(time.perf_counter() - t0)
            local_rows += len(rows)
        with lock:
            lat.extend(local_lat)
            rows_done[0] += local_rows

    # warmup outside the window: every bucket the measured window can
    # hit compiles here (single requests, one request's rows, and the
    # largest coalesced batch the client pool can form)
    srv.predict(pool[:1])
    srv.predict(pool[:rows_per_request])
    srv.predict(pool[:min(len(pool), clients * rows_per_request)])

    threads = [threading.Thread(target=_client, args=(i,), daemon=True)
               for i in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(duration_s)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    wall = time.perf_counter() - t0
    snap = srv.metrics_snapshot()
    eng = snap.get("serve.engine", {})
    occ = snap.get("serve.batch_occupancy", {})
    if fe is not None:
        fe.close()
    srv.close()

    lat_ms = np.asarray(lat) * 1e3
    point = {
        "rows_per_s": round(rows_done[0] / max(wall, 1e-9), 1),
        "p50_ms": round(float(np.percentile(lat_ms, 50)), 3)
        if len(lat_ms) else None,
        "p99_ms": round(float(np.percentile(lat_ms, 99)), 3)
        if len(lat_ms) else None,
        "requests": int(len(lat_ms)),
        "clients": clients,
        "rows_per_request": rows_per_request,
        "http": bool(http),
        "device_binning": bool(device_binning),
        "batch_occupancy_mean": round(occ["sum"] / occ["count"], 4)
        if occ.get("count") else None,
        "engine_buckets": sorted(
            int(b) for b in (eng.get("fused_buckets")
                             if device_binning else eng.get("buckets"))
            or {}),
        "compile_bound": eng.get("max_compiles_bound"),
        "fused_batches": int(snap.get("serve.fused_batches", {})
                             .get("value", 0)),
        "host_fallback_batches": int(
            snap.get("serve.host_fallback_batches", {}).get("value", 0)),
        "table_bytes": eng.get("table_bytes"),
    }
    return point


def main() -> int:
    kv = dict(tok.split("=", 1) for tok in sys.argv[1:] if "=" in tok)
    serve_params = {k: v for k, v in kv.items()
                    if k.startswith("serve_")}
    device = kv.get("device", "0") not in ("0", "false", "")
    point = run_bench(
        duration_s=float(kv.get("duration_s", 3.0)),
        clients=int(kv.get("clients", 4)),
        rows_per_request=int(kv.get("rows_per_request", 64)),
        http=kv.get("http", "0") not in ("0", "false", ""),
        params=serve_params,
        n_train=int(kv.get("n_train", 20000)),
        n_feat=int(kv.get("n_feat", 28)),
        device_binning=device)
    metric = "serve_device_rows_per_s" if device else "serve_rows_per_s"
    print(json.dumps({"metric": metric, **point}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
