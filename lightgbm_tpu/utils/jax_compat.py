"""Version compatibility for JAX APIs the learners depend on.

The distributed learners target the stable ``jax.shard_map`` entry point
(with its ``check_vma`` argument); older JAX releases only ship
``jax.experimental.shard_map.shard_map`` (whose equivalent argument is
``check_rep``).  Every shard_map construction in this package routes
through :func:`shard_map` below so the learners run on both.
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` when available, else the experimental spelling
    with ``check_vma`` translated to ``check_rep``."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=check_vma)
