#!/bin/bash
# Restart the TPU capture watcher (tools/tpu_watch.py) safely: the
# pattern lives in this FILE, not the caller's command line, so pkill
# can't match the invoking shell.  Never touches probe/bench children
# (claim holders must not be killed — see tpu_watch.py docstring).
cd "$(dirname "$0")/.."
for pid in $(pgrep -f "tpu_watch\.py --deadline"); do
    kill "$pid" 2>/dev/null
done
sleep 1
nohup python tools/tpu_watch.py --deadline-hours "${1:-10}" \
    > /dev/null 2>&1 &
echo "watcher restarted (pid $!)"
