"""Versioned model registry with atomic hot swap.

Serving must outlive any single model file: the registry holds
(version -> :class:`ServedModel`) where each entry pairs a loaded
``Booster`` with its compiled :class:`~.engine.PredictorEngine`, and an
atomic "current" pointer.  ``activate`` swaps the pointer under a lock
— a reader that already resolved :meth:`current` keeps its handle, so
in-flight requests finish on the version they started on while new
requests pick up the swap (the hot-reload contract, docs/Serving.md).

Models load from model files / strings / live Boosters, or from
``snapshot.py`` training snapshots: :meth:`load_snapshot` picks the
newest snapshot of an ``output_model`` whose manifest is present and
parseable (the manifest-written-last marker of a COMPLETE snapshot) —
serving has no training dataset, so the params-signature and
data-fingerprint checks that gate training auto-resume do not apply.

Artifacts are VERIFIED before activation (``verify_artifacts``):
snapshot/file loads check SHA-256 against the manifest's recorded
checksum (:class:`ArtifactVerificationError` on mismatch — the current
version keeps serving), and a freshly built engine must pass its
byte-parity ``self_check`` probe or serving falls back to the host
walk.  A failed ``load`` of any kind leaves the registry untouched.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional


class NoModelError(RuntimeError):
    """The registry has no active model."""


class ArtifactVerificationError(RuntimeError):
    """A model artifact failed checksum verification — refused, never
    activated (the current version keeps serving)."""


class ServedModel:
    """One immutable (version, booster, engine) serving unit.

    Carries an IN-FLIGHT request counter (``begin_request`` /
    ``end_request``, bracketed around every batch the server runs on
    this version): the residency-cap eviction skips versions with
    requests in flight.  This is residency ACCOUNTING, not a
    use-after-free guard — the batch's own reference keeps the model
    alive regardless; the counter keeps a mid-batch version registered
    (addressable, its device tables resident) so a swap back to it
    never pays a re-upload the cap bookkeeping thought it had
    reclaimed.  ``self_check_failed`` records
    that the engine's byte-parity probe FAILED at load (as opposed to
    the engine being unsupported) — the continual promotion gate refuses
    such candidates outright where plain serving merely demotes them to
    the host walk.

    Lock contract (tools/analyze/check_races.py):
        _iflock guards: _inflight

    Everything else on a ServedModel is immutable after registration
    (``registry.load`` publishes it under the registry lock)."""

    __slots__ = ("version", "booster", "engine", "source", "loaded_at",
                 "self_check_failed", "sha256", "_inflight", "_iflock")

    def __init__(self, version: str, booster, engine, source: str):
        self.version = version
        self.booster = booster
        self.engine = engine
        self.source = source
        self.loaded_at = time.time()
        self.self_check_failed = False
        # the verified artifact checksum this version was loaded under
        # (None for live boosters / unpinned loads) — the continual
        # gate uses it to decide whether the serving incumbent IS the
        # snapshot a candidate boosted from (lineage applicability)
        self.sha256: "str | None" = None
        self._inflight = 0
        self._iflock = threading.Lock()

    def begin_request(self) -> None:
        with self._iflock:
            self._inflight += 1

    def end_request(self) -> None:
        with self._iflock:
            self._inflight -= 1

    @property
    def inflight(self) -> int:
        # locked read: a torn read is impossible for a GIL int, but the
        # registry's eviction decision ("may I drop this version?")
        # must observe a count that is current with respect to a
        # concurrent begin_request, not a stale register
        with self._iflock:
            return self._inflight

    def describe(self) -> dict:
        return {"version": self.version, "source": self.source,
                "loaded_at": self.loaded_at,
                "num_trees": len(self.booster.trees),
                "num_class": self.booster._num_tree_per_iteration,
                "num_features": self.booster.num_feature(),
                "inflight": self.inflight,
                "fingerprint": self.engine.fingerprint
                if self.engine is not None else None}


class ModelRegistry:
    """Versioned (version -> ServedModel) map with an atomic current
    pointer (module docstring).

    Lock contract (tools/analyze/check_races.py):
        _lock guards: _models, _current, _next_version

    ``_lock`` is leaf-level except for ``ServedModel._iflock``: the
    eviction scan reads ``inflight`` (which takes ``_iflock``) while
    holding ``_lock`` — that order (registry then model) is the ONLY
    sanctioned nesting; ServedModel methods never call back into the
    registry."""

    def __init__(self, *, max_batch: Optional[int] = None,
                 min_bucket: int = 16, build_engine: bool = True,
                 verify_artifacts: bool = True,
                 device_binning: bool = False, packed: bool = True,
                 max_resident: int = 0):
        self._models: Dict[str, ServedModel] = {}
        self._current: Optional[ServedModel] = None
        self._lock = threading.Lock()
        self._next_version = 1
        self._engine_opts = {"max_batch": max_batch,
                             "min_bucket": min_bucket, "packed": packed}
        self._build_engine = build_engine
        self._verify = verify_artifacts
        # the server will serve via the f32 device-binning path
        # (serve_device_binning): self-checks must verify THAT path,
        # not just the host-binned one
        self._device_binning = device_binning
        # co-hosting cap (serve_max_resident): every registered version
        # keeps its engine — packed SoA tables — device-resident, so a
        # swap back to it needs no re-upload and (shapes matching,
        # utils/shapes.py pow2 SoA padding) no re-trace.  Past the cap,
        # loading evicts the oldest non-current version; the current
        # version and the load in hand are never candidates, so a
        # shadow load can exceed the cap by ONE until the next load or
        # swap (refusing it would be worse than a transient +1).
        # 0 = unlimited
        self._max_resident = max(0, int(max_resident))

    # -- loading -----------------------------------------------------------
    def load(self, model_file: Optional[str] = None,
             model_str: Optional[str] = None, booster=None,
             version: Optional[str] = None, source: str = "",
             activate: bool = True,
             expected_sha256: Optional[str] = None) -> str:
        """Load one model (exactly one of file / string / booster),
        register it, and (by default) atomically make it current.

        Verification (``verify_artifacts``, docs/Serving.md): with
        ``expected_sha256`` set, the model file's bytes must hash to it
        or the load raises :class:`ArtifactVerificationError` before
        anything is registered — a truncated, bit-rotted or
        wrong-version artifact can never be swapped in.  A freshly
        built engine must additionally pass its byte-parity
        ``self_check`` probe against the host tree walk, or it is
        discarded in favor of the (always-correct) host walk."""
        from ..booster import Booster
        from ..utils import faultinject
        from ..utils.log import Log
        # reload fault-injection site (tools/soak_serve.py chaos): a
        # failed load must leave the registry — and the current
        # version — exactly as they were
        faultinject.check("serve_reload")
        if sum(a is not None
               for a in (model_file, model_str, booster)) != 1:
            raise ValueError("load needs exactly one of model_file, "
                             "model_str, booster")
        if booster is not None and expected_sha256 is not None:
            # a live Booster has no byte artifact to hash — accepting
            # the pin silently would fake verification
            raise ValueError("expected_sha256 requires model_file or "
                             "model_str, not a live booster")
        if expected_sha256 is not None and not expected_sha256:
            # an empty pin is an unset variable in the caller's deploy
            # script, not a request to skip verification — falling
            # through to the unverified branch would fake enforcement
            raise ValueError("expected_sha256 must be a non-empty "
                             "SHA-256 hex digest (got '')")
        if booster is None:
            if expected_sha256:
                # an EXPLICIT pin is always enforced — verify_artifacts
                # gates only the automatic checks (snapshot-manifest
                # checksums, engine self-check); skipping a pin the
                # caller spelled out would fake verification.  A pinned
                # file is read ONCE: the bytes that hashed clean are the
                # bytes that get parsed, so a file swapped on disk after
                # the hash can never be activated unverified.
                from ..snapshot import sha256_hex
                if model_file is not None:
                    with open(model_file, "rb") as f:
                        data = f.read()
                    got = sha256_hex(data)
                else:
                    got = sha256_hex(model_str)
                if got != expected_sha256:
                    raise ArtifactVerificationError(
                        f"model artifact "
                        f"{model_file or '<model_str>'} checksum "
                        f"mismatch (got {got[:12]}…, expected "
                        f"{expected_sha256[:12]}…); refusing to load")
                if model_file is not None:
                    model_str = data.decode("utf-8")
                booster = Booster(model_str=model_str)
            else:
                booster = Booster(model_file=model_file,
                                  model_str=model_str)
            source = source or (model_file or "<model_str>")
        else:
            source = source or "<booster>"
        engine = None
        self_check_failed = False
        if self._build_engine:
            from .engine import EngineUnsupported, PredictorEngine
            try:
                engine = PredictorEngine.from_booster(booster,
                                                      **self._engine_opts)
                if self._verify:
                    try:
                        ok = engine.self_check(
                            device_binning=self._device_binning)
                    except Exception as e:  # noqa: BLE001 — a probe
                        # that cannot RUN (device blip during reload)
                        # must not fail a load the host walk can serve
                        Log.warning(f"serve: engine self-check errored "
                                    f"for {source} ({e}); treating as "
                                    "failed")
                        ok = False
                    if not ok:
                        # the compiled artifact disagrees with the
                        # model it came from (or could not be proven):
                        # never serve it — the host walk is the oracle
                        # the parity tests trust, fall back to it
                        Log.warning(
                            f"serve: engine self-check FAILED for "
                            f"{source}; discarding engine, serving via "
                            "host walk")
                        engine = None
                        self_check_failed = True
                        booster._engine_cache = False
            except EngineUnsupported as e:
                # an engine-unsupported model is still SERVABLE — the
                # batch path falls back to the host walk exactly like
                # Booster.predict does; only the bucketed cache is lost
                Log.warning(f"serve: bucketed engine unavailable for "
                            f"{source} ({e}); serving via host walk")
                booster._engine_cache = False
            else:
                # make this THE booster's predictor too: Booster.predict
                # on the serve path then rides the same bucketed cache,
                # and the engine's compile ledger (surfaced via
                # /metrics) sees every batch
                if engine is not None:
                    booster._engine_cache = engine
        with self._lock:
            if version is None:
                version = f"v{self._next_version}"
            self._next_version += 1
            if version in self._models:
                raise ValueError(f"model version {version!r} already "
                                 "registered")
            served = ServedModel(version, booster, engine, source)
            served.self_check_failed = self_check_failed
            served.sha256 = expected_sha256 or None
            self._models[version] = served
            if activate:
                # an explicit shadow load (activate=False) NEVER takes
                # traffic — not even into an empty registry: the gated
                # promotion relies on a refused candidate having served
                # zero requests, and an auto-activated shadow would
                # serve during the gate window (model-less registries
                # answer NoModelError until something activates)
                self._current = served
            if self._max_resident > 0:
                # evict oldest non-current versions past the residency
                # cap — the bound on co-hosted HBM footprint.  The
                # just-registered version is never an eviction
                # candidate: a shadow load (activate=False) at the cap
                # must displace an OLDER version, not itself.  Versions
                # with requests IN FLIGHT are skipped too — a batch that
                # resolved its handle must finish on the tables it is
                # traversing; such versions exceed the cap transiently
                # and become evictable at the next load
                others = sorted(
                    (m for m in self._models.values()
                     if m is not self._current and m is not served
                     and m.inflight == 0),
                    key=lambda m: m.loaded_at)
                while len(self._models) > self._max_resident and others:
                    self._models.pop(others.pop(0).version, None)
        return version

    def load_snapshot(self, output_model: str,
                      version: Optional[str] = None,
                      activate: bool = True,
                      expected_sha256: Optional[str] = None) -> str:
        """Load the newest COMPLETE snapshot of ``output_model``
        (manifest present + parseable + checksum-verified,
        snapshot.py).  The manifest's recorded ``model_sha256`` is also
        re-verified at load time, so a file swapped between the lookup
        and the read is still refused.  An explicit ``expected_sha256``
        pin takes precedence over the manifest's checksum: the caller
        vetted a specific artifact, and a snapshot that hashes clean
        against its own manifest but is not THAT artifact must be
        refused, not activated."""
        import json

        from ..snapshot import find_latest_complete_snapshot, pin_snapshot
        from ..utils.log import Log
        for attempt in (0, 1):
            found = find_latest_complete_snapshot(output_model,
                                                  verify=self._verify)
            if found is None:
                raise FileNotFoundError(
                    f"no complete snapshot of {output_model!r} found")
            it, path = found
            try:
                # pinned for the whole find->read window: a concurrent
                # writer's prune_snapshots (continual publish) holds
                # this generation until the load finishes
                with pin_snapshot(path):
                    expected = expected_sha256
                    if expected is None and self._verify:
                        try:
                            # utf-8 like every artifact read (the
                            # manifest is ASCII-escaped JSON today, but
                            # the convention is one encoding on both
                            # sides of every checksummed file)
                            with open(path + ".manifest.json",
                                      encoding="utf-8") as f:
                                expected = json.load(f).get(
                                    "model_sha256")
                        except FileNotFoundError:
                            raise     # pruned mid-load: re-scan below
                        except (OSError, ValueError) as e:
                            # the manifest the finder JUST parsed is
                            # torn (bit rot): refuse — silently loading
                            # with expected=None would be exactly the
                            # unverified activation
                            # serve_verify_artifacts exists to prevent
                            raise ArtifactVerificationError(
                                f"snapshot manifest "
                                f"{path}.manifest.json became "
                                f"unreadable mid-load ({e}); refusing "
                                "unverified activation") from e
                    return self.load(
                        model_file=path, version=version,
                        source=f"{path} (snapshot iter {it})",
                        activate=activate, expected_sha256=expected)
            except FileNotFoundError:
                # the generation the finder located was pruned before
                # this reader could pin it (the unavoidable race: the
                # pin lands after the find).  An older complete
                # snapshot is still a valid bring-up — re-scan ONCE
                # instead of failing; a second miss is a real error
                if attempt:
                    raise
                Log.warning(f"snapshot {path} vanished between lookup "
                            "and load (pruned by a concurrent writer); "
                            "re-scanning once")

    @property
    def max_resident(self) -> int:
        """The co-hosting residency cap (0 = unlimited)."""
        return self._max_resident

    # -- swap / lookup -----------------------------------------------------
    def activate(self, version: str) -> None:
        """Atomically point new requests at ``version``; handles already
        resolved via :meth:`current` are unaffected."""
        with self._lock:
            if version not in self._models:
                raise KeyError(f"unknown model version {version!r}")
            self._current = self._models[version]

    def current(self) -> ServedModel:
        with self._lock:
            if self._current is None:
                raise NoModelError("no model loaded")
            return self._current

    def get(self, version: Optional[str] = None) -> ServedModel:
        if version is None:
            return self.current()
        with self._lock:
            try:
                return self._models[version]
            except KeyError:
                raise KeyError(f"unknown model version {version!r}") \
                    from None

    def unload(self, version: str, force: bool = False) -> None:
        """Drop a non-current version (the current one must be swapped
        away first — unloading what is serving would strand the next
        request with no model).  ``force=True`` expels even the current
        version, returning the registry to model-less; it exists as the
        gated-promotion rollback's belt-and-braces (shadow loads never
        auto-activate, so in normal operation a refused candidate is
        never current — force covers operator surgery and defensive
        rollback paths only)."""
        with self._lock:
            if self._current is not None \
                    and self._current.version == version:
                if not force:
                    raise ValueError("cannot unload the current "
                                     "version; activate another first")
                self._current = None
            self._models.pop(version, None)

    def versions(self) -> List[dict]:
        with self._lock:
            cur = self._current.version if self._current else None
            return [dict(m.describe(), current=(v == cur))
                    for v, m in sorted(self._models.items())]
