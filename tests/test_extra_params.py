"""The four formerly parse-and-ignore params (VERDICT r2 task 6):
extra_trees, forcedbins_filename, feature_contri, deterministic.
"""

import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.binning import BinMapper


def _data(n=2500, f=8, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, f).astype(np.float32)
    y = (1.5 * x[:, 0] - x[:, 1] + 0.3 * rng.randn(n) > 0).astype(np.float32)
    return x, y


BASE = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.1,
        "max_bin": 31, "min_data_in_leaf": 5, "verbosity": -1}


def _train(params, x, y, rounds=15):
    return lgb.train(dict(params), lgb.Dataset(x, label=y, params=params),
                     num_boost_round=rounds)


# ---------------------------------------------------------------- extra_trees
@pytest.mark.parametrize("learner", ["partitioned", "masked"])
def test_extra_trees_changes_and_reproduces(learner):
    x, y = _data()
    p = dict(BASE, tpu_learner=learner)
    plain = _train(p, x, y)
    et1 = _train(dict(p, extra_trees=True), x, y)
    et2 = _train(dict(p, extra_trees=True), x, y)
    # randomized thresholds -> different trees than the exhaustive scan
    assert et1.model_to_string() != plain.model_to_string()
    # ...but deterministic given the same extra_seed
    assert et1.model_to_string() == et2.model_to_string()
    et3 = _train(dict(p, extra_trees=True, extra_seed=99), x, y)
    assert et3.model_to_string() != et1.model_to_string()
    # still learns the signal
    from lightgbm_tpu.metrics import _auc
    auc = _auc(y, np.asarray(et1.predict(x, raw_score=True)), None)
    assert auc > 0.85
    # randomization must differ ACROSS trees (code-review r3: a key
    # without the iteration component froze one draw for the whole run)
    roots = {(t.split_feature[0], t.threshold_bin[0]) for t in et1.trees}
    assert len(roots) > 1, f"all trees share the same random root: {roots}"


def test_extra_trees_fused_parity():
    x, y = _data()
    p = dict(BASE, tpu_learner="masked", extra_trees=True)
    b_f = _train(dict(p, fused_chunk=5), x, y)
    b_p = _train(dict(p, fused_chunk=0), x, y)
    drop = lambda s: "\n".join(l for l in s.splitlines()
                               if not l.startswith("[fused_chunk:"))
    assert drop(b_f.model_to_string()) == drop(b_p.model_to_string())


# ------------------------------------------------------- forcedbins_filename
def test_forcedbins_filename(tmp_path):
    x, y = _data()
    spec = [{"feature": 0, "bin_upper_bound": [-1.0, 0.0, 1.0]}]
    fp = tmp_path / "forced.json"
    fp.write_text(json.dumps(spec))
    p = dict(BASE, forcedbins_filename=str(fp))
    ds = lgb.Dataset(x, label=y, params=p)
    ds.construct()
    ub = ds.bin_mappers[0].bin_upper_bound
    for forced in (-1.0, 0.0, 1.0):
        assert np.any(np.isclose(ub, forced)), \
            f"forced bound {forced} missing from {ub}"
    # other features unaffected by the file
    assert not np.any(np.isclose(ds.bin_mappers[1].bin_upper_bound, -1.0,
                                 atol=1e-9))
    # training on the forced dataset still works
    bst = lgb.train(p, ds, num_boost_round=5)
    assert len(bst.trees) == 5


def test_forced_bounds_binmapper_direct():
    rng = np.random.RandomState(3)
    vals = rng.randn(5000)
    m = BinMapper()
    m.find_bin(vals, 5000, 16, 3, forced_bounds=[-0.5, 0.5])
    assert np.any(np.isclose(m.bin_upper_bound, -0.5))
    assert np.any(np.isclose(m.bin_upper_bound, 0.5))
    assert m.num_bin <= 16
    # values map consistently around the forced boundary
    bins = m.value_to_bin(np.asarray([-0.501, -0.499]))
    assert bins[0] != bins[1]


# ------------------------------------------------------------- feature_contri
@pytest.mark.parametrize("learner", ["partitioned", "masked"])
def test_feature_contri_downweights_feature(learner):
    x, y = _data()
    p = dict(BASE, tpu_learner=learner)
    plain = _train(p, x, y)
    # crush the dominant feature's gain; it should lose importance
    contri = [1.0] * x.shape[1]
    contri[0] = 1e-6
    down = _train(dict(p, feature_contri=contri), x, y)
    imp_plain = plain.feature_importance("split")
    imp_down = down.feature_importance("split")
    assert imp_plain[0] > 0
    assert imp_down[0] < imp_plain[0]
    assert imp_down[0] == 0  # gain scaled to ~0 -> never chosen


# -------------------------------------------------------------- deterministic
def test_deterministic_by_design():
    x, y = _data()
    p = dict(BASE, deterministic=True, bagging_freq=2,
             bagging_fraction=0.8, feature_fraction=0.7)
    m1 = _train(p, x, y).model_to_string()
    m2 = _train(p, x, y).model_to_string()
    assert m1 == m2


@pytest.mark.skipif(not os.path.exists("/root/reference/docs/Parameters.rst"),
                    reason="reference checkout unavailable")
def test_reference_param_surface_partition():
    """VERDICT r3 task 7: every user-facing reference parameter
    (docs/Parameters.rst + config.h members) is either implemented (in
    _PARAMS or its alias table) or enumerated below with a documented
    rejection reason.  A new reference param failing this test must be
    added to one side or the other consciously."""
    import re
    from lightgbm_tpu.config import _PARAMS, _ALIASES

    # consciously rejected / internal-only reference names -> reason
    rejected = {
        # config.h internal computed flags, not user params
        "is_parallel": "derived flag, computed in _check_param_conflict",
        "is_data_based_parallel": "derived flag, computed in "
                                  "_check_param_conflict",
        # config.h helpers that are not parameters
        "value": "config.h parser local, not a parameter",
        "file_load_progress_interval_bytes": "host-side load-progress "
            "logging knob; the C++ parser (native/parser.cpp) loads via "
            "mmap+OpenMP without progress callbacks",
    }

    names = set()
    rst = open("/root/reference/docs/Parameters.rst").read()
    names.update(re.findall(r"^-  ``(\w+)``", rst, re.M))
    hdr = open("/root/reference/include/LightGBM/config.h").read()
    names.update(re.findall(
        r"^\s+(?:int|double|bool|std::string|std::vector<[^>]+>"
        r"|data_size_t|size_t|int64_t)\s+(\w+)\s*=", hdr, re.M))

    unhandled = sorted(
        n for n in names
        if n not in _PARAMS and n not in _ALIASES and n not in rejected)
    assert not unhandled, (
        f"reference params neither implemented nor consciously rejected: "
        f"{unhandled}")


def test_unknown_param_warns():
    import lightgbm_tpu.utils.log as log_mod
    from lightgbm_tpu.config import Config
    seen = []
    old = log_mod._callback
    log_mod._callback = lambda msg: seen.append(msg)
    try:
        Config({"objective": "binary", "definitely_not_a_param": 1})
    finally:
        log_mod._callback = old
    assert any("Unknown parameter: definitely_not_a_param" in m
               for m in seen)
