"""Chaos-injection soak harness for the serving subsystem.

Hammers a live ``serve.Server`` from concurrent clients while a
reloader thread hot-swaps between two model versions and a chaos thread
arms ``utils/faultinject`` windows (``serve_batch`` transient device
faults, ``serve_reload`` failed loads), then checks the INVARIANTS the
hardening layer promises (docs/Serving.md "Hardening"):

- **No request is ever lost or hung**: every accepted submission
  resolves — a prediction, or a typed rejection (``BacklogFull``,
  ``CircuitOpen``, ``DeadlineExceeded``, ``BatcherClosed``).  A
  ``result()`` timeout is a violation.
- **Parity under fire**: every successful prediction is byte-identical
  to ``Booster.predict`` of the model version that served it —
  micro-batch composition, concurrent reloads and injected faults may
  never corrupt a result.
- **Failed reloads are invisible**: an injected ``serve_reload`` fault
  leaves the current version serving.
- **The service recovers**: once chaos stops, predictions succeed again
  (the circuit breaker closes after its half-open probe).
- **Drain is clean**: after the soak, ``Server.drain`` answers every
  queued request, new work is refused, and the queue reads empty.

Run standalone (prints one JSON report, exit 1 on violations)::

    python tools/soak_serve.py duration_s=5 clients=8 chaos=1 http=0

Importable: ``run_soak(...)`` returns the report dict —
``tests/test_serve_hardening.py`` runs a short deterministic soak in
tier-1.
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
from typing import Dict, Optional

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

N_FEAT = 6


def build_models(seed: int = 0):
    """Two small distinguishable regression models to hot-swap between."""
    import lightgbm_tpu as lgb

    def one(s, rounds):
        rs = np.random.RandomState(s)
        x = rs.randn(400, N_FEAT)
        y = x[:, 0] + 0.5 * (s + 1) * x[:, 1]
        return lgb.train({"objective": "regression", "verbosity": -1,
                          "num_leaves": 8},
                         lgb.Dataset(x, label=y), num_boost_round=rounds)

    return one(seed, 8), one(seed + 1, 12)


def _request_pool(pool_size: int, max_rows: int, seed: int):
    rs = np.random.RandomState(seed + 7)
    return [rs.randn(int(n), N_FEAT)
            for n in rs.randint(1, max_rows + 1, pool_size)]


def run_soak(duration_s: float = 2.0, clients: int = 4,
             pool_size: int = 24, max_rows: int = 48, seed: int = 0,
             chaos: bool = True, reload_every_s: float = 0.25,
             deadline_ms: float = 2000.0, http: bool = False,
             device_binning: bool = False,
             chaos_spec: Optional[str] = None,
             params: Optional[Dict] = None) -> Dict:
    """One soak run; returns the report dict (see module docstring).

    ``device_binning=True`` serves through the fused device-resident
    path (``serve_device_binning``) and arms a ``serve_self_check``
    fault in the chaos window: a reload whose engine self-check fails
    must DEMOTE that version to the host walk — still answering every
    request with that version's own exact predictions
    (``serve.host_fallback_batches`` counts them) — never refuse
    traffic.  Successful responses must then byte-match EITHER the
    version's fused-path scores or its host-walk scores (both are
    sanctioned results of the mode; which one served depends on
    whether the chaos window demoted that load)."""
    from lightgbm_tpu.serve import (BacklogFull, BatcherClosed,
                                    BatcherDraining, CircuitOpen,
                                    DeadlineExceeded, Server)
    from lightgbm_tpu.serve.server import start_http
    from lightgbm_tpu.utils import faultinject

    b1, b2 = build_models(seed)
    pool = _request_pool(pool_size, max_rows, seed)
    # byte-parity oracles, computed OUTSIDE the soak: every ok response
    # must equal the serving version's own Booster.predict (host walk)
    # — or, under device_binning, its fused-path scores
    expected = {"m1": [[np.asarray(b1.predict(p))] for p in pool],
                "m2": [[np.asarray(b2.predict(p))] for p in pool]}
    if device_binning:
        from lightgbm_tpu.serve.engine import PredictorEngine
        for tag, bst in (("m1", b1), ("m2", b2)):
            ref = PredictorEngine.from_booster(bst, max_batch=64)
            for i, p in enumerate(pool):
                expected[tag][i].append(ref.fused_predict(p))
    srv_params = {"serve_max_batch": 64, "serve_max_wait_ms": 1.0,
                  "serve_queue_rows": 256, "serve_retries": 1,
                  "serve_breaker_failures": 3,
                  "serve_breaker_cooldown_ms": 200.0,
                  "serve_deadline_ms": deadline_ms, "verbosity": -1,
                  "serve_device_binning": device_binning}
    srv_params.update(params or {})
    srv = Server(srv_params, booster=b1)
    frontend = start_http(srv, port=0) if http else None
    base = f"http://127.0.0.1:{frontend.port}" if frontend else None

    stop = threading.Event()
    violations: list = []
    vlock = threading.Lock()

    def violate(msg: str) -> None:
        with vlock:
            violations.append(msg)

    version_tag = {"v1": "m1"}     # registry version -> model tag

    def tag_of(version) -> Optional[str]:
        return version_tag.get(version)

    # -- reloader: alternate hot swaps; injected failures must be no-ops
    reload_counts = collections.Counter()

    def reloader():
        k = 0
        while not stop.wait(reload_every_s):
            tag, bst = ("m1", b1) if k % 2 == 0 else ("m2", b2)
            version = f"{tag}@{k}"
            # mapping recorded BEFORE the load: activation is atomic
            # inside load, and a batch may resolve the new version the
            # instant it lands; a failed load leaves a harmless entry
            version_tag[version] = tag
            try:
                # through Server.reload, not registry.load directly:
                # the soak must exercise (and count into
                # serve.reload_failures) the surface operators use
                srv.reload(booster=bst, version=version)
                reload_counts["reload_ok"] += 1
            except Exception:     # noqa: BLE001 — injected serve_reload
                reload_counts["reload_failed"] += 1
            k += 1

    # -- chaos: windows of transient batch faults + failing reloads
    # (+ under device_binning: a failing engine self-check, which must
    # demote that reload to the host walk, not refuse traffic)
    spec = chaos_spec or ("serve_batch:1-6,serve_reload:1"
                          + (",serve_self_check:1" if device_binning
                             else ""))

    def chaos_thread():
        while not stop.wait(0.4):
            # the next 6 serve batches fail transiently (retries=1 ->
            # 2 attempts/batch -> 3 failed batches -> breaker opens at
            # threshold 3), and the next reload attempt fails too
            faultinject.configure(spec)
            stop.wait(0.15)
            faultinject.configure(None)

    # -- clients -----------------------------------------------------------
    def classify_and_count(counts, fut, i):
        try:
            out = fut.result(timeout=15.0)
        except DeadlineExceeded:
            counts["deadline_shed"] += 1
        except BatcherClosed:
            counts["closed"] += 1
        except TimeoutError:
            counts["hung"] += 1
            violate(f"request on pool[{i}] hung past 15s")
        except Exception as e:   # noqa: BLE001 — injected batch faults
            counts["error"] += 1
            if "injected fault" not in str(e):
                violate(f"unexpected request error: {e!r}")
        else:
            counts["ok"] += 1
            tag = tag_of(fut.info.get("model_version"))
            if tag is None:
                violate(f"response from unknown model version "
                        f"{fut.info.get('model_version')!r}")
            elif not any(np.array_equal(out, e)
                         for e in expected[tag][i]):
                violate(f"PARITY violation on pool[{i}] "
                        f"(version {fut.info.get('model_version')})")

    def client_inproc(tid, counts):
        rs = np.random.RandomState(seed * 100 + tid)
        while not stop.is_set():
            i = int(rs.randint(len(pool)))
            try:
                fut = srv.submit(pool[i])
            except BacklogFull:
                counts["backlog"] += 1
                stop.wait(0.002)
                continue
            except CircuitOpen:
                counts["circuit_open"] += 1
                stop.wait(0.01)
                continue
            except DeadlineExceeded:
                counts["deadline_rejected"] += 1
                continue
            except BatcherDraining:
                counts["draining"] += 1
                continue
            counts["submitted"] += 1
            classify_and_count(counts, fut, i)

    def client_http(tid, counts):
        import urllib.error
        import urllib.request
        rs = np.random.RandomState(seed * 100 + tid)
        while not stop.is_set():
            i = int(rs.randint(len(pool)))
            req = urllib.request.Request(
                base + "/predict",
                data=json.dumps({"rows": pool[i].tolist()}).encode(),
                headers={"Content-Type": "application/json"})
            try:
                resp = json.loads(urllib.request.urlopen(
                    req, timeout=15.0).read())
            except urllib.error.HTTPError as e:
                code = e.code
                e.read()
                counts[{429: "backlog", 503: "circuit_open",
                        504: "deadline_shed"}.get(code, "error")] += 1
                if code not in (429, 503, 504, 500):
                    violate(f"unexpected HTTP status {code}")
                stop.wait(0.01)
                continue
            except OSError:
                counts["hung"] += 1
                violate("HTTP request timed out (hung request)")
                continue
            counts["submitted"] += 1
            counts["ok"] += 1
            tag = tag_of(resp.get("model_version"))
            got = np.asarray(resp["predictions"])
            if tag is None:
                violate(f"response from unknown model version "
                        f"{resp.get('model_version')!r}")
            elif not any(np.array_equal(got, e)
                         for e in expected[tag][i]):
                violate(f"PARITY violation on pool[{i}] over HTTP "
                        f"(version {resp.get('model_version')})")

    client = client_http if http else client_inproc
    counts_per_thread = [collections.Counter() for _ in range(clients)]
    threads = [threading.Thread(target=client, args=(t, counts_per_thread[t]),
                                daemon=True, name=f"soak-client-{t}")
               for t in range(clients)]
    threads.append(threading.Thread(target=reloader, daemon=True,
                                    name="soak-reloader"))
    if chaos:
        threads.append(threading.Thread(target=chaos_thread, daemon=True,
                                        name="soak-chaos"))
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(duration_s)
    stop.set()
    for t in threads:
        t.join(timeout=30.0)
        if t.is_alive():
            violate(f"thread {t.name} failed to stop")
    faultinject.configure(None)

    # -- recovery: chaos is over, the breaker must close again -------------
    recovered = False
    t_end = time.perf_counter() + 10.0
    while time.perf_counter() < t_end:
        try:
            srv.predict(pool[0], timeout=10.0)
            recovered = True
            break
        except Exception:     # noqa: BLE001 — breaker cooldown et al.
            time.sleep(0.05)
    if not recovered:
        violate("service did not recover after chaos stopped")
    breaker_end = srv.breaker.describe() if srv.breaker else None
    if recovered and breaker_end and breaker_end["state"] != "closed":
        violate(f"breaker did not close after recovery: {breaker_end}")

    # -- graceful drain ----------------------------------------------------
    drain = srv.drain(10.0)
    if not drain["drained"]:
        violate(f"drain timed out with {drain['leftover_rows']} rows")
    if srv.batcher.depth_rows != 0:
        violate("queue not empty after drain")
    try:
        srv.submit(pool[0])
        violate("submit accepted during drain")
    except BatcherDraining:
        pass
    health = srv.health()
    if health["status"] != "draining":
        violate(f"health status {health['status']!r} during drain")

    counts = collections.Counter(reload_counts)
    for c in counts_per_thread:
        counts.update(c)
    snap = srv.metrics_snapshot()
    report = {
        "duration_s": round(time.perf_counter() - t0, 3),
        "mode": "http" if http else "inproc",
        "chaos": bool(chaos),
        "counts": dict(sorted(counts.items())),
        "recovered": recovered,
        "drain": drain,
        "breaker": breaker_end,
        "device_binning": bool(device_binning),
        "metrics": {k: snap[k] for k in
                    ("serve.requests", "serve.errors", "serve.rejected",
                     "serve.deadline_shed", "serve.deadline_rejected",
                     "serve.breaker_opens", "serve.breaker_rejected",
                     "serve.reload_failures", "serve.fused_batches",
                     "serve.host_fallback_batches") if k in snap},
        "violations": violations,
    }
    if frontend is not None:
        frontend.close()
    srv.close()
    return report


def run_continual_soak(duration_s: float = 4.0, clients: int = 3,
                       generations: int = 2, seed: int = 0,
                       gate_failure: bool = True, rows: int = 240,
                       chunk_rows: int = 120,
                       params: Optional[Dict] = None) -> Dict:
    """Continual-pipeline chaos soak (docs/Continual-Training.md): a
    live ``Server`` takes traffic from concurrent clients while a
    ``ContinualTrainer`` runs ``generations`` generations against its
    registry.  With ``gate_failure`` the FIRST continual generation's
    shadow probe is made to fail (injected ``shadow_probe`` fault) and
    must roll back.  Invariants checked:

    - the incumbent serves THROUGHOUT — every response carries a
      version that passed the gate; a rolled-back candidate's version
      never serves a single request;
    - no accepted request is lost or hung;
    - rollback is automatic and counted (``continual.rollbacks``), and
      the pipeline RECOVERS: the following generation publishes and its
      version takes traffic;
    - freshness is observable (``/freshness``-backed trainer state).
    """
    import shutil
    import tempfile

    import lightgbm_tpu  # noqa: F401 — path bootstrap before pipeline
    from lightgbm_tpu.pipeline.continual import ContinualTrainer
    from lightgbm_tpu.serve import (BacklogFull, BatcherClosed,
                                    BatcherDraining, CircuitOpen,
                                    DeadlineExceeded, Server)
    from lightgbm_tpu.utils import faultinject

    rs = np.random.RandomState(seed)

    def chunk(n):
        x = rs.randn(n, N_FEAT)
        return x, x[:, 0] + 0.5 * x[:, 1] + 0.05 * rs.randn(n)

    tmpdir = tempfile.mkdtemp(prefix="lgbtpu_continual_soak_")
    try:
        srv_params = {"objective": "regression", "num_leaves": 8,
                      "min_data_in_leaf": 5, "verbosity": -1,
                      "output_model": os.path.join(tmpdir, "m.txt"),
                      "continual_rounds": 3, "serve_max_batch": 64,
                      "serve_max_wait_ms": 1.0, "serve_queue_rows": 256}
        srv_params.update(params or {})
        srv = Server(srv_params)
        x0, y0 = chunk(rows)
        trainer = ContinualTrainer(srv_params, x0, y0, server=srv)
        base = trainer.run_generation()           # first incumbent
        violations: list = []
        vlock = threading.Lock()

        def violate(msg: str) -> None:
            with vlock:
                violations.append(msg)

        if base["status"] != "published":
            violate(f"base generation failed: {base}")
        promoted = {base.get("version")}
        refused: set = set()
        served_versions: set = set()
        stop = threading.Event()
        counts = collections.Counter()
        clock = threading.Lock()

        def client(tid):
            crs = np.random.RandomState(seed * 100 + tid)
            while not stop.is_set():
                rows_ = crs.randn(int(crs.randint(1, 24)), N_FEAT)
                try:
                    fut = srv.submit(rows_)
                except (BacklogFull, CircuitOpen, DeadlineExceeded,
                        BatcherDraining):
                    stop.wait(0.002)
                    continue
                try:
                    out = fut.result(timeout=15.0)
                except TimeoutError:
                    violate("request hung past 15s")
                    with clock:
                        counts["hung"] += 1
                    continue
                except Exception:   # noqa: BLE001 — incl. BatcherClosed
                    with clock:
                        counts["error"] += 1
                    continue
                with clock:
                    counts["ok"] += 1
                    served_versions.add(fut.info.get("model_version"))
                if not np.all(np.isfinite(np.asarray(out))):
                    violate("non-finite prediction served")

        threads = [threading.Thread(target=client, args=(t,), daemon=True,
                                    name=f"continual-soak-client-{t}")
                   for t in range(clients)]
        for t in threads:
            t.start()
        t0 = time.perf_counter()
        reports = [base]
        deadline = t0 + duration_s
        for g in range(generations):
            if gate_failure and g == 0:
                # one injected gate failure: the probe fires, the candidate
                # must quarantine and the incumbent keep serving
                faultinject.configure("shadow_probe:1-")
            rep = trainer.run_generation(*chunk(chunk_rows))
            faultinject.configure(None)
            reports.append(rep)
            if rep["status"] == "published":
                promoted.add(rep["version"])
            elif rep.get("version_refused"):
                refused.add(rep["version_refused"])
            if gate_failure and g == 0 and rep["status"] != "rolled_back":
                violate(f"injected gate failure did not roll back: {rep}")
            if (not gate_failure or g > 0) and rep["status"] != "published":
                violate(f"clean generation {g} failed: {rep}")
        # keep traffic flowing a moment on the final model
        while time.perf_counter() < deadline and not stop.is_set():
            time.sleep(0.01)
        stop.set()
        for t in threads:
            t.join(timeout=30.0)
            if t.is_alive():
                violate(f"thread {t.name} failed to stop")
        faultinject.configure(None)
        # gate invariants, judged on the COMPLETE ledger (checking inside
        # the client threads would race the activation): every version that
        # served passed the gate; a refused candidate never served
        ghost = served_versions - promoted
        if ghost:
            violate(f"responses from versions that never passed the gate: "
                    f"{sorted(v for v in ghost if v)}")
        hit = served_versions & refused
        if hit:
            violate(f"REFUSED candidate versions served requests: "
                    f"{sorted(hit)}")
        # the freshest published generation must be what serves now
        cur = srv.registry.current().version
        last_pub = [r for r in reports if r["status"] == "published"][-1]
        if cur != last_pub["version"]:
            violate(f"serving {cur!r}, expected freshest published "
                    f"{last_pub['version']!r}")
        fresh = srv.freshness()
        snap = srv.metrics_snapshot()
        drain = srv.drain(10.0)
        if not drain["drained"]:
            violate("drain timed out after continual soak")
        gen_hist = snap.get("continual.generation_seconds") or {}
        report = {
            "duration_s": round(time.perf_counter() - t0, 3),
            "mode": "continual",
            # headline bench numbers (bench.py continual point ->
            # perf_budget.txt pins): chunk-arrival-to-serving lag of the
            # freshest generation, and mean wall time per generation
            "freshness_lag_s": fresh.get("freshness_lag_s"),
            "gen_s": round(gen_hist["sum"] / gen_hist["count"], 4)
            if gen_hist.get("count") else None,
            "generations": [
                {k: r.get(k) for k in ("generation", "status", "version",
                                       "iteration", "reason")}
                for r in reports],
            "counts": dict(sorted(counts.items())),
            "current_version": cur,
            "freshness": {k: fresh.get(k) for k in
                          ("model_version", "generation", "freshness_lag_s",
                           "generations_published",
                           "generations_rolled_back")},
            "metrics": {k: snap[k] for k in
                        ("continual.generations", "continual.published",
                         "continual.rollbacks", "continual.quarantined",
                         "serve.requests", "serve.errors") if k in snap},
            "violations": violations,
        }
        srv.close()
        return report
    finally:
        # the soak's working dir (snapshots, sidecars,
        # quarantine) is disposable: every bench/test
        # invocation must not leave debris in /tmp
        shutil.rmtree(tmpdir, ignore_errors=True)


def main(argv) -> int:
    if "--continual" in argv or \
            dict(a.split("=", 1) for a in argv if "=" in a) \
            .get("continual", "0") not in ("0", "false"):
        kv = dict(a.split("=", 1) for a in argv if "=" in a)
        report = run_continual_soak(
            duration_s=float(kv.get("duration_s", 4.0)),
            clients=int(kv.get("clients", 3)),
            generations=int(kv.get("generations", 2)),
            seed=int(kv.get("seed", 0)),
            gate_failure=kv.get("gate_failure", "1") not in ("0", "false"))
        print(json.dumps(report, indent=1, default=str))
        if report["violations"]:
            print(f"CONTINUAL SOAK FAILED: {len(report['violations'])} "
                  "violation(s)", file=sys.stderr)
            return 1
        print("continual soak clean: no invariant violations",
              file=sys.stderr)
        return 0
    kv = dict(a.split("=", 1) for a in argv if "=" in a)
    report = run_soak(
        duration_s=float(kv.get("duration_s", 3.0)),
        clients=int(kv.get("clients", 4)),
        pool_size=int(kv.get("pool_size", 24)),
        max_rows=int(kv.get("max_rows", 48)),
        seed=int(kv.get("seed", 0)),
        chaos=kv.get("chaos", "1") not in ("0", "false"),
        reload_every_s=float(kv.get("reload_every_s", 0.25)),
        deadline_ms=float(kv.get("deadline_ms", 2000.0)),
        http=kv.get("http", "0") not in ("0", "false"),
        device_binning=kv.get("device", "0") not in ("0", "false"))
    print(json.dumps(report, indent=1, default=str))
    if report["violations"]:
        print(f"SOAK FAILED: {len(report['violations'])} violation(s)",
              file=sys.stderr)
        return 1
    print("soak clean: no invariant violations", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
