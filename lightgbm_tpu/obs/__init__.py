"""Training/inference observability subsystem.

Always-available, low-overhead telemetry for the training and serving
paths — the production counterpart of the reference's
``Common::Timer``/``FunctionTimer`` discipline (common.h:978-1056,
SURVEY.md §5) and of the hand-rolled fences PROFILE.md's round-3
attribution was built from:

- ``trace``     nested span/trace API: monotonic clocks, JSONL event
                sink, Chrome-/Perfetto-trace export, and ``fence()`` —
                the device_get-of-a-scalar trick PROFILE.md proved
                necessary on backends where ``block_until_ready``
                returns early (the axon tunnel).
- ``metrics``   counters/gauges/histograms with labels, deterministic
                snapshot-to-dict export, shard-aware aggregation.
- ``comm``      static bytes-on-the-wire accounting for the collective
                call sites of the distributed learners (no extra syncs:
                byte math is derived from traced shapes at compile
                time, arXiv:1706.08359's instrumentation discipline).
- ``flops``     the compute-side mirror of ``comm``: static FLOP + HBM
                byte accounting for the histogram/split/partition/
                score/traversal sites, per-model ``FlopLedger``.
- ``attrib``    roofline attribution: joins the flop ledger with the
                fenced phase spans and a per-device peak table into
                the ``perf.*`` keys (achieved FLOP/s, MFU,
                compute-vs-memory verdict).
- ``blackbox``  flight recorder: bounded ring of per-iteration
                records dumped as JSONL on exception / watchdog /
                finite-guard trigger.
- ``profiler``  opt-in ``jax.profiler`` capture of an iteration window.

``ObsSession`` ties the four together for a training run; it is built
by ``maybe_session(config)`` which returns None unless ``telemetry``
is enabled — the telemetry-off hot path stays a single attribute-load
+ is-None branch with zero host syncs and no per-iteration allocation.
"""

from __future__ import annotations

from .metrics import (MetricsRegistry, aggregate_snapshots,
                      gather_snapshots)
from .profiler import ProfilerWindow
from .trace import Tracer, fence, jsonl_to_chrome

__all__ = [
    "MetricsRegistry", "ObsSession", "ProfilerWindow", "Tracer",
    "aggregate_snapshots", "fence", "jsonl_to_chrome", "maybe_session",
]


class ObsSession:
    """Per-training-run telemetry bundle: one tracer (optionally sinking
    JSONL), one metrics registry, one optional profiler window.

    The GBDT driver holds ``self._obs`` (None when ``telemetry=false``)
    and brackets its iteration phases through ``phase``/``iter_begin``/
    ``iter_end`` — see models/gbdt.py.  All methods here may sync the
    device (that is their job: attributing time to phases needs fences);
    none of them run when telemetry is off.
    """

    def __init__(self, trace_file: str = "", profile_iters=None,
                 profile_dir: str = ""):
        self.tracer = Tracer(sink_path=trace_file or None)
        self.metrics = MetricsRegistry()
        self.profiler = None
        if profile_iters:
            start, count = (list(profile_iters) + [1])[:2]
            self.profiler = ProfilerWindow(
                int(start), int(count),
                logdir=profile_dir or
                ((trace_file + ".profile") if trace_file
                 else "lgbtpu_profile"))
        self._comm_sites = ()
        self._flop_sites = None
        # (peak FLOP/s, peak HBM bytes/s) for the roofline join;
        # attached by the driver (obs/attrib.config_peaks)
        self.peaks = (None, None)
        from ..utils import timer as _timer
        _timer.global_timer.enabled = True   # FunctionTimer scopes feed in
        _set_compile_watch_target(self)

    # -- iteration lifecycle ---------------------------------------------
    def iter_begin(self, it: int) -> float:
        if self.profiler is not None:
            self.profiler.on_iter_begin(it)
        return self.tracer.now()

    def iter_end(self, it: int, t0: float, n_steps: int = 0) -> None:
        self.metrics.counter("train.iterations").inc()
        if n_steps:
            self.metrics.histogram("train.steps_per_tree").observe(n_steps)
        self.metrics.histogram("train.iter_seconds").observe(
            self.tracer.now() - t0)
        self.record_comm(n_steps)
        self.record_flops(n_steps)
        if self.profiler is not None:
            self.profiler.on_iter_end(it)

    def phase(self, name: str, it: int = -1):
        """Span for one iteration phase (grad/grow/fetch/score); close
        with ``end(device_value)`` so the fence attributes the wall time
        to the phase that queued the work, not to the next blocking
        call (PROFILE.md methodology)."""
        args = {"iteration": it} if it >= 0 else {}
        return self.tracer.span(name, **args)

    def phase_metric(self, name: str, seconds: float) -> None:
        self.metrics.histogram("train.phase_seconds",
                               phase=name).observe(seconds)

    # -- comm accounting --------------------------------------------------
    def attach_comm_sites(self, sites) -> None:
        """Register the grower's static collective ledger (obs/comm.py);
        per-iteration byte counters are derived from it host-side."""
        self._comm_sites = sites

    def record_comm(self, n_steps: int) -> None:
        for site in (self._comm_sites.sites()
                     if self._comm_sites else ()):
            mult = n_steps if site.cadence == "step" else 1
            if mult <= 0:
                continue
            labels = dict(site=site.site, collective=site.collective)
            self.metrics.counter("comm.calls", **labels).inc(mult)
            self.metrics.counter("comm.payload_bytes", **labels).inc(
                site.payload_bytes * mult)
            self.metrics.counter("comm.wire_bytes", **labels).inc(
                site.wire_bytes * mult)

    # -- compute accounting ------------------------------------------------
    def attach_flop_sites(self, ledger) -> None:
        """Register the driver's static compute ledger (obs/flops.py
        FlopLedger, built from LOGICAL GLOBAL shapes); per-iteration
        FLOP/HBM-byte counters are derived from it host-side.  Under
        multi-process training the driver attaches on process 0 only —
        the ledger already accounts the global work, so a per-process
        attach would multiply it by the process count at aggregation."""
        self._flop_sites = ledger

    def attach_peaks(self, peak_flops, peak_bw) -> None:
        self.peaks = (peak_flops, peak_bw)

    @property
    def flop_sites(self):
        return self._flop_sites

    def record_flops(self, n_steps: int) -> None:
        for site in (self._flop_sites.sites()
                     if self._flop_sites is not None else ()):
            mult = n_steps if site.cadence == "step" else 1
            if mult <= 0:
                continue
            labels = dict(phase=site.phase, site=site.site)
            self.metrics.counter("flops.total", **labels).inc(
                site.flops * mult)
            self.metrics.counter("flops.hbm_bytes", **labels).inc(
                site.hbm_bytes * mult)

    # -- snapshot / finish ------------------------------------------------
    def snapshot(self, gather: bool = True) -> dict:
        """Metrics snapshot as a plain dict; with ``gather`` (default)
        per-shard snapshots are gathered and merged on every process
        (host 0's view == everyone's view) under multi-process
        training."""
        snap = self.metrics.snapshot()
        if gather:
            snap = aggregate_snapshots(gather_snapshots(snap))
        return snap

    def finish(self) -> dict:
        """Stop any active profiler capture, flush the trace sink, end
        the process-wide FunctionTimer feed this session switched on,
        and return the final (gathered) metrics snapshot."""
        if self.profiler is not None:
            self.profiler.finish()
        self.tracer.flush()
        from ..utils import timer as _timer
        _timer.global_timer.enabled = False
        return self.snapshot()


# compile/cache events (utils/compile_cache.watch_compiles) go through
# one process-global indirection: jax.monitoring listeners cannot be
# unregistered, so they are registered ONCE and forward to the most
# recently constructed session (latest wins; None = drop)
_compile_watch_target = None
_compile_watch_installed = False


def _set_compile_watch_target(session: "ObsSession") -> None:
    global _compile_watch_target, _compile_watch_installed
    _compile_watch_target = session
    if _compile_watch_installed:
        return

    class _Fwd:
        """Registry/tracer proxies bound to the CURRENT target."""

        @staticmethod
        def histogram(name, **labels):
            t = _compile_watch_target
            return (t.metrics if t else MetricsRegistry()) \
                .histogram(name, **labels)

        @staticmethod
        def counter(name, **labels):
            t = _compile_watch_target
            return (t.metrics if t else MetricsRegistry()) \
                .counter(name, **labels)

        @staticmethod
        def instant(name, **args):
            t = _compile_watch_target
            if t is not None:
                t.tracer.instant(name, **args)

    from ..utils.compile_cache import watch_compiles
    _compile_watch_installed = watch_compiles(_Fwd, tracer=_Fwd)


def maybe_session(config) -> "ObsSession | None":
    """Build an ObsSession from Config telemetry params, or None when
    ``telemetry=false`` (the default) — the only thing the hot path
    ever does with telemetry off is test this None."""
    if not getattr(config, "telemetry", False):
        return None
    return ObsSession(
        trace_file=getattr(config, "telemetry_trace_file", "") or "",
        profile_iters=getattr(config, "telemetry_profile_iters", None))
