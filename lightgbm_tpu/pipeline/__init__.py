"""Training-to-serving pipelines.

``continual`` — the freshness-guaranteed continual boosting loop
(ROADMAP item 6): append data, boost from the newest snapshot, publish
a SHA-pinned artifact, promote it into the serving registry through a
two-stage gate (engine self-check + shadow-traffic parity probe), and
roll back automatically on any failure.  docs/Continual-Training.md.
"""

from __future__ import annotations

from .continual import ContinualTrainer, GateFailure, gated_promote

__all__ = ["ContinualTrainer", "GateFailure", "gated_promote"]
