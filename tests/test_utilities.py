"""Aux subsystem tests: logging, timers, dump_model, refit, pred early stop
(test_utilities.py / SURVEY.md §5 analog)."""

import json

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.utils import FunctionTimer, Log, global_timer, \
    register_log_callback


class TestChooseParamValue:
    """ADVICE r5 #4: the canonical key wins by PRESENCE — an explicitly
    set None must not be overridden by an alias (the reference returns
    immediately when main_param_name is in params)."""

    def test_explicit_none_canonical_beats_alias(self):
        from lightgbm_tpu.basic import _choose_param_value
        out = _choose_param_value(
            "num_iterations",
            {"num_iterations": None, "n_estimators": 77}, 100)
        assert out["num_iterations"] is None
        assert "n_estimators" not in out

    def test_alias_wins_over_default(self):
        from lightgbm_tpu.basic import _choose_param_value
        out = _choose_param_value("num_iterations",
                                  {"n_estimators": 77}, 100)
        assert out["num_iterations"] == 77
        assert "n_estimators" not in out

    def test_canonical_value_wins_over_alias(self):
        from lightgbm_tpu.basic import _choose_param_value
        out = _choose_param_value(
            "num_iterations",
            {"num_iterations": 5, "n_estimators": 77}, 100)
        assert out["num_iterations"] == 5

    def test_default_when_absent(self):
        from lightgbm_tpu.basic import _choose_param_value
        out = _choose_param_value("num_iterations", {"max_bin": 3}, 100)
        assert out["num_iterations"] == 100
        assert out["max_bin"] == 3


class TestLog:
    def test_callback_sink(self):
        msgs = []
        register_log_callback(lambda m: msgs.append(m))
        # the level is process-global and driven by Config verbosity
        # (reference semantics) — pin it for the assertion
        old = Log.level
        Log.level = 1
        try:
            Log.info("hello")
            Log.warning("warn")
            assert any("hello" in m for m in msgs)
            assert any("warn" in m for m in msgs)
        finally:
            Log.level = old
            register_log_callback(None)

    def test_fatal_raises(self):
        with pytest.raises(RuntimeError):
            Log.fatal("boom")


class TestTimer:
    def test_scopes_accumulate(self):
        with FunctionTimer("unit_test_scope"):
            pass
        assert global_timer.counts["unit_test_scope"] >= 1


class TestDumpModel:
    def test_json_dump(self, binary_data):
        x, y = binary_data
        p = {"objective": "binary", "num_leaves": 7, "max_bin": 31}
        bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=3)
        d = bst.dump_model()
        s = json.dumps(d)  # must be JSON-serializable
        assert d["num_class"] == 1
        assert len(d["tree_info"]) == 3
        t0 = d["tree_info"][0]["tree_structure"]
        assert "split_feature" in t0
        assert "left_child" in t0

    def test_pred_early_stop(self, binary_data):
        x, y = binary_data
        p = {"objective": "binary", "num_leaves": 15, "max_bin": 63}
        bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=30)
        full = bst.predict(x[:200], raw_score=True)
        es = bst.predict(x[:200], raw_score=True, pred_early_stop=True,
                         pred_early_stop_freq=5, pred_early_stop_margin=2.0)
        # early-stopped rows keep the same SIGN (classification unchanged)
        assert ((full > 0) == (es > 0)).mean() > 0.98


class TestRefit:
    def test_refit_api(self, binary_data):
        x, y = binary_data
        p = {"objective": "binary", "num_leaves": 7, "max_bin": 31}
        bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=5)
        refitted = bst.refit(x, y, decay_rate=0.5)
        assert refitted.num_trees() == bst.num_trees()
        from lightgbm_tpu.metrics import _auc
        assert _auc(y, refitted.predict(x, raw_score=True), None) > 0.9


class TestSnapshot:
    def test_snapshot_freq(self, binary_data, tmp_path):
        x, y = binary_data
        out = str(tmp_path / "m.txt")
        p = {"objective": "binary", "num_leaves": 7, "max_bin": 31,
             "snapshot_freq": 2, "output_model": out}
        lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=4)
        import os
        assert os.path.exists(out + ".snapshot_iter_2")
        assert os.path.exists(out + ".snapshot_iter_4")
        snap = lgb.Booster(model_file=out + ".snapshot_iter_2")
        assert snap.num_trees() == 2
