"""Cross-process compile-wall coverage (runs late in the suite — the
'z' keeps the subprocess-heavy pieces at the alphabetical tail):

- second-process warm start: replaying the canonical train+predict in a
  FRESH interpreter against the same persistent cache logs zero fresh
  compiles (pure cache hits);
- the retrace-budget lint (tools/check_retraces.py) is green against
  the pinned tools/retrace_budget.txt, catches a tampered budget, and
  reports stale entries;
- tree_learner=data: the leaf-bucketed (L=64-padded) trace trains
  byte-identical models to the unbucketed per-shape path.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT = os.path.join(REPO, "tools", "check_retraces.py")
BUDGET = os.path.join(REPO, "tools", "retrace_budget.txt")

# the canonical warm-start workload: train + engine-routed predict in a
# fresh interpreter, reporting the process compile/cache counters.
# min_compile_s=0 persists every compile so the second process can hit
# on all of them.
_WARM_SCRIPT = r"""
import json, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import lightgbm_tpu as lgb
from lightgbm_tpu.utils.compile_cache import compile_stats
cache_dir = sys.argv[1]
rs = np.random.RandomState(0)
x = rs.randn(300, 8)
y = (x[:, 0] - x[:, 1] + 0.2 * rs.randn(300) > 0).astype(np.float32)
p = {"objective": "binary", "num_leaves": 31, "verbosity": 0,
     "min_data_in_leaf": 5, "max_bin": 15, "tpu_learner": "masked",
     "fused_chunk": 0, "predict_bucketed": "true",
     "compile_cache_dir": cache_dir, "compile_cache_min_compile_s": 0.0}
ds = lgb.Dataset(x, label=y, params=p)
bst = lgb.train(p, ds, num_boost_round=2)
pred = bst.predict(x[:50])
print("STATS " + json.dumps(compile_stats()))
print("PRED " + json.dumps(np.asarray(pred)[:4].round(8).tolist()))
"""


def _run_warm(cache_dir: str) -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-c", _WARM_SCRIPT, cache_dir],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert out.returncode == 0, out.stderr[-3000:]
    stats = pred = None
    for line in out.stdout.splitlines():
        if line.startswith("STATS "):
            stats = json.loads(line[6:])
        elif line.startswith("PRED "):
            pred = json.loads(line[5:])
    assert stats is not None, out.stdout
    stats["pred"] = pred
    return stats


class TestWarmStart:
    def test_second_process_pays_no_fresh_compiles(self, tmp_path):
        cache = str(tmp_path / "cache")
        cold = _run_warm(cache)
        warm = _run_warm(cache)
        # cold process: real compiles, all written to the empty cache
        assert cold["count"] > 0
        assert cold["cache_misses"] > 0
        # warm process: every compile request is served from disk —
        # zero fresh compiles (cache_misses IS the fresh-compile
        # counter; `count` tallies requests and ticks on hits too),
        # with hits covering the cold misses
        assert warm["cache_misses"] == 0, warm
        assert warm["cache_hits"] >= cold["cache_misses"]
        # and the warm-started model predicts identically
        assert warm["pred"] == cold["pred"]


# autotuner warm start (ISSUE 15, ops/hist_tune.py): the FIRST process
# pays the (K, block_rows) sweep and persists both the choice
# (hist_tune.json) and the compiled traces it leads to; a SECOND
# process against the same directory must re-tune zero times and
# compile zero times.
_TUNE_SCRIPT = r"""
import json, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import lightgbm_tpu as lgb
from lightgbm_tpu.ops import hist_tune
from lightgbm_tpu.utils.compile_cache import compile_stats
cache_dir = sys.argv[1]
rs = np.random.RandomState(0)
x = rs.randn(400, 6)
y = (x[:, 0] - x[:, 1] + 0.2 * rs.randn(400) > 0).astype(np.float32)
p = {"objective": "binary", "num_leaves": 33, "verbosity": 0,
     "min_data_in_leaf": 5, "max_bin": 15, "tpu_learner": "masked",
     "fused_chunk": 0, "hist_tune": "on", "split_batch": 0,
     "compile_cache_dir": cache_dir, "compile_cache_min_compile_s": 0.0}
ds = lgb.Dataset(x, label=y, params=p)
bst = lgb.train(p, ds, num_boost_round=2)
rec = {"sweeps": hist_tune.tune_counts()["sweeps"],
       "pred": np.asarray(bst.predict(x[:4])).round(8).tolist()}
rec.update(compile_stats())
print("TUNE " + json.dumps(rec))
"""


class TestAutotunerWarmStart:
    def test_second_process_reuses_choice_and_traces(self, tmp_path):
        cache = str(tmp_path / "cache")

        def run():
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            out = subprocess.run(
                [sys.executable, "-c", _TUNE_SCRIPT, cache],
                capture_output=True, text=True, timeout=420, env=env,
                cwd=REPO)
            assert out.returncode == 0, out.stderr[-3000:]
            for line in out.stdout.splitlines():
                if line.startswith("TUNE "):
                    return json.loads(line[5:])
            raise AssertionError(out.stdout)

        cold = run()
        warm = run()
        # first fit per (platform, shape bucket): exactly one sweep,
        # persisted next to the compile cache
        assert cold["sweeps"] == 1
        assert os.path.exists(os.path.join(cache, "hist_tune.json"))
        # second process: zero re-tune, zero re-compile (the sweep's
        # own traces AND the tuned grower all hit the persistent
        # cache), and the tuned choice reproduces the same model
        assert warm["sweeps"] == 0, warm
        assert warm["cache_misses"] == 0, warm
        assert warm["pred"] == cold["pred"]


class TestRetraceLint:
    """The lint re-runs the whole canonical matrix in a fresh
    subprocess (~15 s with a warm persistent cache — which tier-1's own
    earlier compiles populate — minutes stone-cold).  The GREEN run now
    rides the unified driver (`python tools/lint.py`,
    tests/test_zlint.py — ISSUE 12 replaced the separate sync/retrace
    invocations); this class keeps the standalone entry point's
    tamper/stale sensitivity, slow-marked."""

    def _run(self, *args, timeout=600):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        return subprocess.run([sys.executable, LINT, *args],
                              capture_output=True, text=True,
                              timeout=timeout, env=env, cwd=REPO)

    @pytest.mark.slow
    def test_tampered_budget_is_caught(self, tmp_path):
        import re
        tampered = tmp_path / "budget.txt"
        text = open(BUDGET).read()
        # violate the headline pin AND leave a stale entry behind
        text = re.sub(r"leaf_sweep.grower = \d+",
                      "leaf_sweep.grower = 0", text)
        tampered.write_text(text + "ghost.scenario = 9\n")
        out = self._run("--budget", str(tampered))
        assert out.returncode == 1
        assert "trace budget violated: leaf_sweep.grower" in out.stderr
        assert "stale budget entry" in out.stderr


class TestBudgetFile:
    def test_budget_is_pinned_and_parses(self):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        from check_retraces import load_budget
        budget = load_budget(BUDGET)
        # the headline pins: one grower trace for the whole leaf sweep,
        # and the unbucketed negative control measurably above it
        assert budget.get("leaf_sweep.grower") == 1
        assert budget.get("negative_unbucketed.grower", 0) > 1
        assert "valid_sizes.add_tree_score" in budget
        assert "serve_buckets.forest" in budget


class TestDataParallelBucketing:
    def test_dp_bucketed_equals_unbucketed(self):
        import jax
        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device CPU mesh")
        rs = np.random.RandomState(3)
        x = rs.randn(1600, 10)
        y = (x[:, 0] - 0.5 * x[:, 1] + 0.3 * rs.randn(1600) > 0) \
            .astype(np.float32)
        texts = []
        for tb in (True, False):
            p = {"objective": "binary", "num_leaves": 31, "verbosity": 0,
                 "min_data_in_leaf": 5, "max_bin": 15,
                 "tree_learner": "data", "split_batch": 1,
                 "fused_chunk": 0, "trace_buckets": tb}
            ds = lgb.Dataset(x, label=y, params=p)
            bst = lgb.train(p, ds, num_boost_round=3)
            texts.append(bst.model_to_string()
                         .split("end of parameters", 1)[-1])
        assert texts[0] == texts[1]
