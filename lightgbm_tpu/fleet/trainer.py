"""Vmapped multi-forest fleet training (docs/Fleet.md).

``fleet_train`` grows N boosters — seed replicas, a hyperparameter
grid, or per-segment models over one dataset — inside ONE jitted
program per epoch: the super-epoch scan (models/gbdt.py PR 16) is
``jax.vmap``-ped over a leading member axis, so the histogram
contraction runs batched ``[N, L, ...]`` shapes through the same MXU
kernels, and ONE host fetch per epoch carries every member's trees,
eval block and early-stop flags.

The contract that makes this more than a throughput trick:

- **Byte identity.**  Every member's trained model is byte-identical
  to a solo ``train()`` with that member's params.  Per-member RNG
  streams (bagging, GOSS, stochastic rounding) ride as traced
  arguments into the SAME arithmetic the solo program runs; feature-
  fraction masks are drawn per member from each member's own host RNG;
  eval values replay through member 0's shared teval program (one
  trace, deterministic math).
- **Masked, not branched, early stop.**  A member whose replay stops
  keeps riding its lane with the stop flag latched: blocked lanes make
  ZERO state changes in-scan, and the host simply stops ingesting
  their rows — no retrace when fleet membership shrinks.
- **Ragged progress.**  Per-member iteration indices are operands, so
  members at different absolute iterations (a healed vote/replay
  disagreement) share epochs until fewer than two members remain,
  then finish through the ordinary solo path.
- **Survivability.**  Per-member snapshots (``<output_model>.member<j>``)
  land at the same epoch boundaries the solo path uses; kill+resume
  restores all N members byte-identically.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from .. import callback as callback_mod
from ..booster import Booster
from ..callback import CallbackEnv, EarlyStopException
from ..config import _ALIASES, _PARAMS, Config, _coerce, canonical_params
from ..dataset import Dataset
from ..engine import _superepoch_plan
from ..utils.log import Log

# params allowed to differ between fleet members: everything else must
# be uniform, because the fleet program bakes it once (member 0's) and
# every lane runs the same compiled scan.  num_leaves may differ only
# under padded_leaves bucketing with equal split-batch width — exactly
# the solo _SE_CACHE sharing rule.
MEMBER_AXIS_PARAMS = frozenset({
    "learning_rate", "seed", "bagging_seed", "feature_fraction_seed",
    "num_leaves", "output_model"})


def parse_sweep(spec: str) -> List[Dict[str, Any]]:
    """``"learning_rate=0.05|0.1;num_leaves=31|63"`` -> the cartesian
    grid as member override dicts (4 members here), values coerced to
    the parameter's declared type.  Only member-axis params may be
    swept; aliases resolve (``eta=...`` sweeps learning_rate)."""
    spec = (spec or "").strip()
    if not spec:
        return []
    axes: List[tuple] = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"fleet_sweep: malformed entry {part!r} "
                             "(want param=v1|v2|...)")
        name, vals = part.split("=", 1)
        name = _ALIASES.get(name.strip(), name.strip())
        if name not in _PARAMS:
            raise ValueError(f"fleet_sweep: unknown parameter {name!r}")
        if name not in MEMBER_AXIS_PARAMS:
            raise ValueError(
                f"fleet_sweep: {name!r} is not a member-axis parameter "
                f"(sweepable: {sorted(MEMBER_AXIS_PARAMS - {'output_model'})})")
        typ = _PARAMS[name][0]
        axes.append((name, [_coerce(name, typ, v.strip())
                            for v in vals.split("|") if v.strip()]))
    if not axes:
        return []
    return [dict(zip([n for n, _ in axes], combo))
            for combo in itertools.product(*[vs for _, vs in axes])]


def expand_members(params: Dict[str, Any],
                   members: Optional[Sequence[Dict[str, Any]]] = None,
                   ) -> List[Dict[str, Any]]:
    """Resolve the fleet roster into full per-member param dicts.

    Precedence: an explicit ``members=`` override list > the
    ``fleet_sweep`` grid > ``fleet_members`` seed replicas (member j
    trains with ``seed+j`` / ``bagging_seed+j`` /
    ``feature_fraction_seed+j``)."""
    cfg = Config(params)
    if members is not None:
        over = [dict(m) for m in members]
    elif cfg.fleet_sweep:
        over = parse_sweep(cfg.fleet_sweep)
    elif cfg.fleet_members > 0:
        over = [{"seed": cfg.seed + j,
                 "bagging_seed": cfg.bagging_seed + j,
                 "feature_fraction_seed": cfg.feature_fraction_seed + j}
                for j in range(cfg.fleet_members)]
    else:
        over = []
    out = []
    for j, ov in enumerate(over):
        mp = dict(params)
        explicit_out = False
        for k, v in ov.items():
            name = _ALIASES.get(k, k)
            if name not in MEMBER_AXIS_PARAMS:
                raise ValueError(
                    f"fleet member {j}: {name!r} is not a member-axis "
                    "parameter — fleet members must share every "
                    "structural param (the one-program contract)")
            mp[name] = v
            explicit_out = explicit_out or name == "output_model"
        if not explicit_out:
            # per-member model/snapshot paths: members must never share
            # an output path or their snapshots overwrite each other
            mp["output_model"] = f"{cfg.output_model}.member{j}"
        out.append(mp)
    return out


class FleetResult:
    """What ``fleet_train`` returns: the trained boosters plus the
    per-member params and stop bookkeeping, in roster order."""

    def __init__(self, boosters, member_params, stopped, epochs):
        self.boosters: List[Booster] = boosters
        self.member_params: List[Dict[str, Any]] = member_params
        self.stopped: List[bool] = stopped       # ES/stump per member
        self.epochs: int = epochs                # fleet epochs dispatched

    def __len__(self) -> int:
        return len(self.boosters)

    def __getitem__(self, j: int) -> Booster:
        return self.boosters[j]


def _check_uniform(member_params: List[Dict[str, Any]]) -> None:
    """Every canonical param outside MEMBER_AXIS_PARAMS must be equal
    across the roster."""
    base = None
    for j, mp in enumerate(member_params):
        cp = {k: repr(v) for k, v in sorted(canonical_params(mp).items())
              if k not in MEMBER_AXIS_PARAMS}
        if base is None:
            base = cp
        elif cp != base:
            diff = sorted(set(cp.items()) ^ set(base.items()))
            raise ValueError(
                f"fleet member {j} differs from member 0 outside the "
                f"member axis: {sorted({k for k, _ in diff})} — fleet "
                "members must share every structural param")


def _check_models(boosters: List[Booster]) -> None:
    """Structural uniformity the vmapped program requires beyond the
    param surface: one process, dense binned data, equal shape-bucket
    residue — mirrors the solo ``_superepoch_key`` sharing rule."""
    import jax
    sig0 = None
    for j, b in enumerate(boosters):
        m = b._model
        if m is None or not hasattr(m, "train_superepoch"):
            raise ValueError(f"fleet member {j}: boosting type "
                             "does not support the super-epoch trainer")
        if m._pc > 1:
            raise ValueError("fleet_train is single-process only "
                             "(tree_learner parallelism composes with "
                             "solo training, not the member axis)")
        if m._cegb_state is not None:
            raise ValueError("fleet_train does not support cegb_* "
                             "(per-member host feature-cost state)")
        if not isinstance(m.binned_dev, jax.Array):
            raise ValueError("fleet_train needs dense device binned "
                             "data (sparse_data is solo-only)")
        cfg = m.config
        k_eff = max(1, min(m._split_batch, cfg.num_leaves - 1)) \
            if cfg.num_leaves > 1 else 1
        sig = (m._leaf_pad, m._split_batch, m._block_rows,
               m._hist_overlap, m._learner_kind, m._se_steps(),
               m.max_bin, k_eff, len(m.valid_sets),
               type(m.objective).__name__)
        if sig0 is None:
            sig0 = sig
        elif sig != sig0:
            raise ValueError(
                f"fleet member {j} compiles a different program shape "
                f"than member 0 ({sig} vs {sig0}): num_leaves may only "
                "differ under padded_leaves bucketing with equal "
                "split_batch width (the solo trace-sharing rule)")


def fleet_train(params: Dict[str, Any], train_set: Dataset,
                num_boost_round: int = 100,
                valid_sets: Optional[List[Dataset]] = None,
                valid_names: Optional[List[str]] = None,
                callbacks: Optional[Callable[[int], list]] = None,
                members: Optional[Sequence[Dict[str, Any]]] = None,
                ) -> FleetResult:
    """Train a fleet of N boosters over ONE shared dataset inside one
    vmapped super-epoch program per epoch (module docstring).

    ``callbacks`` is a FACTORY ``f(member_index) -> [callback, ...]``
    (not a list): callbacks carry per-run state, so members must not
    share instances.  Early stopping from ``early_stopping_round`` is
    instantiated per member automatically.

    Every member's config must qualify for the super-epoch plan
    (engine._superepoch_plan); anything else raises rather than
    silently training a different program than solo would."""
    import jax.numpy as jnp

    params = dict(params or {})
    resume_req = False
    for k in list(params):
        if _ALIASES.get(k, k) == "resume":
            resume_req = bool(_coerce("resume", bool, params.pop(k)))
    base_cfg = Config(params)
    from ..utils.compile_cache import maybe_enable_from_config
    maybe_enable_from_config(base_cfg)
    if "num_iterations" in canonical_params(params):
        num_boost_round = base_cfg.num_iterations

    member_params = expand_members(params, members)
    N = len(member_params)
    if N < 2:
        raise ValueError(
            "fleet_train needs >= 2 members — set fleet_members, "
            "fleet_sweep, or pass members=[...] overrides")
    for mp in member_params:
        mp["num_iterations"] = num_boost_round
    _check_uniform(member_params)
    if callbacks is not None and not callable(callbacks):
        raise ValueError("fleet_train callbacks must be a factory "
                         "f(member_index) -> [callback, ...] — a shared "
                         "list would share callback state across members")
    if valid_sets is not None and not isinstance(valid_sets,
                                                 (list, tuple)):
        valid_sets = [valid_sets]
    if valid_sets and any(vs is train_set for vs in valid_sets):
        raise ValueError("fleet_train does not support the training "
                         "set in valid_sets (training-metric replay is "
                         "a solo-path feature)")

    # per-member resume bookkeeping (snapshots are per member)
    sigs = [None] * N
    member_cfgs = [Config(mp) for mp in member_params]
    if any(c.snapshot_freq > 0 for c in member_cfgs) or resume_req:
        from ..snapshot import params_signature
        sigs = [params_signature(mp) for mp in member_params]
    resume_start = 0
    resume_scores: List[Optional[np.ndarray]] = [None] * N
    prev_boosters: List[Optional[Booster]] = [None] * N
    if resume_req:
        from ..snapshot import find_latest_snapshot
        found = [find_latest_snapshot(member_cfgs[j].output_model,
                                      sigs[j], train_set)
                 for j in range(N)]
        if all(f is not None for f in found) \
                and len({f[0] for f in found}) == 1:
            resume_start = found[0][0]
            for j, (it, path, score) in enumerate(found):
                prev_boosters[j] = Booster(model_file=path)
                resume_scores[j] = score
            Log.info(f"fleet auto-resume: all {N} members continuing "
                     f"from iteration {resume_start}")
        elif any(f is not None for f in found):
            Log.warning("fleet resume: members disagree on the newest "
                        "common snapshot iteration; training the fleet "
                        "from scratch")
        else:
            Log.info("fleet resume=true but no valid snapshots found; "
                     "training from scratch")

    # construct the members over the SHARED dataset.  Resume feeds the
    # saved f32 score straight into the model (the shared Dataset's
    # init_score cannot carry per-member state): zeros + score is the
    # same bits the solo init_model path computes.
    boosters: List[Booster] = []
    for j, mp in enumerate(member_params):
        b = Booster(params=mp, train_set=train_set)
        m = b._model
        if resume_start and resume_scores[j] is not None:
            init = np.zeros((m.num_data, m.num_class), np.float32)
            init += np.asarray(resume_scores[j],
                               np.float32).reshape(m.num_data, -1)
            m.score = jnp.asarray(init)
            m._init_applied = True
            m.set_resume_state(resume_start)
        if valid_sets:
            names = valid_names or [f"valid_{i}"
                                    for i in range(len(valid_sets))]
            for vs, name in zip(valid_sets, names):
                b.add_valid(vs, name)
        boosters.append(b)
    _check_models(boosters)

    # per-member callbacks + the shared super-epoch plan
    plans = []
    cbs_after_all: List[list] = []
    for j, b in enumerate(boosters):
        cfg_j = member_cfgs[j]
        cbs = list(callbacks(j)) if callbacks is not None else []
        if cfg_j.early_stopping_round and cfg_j.early_stopping_round > 0:
            cbs.append(callback_mod.early_stopping(
                cfg_j.early_stopping_round, cfg_j.first_metric_only,
                cfg_j.verbosity > 0))
        cbs_before = [c for c in cbs
                      if getattr(c, "before_iteration", False)]
        cbs_after = [c for c in cbs
                     if not getattr(c, "before_iteration", False)]
        cbs_before.sort(key=lambda c: getattr(c, "order", 0))
        cbs_after.sort(key=lambda c: getattr(c, "order", 0))
        plan = _superepoch_plan(cfg_j, b, None, None, cbs_before,
                                cbs_after, None)
        if plan is None:
            raise ValueError(
                f"fleet member {j}: config does not qualify for the "
                "super-epoch trainer (custom fobj/feval, non-replayable "
                "callbacks, sparse valid sets, or untraced metrics) — "
                "fleet_train has no per-iteration fallback")
        plans.append(plan)
        cbs_after_all.append(cbs_after)
    base_k, eval_spec, es_spec = plans[0]
    for j, p in enumerate(plans[1:], 1):
        if p != plans[0]:
            raise ValueError(f"fleet member {j}: super-epoch plan "
                             f"differs from member 0 ({p} vs "
                             f"{plans[0]}) — members must share one "
                             "epoch shape")
    E = len(eval_spec)

    # ONE fleet program for the whole run (the retrace pin: N members,
    # mixed num_leaves buckets, one `fleet_superepoch` trace)
    m0 = boosters[0]._model
    obj_parts = m0._obj_array_attrs()
    fleet_fn = m0.fleet_superepoch_fn(eval_spec, es_spec, obj_parts, N)
    obj_arrs = obj_parts[1] if obj_parts is not None else ()
    teval0 = m0._teval_fn(eval_spec) if E else None
    mrng = (jnp.asarray([c.learning_rate for c in member_cfgs],
                        jnp.float32),
            jnp.asarray([c.bagging_seed for c in member_cfgs],
                        jnp.int32),
            jnp.asarray([c.seed for c in member_cfgs], jnp.int32))
    ml = jnp.asarray([c.num_leaves for c in member_cfgs], jnp.int32)

    obs0 = getattr(m0, "_obs", None)
    if obs0 is not None:
        obs0.metrics.gauge("fleet.members").set(N)

    from ..obs.flops import member_axis
    from ..snapshot import write_snapshot

    rounds = [resume_start] * N       # absolute boosting rounds done
    exited = [False] * N              # lane no longer ingests
    stopped_f = [False] * N           # ES raised / stump (final stop)
    epochs = 0
    while True:
        active = [j for j in range(N) if not exited[j]]
        if len(active) < 2:
            break
        k_eff = min(base_k,
                    min(num_boost_round - rounds[j] for j in active))
        for j in active:
            cfg_j = member_cfgs[j]
            if cfg_j.snapshot_freq > 0:
                k_eff = min(k_eff, cfg_j.snapshot_freq
                            - rounds[j] % cfg_j.snapshot_freq)
        if k_eff < 2:
            break

        # per-member prologue + operands — member order is the RNG
        # contract (_se_operands draws each member's feature masks)
        init0s = [0.0] * N
        spans = [None] * N
        start_iters = [0] * N
        ops = []
        for j, b in enumerate(boosters):
            m = b._model
            start_iters[j] = m.iter_
            init0s[j], spans[j] = m._se_begin(k_eff, E)
            ops.append(m._se_operands(k_eff, rounds[j], E))

        score0 = jnp.stack([b._model.score for b in boosters])
        fmasks = jnp.stack([o[0] for o in ops])
        iters = jnp.stack([o[1] for o in ops])
        eiters = jnp.stack([o[2] for o in ops])
        cuse0 = ops[0][3]
        es_state = (
            jnp.stack([o[4][0] for o in ops]),
            jnp.stack([o[4][1] for o in ops]),
            jnp.stack([o[4][2] for o in ops]),
            jnp.stack([jnp.bool_(True) if exited[j] else ops[j][4][3]
                       for j in range(N)]))
        vscores = tuple(jnp.stack([ops[j][5][vi] for j in range(N)])
                        for vi in range(len(m0.valid_sets)))
        valid_ops = ops[0][6]

        with member_axis(N):
            (score_out, new_vsc, es_out, stacked, bad_flags, stops_dev,
             vstack) = fleet_fn(score0, vscores, es_state, fmasks,
                                iters, eiters, cuse0, ml, m0.binned_dev,
                                m0._nb_grow, m0._na_grow, m0.na_bin_dev,
                                obj_arrs, valid_ops, mrng)
        epochs += 1
        if E:
            ev_all = jnp.stack([
                boosters[j]._model._se_eval_block(
                    tuple(v[j] for v in vstack), eval_spec, k_eff,
                    teval=teval0)
                for j in range(N)])
        else:
            ev_all = jnp.zeros((N, k_eff, 0), jnp.float32)
        # the ONE host sync of the epoch: every member's trees, guard
        # flags, eval block and stop rows in a single fetch
        host, bad_host, ev_host, stops_np = m0._eget(
            (stacked, bad_flags, ev_all, stops_dev), "fleet_fetch")

        for j, b in enumerate(boosters):
            m = b._model
            m.score = score_out[j]
            m._se_absorb(tuple(v[j] for v in new_vsc),
                         tuple(t[j] for t in es_out))
            obs_j = getattr(m, "_obs", None)
            if obs_j is not None and spans[j] is not None:
                spans[j].end()
                if obs_j.profiler is not None:
                    obs_j.profiler.on_iter_end(start_iters[j]
                                               + k_eff - 1)
            if exited[j]:
                continue
            res = m._se_ingest(tuple(f[j] for f in host),
                               tuple(f[j] for f in stacked),
                               bad_host[j], stops_np[j],
                               np.asarray(ev_host)[j], k_eff,
                               start_iters[j], init0s[j], E)
            b._sync_trees()
            done = res["done"]
            round0 = rounds[j]
            cfg_j = member_cfgs[j]
            if cfg_j.snapshot_freq > 0 and done == k_eff \
                    and (round0 + done) % cfg_j.snapshot_freq == 0:
                try:
                    write_snapshot(b, prev_boosters[j], cfg_j,
                                   round0 + done, sigs[j], train_set)
                except Exception as e:
                    Log.warning(f"fleet member {j} snapshot at "
                                f"iteration {round0 + done} failed "
                                f"({e}); training continues")
            es_raised = False
            for r in range(done):
                ev_row = [(nm, mn, float(res["evals"][r][e]), hib)
                          for e, (_vi, nm, mn, hib)
                          in enumerate(eval_spec)]
                env = CallbackEnv(model=b, params=member_params[j],
                                  iteration=round0 + r,
                                  begin_iteration=0,
                                  end_iteration=num_boost_round,
                                  evaluation_result_list=ev_row)
                try:
                    for cb in cbs_after_all[j]:
                        cb(env)
                except EarlyStopException as e:
                    _apply_early_stop(b, prev_boosters[j], e,
                                      resume_start)
                    es_raised = True
                    extra = done - (r + 1)
                    if extra > 0:
                        Log.warning(
                            f"fleet member {j}: vote overshot the host "
                            f"early stop by {extra} iteration(s); "
                            "dropping surplus trees")
                        m.drop_iterations(extra)
                        b._sync_trees()
                    break
            rounds[j] = resume_start + b.current_iteration
            if es_raised or res["stump"]:
                exited[j] = True
                stopped_f[j] = True
                if obs0 is not None:
                    obs0.metrics.counter("fleet.stopped").inc()
            elif res["stop_row"] is not None:
                Log.warning(f"fleet member {j}: early-stop vote "
                            "tripped but the host callbacks did not; "
                            "resuming")
                m.clear_es_stop()
            if rounds[j] >= num_boost_round:
                exited[j] = True
        if obs0 is not None:
            obs0.metrics.counter("fleet.epochs").inc()
            obs0.metrics.gauge("fleet.active").set(
                sum(1 for j in range(N) if not exited[j]))
            _note_member_evals(obs0, boosters, member_cfgs, eval_spec)

    # stragglers (vote/replay disagreement, odd remainders, or a fleet
    # reduced below two members) finish through the ordinary solo path
    # — byte-identical by construction
    for j in range(N):
        if not exited[j]:
            _solo_finish(boosters[j], member_cfgs[j], member_params[j],
                         prev_boosters[j], sigs[j], train_set,
                         cbs_after_all[j], plans[j], rounds, j,
                         num_boost_round, resume_start)

    for j, b in enumerate(boosters):
        if prev_boosters[j] is not None:
            b.trees = prev_boosters[j].trees + b.trees
            b.tree_weights = (prev_boosters[j].tree_weights
                              + b.tree_weights)
    return FleetResult(boosters, member_params, stopped_f, epochs)


def _apply_early_stop(b: Booster, prev: Optional[Booster],
                      e: EarlyStopException, resume_start: int) -> None:
    """engine.train's EarlyStopException bookkeeping, verbatim."""
    best_iter_offset = 0
    if prev is not None:
        k = max(1, b._num_tree_per_iteration)
        best_iter_offset = len(prev.trees) // k - resume_start
    b.best_iteration = best_iter_offset + e.best_iteration + 1
    for (name, metric, value, _) in e.best_score:
        b.best_score.setdefault(name, {})[metric] = value


def _note_member_evals(obs0, boosters, member_cfgs, eval_spec) -> None:
    """Per-member eval gauges, cardinality-bounded: members beyond
    ``serve_metrics_max_versions`` aggregate under one ``__other__``
    label so a 500-member fleet cannot bloat the exposition."""
    if not eval_spec:
        return
    cap = member_cfgs[0].serve_metrics_max_versions
    for j, b in enumerate(boosters):
        m = b._model
        es = getattr(m, "_es_dev", None)
        if es is None:
            continue
        label = str(j) if (cap <= 0 or j < cap) else "__other__"
        g = obs0.metrics.gauge("fleet.member_best", member=label)
        try:
            g.set(float(np.asarray(es[0])[0]))
        except (TypeError, ValueError, IndexError):
            pass


def _solo_finish(b: Booster, cfg, mparams, prev, sig, train_set,
                 cbs_after, plan, rounds, j, num_boost_round,
                 resume_start) -> None:
    """Finish one member through engine.train's solo loop: super-epochs
    while they fit, then per-iteration remainder rounds with traced
    eval — the exact replay semantics of the solo path, so a member
    that leaves the fleet stays byte-identical to its solo twin."""
    base_k, eval_spec, es_spec = plan
    stopped = False
    from ..snapshot import write_snapshot
    while not stopped:
        k_eff = min(base_k, num_boost_round - rounds[j])
        if cfg.snapshot_freq > 0:
            k_eff = min(k_eff, cfg.snapshot_freq
                        - rounds[j] % cfg.snapshot_freq)
        if k_eff < 2:
            break
        out = b.update_superepoch(k_eff, rounds[j], eval_spec, es_spec)
        done = out["done"]
        round0 = rounds[j]
        if cfg.snapshot_freq > 0 and done == k_eff \
                and (round0 + done) % cfg.snapshot_freq == 0:
            try:
                write_snapshot(b, prev, cfg, round0 + done, sig,
                               train_set)
            except Exception as e:
                Log.warning(f"fleet member {j} snapshot at iteration "
                            f"{round0 + done} failed ({e}); training "
                            "continues")
        es_raised = False
        for r in range(done):
            ev_row = [(nm, mn, float(out["evals"][r][e]), hib)
                      for e, (_vi, nm, mn, hib) in enumerate(eval_spec)]
            env = CallbackEnv(model=b, params=mparams,
                              iteration=round0 + r, begin_iteration=0,
                              end_iteration=num_boost_round,
                              evaluation_result_list=ev_row)
            try:
                for cb in cbs_after:
                    cb(env)
            except EarlyStopException as e:
                _apply_early_stop(b, prev, e, resume_start)
                es_raised = True
                extra = done - (r + 1)
                if extra > 0:
                    Log.warning(
                        f"fleet member {j}: vote overshot the host "
                        f"early stop by {extra} iteration(s); "
                        "dropping surplus trees")
                    b._model.drop_iterations(extra)
                    b._sync_trees()
                break
        rounds[j] = resume_start + b.current_iteration
        if es_raised or out["stump"]:
            stopped = True
        elif out["stop_row"] is not None:
            Log.warning(f"fleet member {j}: early-stop vote tripped "
                        "but the host callbacks did not; resuming")
            b._model.clear_es_stop()
    if not stopped and rounds[j] < num_boost_round and eval_spec:
        b._traced_eval = True
    for i in range(rounds[j], num_boost_round if not stopped else 0):
        st = b.update()
        if cfg.snapshot_freq > 0 and (i + 1) % cfg.snapshot_freq == 0:
            try:
                write_snapshot(b, prev, cfg, i + 1, sig, train_set)
            except Exception as e:
                Log.warning(f"fleet member {j} snapshot at iteration "
                            f"{i + 1} failed ({e}); training continues")
        evals = []
        if b._valid_names:
            if getattr(b, "_traced_eval", False):
                evals.extend(b.eval_valid_traced())
            else:
                evals.extend(b.eval_valid())
        env = CallbackEnv(model=b, params=mparams, iteration=i,
                          begin_iteration=0,
                          end_iteration=num_boost_round,
                          evaluation_result_list=evals)
        try:
            for cb in cbs_after:
                cb(env)
        except EarlyStopException as e:
            _apply_early_stop(b, prev, e, resume_start)
            break
        rounds[j] = i + 1
        if st:
            break
