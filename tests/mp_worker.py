"""Worker for the REAL multi-process distributed e2e test
(tests/test_multiprocess.py — the reference proves its network layer with N
localhost-socket processes, tests/distributed/_test_distributed.py:79-100;
this is the jax.distributed analog with genuine cross-process gloo
collectives).

Each process: launch.init over localhost -> deterministic global data ->
launch.row_shard -> distributed bin mappers (sharded FindBin + allgather)
-> data-parallel tree growth over the 2-process mesh -> rank 0 dumps the
tree for comparison with a single-process run.
"""

import json
import os
import sys


def main():
    rank = int(sys.argv[1])
    nproc = int(sys.argv[2])
    port = sys.argv[3]
    out = sys.argv[4]

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from lightgbm_tpu.utils.compile_cache import enable_persistent_cache
    enable_persistent_cache()   # pods re-pay every compile without it
    from lightgbm_tpu.parallel import launch

    # the REAL init path: explicit coordinator, real processes
    launch.init(coordinator_address=f"127.0.0.1:{port}",
                num_processes=nproc, process_id=rank)
    assert jax.process_count() == nproc, jax.process_count()
    assert len(jax.devices()) == nproc

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.parallel import make_mesh
    from lightgbm_tpu.parallel.data_parallel import make_dp_grower
    from lightgbm_tpu.ops.split import SplitParams

    rng = np.random.RandomState(0)
    n, f = 4096, 10
    x = rng.randn(n, f).astype(np.float64)
    y = (x[:, 0] - 0.7 * x[:, 1] > 0).astype(np.float32)

    shard = launch.row_shard(x, y)
    assert shard.process_count == nproc
    assert len(shard.x) == n // nproc

    # distributed binning: sharded FindBin + mapper allgather over the
    # real process group (dataset_loader.cpp:1009 analog)
    cfg = Config({"max_bin": 31})
    mappers = launch.global_bin_mappers(shard.sample(2048), cfg)
    assert len(mappers) == f

    local_binned = np.column_stack(
        [mappers[j].value_to_bin(shard.x[:, j]) for j in range(f)]
    ).astype(np.uint8)
    g_local = (1.0 / (1.0 + np.exp(-0.0)) - shard.y).astype(np.float32)
    h_local = np.full(len(shard.x), 0.25, np.float32)
    vals_local = np.stack([g_local, h_local, np.ones_like(g_local)], axis=1)

    mesh = make_mesh((nproc,), ("data",))
    sh = NamedSharding(mesh, P("data", None))
    binned = jax.make_array_from_process_local_data(sh, local_binned)
    vals = jax.make_array_from_process_local_data(sh, vals_local)

    B = max(m.num_bin for m in mappers)
    grow = make_dp_grower(mesh, num_leaves=15, num_bins=B,
                          params=SplitParams(min_data_in_leaf=5))
    num_bin = jnp.asarray([m.num_bin for m in mappers], jnp.int32)
    na_bin = jnp.asarray([m.na_bin for m in mappers], jnp.int32)
    arrays = grow(binned, vals, jnp.ones(f, bool), num_bin, na_bin)

    rec = {
        "num_leaves": int(arrays.num_leaves),
        "split_feature": np.asarray(arrays.split_feature).tolist(),
        "threshold_bin": np.asarray(arrays.threshold_bin).tolist(),
        "leaf_value": np.asarray(arrays.leaf_value).round(6).tolist(),
        # full mapper state so the single-process reference run bins with
        # EXACTLY the distributed-fitted mappers (distributed FindBin uses
        # per-process samples by design, dataset_loader.cpp:1009)
        "mappers": [{"bounds": [float(v) for v in m.bin_upper_bound],
                     "num_bin": int(m.num_bin), "na_bin": int(m.na_bin)}
                    for m in mappers],
    }
    if rank == 0:
        with open(out, "w") as fh:
            json.dump(rec, fh)
    print(f"rank {rank}: tree with {rec['num_leaves']} leaves", flush=True)


if __name__ == "__main__":
    main()
