"""Tests for BinMapper / Dataset (test_basic.py analog, SURVEY.md §4)."""

import numpy as np
import pytest

from lightgbm_tpu.binning import BinMapper, BinType, MissingType
from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import Dataset


class TestBinMapper:
    def test_uniform_values(self):
        m = BinMapper()
        vals = np.linspace(-1, 1, 1000)
        m.find_bin(vals, 1000, max_bin=16, min_data_in_bin=3)
        assert 2 <= m.num_bin <= 16
        bins = m.value_to_bin(vals)
        assert bins.min() == 0
        assert bins.max() == m.num_bin - 1
        # monotone: larger value -> same or larger bin
        assert (np.diff(bins) >= 0).all()
        # roughly equal counts — EXCLUDING the reserved zero bin
        # (FindBinWithZeroAsOneBin always carves out (-eps, eps]; with
        # no exact zeros in the data that bin is legitimately empty)
        counts = np.bincount(bins)
        nz = counts[counts > 0]
        assert nz.max() <= 3 * nz.min() + 10

    def test_few_distinct_values(self):
        m = BinMapper()
        vals = np.repeat([1.0, 2.0, 5.0], 100)
        m.find_bin(vals, 300, max_bin=255, min_data_in_bin=3)
        bins = m.value_to_bin(np.array([1.0, 2.0, 5.0]))
        assert len(set(bins.tolist())) == 3

    def test_min_data_in_bin_merges(self):
        m = BinMapper()
        vals = np.concatenate([np.zeros(100), np.ones(2), np.full(100, 2.0)])
        m.find_bin(vals, 202, max_bin=255, min_data_in_bin=5)
        b0, b1, b2 = m.value_to_bin(np.array([0.0, 1.0, 2.0]))
        assert b1 in (b0, b2)  # tiny middle group merged into a neighbor

    def test_nan_missing(self):
        m = BinMapper()
        vals = np.array([1.0, 2.0, 3.0, np.nan, np.nan, 4.0] * 50)
        m.find_bin(vals, 300, max_bin=16, min_data_in_bin=1)
        assert m.missing_type == MissingType.NAN
        bins = m.value_to_bin(np.array([1.0, np.nan]))
        assert bins[1] == m.num_bin - 1
        assert bins[0] < m.num_bin - 1

    def test_zero_as_missing(self):
        m = BinMapper()
        vals = np.array([0.0, 1.0, 2.0, 3.0] * 50)
        m.find_bin(vals, 200, max_bin=16, min_data_in_bin=1, zero_as_missing=True)
        assert m.missing_type == MissingType.ZERO
        bz, bn = m.value_to_bin(np.array([0.0, np.nan]))
        assert bz == bn  # NaN goes to the zero bin

    def test_categorical(self):
        m = BinMapper()
        vals = np.concatenate([np.full(100, 7.0), np.full(50, 3.0), np.full(10, 9.0)])
        m.find_bin(vals, 160, max_bin=32, min_data_in_bin=1,
                   bin_type=BinType.CATEGORICAL)
        assert m.bin_type == BinType.CATEGORICAL
        bins = m.value_to_bin(np.array([7.0, 3.0, 9.0]))
        assert bins[0] == 0  # most frequent category -> bin 0
        assert len(set(bins.tolist())) == 3
        # unseen category falls back to bin 0 semantics handled at split level
        assert m.value_to_bin(np.array([123.0]))[0] == 0

    def test_roundtrip_state(self):
        m = BinMapper()
        vals = np.random.RandomState(0).randn(500)
        m.find_bin(vals, 500, max_bin=32, min_data_in_bin=3)
        m2 = BinMapper.from_state(m.to_state())
        x = np.random.RandomState(1).randn(100)
        np.testing.assert_array_equal(m.value_to_bin(x), m2.value_to_bin(x))


class TestDataset:
    def test_basic_construct(self):
        rs = np.random.RandomState(0)
        x = rs.randn(500, 5)
        x[:, 3] = 1.0  # constant -> trivial, dropped
        y = rs.rand(500)
        ds = Dataset(x, label=y, params={"max_bin": 15}).construct()
        assert ds.num_data == 500
        assert ds.num_total_features == 5
        assert 3 not in ds.used_features
        assert ds.binned.shape == (500, len(ds.used_features))
        assert ds.binned.dtype == np.uint8
        assert ds.max_bin <= 15
        np.testing.assert_allclose(ds.get_label(), y, rtol=1e-6)

    def test_valid_aligned_to_train(self):
        rs = np.random.RandomState(1)
        xt = rs.randn(400, 4)
        xv = rs.randn(100, 4)
        train = Dataset(xt, label=rs.rand(400)).construct()
        valid = train.create_valid(xv, label=rs.rand(100)).construct()
        assert valid.bin_mappers is train.bin_mappers
        assert valid.binned.shape[1] == train.binned.shape[1]

    def test_group_and_weight(self):
        rs = np.random.RandomState(2)
        x = rs.randn(100, 3)
        ds = Dataset(x, label=rs.rand(100), weight=np.ones(100),
                     group=[30, 30, 40]).construct()
        assert ds.metadata.num_queries == 3
        assert ds.metadata.query_boundaries[-1] == 100
        with pytest.raises(ValueError):
            Dataset(x, label=rs.rand(100), group=[10, 10]).construct()

    def test_binary_cache_roundtrip(self, tmp_path):
        rs = np.random.RandomState(3)
        x = rs.randn(200, 4)
        ds = Dataset(x, label=rs.rand(200), weight=rs.rand(200)).construct()
        p = str(tmp_path / "cache.npz")
        ds.save_binary(p)
        ds2 = Dataset.load_binary(p)
        np.testing.assert_array_equal(ds.binned, ds2.binned)
        np.testing.assert_allclose(ds.get_label(), ds2.get_label())
        np.testing.assert_array_equal(ds.bin_offsets, ds2.bin_offsets)
        x2 = rs.randn(50)
        np.testing.assert_array_equal(ds.bin_mappers[0].value_to_bin(x2),
                                      ds2.bin_mappers[0].value_to_bin(x2))

    def test_subset(self):
        rs = np.random.RandomState(4)
        x = rs.randn(300, 4)
        y = rs.rand(300)
        ds = Dataset(x, label=y).construct()
        sub = ds.subset(np.arange(0, 300, 3))
        assert sub.num_data == 100
        np.testing.assert_allclose(sub.get_label(), y[::3])
        np.testing.assert_array_equal(sub.binned, ds.binned[::3])

    def test_pandas_categorical(self):
        pd = pytest.importorskip("pandas")
        rs = np.random.RandomState(5)
        df = pd.DataFrame({
            "a": rs.randn(300),
            "b": pd.Categorical(rs.choice(["x", "y", "z"], 300)),
        })
        ds = Dataset(df, label=rs.rand(300)).construct()
        assert ds.feature_names == ["a", "b"]
        assert ds.bin_mappers[1].bin_type == BinType.CATEGORICAL


def test_greedy_fast_path_matches_loop():
    """The no-big-values fast path in _greedy_find_bin (one binary
    search per bin) must reproduce the sequential accumulate-and-reset
    loop exactly, for unit and mixed counts."""
    from lightgbm_tpu.binning import _greedy_find_bin

    def loop_ref(dv, counts, max_bin, total, mdb):
        # the reference's EXACT sequential form (bin.cpp GreedyFindBin):
        # half-mean early close before big values, and the mean
        # recomputed from remaining small samples/bins on every close
        bounds = []
        if mdb > 0:
            max_bin = max(1, min(max_bin, total // mdb))
        m = total / max_bin
        is_big = counts >= m
        rest = total - int(counts[is_big].sum())
        rb = max_bin - int(is_big.sum())
        m = rest / rb if rb > 0 else np.inf
        cur = 0
        bc = 0
        n = len(dv)
        for i in range(n - 1):
            if not is_big[i]:
                rest -= int(counts[i])
            cur += int(counts[i])
            close = bool(is_big[i]) or cur >= m \
                or (bool(is_big[i + 1]) and cur >= max(1.0, m * 0.5))
            if close:
                bounds.append((float(dv[i]) + float(dv[i + 1])) / 2.0)
                bc += 1
                if bc >= max_bin - 1:
                    break
                cur = 0
                if not is_big[i]:
                    rb -= 1
                    m = rest / max(rb, 1)
        bounds.append(np.inf)
        return bounds

    rng = np.random.RandomState(7)
    for trial in range(60):
        dv = np.unique(rng.randn(rng.randint(80, 2000))
                       .astype(np.float32).astype(np.float64))
        counts = rng.randint(1, 4, size=len(dv)).astype(np.float64)
        mb = rng.randint(3, min(len(dv) - 1, 200))
        mdb = int(rng.choice([0, 1, 3, 10]))
        total = int(counts.sum())
        assert _greedy_find_bin(dv, counts, mb, total, mdb) \
            == loop_ref(dv, counts, mb, total, mdb), (trial, mb, mdb)
