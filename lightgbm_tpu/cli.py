"""Command-line application: config-file driven train / predict / refit.

Analog of the reference Application layer
(/root/reference/src/application/application.cpp:31-269 task dispatch +
src/main.cpp): ``python -m lightgbm_tpu config=train.conf [key=value ...]``
with the reference's config-file syntax (``key = value``, ``#`` comments).
"""

from __future__ import annotations

import os
import sys
import time
from typing import Dict, List

import numpy as np

from .booster import Booster
from .config import Config, kv2map, load_config_file
from .data_io import load_text
from .dataset import Dataset
from .engine import train as train_fn
from . import callback as cb


_BARE_TASKS = ("train", "predict", "refit", "serve", "continual",
               "fleet", "save_binary", "convert_model")


def _load_params(argv: List[str]) -> Dict[str, str]:
    # a bare leading task word is sugar for task=<word>:
    # ``python -m lightgbm_tpu serve input_model=model.txt``
    argv = list(argv)
    task_token = None
    if argv and "=" not in argv[0] and argv[0] in _BARE_TASKS:
        task_token = argv.pop(0)
    params = kv2map(argv)
    conf_path = params.pop("config", params.pop("config_file", None))
    if conf_path:
        file_params = load_config_file(conf_path)
        file_params.update(params)   # CLI overrides file (application.cpp:50)
        params = file_params
    if task_token is not None:
        params["task"] = task_token  # the bare word outranks the file
    return params


def run(argv: List[str]) -> int:
    params = _load_params(argv)
    cfg = Config(params)
    task = cfg.task
    if task == "train":
        return _task_train(cfg, params)
    if task in ("predict", "prediction", "test"):
        return _task_predict(cfg, params)
    if task == "refit":
        return _task_refit(cfg, params)
    if task == "serve":
        return _task_serve(cfg, params)
    if task == "continual":
        return _task_continual(cfg, params)
    if task == "fleet":
        return _task_fleet(cfg, params)
    if task == "save_binary":
        return _task_save_binary(cfg, params)
    if task == "convert_model":
        return _task_convert_model(cfg, params)
    print(f"Unknown task: {task}", file=sys.stderr)
    return 1


def _load_dataset(cfg: Config, path: str, params: Dict,
                  reference=None) -> Dataset:
    if path.endswith(".npz") or path.endswith(".bin"):
        return Dataset.load_binary(path)
    if cfg.ingest_enable or os.path.isdir(path):
        # streaming out-of-core ingest (lightgbm_tpu/ingest.py):
        # chunked + checkpointed + sketch-binned; a directory source
        # (one chunk per file) implies it
        from .ingest import ingest_dataset
        return ingest_dataset(path, params, has_header=cfg.header,
                              label_column=cfg.label_column,
                              reference=reference)
    x, y = load_text(path, has_header=cfg.header,
                     label_column=cfg.label_column)
    return Dataset(x, label=y, params=params, reference=reference)


def _task_train(cfg: Config, params: Dict) -> int:
    t0 = time.time()
    train_set = _load_dataset(cfg, cfg.data, params)
    valid_sets, valid_names = [], []
    for i, vpath in enumerate(cfg.valid or []):
        valid_sets.append(_load_dataset(cfg, str(vpath), params,
                                        reference=train_set))
        valid_names.append(f"valid_{i}")
    callbacks = []
    if cfg.verbosity > 0 and cfg.metric_freq > 0:
        callbacks.append(cb.log_evaluation(cfg.metric_freq))
    if cfg.is_provide_training_metric:
        params.setdefault("is_provide_training_metric", True)
    init_model = cfg.input_model or None
    booster = train_fn(params, train_set, num_boost_round=cfg.num_iterations,
                       valid_sets=valid_sets or None,
                       valid_names=valid_names or None,
                       init_model=init_model, callbacks=callbacks)
    booster.save_model(cfg.output_model)
    print(f"Finished training in {time.time() - t0:.2f} seconds; "
          f"model saved to {cfg.output_model}")
    if cfg.save_binary:
        train_set.save_binary(cfg.data + ".bin.npz")
    return 0


def _task_fleet(cfg: Config, params: Dict) -> int:
    """``task=fleet`` / ``python -m lightgbm_tpu fleet``: train N
    boosters over one dataset inside one vmapped program per epoch
    (docs/Fleet.md).  The roster comes from ``fleet_sweep`` (a
    ``param=v1|v2;...`` grid over member-axis params) or
    ``fleet_members`` (N seed replicas); each member's model is saved
    to ``<output_model>.member<j>``."""
    from .fleet import fleet_train
    t0 = time.time()
    train_set = _load_dataset(cfg, cfg.data, params)
    valid_sets, valid_names = [], []
    for i, vpath in enumerate(cfg.valid or []):
        valid_sets.append(_load_dataset(cfg, str(vpath), params,
                                        reference=train_set))
        valid_names.append(f"valid_{i}")
    result = fleet_train(params, train_set,
                         num_boost_round=cfg.num_iterations,
                         valid_sets=valid_sets or None,
                         valid_names=valid_names or None)
    for j, booster in enumerate(result.boosters):
        out = Config(result.member_params[j]).output_model
        booster.save_model(out)
        print(f"member {j}: {len(booster.trees)} trees"
              f"{' (early-stopped)' if result.stopped[j] else ''}"
              f" -> {out}")
    print(f"Finished fleet training ({len(result)} members, "
          f"{result.epochs} vmapped epochs) in "
          f"{time.time() - t0:.2f} seconds")
    return 0


def _task_predict(cfg: Config, params: Dict) -> int:
    booster = Booster(model_file=cfg.input_model)
    x, _ = load_text(cfg.data, has_header=cfg.header,
                     label_column=cfg.label_column)
    pred = booster.predict(
        x, raw_score=cfg.predict_raw_score,
        pred_leaf=cfg.predict_leaf_index, pred_contrib=cfg.predict_contrib,
        start_iteration=cfg.start_iteration_predict,
        num_iteration=cfg.num_iteration_predict)
    np.savetxt(cfg.output_result, np.asarray(pred), delimiter="\t", fmt="%g")
    print(f"Saved predictions to {cfg.output_result}")
    return 0


def _task_serve(cfg: Config, params: Dict) -> int:
    """``task=serve`` / ``python -m lightgbm_tpu serve``: long-lived
    HTTP prediction service (docs/Serving.md).  The model comes from
    ``input_model``, or — with ``resume=true`` — from the newest
    complete snapshot of ``output_model`` (hot-reloadable at runtime
    via ``POST /reload``).

    Shutdown is GRACEFUL: SIGTERM (the orchestrator's stop signal) and
    SIGINT first drain — new requests are refused with 503, queued
    work finishes within ``serve_drain_s`` — then the frontend and
    server close.  A second signal during the drain skips straight to
    exit."""
    import os
    import signal
    import threading as _threading

    from .serve.server import Server, start_http
    stop = _threading.Event()

    def _on_signal(signum, _frame):
        if stop.is_set():
            # second signal: the operator wants OUT NOW.  os._exit, not
            # SystemExit — an exception would still unwind through the
            # finally below, whose frontend/server closes join the very
            # worker the drain is already stuck on (up to ~5s), and the
            # orchestrator's kill grace would SIGKILL us mid-close
            print(f"serve: second signal {signum}; exiting immediately",
                  flush=True)
            os._exit(128 + signum)
        print(f"serve: received signal {signum}; draining "
              f"(budget {cfg.serve_drain_s:g}s)", flush=True)
        stop.set()

    # handlers BEFORE bring-up: a stop signal racing the announcement
    # (or arriving mid-bring-up) must drain, not kill the process
    previous = {s: signal.signal(s, _on_signal)
                for s in (signal.SIGTERM, signal.SIGINT)}
    try:
        server = Server(params)
        frontend = start_http(server, cfg.serve_host, cfg.serve_port,
                              background=True)
        health = server.health()
        model = health.get("model") or {}
        print(f"serving {model.get('source', '<none>')} "
              f"(version {model.get('version')}) on "
              f"http://{cfg.serve_host}:{frontend.port} — "
              f"/predict /healthz /metrics /reload /drain", flush=True)
        stop.wait()
        report = server.drain()
        print(f"serve: drain {'complete' if report['drained'] else 'TIMED OUT'}"
              f" ({report['leftover_rows']} rows left)", flush=True)
    finally:
        for s, h in previous.items():
            signal.signal(s, h)
        if "frontend" in locals():
            frontend.close()
        if "server" in locals():
            server.close()
    return 0


def _task_continual(cfg: Config, params: Dict) -> int:
    """``task=continual`` / ``python -m lightgbm_tpu continual``: the
    freshness-guaranteed continual boosting loop
    (docs/Continual-Training.md).  ``data`` is the base training file;
    each file in ``continual_data`` is appended as one generation —
    boost ``continual_rounds`` from the newest snapshot, publish a
    SHA-pinned artifact under ``output_model``, promote it through the
    two-stage gate (engine self-check + shadow parity probe), roll back
    and quarantine on any gate failure.  A serving process pointed at
    the same ``output_model`` (``task=serve resume=true``) hot-reloads
    the published generations via ``POST /promote``.  Prints one JSON
    report per generation; exit 0 when at least one generation
    published."""
    import json as _json

    from .pipeline.continual import ContinualTrainer
    t0 = time.time()
    base_x, base_y = load_text(cfg.data, has_header=cfg.header,
                               label_column=cfg.label_column)
    trainer = ContinualTrainer(params, base_x, base_y)
    # the base generation publishes the first incumbent (no parity gate
    # yet — there is nothing to compare against)
    reports = [trainer.run_generation()]
    for chunk_path in (cfg.continual_data or []):
        x, y = load_text(str(chunk_path), has_header=cfg.header,
                         label_column=cfg.label_column)
        reports.append(trainer.run_generation(x, y))
    for r in reports:
        print(_json.dumps(r, default=str))
    ok = sum(r["status"] == "published" for r in reports)
    rb = len(reports) - ok
    print(f"continual: {ok}/{len(reports)} generations published"
          f"{f', {rb} rolled back' if rb else ''} in "
          f"{time.time() - t0:.2f} seconds; newest artifact under "
          f"{cfg.output_model}.snapshot_iter_*")
    return 0 if ok else 1


def _task_refit(cfg: Config, params: Dict) -> int:
    booster = Booster(model_file=cfg.input_model)
    x, y = load_text(cfg.data, has_header=cfg.header,
                     label_column=cfg.label_column)
    refit_booster = refit(booster, x, y, cfg)
    refit_booster.save_model(cfg.output_model)
    print(f"Refit model saved to {cfg.output_model}")
    return 0


def _task_save_binary(cfg: Config, params: Dict) -> int:
    ds = _load_dataset(cfg, cfg.data, params)
    ds.construct(cfg)
    out = cfg.data + ".bin.npz"
    ds.save_binary(out)
    print(f"Saved binary dataset to {out}")
    return 0


def _task_convert_model(cfg: Config, params: Dict) -> int:
    """``task=convert_model`` (application.cpp ConvertModel,
    gbdt_model_text.cpp:124 ModelToIfElse): model file -> standalone C."""
    lang = (cfg.convert_model_language or "c").lower()
    if lang not in ("c", "cpp"):  # the emitted C compiles as C++ too
        raise ValueError(f"convert_model_language={lang!r} not supported "
                         "(use 'c' or 'cpp')")
    booster = Booster(model_file=cfg.input_model)
    out = cfg.convert_model
    with open(out, "w") as f:
        f.write(booster.to_c_code())
    print(f"Converted model saved to {out}")
    return 0


def refit_leaf_values(booster: Booster, leaf_preds: np.ndarray,
                      y: np.ndarray, cfg: Config) -> Booster:
    """GBDT::RefitTree core (gbdt.cpp:287-323) from GIVEN per-tree leaf
    assignments [N, num_trees]: per tree, recompute the regularized
    optimal output from the gradients at the evolving score, blended
    with ``refit_decay_rate`` (FitByExistingTree)."""
    if any(t.is_linear for t in booster.trees):
        raise ValueError(
            "refit is not supported for linear-tree models: only the "
            "constant leaf values would be re-fit, leaving the leaf linear "
            "models stale")
    from .objectives import create_objective
    obj = create_objective(booster.config)
    from .dataset import Metadata
    md = Metadata(len(y))
    md.set_label(y)
    obj.init(md, len(y))
    k = booster._num_tree_per_iteration
    import jax.numpy as jnp
    score = np.zeros((len(y), k), np.float64)
    decay = cfg.refit_decay_rate
    lam = booster.config.lambda_l2
    if leaf_preds.shape != (len(y), len(booster.trees)):
        raise ValueError(
            f"leaf_preds shape {leaf_preds.shape} != "
            f"({len(y)}, {len(booster.trees)})")
    for ti, tree in enumerate(booster.trees):
        kk = ti % k
        g, h = obj.get_gradients(jnp.asarray(score[:, kk], jnp.float32)
                                 if k == 1 else jnp.asarray(score, jnp.float32))
        g = np.asarray(g).reshape(len(y), -1)[:, kk]
        h = np.asarray(h).reshape(len(y), -1)[:, kk]
        leaves = leaf_preds[:, ti]
        for leaf in range(tree.num_leaves):
            m = leaves == leaf
            if not m.any():
                continue
            new_out = -g[m].sum() / (h[m].sum() + lam)
            tree.leaf_value[leaf] = (decay * tree.leaf_value[leaf]
                                     + (1.0 - decay) * new_out
                                     * tree.shrinkage)
        score[:, kk] += tree.leaf_value[leaves]
    booster._drop_predict_cache()        # leaf values changed in place
    return booster


def refit(booster: Booster, x: np.ndarray, y: np.ndarray,
          cfg: Config) -> Booster:
    """Re-fit leaf values of an existing structure on new data
    (GBDT::RefitTree, gbdt.cpp:287-323): route rows to leaves, then
    re-fit from the assignments."""
    leaf_preds = np.stack([t.predict_leaf(x) for t in booster.trees],
                          axis=1).astype(np.int32)
    return refit_leaf_values(booster, leaf_preds, y, cfg)


def main() -> int:
    return run(sys.argv[1:])


if __name__ == "__main__":
    sys.exit(main())
