"""Data-parallel tree learner: rows sharded over the mesh ``data`` axis.

TPU-native redesign of the reference DataParallelTreeLearner
(/root/reference/src/treelearner/data_parallel_tree_learner.cpp:13-283):

- rows live sharded; every shard builds LOCAL histograms for all features;
- the reference's ``Network::ReduceScatter(hists, HistogramSumReducer)``
  (:185) is a real ``lax.psum_scatter`` over a feature-chunked histogram
  layout: the feature-group axis is padded to ``n_shards`` equal chunks
  and reduce-scattered, so each shard ends up holding only ITS chunk of
  the GLOBAL histograms — the grower's per-shard histogram carry is
  ``[L, G/n_shards, B, 3]`` and per-chip histogram state stops scaling
  with the global feature width (the owner-shard memory shape the
  reference gets from ReduceScatter; arXiv:1611.01276's communication
  pattern for distributed tree induction);
- the split scan (ops/split.py) runs on the owned slice only; the
  per-shard best ``SplitResult`` is globalized back to global feature ids
  and allgathered (``SyncUpGlobalBestSplit``, parallel_tree_learner.h:191)
  — a few scalars plus the [B] rank vector per leaf cross the
  interconnect, never a histogram tensor;
- the histogram subtraction trick runs POST-scatter, on owned features
  only (parent chunk - smaller-child chunk);
- the root Σgrad/Σhess allreduce (:126-152) stays one tiny [3] psum;
- row partition stays local (no row data ever moves, like the reference).

``owner_shard=False`` restores the previous design — ONE full-tensor
``lax.psum`` of ``[F, B, 3]`` with the split decision recomputed
replicated on every shard — kept for A/B benchmarking
(tools/bench_hist.py --sharded) and as a config escape hatch
(``dp_owner_shard=false``).

The same grower program (grower.py) is used for both — distribution is a
``shard_map`` wrapper plus reduce/expand/select hooks, not a separate
learner implementation.  With ``efb`` the chunked axis is the BUNDLED
group axis — exactly where the reference bundles before its
reduce-scatter (dataset.cpp:239; data_parallel_tree_learner.cpp:174-186).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..grower import TreeArrays, make_grower
from ..obs.comm import CommLedger
from ..ops.histogram import pad_feature_axis
from ..ops.split import (SplitParams, SplitResult, gather_best,
                         globalize_feature)
from ..utils.jax_compat import shard_map
from ..utils.memo import memo_get_or_build
from .mesh import owner_shard_plan

# process-level memo of built dp growers (the voting/feature builders'
# _SHARED pattern, utils/memo.py): a leaf sweep inside one padded
# bucket — and every Booster the elastic recovery ladder constructs on
# the SAME topology while retrying a rung — shares one jitted program
# per (mesh, config family) instead of re-tracing per Booster.  Keyed
# through grower._grower_key so unkeyable configs simply build private
# programs (never a correctness risk).
import threading
from collections import OrderedDict

_SHARED: "OrderedDict[tuple, object]" = OrderedDict()
_SHARED_LOCK = threading.Lock()
_SHARED_MAX = 32


def pad_to_multiple(n: int, k: int) -> int:
    return (n + k - 1) // k * k


def shard_rows(mesh: Mesh, arr, axis: str = "data"):
    """Place a row-major array sharded over the mesh data axis (rows padded
    by the caller to a multiple of the axis size).

    Multi-process (one controller per host, the TPU-pod topology): ``arr``
    is each process's LOCAL rows and the global array is assembled with
    ``make_array_from_process_local_data`` — ``device_put`` of a global
    value is single-controller-only (every process would need the whole
    array, and JAX asserts the values match across processes).  The
    caller must have padded every process to the same local row count."""
    spec = P(axis, *([None] * (np.ndim(arr) - 1)))
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() > 1:
        return jax.make_array_from_process_local_data(sharding,
                                                      np.asarray(arr))
    return jax.device_put(jnp.asarray(arr), sharding)


def _dp_out_specs(axis: str) -> TreeArrays:
    """Tree fields replicated, the row->leaf vector row-sharded."""
    return TreeArrays(
        num_leaves=P(), split_feature=P(), threshold_bin=P(),
        default_left=P(), left_child=P(), right_child=P(), split_gain=P(),
        leaf_value=P(), leaf_weight=P(), leaf_count=P(), internal_value=P(),
        internal_weight=P(), internal_count=P(), leaf_depth=P(),
        leaf_of_row=P(axis), is_cat_node=P(), cat_rank=P(), n_steps=P())


def owner_hist_reduce(axis: str, n_shards: int, chunk: int,
                      ledger: CommLedger = None):
    """The ReduceScatter hook: pad the histogram's feature-group axis to
    ``n_shards * chunk`` rows and ``psum_scatter`` it, leaving each shard
    with its owned ``[chunk, B, C]`` slice of the GLOBAL histograms
    (data_parallel_tree_learner.cpp:185's communication shape; XLA
    lowers this to a true reduce-scatter over ICI, moving 1/n_shards of
    the bytes a full psum replicates to every chip).  ``ledger`` records
    the payload statically at trace time (obs/comm.py) — dtype-aware,
    so quantized training's int32 payload (exact integer reduce, half
    the reference's f64 ReduceScatter wire format) is accounted at its
    real width.  ``scales`` is the quant hook contract (grower.py
    ``_hist``); the reduce itself never needs it."""
    total = n_shards * chunk

    def hist_reduce(h, scales=None):
        h = pad_feature_axis(h, total)
        if ledger is not None:
            return ledger.psum_scatter(h, axis, site="dp.hist_reduce",
                                       scatter_dimension=0, tiled=True)
        return lax.psum_scatter(h, axis, scatter_dimension=0, tiled=True)

    return hist_reduce


def make_dp_grower(mesh: Mesh, *, num_leaves: int, num_bins: int,
                   params: SplitParams, max_depth: int = -1,
                   block_rows: int = 0, axis: str = "data", efb=None,
                   split_batch: int = 1, hist_overlap: bool = False,
                   mono=None,
                   mono_penalty: float = 0.0, sparse: bool = False,
                   owner_shard: bool = True,
                   padded_leaves=None, quant=None):
    """Jitted data-parallel ``grow_tree`` over ``mesh``.

    Inputs: binned [N, F] (or the bundled [N, G] group matrix when ``efb``
    is set) and vals [N, 3] sharded on rows; feature metadata replicated.
    Output tree arrays are replicated; ``leaf_of_row`` stays row-sharded.
    Child histograms use the masked full pass (gather tiers measured slower
    on TPU — PROFILE.md §2), which also keeps every shard's collective
    schedule trivially congruent.

    owner_shard=True (default): reduce-scatter + owned-slice split scan +
    best-split allgather (module docstring).  False: the legacy full
    ``lax.psum`` with replicated split decisions.
    """
    kw = dict(num_leaves=num_leaves, num_bins=num_bins, params=params,
              max_depth=max_depth, block_rows=block_rows, axis=axis,
              efb=efb, split_batch=split_batch,
              hist_overlap=hist_overlap, mono=mono,
              mono_penalty=mono_penalty, sparse=sparse,
              padded_leaves=padded_leaves, quant=quant)
    build = (lambda: _make_dp_owner_grower(mesh, **kw)) if owner_shard \
        else (lambda: _make_dp_psum_grower(mesh, **kw))

    from ..grower import _grower_key
    kw_key = dict(kw)
    if padded_leaves:
        # the padded budget is the trace-relevant leaf dimension; the
        # actual num_leaves rides in as the traced max_leaves argument,
        # so 31/63 inside one bucket share the memo entry
        kw_key["num_leaves"] = None
    key_part = _grower_key(kw_key)
    if key_part is None:
        inner = build()
    else:
        key = (tuple(int(d.id) for d in np.ravel(mesh.devices)),
               bool(owner_shard), key_part)
        inner = memo_get_or_build(_SHARED, _SHARED_LOCK, _SHARED_MAX,
                                  key, build)
    return _CollectiveGate(inner)


class _CollectiveGate:
    """Callable pass-through hosting the 'collective' fault-injection
    site (utils/faultinject.py) at the dispatch of the cross-shard
    histogram reduction program — one dict-empty check when inactive.
    Attribute access (e.g. the owner-shard ``plan``, attached to the
    inner grower lazily at first trace) delegates to the wrapped
    grower."""

    def __init__(self, inner):
        self._inner = inner

    def __call__(self, *args, **kwargs):
        from ..utils import faultinject
        faultinject.check("collective")
        return self._inner(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _quant_hooks(axis: str, ledger: CommLedger, quant,
                 site: str = "dp.quant_scale"):
    """Quantized-training hooks for the row-sharded learners: the [3]
    scale vector pmaxes across the mesh so every shard quantizes with
    the GLOBAL per-iteration scale, and the stochastic-rounding stream
    is keyed by GLOBAL row ids via this shard's row offset — together
    they make the int32 histogram reduce bitwise dp==serial
    (ops/quantize.py module docstring).  ``site`` names the pmax in the
    comm ledger — the voting learner reuses these hooks under its own
    label."""
    if quant is None:
        return dict(quant=None)
    return dict(
        quant=quant,
        scale_reduce=lambda s: ledger.pmax(s, axis, site=site,
                                           cadence="tree"),
        row_offset=lambda n_local: lax.axis_index(axis) * n_local)


def _make_dp_owner_grower(mesh: Mesh, *, num_leaves, num_bins, params,
                          max_depth, block_rows, axis, efb, split_batch,
                          hist_overlap=False,
                          mono, mono_penalty, sparse, padded_leaves=None,
                          quant=None):
    """Owner-shard data-parallel grower (see module docstring)."""
    n_shards = mesh.shape[axis]
    out_specs = _dp_out_specs(axis)
    cache = {}
    ledger = CommLedger(n_shards)     # static comm-bytes sites (obs/comm)

    def _build(nf: int, sparse_key=None):
        group_of = np.asarray(efb.group_host) if efb is not None \
            else np.arange(nf)
        plan = owner_shard_plan(group_of, n_shards)
        sf_dev = jnp.asarray(plan.shard_feat)        # [S, fmax] global ids
        chunk, fmax = plan.chunk, plan.fmax
        hist_reduce = owner_hist_reduce(axis, n_shards, chunk, ledger)

        def _gfid():
            """This shard's scan-slot -> global-feature map (in-graph)."""
            return sf_dev[lax.axis_index(axis)]

        if efb is not None:
            # per-shard EFB expansion: owned-groups histogram
            # [chunk, Bg, C] -> scan feature space [fmax, B, C], with the
            # FixHistogram default-bin reconstruction (dataset.cpp:1292)
            # done from the leaf totals on owned features only
            bg = int(efb.group_bins)
            g_of = efb.group_of_feat

            def hist_expand(gh, total):
                idx = lax.axis_index(axis)
                gfid = sf_dev[idx]
                safe = jnp.maximum(gfid, 0)
                ok = gfid >= 0
                glocal = jnp.clip(jnp.take(g_of, safe) - idx * chunk,
                                  0, gh.shape[0] - 1)
                src = jnp.take(gh, glocal, axis=0)       # [fmax, Bg, C]
                ci = jnp.take(efb.col_idx, safe, axis=0)  # [fmax, B]
                fh = jnp.take_along_axis(
                    src, jnp.clip(ci, 0, bg - 1)[:, :, None], axis=1)
                fh = jnp.where((ok[:, None] & (ci >= 0))[:, :, None],
                               fh, 0.0)
                rest = fh[:, 1:, :].sum(axis=1)
                bin0 = jnp.where((jnp.take(efb.fix0, safe) & ok)[:, None],
                                 total[None, :] - rest, fh[:, 0, :])
                return fh.at[:, 0, :].set(bin0)
        else:
            # unbundled: group == feature, owned features are the
            # contiguous chunk — the scan view just trims reduce padding
            def hist_expand(h, total):
                return lax.slice_in_dim(h, 0, fmax, axis=0)

        def mono_view(m):
            gfid = _gfid()
            return jnp.where(gfid >= 0,
                             jnp.take(m, jnp.maximum(gfid, 0)), 0)

        def select_best(res: SplitResult) -> SplitResult:
            ledger.note_all_gather(res, site="dp.best_split")
            return gather_best(globalize_feature(res, _gfid()), axis)

        inner = make_grower(
            num_leaves=num_leaves, num_bins=num_bins, params=params,
            max_depth=max_depth, block_rows=block_rows,
            hist_reduce=hist_reduce,
            sum_reduce=lambda t: ledger.psum(t, axis, site="dp.root_sum",
                                             cadence="tree"),
            hist_expand=hist_expand, select_best=select_best,
            efb=efb, split_batch=split_batch,
            hist_overlap=hist_overlap, mono=mono,
            mono_view=None if mono is None else mono_view,
            mono_penalty=mono_penalty, padded_leaves=padded_leaves,
            **_quant_hooks(axis, ledger, quant),
            jit=False)

        def _localize(fmask, nb, na, ic):
            """Scan-space metadata slices for this shard's owned
            features; pad slots are masked (and given harmless bins)."""
            gfid = _gfid()
            safe = jnp.maximum(gfid, 0)
            ok = gfid >= 0
            return (fmask[safe] & ok,
                    jnp.where(ok, nb[safe], 2),
                    jnp.where(ok, na[safe], -1),
                    ic[safe] & ok)

        if sparse_key is not None:
            from ..sparse_data import SparseBinned
            stride, nfs = sparse_key

            def wrapped(flat, db, vals, fmask, nb, na, nabp, ic, ml, ri):
                fm_l, nb_l, na_l, ic_l = _localize(fmask, nb, na, ic)
                return inner(SparseBinned(flat, db, stride, nfs), vals,
                             fm_l, nb_l, na_l, nabp, ic_l, rng_iter=ri,
                             num_bin_part=nb, max_leaves=ml)

            in_specs = (P(axis, None), P(None), P(axis, None),
                        P(), P(), P(), P(), P(), P(), P())
        else:
            def wrapped(binned, vals, fmask, nb, na, nabp, ic, ml, ri):
                fm_l, nb_l, na_l, ic_l = _localize(fmask, nb, na, ic)
                return inner(binned, vals, fm_l, nb_l, na_l, nabp, ic_l,
                             rng_iter=ri, num_bin_part=nb, max_leaves=ml)

            in_specs = (P(axis, None), P(axis, None),
                        P(), P(), P(), P(), P(), P(), P())

        fn = jax.jit(shard_map(wrapped, mesh=mesh, in_specs=in_specs,
                               out_specs=out_specs, check_vma=False))
        return fn, plan

    def grow(binned, vals, feature_mask, num_bin, na_bin, is_cat=None,
             max_leaves=None, rng_iter=None):
        if is_cat is None:
            is_cat = jnp.zeros(num_bin.shape[0], bool)
        ml = jnp.int32(num_leaves if max_leaves is None else max_leaves)
        # always a traced argument (0 when unused) so the jit signature
        # is stable whether or not quantized rounding consumes it
        ri = jnp.int32(0 if rng_iter is None else rng_iter)
        nf = int(num_bin.shape[0])
        if sparse:
            key = (nf, binned.stride, binned.num_features)
            if key not in cache:
                cache[key] = _build(nf, (binned.stride,
                                         binned.num_features))
            fn, plan = cache[key]
            grow.plan = plan
            return fn(binned.flat, binned.default_bin, vals, feature_mask,
                      num_bin, na_bin, na_bin, is_cat, ml, ri)
        if nf not in cache:
            cache[nf] = _build(nf)
        fn, plan = cache[nf]
        grow.plan = plan
        return fn(binned, vals, feature_mask, num_bin, na_bin, na_bin,
                  is_cat, ml, ri)

    grow.owner_shard = True
    grow.comm = ledger
    if efb is not None:
        # bundle structure is static: expose the plan before the first call
        grow.plan = owner_shard_plan(np.asarray(efb.group_host), n_shards)
    return grow


def _make_dp_psum_grower(mesh: Mesh, *, num_leaves, num_bins, params,
                         max_depth, block_rows, axis, efb, split_batch,
                         hist_overlap=False,
                         mono, mono_penalty, sparse, padded_leaves=None,
                         quant=None):
    """Legacy full-psum data-parallel grower: every shard receives ALL
    global histograms and recomputes the split decision replicated (no
    separate best-split sync needed — but per-chip histogram state scales
    with the full feature width; see the owner-shard default)."""
    ledger = CommLedger(mesh.shape[axis])
    inner = make_grower(
        num_leaves=num_leaves, num_bins=num_bins, params=params,
        max_depth=max_depth, block_rows=block_rows,
        hist_reduce=lambda h, scales=None: ledger.psum(
            h, axis, site="dp.hist_psum"),
        sum_reduce=lambda t: ledger.psum(t, axis, site="dp.root_sum",
                                         cadence="tree"),
        efb=efb,
        split_batch=split_batch, hist_overlap=hist_overlap,
        mono=mono, mono_penalty=mono_penalty,
        padded_leaves=padded_leaves,
        **_quant_hooks(axis, ledger, quant), jit=False)

    out_specs = _dp_out_specs(axis)

    if sparse:
        # SparseBinned pytree (sparse_data.py): the flat [N, K] entry
        # matrix shards on rows while the [F] default_bin vector is
        # replicated — a single prefix spec cannot describe both leaves,
        # so the wrapper ships the leaves as separate shard_map arguments
        # and rebuilds the pytree inside (stride/F are static aux, cached
        # per shape).
        from ..sparse_data import SparseBinned
        cache = {}

        def _sparse_fn(stride: int, nf: int):
            def wrapped(flat, db, vals, fm, nb, nab, nabp, ic, ml, ri):
                return inner(SparseBinned(flat, db, stride, nf), vals,
                             fm, nb, nab, nabp, ic, rng_iter=ri,
                             max_leaves=ml)
            return shard_map(
                wrapped, mesh=mesh,
                in_specs=(P(axis, None), P(None), P(axis, None),
                          P(), P(), P(), P(), P(), P(), P()),
                out_specs=out_specs, check_vma=False)

        def grow(binned, vals, feature_mask, num_bin, na_bin, is_cat=None,
                 max_leaves=None, rng_iter=None):
            if is_cat is None:
                is_cat = jnp.zeros(num_bin.shape[0], bool)
            ml = jnp.int32(num_leaves if max_leaves is None else max_leaves)
            ri = jnp.int32(0 if rng_iter is None else rng_iter)
            key = (binned.stride, binned.num_features)
            if key not in cache:
                cache[key] = jax.jit(_sparse_fn(*key))
            return cache[key](binned.flat, binned.default_bin, vals,
                              feature_mask, num_bin, na_bin, na_bin,
                              is_cat, ml, ri)

        grow.owner_shard = False
        grow.comm = ledger
        return grow

    def _dense(b, v, fm, nb, na, ic, ml, ri):
        # na doubles as na_bin_part (the old outside-the-shard_map
        # duplication, folded in), so _dense has 8 params — in_specs
        # must match that arity, not inner's
        return inner(b, v, fm, nb, na, na, ic, rng_iter=ri, max_leaves=ml)

    f = shard_map(
        _dense, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P(), P(), P(), P(), P(),
                  P()),
        out_specs=out_specs, check_vma=False)

    jitted = jax.jit(f)

    def grow(binned, vals, feature_mask, num_bin, na_bin, is_cat=None,
             max_leaves=None, rng_iter=None):
        if is_cat is None:
            is_cat = jnp.zeros(num_bin.shape[0], bool)
        ml = jnp.int32(num_leaves if max_leaves is None else max_leaves)
        ri = jnp.int32(0 if rng_iter is None else rng_iter)
        return jitted(binned, vals, feature_mask, num_bin, na_bin, is_cat,
                      ml, ri)

    grow.owner_shard = False
    grow.comm = ledger
    return grow
