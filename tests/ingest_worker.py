"""Subprocess worker for the ingest kill -9 resume test
(tests/test_ingest.py, the ``elastic_worker.py`` mold).

One mode: ingest ``<outdir>/train.csv`` through the streaming pipeline
(spool at ``<outdir>/<spoolname>``), train a small model, and write its
text (parameters section stripped) to ``<outdir>/model_<tag>.txt``.

The DRIVER arms the death: exporting
``LGBM_TPU_FAULTS="ingest_read:<k>:exit"`` makes the k-th chunk read
``os._exit(23)`` — a real mid-ingest death between chunk commits, after
k-1 manifests landed.  A second invocation without the fault must
resume from the manifests (never re-reading the committed chunks) and
produce a model byte-identical to an uninterrupted run in a fresh
spool.  Prints ``WORKER_DONE resumed=<n>`` on success.

Usage: python ingest_worker.py <outdir> <spoolname> <tag>
"""

import os
import sys

ROUNDS = 8
CHUNK_ROWS = 150


def main():
    outdir, spoolname, tag = sys.argv[1], sys.argv[2], sys.argv[3]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from lightgbm_tpu.utils.compile_cache import enable_persistent_cache
    enable_persistent_cache()
    import lightgbm_tpu as lgb

    params = {"objective": "binary", "num_leaves": 8, "max_bin": 31,
              "min_data_in_leaf": 5, "verbosity": -1,
              "ingest_chunk_rows": CHUNK_ROWS,
              "ingest_retries": 0}
    ds = lgb.ingest_dataset(os.path.join(outdir, "train.csv"), params,
                            spool_dir=os.path.join(outdir, spoolname))
    resumed = ds.ingest_report["resumed_chunks"]
    bst = lgb.train(params, ds, num_boost_round=ROUNDS)
    with open(os.path.join(outdir, f"model_{tag}.txt"), "w",
              encoding="utf-8") as f:
        f.write(bst.model_to_string().split("parameters:")[0])
    print(f"WORKER_DONE resumed={resumed}", flush=True)


if __name__ == "__main__":
    main()
