from .log import Log, register_log_callback
from .timer import FunctionTimer, global_timer
