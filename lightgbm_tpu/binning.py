"""Feature discretization (value -> bin) for the TPU GBDT.

Re-implements the reference BinMapper semantics
(/root/reference/include/LightGBM/bin.h:61-235, src/io/bin.cpp ``FindBin`` /
``GreedyFindBin``): greedy equal-count numerical binning with
``min_data_in_bin``, a dedicated zero bin (|v| <= kZeroThreshold), three
missing-value modes (None/Zero/NaN, bin.h ``MissingType``), and count-sorted
categorical bins.  Host-side preprocessing in NumPy (the reference also bins
on CPU); the binned matrix handed to the learner is a device array.
"""

from __future__ import annotations

import enum
from typing import Dict, List, Optional, Sequence

import numpy as np

kZeroThreshold = 1e-35


class BinType(enum.Enum):
    NUMERICAL = 0
    CATEGORICAL = 1


class MissingType(enum.Enum):
    NONE = 0
    ZERO = 1
    NAN = 2


def _forced_find_bin(distinct_values: np.ndarray, counts: np.ndarray,
                     max_bin: int, total_cnt: int, min_data_in_bin: int,
                     forced_bounds) -> List[float]:
    """FindBinWithPredefinedBin analog (src/io/bin.cpp; forced bounds come
    from ``forcedbins_filename``, dataset_loader.cpp:519-524): the forced
    upper bounds become mandatory boundaries; the remaining bin budget is
    distributed over the inter-boundary segments proportionally to their
    sample counts and filled greedily within each segment."""
    forced = sorted({float(f) for f in forced_bounds})
    lo = float(distinct_values[0]) if len(distinct_values) else 0.0
    hi = float(distinct_values[-1]) if len(distinct_values) else 0.0
    forced = [f for f in forced if lo <= f < hi][:max(max_bin - 1, 0)]
    if not forced:
        return _greedy_find_bin(distinct_values, counts, max_bin, total_cnt,
                                min_data_in_bin)
    edges = [-np.inf] + forced + [np.inf]
    seg_budget_total = max_bin - len(forced)
    segs = []
    for i in range(len(edges) - 1):
        m = (distinct_values > edges[i]) & (distinct_values <= edges[i + 1])
        segs.append((distinct_values[m], counts[m]))
    seg_cnts = np.array([int(c.sum()) for _, c in segs], dtype=np.float64)
    weights = seg_cnts / max(seg_cnts.sum(), 1.0)
    bounds: List[float] = list(forced)
    for (vals, cnts), w in zip(segs, weights):
        if len(vals) == 0:
            continue
        b = max(1, int(round(seg_budget_total * w)))
        sub = _greedy_find_bin(vals, cnts, b, int(cnts.sum()),
                               min_data_in_bin)
        bounds.extend(x for x in sub if np.isfinite(x))
    uniq = sorted(set(bounds))
    if len(uniq) > max_bin - 1:
        # per-segment minimum budgets (max(1, ...)) can overshoot; drop
        # GREEDY bounds only — forced boundaries are mandatory (they were
        # already capped to max_bin-1 above, so they always fit)
        fset = set(forced)
        greedy_keep = (max_bin - 1) - len(fset)
        uniq = sorted(fset | set(
            [x for x in uniq if x not in fset][:max(greedy_keep, 0)]))
    return uniq + [np.inf]


def _zero_aware_find_bin(distinct: np.ndarray, counts: np.ndarray,
                         max_bin: int, total_cnt: int,
                         min_data_in_bin: int) -> np.ndarray:
    """FindBinWithZeroAsOneBin (bin.cpp:256): the numeric axis is split
    at zero — negative values bin with a budget proportional to their
    share, the band (-kZeroThreshold, kZeroThreshold] is ALWAYS its own
    bin whenever positive values exist (even with zero count 0: the
    reference reserves it so unseen zeros at prediction time land in a
    well-defined bin), and positives take the remaining budget.
    ``distinct`` is sorted with near-zeros already collapsed to 0.0."""
    left = distinct < 0.0
    right = distinct > 0.0
    cnt_zero = int(counts[(~left) & (~right)].sum())
    left_cnt = int(counts[left].sum())
    right_cnt = int(counts[right].sum())
    bounds: List[float] = []
    if left.any() and max_bin > 1:
        denom = max(total_cnt - cnt_zero, 1)
        left_max_bin = max(1, int(left_cnt / denom * (max_bin - 1)))
        lb = _greedy_find_bin(distinct[left], counts[left], left_max_bin,
                              left_cnt, min_data_in_bin)
        if lb:
            lb[-1] = -kZeroThreshold
        bounds = list(lb)
    right_max_bin = max_bin - 1 - len(bounds)
    if right.any() and right_max_bin > 0:
        rb = _greedy_find_bin(distinct[right], counts[right],
                              right_max_bin, right_cnt, min_data_in_bin)
        bounds.append(kZeroThreshold)
        bounds.extend(rb)
    else:
        bounds.append(np.inf)
    return np.asarray(bounds, dtype=np.float64)


def _greedy_find_bin(distinct_values: np.ndarray, counts: np.ndarray,
                     max_bin: int, total_cnt: int, min_data_in_bin: int) -> List[float]:
    """Greedy equal-count bin upper bounds over sorted distinct values.

    Equivalent of GreedyFindBin (src/io/bin.cpp): when few distinct values,
    one bin per value (merged up to min_data_in_bin); otherwise large-count
    values get dedicated bins and the rest are accumulated to the running
    mean bin size.  Returns upper bounds; last bound is +inf.
    """
    bounds: List[float] = []
    num_distinct = len(distinct_values)
    if num_distinct == 0:
        return [np.inf]
    if num_distinct <= max_bin:
        cur_cnt = 0
        for i in range(num_distinct - 1):
            cur_cnt += int(counts[i])
            if cur_cnt >= min_data_in_bin:
                bounds.append((float(distinct_values[i]) + float(distinct_values[i + 1])) / 2.0)
                cur_cnt = 0
        bounds.append(np.inf)
        return bounds

    if min_data_in_bin > 0:
        max_bin = max(1, min(max_bin, total_cnt // min_data_in_bin))
    mean_bin_size = total_cnt / max_bin
    # values whose count alone exceeds the mean get their own bin
    is_big = counts >= mean_bin_size
    rest_cnt = total_cnt - int(counts[is_big].sum())
    rest_bins = max_bin - int(is_big.sum())
    if rest_bins > 0:
        mean_bin_size = rest_cnt / rest_bins
    else:
        mean_bin_size = np.inf

    if not is_big.any():
        # continuous fast path (no value large enough to demand its own
        # bin — the overwhelmingly common case for real-valued columns):
        # the sequential accumulate-and-reset closes a bin at the first
        # index where the count accumulated since the last close reaches
        # mean_bin_size, i.e. at searchsorted(cumsum, last + mean) —
        # one binary search per BIN instead of one Python iteration per
        # DISTINCT VALUE (a 2000-feature Epsilon-shaped construct spent
        # ~50 s in this loop; this form is milliseconds).  Output is
        # identical to the loop below.
        cum = np.cumsum(counts)
        total = float(cum[-1])
        last = 0.0
        for closed in range(max_bin - 1):
            j = int(np.searchsorted(cum, last + mean_bin_size,
                                    side="left"))
            if j >= num_distinct - 1:
                break
            bounds.append((float(distinct_values[j])
                           + float(distinct_values[j + 1])) / 2.0)
            last = float(cum[j])
            # adaptive mean (bin.cpp GreedyFindBin recomputes
            # mean_bin_size from the REMAINING samples and bins after
            # every close) — a fixed mean drifts high when early bins
            # overshoot and silently loses tail bins
            mean_bin_size = (total - last) / (max_bin - closed - 1)
        bounds.append(np.inf)
        return bounds

    # mixed big/small values: the reference's sequential form with BOTH
    # of its subtleties — a pending small bin closes early before a big
    # value only once it holds >= half the mean, and the mean is
    # recomputed from the REMAINING small samples/bins after every
    # small-bin close (GreedyFindBin, bin.cpp:78)
    cur_cnt = 0
    bin_cnt = 0
    rest_sample = rest_cnt
    for i in range(num_distinct - 1):
        if not is_big[i]:
            rest_sample -= int(counts[i])
        cur_cnt += int(counts[i])
        close = (bool(is_big[i]) or cur_cnt >= mean_bin_size
                 or (bool(is_big[i + 1])
                     and cur_cnt >= max(1.0, mean_bin_size * 0.5)))
        if close:
            bounds.append((float(distinct_values[i]) + float(distinct_values[i + 1])) / 2.0)
            bin_cnt += 1
            if bin_cnt >= max_bin - 1:
                break
            cur_cnt = 0
            if not is_big[i]:
                rest_bins -= 1
                mean_bin_size = rest_sample / max(rest_bins, 1)
    bounds.append(np.inf)
    return bounds


class BinMapper:
    """Per-feature value->bin mapping (bin.h:61-235 analog)."""

    def __init__(self):
        self.num_bin: int = 1
        self.bin_type: BinType = BinType.NUMERICAL
        self.missing_type: MissingType = MissingType.NONE
        self.is_trivial: bool = True
        self.bin_upper_bound: np.ndarray = np.array([np.inf])
        # categorical
        self.categories: np.ndarray = np.array([], dtype=np.int64)  # bin i -> category
        self._cat_to_bin: Dict[int, int] = {}
        self.default_bin: int = 0      # bin of value 0.0 (most common for sparse)
        self.most_freq_bin: int = 0
        self.sparse_rate: float = 0.0
        # exact fraction of the fit sample that lands in bin 0 (incl.
        # NaNs when they map there); 1.0 = "unknown" — the conservative
        # value for the EFB pigeonhole pre-check (dataset.py), which
        # needs a LOWER bound on the non-default rate
        self.bin0_frac: float = 1.0

    # -- fit ---------------------------------------------------------------
    def find_bin(self, values: np.ndarray, total_sample_cnt: int, max_bin: int,
                 min_data_in_bin: int, min_split_data: int = 0,
                 pre_filter: bool = False, bin_type: BinType = BinType.NUMERICAL,
                 use_missing: bool = True, zero_as_missing: bool = False,
                 forced_bounds: Optional[Sequence[float]] = None) -> None:
        """Fit the mapping from sampled ``values`` (bin.cpp FindBin analog).

        ``values`` are the sampled non-trivial rows; zeros that were not
        sampled are accounted through ``total_sample_cnt``.
        """
        values = np.asarray(values, dtype=np.float64)
        na_cnt = int(np.isnan(values).sum())
        vals = values[~np.isnan(values)]
        zero_cnt = int(total_sample_cnt - len(vals) - na_cnt
                       + (np.abs(vals) <= kZeroThreshold).sum())

        if not use_missing:
            self.missing_type = MissingType.NONE
        elif zero_as_missing:
            self.missing_type = MissingType.ZERO
        else:
            self.missing_type = MissingType.NAN if na_cnt > 0 else MissingType.NONE

        if bin_type == BinType.CATEGORICAL:
            self._find_bin_categorical(vals, total_sample_cnt, max_bin, min_data_in_bin)
            return

        # collapse |v|<=eps to exactly 0 so the zero bin is well defined
        vals = np.where(np.abs(vals) <= kZeroThreshold, 0.0, vals)
        n_implicit_zero = total_sample_cnt - len(values)
        distinct, counts = np.unique(vals, return_counts=True)
        if len(distinct) > 0 and n_implicit_zero > 0:
            zpos = np.searchsorted(distinct, 0.0)
            if zpos < len(distinct) and distinct[zpos] == 0.0:
                counts[zpos] += n_implicit_zero
            else:
                distinct = np.insert(distinct, zpos, 0.0)
                counts = np.insert(counts, zpos, n_implicit_zero)
        elif len(distinct) == 0 and n_implicit_zero > 0:
            distinct, counts = np.array([0.0]), np.array([n_implicit_zero])

        self._fit_numerical_from_distinct(
            distinct, counts, na_cnt, max_bin, min_data_in_bin,
            min_split_data, pre_filter, forced_bounds)

    def _fit_numerical_from_distinct(
            self, distinct: np.ndarray, counts: np.ndarray, na_cnt: int,
            max_bin: int, min_data_in_bin: int, min_split_data: int = 0,
            pre_filter: bool = False,
            forced_bounds: Optional[Sequence[float]] = None) -> None:
        """The numerical FindBin tail shared by the raw-values path above
        and the streaming sketch path (:meth:`find_bin_from_sketch`):
        ``distinct``/``counts`` are the sorted distinct non-NaN values
        (|v| <= kZeroThreshold already collapsed to 0.0, implicit zeros
        already merged) with their sample counts.  ``self.missing_type``
        must already be decided by the caller."""
        distinct = np.asarray(distinct, dtype=np.float64)
        counts = np.asarray(counts)
        zero_cnt = int(counts[distinct == 0.0].sum()) if len(distinct) else 0
        budget = max_bin - 1 if self.missing_type == MissingType.NAN else max_bin
        budget = max(budget, 2) if len(distinct) > 1 else max(budget, 1)
        total_non_na = int(counts.sum())
        if forced_bounds:
            bounds = _forced_find_bin(distinct, counts, budget, total_non_na,
                                      min_data_in_bin, forced_bounds)
        else:
            bounds = _zero_aware_find_bin(distinct, counts, budget,
                                          total_non_na, min_data_in_bin)

        # make sure zero sits alone in its bin boundary band when present
        # (FindBin carves [-kZeroThreshold, kZeroThreshold] out, bin.cpp)
        ub = np.array(bounds, dtype=np.float64)
        self.bin_upper_bound = ub
        self.num_bin = len(ub) + (1 if self.missing_type == MissingType.NAN else 0)
        self.is_trivial = self.num_bin <= 1
        if not self.is_trivial and pre_filter and min_split_data > 0:
            # NeedFilter (bin.cpp:54): a feature is useful only if SOME
            # threshold puts >= min_split_data rows on both sides —
            # e.g. a constant non-zero column has 2 bins (the reserved
            # zero bin is empty) but can never split, so it is trivial
            cnt_in_bin = np.zeros(len(ub), np.int64)
            np.add.at(cnt_in_bin, np.searchsorted(ub, distinct,
                                                  side="left"),
                      counts.astype(np.int64))
            if self.missing_type == MissingType.NAN:
                cnt_in_bin = np.append(cnt_in_bin, na_cnt)
            left = np.cumsum(cnt_in_bin[:-1])
            total_all = int(cnt_in_bin.sum())
            if not ((left >= min_split_data)
                    & (total_all - left >= min_split_data)).any():
                self.is_trivial = True
        # bin of literal zero / most frequent bin
        self.default_bin = int(np.searchsorted(ub, 0.0, side="left"))
        if len(counts) > 0:
            mf_val = distinct[int(np.argmax(counts))]
            self.most_freq_bin = int(np.searchsorted(ub, mf_val, side="left"))
            self.sparse_rate = float(counts.max() / max(total_non_na, 1))
        # exact bin-0 occupancy of the sample: cumulative count of the
        # distinct values at/below the first upper bound (bin 0 may merge
        # SEVERAL distinct values — sparse_rate, the single most frequent
        # VALUE's share, underestimates it), plus NaN rows when the
        # missing policy routes them to the zero bin
        if len(counts) > 0 and len(ub) > 0:
            in_bin0 = int(counts[distinct <= ub[0]].sum())
            if self.missing_type == MissingType.ZERO:
                in_bin0 += na_cnt
            self.bin0_frac = in_bin0 / max(total_non_na + na_cnt, 1)
        if self.missing_type == MissingType.ZERO and zero_cnt + na_cnt == 0:
            self.missing_type = MissingType.NONE

    def _find_bin_categorical(self, vals: np.ndarray, total_sample_cnt: int,
                              max_bin: int, min_data_in_bin: int) -> None:
        cats = vals.astype(np.int64)
        cats = cats[cats >= 0]  # negative categoricals treated as missing (bin.cpp warns)
        uniq, counts = np.unique(cats, return_counts=True)
        self._fit_categorical_from_distinct(uniq, counts, max_bin)

    def _fit_categorical_from_distinct(self, uniq: np.ndarray,
                                       counts: np.ndarray,
                                       max_bin: int) -> None:
        """Categorical FindBin tail over distinct non-negative categories
        and their counts (shared with the sketch path)."""
        self.bin_type = BinType.CATEGORICAL
        if len(uniq) == 0:
            self.num_bin = 1
            self.is_trivial = True
            return
        order = np.argsort(-counts, kind="stable")  # count-sorted, most frequent first
        uniq, counts = uniq[order], counts[order]
        # drop overly rare cats beyond the bin budget (rare -> unseen at split)
        keep = min(len(uniq), max_bin - 1 if self.missing_type != MissingType.NONE else max_bin)
        cut = counts >= 1
        uniq, counts = uniq[:keep][cut[:keep]], counts[:keep][cut[:keep]]
        self.categories = uniq
        self._cat_to_bin = {int(c): i for i, c in enumerate(uniq)}
        self.num_bin = len(uniq) + (1 if self.missing_type == MissingType.NAN else 0)
        self.is_trivial = len(uniq) <= 1
        self.most_freq_bin = 0
        self.default_bin = self._cat_to_bin.get(0, 0)

    def find_bin_from_sketch(self, sketch: "QuantileSketch", max_bin: int,
                             min_data_in_bin: int, min_split_data: int = 0,
                             pre_filter: bool = False,
                             use_missing: bool = True,
                             zero_as_missing: bool = False,
                             forced_bounds: Optional[Sequence[float]] = None
                             ) -> None:
        """Fit the mapping from a streaming :class:`QuantileSketch`
        instead of materialized raw values — the one-pass out-of-core
        binning path (lightgbm_tpu/ingest.py) and the distributed
        sketch-allgather path (parallel/dist_data.py).

        Equivalence contract (docs/Ingest.md): while the sketch never
        compacted (``sketch.compactions == 0`` — every distinct value
        retained, the dense small-bin regime) the fitted bounds are
        BYTE-IDENTICAL to :meth:`find_bin` over the same rows; after
        compaction each greedy boundary's rank displacement is bounded
        by the sketch's rank-error bound (~2·n/capacity rows per
        compaction generation)."""
        na_cnt = int(sketch.na_cnt)
        if not use_missing:
            self.missing_type = MissingType.NONE
        elif zero_as_missing:
            self.missing_type = MissingType.ZERO
        else:
            self.missing_type = MissingType.NAN if na_cnt > 0 \
                else MissingType.NONE
        if sketch.categorical:
            uniq, counts = sketch.categorical_counts()
            self._fit_categorical_from_distinct(uniq, counts, max_bin)
            return
        self._fit_numerical_from_distinct(
            sketch.values, sketch.counts, na_cnt, max_bin,
            min_data_in_bin, min_split_data, pre_filter, forced_bounds)

    # -- transform ---------------------------------------------------------
    def value_to_bin(self, values: np.ndarray) -> np.ndarray:
        """Vectorized ValueToBin (bin.h:486-524)."""
        values = np.asarray(values, dtype=np.float64)
        if self.bin_type == BinType.CATEGORICAL:
            out = np.zeros(len(values), dtype=np.int32)
            cats = np.where(np.isnan(values), -1, values).astype(np.int64)
            if len(self.categories) > 0:
                sorter = np.argsort(self.categories)
                sorted_cats = self.categories[sorter]
                pos = np.searchsorted(sorted_cats, cats)
                pos = np.clip(pos, 0, len(sorted_cats) - 1)
                found = sorted_cats[pos] == cats
                out = np.where(found, sorter[pos], 0).astype(np.int32)
            if self.missing_type == MissingType.NAN:
                out = np.where(np.isnan(values) | (values < 0), self.num_bin - 1, out)
            return out

        nan_mask = np.isnan(values)
        vals = np.where(nan_mask, 0.0, values)
        vals = np.where(np.abs(vals) <= kZeroThreshold, 0.0, vals)
        if self.missing_type == MissingType.ZERO:
            vals = np.where(nan_mask, 0.0, vals)  # NaN -> zero bin
        bins = np.searchsorted(self.bin_upper_bound, vals, side="left").astype(np.int32)
        if self.missing_type == MissingType.NAN:
            bins = np.where(nan_mask, self.num_bin - 1, bins)
        return bins

    def bin_to_value(self, b: int) -> float:
        """Representative value of a bin (used for threshold real values)."""
        if self.bin_type == BinType.CATEGORICAL:
            if 0 <= b < len(self.categories):
                return float(self.categories[b])
            return -1.0
        if self.missing_type == MissingType.NAN and b == self.num_bin - 1:
            return float("nan")
        return float(self.bin_upper_bound[min(b, len(self.bin_upper_bound) - 1)])

    @property
    def na_bin(self) -> int:
        if self.missing_type == MissingType.NAN:
            return self.num_bin - 1
        if self.missing_type == MissingType.ZERO:
            return self.default_bin
        return -1

    # -- serialization (dataset binary cache) -------------------------------
    def to_state(self) -> dict:
        return {
            "num_bin": self.num_bin,
            "bin_type": self.bin_type.value,
            "missing_type": self.missing_type.value,
            "is_trivial": self.is_trivial,
            "bin_upper_bound": self.bin_upper_bound,
            "categories": self.categories,
            "default_bin": self.default_bin,
            "most_freq_bin": self.most_freq_bin,
            "sparse_rate": self.sparse_rate,
            "bin0_frac": self.bin0_frac,
        }

    @classmethod
    def from_state(cls, st: dict) -> "BinMapper":
        m = cls()
        m.num_bin = int(st["num_bin"])
        m.bin_type = BinType(int(st["bin_type"]))
        m.missing_type = MissingType(int(st["missing_type"]))
        m.is_trivial = bool(st["is_trivial"])
        m.bin_upper_bound = np.asarray(st["bin_upper_bound"], dtype=np.float64)
        m.categories = np.asarray(st["categories"], dtype=np.int64)
        m._cat_to_bin = {int(c): i for i, c in enumerate(m.categories)}
        m.default_bin = int(st["default_bin"])
        m.most_freq_bin = int(st["most_freq_bin"])
        m.sparse_rate = float(st["sparse_rate"])
        m.bin0_frac = float(st.get("bin0_frac", 1.0))
        return m


# ---------------------------------------------------------------------------
# Mergeable quantile sketch (streaming / distributed FindBin substrate)
# ---------------------------------------------------------------------------

class QuantileSketch:
    """Fixed-capacity mergeable summary of one feature's value
    distribution — the streaming substrate FindBin fits from when the
    raw rows never fit in host RAM (arXiv:1804.06755's per-shard
    sketches merged into global bin bounds; arXiv:1611.01276's
    ship-summaries-not-samples communication argument).

    The sketch keeps sorted distinct (value, count) pairs and is EXACT
    — a lossless ``np.unique`` of everything it has seen — until the
    distinct count exceeds ``capacity``.  Past capacity it compacts
    deterministically: representatives are picked at equal
    cumulative-count targets (first, last and the 0.0 zero-band value
    are always retained — the zero-aware FindBin carve-out needs the
    exact zero count) and each dropped value's count folds into the
    nearest retained representative on its left.  One compaction moves
    no value's rank by more than the largest folded segment, ~2·n/
    capacity rows; ``compactions`` counts the generations so callers
    can report the bound (docs/Ingest.md "Equivalence").

    ``update`` and ``merge`` are deterministic pure functions of the
    (state, input) pair — every process merging the same shard
    sketches in the same rank order derives byte-identical global
    bounds, which is what lets ``parallel/dist_data.py`` allgather
    sketches instead of raw samples.

    Categorical mode (``categorical=True``) never compacts: category
    ids are identity-significant, so the sketch is an exact value->
    count map (real categorical cardinalities are far below any sane
    capacity; a pathological one should raise, not silently merge
    categories).
    """

    __slots__ = ("capacity", "categorical", "values", "counts", "n",
                 "na_cnt", "compactions")

    STATE_VERSION = 1

    def __init__(self, capacity: int = 2048, categorical: bool = False):
        self.capacity = max(16, int(capacity))
        self.categorical = bool(categorical)
        self.values = np.empty(0, np.float64)
        self.counts = np.empty(0, np.int64)
        self.n = 0                  # total non-NaN rows seen
        self.na_cnt = 0
        self.compactions = 0

    # -- ingest -----------------------------------------------------------
    def update(self, values: np.ndarray) -> "QuantileSketch":
        """Fold a batch of raw values (NaNs counted separately)."""
        values = np.asarray(values, dtype=np.float64)
        nan_mask = np.isnan(values)
        self.na_cnt += int(nan_mask.sum())
        vals = values[~nan_mask]
        if len(vals) == 0:
            return self
        if not self.categorical:
            # the FindBin preprocessing, applied at ingest time so the
            # lossless regime reproduces find_bin() byte-for-byte
            vals = np.where(np.abs(vals) <= kZeroThreshold, 0.0, vals)
        distinct, counts = np.unique(vals, return_counts=True)
        self._fold(distinct, counts.astype(np.int64))
        self.n += int(len(vals))
        return self

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        """Deterministically absorb another sketch (same feature)."""
        if other.categorical != self.categorical:
            raise ValueError("cannot merge categorical and numerical "
                             "sketches")
        self._fold(other.values, other.counts)
        self.n += int(other.n)
        self.na_cnt += int(other.na_cnt)
        self.compactions = max(self.compactions, int(other.compactions))
        return self

    def _fold(self, distinct: np.ndarray, counts: np.ndarray) -> None:
        if len(self.values) == 0:
            merged_v, merged_c = distinct, counts
        else:
            allv = np.concatenate([self.values, distinct])
            allc = np.concatenate([self.counts, counts])
            order = np.argsort(allv, kind="stable")
            allv, allc = allv[order], allc[order]
            # sum counts of duplicate values
            uniq_mask = np.empty(len(allv), bool)
            uniq_mask[0] = True
            np.not_equal(allv[1:], allv[:-1], out=uniq_mask[1:])
            idx = np.cumsum(uniq_mask) - 1
            merged_v = allv[uniq_mask]
            merged_c = np.zeros(len(merged_v), np.int64)
            np.add.at(merged_c, idx, allc)
        if not self.categorical and len(merged_v) > self.capacity:
            merged_v, merged_c = self._compact(merged_v, merged_c)
            self.compactions += 1
        self.values, self.counts = merged_v, merged_c

    def _compact(self, v: np.ndarray, c: np.ndarray):
        """Deterministic capacity-bounded compaction (class docstring)."""
        k = self.capacity
        cum = np.cumsum(c, dtype=np.float64)
        total = cum[-1]
        # representative index per equal-weight target (one per slot)
        targets = (np.arange(1, k + 1) / k) * total
        keep = np.searchsorted(cum, targets, side="left")
        keep = np.minimum(keep, len(v) - 1)
        keep = np.union1d(keep, [0, len(v) - 1])
        zpos = np.searchsorted(v, 0.0)
        if zpos < len(v) and v[zpos] == 0.0:
            keep = np.union1d(keep, [zpos])   # exact zero count survives
        new_v = v[keep]
        # fold each dropped value's count into the retained
        # representative at or to its RIGHT (ranks never move left past
        # a representative, so bin upper bounds stay upper bounds)
        seg = np.searchsorted(keep, np.arange(len(v)), side="left")
        new_c = np.zeros(len(keep), np.int64)
        np.add.at(new_c, seg, c)
        return new_v, new_c

    # -- queries ----------------------------------------------------------
    def zero_count(self) -> int:
        z = np.searchsorted(self.values, 0.0)
        if z < len(self.values) and self.values[z] == 0.0:
            return int(self.counts[z])
        return 0

    def categorical_counts(self):
        """(uniq int64 cats >= 0, counts) for the categorical tail."""
        cats = self.values.astype(np.int64)
        ok = cats >= 0
        return cats[ok], self.counts[ok]

    # -- serialization (the distributed allgather payload) ----------------
    def to_state(self) -> dict:
        return {"version": self.STATE_VERSION,
                "capacity": int(self.capacity),
                "categorical": bool(self.categorical),
                "values": self.values, "counts": self.counts,
                "n": int(self.n), "na_cnt": int(self.na_cnt),
                "compactions": int(self.compactions)}

    @classmethod
    def from_state(cls, st: dict) -> "QuantileSketch":
        if int(st.get("version", -1)) != cls.STATE_VERSION:
            raise ValueError(
                f"unsupported sketch state version {st.get('version')!r}")
        s = cls(int(st["capacity"]), bool(st["categorical"]))
        s.values = np.asarray(st["values"], np.float64)
        s.counts = np.asarray(st["counts"], np.int64)
        s.n = int(st["n"])
        s.na_cnt = int(st["na_cnt"])
        s.compactions = int(st["compactions"])
        return s


def sketch_features(x: np.ndarray, sketches: List[QuantileSketch]) -> None:
    """Fold one raw row-chunk ``[n, F]`` into F per-feature sketches."""
    if x.shape[1] != len(sketches):
        raise ValueError(f"chunk has {x.shape[1]} features, "
                         f"{len(sketches)} sketches")
    for f, sk in enumerate(sketches):
        sk.update(x[:, f])


def fit_mappers_from_sketches(sketches: Sequence[QuantileSketch],
                              config, cat_idx: Optional[set] = None
                              ) -> List[BinMapper]:
    """One BinMapper per feature sketch under ``config``'s binning
    params — the FindBin step of the streaming ingest pass
    (lightgbm_tpu/ingest.py) and of the distributed sketch allgather
    (parallel/dist_data.py).  ``config`` is duck-typed (a
    ``config.Config``): only the binning-relevant attributes are read."""
    cat_idx = cat_idx or set()
    mbf = config.max_bin_by_feature
    mappers: List[BinMapper] = []
    for f, sk in enumerate(sketches):
        m = BinMapper()
        mb = int(mbf[f]) if mbf else config.max_bin
        m.find_bin_from_sketch(
            sk, mb, config.min_data_in_bin,
            min_split_data=config.min_data_in_leaf,
            pre_filter=config.feature_pre_filter,
            use_missing=config.use_missing,
            zero_as_missing=config.zero_as_missing)
        mappers.append(m)
    return mappers
