"""Segment routing for fleet serving (docs/Fleet.md).

A fleet deployment co-hosts one packed model per user segment / region
/ experiment arm in the serve registry (serve/registry.py pow2 SoA
engines — same-family segments share every compiled serve program).
The :class:`SegmentRouter` is the thin, thread-safe map from a
request's ``segment`` key to the registry version that should serve it:

- ``assign(segment, version)`` — per-segment promote: the continual
  pipeline advances each segment independently
  (``pipeline/continual.gated_promote`` with ``activate=False`` +
  ``router.assign``), so a bad candidate for one segment never touches
  the others.
- ``resolve(segment)`` — the version for a key, falling back to the
  DEFAULT segment's version for unknown keys, and to None (the
  registry's current model) when the default is unassigned too.

The router stores version STRINGS, not ServedModel handles: resolution
re-enters the registry under its own lock, so an evicted/unloaded
version fails lookup there (and the server falls back to current)
instead of pinning a stale model alive here.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple


class SegmentRouter:
    """Thread-safe segment -> model-version map with default fallback.

    Lock contract (tools/analyze/check_races.py):
        _lock guards: _segments, _fallbacks

    ``_lock`` is leaf-level: no callback, registry, or batcher call is
    ever made while holding it."""

    def __init__(self, default_segment: str = "default"):
        self._default = str(default_segment)
        self._segments: Dict[str, str] = {}
        self._fallbacks = 0
        self._lock = threading.Lock()

    @property
    def default_segment(self) -> str:
        return self._default

    def assign(self, segment: str, version: str) -> None:
        """Point ``segment`` at registry ``version`` (per-segment
        promote).  Existing in-flight requests keep the version they
        resolved; only new resolutions see the assignment."""
        with self._lock:
            self._segments[str(segment)] = str(version)

    def unassign(self, segment: str) -> Optional[str]:
        """Drop a segment's assignment (rollback to default routing).
        Returns the version it pointed at, or None."""
        with self._lock:
            return self._segments.pop(str(segment), None)

    def resolve(self, segment: Optional[str]) -> Tuple[Optional[str], bool]:
        """``(version, fell_back)`` for a request's segment key.

        ``segment=None`` (no key on the request) routes to the default
        segment's version with ``fell_back=False`` — an unsegmented
        request is not a routing miss.  An UNKNOWN key falls back the
        same way but counts (``fell_back=True``, the
        ``serve.segment_fallbacks`` metric).  Returns version None when
        neither the key nor the default segment is assigned — the
        caller serves the registry's current model."""
        with self._lock:
            if segment is None:
                return self._segments.get(self._default), False
            v = self._segments.get(str(segment))
            if v is not None:
                return v, False
            self._fallbacks += 1
            return self._segments.get(self._default), True

    def drop_version(self, version: str) -> List[str]:
        """Remove every assignment pointing at ``version`` (called when
        the registry unloads/evicts it).  Returns the segments
        dropped."""
        with self._lock:
            gone = [s for s, v in self._segments.items() if v == version]
            for s in gone:
                self._segments.pop(s)
            return gone

    def fallbacks(self) -> int:
        """Unknown-segment resolutions served by the default so far."""
        with self._lock:
            return self._fallbacks

    def snapshot(self) -> Dict[str, str]:
        """Copy of the segment -> version map (metrics / admin)."""
        with self._lock:
            return dict(self._segments)
