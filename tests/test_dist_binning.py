"""Distributed binning (dataset_loader.cpp:1104-1186 analog): feature-sharded
FindBin + mapper allgather, simulated in-process the way the reference's
distributed tests simulate machines (SURVEY.md §4)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.parallel.dist_data import (distributed_bin_mappers,
                                             shard_features)


def test_shard_features_balanced():
    for f, m in [(10, 4), (3, 4), (28, 3), (1, 2), (8, 8)]:
        start, length = shard_features(f, m)
        assert sum(length) == f
        # contiguous coverage
        pos = 0
        for s, l in zip(start, length):
            assert s == pos
            pos += l


def _run_world(world: int, fn):
    """Run fn(rank, allgather) on `world` threads with a real barrier-style
    allgather — multi-machine simulated in-process, the way the reference
    runs N CLI trainers in threads (_test_distributed.py:79-83)."""
    import threading
    mailbox = [None] * world
    barrier = threading.Barrier(world)
    results = [None] * world
    errors = []

    def make_ag(rank):
        def ag(payload: bytes):
            mailbox[rank] = payload
            barrier.wait(timeout=60)
            out = list(mailbox)
            barrier.wait(timeout=60)
            return out
        return ag

    def runner(rank):
        try:
            results[rank] = fn(rank, make_ag(rank))
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=runner, args=(r,))
               for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]
    return results


def test_mappers_consistent_across_ranks():
    rs = np.random.RandomState(0)
    n, f, world = 6000, 11, 4
    x = rs.randn(n, f)
    x[:, 3] = rs.randint(0, 6, n)  # categorical-ish
    cfg = Config({"max_bin": 63, "min_data_in_bin": 3})
    shards = np.array_split(x, world)

    final = _run_world(world, lambda rank, ag: distributed_bin_mappers(
        shards[rank], cfg, cat_idx={3},
        process_index=rank, process_count=world, allgather=ag))
    for rank in range(1, world):
        for m0, m1 in zip(final[0], final[rank]):
            assert m0.num_bin == m1.num_bin
            np.testing.assert_array_equal(m0.to_state()["bin_upper_bound"],
                                          m1.to_state()["bin_upper_bound"])


def test_dataset_with_preset_mappers_trains():
    rs = np.random.RandomState(1)
    n, f, world = 4000, 8, 2
    x = rs.randn(n, f)
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float32)
    cfg = Config({"max_bin": 63})
    shards = np.array_split(x, world)
    mappers = _run_world(world, lambda rank, ag: distributed_bin_mappers(
        shards[rank], cfg, process_index=rank, process_count=world,
        allgather=ag))[0]

    ds = lgb.Dataset(x, label=y, bin_mappers=mappers,
                     params={"enable_bundle": False}).construct()
    assert len(ds.bin_mappers) == f
    bst = lgb.train({"objective": "binary", "num_leaves": 15, "max_bin": 63,
                     "verbosity": -1, "enable_bundle": False},
                    lgb.Dataset(x, label=y, bin_mappers=mappers,
                                params={"enable_bundle": False}),
                    num_boost_round=10)
    from lightgbm_tpu.metrics import _auc
    assert _auc(y, bst.predict(x, raw_score=True), None) > 0.9
