"""lightgbm_tpu: a TPU-native gradient-boosting framework.

A from-scratch JAX/XLA/Pallas re-design of the LightGBM GBDT framework
(reference: /root/reference) for TPU hardware: the tree learner is a fully
device-resident jitted program (histograms on the MXU, vectorized split
scans, row->leaf partition vector), distributed training uses XLA
collectives over a `jax.sharding.Mesh`, and the Python API mirrors the
reference's (`Dataset`, `Booster`, `train`, `cv`, sklearn wrappers).
"""

__version__ = "0.1.0"

from .binning import BinMapper, BinType, MissingType
from .booster import Booster
from .callback import (EarlyStopException, early_stopping, log_evaluation,
                       record_evaluation, reset_parameter)
from .config import Config
from .dataset import Dataset, Sequence
from .engine import CVBooster, cv, train

__all__ = [
    "BinMapper", "BinType", "MissingType", "Booster", "Config", "CVBooster",
    "Dataset", "EarlyStopException", "Sequence", "cv", "early_stopping",
    "log_evaluation", "record_evaluation", "reset_parameter", "train",
]
