"""Opportunistic TPU bench capture (VERDICT r4 task 1).

The axon TPU tunnel is exclusive and can wedge for hours (a killed
mid-claim client leaves the relay grant held; BENCH_POINTS.jsonl rounds
3-4 carry the diagnosis).  Waiting until the end of the round to measure
means one wedge costs the round its only hardware numbers.

This watcher inverts that: started at round BEGIN, it parks ONE orphaned
claim probe against the tunnel and polls its output.  The probe sits in
``jax.devices()`` until the relay grants (a healthy claim takes ~0.1 s);
the moment it lands, the watcher runs bench.py's measurement children
(primary + extras) with the points file redirected to the durable
``BENCH_TPU_CAPTURE.jsonl`` — which the end-of-round ``bench.py`` run
prefers over a CPU fallback if the tunnel has wedged again by then.

The probe child is NEVER killed: SIGKILLing a client mid-claim is
exactly what creates the wedge.  If the probe never lands, the watcher
exits at its deadline leaving the orphan parked (it exits cleanly on its
own if the grant ever arrives).

Usage:  nohup python tools/tpu_watch.py [--deadline-hours H] &
Log:    tools/tpu_watch.log
"""

import argparse
import json
import os
import subprocess
import sys
import time

_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(_DIR)
sys.path.insert(0, REPO)
BENCH = os.path.join(REPO, "bench.py")
CAPTURE = os.path.join(REPO, "BENCH_TPU_CAPTURE.jsonl")
PROBE_OUT = os.path.join(_DIR, ".tpu_watch_probe.out")
LOG = os.path.join(_DIR, "tpu_watch.log")
TRACE = os.path.join(_DIR, "tpu_watch_trace.jsonl")

POLL_S = 20
PRIMARY_TIMEOUT = 900
EXTRAS_TIMEOUT = 900

# structured sibling of the text log: every probe wait / retry / bench
# child becomes a span or instant in an obs JSONL trace, so a whole
# round's tunnel behavior loads in Perfetto (obs.trace.jsonl_to_chrome).
# pid=0 is REQUIRED: the default would call jax.process_index(), whose
# backend init is itself a TPU claim — the watcher must never touch the
# tunnel its probe children exist to wait on
from lightgbm_tpu.obs.trace import Tracer  # noqa: E402

tracer = Tracer(sink_path=TRACE, pid=0)


def log(msg: str) -> None:
    line = f"[{time.strftime('%H:%M:%S')}] {msg}"
    print(line, flush=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")
    tracer.instant("watch_log", msg=msg)


def spawn_probe() -> subprocess.Popen:
    """One orphaned claim probe; never killed (see module docstring) —
    but a probe that EXITS on its own (e.g. 'TPU backend setup/compile
    error (Unavailable)' when the relay is mid-wedge or mid-handover)
    holds nothing, so the caller may safely spawn a replacement.

    The claim runs under the in-repo resilience layer
    (lightgbm_tpu/utils/resilience.py): transient backend-init failures
    back off and retry INSIDE the probe, and every attempt is printed as
    a ``PROBE_RETRY`` line that the watcher relays into the watch log —
    the round-5 wedge left no trace of what the claim was doing.  Fault
    sites stay armable: an LGBM_TPU_FAULTS env spec is inherited by the
    probe child (utils/faultinject.py reads it at import)."""
    code = (
        "import sys, time\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from lightgbm_tpu.utils.resilience import RetryPolicy, retry_call\n"
        "t0 = time.time()\n"
        "def _claim():\n"
        "    import jax\n"
        "    return jax.devices()\n"
        "def _note(attempt, delay, err):\n"
        "    print(f'PROBE_RETRY attempt={attempt} backoff={delay:.0f}s'\n"
        "          f' err={err}', flush=True)\n"
        "d = retry_call(_claim, policy=RetryPolicy(max_attempts=4,\n"
        "    base_delay_s=30, max_delay_s=600), label='tpu-claim',\n"
        "    on_retry=_note)\n"
        "print('PROBE_OK', d[0].device_kind, round(time.time()-t0,2),"
        " flush=True)\n")
    with open(PROBE_OUT, "w") as out:
        return subprocess.Popen([sys.executable, "-c", code], stdout=out,
                                stderr=subprocess.STDOUT,
                                start_new_session=True)


def run_bench_child(mode: str, timeout: int) -> bool:
    """Run one bench.py measurement child, points -> CAPTURE file.

    On overrun the child is LEFT RUNNING, never killed: it holds a
    granted tunnel claim, and SIGKILLing a claim holder is exactly what
    wedges the relay (the same discipline as the probe).  Each point the
    child lands is already persisted to the capture file, so abandoning
    it costs only the points not yet reached."""
    env = dict(os.environ, _BENCH_CHILD=mode,
               _BENCH_POINTS_FILE=CAPTURE)
    log(f"running bench child '{mode}' (budget {timeout}s, not killed "
        "on overrun)...")
    span = tracer.span(f"bench_child:{mode}", budget_s=timeout)
    err_path = os.path.join(_DIR, f".tpu_watch_{mode}.err")
    with open(err_path, "w") as err_f:
        p = subprocess.Popen([sys.executable, BENCH], env=env,
                             stdout=subprocess.DEVNULL, stderr=err_f,
                             start_new_session=True)
    t0 = time.time()
    while time.time() - t0 < timeout:
        if p.poll() is not None:
            break
        time.sleep(5)
    if p.poll() is None:
        span.args["outcome"] = "parked"
        span.end()
        log(f"child '{mode}' still running after {timeout}s — left "
            "parked (claim holder; killing it would wedge the relay)")
        return False
    try:
        with open(err_path) as f:
            tail = f.read()[-1500:]
    except OSError:
        tail = ""
    span.args["outcome"] = f"rc={p.returncode}"
    span.end()
    log(f"child '{mode}' rc={p.returncode}; stderr tail:\n{tail}")
    return p.returncode == 0


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--deadline-hours", type=float, default=11.0)
    args = ap.parse_args()
    deadline = time.time() + args.deadline_hours * 3600

    # truncate the capture file at round start: bench.py prefers the
    # newest capture, and a point measured against a PREVIOUS round's
    # code must never be attributed to this round's
    try:
        os.replace(CAPTURE, CAPTURE + ".prev")
    except OSError:
        pass
    log(f"watch start; capture -> {CAPTURE}")
    probe = spawn_probe()
    t_probe = time.time()
    probe_span = tracer.span("probe_wait")
    retry_backoff = 60
    relayed_retries = set()
    while time.time() < deadline:
        time.sleep(POLL_S)
        try:
            with open(PROBE_OUT) as f:
                out = f.read()
        except OSError:
            out = ""
        # relay the probe's resilience-layer retry/backoff attempts into
        # the durable watch log (each attempt once)
        for ln in out.splitlines():
            if ln.startswith("PROBE_RETRY") and ln not in relayed_retries:
                relayed_retries.add(ln)
                log(f"probe backoff: {ln}")
        if "PROBE_OK" in out:
            probe_span.args["outcome"] = "granted"
            probe_span.end()
            log(f"claim landed after {time.time() - t_probe:.0f}s: "
                f"{out.strip().splitlines()[-1]}")
            ok = run_bench_child("primary", PRIMARY_TIMEOUT)
            if ok:
                run_bench_child("extras", EXTRAS_TIMEOUT)
            n = sum(1 for ln in open(CAPTURE)) if os.path.exists(CAPTURE) \
                else 0
            log(f"capture finished; {n} points in {CAPTURE}; exiting")
            return
        if probe.poll() is not None:
            # the probe FAILED (exited without a grant) — it holds no
            # claim, so replacing it is safe; back off so a hard-down
            # relay isn't hammered
            tail = out.strip().splitlines()[-1] if out.strip() else "(empty)"
            probe_span.args["outcome"] = f"exited rc={probe.returncode}"
            # machine-readable claim-loss reason (ISSUE 14 satellite,
            # mirrored by bench.py's claim classification): a probe that
            # EXITS failed to claim; one that never returns is a wedge
            probe_span.args["reason"] = "no_claim"
            probe_span.end()
            log(f"probe exited rc={probe.returncode} without a grant "
                f"({tail!r}); respawning in {retry_backoff}s")
            time.sleep(retry_backoff)
            retry_backoff = min(retry_backoff * 2, 1800)
            probe = spawn_probe()
            t_probe = time.time()
            probe_span = tracer.span("probe_wait")
        elif int(time.time() - t_probe) % 600 < POLL_S:
            log(f"still waiting on claim ({time.time() - t_probe:.0f}s; "
                "orphan parked, tunnel presumed wedged)")
    probe_span.args["outcome"] = "deadline"
    probe_span.args["reason"] = "wedge"
    probe_span.end()
    log("deadline reached; probe orphan left parked; exiting "
        "(claim-loss reason: wedge)")


if __name__ == "__main__":
    main()
