"""Host-side tree model: serialization + raw-feature prediction.

Analog of the reference ``Tree`` (/root/reference/include/LightGBM/tree.h:25-729,
src/io/tree.cpp): array-encoded binary tree with leaves addressed as
``~leaf_index`` in child pointers.  Text serialization follows the reference
model format (``Tree::ToString`` tree.cpp / gbdt_model_text.cpp:311) so
models round-trip and stay ecosystem-compatible: per-node
``decision_type`` bit-field (bit0 categorical, bit1 default-left,
bits2-3 missing type), real-valued thresholds (bin upper bounds), and
categorical splits stored as bitsets over raw category values.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import numpy as np

from .binning import BinMapper, BinType, MissingType

_CAT_BIT = 1          # decision_type bit 0: categorical split
_DEFAULT_LEFT_BIT = 2  # bit 1
_MISSING_SHIFT = 2     # bits 2-3: 0 none / 1 zero / 2 nan


class Tree:
    """A single decision tree in host (NumPy) form."""

    def __init__(self, num_leaves: int):
        self.num_leaves = num_leaves
        n = max(num_leaves - 1, 1)
        self.split_feature = np.zeros(n, np.int32)     # original feature idx
        self.threshold = np.zeros(n, np.float64)       # real-valued threshold
        self.threshold_bin = np.zeros(n, np.int32)
        self.decision_type = np.zeros(n, np.int32)
        self.left_child = np.full(n, -1, np.int32)
        self.right_child = np.full(n, -2, np.int32)
        self.split_gain = np.zeros(n, np.float64)
        self.leaf_value = np.zeros(num_leaves, np.float64)
        self.leaf_weight = np.zeros(num_leaves, np.float64)
        self.leaf_count = np.zeros(num_leaves, np.int64)
        self.internal_value = np.zeros(n, np.float64)
        self.internal_weight = np.zeros(n, np.float64)
        self.internal_count = np.zeros(n, np.int64)
        # categorical storage (tree.h cat_boundaries_/cat_threshold_)
        self.num_cat = 0
        self.cat_boundaries = [0]
        self.cat_threshold: List[int] = []             # packed uint32 bitset words
        self.shrinkage = 1.0
        # linear trees (LinearTreeLearner, linear_tree_learner.cpp): per-leaf
        # linear model out = leaf_const + sum(leaf_coeff * x[leaf_features])
        self.is_linear = False
        self.leaf_const = np.zeros(num_leaves, np.float64)
        self.leaf_features: List[List[int]] = [[] for _ in range(num_leaves)]
        self.leaf_coeff: List[List[float]] = [[] for _ in range(num_leaves)]

    # ------------------------------------------------------------------
    @classmethod
    def from_arrays(cls, arrays, feature_map: Sequence[int],
                    mappers: Sequence[BinMapper]) -> "Tree":
        """Build from the grower's device ``TreeArrays``.

        feature_map: used-feature slot -> original feature index.
        mappers: per original feature (for bin -> real threshold).
        """
        nl = int(arrays.num_leaves)
        t = cls(nl)
        n = max(nl - 1, 0)
        slot_feat = np.asarray(arrays.split_feature)[:n]
        t.split_feature = np.asarray([feature_map[s] for s in slot_feat], np.int32)
        t.threshold_bin = np.asarray(arrays.threshold_bin)[:n].astype(np.int32)
        dl = np.asarray(arrays.default_left)[:n]
        t.left_child = np.asarray(arrays.left_child)[:n].astype(np.int32)
        t.right_child = np.asarray(arrays.right_child)[:n].astype(np.int32)
        t.split_gain = np.asarray(arrays.split_gain)[:n].astype(np.float64)
        t.leaf_value = np.asarray(arrays.leaf_value)[:nl].astype(np.float64)
        t.leaf_weight = np.asarray(arrays.leaf_weight)[:nl].astype(np.float64)
        t.leaf_count = np.rint(np.asarray(arrays.leaf_count)[:nl]).astype(np.int64)
        t.internal_value = np.asarray(arrays.internal_value)[:n].astype(np.float64)
        t.internal_weight = np.asarray(arrays.internal_weight)[:n].astype(np.float64)
        t.internal_count = np.rint(np.asarray(arrays.internal_count)[:n]).astype(np.int64)

        t.threshold = np.zeros(n, np.float64)
        t.decision_type = np.zeros(n, np.int32)
        is_cat_node = np.asarray(arrays.is_cat_node)[:n]
        cat_rank = np.asarray(arrays.cat_rank)[:n]
        for i in range(n):
            f = t.split_feature[i]
            m = mappers[f]
            dt = 0
            if m.missing_type == MissingType.ZERO:
                dt |= 1 << _MISSING_SHIFT
            elif m.missing_type == MissingType.NAN:
                dt |= 2 << _MISSING_SHIFT
            if is_cat_node[i]:
                # left set = bins whose decision rank <= threshold
                # (gradient-ratio subset, ops/split.py categorical scan)
                dt |= _CAT_BIT
                rank = cat_rank[i]
                ncat = len(m.categories)
                sel = [b for b in range(min(ncat, len(rank)))
                       if rank[b] <= t.threshold_bin[i]]
                cats = m.categories[sel]
                t.threshold[i] = t._add_cat_bitset(cats)
            else:
                if dl[i]:
                    dt |= _DEFAULT_LEFT_BIT
                t.threshold[i] = m.bin_to_value(int(t.threshold_bin[i]))
            t.decision_type[i] = dt
        return t

    def _add_cat_bitset(self, cats: np.ndarray) -> int:
        """Append a category bitset; returns the cat-split index stored in
        ``threshold`` (tree.h cat_threshold_ layout)."""
        if len(cats) == 0:
            words = [0]
        else:
            nwords = int(np.max(cats)) // 32 + 1
            arr = np.zeros(nwords, np.uint32)
            for c in cats:
                arr[int(c) // 32] |= np.uint32(1 << (int(c) % 32))
            words = arr.tolist()
        idx = self.num_cat
        self.cat_threshold.extend(int(w) for w in words)
        self.cat_boundaries.append(len(self.cat_threshold))
        self.num_cat += 1
        return float(idx)

    def _cat_contains(self, cat_idx: int, value: float) -> np.ndarray:
        lo, hi = self.cat_boundaries[cat_idx], self.cat_boundaries[cat_idx + 1]
        words = self.cat_threshold[lo:hi]
        v = np.asarray(value)
        iv = np.where(np.isfinite(v), v, -1).astype(np.int64)
        ok = (iv >= 0) & (iv < 32 * len(words))
        word_idx = np.clip(iv // 32, 0, len(words) - 1)
        bits = np.asarray(words, np.uint64)[word_idx]
        return ok & ((bits >> (iv % 32).astype(np.uint64)) & 1).astype(bool)

    # ------------------------------------------------------------------
    def shrink(self, rate: float) -> None:
        """Tree::Shrinkage (tree.h:187)."""
        self.leaf_value *= rate
        self.internal_value *= rate
        self.shrinkage *= rate

    def add_bias(self, val: float) -> None:
        """Tree::AddBias (tree.h:212)."""
        self.leaf_value += val
        self.internal_value += val

    def num_nodes(self) -> int:
        return max(self.num_leaves - 1, 0)

    def max_depth(self) -> int:
        if self.num_leaves <= 1:
            return 0
        depth = {0: 1}
        best = 1
        for i in range(self.num_nodes()):
            d = depth.get(i, 1)
            for c in (self.left_child[i], self.right_child[i]):
                if c >= 0:
                    depth[c] = d + 1
                    best = max(best, d + 1)
                else:
                    best = max(best, d)
        return best

    # ------------------------------------------------------------------
    def _decide(self, node: int, x_col: np.ndarray) -> np.ndarray:
        """Vectorized per-node decision: True -> left.
        NumericalDecision / CategoricalDecision (tree.h:335-412)."""
        dt = self.decision_type[node]
        if dt & _CAT_BIT:
            return self._cat_contains(int(self.threshold[node]), x_col)
        miss = (dt >> _MISSING_SHIFT) & 3
        default_left = bool(dt & _DEFAULT_LEFT_BIT)
        thr = self.threshold[node]
        v = x_col.astype(np.float64, copy=True)
        isnan = np.isnan(v)
        if miss == 1:   # zero-as-missing: NaN -> 0
            v = np.where(isnan, 0.0, v)
            isnan = np.zeros_like(isnan)
        elif miss == 0:  # no missing handling: NaN -> 0 (tree.h converts)
            v = np.where(isnan, 0.0, v)
            isnan = np.zeros_like(isnan)
        go_left = v <= thr
        if miss == 2:
            go_left = np.where(isnan, default_left, go_left)
        return go_left

    def predict(self, X: np.ndarray) -> np.ndarray:
        leaves = self.predict_leaf(X)
        if not self.is_linear:
            return self.leaf_value[leaves]
        return self.linear_leaf_outputs(leaves, X)

    def linear_leaf_outputs(self, leaves: np.ndarray,
                            X: np.ndarray) -> np.ndarray:
        """Linear-leaf outputs given row->leaf: const + coeffs on raw
        feature values; rows with NaN in used features fall back to the
        constant leaf_value (linear_tree_learner.cpp nan path).  Single
        implementation shared by model prediction and train/valid score
        replay."""
        out = self.leaf_value[leaves].astype(np.float64)
        for leaf in range(self.num_leaves):
            feats = self.leaf_features[leaf]
            if not feats:
                continue
            m = leaves == leaf
            if not m.any():
                continue
            sub = X[np.ix_(m, feats)].astype(np.float64)
            val = self.leaf_const[leaf] + sub @ np.asarray(self.leaf_coeff[leaf])
            out[m] = np.where(np.isnan(sub).any(axis=1),
                              self.leaf_value[leaf], val)
        return out

    def predict_leaf(self, X: np.ndarray) -> np.ndarray:
        """Vectorized level-by-level traversal over raw features."""
        n = len(X)
        if self.num_leaves <= 1:
            return np.zeros(n, np.int32)
        node = np.zeros(n, np.int32)   # >=0 internal, <0 -> leaf ~node
        active = node >= 0
        for _ in range(self.num_leaves):  # depth bound
            if not active.any():
                break
            nid = np.clip(node, 0, None)
            # group rows by node for vectorized decisions
            for u in np.unique(nid[active]):
                rows = active & (nid == u)
                go_left = self._decide(int(u), X[rows, self.split_feature[u]])
                nxt = np.where(go_left, self.left_child[u], self.right_child[u])
                node[rows] = nxt
            active = node >= 0
        return (~node).astype(np.int32)

    # ------------------------------------------------------------------
    def to_string(self, index: int) -> str:
        """Tree::ToString (tree.cpp) — reference text block format."""
        def fmt(arr, f="%g"):
            return " ".join(f % v for v in arr)
        n = self.num_nodes()
        lines = [
            f"Tree={index}",
            f"num_leaves={self.num_leaves}",
            f"num_cat={self.num_cat}",
            f"split_feature={fmt(self.split_feature[:n], '%d')}",
            f"split_gain={fmt(self.split_gain[:n])}",
            f"threshold={fmt(self.threshold[:n], '%.17g')}",
            f"decision_type={fmt(self.decision_type[:n], '%d')}",
            f"left_child={fmt(self.left_child[:n], '%d')}",
            f"right_child={fmt(self.right_child[:n], '%d')}",
            f"leaf_value={fmt(self.leaf_value, '%.17g')}",
            f"leaf_weight={fmt(self.leaf_weight, '%g')}",
            f"leaf_count={fmt(self.leaf_count, '%d')}",
            f"internal_value={fmt(self.internal_value[:n])}",
            f"internal_weight={fmt(self.internal_weight[:n])}",
            f"internal_count={fmt(self.internal_count[:n], '%d')}",
        ]
        if self.num_cat > 0:
            lines.append(f"cat_boundaries={fmt(self.cat_boundaries, '%d')}")
            lines.append(f"cat_threshold={fmt(self.cat_threshold, '%d')}")
        lines.append(f"is_linear={int(self.is_linear)}")
        if self.is_linear:
            # linear-tree block (gbdt_model_text per-leaf linear model lines)
            lines.append(f"leaf_const={fmt(self.leaf_const, '%.17g')}")
            lines.append("num_features=" + " ".join(
                str(len(f_)) for f_ in self.leaf_features))
            lines.append("leaf_features=" + " ".join(
                " ".join(str(int(v)) for v in f_) for f_ in self.leaf_features
                if len(f_)))
            lines.append("leaf_coeff=" + " ".join(
                " ".join(f"{v:.17g}" for v in c_) for c_ in self.leaf_coeff
                if len(c_)))
        lines.append(f"shrinkage={self.shrinkage:g}")
        lines.append("")
        return "\n".join(lines)

    @classmethod
    def from_string(cls, block: str) -> "Tree":
        kv: Dict[str, str] = {}
        for line in block.strip().splitlines():
            if "=" in line:
                k, v = line.split("=", 1)
                kv[k.strip()] = v.strip()
        nl = int(kv["num_leaves"])
        t = cls(nl)

        def arr(key, dtype, size):
            if key not in kv or kv[key] == "":
                return np.zeros(size, dtype)
            return np.asarray(kv[key].split(" "), dtype=dtype)

        n = max(nl - 1, 0)
        t.split_feature = arr("split_feature", np.int32, n)
        t.split_gain = arr("split_gain", np.float64, n)
        t.threshold = arr("threshold", np.float64, n)
        t.decision_type = arr("decision_type", np.int32, n)
        t.left_child = arr("left_child", np.int32, n)
        t.right_child = arr("right_child", np.int32, n)
        t.leaf_value = arr("leaf_value", np.float64, nl)
        t.leaf_weight = arr("leaf_weight", np.float64, nl)
        t.leaf_count = arr("leaf_count", np.int64, nl)
        t.internal_value = arr("internal_value", np.float64, n)
        t.internal_weight = arr("internal_weight", np.float64, n)
        t.internal_count = arr("internal_count", np.int64, n)
        t.num_cat = int(kv.get("num_cat", "0"))
        if t.num_cat > 0:
            t.cat_boundaries = [int(x) for x in kv["cat_boundaries"].split(" ")]
            t.cat_threshold = [int(x) for x in kv["cat_threshold"].split(" ")]
        t.shrinkage = float(kv.get("shrinkage", "1"))
        t.is_linear = bool(int(kv.get("is_linear", "0")))
        if t.is_linear and "leaf_const" in kv:
            t.leaf_const = arr("leaf_const", np.float64, nl)
            nfeat = [int(v) for v in kv.get("num_features", "").split(" ")
                     if v != ""]
            flat_f = [int(v) for v in kv.get("leaf_features", "").split(" ")
                      if v != ""]
            flat_c = [float(v) for v in kv.get("leaf_coeff", "").split(" ")
                      if v != ""]
            t.leaf_features, t.leaf_coeff = [], []
            pos = 0
            for cnt in nfeat:
                t.leaf_features.append(flat_f[pos:pos + cnt])
                t.leaf_coeff.append(flat_c[pos:pos + cnt])
                pos += cnt
            while len(t.leaf_features) < nl:
                t.leaf_features.append([])
                t.leaf_coeff.append([])
        return t
