"""Histogram construction: the hot kernel of GBDT training.

Replaces the reference's histogram kernels — CPU
``DenseBin::ConstructHistogram`` (/root/reference/src/io/dense_bin.hpp),
CUDA ``CUDAConstructHistogramDenseKernel``
(/root/reference/src/treelearner/cuda/cuda_histogram_constructor.cu:18-70,
shared-memory atomicAdd per (bin, grad/hess)) — with a TPU-native
formulation: scatter-add has no fast TPU lowering, so the histogram is
computed as a **one-hot contraction on the MXU**:

    hist[f*B + b, c] = sum_n (binned[n, f] == b) * vals[n, c]

i.e. a single ``[F*B, n] @ [n, C]`` matmul per row-block, accumulated over
blocks with ``lax.scan``.  The one-hot operand is generated on the fly
(iota-compare) and fused by XLA into the matmul operand load, so HBM traffic
stays at the binned-matrix + vals bytes.  Channels C = (grad, hess, count).

All features share a uniform padded bin axis ``B`` (= dataset max_bin) so
shapes are static; per-feature valid-bin masking happens in the split scan.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def hist_block_rows(num_features: int, num_bins: int,
                    vmem_budget_bytes: int = 6 * 1024 * 1024) -> int:
    """Pick a row-block size so a block's one-hot tile stays VMEM-friendly."""
    per_row = num_features * num_bins * 4
    blk = max(8, vmem_budget_bytes // max(per_row, 1))
    # round down to a multiple of 8 (f32 sublane), cap for scan efficiency
    blk = min(int(blk) // 8 * 8, 16384)
    return max(blk, 8)


def compute_histogram(binned: jax.Array, vals: jax.Array, *, num_bins: int,
                      block_rows: int = 0) -> jax.Array:
    """hist[f, b, c] = sum over rows n of (binned[n,f]==b) * vals[n,c].

    binned: [N, F] integer bins (uint8/uint16/int32)
    vals:   [N, C] float32 per-row accumulands (grad, hess, count-weight);
            rows outside the target leaf / bag must already be zeroed.
    returns [F, num_bins, C] float32.

    Backend: the XLA one-hot-matmul scan below on every platform (fastest
    measured on TPU v5e as well); LGBM_TPU_HIST=pallas selects the
    experimental Pallas kernel (hist_pallas.py) instead.
    """
    import os
    mode = os.environ.get("LGBM_TPU_HIST", "auto")
    # Default is the XLA one-hot matmul everywhere: measured on TPU v5e
    # (1M x 28 x 64 bins, amortized in-graph) it runs 4.7 ms vs 8.2 ms for
    # the best hand-written Pallas variant — XLA fuses the one-hot
    # generation into the dot better than the explicit kernel.  The Pallas
    # path is kept for experimentation via LGBM_TPU_HIST=pallas.
    if mode == "pallas" and num_bins <= 4096:
        from .hist_pallas import compute_histogram_pallas
        return compute_histogram_pallas(binned, vals, num_bins=num_bins,
                                        block_rows=block_rows)
    return _compute_histogram_matmul(binned, vals, num_bins=num_bins,
                                     block_rows=block_rows)


@functools.partial(jax.jit, static_argnames=("num_bins", "block_rows"))
def _compute_histogram_matmul(binned: jax.Array, vals: jax.Array, *,
                              num_bins: int, block_rows: int = 0) -> jax.Array:
    n, f = binned.shape
    c = vals.shape[1]
    if block_rows <= 0:
        block_rows = hist_block_rows(f, num_bins)
    block_rows = min(block_rows, max(8, n))

    pad = (-n) % block_rows
    if pad:
        binned = jnp.pad(binned, ((0, pad), (0, 0)))
        vals = jnp.pad(vals, ((0, pad), (0, 0)))
    nblocks = (n + pad) // block_rows

    binned_b = binned.reshape(nblocks, block_rows, f)
    vals_b = vals.reshape(nblocks, block_rows, c)
    iota = jnp.arange(num_bins, dtype=jnp.int32)

    def body(acc, chunk):
        bins_blk, vals_blk = chunk
        onehot = (bins_blk.astype(jnp.int32)[:, :, None] == iota).astype(jnp.float32)
        # [block, F*B]^T contracted with [block, C] -> [F*B, C]
        h = lax.dot_general(
            onehot.reshape(block_rows, f * num_bins), vals_blk,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc + h, None

    acc0 = jnp.zeros((f * num_bins, c), dtype=jnp.float32)
    acc, _ = lax.scan(body, acc0, (binned_b, vals_b))
    return acc.reshape(f, num_bins, c)


def masked_histogram(binned: jax.Array, vals: jax.Array, leaf_of_row: jax.Array,
                     leaf: jax.Array, *, num_bins: int, block_rows: int = 0) -> jax.Array:
    """Histogram over only the rows whose current leaf == ``leaf``.

    The masked-full-pass equivalent of the reference's gathered smaller-leaf
    construction (cuda_histogram_constructor.cu) — static shapes, mask folded
    into the accumulands.
    """
    mask = (leaf_of_row == leaf).astype(vals.dtype)[:, None]
    return compute_histogram(binned, vals * mask, num_bins=num_bins,
                             block_rows=block_rows)
