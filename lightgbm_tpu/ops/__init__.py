from .histogram import compute_histogram, hist_block_rows, HIST_BLOCK_ROWS
from .quantize import QuantSpec
from .split import dequantize_hist, find_best_split, SplitParams
