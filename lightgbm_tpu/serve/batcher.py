"""Micro-batching request queue with bounded backpressure.

One worker thread coalesces concurrent prediction requests into device
batches: the first queued request opens a window of ``max_wait_ms``;
everything that arrives before the window closes (or before the batch
reaches ``max_batch`` rows) rides the same traversal.  The queue is
BOUNDED in rows — when ``queue_rows`` of work is already pending,
``submit`` rejects immediately with :class:`BacklogFull` carrying a
``retry_after_ms`` estimate instead of growing without bound (the
explicit reject-with-retry-after discipline; HTTP maps it to 429 +
``Retry-After``).  Transient device errors retry through
``utils/resilience.RetryPolicy``; non-transient errors fail only the
requests of the batch that hit them.

Metrics (when a registry is attached): ``serve.queue_depth`` gauge
(rows), ``serve.batch_rows`` / ``serve.batch_occupancy`` /
``serve.latency`` histograms, ``serve.requests`` / ``serve.rows`` /
``serve.rejected`` / ``serve.errors`` counters, plus a ``serve.batch``
span per dispatched batch on the tracer.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from ..utils.resilience import (RetryPolicy, is_retryable_device_error,
                                retry_call)


class BacklogFull(RuntimeError):
    """Queue is at capacity; retry after ``retry_after_ms``."""

    def __init__(self, retry_after_ms: float, depth_rows: int):
        super().__init__(
            f"serve queue full ({depth_rows} rows pending); "
            f"retry in ~{retry_after_ms:.0f} ms")
        self.retry_after_ms = float(retry_after_ms)
        self.depth_rows = int(depth_rows)


class BatcherClosed(RuntimeError):
    """The batcher was shut down before this request completed."""


class PredictionFuture:
    """Handle for one submitted request; ``result()`` blocks."""

    __slots__ = ("_event", "_value", "_exc", "info", "t_submit")

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._exc: Optional[BaseException] = None
        self.info: dict = {}
        self.t_submit = time.perf_counter()

    def _set(self, value, info: Optional[dict] = None) -> None:
        self._value = value
        if info:
            self.info = info
        self._event.set()

    def _set_exception(self, exc: BaseException) -> None:
        self._exc = exc
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("prediction did not complete in time")
        if self._exc is not None:
            raise self._exc
        return self._value


class _Item:
    __slots__ = ("rows", "future")

    def __init__(self, rows: np.ndarray, future: PredictionFuture):
        self.rows = rows
        self.future = future


class MicroBatcher:
    """Coalesce concurrent requests into bounded device batches.

    ``predict_fn(rows) -> (outputs, info)``: outputs is an array whose
    leading axis matches ``rows`` (sliced back per request), ``info`` a
    small dict attached to every future of the batch (model version
    etc.); a plain-array return is also accepted.
    """

    def __init__(self, predict_fn: Callable, *, max_batch: int = 1024,
                 max_wait_ms: float = 2.0, queue_rows: int = 8192,
                 retry_policy: Optional[RetryPolicy] = None,
                 metrics=None, tracer=None):
        self.predict_fn = predict_fn
        self.max_batch = max(1, int(max_batch))
        self.max_wait_s = max(0.0, float(max_wait_ms)) / 1e3
        self.queue_rows = max(self.max_batch, int(queue_rows))
        self.retry_policy = retry_policy
        self.metrics = metrics
        self.tracer = tracer
        self._queue: List[_Item] = []
        self._depth_rows = 0
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._closed = False
        self.batches_dispatched = 0
        self._worker = threading.Thread(target=self._run,
                                        name="lgbtpu-serve-batcher",
                                        daemon=True)
        self._worker.start()

    # -- client side -------------------------------------------------------
    def submit(self, rows: np.ndarray) -> PredictionFuture:
        """Enqueue one request; raises :class:`BacklogFull` when the
        bounded queue cannot take it.  A 1-D vector is one row; anything
        not coercible to a 2-D array is rejected HERE, where the error
        reaches only the offending caller — malformed rows must never
        travel into a shared batch where they would poison the other
        requests riding it."""
        rows = np.asarray(rows)
        if rows.ndim == 1:
            rows = rows.reshape(1, -1)
        if rows.ndim != 2:
            raise ValueError(f"rows must be 2-D, got {rows.ndim}-D")
        n = len(rows)
        fut = PredictionFuture()
        with self._lock:
            if self._closed:
                raise BatcherClosed("batcher is closed")
            if self._depth_rows + n > self.queue_rows and self._queue:
                pending_batches = -(-self._depth_rows // self.max_batch)
                retry_ms = pending_batches * max(
                    self.max_wait_ms_effective(), 1.0)
                if self.metrics is not None:
                    self.metrics.counter("serve.rejected").inc()
                raise BacklogFull(retry_ms, self._depth_rows)
            self._queue.append(_Item(rows, fut))
            self._depth_rows += n
            if self.metrics is not None:
                self.metrics.gauge("serve.queue_depth").set(
                    self._depth_rows)
            self._wake.notify()
        return fut

    def max_wait_ms_effective(self) -> float:
        return self.max_wait_s * 1e3

    @property
    def depth_rows(self) -> int:
        with self._lock:
            return self._depth_rows

    def close(self, timeout: float = 5.0) -> None:
        """Graceful shutdown: new submissions are rejected immediately,
        already-queued work drains, and only requests the worker could
        not drain within ``timeout`` fail with :class:`BatcherClosed`."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._wake.notify_all()
        self._worker.join(timeout)
        with self._lock:
            leftovers, self._queue = self._queue, []
            self._depth_rows = 0
        for item in leftovers:
            item.future._set_exception(BatcherClosed("batcher closed"))

    # -- worker side -------------------------------------------------------
    def _collect(self) -> List[_Item]:
        """Block for the next batch: wait for a first request, then hold
        the window open until ``max_wait_s`` passes or ``max_batch``
        rows are in hand.  An oversized single request becomes its own
        batch (the engine chunks internally)."""
        with self._lock:
            while not self._queue and not self._closed:
                self._wake.wait()
            if not self._queue:
                return []
            deadline = self._queue[0].future.t_submit + self.max_wait_s
            while not self._closed:
                have = sum(len(i.rows) for i in self._queue)
                if have >= self.max_batch:
                    break
                left = deadline - time.perf_counter()
                if left <= 0:
                    break
                self._wake.wait(left)
            batch: List[_Item] = []
            rows = 0
            while self._queue:
                nxt = len(self._queue[0].rows)
                if batch and (rows + nxt > self.max_batch
                              or self._queue[0].rows.shape[1]
                              != batch[0].rows.shape[1]):
                    # width mismatch (a request sized for a different
                    # model width): never concatenated into this batch —
                    # it opens the NEXT batch and fails alone if invalid
                    break
                item = self._queue.pop(0)
                batch.append(item)
                rows += nxt
            self._depth_rows -= rows
            if self.metrics is not None:
                self.metrics.gauge("serve.queue_depth").set(
                    self._depth_rows)
            return batch

    def _dispatch(self, batch: List[_Item]) -> None:
        n = sum(len(i.rows) for i in batch)
        span = (self.tracer.span("serve.batch", rows=n,
                                 requests=len(batch))
                if self.tracer is not None else None)
        try:
            # concatenation INSIDE the guarded region: any surviving
            # shape surprise fails this batch's futures, never the
            # worker thread
            rows = (batch[0].rows if len(batch) == 1
                    else np.concatenate([i.rows for i in batch], axis=0))
            out = retry_call(self.predict_fn, rows,
                             policy=self.retry_policy,
                             classify=is_retryable_device_error,
                             label="serve.predict")
            outputs, info = out if isinstance(out, tuple) else (out, {})
            outputs = np.asarray(outputs)
        except BaseException as e:
            if span is not None:
                span.end()
            if self.metrics is not None:
                self.metrics.counter("serve.errors").inc(len(batch))
            for item in batch:
                item.future._set_exception(e)
            return
        if span is not None:
            span.end()
        self.batches_dispatched += 1
        now = time.perf_counter()
        if self.metrics is not None:
            self.metrics.counter("serve.requests").inc(len(batch))
            self.metrics.counter("serve.rows").inc(n)
            self.metrics.histogram("serve.batch_rows").observe(n)
            self.metrics.histogram("serve.batch_occupancy").observe(
                min(1.0, n / self.max_batch))
            for item in batch:
                self.metrics.histogram("serve.latency").observe(
                    now - item.future.t_submit)
        lo = 0
        for item in batch:
            hi = lo + len(item.rows)
            item.future._set(outputs[lo:hi], dict(info))
            lo = hi

    def _run(self) -> None:
        while True:
            batch = self._collect()
            if not batch:
                with self._lock:
                    if self._closed:
                        return
                continue
            try:
                self._dispatch(batch)
            except BaseException as e:       # noqa: BLE001 — the worker
                # must outlive ANY single batch; _dispatch already fails
                # the batch's own futures, this is the last-ditch belt
                for item in batch:
                    if not item.future.done():
                        item.future._set_exception(e)
