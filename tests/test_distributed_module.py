"""User-facing cluster orchestration e2e (VERDICT r3 task 9): >= 2 REAL
coordinated processes spawned THROUGH ``lightgbm_tpu.distributed.run``
(the dask.py:393-810 _train analog: port allocation, machines parameter,
one trainer per worker), each training via ``distributed.train`` with
row sharding + distributed binning + data-parallel growth, then the
replicated model must agree across ranks and match single-process
training quality."""

import os

import numpy as np
import pytest

from lightgbm_tpu import distributed

HERE = os.path.dirname(os.path.abspath(__file__))
PARAMS = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
          "min_data_in_leaf": 5, "verbosity": -1}
ROUNDS = 8


def test_run_spawns_coordinated_workers():
    results = distributed.run(
        "dist_worker:worker", num_workers=2,
        args={"params": PARAMS, "rounds": ROUNDS, "weighted": True},
        extra_pythonpath=[HERE], timeout=420)
    assert [r["rank"] for r in results] == [0, 1]
    # the machines parameter followed the reference conventions
    assert results[0]["machines"].count(",") == 1
    assert all(m.startswith("127.0.0.1:")
               for m in results[0]["machines"].split(","))
    # replicated model: byte-identical across ranks
    assert results[0]["model"] == results[1]["model"]
    np.testing.assert_allclose(results[0]["pred_head"],
                               results[1]["pred_head"], rtol=1e-6)

    # quality sanity vs a single-process run on the same global data
    from dist_worker import _global_data
    import sys
    sys.path.insert(0, HERE)
    import lightgbm_tpu as lgb
    from lightgbm_tpu.metrics import _auc
    x, y = _global_data()
    bst = lgb.train(dict(PARAMS), lgb.Dataset(x, label=y),
                    num_boost_round=ROUNDS)
    auc_single = _auc(y, bst.predict(x, raw_score=True), None)

    from lightgbm_tpu.booster import Booster
    dist_bst = Booster(model_str=results[0]["model"])
    auc_dist = _auc(y, dist_bst.predict(x, raw_score=True), None)
    assert auc_dist > 0.9
    assert abs(auc_single - auc_dist) < 0.05


def test_multi_host_emits_commands():
    with pytest.raises(SystemExit) as ei:
        distributed.run("dist_worker:worker", hosts=["10.0.0.1", "10.0.0.2"])
    msg = str(ei.value)
    assert "-m lightgbm_tpu.distributed" in msg
    assert "--machines 10.0.0.1:12400,10.0.0.2:12400" in msg
