from .histogram import compute_histogram, hist_block_rows
from .split import find_best_split, SplitParams
