"""Perf regression gate: compare two BENCH json records on pinned
metrics with noise tolerances.

The third lint of the family (tools/check_syncs.py pins host syncs,
tools/check_retraces.py pins jit traces): nothing used to stop a PR
from silently regressing ``iters_per_s`` or ``serve_p99_ms`` — the
bench numbers were recorded, never compared.  This tool compares a NEW
bench record against an OLD one on exactly the metrics pinned in
``tools/perf_budget.txt``:

- each pin is ``<key> = <direction> <tolerance>`` — ``direction`` is
  ``higher`` (throughput-like: new must not fall more than
  ``tolerance`` fraction below old) or ``lower`` (latency-like: new
  must not rise more than ``tolerance`` above old).  The tolerance IS
  the noise allowance — pin it at the metric's observed run-to-run
  spread, not at zero;
- ``value`` resolves at the record's top level, every other key in
  its ``extra`` dict (the bench.py merge layout);
- a pinned key found in NEITHER record is reported STALE (the budget
  file cannot rot), and a key the old record had but the new one lost
  is a violation (a disappearing metric is a regression in coverage);
  a key only the new record has passes (new coverage needs a round of
  history before it can be pinned meaningfully);
- ``--update NEW`` re-pins the budget from a record: existing pins
  keep their direction/tolerance, newly appearing gateable metrics
  get direction-by-name defaults, pins the record no longer carries
  are dropped.

Input files may be either the raw final bench line
(``{"metric", "value", ..., "extra": {...}}``) or the round wrapper
(``{"parsed": {...}}``, the BENCH_r*.json shape).  With one file
argument the OLD side defaults to the newest ``BENCH_r*.json`` in the
repo root that parses (current-vs-history mode).

Run: ``python tools/bench_diff.py NEW [OLD]`` — exit 1 on any
violation or stale pin; tier-1 exercises green/tamper/stale on a
synthetic pair (tests/test_perf_ledger.py, the test_zretrace lint
mold).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BUDGET = os.path.join(REPO, "tools", "perf_budget.txt")

# direction-by-name defaults for --update: latency/compile/freshness
# metrics gate downward, everything else (rates, MFU) upward
_LOWER_BETTER = re.compile(
    r"(_ms|compile_s|_seconds|_lag_s|_gen_s|_hbm_bytes_per_iter"
    r"|_ms_per_pass|_ms_per_leaf(_k\d+|_wide)?"
    r"|_sync(s|_count)_per_iter"
    r"|_peak_rss_mb|_wire_bytes|_overhead_pct)$")
# extras worth gating by default: primary value, throughput points,
# serve latency/throughput (host-accumulation AND fused device paths),
# mfu, the continual pipeline's freshness numbers, and the histogram
# contraction's measured pass/per-leaf costs (ISSUE 15 — both the
# hist_* headline aliases and the per-width hist_quant_* sweep keys)
_GATEABLE = re.compile(
    r"(^value$|_iters_per_sec$|^serve(_device)?_rows_per_s$"
    r"|^serve(_device)?_p\d+_ms$|_mfu$|_compile_s$"
    r"|^hist_hbm_bytes_per_iter$"
    r"|^hist_ms_per_(pass|leaf_k\d+|leaf_wide)$"
    r"|^hist_quant_q(off|8|16)_k\d+_ms_per_(pass|leaf)$"
    # super-epoch sweep (ISSUE 16, tools/bench_fused.sweep): headline
    # throughput + the structural syncs-per-iter count (1/k), plus the
    # per-k sweep keys
    r"|^superepoch_(iters_per_s|sync_count_per_iter"
    r"|k\d+_(valid|novalid)_(iters_per_s|syncs_per_iter))$"
    # fleet sweep (ISSUE 19, tools/bench_fleet.run_bench): the N=8
    # vmapped aggregate + the speedup ratio vs sequential solos, plus
    # the per-width sweep keys
    r"|^fleet_(agg_iters_per_s|speedup_x8"
    r"|n\d+_(agg_iters_per_s|speedup)|solo\d+_agg_iters_per_s)$"
    r"|^continual_(freshness_lag_s|gen_s)$"
    # out-of-core ingest (ISSUE 17, lightgbm_tpu/ingest.py): streaming
    # throughput, the bounded-memory subprocess RSS, and the
    # sketch-allgather wire bytes
    r"|^ingest_(rows_per_s|peak_rss_mb)$"
    r"|^binning_wire_bytes$"
    # integrity layer (ISSUE 20, lightgbm_tpu/integrity.py): the
    # measured cost of integrity_check_freq=16 over an unchecked run —
    # the "pay only on check iterations" contract as a gated number
    r"|^integrity_overhead_pct$)")
_DEFAULT_TOL = {"higher": 0.20, "lower": 0.30}


def load_record(path: str) -> Dict:
    """A bench record from either the raw final-line shape or the
    round wrapper ({"parsed": {...}})."""
    with open(path) as f:
        obj = json.load(f)
    if isinstance(obj, dict) and "parsed" in obj \
            and isinstance(obj["parsed"], dict):
        obj = obj["parsed"]
    if not isinstance(obj, dict) or "metric" not in obj:
        raise ValueError(f"{path}: not a bench record "
                         "(no 'metric'/'parsed' key)")
    return obj


def resolve(rec: Dict, key: str) -> Optional[float]:
    """Pinned key -> numeric value: top-level for ``value`` /
    ``vs_baseline``, else ``extra[key]``; None when absent or
    non-numeric."""
    v = rec.get(key) if key in ("value", "vs_baseline") \
        else (rec.get("extra") or {}).get(key)
    return float(v) if isinstance(v, (int, float)) \
        and not isinstance(v, bool) else None


def load_budget(path: str = BUDGET) -> Dict[str, Tuple[str, float]]:
    """{key: (direction, tolerance)} from the pin file."""
    out: Dict[str, Tuple[str, float]] = {}
    try:
        with open(path) as f:
            for raw in f:
                raw = raw.split("#")[0].strip()
                if not raw or "=" not in raw:
                    continue
                k, _, v = raw.partition("=")
                parts = v.split()
                if len(parts) != 2 or parts[0] not in ("higher", "lower"):
                    raise ValueError(
                        f"bad budget line {raw!r} "
                        "(want: <key> = higher|lower <tolerance>)")
                out[k.strip()] = (parts[0], float(parts[1]))
    except OSError:
        pass
    return out


def write_budget(pins: Dict[str, Tuple[str, float]],
                 path: str = BUDGET) -> None:
    lines = [
        "# Perf budget (tools/bench_diff.py): metrics gated between",
        "# bench rounds.  <key> = higher|lower <tolerance>: 'higher'",
        "# metrics may not fall more than <tolerance> (fraction) below",
        "# the old record, 'lower' metrics may not rise more than",
        "# <tolerance> above it.  The tolerance is the metric's noise",
        "# allowance — re-pin with `python tools/bench_diff.py --update",
        "# NEW.json` and justify tolerance changes in review.",
        "",
    ]
    for k in sorted(pins):
        d, t = pins[k]
        lines.append(f"{k} = {d} {t:g}")
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


def check(old: Dict, new: Dict,
          budget: Dict[str, Tuple[str, float]]) -> List[str]:
    """Violations + stale pins (empty list = gate green)."""
    findings: List[str] = []
    if not budget:
        return ["empty perf budget: nothing is pinned "
                "(tools/perf_budget.txt)"]
    eps = 1e-12
    for key in sorted(budget):
        direction, tol = budget[key]
        ov, nv = resolve(old, key), resolve(new, key)
        if ov is None and nv is None:
            findings.append(f"stale budget entry (metric in neither "
                            f"record): {key}")
            continue
        if ov is None:
            continue          # new coverage: gateable next round
        if nv is None:
            findings.append(f"metric disappeared: {key} "
                            f"(old={ov:g}, absent from the new record)")
            continue
        if direction == "higher":
            floor = ov * (1.0 - tol)
            if nv < floor - eps:
                findings.append(
                    f"regression: {key} = {nv:g} < {floor:g} "
                    f"(old {ov:g} - {tol:.0%} tolerance)")
        else:
            ceil = ov * (1.0 + tol)
            if nv > ceil + eps:
                findings.append(
                    f"regression: {key} = {nv:g} > {ceil:g} "
                    f"(old {ov:g} + {tol:.0%} tolerance)")
    return findings


def update(new: Dict, budget: Dict[str, Tuple[str, float]]
           ) -> Dict[str, Tuple[str, float]]:
    """Re-pin: keep tolerances of pins the record still carries, add
    defaults for newly gateable metrics, drop the rest."""
    keys = ["value"] + sorted(new.get("extra") or {})
    out: Dict[str, Tuple[str, float]] = {}
    for k in keys:
        if resolve(new, k) is None:
            continue
        if k in budget:
            out[k] = budget[k]
        elif _GATEABLE.search(k):
            d = "lower" if _LOWER_BETTER.search(k) else "higher"
            out[k] = (d, _DEFAULT_TOL[d])
    return out


def default_old(exclude: str) -> Optional[str]:
    """Newest BENCH_r*.json in the repo root that parses (the
    current-vs-history default when only NEW is given).  Ordered by
    the ROUND NUMBER, not the filename string — lexicographic order
    would put r99 after r100 once rounds outgrow the zero padding."""
    def round_no(p):
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        return int(m.group(1)) if m else -1
    cands = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")),
                   key=round_no, reverse=True)
    for path in cands:
        if os.path.abspath(path) == os.path.abspath(exclude):
            continue
        try:
            load_record(path)
            return path
        except (ValueError, OSError):
            continue
    return None


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("new", help="new bench json (the candidate)")
    ap.add_argument("old", nargs="?", default=None,
                    help="old bench json (default: newest parseable "
                         "BENCH_r*.json in the repo root)")
    ap.add_argument("--budget", default=BUDGET,
                    help="pin file (tests point this at a temp copy)")
    ap.add_argument("--update", action="store_true",
                    help="re-pin the budget from NEW instead of checking")
    args = ap.parse_args()

    new = load_record(args.new)
    if args.update:
        pins = update(new, load_budget(args.budget))
        write_budget(pins, args.budget)
        print(f"pinned {len(pins)} metric(s) to {args.budget}")
        return 0

    old_path = args.old or default_old(args.new)
    if old_path is None:
        print("bench_diff: no old record to compare against "
              "(no parseable BENCH_r*.json found)", file=sys.stderr)
        return 2
    old = load_record(old_path)
    print(f"bench_diff: {os.path.basename(old_path)} -> "
          f"{os.path.basename(args.new)}")
    findings = check(old, new, load_budget(args.budget))
    if findings:
        print("perf gate: regressions / stale pins:", file=sys.stderr)
        for f in findings:
            print(f"  {f}", file=sys.stderr)
        print(f"\n{len(findings)} finding(s).  If the perf change is "
              "intentional (or the pin is stale), re-pin with `python "
              "tools/bench_diff.py --update <NEW.json>` and justify "
              "the diff in review", file=sys.stderr)
        return 1
    print("perf gate: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
