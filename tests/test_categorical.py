"""Categorical split tests (test_engine.py categorical-handling analog).

The informative category subset is deliberately NOT count-ordered, so only
the gradient-ratio sorted-subset search (feature_histogram.hpp:278 analog)
can find it.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.metrics import _auc


def _cat_data(n=4000, seed=0, n_cats=12):
    rs = np.random.RandomState(seed)
    # category frequencies unrelated to label effect
    freqs = rs.dirichlet(np.ones(n_cats) * 2)
    cats = rs.choice(n_cats, size=n, p=freqs)
    # "good" categories = odd ids (interleaved with frequencies)
    good = {c for c in range(n_cats) if c % 2 == 1}
    noise = rs.randn(n, 3)
    logit = np.where(np.isin(cats, list(good)), 1.5, -1.5) \
        + 0.3 * noise[:, 0] + 0.2 * rs.randn(n)
    y = (logit > 0).astype(np.float32)
    x = np.column_stack([cats.astype(np.float64), noise])
    return x, y, good


class TestCategoricalSplits:
    def test_subset_split_quality(self):
        x, y, good = _cat_data()
        ds = lgb.Dataset(x, label=y, categorical_feature=[0],
                         params={"max_bin": 63})
        p = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
             "min_data_in_leaf": 5, "min_data_per_group": 1,
             "cat_smooth": 1.0, "cat_l2": 1.0}
        bst = lgb.train(p, ds, num_boost_round=20)
        auc = _auc(y, bst.predict(x, raw_score=True), None)
        assert auc > 0.93, f"categorical AUC too low: {auc}"
        # the categorical feature must dominate importance
        imp = bst.feature_importance("gain")
        assert imp[0] > imp[1:].sum()

    def test_model_io_with_categorical(self, tmp_path):
        x, y, good = _cat_data(seed=1)
        ds = lgb.Dataset(x, label=y, categorical_feature=[0],
                         params={"max_bin": 63})
        p = {"objective": "binary", "num_leaves": 7, "max_bin": 63,
             "min_data_per_group": 1, "cat_smooth": 1.0}
        bst = lgb.train(p, ds, num_boost_round=8)
        path = str(tmp_path / "cat_model.txt")
        bst.save_model(path)
        s = open(path).read()
        assert "num_cat=" in s
        bst2 = lgb.Booster(model_file=path)
        np.testing.assert_allclose(bst.predict(x[:200], raw_score=True),
                                   bst2.predict(x[:200], raw_score=True),
                                   rtol=1e-6, atol=1e-10)

    def test_unseen_category_goes_right(self):
        x, y, good = _cat_data(seed=2)
        ds = lgb.Dataset(x, label=y, categorical_feature=[0],
                         params={"max_bin": 63})
        p = {"objective": "binary", "num_leaves": 7, "max_bin": 63,
             "min_data_per_group": 1, "cat_smooth": 1.0}
        bst = lgb.train(p, ds, num_boost_round=8)
        xt = x[:10].copy()
        xt[:, 0] = 999.0   # unseen category
        pred = bst.predict(xt)
        assert np.isfinite(pred).all()

    def test_onehot_mode_few_categories(self):
        rs = np.random.RandomState(3)
        n = 3000
        cats = rs.choice(3, size=n)
        y = (cats == 1).astype(np.float32)
        x = np.column_stack([cats.astype(np.float64), rs.randn(n, 2)])
        ds = lgb.Dataset(x, label=y, categorical_feature=[0])
        p = {"objective": "binary", "num_leaves": 7, "max_bin": 31,
             "max_cat_to_onehot": 4, "min_data_per_group": 1}
        bst = lgb.train(p, ds, num_boost_round=10)
        pred = bst.predict(x)
        acc = ((pred > 0.5) == y).mean()
        assert acc > 0.99, f"one-vs-rest split should isolate category: {acc}"

    def test_pandas_category_dtype(self):
        pd = pytest.importorskip("pandas")
        x, y, good = _cat_data(seed=4)
        df = pd.DataFrame({
            "cat": pd.Categorical([f"c{int(v)}" for v in x[:, 0]]),
            "a": x[:, 1], "b": x[:, 2],
        })
        from lightgbm_tpu.sklearn import LGBMClassifier
        m = LGBMClassifier(n_estimators=10, num_leaves=15, max_bin=63,
                           min_data_per_group=1, cat_smooth=1.0)
        m.fit(df, y)
        pred = m.predict_proba(df)[:, 1]
        assert _auc(y, pred, None) > 0.9
