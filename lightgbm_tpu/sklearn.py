"""scikit-learn compatible estimator API.

Analog of the reference python-package sklearn layer
(/root/reference/python-package/lightgbm/sklearn.py:343-1100):
``LGBMModel`` base with ``LGBMRegressor`` / ``LGBMClassifier`` /
``LGBMRanker``, objective/eval-function wrappers (:45-126), and the same
constructor parameter surface.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from .booster import Booster
from .callback import early_stopping as early_stopping_cb
from .callback import log_evaluation
from .config import Config
from .dataset import Dataset
from .engine import train as train_fn

try:
    # real sklearn estimators (the reference inherits the same bases
    # through compat): BaseEstimator supplies __sklearn_tags__ /
    # clone / pipeline / GridSearchCV integration, the mixins tag the
    # estimator type
    from sklearn.base import (BaseEstimator as _LGBMModelBase,
                              ClassifierMixin as _LGBMClassifierBase,
                              RegressorMixin as _LGBMRegressorBase)
except ImportError:             # sklearn is optional
    class _LGBMModelBase:
        pass

    class _LGBMClassifierBase:
        pass

    class _LGBMRegressorBase:
        pass


class LGBMModel(_LGBMModelBase):
    """Base estimator (sklearn.py:343 LGBMModel analog)."""

    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 100, subsample_for_bin: int = 200000,
                 objective: Optional[str] = None, class_weight=None,
                 min_split_gain: float = 0.0, min_child_weight: float = 1e-3,
                 min_child_samples: int = 20, subsample: float = 1.0,
                 subsample_freq: int = 0, colsample_bytree: float = 1.0,
                 reg_alpha: float = 0.0, reg_lambda: float = 0.0,
                 random_state: Optional[int] = None, n_jobs: int = -1,
                 importance_type: str = "split", **kwargs):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.importance_type = importance_type
        self._other_params = dict(kwargs)
        self._Booster: Optional[Booster] = None
        self._n_features = 0
        self._classes = None
        self._n_classes = 1
        self.best_iteration_ = -1
        self.best_score_: Dict = {}

    # -- sklearn plumbing --------------------------------------------------
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = {k: getattr(self, k) for k in (
            "boosting_type", "num_leaves", "max_depth", "learning_rate",
            "n_estimators", "subsample_for_bin", "objective", "class_weight",
            "min_split_gain", "min_child_weight", "min_child_samples",
            "subsample", "subsample_freq", "colsample_bytree", "reg_alpha",
            "reg_lambda", "random_state", "n_jobs", "importance_type")}
        params.update(self._other_params)
        return params

    def set_params(self, **params) -> "LGBMModel":
        for k, v in params.items():
            if hasattr(self, k):
                setattr(self, k, v)
            else:
                self._other_params[k] = v
        return self

    def _lgb_params(self) -> Dict[str, Any]:
        p = {
            "boosting": self.boosting_type,
            "num_leaves": self.num_leaves,
            "max_depth": self.max_depth,
            "learning_rate": self.learning_rate,
            "bin_construct_sample_cnt": self.subsample_for_bin,
            "objective": self.objective or self._default_objective(),
            "min_gain_to_split": self.min_split_gain,
            "min_sum_hessian_in_leaf": self.min_child_weight,
            "min_data_in_leaf": self.min_child_samples,
            "bagging_fraction": self.subsample,
            "bagging_freq": self.subsample_freq,
            "feature_fraction": self.colsample_bytree,
            "lambda_l1": self.reg_alpha,
            "lambda_l2": self.reg_lambda,
            "verbosity": 0,
        }
        if self.random_state is not None:
            if isinstance(self.random_state, np.random.RandomState):
                # reference sklearn.py: a RandomState draws one int seed
                p["seed"] = int(self.random_state.randint(
                    np.iinfo(np.int32).max))
            elif isinstance(self.random_state, np.random.Generator):
                p["seed"] = int(self.random_state.integers(
                    np.iinfo(np.int32).max))
            else:
                p["seed"] = int(self.random_state)
        p.update(self._other_params)
        if callable(p.get("objective")):
            # custom objective callable (reference _ObjectiveFunctionWrapper):
            # training uses fobj; the recorded objective becomes 'none'
            self._fobj_callable = p["objective"]
            p["objective"] = "none"
        else:
            self._fobj_callable = None
        return p

    def _default_objective(self) -> str:
        return "regression"

    # -- fit/predict -------------------------------------------------------
    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_class_weight=None, eval_init_score=None,
            eval_group=None, eval_metric=None, feval=None,
            early_stopping_rounds=None, callbacks=None, init_model=None,
            categorical_feature="auto", feature_name="auto") -> "LGBMModel":
        params = self._lgb_params()
        from .basic import list_to_1d_numpy
        y_arr = list_to_1d_numpy(np.asarray(y), dtype=np.float64,
                                 name="label")
        y_t = self._process_label(y_arr)
        if init_model is not None and hasattr(init_model, "booster_"):
            init_model = init_model.booster_   # fitted estimator
        sample_weight = self._class_weights(sample_weight, y_t)
        # eval_metric: strings extend the params metric, callables become
        # feval wrappers (reference sklearn.py _EvalFunctionWrapper:
        # f(y_true, y_pred) -> (name, value, is_higher_better))
        fevals = list(feval) if isinstance(feval, (list, tuple)) \
            else ([feval] if feval else [])

        def _wrap_eval(fn):
            def _fe(score, dsx):
                return fn(np.asarray(dsx.get_label()), np.asarray(score))
            return _fe

        if eval_metric is not None:
            ms = eval_metric if isinstance(eval_metric, list) else [eval_metric]
            str_metrics = [m for m in ms if isinstance(m, str)]
            fevals += [_wrap_eval(m) for m in ms if callable(m)]
            if str_metrics:
                params["metric"] = str_metrics
        if early_stopping_rounds:
            params["early_stopping_round"] = int(early_stopping_rounds)
        if self._fobj_callable is not None:
            fobj_fn = self._fobj_callable

            def _fobj(preds, dsx):
                return fobj_fn(np.asarray(dsx.get_label()),
                               np.asarray(preds))
        else:
            _fobj = None

        ds = Dataset(X, label=y_t, weight=sample_weight, group=group,
                     init_score=init_score, params=params,
                     feature_name=feature_name,
                     categorical_feature=categorical_feature)
        valid_sets, valid_names = [], []
        if eval_set:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]    # a bare (X, y) pair
            for i, (vx, vy) in enumerate(eval_set):
                name = eval_names[i] if eval_names else f"valid_{i}"
                if vx is X and (vy is y or
                                vy is getattr(self, "_train_label_ref",
                                              None)):
                    # the training pair in eval_set reports the train
                    # metrics under its name (reference _get_meta_data)
                    valid_sets.append(ds)
                    valid_names.append(name if eval_names else "training")
                    continue
                vw = eval_sample_weight[i] if eval_sample_weight else None
                vy_t = self._encode_eval_label(np.asarray(vy))
                if eval_class_weight and i < len(eval_class_weight):
                    cw = self._class_weights(vw, vy_t,
                                             eval_class_weight[i])
                    vw = cw if cw is not None else vw
                vg = eval_group[i] if eval_group else None
                vis = eval_init_score[i] if eval_init_score else None
                valid_sets.append(Dataset(
                    vx, label=vy_t, weight=vw, group=vg, init_score=vis,
                    reference=ds))
                valid_names.append(name)

        from .callback import record_evaluation
        evals: Dict = {}
        cbs = list(callbacks or [])
        if valid_sets:
            cbs.append(record_evaluation(evals))
        self._Booster = train_fn(params, ds,
                                 num_boost_round=self.n_estimators,
                                 valid_sets=valid_sets or None,
                                 valid_names=valid_names or None,
                                 feval=fevals or None, fobj=_fobj,
                                 init_model=init_model,
                                 callbacks=cbs or None)
        self._n_features = np.asarray(X).shape[1] if hasattr(X, "shape") else \
            len(X[0])
        self.best_iteration_ = self._Booster.best_iteration
        self.best_score_ = self._Booster.best_score
        # sklearn-API result attributes (reference sklearn.py fit tail)
        self._evals_result = evals
        self.fitted_ = True
        self.n_iter_ = (self.best_iteration_
                        if self.best_iteration_ and self.best_iteration_ > 0
                        else self._Booster.current_iteration)
        self.objective_ = params.get("objective",
                                     getattr(self, "objective", None))
        return self

    @property
    def evals_result_(self) -> Dict:
        """Per-eval-set metric history recorded during fit
        (reference sklearn.py evals_result_)."""
        return getattr(self, "_evals_result", {})

    def _process_label(self, y: np.ndarray) -> np.ndarray:
        return y.astype(np.float32)

    def _encode_eval_label(self, y: np.ndarray) -> np.ndarray:
        """eval_set labels through the same transform as train labels
        (the classifier maps through its fitted classes)."""
        if y.ndim == 2 and y.shape[1] == 1:
            y = y.ravel()
        return self._process_label(y)

    def _class_weights(self, sample_weight, y, class_weight=None):
        return sample_weight

    def predict(self, X, raw_score: bool = False, num_iteration=None,
                pred_leaf: bool = False, pred_contrib: bool = False,
                **kw) -> np.ndarray:
        self._check_fitted()
        return self._Booster.predict(X, raw_score=raw_score,
                                     num_iteration=num_iteration,
                                     pred_leaf=pred_leaf,
                                     pred_contrib=pred_contrib)

    def _check_fitted(self):
        if self._Booster is None:
            raise ValueError("Estimator not fitted; call fit first")

    # -- attributes --------------------------------------------------------
    @property
    def booster_(self) -> Booster:
        self._check_fitted()
        return self._Booster

    @property
    def feature_importances_(self) -> np.ndarray:
        self._check_fitted()
        return self._Booster.feature_importance(self.importance_type)

    @property
    def n_features_(self) -> int:
        return self._n_features

    @property
    def n_features_in_(self) -> int:
        return self._n_features

    @property
    def n_estimators_(self) -> int:
        self._check_fitted()
        return self._Booster.current_iteration

    @property
    def feature_name_(self) -> List[str]:
        self._check_fitted()
        return self._Booster.feature_names


class LGBMRegressor(_LGBMRegressorBase, LGBMModel):
    """sklearn.py:919 LGBMRegressor analog."""

    def _default_objective(self) -> str:
        return "regression"


class LGBMClassifier(_LGBMClassifierBase, LGBMModel):
    """sklearn.py:~990 LGBMClassifier analog."""

    def _default_objective(self) -> str:
        return "binary" if self._n_classes <= 2 else "multiclass"

    def fit(self, X, y, **kw):
        from .basic import list_to_1d_numpy
        y = np.asarray(y)
        if y.ndim == 2 and y.shape[1] == 1:
            y = list_to_1d_numpy(y, dtype=y.dtype, name="label")
        self._classes, y_enc = np.unique(y, return_inverse=True)
        self._n_classes = len(self._classes)
        self._y_encoded = y_enc
        self._train_label_ref = y     # eval_set identity check in base fit
        params_extra = {}
        if self._n_classes > 2:
            params_extra["num_class"] = self._n_classes
            self._other_params.setdefault("num_class", self._n_classes)
        return super().fit(X, y_enc, **kw)

    def _process_label(self, y):
        return y.astype(np.float32)

    def _class_weights(self, sample_weight, y, class_weight=None):
        cw = class_weight if class_weight is not None else self.class_weight
        if cw is None:
            return sample_weight
        if cw == "balanced":
            counts = np.bincount(y.astype(int), minlength=self._n_classes)
            w_per_class = len(y) / (self._n_classes * np.maximum(counts, 1))
        else:
            # dict keys are the ORIGINAL label values ({1: w, 2: w} or
            # strings), not encoded class indices — look up through the
            # fitted classes (reference: compute_sample_weight keys by
            # original label)
            w_per_class = np.asarray([cw.get(self._classes[c], 1.0)
                                      for c in range(self._n_classes)])
        w = w_per_class[y.astype(int)]
        if sample_weight is not None:
            w = w * np.asarray(sample_weight)
        return w

    def _encode_eval_label(self, y: np.ndarray) -> np.ndarray:
        if y.ndim == 2 and y.shape[1] == 1:
            y = y.ravel()
        idx = np.searchsorted(self._classes, y)
        idx = np.clip(idx, 0, len(self._classes) - 1)
        if not np.array_equal(self._classes[idx], y):
            raise ValueError(
                "eval_set contains labels not present in the training "
                f"classes {list(self._classes)}")
        return idx.astype(np.float32)

    @property
    def classes_(self):
        self._check_fitted()
        return self._classes

    @property
    def n_classes_(self) -> int:
        return self._n_classes

    def predict(self, X, raw_score=False, num_iteration=None,
                pred_leaf=False, pred_contrib=False, **kw):
        res = self.predict_proba(X, raw_score=raw_score,
                                 num_iteration=num_iteration,
                                 pred_leaf=pred_leaf,
                                 pred_contrib=pred_contrib)
        if raw_score or pred_leaf or pred_contrib:
            return res
        if res.ndim > 1:
            return self._classes[np.argmax(res, axis=1)]
        return self._classes[(res > 0.5).astype(int)]

    def predict_proba(self, X, raw_score=False, num_iteration=None,
                      pred_leaf=False, pred_contrib=False, **kw):
        self._check_fitted()
        res = self._Booster.predict(X, raw_score=raw_score,
                                    num_iteration=num_iteration,
                                    pred_leaf=pred_leaf,
                                    pred_contrib=pred_contrib)
        if raw_score or pred_leaf or pred_contrib:
            return res
        if self._n_classes <= 2 and res.ndim == 1:
            return np.column_stack([1.0 - res, res])
        return res


class LGBMRanker(LGBMModel):
    """sklearn.py:~1060 LGBMRanker analog."""

    def _default_objective(self) -> str:
        return "lambdarank"

    def fit(self, X, y, group=None, eval_at=(1, 2, 3, 4, 5), **kw):
        if group is None:
            raise ValueError("LGBMRanker requires group")
        # eval_at rides the params for this fit only (reference
        # LGBMRanker.fit: params['eval_at'] = self.eval_at)
        saved = dict(self._other_params)
        try:
            self._other_params = dict(self._other_params,
                                      eval_at=list(eval_at))
            return super().fit(X, y, group=group, **kw)
        finally:
            self._other_params = saved
