"""convert_model codegen parity (gbdt_model_text.cpp:124 ModelToIfElse
analog): compile the generated C and compare against Booster.predict,
including NaN routing, categorical bitsets and multiclass softmax."""

import ctypes
import subprocess

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _compile(code: str, tmp_path):
    src = tmp_path / "model.c"
    so = tmp_path / "model.so"
    src.write_text(code)
    subprocess.run(["cc", "-O1", "-shared", "-fPIC", str(src),
                    "-o", str(so), "-lm"], check=True)
    lib = ctypes.CDLL(str(so))
    lib.predict.argtypes = [ctypes.POINTER(ctypes.c_double),
                            ctypes.POINTER(ctypes.c_double)]
    lib.predict_raw.argtypes = lib.predict.argtypes
    lib.get_num_class.restype = ctypes.c_int
    return lib


def _c_predict(lib, X, raw=False):
    k = lib.get_num_class()
    out = np.zeros((len(X), k))
    buf = (ctypes.c_double * k)()
    fn = lib.predict_raw if raw else lib.predict
    for i, row in enumerate(np.ascontiguousarray(X, np.float64)):
        fn(row.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), buf)
        out[i] = buf[:]
    return out[:, 0] if k == 1 else out


def test_binary_with_nan(tmp_path):
    rs = np.random.RandomState(0)
    x = rs.randn(1500, 8)
    x[rs.rand(1500, 8) < 0.1] = np.nan
    y = (np.nan_to_num(x[:, 0]) + np.nan_to_num(x[:, 1]) > 0).astype(np.float32)
    bst = lgb.train({"objective": "binary", "num_leaves": 15, "max_bin": 63,
                     "verbosity": -1}, lgb.Dataset(x, label=y),
                    num_boost_round=12)
    lib = _compile(bst.to_c_code(), tmp_path)
    np.testing.assert_allclose(_c_predict(lib, x), bst.predict(x), rtol=2e-6)
    np.testing.assert_allclose(_c_predict(lib, x, raw=True),
                               bst.predict(x, raw_score=True), rtol=1e-10)


def test_multiclass_softmax(tmp_path):
    rs = np.random.RandomState(1)
    x = rs.randn(1200, 6)
    y = (x[:, 0] > 0).astype(int) + (x[:, 1] > 0.5).astype(int)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 7, "max_bin": 31, "verbosity": -1},
                    lgb.Dataset(x, label=y), num_boost_round=8)
    lib = _compile(bst.to_c_code(), tmp_path)
    np.testing.assert_allclose(_c_predict(lib, x), bst.predict(x), rtol=2e-6)


def test_categorical_split(tmp_path):
    rs = np.random.RandomState(2)
    n = 2000
    cat = rs.randint(0, 12, n).astype(np.float64)
    num = rs.randn(n)
    x = np.column_stack([cat, num])
    y = (np.isin(cat, [1, 4, 7]) ^ (num > 0.3)).astype(np.float32)
    bst = lgb.train({"objective": "binary", "num_leaves": 15, "max_bin": 63,
                     "min_data_in_leaf": 5, "verbosity": -1},
                    lgb.Dataset(x, label=y, categorical_feature=[0]),
                    num_boost_round=10)
    lib = _compile(bst.to_c_code(), tmp_path)
    # include out-of-range / negative category probes
    probe = np.column_stack([np.array([0., 1., 4., 7., 11., 25., -3., np.nan]),
                             np.zeros(8)])
    np.testing.assert_allclose(_c_predict(lib, probe), bst.predict(probe),
                               rtol=2e-6)
    np.testing.assert_allclose(_c_predict(lib, x), bst.predict(x), rtol=2e-6)


def test_cli_convert_model_task(tmp_path):
    rs = np.random.RandomState(3)
    x = rs.randn(500, 4)
    y = (x[:, 0] > 0).astype(np.float32)
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1},
                    lgb.Dataset(x, label=y), num_boost_round=3)
    model_path = tmp_path / "model.txt"
    bst.save_model(str(model_path))
    out = tmp_path / "model.c"
    from lightgbm_tpu.cli import run
    assert run(["task=convert_model", f"input_model={model_path}",
                f"convert_model={out}"]) == 0
    assert "predict_raw" in out.read_text()


def test_linear_tree_codegen(tmp_path):
    rs = np.random.RandomState(4)
    x = rs.randn(1500, 5)
    y = (2.0 * x[:, 0] - x[:, 1] + 0.1 * rs.randn(1500)).astype(np.float32)
    bst = lgb.train({"objective": "regression", "linear_tree": True,
                     "num_leaves": 7, "verbosity": -1},
                    lgb.Dataset(x, label=y), num_boost_round=8)
    lib = _compile(bst.to_c_code(), tmp_path)
    xp = x.copy()
    xp[0, 0] = np.nan  # linear-leaf NaN fallback
    np.testing.assert_allclose(_c_predict(lib, xp), bst.predict(xp),
                               rtol=2e-6, atol=1e-6)
