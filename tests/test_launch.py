"""Multi-host orchestration helpers (parallel/launch.py — the dask.py
process-orchestration analog)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.parallel import launch


def test_row_shard_partition():
    x = np.arange(100, dtype=np.float64).reshape(50, 2)
    y = np.arange(50, dtype=np.float32)
    shards = [launch.row_shard(x, y, process_index=i, process_count=4)
              for i in range(4)]
    assert sum(len(s.x) for s in shards) == 50
    np.testing.assert_array_equal(np.vstack([s.x for s in shards]), x)
    np.testing.assert_array_equal(np.concatenate([s.y for s in shards]), y)


def test_machines_param_parsing(monkeypatch):
    captured = {}

    class FakeDist:
        def initialize(self, **kw):
            captured.update(kw)

    import jax
    monkeypatch.setattr(jax, "distributed", FakeDist())
    monkeypatch.setattr(launch, "init", launch.init)  # reset memo
    if hasattr(launch.init, "_done"):
        del launch.init._done
    launch.init(machines="127.0.0.1:12400,10.0.0.2:12400")
    assert captured["coordinator_address"] == "127.0.0.1:12400"
    assert captured["num_processes"] == 2
    assert captured["process_id"] == 0
    del launch.init._done


def test_shard_sample_and_global_mappers():
    rs = np.random.RandomState(0)
    x = rs.randn(4000, 6)
    shards = [launch.row_shard(x, process_index=i, process_count=2)
              for i in range(2)]
    cfg = Config({"max_bin": 31})

    import threading
    mailbox = [None, None]
    barrier = threading.Barrier(2)

    def make_ag(rank):
        def ag(payload):
            mailbox[rank] = payload
            barrier.wait(timeout=30)
            out = list(mailbox)
            barrier.wait(timeout=30)
            return out
        return ag

    out = [None, None]

    def run(rank):
        from lightgbm_tpu.parallel.dist_data import distributed_bin_mappers
        out[rank] = distributed_bin_mappers(
            shards[rank].sample(1000), cfg, process_index=rank,
            process_count=2, allgather=make_ag(rank))

    ts = [threading.Thread(target=run, args=(r,)) for r in range(2)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert len(out[0]) == 6
    for m0, m1 in zip(out[0], out[1]):
        assert m0.num_bin == m1.num_bin
