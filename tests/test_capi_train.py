"""Native training C API (capi_train.cpp): the LGBM-style train-from-C
lifecycle (c_api.h dataset create -> booster create -> UpdateOneIter ->
SaveModel -> PredictForMat) driven both from a pure-C host process
(embedded interpreter) and in-process via ctypes."""

import ctypes
import os
import subprocess
import sysconfig

import numpy as np
import pytest

import lightgbm_tpu as lgb

SO = os.path.join(os.path.dirname(lgb.__file__), "native", "libcapi_train.so")
SRC = os.path.join(os.path.dirname(lgb.__file__), "native", "capi_train.cpp")


def _ensure_built() -> str:
    """Build libcapi_train.so on demand (VERDICT r2: a stale-path skipif
    meant these tests silently guarded nothing; now only a FAILING build
    skips, with the compiler error in the reason).  Flags come from THIS
    interpreter's sysconfig — `python3-config` on PATH may belong to a
    different Python, and an .so embedding a mismatched libpython
    corrupts the test process instead of skipping."""
    if os.path.exists(SO) and os.path.getmtime(SO) >= os.path.getmtime(SRC):
        return ""
    inc = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    ver = sysconfig.get_config_var("LDVERSION") \
        or sysconfig.get_config_var("VERSION")
    if not inc or not ver:
        return "sysconfig lacks include/version info"
    cmd = (["g++", "-O2", "-shared", "-fPIC", SRC, "-o", SO, f"-I{inc}"]
           + ([f"-L{libdir}"] if libdir else [])
           + [f"-lpython{ver}"]
           + (sysconfig.get_config_var("LIBS") or "").split())
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    if r.returncode != 0:
        return f"build failed: {r.stderr[-400:]}"
    return ""


_BUILD_ERR = _ensure_built()
pytestmark = pytest.mark.skipif(bool(_BUILD_ERR), reason=_BUILD_ERR)


def _data(n=1200, f=6, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, f)
    y = (x[:, 0] - 0.5 * x[:, 1] > 0).astype(np.float32)
    return np.ascontiguousarray(x, np.float64), y


def test_inprocess_train_lifecycle():
    lib = ctypes.CDLL(SO)
    lib.LGBM_TrainGetLastError.restype = ctypes.c_char_p
    x, y = _data()
    n, f = x.shape

    ds = ctypes.c_void_p()
    rc = lib.LGBM_TrainDatasetCreateFromMat(
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), n, f,
        b"max_bin=63 verbosity=-1", None, ctypes.byref(ds))
    assert rc == 0, lib.LGBM_TrainGetLastError()
    rc = lib.LGBM_TrainDatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p), n, 0)
    assert rc == 0, lib.LGBM_TrainGetLastError()

    nd = ctypes.c_int()
    assert lib.LGBM_TrainDatasetGetNumData(ds, ctypes.byref(nd)) == 0
    assert nd.value == n

    bst = ctypes.c_void_p()
    rc = lib.LGBM_TrainBoosterCreate(
        ds, b"objective=binary num_leaves=15 learning_rate=0.1 verbosity=-1",
        ctypes.byref(bst))
    assert rc == 0, lib.LGBM_TrainGetLastError()

    fin = ctypes.c_int()
    for _ in range(10):
        rc = lib.LGBM_TrainBoosterUpdateOneIter(bst, ctypes.byref(fin))
        assert rc == 0, lib.LGBM_TrainGetLastError()
    it = ctypes.c_int()
    assert lib.LGBM_TrainBoosterGetCurrentIteration(bst, ctypes.byref(it)) == 0
    assert it.value == 10

    s = ctypes.c_char_p()
    rc = lib.LGBM_TrainBoosterSaveModelToString(bst, 0, -1, ctypes.byref(s))
    assert rc == 0, lib.LGBM_TrainGetLastError()
    model_str = s.value.decode()
    assert "Tree=0" in model_str

    out = np.zeros(n, np.float64)
    out_len = ctypes.c_int64()
    # out_capacity is int64_t and sits PAST the 6 integer registers: a
    # bare python int marshals as 4 bytes into an 8-byte stack slot whose
    # upper half is whatever the caller left there — wrap it explicitly
    rc = lib.LGBM_TrainBoosterPredictForMat(
        bst, x.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), n, f,
        0, 0, -1, ctypes.c_int64(n),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ctypes.byref(out_len))
    assert rc == 0, lib.LGBM_TrainGetLastError()
    assert out_len.value == n

    # parity with the Python API on the same model text
    ref = lgb.Booster(model_str=model_str).predict(x)
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-8)
    acc = ((out > 0.5) == y).mean()
    assert acc > 0.9, acc

    lib.LGBM_TrainBoosterFree(bst)
    lib.LGBM_TrainDatasetFree(ds)


def test_error_reporting():
    lib = ctypes.CDLL(SO)
    lib.LGBM_TrainGetLastError.restype = ctypes.c_char_p
    bst = ctypes.c_void_p()
    rc = lib.LGBM_TrainBoosterCreateFromModelString(
        b"not a model", ctypes.byref(bst))
    assert rc == -1
    assert lib.LGBM_TrainGetLastError()


C_HOST = r"""
#include <stdio.h>
#include <stdlib.h>

typedef void* H;
extern const char* LGBM_TrainGetLastError(void);
extern int LGBM_TrainDatasetCreateFromMat(const double*, int, int,
                                          const char*, H, H*);
extern int LGBM_TrainDatasetSetField(H, const char*, const void*, int, int);
extern int LGBM_TrainBoosterCreate(H, const char*, H*);
extern int LGBM_TrainBoosterUpdateOneIter(H, int*);
extern int LGBM_TrainBoosterSaveModel(H, int, int, const char*);
extern int LGBM_TrainBoosterPredictForMat(H, const double*, int, int, int,
                                          int, int, long long, double*,
                                          long long*);

#define CHECK(rc) if ((rc) != 0) { \
  fprintf(stderr, "FAIL: %s\n", LGBM_TrainGetLastError()); return 1; }

int main(int argc, char** argv) {
  const int n = 800, f = 4;
  double* x = (double*)malloc(sizeof(double) * n * f);
  float* y = (float*)malloc(sizeof(float) * n);
  unsigned s = 42;
  for (int i = 0; i < n; ++i) {
    double acc = 0.0;
    for (int j = 0; j < f; ++j) {
      s = s * 1664525u + 1013904223u;
      double v = (double)(s >> 8) / (double)(1 << 24) - 0.5;
      x[i * f + j] = v;
      if (j == 0) acc = v;
    }
    y[i] = acc > 0.0 ? 1.0f : 0.0f;
  }
  H ds = 0, bst = 0;
  CHECK(LGBM_TrainDatasetCreateFromMat(x, n, f, "max_bin=63", 0, &ds));
  CHECK(LGBM_TrainDatasetSetField(ds, "label", y, n, 0));
  CHECK(LGBM_TrainBoosterCreate(ds,
        "objective=binary num_leaves=7 verbosity=-1", &bst));
  int fin = 0;
  for (int i = 0; i < 5; ++i) CHECK(LGBM_TrainBoosterUpdateOneIter(bst, &fin));
  CHECK(LGBM_TrainBoosterSaveModel(bst, 0, -1, argv[1]));
  double* out = (double*)malloc(sizeof(double) * n);
  long long out_len = 0;
  CHECK(LGBM_TrainBoosterPredictForMat(bst, x, n, f, 0, 0, -1, n, out,
                                       &out_len));
  int correct = 0;
  for (int i = 0; i < n; ++i)
    if ((out[i] > 0.5) == (y[i] > 0.5f)) ++correct;
  printf("acc=%f\n", (double)correct / n);
  return (double)correct / n > 0.9 ? 0 : 2;
}
"""


def test_pure_c_host(tmp_path):
    """Compile a C program against libcapi_train.so and train end-to-end in
    a process that starts with NO Python interpreter."""
    src = tmp_path / "host.c"
    exe = tmp_path / "host"
    model = tmp_path / "model.txt"
    src.write_text(C_HOST)
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    subprocess.run(
        ["cc", "-O1", str(src), "-o", str(exe), SO,
         f"-Wl,-rpath,{os.path.dirname(SO)}", f"-Wl,-rpath,{libdir}"],
        check=True)
    env = dict(os.environ,
               PYTHONPATH="/root/repo",
               LGBM_TPU_FORCE_CPU="1",
               XLA_FLAGS="--xla_force_host_platform_device_count=1")
    r = subprocess.run([str(exe), str(model)], env=env, capture_output=True,
                       text=True, timeout=300)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert model.exists()
    # the saved model loads back in the Python API
    bst = lgb.Booster(model_file=str(model))
    assert bst.current_iteration == 5


# ---------------------------------------------------------------------------
# round-3 surface: CSR/CSC/streaming dataset create, CSR predict, getters,
# reset-parameter, network init (c_api.h:109-313, 815, 1350)
# ---------------------------------------------------------------------------

def _lib():
    lib = ctypes.CDLL(SO)
    lib.LGBM_TrainGetLastError.restype = ctypes.c_char_p
    return lib


def _csr_parts(x):
    from scipy.sparse import csr_matrix
    m = csr_matrix(x)
    return (np.ascontiguousarray(m.indptr, np.int32),
            np.ascontiguousarray(m.indices, np.int32),
            np.ascontiguousarray(m.data, np.float64))


def _train_c(lib, ds, rounds=8,
             params=b"objective=binary num_leaves=15 verbosity=-1"):
    bst = ctypes.c_void_p()
    rc = lib.LGBM_TrainBoosterCreate(ds, params, ctypes.byref(bst))
    assert rc == 0, lib.LGBM_TrainGetLastError()
    fin = ctypes.c_int()
    for _ in range(rounds):
        assert lib.LGBM_TrainBoosterUpdateOneIter(bst, ctypes.byref(fin)) == 0
    s = ctypes.c_char_p()
    assert lib.LGBM_TrainBoosterSaveModelToString(bst, 0, -1,
                                                  ctypes.byref(s)) == 0
    return bst, s.value.decode()


def test_csr_create_and_predict():
    lib = _lib()
    x, y = _data(n=800, f=6, seed=3)
    x[np.random.RandomState(0).rand(*x.shape) < 0.6] = 0.0  # sparsify
    indptr, indices, data = _csr_parts(x)

    ds = ctypes.c_void_p()
    rc = lib.LGBM_TrainDatasetCreateFromCSR(
        indptr.ctypes.data_as(ctypes.c_void_p), ctypes.c_int64(len(indptr)),
        indices.ctypes.data_as(ctypes.c_void_p),
        data.ctypes.data_as(ctypes.c_void_p), ctypes.c_int64(len(data)),
        ctypes.c_int64(x.shape[1]), b"max_bin=63 verbosity=-1", None,
        ctypes.byref(ds))
    assert rc == 0, lib.LGBM_TrainGetLastError()
    assert lib.LGBM_TrainDatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p), len(y), 0) == 0
    bst, model_str = _train_c(lib, ds)

    # CSR predict == dense predict == Python predict on the same model
    n = x.shape[0]
    out = np.zeros(n, np.float64)
    out_len = ctypes.c_int64()
    rc = lib.LGBM_TrainBoosterPredictForCSR(
        bst, indptr.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int64(len(indptr)),
        indices.ctypes.data_as(ctypes.c_void_p),
        data.ctypes.data_as(ctypes.c_void_p), ctypes.c_int64(len(data)),
        ctypes.c_int64(x.shape[1]), 0, 0, -1, ctypes.c_int64(n),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ctypes.byref(out_len))
    assert rc == 0, lib.LGBM_TrainGetLastError()
    assert out_len.value == n
    ref = lgb.Booster(model_str=model_str).predict(x)
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-8)
    lib.LGBM_TrainBoosterFree(bst)
    lib.LGBM_TrainDatasetFree(ds)


def test_csc_create_matches_dense():
    lib = _lib()
    x, y = _data(n=600, f=5, seed=4)
    from scipy.sparse import csc_matrix
    m = csc_matrix(x)
    indptr = np.ascontiguousarray(m.indptr, np.int32)
    indices = np.ascontiguousarray(m.indices, np.int32)
    data = np.ascontiguousarray(m.data, np.float64)

    ds = ctypes.c_void_p()
    rc = lib.LGBM_TrainDatasetCreateFromCSC(
        indptr.ctypes.data_as(ctypes.c_void_p), ctypes.c_int64(len(indptr)),
        indices.ctypes.data_as(ctypes.c_void_p),
        data.ctypes.data_as(ctypes.c_void_p), ctypes.c_int64(len(data)),
        ctypes.c_int64(x.shape[0]), b"max_bin=63 verbosity=-1", None,
        ctypes.byref(ds))
    assert rc == 0, lib.LGBM_TrainGetLastError()
    assert lib.LGBM_TrainDatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p), len(y), 0) == 0
    _, model_csc = _train_c(lib, ds)

    ds2 = ctypes.c_void_p()
    assert lib.LGBM_TrainDatasetCreateFromMat(
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), x.shape[0],
        x.shape[1], b"max_bin=63 verbosity=-1", None, ctypes.byref(ds2)) == 0
    assert lib.LGBM_TrainDatasetSetField(
        ds2, b"label", y.ctypes.data_as(ctypes.c_void_p), len(y), 0) == 0
    _, model_dense = _train_c(lib, ds2)
    # CSC zeros become missing-type zero bins exactly like dense zeros
    assert model_csc.split("\n\n")[1] == model_dense.split("\n\n")[1]


def test_streaming_push_rows_matches_dense():
    lib = _lib()
    x, y = _data(n=1000, f=5, seed=5)
    n, f = x.shape

    sd = ctypes.c_void_p()
    rc = lib.LGBM_TrainDatasetCreateStreaming(
        ctypes.c_int64(n), f, b"max_bin=63 verbosity=-1", ctypes.byref(sd))
    assert rc == 0, lib.LGBM_TrainGetLastError()
    for start in range(0, n, 300):           # push in 300-row chunks
        chunk = np.ascontiguousarray(x[start:start + 300])
        rc = lib.LGBM_TrainDatasetPushRows(
            sd, chunk.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            chunk.shape[0], f, start)
        assert rc == 0, lib.LGBM_TrainGetLastError()
    assert lib.LGBM_TrainDatasetSetField(
        sd, b"label", y.ctypes.data_as(ctypes.c_void_p), n, 0) == 0
    nd = ctypes.c_int()
    assert lib.LGBM_TrainDatasetGetNumData(sd, ctypes.byref(nd)) == 0
    assert nd.value == n
    _, model_stream = _train_c(lib, sd)

    ds2 = ctypes.c_void_p()
    assert lib.LGBM_TrainDatasetCreateFromMat(
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), n, f,
        b"max_bin=63 verbosity=-1", None, ctypes.byref(ds2)) == 0
    assert lib.LGBM_TrainDatasetSetField(
        ds2, b"label", y.ctypes.data_as(ctypes.c_void_p), n, 0) == 0
    # construct both datasets at the same phase (GetNumData) so train-time
    # feature pre-filtering can't differ between the two paths
    assert lib.LGBM_TrainDatasetGetNumData(ds2, ctypes.byref(nd)) == 0
    _, model_dense = _train_c(lib, ds2)
    assert model_stream.split("\n\n")[1] == model_dense.split("\n\n")[1]


def test_booster_getters_and_reset_parameter():
    lib = _lib()
    x, y = _data(n=600, f=5, seed=6)
    ds = ctypes.c_void_p()
    assert lib.LGBM_TrainDatasetCreateFromMat(
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), x.shape[0],
        x.shape[1], b"max_bin=63 verbosity=-1", None, ctypes.byref(ds)) == 0
    assert lib.LGBM_TrainDatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p), len(y), 0) == 0
    bst, _ = _train_c(lib, ds, rounds=3)

    nf = ctypes.c_int()
    assert lib.LGBM_TrainBoosterGetNumFeature(bst, ctypes.byref(nf)) == 0
    assert nf.value == 5

    names = ctypes.c_char_p()
    assert lib.LGBM_TrainBoosterGetEvalNames(bst, ctypes.byref(names)) == 0
    assert b"binary_logloss" in names.value

    imp = np.zeros(5, np.float64)
    out_n = ctypes.c_int()
    rc = lib.LGBM_TrainBoosterFeatureImportance(
        bst, 0, ctypes.c_int64(5),
        imp.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ctypes.byref(out_n))
    assert rc == 0, lib.LGBM_TrainGetLastError()
    assert out_n.value == 5 and imp.sum() > 0

    # learning-rate reset applies to FUTURE trees only
    assert lib.LGBM_TrainBoosterResetParameter(
        bst, b"learning_rate=0.77") == 0
    fin = ctypes.c_int()
    assert lib.LGBM_TrainBoosterUpdateOneIter(bst, ctypes.byref(fin)) == 0
    s = ctypes.c_char_p()
    assert lib.LGBM_TrainBoosterSaveModelToString(bst, 0, -1,
                                                  ctypes.byref(s)) == 0
    txt = s.value.decode()
    assert "shrinkage=0.77" in txt and "shrinkage=0.1" in txt
    # structural params are refused, with the error reported through
    # LGBM_TrainGetLastError
    assert lib.LGBM_TrainBoosterResetParameter(bst, b"num_leaves=63") == -1
    assert b"num_leaves" in lib.LGBM_TrainGetLastError()


def test_network_init_validation():
    lib = _lib()
    # bad machine-count mismatch surfaces as an error, not a crash
    rc = lib.LGBM_TrainNetworkInit(b"127.0.0.1:9999", 9999, 120, 3)
    assert rc == -1
    assert b"3" in lib.LGBM_TrainGetLastError()
    # single machine is a no-op success (reference behavior)
    assert lib.LGBM_TrainNetworkInit(b"", 12400, 120, 1) == 0
    assert lib.LGBM_TrainNetworkFree() == 0


def test_dump_refit_binary_and_feature_names(tmp_path):
    lib = _lib()
    x, y = _data(n=500, f=4, seed=7)
    ds = ctypes.c_void_p()
    assert lib.LGBM_TrainDatasetCreateFromMat(
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), x.shape[0],
        x.shape[1], b"max_bin=63 verbosity=-1", None, ctypes.byref(ds)) == 0
    assert lib.LGBM_TrainDatasetSetFeatureNames(
        ds, b"alpha\tbeta\tgamma\tdelta") == 0
    names = ctypes.c_char_p()
    assert lib.LGBM_TrainDatasetGetFeatureNames(ds, ctypes.byref(names)) == 0
    assert names.value == b"alpha\tbeta\tgamma\tdelta"
    assert lib.LGBM_TrainDatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p), len(y), 0) == 0

    bst, model_str = _train_c(lib, ds, rounds=5)
    assert "alpha" in model_str

    # JSON dump parses and carries the trees
    js = ctypes.c_char_p()
    assert lib.LGBM_TrainBoosterDumpModel(bst, 0, -1, ctypes.byref(js)) == 0
    import json as _json
    dump = _json.loads(js.value.decode())
    assert dump["num_tree_per_iteration"] == 1
    assert len(dump["tree_info"]) == 5
    assert dump["feature_names"][0] == "alpha"

    # refit on perturbed data returns a working new booster
    x2 = np.ascontiguousarray(x + 0.01)
    y2 = y.astype(np.float32)
    b2 = ctypes.c_void_p()
    rc = lib.LGBM_TrainBoosterRefit(
        bst, x2.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        x2.shape[0], x2.shape[1], y2.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_double(0.9), ctypes.byref(b2))
    assert rc == 0, lib.LGBM_TrainGetLastError()
    out = np.zeros(x.shape[0], np.float64)
    out_len = ctypes.c_int64()
    # out_capacity is a BY-VALUE int64_t past the register args — see the
    # marshalling note in test_inprocess_train_lifecycle
    assert lib.LGBM_TrainBoosterPredictForMat(
        b2, x2.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), x2.shape[0],
        x2.shape[1], 0, 0, -1, ctypes.c_int64(len(out)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ctypes.byref(out_len)) == 0
    acc = ((out > 0.5) == y2).mean()
    assert acc > 0.85, acc

    # binary dataset cache from C loads back in Python
    binpath = str(tmp_path / "c.ds.bin").encode()
    assert lib.LGBM_TrainDatasetSaveBinary(ds, binpath) == 0
    ds2 = lgb.Dataset.load_binary(binpath.decode())
    assert ds2.num_data == 500
    lib.LGBM_TrainBoosterFree(bst)
    lib.LGBM_TrainBoosterFree(b2)
    lib.LGBM_TrainDatasetFree(ds)


def test_get_field_roundtrip():
    lib = _lib()
    x, y = _data(n=300, f=4, seed=8)
    w = np.abs(np.random.RandomState(8).randn(300)).astype(np.float32)
    ds = ctypes.c_void_p()
    assert lib.LGBM_TrainDatasetCreateFromMat(
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), 300, 4,
        b"max_bin=31 verbosity=-1", None, ctypes.byref(ds)) == 0
    assert lib.LGBM_TrainDatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p), 300, 0) == 0
    assert lib.LGBM_TrainDatasetSetField(
        ds, b"weight", w.ctypes.data_as(ctypes.c_void_p), 300, 0) == 0

    out_len = ctypes.c_int()
    out_ptr = ctypes.c_void_p()
    out_type = ctypes.c_int()
    rc = lib.LGBM_TrainDatasetGetField(
        ds, b"label", ctypes.byref(out_len), ctypes.byref(out_ptr),
        ctypes.byref(out_type))
    assert rc == 0, lib.LGBM_TrainGetLastError()
    assert out_len.value == 300 and out_type.value == 0
    got = np.ctypeslib.as_array(
        ctypes.cast(out_ptr, ctypes.POINTER(ctypes.c_float)), (300,))
    np.testing.assert_array_equal(got, y)

    assert lib.LGBM_TrainDatasetGetField(
        ds, b"weight", ctypes.byref(out_len), ctypes.byref(out_ptr),
        ctypes.byref(out_type)) == 0
    got_w = np.ctypeslib.as_array(
        ctypes.cast(out_ptr, ctypes.POINTER(ctypes.c_float)), (300,))
    np.testing.assert_array_equal(got_w, w)

    # unset field -> length 0 with a VALID dtype code (reference behavior)
    assert lib.LGBM_TrainDatasetGetField(
        ds, b"init_score", ctypes.byref(out_len), ctypes.byref(out_ptr),
        ctypes.byref(out_type)) == 0
    assert out_len.value == 0 and out_type.value == 1
    # unknown field -> error
    assert lib.LGBM_TrainDatasetGetField(
        ds, b"nonsense", ctypes.byref(out_len), ctypes.byref(out_ptr),
        ctypes.byref(out_type)) == -1
    lib.LGBM_TrainDatasetFree(ds)
