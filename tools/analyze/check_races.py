"""Lock-discipline race lint for the threaded serve/continual stack.

The reference C++ LightGBM leans on compile-time types and yamc
rwlocks for its thread-safety story; this Python/JAX rebuild has
neither, yet PRs 5-9 grew a genuinely concurrent production surface —
the batcher worker thread, hot-swap registry with in-flight counters,
circuit breaker, drain, the continual shadow-probe thread — where a
single unguarded field read is a silent corruption bug no tier-1 test
deterministically catches.  This lint keeps the lock discipline true
STRUCTURALLY, in the check_syncs/check_retraces mold:

For each threaded module (``THREADED_MODULES``, plus any module whose
classes own a ``threading.Lock``/``RLock``/``Condition``), per class:

1. **Guard-map inference.**  A ``self._x`` attribute WRITTEN inside a
   ``with self._lock:`` block (in any non-``__init__`` method,
   including private helpers only ever called with the lock held —
   call contexts propagate through same-class calls) is *guarded by*
   that lock.  Class docstrings can pin or disambiguate the map with
   lock-contract annotations::

       Lock contract (tools/analyze/check_races.py):
           _lock guards: _queue, _depth_rows
           breaker type: lightgbm_tpu/serve/breaker.py:ServeBreaker

   A ``guards:`` line declares attributes guarded even where inference
   alone is ambiguous; a ``type:`` line names the class behind an
   attribute so cross-object lock acquisitions feed the lock-order
   graph.  Contract lines that match nothing are STALE and fail the
   lint, like every pin in the family.
2. **Findings.**  (a) any read/write of a guarded attribute on a code
   path that does not hold its lock; (b) attributes mutated from more
   than one method with no lock at all (multi-writer, zero guards);
   (c) lock-acquisition-order cycles across classes/modules (static
   deadlock detection over the nested-``with`` + cross-object call
   graph; a non-reentrant lock re-acquired on one path is a self-cycle).
3. **Allowlist.**  Intentional lock-free accesses are pinned in
   ``tools/race_allowlist.txt`` as
   ``path | Class.method | attribute | rationale`` (rationale
   MANDATORY; ``Class`` alone pins a multi-writer finding).  Stale
   entries are errors.

Construction (``__init__`` and everything it calls) is exempt:
publication of ``self`` happens-after construction.  The analysis is
deliberately first-order — ``self.attr`` accesses only; aliasing
through locals and foreign objects is out of scope (the lint is a
discipline gate, not a verifier).

Run via ``python tools/lint.py`` (tier-1), or standalone
(``python tools/analyze/check_races.py``; exit 1 on findings).
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

if __package__:
    from . import lintlib
else:                                        # standalone execution
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import lintlib

REPO = lintlib.REPO
PACKAGE = lintlib.PACKAGE
ALLOWLIST = os.path.join(REPO, "tools", "race_allowlist.txt")

# the threaded production surface (paths inside the package root);
# modules that own locks are pulled in automatically on top
THREADED_MODULES = (
    "serve/batcher.py",
    "serve/registry.py",
    "serve/server.py",
    "serve/engine.py",
    "serve/breaker.py",
    "pipeline/continual.py",
    "utils/resilience.py",
)

# container methods that mutate their receiver: self._q.append(x) is a
# WRITE to the structure _q names, not just a read of the reference
_MUTATORS = {"append", "appendleft", "extend", "insert", "pop",
             "popleft", "popitem", "remove", "clear", "add", "discard",
             "update", "setdefault", "sort", "reverse"}

_LOCK_FACTORIES = {"Lock", "RLock"}

_GUARDS_RE = re.compile(r"^\s*(\w+) guards:\s*(.+?)\s*$")
_TYPE_RE = re.compile(r"^\s*(\w+) type:\s*(\S+?):(\w+)\s*$")

Held = FrozenSet[str]


class _Access:
    __slots__ = ("attr", "kind", "held", "lineno", "method")

    def __init__(self, attr: str, kind: str, held: Held, lineno: int,
                 method: str):
        self.attr, self.kind, self.held = attr, kind, held
        self.lineno, self.method = lineno, method


class _Method:
    def __init__(self, name: str):
        self.name = name
        self.accesses: List[_Access] = []
        # (callee method name, held at call, lineno)
        self.self_calls: List[Tuple[str, Held, int]] = []
        # (self-attr the call goes through, callee name, held, lineno)
        self.foreign_calls: List[Tuple[str, str, Held, int]] = []
        # direct `with self.<lock>` acquisitions: (lock, held before)
        self.acquisitions: List[Tuple[str, Held, int]] = []
        self.escapes = False     # referenced without a call (callback)


class _Class:
    def __init__(self, rel: str, name: str):
        self.rel, self.name = rel, name
        self.locks: Dict[str, str] = {}      # lock attr -> "lock"|"rlock"
        self.alias: Dict[str, str] = {}      # Condition attr -> lock attr
        self.methods: Dict[str, _Method] = {}
        self.properties: Set[str] = set()
        self.decl_guards: Dict[str, str] = {}        # attr -> lock
        self.attr_types: Dict[str, Tuple[str, str]] = {}
        self.decl_lines: Dict[str, int] = {}

    def lock_of(self, attr: str) -> Optional[str]:
        attr = self.alias.get(attr, attr)
        return attr if attr in self.locks else None


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _lock_factory(call: ast.AST) -> Optional[str]:
    """'lock'/'rlock'/'condition' when ``call`` constructs one."""
    if not isinstance(call, ast.Call):
        return None
    f = call.func
    name = f.attr if isinstance(f, ast.Attribute) else \
        (f.id if isinstance(f, ast.Name) else None)
    if name in _LOCK_FACTORIES:
        return "rlock" if name == "RLock" else "lock"
    if name == "Condition":
        return "condition"
    return None


# ---------------------------------------------------------------------------
# per-method AST walk
# ---------------------------------------------------------------------------

class _MethodWalker:
    """Walks one method body tracking the held-lock set through
    ``with self.<lock>:`` blocks, recording every ``self.<attr>``
    access, same-class call, and cross-object call."""

    def __init__(self, cls: _Class, minfo: _Method):
        self.cls, self.m = cls, minfo

    def walk_body(self, body, held: Held) -> None:
        for stmt in body:
            self.walk(stmt, held)

    def walk(self, node: ast.AST, held: Held) -> None:
        cls, m = self.cls, self.m
        if isinstance(node, (ast.With, ast.AsyncWith)):
            newheld = set(held)
            for item in node.items:
                a = _self_attr(item.context_expr)
                lk = cls.lock_of(a) if a else None
                if lk is not None:
                    m.acquisitions.append((lk, held, node.lineno))
                    newheld.add(lk)
                else:
                    self.walk(item.context_expr, held)
            self.walk_body(node.body, frozenset(newheld))
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested function/closure: runs on the same thread, in the
            # enclosing method's protocol — attribute its accesses here
            # with the held set at the definition site (the common
            # define-then-run-synchronously pattern; a closure handed
            # to another THREAD is exactly what the lint should flag)
            for d in node.decorator_list:
                self.walk(d, held)
            self.walk_body(node.body, held)
            return
        if isinstance(node, ast.Lambda):
            self.walk(node.body, held)
            return
        if isinstance(node, ast.Call):
            f = node.func
            a = _self_attr(f)
            if a is not None:
                if cls.lock_of(a) is not None:
                    pass           # lock-object call (.acquire handled
                    #                conservatively as opaque)
                elif a in cls.methods:
                    m.self_calls.append((a, held, node.lineno))
                else:
                    # calling a stored callable: a read of the attr
                    m.accesses.append(_Access(a, "read", held,
                                              node.lineno, m.name))
            elif isinstance(f, ast.Attribute):
                base_attr = _self_attr(f.value)
                if base_attr is not None:
                    if cls.lock_of(base_attr) is not None:
                        pass       # condition/lock method: wait/notify
                    else:
                        m.accesses.append(_Access(
                            base_attr, "read", held, node.lineno,
                            m.name))
                        if f.attr in _MUTATORS:
                            m.accesses.append(_Access(
                                base_attr, "mutate", held, node.lineno,
                                m.name))
                        m.foreign_calls.append(
                            (base_attr, f.attr, held, node.lineno))
                else:
                    self.walk(f, held)
            else:
                self.walk(f, held)
            for arg in node.args:
                self.walk(arg, held)
            for kw in node.keywords:
                self.walk(kw.value, held)
            return
        if isinstance(node, ast.AugAssign):
            a = _self_attr(node.target)
            if a is not None and cls.lock_of(a) is None:
                m.accesses.append(_Access(a, "read", held,
                                          node.lineno, m.name))
                m.accesses.append(_Access(a, "write", held,
                                          node.lineno, m.name))
            else:
                self.walk(node.target, held)
            self.walk(node.value, held)
            return
        if isinstance(node, ast.Subscript):
            a = _self_attr(node.value)
            if a is not None and cls.lock_of(a) is None:
                kind = "read" if isinstance(node.ctx, ast.Load) \
                    else "mutate"
                m.accesses.append(_Access(a, "read", held,
                                          node.lineno, m.name))
                if kind == "mutate":
                    m.accesses.append(_Access(a, "mutate", held,
                                              node.lineno, m.name))
            else:
                self.walk(node.value, held)
            self.walk(node.slice, held)
            return
        if isinstance(node, ast.Attribute):
            a = _self_attr(node)
            if a is not None:
                if cls.lock_of(a) is not None:
                    return
                if a in cls.methods:
                    if a in cls.properties:
                        # property access executes the getter inline
                        m.self_calls.append((a, held, node.lineno))
                    elif isinstance(node.ctx, ast.Load):
                        # bound method escaping (thread target,
                        # callback): the callee must assume NO lock
                        cls.methods[a].escapes = True
                    return
                kind = "read" if isinstance(node.ctx, ast.Load) \
                    else "write"
                m.accesses.append(_Access(a, kind, held, node.lineno,
                                          m.name))
                return
            self.walk(node.value, held)
            return
        for child in ast.iter_child_nodes(node):
            self.walk(child, held)


# ---------------------------------------------------------------------------
# module / class harvesting
# ---------------------------------------------------------------------------

def _parse_contract(cls: _Class, doc: Optional[str],
                    lineno: int) -> List[str]:
    """Lock-contract annotations from the class docstring; returns
    malformed-directive findings."""
    findings: List[str] = []
    if not doc:
        return findings
    for line in doc.splitlines():
        mg = _GUARDS_RE.match(line)
        if mg:
            lock, attrs = mg.group(1), mg.group(2)
            if cls.lock_of(lock) is None:
                findings.append(
                    f"{cls.rel}:{lineno}: {cls.name}: lock contract "
                    f"names unknown lock '{lock}' (class owns: "
                    f"{sorted(cls.locks) or 'none'})")
                continue
            for attr in [a.strip() for a in attrs.split(",")]:
                if attr:
                    cls.decl_guards[attr] = cls.lock_of(lock)
                    cls.decl_lines[attr] = lineno
            continue
        mt = _TYPE_RE.match(line)
        if mt:
            cls.attr_types[mt.group(1)] = (mt.group(2), mt.group(3))
    return findings


def harvest(root: str) -> Tuple[Dict[Tuple[str, str], _Class],
                                List[str]]:
    """Parse every module under ``root``; returns
    ``{(rel, classname): _Class}`` plus parse/contract findings."""
    classes: Dict[Tuple[str, str], _Class] = {}
    findings: List[str] = []
    for path in lintlib.iter_py(root):
        rel = lintlib.rel_to_root(path, root)
        try:
            with open(path, "rb") as f:
                tree = ast.parse(f.read(), filename=path)
        except SyntaxError as e:
            findings.append(f"{rel}: unparseable ({e})")
            continue
        for node in tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            cls = _Class(rel, node.name)
            # pass 1: locks, aliases, method inventory
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    cls.methods[sub.name] = _Method(sub.name)
                    for d in sub.decorator_list:
                        dn = d.id if isinstance(d, ast.Name) else (
                            d.attr if isinstance(d, ast.Attribute)
                            else None)
                        if dn in ("property", "cached_property",
                                  "setter", "getter"):
                            cls.properties.add(sub.name)
            for fn in [s for s in node.body
                       if isinstance(s, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]:
                for stmt in ast.walk(fn):
                    if not isinstance(stmt, ast.Assign):
                        continue
                    kind = _lock_factory(stmt.value)
                    if kind is None:
                        continue
                    for tgt in stmt.targets:
                        a = _self_attr(tgt)
                        if a is None:
                            continue
                        if kind == "condition":
                            arg = stmt.value.args[0] \
                                if stmt.value.args else None
                            wrapped = _self_attr(arg) \
                                if arg is not None else None
                            if wrapped:
                                cls.alias[a] = wrapped
                            else:
                                cls.locks[a] = "lock"
                        else:
                            cls.locks[a] = kind
            findings.extend(_parse_contract(cls, ast.get_docstring(node),
                                            node.lineno))
            # pass 2: walk method bodies
            for fn in [s for s in node.body
                       if isinstance(s, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]:
                if fn.name == "__init__":
                    continue     # construction happens-before publish
                _MethodWalker(cls, cls.methods[fn.name]) \
                    .walk_body(fn.body, frozenset())
            classes[(rel, node.name)] = cls
    return classes, findings


# ---------------------------------------------------------------------------
# call-context propagation (so `_trip_locked`-style helpers inherit the
# caller's held set instead of being flagged as unguarded)
# ---------------------------------------------------------------------------

def _entry_contexts(cls: _Class) -> Dict[str, Set[Held]]:
    ctx: Dict[str, Set[Held]] = {m: set() for m in cls.methods}
    internal_callees = {c for m in cls.methods.values()
                        for (c, _h, _l) in m.self_calls}
    for name, m in cls.methods.items():
        public = not name.startswith("_") or (
            name.startswith("__") and name.endswith("__"))
        if public or m.escapes or name not in internal_callees:
            ctx[name].add(frozenset())
    for _ in range(len(cls.methods) + 2):       # fixed point (held sets
        changed = False                          # only grow)
        for name, m in cls.methods.items():
            for callee, held, _ln in m.self_calls:
                for c in ctx[name]:
                    nc = c | held
                    if nc not in ctx[callee]:
                        ctx[callee].add(nc)
                        changed = True
        if not changed:
            break
    for name in ctx:                             # dead private methods
        if not ctx[name]:
            ctx[name].add(frozenset())
    return ctx


def _effective(cls: _Class) -> List[_Access]:
    """Accesses with call contexts folded in: one access per
    (site, entry context)."""
    ctx = _entry_contexts(cls)
    out: List[_Access] = []
    for name, m in cls.methods.items():
        for acc in m.accesses:
            for c in ctx[name]:
                out.append(_Access(acc.attr, acc.kind, acc.held | c,
                                   acc.lineno, name))
    return out


# ---------------------------------------------------------------------------
# lock-order graph (static deadlock detection)
# ---------------------------------------------------------------------------

def _lock_events(classes: Dict[Tuple[str, str], _Class]
                 ) -> Dict[Tuple[str, str, str],
                           Set[Tuple[Tuple[str, str, str], Held]]]:
    """Per (rel, Class, method): the set of lock-acquisition events
    ``(lock node, frozenset of SAME-CLASS locks held when acquiring)``
    reachable from it — own ``with`` blocks plus same-class and typed
    cross-object calls, to a fixed point."""
    events: Dict[Tuple[str, str, str],
                 Set[Tuple[Tuple[str, str, str], Held]]] = {}
    for (rel, cname), cls in classes.items():
        for mname, m in cls.methods.items():
            ev = set()
            for lk, held, _ln in m.acquisitions:
                ev.add(((rel, cname, lk), held))
            events[(rel, cname, mname)] = ev

    def _callee_keys(cls: _Class, m: _Method):
        for callee, held, _ln in m.self_calls:
            yield (cls.rel, cls.name, callee), held
        for attr, callee, held, _ln in m.foreign_calls:
            tgt = cls.attr_types.get(attr)
            if tgt and (tgt[0], tgt[1]) in classes:
                tcls = classes[(tgt[0], tgt[1])]
                if callee in tcls.methods:
                    yield (tgt[0], tgt[1], callee), held

    for _ in range(len(events) + 2):
        changed = False
        for (rel, cname), cls in classes.items():
            for mname, m in cls.methods.items():
                key = (rel, cname, mname)
                for ckey, held in _callee_keys(cls, m):
                    for node, _h in events.get(ckey, ()):
                        item = (node, held)
                        if item not in events[key]:
                            events[key].add(item)
                            changed = True
        if not changed:
            break
    return events


def lock_order_findings(classes: Dict[Tuple[str, str], _Class]
                        ) -> List[str]:
    events = _lock_events(classes)
    edges: Dict[Tuple[str, str, str], Set[Tuple[str, str, str]]] = {}
    for (rel, cname, _m), evs in events.items():
        cls = classes[(rel, cname)]
        for node, held in evs:
            for h in held:
                src = (rel, cname, h)
                if src == node and cls.locks.get(h) == "rlock":
                    continue               # reentrant: self-edge fine
                edges.setdefault(src, set()).add(node)
    findings: List[str] = []

    def fmt(n):
        return f"{n[0]}:{n[1]}.{n[2]}"

    # self-loops: a non-reentrant lock re-acquired while held
    for src, dsts in sorted(edges.items()):
        if src in dsts:
            findings.append(
                f"lock-order: non-reentrant lock {fmt(src)} acquired "
                "while already held (self-deadlock)")
    # cycles across locks: recursive coloring DFS (lock graphs are tiny)
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in
             set(edges) | {d for ds in edges.values() for d in ds}}
    seen_cycles: Set[Tuple] = set()

    def dfs(n, path):
        color[n] = GRAY
        for nxt in sorted(edges.get(n, ())):
            if nxt == n:
                continue
            if color.get(nxt, WHITE) == GRAY:
                cyc = path[path.index(nxt):] + [nxt]
                key = frozenset(cyc)
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    findings.append(
                        "lock-order cycle (potential deadlock): "
                        + " -> ".join(fmt(c) for c in cyc))
            elif color.get(nxt, WHITE) == WHITE:
                dfs(nxt, path + [nxt])
        color[n] = BLACK

    for n in sorted(color):
        if color[n] == WHITE:
            dfs(n, [n])
    return findings


# ---------------------------------------------------------------------------
# the lint
# ---------------------------------------------------------------------------

def run(root: str = PACKAGE, allowlist_path: str = ALLOWLIST,
        modules: Optional[List[str]] = None) -> List[str]:
    """The full race lint; returns findings (empty = green)."""
    classes, findings = harvest(root)
    allow = lintlib.load_pin_keys(allowlist_path)
    used: Set[Tuple[str, str, str]] = set()
    threaded = set(modules if modules is not None else THREADED_MODULES)
    pkg = os.path.basename(os.path.abspath(root))
    report_rels = {f"{pkg}/{m}" for m in threaded} | {
        rel for (rel, _c), cls in classes.items() if cls.locks}

    def pinned(rel: str, scope: str, attr: str) -> bool:
        key = (rel, scope, attr)
        if key in allow:
            used.add(key)
            return True
        return False

    for (rel, cname), cls in sorted(classes.items()):
        if rel not in report_rels:
            continue
        eff = _effective(cls)
        by_attr: Dict[str, List[_Access]] = {}
        for acc in eff:
            by_attr.setdefault(acc.attr, []).append(acc)
        # stale lock-contract guards: a declared attr no method touches
        for attr, lk in sorted(cls.decl_guards.items()):
            if attr not in by_attr:
                findings.append(
                    f"{rel}:{cls.decl_lines.get(attr, 0)}: {cname}: "
                    f"stale lock contract — '{attr}' (declared guarded "
                    f"by '{lk}') is never accessed")
        # stale type lines: an unresolvable target (or an attribute no
        # method touches) silently DROPS edges from the deadlock graph,
        # so contract rot here must fail the lint like everywhere else
        for attr, tgt in sorted(cls.attr_types.items()):
            if (tgt[0], tgt[1]) not in classes:
                findings.append(
                    f"{rel}: {cname}: stale lock contract — "
                    f"'{attr} type: {tgt[0]}:{tgt[1]}' resolves to no "
                    "analyzed class (renamed/moved?); its lock-order "
                    "edges are lost")
            elif attr not in by_attr:
                findings.append(
                    f"{rel}: {cname}: stale lock contract — typed "
                    f"attribute '{attr}' is never accessed")
        for attr, accs in sorted(by_attr.items()):
            # guard inference: any lock held across a write establishes
            # a guard candidate
            inferred: Set[str] = set()
            for acc in accs:
                if acc.kind in ("write", "mutate"):
                    inferred |= acc.held
            declared = cls.decl_guards.get(attr)
            if declared is not None:
                guard: Optional[str] = declared
            elif len(inferred) == 1:
                guard = next(iter(inferred))
            elif len(inferred) > 1:
                if not pinned(rel, cname, attr):
                    findings.append(
                        f"{rel}: {cname}: ambiguous guard for "
                        f"'{attr}' — written under "
                        f"{sorted(inferred)}; disambiguate with a "
                        f"lock-contract 'X guards: {attr}' line")
                continue
            else:
                guard = None
            if guard is not None:
                # rule (a): every access must hold the guard
                for acc in accs:
                    if guard in acc.held:
                        continue
                    scope = f"{cname}.{acc.method}"
                    if pinned(rel, scope, attr):
                        continue
                    findings.append(
                        f"{rel}:{acc.lineno}: {scope}: {acc.kind} of "
                        f"'{attr}' outside its guard 'self.{guard}'")
            else:
                # rule (b): unguarded multi-writer
                writers = {acc.method for acc in accs
                           if acc.kind in ("write", "mutate")}
                if len(writers) > 1 and not pinned(rel, cname, attr):
                    sites = sorted(
                        {f"{acc.method}:{acc.lineno}" for acc in accs
                         if acc.kind in ("write", "mutate")})
                    findings.append(
                        f"{rel}: {cname}: '{attr}' mutated from "
                        f"{len(writers)} methods with no lock "
                        f"({', '.join(sites)})")
    findings.extend(lock_order_findings(
        {k: v for k, v in classes.items() if k[0] in report_rels
         or v.locks or v.attr_types}))
    findings.extend(lintlib.stale_pins(allow, used, "race allowlist"))
    return findings


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=PACKAGE)
    ap.add_argument("--allowlist", default=ALLOWLIST)
    args = ap.parse_args(argv)
    findings = run(args.root, args.allowlist)
    if findings:
        print("race lint: lock-discipline violations:", file=sys.stderr)
        for f in findings:
            print(f"  {f}", file=sys.stderr)
        print(f"\n{len(findings)} finding(s).  Take the lock, declare "
              "the contract in the class docstring, or pin an "
              "intentional lock-free access in tools/race_allowlist.txt "
              "(rationale required)", file=sys.stderr)
        return 1
    print("race lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
