"""CEGB + forced-splits tests (test_engine.py forced_splits / cegb analog)."""

import json

import numpy as np
import pytest

import lightgbm_tpu as lgb


class TestCEGB:
    def test_coupled_penalty_discourages_feature(self, binary_data):
        x, y = binary_data
        base = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
                "min_data_in_leaf": 5}
        bst0 = lgb.train(base, lgb.Dataset(x, label=y), num_boost_round=10)
        imp0 = bst0.feature_importance("split")
        top = int(np.argmax(imp0))
        # huge coupled penalty on the top feature bans it
        penalties = [0.0] * x.shape[1]
        penalties[top] = 1e9
        p = dict(base, cegb_tradeoff=1.0,
                 cegb_penalty_feature_coupled=penalties)
        bst1 = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=10)
        imp1 = bst1.feature_importance("split")
        assert imp1[top] == 0

    def test_split_penalty_prunes(self, binary_data):
        x, y = binary_data
        p = {"objective": "binary", "num_leaves": 31, "max_bin": 63,
             "min_data_in_leaf": 5, "cegb_tradeoff": 1.0,
             "cegb_penalty_split": 1e9}
        bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=3)
        # penalty so large no split is worth it -> stump trees
        assert all(t.num_leaves == 1 for t in bst.trees)


class TestForcedSplits:
    def test_forced_top(self, binary_data, tmp_path):
        x, y = binary_data
        forced = {"feature": 5, "threshold": 0.0,
                  "left": {"feature": 6, "threshold": 0.5}}
        path = str(tmp_path / "forced.json")
        with open(path, "w") as f:
            json.dump(forced, f)
        p = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
             "min_data_in_leaf": 5, "forcedsplits_filename": path}
        bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=5)
        for t in bst.trees:
            assert t.split_feature[0] == 5          # forced root
            # node 1 (left child of root) forced to feature 6
            if t.num_nodes() > 1 and t.left_child[0] == 1:
                assert t.split_feature[1] == 6
        from lightgbm_tpu.metrics import _auc
        assert _auc(y, bst.predict(x, raw_score=True), None) > 0.9
