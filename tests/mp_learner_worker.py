"""Worker for the multi-process feature-/voting-parallel topology tests
(tests/test_multiprocess.py::test_two_process_{feature,voting}_parallel).

The reference runs ALL THREE distributed learners across machines
(tree_learner.cpp:16-64 dispatches data/feature/voting x socket/mpi); the
round-4 verdict flagged that this framework only proved tree_learner=data
on real processes.  This worker closes the matrix: each process joins a
2-process gloo pod and trains with tree_learner=feature (data REPLICATED
per process, split search sharded over features) or tree_learner=voting
(rows sharded, vote-compressed histogram reduction), then rank 0 dumps
the trees.  The host test trains single-controller on a 2-device mesh
with identical data/mappers and requires tree-for-tree equality — the
topology-invariance contract (2 processes x 1 device == 1 process x 2
devices) that the reference checks with localhost-socket workers
(tests/distributed/_test_distributed.py:79-100).

Bin mappers are fitted on the FULL global data identically on every
process so any divergence is attributable to the learner, not binning.
"""

import json
import os
import sys


def main():
    rank = int(sys.argv[1])
    nproc = int(sys.argv[2])
    port = sys.argv[3]
    out = sys.argv[4]
    learner = sys.argv[5]

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from lightgbm_tpu.utils.compile_cache import enable_persistent_cache
    enable_persistent_cache()   # pods re-pay every compile without it
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from lightgbm_tpu.parallel import launch

    launch.init(coordinator_address=f"127.0.0.1:{port}",
                num_processes=nproc, process_id=rank)
    assert jax.process_count() == nproc

    from lightgbm_tpu import Dataset, train
    from tests_goss_shared import tree_records
    from mp_learner_shared import PARAMS, ROUNDS, VARIANTS, global_data, \
        full_data_mappers

    learner, _, variant = learner.partition("+")
    x, y = global_data()
    mappers = full_data_mappers(x)
    params = dict(PARAMS, num_machines=nproc, tree_learner=learner,
                  **VARIANTS[variant])

    if learner == "feature":
        # feature-parallel replicates the data: every process holds ALL
        # rows (feature_parallel_tree_learner.cpp:13 — "data is duplicated
        # on each machine"); only the split search is sharded
        ds = Dataset(x, label=y, bin_mappers=mappers, params=params)
    else:
        shard = launch.row_shard(x, y)
        ds = Dataset(shard.x, label=shard.y, bin_mappers=mappers,
                     params=params)

    bst = train(params, ds, num_boost_round=ROUNDS)

    if rank == 0:
        with open(out, "w") as f:
            json.dump({"trees": tree_records(bst),
                       "pred_head": bst.predict(x[:256]).tolist()}, f)


if __name__ == "__main__":
    main()
