// Native training C API: LGBM-style entry points over the JAX core.
//
// The reference's C API exposes the FULL training lifecycle natively
// (include/LightGBM/c_api.h:109-1350: dataset create, booster create,
// update-one-iter, save/predict; src/c_api.cpp).  In the TPU rebuild the
// training core is a JAX/XLA program that lives in Python, so this shim
// embeds CPython (dual-mode: bootstraps an interpreter for pure-C hosts,
// joins the existing one when loaded into a Python process) and drives
// lightgbm_tpu.capi_embed.  External C/C++/FFI callers get the same
// train-from-C workflow the reference offers; inference without Python
// stays in libcapi.so.
//
// Build:
//   g++ -O2 -shared -fPIC capi_train.cpp -o libcapi_train.so \
//       $(python3-config --includes) $(python3-config --ldflags --embed)

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <functional>
#include <fstream>
#include <string>
#include <vector>

namespace {

thread_local std::string g_last_error;

int SetError(const std::string& msg) {
  g_last_error = msg;
  return -1;
}

bool g_we_initialized = false;

// Acquire the GIL, bootstrapping the interpreter for non-Python hosts.
class Gil {
 public:
  Gil() {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      g_we_initialized = true;
      // release the GIL the init gave us so PyGILState_Ensure below works
      // uniformly from any thread
      (void)PyEval_SaveThread();
    }
    state_ = PyGILState_Ensure();
  }
  ~Gil() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

// PyUnicode_AsUTF8 may return nullptr on conversion failure; constructing
// std::string from nullptr is UB, so always funnel through this.
const char* SafeUTF8(PyObject* s, const char* fallback) {
  const char* p = s ? PyUnicode_AsUTF8(s) : nullptr;
  if (!p) {
    PyErr_Clear();
    return fallback;
  }
  return p;
}

int PyError() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  std::string msg = "python error";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      msg = SafeUTF8(s, "python error (unprintable)");
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  return SetError(msg);
}

PyObject* Bridge() {  // borrowed-style cached module handle
  static PyObject* mod = nullptr;
  if (!mod) mod = PyImport_ImportModule("lightgbm_tpu.capi_embed");
  return mod;
}

// vectorcall into the bridge; returns new ref or nullptr (error set)
PyObject* Call(const char* fn, PyObject* args) {
  PyObject* mod = Bridge();
  if (!mod) return nullptr;
  PyObject* f = PyObject_GetAttrString(mod, fn);
  if (!f) return nullptr;
  PyObject* r = PyObject_CallObject(f, args);
  Py_DECREF(f);
  return r;
}

PyObject* View(const void* data, Py_ssize_t nbytes, bool writable = false) {
  return PyMemoryView_FromMemory(
      reinterpret_cast<char*>(const_cast<void*>(data)), nbytes,
      writable ? PyBUF_WRITE : PyBUF_READ);
}

}  // namespace

extern "C" {

typedef void* DatasetHandle;
typedef void* BoosterHandle;

const char* LGBM_TrainGetLastError() { return g_last_error.c_str(); }

int LGBM_TrainDatasetCreateFromMat(const double* data, int nrow, int ncol,
                                   const char* parameters,
                                   DatasetHandle reference,
                                   DatasetHandle* out) {
  Gil gil;
  PyObject* mv = View(data, static_cast<Py_ssize_t>(nrow) * ncol * 8);
  PyObject* ref = reference ? reinterpret_cast<PyObject*>(reference) : Py_None;
  PyObject* args = Py_BuildValue("(OiisO)", mv, nrow, ncol,
                                 parameters ? parameters : "", ref);
  Py_DECREF(mv);
  PyObject* r = Call("dataset_create_from_mat", args);
  Py_DECREF(args);
  if (!r) return PyError();
  *out = r;  // ownership transferred to the handle
  return 0;
}

int LGBM_TrainDatasetCreateFromFile(const char* filename,
                                    const char* parameters,
                                    DatasetHandle reference,
                                    DatasetHandle* out) {
  Gil gil;
  PyObject* ref = reference ? reinterpret_cast<PyObject*>(reference) : Py_None;
  PyObject* args = Py_BuildValue("(ssO)", filename,
                                 parameters ? parameters : "", ref);
  PyObject* r = Call("dataset_create_from_file", args);
  Py_DECREF(args);
  if (!r) return PyError();
  *out = r;
  return 0;
}

// CSR dataset construction (LGBM_DatasetCreateFromCSR, c_api.h:200).
// indptr is int32[nindptr]; indices int32[nelem]; data double[nelem].
int LGBM_TrainDatasetCreateFromCSR(const int32_t* indptr, int64_t nindptr,
                                   const int32_t* indices, const double* data,
                                   int64_t nelem, int64_t ncol,
                                   const char* parameters,
                                   DatasetHandle reference,
                                   DatasetHandle* out) {
  Gil gil;
  PyObject* ip = View(indptr, nindptr * 4);
  PyObject* ix = View(indices, nelem * 4);
  PyObject* dv = View(data, nelem * 8);
  PyObject* ref = reference ? reinterpret_cast<PyObject*>(reference) : Py_None;
  PyObject* args = Py_BuildValue("(OLOOLLsO)", ip, (long long)nindptr, ix, dv,
                                 (long long)nelem, (long long)ncol,
                                 parameters ? parameters : "", ref);
  Py_DECREF(ip);
  Py_DECREF(ix);
  Py_DECREF(dv);
  PyObject* r = Call("dataset_create_from_csr", args);
  Py_DECREF(args);
  if (!r) return PyError();
  *out = r;
  return 0;
}

// CSC dataset construction (LGBM_DatasetCreateFromCSC, c_api.h:268).
int LGBM_TrainDatasetCreateFromCSC(const int32_t* indptr, int64_t nindptr,
                                   const int32_t* indices, const double* data,
                                   int64_t nelem, int64_t nrow,
                                   const char* parameters,
                                   DatasetHandle reference,
                                   DatasetHandle* out) {
  Gil gil;
  PyObject* ip = View(indptr, nindptr * 4);
  PyObject* ix = View(indices, nelem * 4);
  PyObject* dv = View(data, nelem * 8);
  PyObject* ref = reference ? reinterpret_cast<PyObject*>(reference) : Py_None;
  PyObject* args = Py_BuildValue("(OLOOLLsO)", ip, (long long)nindptr, ix, dv,
                                 (long long)nelem, (long long)nrow,
                                 parameters ? parameters : "", ref);
  Py_DECREF(ip);
  Py_DECREF(ix);
  Py_DECREF(dv);
  PyObject* r = Call("dataset_create_from_csc", args);
  Py_DECREF(args);
  if (!r) return PyError();
  *out = r;
  return 0;
}

// Streaming construction (LGBM_DatasetCreateFromSampledColumn +
// LGBM_DatasetPushRows[ByCSR], c_api.h:109-313): pre-size the dataset,
// push row chunks from any producer, finalize implicitly on first use.
int LGBM_TrainDatasetCreateStreaming(int64_t nrow, int32_t ncol,
                                     const char* parameters,
                                     DatasetHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Lis)", (long long)nrow, (int)ncol,
                                 parameters ? parameters : "");
  PyObject* r = Call("dataset_create_streaming", args);
  Py_DECREF(args);
  if (!r) return PyError();
  *out = r;
  return 0;
}

int LGBM_TrainDatasetPushRows(DatasetHandle handle, const double* data,
                              int32_t nrow, int32_t ncol,
                              int32_t start_row) {
  Gil gil;
  PyObject* mv = View(data, static_cast<Py_ssize_t>(nrow) * ncol * 8);
  PyObject* args = Py_BuildValue("(OOiii)",
                                 reinterpret_cast<PyObject*>(handle), mv,
                                 (int)nrow, (int)ncol, (int)start_row);
  Py_DECREF(mv);
  PyObject* r = Call("dataset_push_rows", args);
  Py_DECREF(args);
  if (!r) return PyError();
  Py_DECREF(r);
  return 0;
}

int LGBM_TrainDatasetPushRowsByCSR(DatasetHandle handle,
                                   const int32_t* indptr, int64_t nindptr,
                                   const int32_t* indices,
                                   const double* data, int64_t nelem,
                                   int32_t start_row) {
  Gil gil;
  PyObject* ip = View(indptr, nindptr * 4);
  PyObject* ix = View(indices, nelem * 4);
  PyObject* dv = View(data, nelem * 8);
  PyObject* args = Py_BuildValue("(OOLOOLi)",
                                 reinterpret_cast<PyObject*>(handle), ip,
                                 (long long)nindptr, ix, dv,
                                 (long long)nelem, (int)start_row);
  Py_DECREF(ip);
  Py_DECREF(ix);
  Py_DECREF(dv);
  PyObject* r = Call("dataset_push_rows_by_csr", args);
  Py_DECREF(args);
  if (!r) return PyError();
  Py_DECREF(r);
  return 0;
}

// field_type: 0 float32, 1 float64, 2 int32, 3 int64 (capi_embed._NP_OF)
int LGBM_TrainDatasetSetField(DatasetHandle handle, const char* field_name,
                              const void* field_data, int num_element,
                              int field_type) {
  Gil gil;
  static const int kWidth[] = {4, 8, 4, 8};
  if (field_type < 0 || field_type > 3) return SetError("bad field_type");
  PyObject* mv = View(field_data,
                      static_cast<Py_ssize_t>(num_element) * kWidth[field_type]);
  PyObject* args = Py_BuildValue("(OsOii)",
                                 reinterpret_cast<PyObject*>(handle),
                                 field_name, mv, num_element, field_type);
  Py_DECREF(mv);
  PyObject* r = Call("dataset_set_field", args);
  Py_DECREF(args);
  if (!r) return PyError();
  Py_DECREF(r);
  return 0;
}

static int GetInt(const char* fn, PyObject* obj, int* out) {
  PyObject* args = Py_BuildValue("(O)", obj);
  PyObject* r = Call(fn, args);
  Py_DECREF(args);
  if (!r) return PyError();
  long v = PyLong_AsLong(r);
  Py_DECREF(r);
  if (v == -1 && PyErr_Occurred()) return PyError();
  *out = static_cast<int>(v);
  return 0;
}

int LGBM_TrainDatasetGetNumData(DatasetHandle handle, int* out) {
  Gil gil;
  return GetInt("dataset_num_data", reinterpret_cast<PyObject*>(handle), out);
}

int LGBM_TrainDatasetGetNumFeature(DatasetHandle handle, int* out) {
  Gil gil;
  return GetInt("dataset_num_feature", reinterpret_cast<PyObject*>(handle),
                out);
}

int LGBM_TrainDatasetFree(DatasetHandle handle) {
  Gil gil;
  Py_XDECREF(reinterpret_cast<PyObject*>(handle));
  return 0;
}

int LGBM_TrainBoosterCreate(DatasetHandle train_data, const char* parameters,
                            BoosterHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)",
                                 reinterpret_cast<PyObject*>(train_data),
                                 parameters ? parameters : "");
  PyObject* r = Call("booster_create", args);
  Py_DECREF(args);
  if (!r) return PyError();
  *out = r;
  return 0;
}

int LGBM_TrainBoosterCreateFromModelString(const char* model_str,
                                           BoosterHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", model_str);
  PyObject* r = Call("booster_create_from_model_string", args);
  Py_DECREF(args);
  if (!r) return PyError();
  *out = r;
  return 0;
}

int LGBM_TrainBoosterAddValidData(BoosterHandle handle, DatasetHandle valid,
                                  const char* name) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OOs)", reinterpret_cast<PyObject*>(handle),
                                 reinterpret_cast<PyObject*>(valid),
                                 name ? name : "valid_0");
  PyObject* r = Call("booster_add_valid", args);
  Py_DECREF(args);
  if (!r) return PyError();
  Py_DECREF(r);
  return 0;
}

int LGBM_TrainBoosterUpdateOneIter(BoosterHandle handle, int* is_finished) {
  Gil gil;
  return GetInt("booster_update", reinterpret_cast<PyObject*>(handle),
                is_finished);
}

int LGBM_TrainBoosterRollbackOneIter(BoosterHandle handle) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", reinterpret_cast<PyObject*>(handle));
  PyObject* r = Call("booster_rollback", args);
  Py_DECREF(args);
  if (!r) return PyError();
  Py_DECREF(r);
  return 0;
}

int LGBM_TrainBoosterGetCurrentIteration(BoosterHandle handle, int* out) {
  Gil gil;
  return GetInt("booster_current_iteration",
                reinterpret_cast<PyObject*>(handle), out);
}

int LGBM_TrainBoosterGetNumClasses(BoosterHandle handle, int* out) {
  Gil gil;
  return GetInt("booster_num_classes", reinterpret_cast<PyObject*>(handle),
                out);
}

// caller owns nothing: the string lives until the next call on this thread
int LGBM_TrainBoosterSaveModelToString(BoosterHandle handle,
                                       int start_iteration, int num_iteration,
                                       const char** out_str) {
  Gil gil;
  static thread_local std::string buf;
  PyObject* args = Py_BuildValue("(Oii)", reinterpret_cast<PyObject*>(handle),
                                 start_iteration, num_iteration);
  PyObject* r = Call("booster_save_model_to_string", args);
  Py_DECREF(args);
  if (!r) return PyError();
  const char* p = PyUnicode_AsUTF8(r);
  if (!p) {  // conversion failure must be an error, not an empty model
    Py_DECREF(r);
    return PyError();
  }
  buf = p;
  Py_DECREF(r);
  *out_str = buf.c_str();
  return 0;
}

int LGBM_TrainBoosterSaveModel(BoosterHandle handle, int start_iteration,
                               int num_iteration, const char* filename) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oiis)", reinterpret_cast<PyObject*>(handle),
                                 start_iteration, num_iteration, filename);
  PyObject* r = Call("booster_save_model", args);
  Py_DECREF(args);
  if (!r) return PyError();
  Py_DECREF(r);
  return 0;
}

int LGBM_TrainBoosterGetEval(BoosterHandle handle, const char** out_str) {
  Gil gil;
  static thread_local std::string buf;
  PyObject* args = Py_BuildValue("(O)", reinterpret_cast<PyObject*>(handle));
  PyObject* r = Call("booster_get_eval", args);
  Py_DECREF(args);
  if (!r) return PyError();
  const char* p = PyUnicode_AsUTF8(r);
  if (!p) {
    Py_DECREF(r);
    return PyError();
  }
  buf = p;
  Py_DECREF(r);
  *out_str = buf.c_str();
  return 0;
}

// predict_type: 0 normal, 1 raw, 2 leaf index, 3 contrib
// (C_API_PREDICT_*, c_api.h:527-535)
int LGBM_TrainBoosterPredictForMat(BoosterHandle handle, const double* data,
                                   int nrow, int ncol, int predict_type,
                                   int start_iteration, int num_iteration,
                                   int64_t out_capacity, double* out_result,
                                   int64_t* out_len) {
  Gil gil;
  PyObject* in_mv = View(data, static_cast<Py_ssize_t>(nrow) * ncol * 8);
  PyObject* out_mv = View(out_result, out_capacity * 8, /*writable=*/true);
  PyObject* args = Py_BuildValue("(OOiiiiiO)",
                                 reinterpret_cast<PyObject*>(handle), in_mv,
                                 nrow, ncol, predict_type, start_iteration,
                                 num_iteration, out_mv);
  Py_DECREF(in_mv);
  Py_DECREF(out_mv);
  PyObject* r = Call("booster_predict_mat", args);
  Py_DECREF(args);
  if (!r) return PyError();
  long long len = PyLong_AsLongLong(r);
  Py_DECREF(r);
  if (len == -1 && PyErr_Occurred()) return PyError();
  *out_len = len;
  return 0;
}

// CSR prediction (LGBM_BoosterPredictForCSR, c_api.h:815).
int LGBM_TrainBoosterPredictForCSR(BoosterHandle handle,
                                   const int32_t* indptr, int64_t nindptr,
                                   const int32_t* indices, const double* data,
                                   int64_t nelem, int64_t ncol,
                                   int predict_type, int start_iteration,
                                   int num_iteration, int64_t out_capacity,
                                   double* out_result, int64_t* out_len) {
  Gil gil;
  PyObject* ip = View(indptr, nindptr * 4);
  PyObject* ix = View(indices, nelem * 4);
  PyObject* dv = View(data, nelem * 8);
  PyObject* out_mv = View(out_result, out_capacity * 8, /*writable=*/true);
  PyObject* args = Py_BuildValue("(OOLOOLLiiiO)",
                                 reinterpret_cast<PyObject*>(handle), ip,
                                 (long long)nindptr, ix, dv,
                                 (long long)nelem, (long long)ncol,
                                 predict_type, start_iteration,
                                 num_iteration, out_mv);
  Py_DECREF(ip);
  Py_DECREF(ix);
  Py_DECREF(dv);
  Py_DECREF(out_mv);
  PyObject* r = Call("booster_predict_csr", args);
  Py_DECREF(args);
  if (!r) return PyError();
  long long len = PyLong_AsLongLong(r);
  Py_DECREF(r);
  if (len == -1 && PyErr_Occurred()) return PyError();
  *out_len = len;
  return 0;
}

int LGBM_TrainBoosterGetNumFeature(BoosterHandle handle, int* out) {
  Gil gil;
  return GetInt("booster_num_feature", reinterpret_cast<PyObject*>(handle),
                out);
}

// tab-separated metric names (LGBM_BoosterGetEvalNames analog)
int LGBM_TrainBoosterGetEvalNames(BoosterHandle handle,
                                  const char** out_str) {
  Gil gil;
  static thread_local std::string buf;
  PyObject* args = Py_BuildValue("(O)", reinterpret_cast<PyObject*>(handle));
  PyObject* r = Call("booster_get_eval_names", args);
  Py_DECREF(args);
  if (!r) return PyError();
  const char* p = PyUnicode_AsUTF8(r);
  if (!p) {
    Py_DECREF(r);
    return PyError();
  }
  buf = p;
  Py_DECREF(r);
  *out_str = buf.c_str();
  return 0;
}

// importance_type: 0 split, 1 gain (LGBM_BoosterFeatureImportance)
int LGBM_TrainBoosterFeatureImportance(BoosterHandle handle,
                                       int importance_type,
                                       int64_t out_capacity,
                                       double* out_result, int* out_len) {
  Gil gil;
  PyObject* out_mv = View(out_result, out_capacity * 8, /*writable=*/true);
  PyObject* args = Py_BuildValue("(OiO)",
                                 reinterpret_cast<PyObject*>(handle),
                                 importance_type, out_mv);
  Py_DECREF(out_mv);
  PyObject* r = Call("booster_feature_importance", args);
  Py_DECREF(args);
  if (!r) return PyError();
  long v = PyLong_AsLong(r);
  Py_DECREF(r);
  if (v == -1 && PyErr_Occurred()) return PyError();
  *out_len = static_cast<int>(v);
  return 0;
}

// JSON model dump (LGBM_BoosterDumpModel, c_api.h)
int LGBM_TrainBoosterDumpModel(BoosterHandle handle, int start_iteration,
                               int num_iteration, const char** out_str) {
  Gil gil;
  static thread_local std::string buf;
  PyObject* args = Py_BuildValue("(Oii)", reinterpret_cast<PyObject*>(handle),
                                 start_iteration, num_iteration);
  PyObject* r = Call("booster_dump_model", args);
  Py_DECREF(args);
  if (!r) return PyError();
  const char* p = PyUnicode_AsUTF8(r);
  if (!p) {
    Py_DECREF(r);
    return PyError();
  }
  buf = p;
  Py_DECREF(r);
  *out_str = buf.c_str();
  return 0;
}

// Refit existing tree structures on new data (LGBM_BoosterRefit analog;
// returns a NEW booster handle — the JAX-side refit is functional).
int LGBM_TrainBoosterRefit(BoosterHandle handle, const double* data,
                           int32_t nrow, int32_t ncol, const float* label,
                           double decay_rate, BoosterHandle* out) {
  Gil gil;
  PyObject* mv = View(data, static_cast<Py_ssize_t>(nrow) * ncol * 8);
  PyObject* lv = View(label, static_cast<Py_ssize_t>(nrow) * 4);
  PyObject* args = Py_BuildValue("(OOiiOd)",
                                 reinterpret_cast<PyObject*>(handle), mv,
                                 (int)nrow, (int)ncol, lv, decay_rate);
  Py_DECREF(mv);
  Py_DECREF(lv);
  PyObject* r = Call("booster_refit", args);
  Py_DECREF(args);
  if (!r) return PyError();
  *out = r;
  return 0;
}

// field_type out: 0 float32, 1 float64, 2 int32, 3 int64 (always a valid
// code; unset fields report length 0 with a null pointer); 'group' yields
// the query-boundaries array and multiclass init_score is class-major,
// both per reference GetField semantics.  The buffer belongs to the
// dataset handle and stays valid until the next GetField.
int LGBM_TrainDatasetGetField(DatasetHandle handle, const char* field_name,
                              int* out_len, const void** out_ptr,
                              int* out_type) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", reinterpret_cast<PyObject*>(handle),
                                 field_name);
  PyObject* r = Call("dataset_get_field", args);
  Py_DECREF(args);
  if (!r) return PyError();
  unsigned long long addr = 0;
  long long len = 0;
  int code = -1;
  if (!PyArg_ParseTuple(r, "KLi", &addr, &len, &code)) {
    Py_DECREF(r);
    return PyError();
  }
  Py_DECREF(r);
  *out_ptr = reinterpret_cast<const void*>(static_cast<uintptr_t>(addr));
  *out_len = static_cast<int>(len);
  *out_type = code;
  return 0;
}

int LGBM_TrainDatasetSaveBinary(DatasetHandle handle, const char* filename) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", reinterpret_cast<PyObject*>(handle),
                                 filename);
  PyObject* r = Call("dataset_save_binary", args);
  Py_DECREF(args);
  if (!r) return PyError();
  Py_DECREF(r);
  return 0;
}

// tab-separated names (LGBM_DatasetGetFeatureNames / SetFeatureNames)
int LGBM_TrainDatasetGetFeatureNames(DatasetHandle handle,
                                     const char** out_str) {
  Gil gil;
  static thread_local std::string buf;
  PyObject* args = Py_BuildValue("(O)", reinterpret_cast<PyObject*>(handle));
  PyObject* r = Call("dataset_get_feature_names", args);
  Py_DECREF(args);
  if (!r) return PyError();
  const char* p = PyUnicode_AsUTF8(r);
  if (!p) {
    Py_DECREF(r);
    return PyError();
  }
  buf = p;
  Py_DECREF(r);
  *out_str = buf.c_str();
  return 0;
}

int LGBM_TrainDatasetSetFeatureNames(DatasetHandle handle,
                                     const char* names) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", reinterpret_cast<PyObject*>(handle),
                                 names ? names : "");
  PyObject* r = Call("dataset_set_feature_names", args);
  Py_DECREF(args);
  if (!r) return PyError();
  Py_DECREF(r);
  return 0;
}

int LGBM_TrainBoosterResetParameter(BoosterHandle handle,
                                    const char* parameters) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", reinterpret_cast<PyObject*>(handle),
                                 parameters ? parameters : "");
  PyObject* r = Call("booster_reset_parameter", args);
  Py_DECREF(args);
  if (!r) return PyError();
  Py_DECREF(r);
  return 0;
}

// LGBM_NetworkInit (c_api.h:1350): brings up the jax.distributed runtime
// over the reference's "ip1:port1,ip2:port2" machines format; the XLA
// collectives then ride it (SURVEY.md §2.5 TPU mapping).
int LGBM_TrainNetworkInit(const char* machines, int local_listen_port,
                          int listen_time_out, int num_machines) {
  Gil gil;
  PyObject* args = Py_BuildValue("(siii)", machines ? machines : "",
                                 local_listen_port, listen_time_out,
                                 num_machines);
  PyObject* r = Call("network_init", args);
  Py_DECREF(args);
  if (!r) return PyError();
  Py_DECREF(r);
  return 0;
}

int LGBM_TrainNetworkFree() {
  Gil gil;
  PyObject* args = Py_BuildValue("()");
  PyObject* r = Call("network_free", args);
  Py_DECREF(args);
  if (!r) return PyError();
  Py_DECREF(r);
  return 0;
}

int LGBM_TrainBoosterFree(BoosterHandle handle) {
  Gil gil;
  Py_XDECREF(reinterpret_cast<PyObject*>(handle));
  return 0;
}

// ===========================================================================
// Reference-exact ABI (VERDICT r3 task 5): the LGBM_* names and prototypes
// from include/LightGBM/c_api.h, so the reference's own bindings, apps and
// tests/c_api_test/test_.py link against libcapi_train.so unmodified.
// Typed data (C_API_DTYPE_*), row/column-major, FastConfig single-row path.
// The LGBM_Train*-named exports above remain as the stable internal ABI.
// ===========================================================================

static size_t DtypeSize(int t) { return (t == 0 || t == 2) ? 4 : 8; }

static PyObject* RefOrNone(void* reference) {
  return reference ? reinterpret_cast<PyObject*>(reference) : Py_None;
}

// copy a Python str result into a (buffer_len, out_len, out_str) triple
// with the reference's truncate-and-report-needed contract
static int StrOut(PyObject* r, int64_t buffer_len, int64_t* out_len,
                  char* out_str) {
  Py_ssize_t n = 0;
  const char* s = PyUnicode_AsUTF8AndSize(r, &n);
  if (!s) return PyError();
  if (out_len) *out_len = static_cast<int64_t>(n) + 1;
  if (out_str && buffer_len > 0) {
    size_t c = static_cast<size_t>(
        n + 1 < buffer_len ? n + 1 : buffer_len);
    std::memcpy(out_str, s, c - 1);
    out_str[c - 1] = '\0';
  }
  return 0;
}

const char* LGBM_GetLastError() { return g_last_error.c_str(); }

int LGBM_DatasetCreateFromFile(const char* filename, const char* parameters,
                               const DatasetHandle reference,
                               DatasetHandle* out) {
  return LGBM_TrainDatasetCreateFromFile(
      filename, parameters, const_cast<DatasetHandle>(reference), out);
}

int LGBM_DatasetCreateFromMat(const void* data, int data_type, int32_t nrow,
                              int32_t ncol, int is_row_major,
                              const char* parameters,
                              const DatasetHandle reference,
                              DatasetHandle* out) {
  Gil gil;
  PyObject* mv = View(data, static_cast<Py_ssize_t>(nrow) * ncol
                                * DtypeSize(data_type));
  PyObject* args = Py_BuildValue("(OiiiisO)", mv, data_type, (int)nrow,
                                 (int)ncol, is_row_major,
                                 parameters ? parameters : "",
                                 RefOrNone(reference));
  Py_DECREF(mv);
  PyObject* r = Call("dataset_create_from_mat2", args);
  Py_DECREF(args);
  if (!r) return PyError();
  *out = r;
  return 0;
}

int LGBM_DatasetCreateFromCSR(const void* indptr, int indptr_type,
                              const int32_t* indices, const void* data,
                              int data_type, int64_t nindptr, int64_t nelem,
                              int64_t num_col, const char* parameters,
                              const DatasetHandle reference,
                              DatasetHandle* out) {
  Gil gil;
  PyObject* ip = View(indptr, nindptr * DtypeSize(indptr_type));
  PyObject* ix = View(indices, nelem * 4);
  PyObject* dv = View(data, nelem * DtypeSize(data_type));
  PyObject* args = Py_BuildValue(
      "(OiOOiLLLsO)", ip, indptr_type, ix, dv, data_type,
      (long long)nindptr, (long long)nelem, (long long)num_col,
      parameters ? parameters : "", RefOrNone(reference));
  Py_DECREF(ip);
  Py_DECREF(ix);
  Py_DECREF(dv);
  PyObject* r = Call("dataset_create_from_csr2", args);
  Py_DECREF(args);
  if (!r) return PyError();
  *out = r;
  return 0;
}

int LGBM_DatasetCreateFromCSC(const void* col_ptr, int col_ptr_type,
                              const int32_t* indices, const void* data,
                              int data_type, int64_t ncol_ptr, int64_t nelem,
                              int64_t num_row, const char* parameters,
                              const DatasetHandle reference,
                              DatasetHandle* out) {
  Gil gil;
  PyObject* cp = View(col_ptr, ncol_ptr * DtypeSize(col_ptr_type));
  PyObject* ix = View(indices, nelem * 4);
  PyObject* dv = View(data, nelem * DtypeSize(data_type));
  PyObject* args = Py_BuildValue(
      "(OiOOiLLLsO)", cp, col_ptr_type, ix, dv, data_type,
      (long long)ncol_ptr, (long long)nelem, (long long)num_row,
      parameters ? parameters : "", RefOrNone(reference));
  Py_DECREF(cp);
  Py_DECREF(ix);
  Py_DECREF(dv);
  PyObject* r = Call("dataset_create_from_csc2", args);
  Py_DECREF(args);
  if (!r) return PyError();
  *out = r;
  return 0;
}

int LGBM_DatasetGetNumData(DatasetHandle handle, int* out) {
  return LGBM_TrainDatasetGetNumData(handle, out);
}
int LGBM_DatasetGetNumFeature(DatasetHandle handle, int* out) {
  return LGBM_TrainDatasetGetNumFeature(handle, out);
}
int LGBM_DatasetSetField(DatasetHandle handle, const char* field_name,
                         const void* field_data, int num_element, int type) {
  return LGBM_TrainDatasetSetField(handle, field_name, field_data,
                                   num_element, type);
}
int LGBM_DatasetGetField(DatasetHandle handle, const char* field_name,
                         int* out_len, const void** out_ptr, int* out_type) {
  return LGBM_TrainDatasetGetField(handle, field_name, out_len, out_ptr,
                                   out_type);
}
int LGBM_DatasetSaveBinary(DatasetHandle handle, const char* filename) {
  return LGBM_TrainDatasetSaveBinary(handle, filename);
}
int LGBM_DatasetSetFeatureNames(DatasetHandle handle,
                                const char** feature_names, int num) {
  // reference shape: array of C strings; internal ABI: one tab-joined
  std::string joined;
  for (int i = 0; i < num; ++i) {
    if (i) joined += '\t';
    joined += feature_names[i] ? feature_names[i] : "";
  }
  return LGBM_TrainDatasetSetFeatureNames(handle, joined.c_str());
}
int LGBM_DatasetFree(DatasetHandle handle) {
  return LGBM_TrainDatasetFree(handle);
}

int LGBM_BoosterCreate(const DatasetHandle train_data,
                       const char* parameters, BoosterHandle* out) {
  return LGBM_TrainBoosterCreate(const_cast<DatasetHandle>(train_data),
                                 parameters, out);
}

int LGBM_BoosterLoadModelFromString(const char* model_str,
                                    int* out_num_iterations,
                                    BoosterHandle* out) {
  int rc = LGBM_TrainBoosterCreateFromModelString(model_str, out);
  if (rc != 0) return rc;
  if (out_num_iterations) {
    rc = LGBM_TrainBoosterGetCurrentIteration(*out, out_num_iterations);
  }
  return rc;
}

int LGBM_BoosterCreateFromModelfile(const char* filename,
                                    int* out_num_iterations,
                                    BoosterHandle* out) {
  std::ifstream in(filename);
  if (!in) return SetError(std::string("cannot open model file: ")
                           + (filename ? filename : "(null)"));
  std::string s((std::istreambuf_iterator<char>(in)),
                std::istreambuf_iterator<char>());
  return LGBM_BoosterLoadModelFromString(s.c_str(), out_num_iterations, out);
}

int LGBM_BoosterFree(BoosterHandle handle) {
  return LGBM_TrainBoosterFree(handle);
}

int LGBM_BoosterAddValidData(BoosterHandle handle,
                             const DatasetHandle valid_data) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OO)",
                                 reinterpret_cast<PyObject*>(handle),
                                 reinterpret_cast<PyObject*>(
                                     const_cast<DatasetHandle>(valid_data)));
  PyObject* r = Call("booster_add_valid_auto", args);
  Py_DECREF(args);
  if (!r) return PyError();
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterUpdateOneIter(BoosterHandle handle, int* is_finished) {
  return LGBM_TrainBoosterUpdateOneIter(handle, is_finished);
}

int LGBM_BoosterUpdateOneIterCustom(BoosterHandle handle, const float* grad,
                                    const float* hess, int* is_finished) {
  Gil gil;
  int n = 0;
  {
    PyObject* args = Py_BuildValue("(O)",
                                   reinterpret_cast<PyObject*>(handle));
    PyObject* r = Call("booster_train_num_data", args);
    Py_DECREF(args);
    if (!r) return PyError();
    n = (int)PyLong_AsLong(r);
    Py_DECREF(r);
  }
  PyObject* g = View(grad, static_cast<Py_ssize_t>(n) * 4);
  PyObject* h = View(hess, static_cast<Py_ssize_t>(n) * 4);
  PyObject* args = Py_BuildValue("(OOOi)",
                                 reinterpret_cast<PyObject*>(handle), g, h,
                                 n);
  Py_DECREF(g);
  Py_DECREF(h);
  PyObject* r = Call("booster_update_custom", args);
  Py_DECREF(args);
  if (!r) return PyError();
  if (is_finished) *is_finished = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterRollbackOneIter(BoosterHandle handle) {
  return LGBM_TrainBoosterRollbackOneIter(handle);
}
int LGBM_BoosterGetCurrentIteration(BoosterHandle handle, int* out) {
  return LGBM_TrainBoosterGetCurrentIteration(handle, out);
}
int LGBM_BoosterGetNumClasses(BoosterHandle handle, int* out) {
  return LGBM_TrainBoosterGetNumClasses(handle, out);
}
int LGBM_BoosterGetNumFeature(BoosterHandle handle, int* out) {
  return LGBM_TrainBoosterGetNumFeature(handle, out);
}
int LGBM_BoosterResetParameter(BoosterHandle handle,
                               const char* parameters) {
  return LGBM_TrainBoosterResetParameter(handle, parameters);
}

static int IntFromBridge(BoosterHandle handle, const char* fn, int* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", reinterpret_cast<PyObject*>(handle));
  PyObject* r = Call(fn, args);
  Py_DECREF(args);
  if (!r) return PyError();
  if (out) *out = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterNumModelPerIteration(BoosterHandle handle, int* out) {
  return IntFromBridge(handle, "booster_num_model_per_iteration", out);
}
int LGBM_BoosterNumberOfTotalModel(BoosterHandle handle, int* out) {
  return IntFromBridge(handle, "booster_num_total_model", out);
}
int LGBM_BoosterGetEvalCounts(BoosterHandle handle, int* out_len) {
  return IntFromBridge(handle, "booster_get_eval_counts", out_len);
}

int LGBM_BoosterGetEval(BoosterHandle handle, int data_idx, int* out_len,
                        double* out_results) {
  Gil gil;
  int counts = 0;
  if (IntFromBridge(handle, "booster_get_eval_counts", &counts) != 0)
    return -1;
  PyObject* mv = View(out_results,
                      static_cast<Py_ssize_t>(counts > 0 ? counts : 1) * 8,
                      /*writable=*/true);
  PyObject* args = Py_BuildValue("(OiO)",
                                 reinterpret_cast<PyObject*>(handle),
                                 data_idx, mv);
  Py_DECREF(mv);
  PyObject* r = Call("booster_get_eval_values", args);
  Py_DECREF(args);
  if (!r) return PyError();
  if (out_len) *out_len = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterGetEvalNames(BoosterHandle handle, const int len,
                             int* out_len, const size_t buffer_len,
                             size_t* out_buffer_len, char** out_strs) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", reinterpret_cast<PyObject*>(handle));
  PyObject* r = Call("booster_get_eval_names", args);
  Py_DECREF(args);
  if (!r) return PyError();
  const char* joined = SafeUTF8(r, "");
  std::string all(joined);
  Py_DECREF(r);
  // split the tab-joined names into the caller's string buffers
  std::vector<std::string> names;
  size_t pos = 0;
  if (!all.empty()) {
    while (true) {
      size_t t = all.find('\t', pos);
      names.push_back(all.substr(pos, t == std::string::npos
                                          ? std::string::npos : t - pos));
      if (t == std::string::npos) break;
      pos = t + 1;
    }
  }
  if (out_len) *out_len = (int)names.size();
  size_t need = 1;
  for (const auto& s : names) need = s.size() + 1 > need ? s.size() + 1 : need;
  if (out_buffer_len) *out_buffer_len = need;
  if (out_strs) {
    int n = (int)names.size() < len ? (int)names.size() : len;
    for (int i = 0; i < n; ++i) {
      if (!out_strs[i] || buffer_len == 0) continue;
      size_t c = names[i].size() + 1 < buffer_len ? names[i].size() + 1
                                                  : buffer_len;
      std::memcpy(out_strs[i], names[i].c_str(), c - 1);
      out_strs[i][c - 1] = '\0';
    }
  }
  return 0;
}

int LGBM_BoosterSaveModel(BoosterHandle handle, int start_iteration,
                          int num_iteration, int feature_importance_type,
                          const char* filename) {
  (void)feature_importance_type;  // cosmetic importance comment only
  return LGBM_TrainBoosterSaveModel(handle, start_iteration, num_iteration,
                                    filename);
}

int LGBM_BoosterSaveModelToString(BoosterHandle handle, int start_iteration,
                                  int num_iteration,
                                  int feature_importance_type,
                                  int64_t buffer_len, int64_t* out_len,
                                  char* out_str) {
  (void)feature_importance_type;
  Gil gil;
  PyObject* args = Py_BuildValue("(Oii)",
                                 reinterpret_cast<PyObject*>(handle),
                                 start_iteration, num_iteration);
  PyObject* r = Call("booster_save_model_to_string", args);
  Py_DECREF(args);
  if (!r) return PyError();
  int rc = StrOut(r, buffer_len, out_len, out_str);
  Py_DECREF(r);
  return rc;
}

int LGBM_BoosterDumpModel(BoosterHandle handle, int start_iteration,
                          int num_iteration, int feature_importance_type,
                          int64_t buffer_len, int64_t* out_len,
                          char* out_str) {
  (void)feature_importance_type;
  Gil gil;
  PyObject* args = Py_BuildValue("(Oii)",
                                 reinterpret_cast<PyObject*>(handle),
                                 start_iteration, num_iteration);
  PyObject* r = Call("booster_dump_model", args);
  Py_DECREF(args);
  if (!r) return PyError();
  int rc = StrOut(r, buffer_len, out_len, out_str);
  Py_DECREF(r);
  return rc;
}

int LGBM_BoosterFeatureImportance(BoosterHandle handle, int num_iteration,
                                  int importance_type, double* out_results) {
  (void)num_iteration;  // the Python path computes over the full model
  Gil gil;
  int nf = 0;
  if (IntFromBridge(handle, "booster_num_feature", &nf) != 0) return -1;
  PyObject* mv = View(out_results, static_cast<Py_ssize_t>(nf) * 8, true);
  PyObject* args = Py_BuildValue("(OiO)",
                                 reinterpret_cast<PyObject*>(handle),
                                 importance_type, mv);
  Py_DECREF(mv);
  PyObject* r = Call("booster_feature_importance", args);
  Py_DECREF(args);
  if (!r) return PyError();
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterPredictForMat(BoosterHandle handle, const void* data,
                              int data_type, int32_t nrow, int32_t ncol,
                              int is_row_major, int predict_type,
                              int start_iteration, int num_iteration,
                              const char* parameter, int64_t* out_len,
                              double* out_result) {
  (void)parameter;
  Gil gil;
  PyObject* mv = View(data, static_cast<Py_ssize_t>(nrow) * ncol
                                * DtypeSize(data_type));
  // the caller pre-allocated per the c_api.h contract; expose a view of
  // the worst-case contrib width so the bridge can bound-check
  int nf = 0;
  (void)IntFromBridge(handle, "booster_num_feature", &nf);
  int nc = 1;
  (void)LGBM_TrainBoosterGetNumClasses(handle, &nc);
  int64_t cap = static_cast<int64_t>(nrow) * (nf + 1) * (nc > 0 ? nc : 1);
  int iters = 0;
  (void)LGBM_TrainBoosterGetCurrentIteration(handle, &iters);
  int64_t leaf_cap = static_cast<int64_t>(nrow) * (nc > 0 ? nc : 1)
                     * (iters > 0 ? iters : 1);
  if (leaf_cap > cap) cap = leaf_cap;
  PyObject* out_mv = View(out_result, cap * 8, true);
  PyObject* args = Py_BuildValue("(OOiiiiiiiO)",
                                 reinterpret_cast<PyObject*>(handle), mv,
                                 data_type, (int)nrow, (int)ncol,
                                 is_row_major, predict_type,
                                 start_iteration, num_iteration, out_mv);
  Py_DECREF(mv);
  Py_DECREF(out_mv);
  PyObject* r = Call("booster_predict_mat2", args);
  Py_DECREF(args);
  if (!r) return PyError();
  if (out_len) *out_len = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterPredictForMatSingleRow(BoosterHandle handle,
                                       const void* data, int data_type,
                                       int ncol, int is_row_major,
                                       int predict_type, int start_iteration,
                                       int num_iteration,
                                       const char* parameter,
                                       int64_t* out_len, double* out_result) {
  return LGBM_BoosterPredictForMat(handle, data, data_type, 1, ncol,
                                   is_row_major, predict_type,
                                   start_iteration, num_iteration, parameter,
                                   out_len, out_result);
}

int LGBM_BoosterPredictForCSR(BoosterHandle handle, const void* indptr,
                              int indptr_type, const int32_t* indices,
                              const void* data, int data_type,
                              int64_t nindptr, int64_t nelem, int64_t num_col,
                              int predict_type, int start_iteration,
                              int num_iteration, const char* parameter,
                              int64_t* out_len, double* out_result) {
  (void)parameter;
  Gil gil;
  PyObject* ip = View(indptr, nindptr * DtypeSize(indptr_type));
  PyObject* ix = View(indices, nelem * 4);
  PyObject* dv = View(data, nelem * DtypeSize(data_type));
  int nf = 0;
  (void)IntFromBridge(handle, "booster_num_feature", &nf);
  int nc = 1;
  (void)LGBM_TrainBoosterGetNumClasses(handle, &nc);
  int64_t nrow = nindptr - 1;
  int iters = 0;
  (void)LGBM_TrainBoosterGetCurrentIteration(handle, &iters);
  int64_t cap = nrow * (nf + 1) * (nc > 0 ? nc : 1);
  int64_t leaf_cap = nrow * (nc > 0 ? nc : 1) * (iters > 0 ? iters : 1);
  if (leaf_cap > cap) cap = leaf_cap;
  PyObject* out_mv = View(out_result, cap * 8, true);
  PyObject* args = Py_BuildValue(
      "(OOiOOiLLLiiiO)", reinterpret_cast<PyObject*>(handle), ip,
      indptr_type, ix, dv, data_type, (long long)nindptr, (long long)nelem,
      (long long)num_col, predict_type, start_iteration, num_iteration,
      out_mv);
  Py_DECREF(ip);
  Py_DECREF(ix);
  Py_DECREF(dv);
  Py_DECREF(out_mv);
  PyObject* r = Call("booster_predict_csr2", args);
  Py_DECREF(args);
  if (!r) return PyError();
  if (out_len) *out_len = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterPredictForFile(BoosterHandle handle,
                               const char* data_filename,
                               int data_has_header, int predict_type,
                               int start_iteration, int num_iteration,
                               const char* parameter,
                               const char* result_filename) {
  (void)parameter;
  Gil gil;
  PyObject* args = Py_BuildValue("(Osiiiis)",
                                 reinterpret_cast<PyObject*>(handle),
                                 data_filename, data_has_header,
                                 predict_type, start_iteration,
                                 num_iteration, result_filename);
  PyObject* r = Call("booster_predict_for_file", args);
  Py_DECREF(args);
  if (!r) return PyError();
  Py_DECREF(r);
  return 0;
}

// FastConfig single-row fast path (c_api.h:1141-1196): freeze the predict
// configuration once; per-call work is one bridge hop with the frozen
// arguments.
struct FastConfig {        // shared by the Mat and CSR single-row paths
  PyObject* booster;
  int predict_type;
  int start_iteration;
  int num_iteration;
  int data_type;
  int64_t ncol;
  int64_t cap;  // pre-computed output capacity (doubles)
};
typedef void* FastConfigHandle;

int LGBM_BoosterPredictForMatSingleRowFastInit(
    BoosterHandle handle, const int predict_type, const int start_iteration,
    const int num_iteration, const int data_type, const int32_t ncol,
    const char* parameter, FastConfigHandle* out_fastConfig) {
  (void)parameter;
  Gil gil;
  int nf = 0;
  if (IntFromBridge(handle, "booster_num_feature", &nf) != 0) return -1;
  int nc = 1;
  (void)LGBM_TrainBoosterGetNumClasses(handle, &nc);
  int iters = 0;
  (void)LGBM_TrainBoosterGetCurrentIteration(handle, &iters);
  FastConfig* fc = new FastConfig();
  fc->booster = reinterpret_cast<PyObject*>(handle);
  Py_INCREF(fc->booster);
  fc->predict_type = predict_type;
  fc->start_iteration = start_iteration;
  fc->num_iteration = num_iteration;
  fc->data_type = data_type;
  fc->ncol = ncol;
  int64_t cap = static_cast<int64_t>(nf + 1) * (nc > 0 ? nc : 1);
  int64_t leaf_cap = static_cast<int64_t>(nc > 0 ? nc : 1)
                     * (iters > 0 ? iters : 1);
  fc->cap = leaf_cap > cap ? leaf_cap : cap;
  *out_fastConfig = fc;
  return 0;
}

int LGBM_BoosterPredictForMatSingleRowFast(FastConfigHandle fastConfig_handle,
                                           const void* data, int64_t* out_len,
                                           double* out_result) {
  FastConfig* fc = reinterpret_cast<FastConfig*>(fastConfig_handle);
  if (!fc) return SetError("null FastConfig handle");
  Gil gil;
  PyObject* mv = View(data, static_cast<Py_ssize_t>(fc->ncol)
                                * DtypeSize(fc->data_type));
  PyObject* out_mv = View(out_result, fc->cap * 8, true);
  PyObject* args = Py_BuildValue("(OOiiiiiiiO)", fc->booster, mv,
                                 fc->data_type, 1, (int)fc->ncol, 1,
                                 fc->predict_type, fc->start_iteration,
                                 fc->num_iteration, out_mv);
  Py_DECREF(mv);
  Py_DECREF(out_mv);
  PyObject* r = Call("booster_predict_mat2", args);
  Py_DECREF(args);
  if (!r) return PyError();
  if (out_len) *out_len = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

int LGBM_FastConfigFree(FastConfigHandle fastConfig) {
  FastConfig* fc = reinterpret_cast<FastConfig*>(fastConfig);
  if (!fc) return 0;
  Gil gil;
  Py_XDECREF(fc->booster);
  delete fc;
  return 0;
}

int LGBM_NetworkInit(const char* machines, int local_listen_port,
                     int listen_time_out, int num_machines) {
  return LGBM_TrainNetworkInit(machines, local_listen_port, listen_time_out,
                               num_machines);
}
int LGBM_NetworkFree() { return LGBM_TrainNetworkFree(); }

// ---------------------------------------------------------------------------
// Full-surface closure: the remaining c_api.h entry points (sampled-column
// / by-reference streaming, subset, feature merge, dumps, model surgery,
// leaf-pred refit, sparse-output predict, utility calls).
// ---------------------------------------------------------------------------

// split a tab-joined bridge string into the reference's (len, out_len,
// buffer_len, out_buffer_len, out_strs) string-list contract
static int StrListOut(const std::string& all, const int len, int* out_len,
                      const size_t buffer_len, size_t* out_buffer_len,
                      char** out_strs) {
  std::vector<std::string> names;
  if (!all.empty()) {
    size_t pos = 0;
    while (true) {
      size_t t = all.find('\t', pos);
      names.push_back(all.substr(pos, t == std::string::npos
                                          ? std::string::npos : t - pos));
      if (t == std::string::npos) break;
      pos = t + 1;
    }
  }
  if (out_len) *out_len = (int)names.size();
  size_t need = 1;
  for (const auto& s : names) need = s.size() + 1 > need ? s.size() + 1 : need;
  if (out_buffer_len) *out_buffer_len = need;
  if (out_strs) {
    int n = (int)names.size() < len ? (int)names.size() : len;
    for (int i = 0; i < n; ++i) {
      if (!out_strs[i] || buffer_len == 0) continue;
      size_t c = names[i].size() + 1 < buffer_len ? names[i].size() + 1
                                                  : buffer_len;
      std::memcpy(out_strs[i], names[i].c_str(), c - 1);
      out_strs[i][c - 1] = '\0';
    }
  }
  return 0;
}

// bridge call returning a string copied through (buffer_len, out_len,
// out_str)
static int StrCall(const char* fn, PyObject* args, int64_t buffer_len,
                   int64_t* out_len, char* out_str) {
  PyObject* r = Call(fn, args);
  Py_DECREF(args);
  if (!r) return PyError();
  int rc = StrOut(r, buffer_len, out_len, out_str);
  Py_DECREF(r);
  return rc;
}

int LGBM_DumpParamAliases(int64_t buffer_len, int64_t* out_len,
                          char* out_str) {
  Gil gil;
  return StrCall("dump_param_aliases", Py_BuildValue("()"), buffer_len,
                 out_len, out_str);
}

int LGBM_RegisterLogCallback(void (*callback)(const char*)) {
  Gil gil;
  PyObject* args = Py_BuildValue("(L)",
                                 (long long)(intptr_t)callback);
  PyObject* r = Call("register_log_forward", args);
  Py_DECREF(args);
  if (!r) return PyError();
  Py_DECREF(r);
  return 0;
}

int LGBM_GetSampleCount(int32_t num_total_row, const char* parameters,
                        int* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(is)", (int)num_total_row,
                                 parameters ? parameters : "");
  PyObject* r = Call("sample_count", args);
  Py_DECREF(args);
  if (!r) return PyError();
  if (out) *out = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

int LGBM_SampleIndices(int32_t num_total_row, const char* parameters,
                       void* out, int32_t* out_len) {
  Gil gil;
  PyObject* mv = View(out, (Py_ssize_t)num_total_row * 4, true);
  PyObject* args = Py_BuildValue("(isO)", (int)num_total_row,
                                 parameters ? parameters : "", mv);
  Py_DECREF(mv);
  PyObject* r = Call("sample_indices", args);
  Py_DECREF(args);
  if (!r) return PyError();
  if (out_len) *out_len = (int32_t)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetCreateFromSampledColumn(double** sample_data,
                                        int** sample_indices, int32_t ncol,
                                        const int* num_per_col,
                                        int32_t num_sample_row,
                                        int32_t num_total_row,
                                        const char* parameters,
                                        DatasetHandle* out) {
  (void)sample_indices;
  Gil gil;
  PyObject* cols = PyList_New(ncol);
  if (!cols) return PyError();
  for (int32_t j = 0; j < ncol; ++j) {
    PyObject* mv = View(sample_data[j],
                        (Py_ssize_t)num_per_col[j] * 8);
    PyObject* arr = Py_BuildValue("O", mv);  // keep as memoryview
    Py_DECREF(mv);
    PyList_SET_ITEM(cols, j, arr);
  }
  PyObject* args = Py_BuildValue("(OLLs)", cols,
                                 (long long)num_sample_row,
                                 (long long)num_total_row,
                                 parameters ? parameters : "");
  Py_DECREF(cols);
  PyObject* r = Call("dataset_create_from_sampled_column", args);
  Py_DECREF(args);
  if (!r) return PyError();
  *out = r;
  return 0;
}

int LGBM_DatasetCreateByReference(const DatasetHandle reference,
                                  int64_t num_total_row,
                                  DatasetHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OL)", RefOrNone(
                                     const_cast<DatasetHandle>(reference)),
                                 (long long)num_total_row);
  PyObject* r = Call("dataset_create_by_reference", args);
  Py_DECREF(args);
  if (!r) return PyError();
  *out = r;
  return 0;
}

int LGBM_DatasetPushRows(DatasetHandle dataset, const void* data,
                         int data_type, int32_t nrow, int32_t ncol,
                         int32_t start_row) {
  Gil gil;
  PyObject* mv = View(data, (Py_ssize_t)nrow * ncol * DtypeSize(data_type));
  PyObject* args = Py_BuildValue("(OOiiii)",
                                 reinterpret_cast<PyObject*>(dataset), mv,
                                 data_type, (int)nrow, (int)ncol,
                                 (int)start_row);
  Py_DECREF(mv);
  PyObject* r = Call("dataset_push_rows2", args);
  Py_DECREF(args);
  if (!r) return PyError();
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetPushRowsByCSR(DatasetHandle dataset, const void* indptr,
                              int indptr_type, const int32_t* indices,
                              const void* data, int data_type,
                              int64_t nindptr, int64_t nelem,
                              int64_t num_col, int64_t start_row) {
  (void)num_col;
  Gil gil;
  PyObject* ip = View(indptr, nindptr * DtypeSize(indptr_type));
  PyObject* ix = View(indices, nelem * 4);
  PyObject* dv = View(data, nelem * DtypeSize(data_type));
  PyObject* args = Py_BuildValue(
      "(OOiOOiLLL)", reinterpret_cast<PyObject*>(dataset), ip, indptr_type,
      ix, dv, data_type, (long long)nindptr, (long long)nelem,
      (long long)start_row);
  Py_DECREF(ip);
  Py_DECREF(ix);
  Py_DECREF(dv);
  PyObject* r = Call("dataset_push_rows_by_csr2", args);
  Py_DECREF(args);
  if (!r) return PyError();
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetGetSubset(const DatasetHandle handle,
                          const int32_t* used_row_indices,
                          int32_t num_used_row_indices,
                          const char* parameters, DatasetHandle* out) {
  Gil gil;
  PyObject* mv = View(used_row_indices,
                      (Py_ssize_t)num_used_row_indices * 4);
  PyObject* args = Py_BuildValue(
      "(OOis)", reinterpret_cast<PyObject*>(
          const_cast<DatasetHandle>(handle)),
      mv, (int)num_used_row_indices, parameters ? parameters : "");
  Py_DECREF(mv);
  PyObject* r = Call("dataset_get_subset", args);
  Py_DECREF(args);
  if (!r) return PyError();
  *out = r;
  return 0;
}

int LGBM_DatasetDumpText(DatasetHandle handle, const char* filename) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)",
                                 reinterpret_cast<PyObject*>(handle),
                                 filename);
  PyObject* r = Call("dataset_dump_text", args);
  Py_DECREF(args);
  if (!r) return PyError();
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetUpdateParamChecking(const char* old_parameters,
                                    const char* new_parameters) {
  Gil gil;
  PyObject* args = Py_BuildValue("(ss)",
                                 old_parameters ? old_parameters : "",
                                 new_parameters ? new_parameters : "");
  PyObject* r = Call("dataset_update_param_checking", args);
  Py_DECREF(args);
  if (!r) return PyError();
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetGetFeatureNumBin(DatasetHandle handle, int feature,
                                 int* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oi)",
                                 reinterpret_cast<PyObject*>(handle),
                                 feature);
  PyObject* r = Call("dataset_feature_num_bin", args);
  Py_DECREF(args);
  if (!r) return PyError();
  if (out) *out = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

int LGBM_DatasetAddFeaturesFrom(DatasetHandle target,
                                DatasetHandle source) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OO)",
                                 reinterpret_cast<PyObject*>(target),
                                 reinterpret_cast<PyObject*>(source));
  PyObject* r = Call("dataset_add_features_from", args);
  Py_DECREF(args);
  if (!r) return PyError();
  Py_DECREF(r);
  return 0;
}

static int NamesFromBridge(PyObject* handle, const char* fn, const int len,
                           int* out_len, const size_t buffer_len,
                           size_t* out_buffer_len, char** out_strs) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", handle);
  PyObject* r = Call(fn, args);
  Py_DECREF(args);
  if (!r) return PyError();
  std::string all(SafeUTF8(r, ""));
  Py_DECREF(r);
  return StrListOut(all, len, out_len, buffer_len, out_buffer_len,
                    out_strs);
}

int LGBM_DatasetGetFeatureNames(DatasetHandle handle, const int len,
                                int* out_len, const size_t buffer_len,
                                size_t* out_buffer_len, char** out_strs) {
  return NamesFromBridge(reinterpret_cast<PyObject*>(handle),
                         "dataset_get_feature_names", len, out_len,
                         buffer_len, out_buffer_len, out_strs);
}

int LGBM_BoosterGetFeatureNames(BoosterHandle handle, const int len,
                                int* out_len, const size_t buffer_len,
                                size_t* out_buffer_len, char** out_strs) {
  return NamesFromBridge(reinterpret_cast<PyObject*>(handle),
                         "booster_get_feature_names", len, out_len,
                         buffer_len, out_buffer_len, out_strs);
}

int LGBM_BoosterGetLinear(BoosterHandle handle, int* out) {
  return IntFromBridge(handle, "booster_get_linear", out);
}

int LGBM_BoosterGetLeafValue(BoosterHandle handle, int tree_idx,
                             int leaf_idx, double* out_val) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oii)",
                                 reinterpret_cast<PyObject*>(handle),
                                 tree_idx, leaf_idx);
  PyObject* r = Call("booster_get_leaf_value", args);
  Py_DECREF(args);
  if (!r) return PyError();
  if (out_val) *out_val = PyFloat_AsDouble(r);
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterSetLeafValue(BoosterHandle handle, int tree_idx,
                             int leaf_idx, double val) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oiid)",
                                 reinterpret_cast<PyObject*>(handle),
                                 tree_idx, leaf_idx, val);
  PyObject* r = Call("booster_set_leaf_value", args);
  Py_DECREF(args);
  if (!r) return PyError();
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterGetNumPredict(BoosterHandle handle, int data_idx,
                              int64_t* out_len) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oi)",
                                 reinterpret_cast<PyObject*>(handle),
                                 data_idx);
  PyObject* r = Call("booster_num_predict", args);
  Py_DECREF(args);
  if (!r) return PyError();
  if (out_len) *out_len = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterGetPredict(BoosterHandle handle, int data_idx,
                           int64_t* out_len, double* out_result) {
  Gil gil;
  int64_t cap = 0;
  if (LGBM_BoosterGetNumPredict(handle, data_idx, &cap) != 0) return -1;
  PyObject* mv = View(out_result, (cap > 0 ? cap : 1) * 8, true);
  PyObject* args = Py_BuildValue("(OiO)",
                                 reinterpret_cast<PyObject*>(handle),
                                 data_idx, mv);
  Py_DECREF(mv);
  PyObject* r = Call("booster_get_predict", args);
  Py_DECREF(args);
  if (!r) return PyError();
  if (out_len) *out_len = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterCalcNumPredict(BoosterHandle handle, int num_row,
                               int predict_type, int start_iteration,
                               int num_iteration, int64_t* out_len) {
  int nc = 1, nf = 0, iters = 0;
  (void)LGBM_TrainBoosterGetNumClasses(handle, &nc);
  (void)IntFromBridge(handle, "booster_num_feature", &nf);
  (void)LGBM_TrainBoosterGetCurrentIteration(handle, &iters);
  int used = num_iteration > 0
                 ? (num_iteration < iters - start_iteration
                        ? num_iteration : iters - start_iteration)
                 : iters - start_iteration;
  if (used < 0) used = 0;
  int64_t per_row = nc;
  if (predict_type == 2) per_row = (int64_t)nc * used;
  if (predict_type == 3) per_row = (int64_t)nc * (nf + 1);
  if (out_len) *out_len = (int64_t)num_row * per_row;
  return 0;
}

int LGBM_BoosterMerge(BoosterHandle handle, BoosterHandle other_handle) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OO)",
                                 reinterpret_cast<PyObject*>(handle),
                                 reinterpret_cast<PyObject*>(other_handle));
  PyObject* r = Call("booster_merge", args);
  Py_DECREF(args);
  if (!r) return PyError();
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterShuffleModels(BoosterHandle handle, int start_iter,
                              int end_iter) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oii)",
                                 reinterpret_cast<PyObject*>(handle),
                                 start_iter, end_iter);
  PyObject* r = Call("booster_shuffle_models", args);
  Py_DECREF(args);
  if (!r) return PyError();
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterResetTrainingData(BoosterHandle handle,
                                  const DatasetHandle train_data) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(OO)", reinterpret_cast<PyObject*>(handle),
      reinterpret_cast<PyObject*>(const_cast<DatasetHandle>(train_data)));
  PyObject* r = Call("booster_reset_training_data", args);
  Py_DECREF(args);
  if (!r) return PyError();
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterRefit(BoosterHandle handle, const int32_t* leaf_preds,
                      int32_t nrow, int32_t ncol) {
  Gil gil;
  PyObject* mv = View(leaf_preds, (Py_ssize_t)nrow * ncol * 4);
  PyObject* args = Py_BuildValue("(OOii)",
                                 reinterpret_cast<PyObject*>(handle), mv,
                                 (int)nrow, (int)ncol);
  Py_DECREF(mv);
  PyObject* r = Call("booster_refit_leaf_preds", args);
  Py_DECREF(args);
  if (!r) return PyError();
  Py_DECREF(r);
  return 0;
}

static int DoubleFromBridge(BoosterHandle handle, const char* fn,
                            double* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", reinterpret_cast<PyObject*>(handle));
  PyObject* r = Call(fn, args);
  Py_DECREF(args);
  if (!r) return PyError();
  if (out) *out = PyFloat_AsDouble(r);
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterGetUpperBoundValue(BoosterHandle handle,
                                   double* out_results) {
  return DoubleFromBridge(handle, "booster_upper_bound", out_results);
}
int LGBM_BoosterGetLowerBoundValue(BoosterHandle handle,
                                   double* out_results) {
  return DoubleFromBridge(handle, "booster_lower_bound", out_results);
}

int LGBM_BoosterPredictForCSC(BoosterHandle handle, const void* col_ptr,
                              int col_ptr_type, const int32_t* indices,
                              const void* data, int data_type,
                              int64_t ncol_ptr, int64_t nelem,
                              int64_t num_row, int predict_type,
                              int start_iteration, int num_iteration,
                              const char* parameter, int64_t* out_len,
                              double* out_result) {
  (void)parameter;
  Gil gil;
  PyObject* cp = View(col_ptr, ncol_ptr * DtypeSize(col_ptr_type));
  PyObject* ix = View(indices, nelem * 4);
  PyObject* dv = View(data, nelem * DtypeSize(data_type));
  int nf = 0, nc = 1, iters = 0;
  (void)IntFromBridge(handle, "booster_num_feature", &nf);
  (void)LGBM_TrainBoosterGetNumClasses(handle, &nc);
  (void)LGBM_TrainBoosterGetCurrentIteration(handle, &iters);
  int64_t cap = num_row * (nf + 1) * (nc > 0 ? nc : 1);
  int64_t leaf_cap = num_row * (nc > 0 ? nc : 1) * (iters > 0 ? iters : 1);
  if (leaf_cap > cap) cap = leaf_cap;
  PyObject* out_mv = View(out_result, cap * 8, true);
  PyObject* args = Py_BuildValue(
      "(OOiOOiLLLiiiO)", reinterpret_cast<PyObject*>(handle), cp,
      col_ptr_type, ix, dv, data_type, (long long)ncol_ptr,
      (long long)nelem, (long long)num_row, predict_type, start_iteration,
      num_iteration, out_mv);
  Py_DECREF(cp);
  Py_DECREF(ix);
  Py_DECREF(dv);
  Py_DECREF(out_mv);
  PyObject* r = Call("booster_predict_csc2", args);
  Py_DECREF(args);
  if (!r) return PyError();
  if (out_len) *out_len = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterPredictForMats(BoosterHandle handle, const void** data,
                               int data_type, int32_t nrow, int32_t ncol,
                               int predict_type, int start_iteration,
                               int num_iteration, const char* parameter,
                               int64_t* out_len, double* out_result) {
  // assemble the row pointers into one contiguous f64 matrix, then the
  // regular mat path
  std::vector<double> buf((size_t)nrow * ncol);
  for (int32_t i = 0; i < nrow; ++i) {
    if (data_type == 0) {
      const float* row = reinterpret_cast<const float*>(data[i]);
      for (int32_t j = 0; j < ncol; ++j) buf[(size_t)i * ncol + j] = row[j];
    } else {
      const double* row = reinterpret_cast<const double*>(data[i]);
      for (int32_t j = 0; j < ncol; ++j) buf[(size_t)i * ncol + j] = row[j];
    }
  }
  return LGBM_BoosterPredictForMat(handle, buf.data(), /*f64*/ 1, nrow,
                                   ncol, 1, predict_type, start_iteration,
                                   num_iteration, parameter, out_len,
                                   out_result);
}

// CSR FastConfig single-row path (c_api.h:953-1018) — reuses the SAME
// FastConfig struct as the Mat variant so LGBM_FastConfigFree handles
// both uniformly
int LGBM_BoosterPredictForCSRSingleRowFastInit(
    BoosterHandle handle, const int predict_type, const int start_iteration,
    const int num_iteration, const int data_type, const int64_t num_col,
    const char* parameter, FastConfigHandle* out_fastConfig) {
  return LGBM_BoosterPredictForMatSingleRowFastInit(
      handle, predict_type, start_iteration, num_iteration, data_type,
      (int32_t)num_col, parameter, out_fastConfig);
}

int LGBM_BoosterPredictForCSRSingleRowFast(
    FastConfigHandle fastConfig_handle, const void* indptr,
    const int indptr_type, const int32_t* indices, const void* data,
    const int64_t nindptr, const int64_t nelem, int64_t* out_len,
    double* out_result) {
  FastConfig* fc = reinterpret_cast<FastConfig*>(fastConfig_handle);
  if (!fc) return SetError("null FastConfig handle");
  Gil gil;
  PyObject* ip = View(indptr, nindptr * DtypeSize(indptr_type));
  PyObject* ix = View(indices, nelem * 4);
  PyObject* dv = View(data, nelem * DtypeSize(fc->data_type));
  PyObject* out_mv = View(out_result, fc->cap * 8, true);
  PyObject* args = Py_BuildValue(
      "(OOiOOiLLLiiiO)", fc->booster, ip, indptr_type, ix, dv,
      fc->data_type, (long long)nindptr, (long long)nelem,
      (long long)fc->ncol, fc->predict_type, fc->start_iteration,
      fc->num_iteration, out_mv);
  Py_DECREF(ip);
  Py_DECREF(ix);
  Py_DECREF(dv);
  Py_DECREF(out_mv);
  PyObject* r = Call("booster_predict_csr2", args);
  Py_DECREF(args);
  if (!r) return PyError();
  if (out_len) *out_len = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return 0;
}

int LGBM_BoosterPredictSparseOutput(
    BoosterHandle handle, const void* indptr, int indptr_type,
    const int32_t* indices, const void* data, int data_type,
    int64_t nindptr, int64_t nelem, int64_t num_col_or_row,
    int predict_type, int start_iteration, int num_iteration,
    const char* parameter, int matrix_type, int64_t* out_len,
    void** out_indptr, int32_t** out_indices, void** out_data) {
  (void)parameter;
  Gil gil;
  PyObject* ip = View(indptr, nindptr * DtypeSize(indptr_type));
  PyObject* ix = View(indices, nelem * 4);
  PyObject* dv = View(data, nelem * DtypeSize(data_type));
  PyObject* args = Py_BuildValue(
      "(OOiOOiLLLiiii)", reinterpret_cast<PyObject*>(handle), ip,
      indptr_type, ix, dv, data_type, (long long)nindptr, (long long)nelem,
      (long long)num_col_or_row, predict_type, start_iteration,
      num_iteration, matrix_type);
  Py_DECREF(ip);
  Py_DECREF(ix);
  Py_DECREF(dv);
  PyObject* r = Call("booster_predict_sparse", args);
  Py_DECREF(args);
  if (!r) return PyError();
  // (indptr_addr, indptr_len, indices_addr, data_addr, data_len) — the
  // backing numpy buffers are pinned on the booster; copy into malloc'd
  // buffers the caller frees with LGBM_BoosterFreePredictSparse
  long long pa, pl, ia, da, dl;
  if (!PyArg_ParseTuple(r, "LLLLL", &pa, &pl, &ia, &da, &dl)) {
    Py_DECREF(r);
    return PyError();
  }
  Py_DECREF(r);
  // buffers typed to the CALLER's indptr/data types (the bridge already
  // produced matching numpy dtypes), per the reference contract
  size_t ip_sz = DtypeSize(indptr_type), dt_sz = DtypeSize(data_type);
  void* oip = malloc(ip_sz * pl);
  int32_t* oix = static_cast<int32_t*>(malloc(sizeof(int32_t) * dl));
  void* odt = malloc(dt_sz * dl);
  if (!oip || !oix || !odt) {
    free(oip);
    free(oix);
    free(odt);
    return SetError("out of memory");
  }
  std::memcpy(oip, reinterpret_cast<void*>(pa), ip_sz * pl);
  std::memcpy(oix, reinterpret_cast<void*>(ia), sizeof(int32_t) * dl);
  std::memcpy(odt, reinterpret_cast<void*>(da), dt_sz * dl);
  if (out_len) {
    out_len[0] = dl;   // nnz
    out_len[1] = pl;   // indptr length
  }
  if (out_indptr) *out_indptr = oip;
  if (out_indices) *out_indices = oix;
  if (out_data) *out_data = odt;
  return 0;
}

int LGBM_BoosterFreePredictSparse(void* indptr, int32_t* indices,
                                  void* data, int indptr_type,
                                  int data_type) {
  (void)indptr_type;
  (void)data_type;
  free(indptr);
  free(indices);
  free(data);
  return 0;
}

int LGBM_BoosterPredictForCSRSingleRow(
    BoosterHandle handle, const void* indptr, int indptr_type,
    const int32_t* indices, const void* data, int data_type,
    int64_t nindptr, int64_t nelem, int64_t num_col, int predict_type,
    int start_iteration, int num_iteration, const char* parameter,
    int64_t* out_len, double* out_result) {
  return LGBM_BoosterPredictForCSR(handle, indptr, indptr_type, indices,
                                   data, data_type, nindptr, nelem, num_col,
                                   predict_type, start_iteration,
                                   num_iteration, parameter, out_len,
                                   out_result);
}

int LGBM_DatasetCreateFromMats(int32_t nmat, const void** data,
                               int data_type, int32_t* nrow, int32_t ncol,
                               int is_row_major, const char* parameters,
                               const DatasetHandle reference,
                               DatasetHandle* out) {
  // concatenate the row-blocks into one f64 matrix, then the mat path
  int64_t total = 0;
  for (int32_t m = 0; m < nmat; ++m) total += nrow[m];
  std::vector<double> buf((size_t)total * ncol);
  int64_t at = 0;
  for (int32_t m = 0; m < nmat; ++m) {
    for (int32_t i = 0; i < nrow[m]; ++i) {
      for (int32_t j = 0; j < ncol; ++j) {
        size_t src = is_row_major ? (size_t)i * ncol + j
                                  : (size_t)j * nrow[m] + i;
        double v = data_type == 0
                       ? (double)reinterpret_cast<const float*>(data[m])[src]
                       : reinterpret_cast<const double*>(data[m])[src];
        buf[(size_t)(at + i) * ncol + j] = v;
      }
    }
    at += nrow[m];
  }
  return LGBM_DatasetCreateFromMat(buf.data(), /*f64*/ 1, (int32_t)total,
                                   ncol, 1, parameters, reference, out);
}

// NetworkInitWithFunctions (c_api.h:1350): the reference lets external
// launchers inject reduce-scatter/allgather implementations.  The TPU
// framework's collectives are XLA's own (compiled into the program), so
// external function injection cannot replace them; accept the call for
// link compatibility when the caller only needs rank bookkeeping, and
// fail loudly if custom collectives were actually expected to be used.
int LGBM_NetworkInitWithFunctions(int num_machines, int rank,
                                  void* reduce_scatter_ext_fun,
                                  void* allgather_ext_fun) {
  if (num_machines <= 1) return 0;
  if (reduce_scatter_ext_fun || allgather_ext_fun) {
    return SetError(
        "LGBM_NetworkInitWithFunctions: external collective functions "
        "cannot be injected into the XLA runtime (collectives are "
        "compiled); use LGBM_NetworkInit with a machine list instead");
  }
  (void)rank;
  return 0;
}

// CSRFunc: the caller hands a pointer to a C++
// std::function<void(int, std::vector<std::pair<int, double>>&)> (the
// reference's documented contract, c_api.h:226-236) — same-toolchain
// assumption as the reference itself makes
int LGBM_DatasetCreateFromCSRFunc(void* get_row_funptr, int num_rows,
                                  int64_t num_col, const char* parameters,
                                  const DatasetHandle reference,
                                  DatasetHandle* out) {
  using RowFn = std::function<void(int, std::vector<std::pair<int, double>>&)>;
  RowFn* fn = reinterpret_cast<RowFn*>(get_row_funptr);
  std::vector<int64_t> indptr(1, 0);
  std::vector<int32_t> idx;
  std::vector<double> vals;
  std::vector<std::pair<int, double>> row;
  for (int i = 0; i < num_rows; ++i) {
    row.clear();
    (*fn)(i, row);
    for (const auto& kv : row) {
      idx.push_back(kv.first);
      vals.push_back(kv.second);
    }
    indptr.push_back(static_cast<int64_t>(idx.size()));
  }
  if (idx.empty()) {               // keep the buffers non-null for View
    idx.push_back(0);
    vals.push_back(0.0);
  }
  return LGBM_DatasetCreateFromCSR(indptr.data(), /*int64*/ 3, idx.data(),
                                   vals.data(), /*f64*/ 1,
                                   (int64_t)indptr.size(),
                                   (int64_t)(indptr.back()), num_col,
                                   parameters, reference, out);
}

}  // extern "C"
