"""Training callbacks (reference: python-package/lightgbm/callback.py:15-356).

Same surface: ``log_evaluation``, ``record_evaluation``, ``reset_parameter``,
``early_stopping``; early stopping signals via ``EarlyStopException`` caught
by the train loop (engine.py:252 pattern).
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List

CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


class EarlyStopException(Exception):
    def __init__(self, best_iteration: int, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


def _fmt_eval(res) -> str:
    name, metric, value, _ = res
    return f"{name}'s {metric}: {value:g}"


def log_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list \
                and (env.iteration + 1) % period == 0:
            msg = "\t".join(_fmt_eval(r) for r in env.evaluation_result_list)
            print(f"[{env.iteration + 1}]\t{msg}")
    _callback.order = 10
    return _callback


def record_evaluation(eval_result: Dict) -> Callable:
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result must be a dict")

    def _callback(env: CallbackEnv) -> None:
        for name, metric, value, _ in env.evaluation_result_list:
            eval_result.setdefault(name, collections.OrderedDict())
            eval_result[name].setdefault(metric, []).append(value)
    _callback.order = 20
    return _callback


def reset_parameter(**kwargs) -> Callable:
    """Per-iteration parameter schedule; supports ``learning_rate`` as a
    list or ``f(iteration) -> value`` (callback.py reset_parameter)."""

    def _callback(env: CallbackEnv) -> None:
        it = env.iteration - env.begin_iteration
        for key, value in kwargs.items():
            new_val = value[it] if isinstance(value, list) else value(it)
            if key == "learning_rate":
                env.model._model.learning_rate = new_val
            else:
                setattr(env.model._model.config, key, new_val)
    _callback.before_iteration = True
    _callback.order = 10
    return _callback


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True, min_delta: float = 0.0) -> Callable:
    best_score: List[float] = []
    best_iter: List[int] = []
    best_score_list: List[list] = []
    cmp_op: List[Callable] = []
    enabled = [True]
    first_metric = [""]

    def _init(env: CallbackEnv) -> None:
        enabled[0] = bool(env.evaluation_result_list)
        if not enabled[0]:
            return
        best_score.clear(), best_iter.clear()
        best_score_list.clear(), cmp_op.clear()
        first_metric[0] = env.evaluation_result_list[0][1].split("@")[0]
        for (_name, _metric, _val, higher_better) in env.evaluation_result_list:
            best_iter.append(0)
            best_score_list.append(None)
            if higher_better:
                best_score.append(float("-inf"))
                cmp_op.append(lambda new, best: new > best + min_delta)
            else:
                best_score.append(float("inf"))
                cmp_op.append(lambda new, best: new < best - min_delta)

    def _callback(env: CallbackEnv) -> None:
        if not best_score:
            _init(env)
        if not enabled[0]:
            return
        for i, (name, metric, val, _hib) in enumerate(env.evaluation_result_list):
            if best_score_list[i] is None or cmp_op[i](val, best_score[i]):
                best_score[i] = val
                best_iter[i] = env.iteration
                best_score_list[i] = list(env.evaluation_result_list)
            if first_metric_only and metric.split("@")[0] != first_metric[0]:
                continue
            if name == "training":
                continue
            if env.iteration - best_iter[i] >= stopping_rounds:
                if verbose:
                    print(f"Early stopping, best iteration is:\n"
                          f"[{best_iter[i] + 1}]\t" +
                          "\t".join(_fmt_eval(r) for r in best_score_list[i]))
                raise EarlyStopException(best_iter[i], best_score_list[i])
            if env.iteration == env.end_iteration - 1:
                if verbose:
                    print(f"Did not meet early stopping. Best iteration is:\n"
                          f"[{best_iter[i] + 1}]\t" +
                          "\t".join(_fmt_eval(r) for r in best_score_list[i]))
                raise EarlyStopException(best_iter[i], best_score_list[i])
    _callback.order = 30
    return _callback
