"""Generate docs/Parameters.md from the single config table of record.

The reference generates docs/Parameters.rst AND its parsing code from
config.h header comments via helpers/parameter_generator.py; here the
``_PARAMS`` table in lightgbm_tpu/config.py is the single source, and this
script renders it (grouped by the table's section comments) so docs can
never drift from the accepted surface.

Run: python tools/gen_param_docs.py   (writes docs/Parameters.md)
"""

import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from lightgbm_tpu.config import _PARAMS  # noqa: E402

CONFIG_PY = os.path.join(os.path.dirname(__file__), "..", "lightgbm_tpu",
                         "config.py")
OUT = os.path.join(os.path.dirname(__file__), "..", "docs", "Parameters.md")


def sections():
    """(section_title, [param names]) in table order, from the
    ``# ---- section ----`` comments inside _PARAMS."""
    src = open(CONFIG_PY).read()
    body = src.split("_PARAMS: Dict[str, tuple] = {", 1)[1]
    body = body.split("\n}", 1)[0]
    out, cur, title = [], [], "core"
    for line in body.splitlines():
        m = re.match(r"\s*# ---- (.+?) ----", line)
        if m:
            if cur:
                out.append((title, cur))
            title, cur = m.group(1), []
            continue
        pm = re.match(r'\s*"([a-z0-9_]+)":', line)
        if pm and pm.group(1) in _PARAMS:
            cur.append(pm.group(1))
    if cur:
        out.append((title, cur))
    return out


def fmt_default(v):
    if v is None:
        return "`None`"
    if isinstance(v, bool):
        return "`true`" if v else "`false`"
    if isinstance(v, str):
        return f'`"{v}"`' if v else '`""`'
    return f"`{v}`"


def main():
    lines = [
        "# Parameters",
        "",
        "Generated from `lightgbm_tpu/config.py` `_PARAMS` — the single",
        "table of record for names, types, defaults and aliases (the",
        "analog of the reference's docs/Parameters.rst, which is likewise",
        "generated from its config source).  Regenerate with",
        "`python tools/gen_param_docs.py`; do not edit by hand.",
        "",
        "Aliases resolve to the canonical name exactly as in the",
        "reference (`Config::Set` alias table).  Unknown keys are kept",
        "and ignored, matching the reference's pass-through behavior.",
        "",
    ]
    total = 0
    for title, names in sections():
        lines += [f"## {title}", "",
                  "| Parameter | Type | Default | Aliases |",
                  "|---|---|---|---|"]
        for name in names:
            typ, default, aliases = _PARAMS[name]
            al = ", ".join(f"`{a}`" for a in aliases) if aliases else "—"
            lines.append(f"| `{name}` | {typ.__name__} | "
                         f"{fmt_default(default)} | {al} |")
            total += 1
        lines.append("")
    assert total == len(_PARAMS), \
        f"section scan covered {total} of {len(_PARAMS)} params"
    lines.append(f"_{total} parameters._")
    lines.append("")
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "w") as f:
        f.write("\n".join(lines))
    print(f"wrote {os.path.relpath(OUT)} ({total} params)")


if __name__ == "__main__":
    main()
