"""Survivable out-of-core ingest: streaming, checkpointed, fault-injectable.

ROADMAP item 3's data path assumed every process could materialize its
full shard in host RAM and died on the first torn/corrupt/slow chunk —
none of the fault machinery training got (retry ladders, snapshots,
heartbeats, fault injection) guarded the loader.  This module applies
the same treatment to ingest, in the shape "Exact Distributed Training:
Random Forest with Billions of Examples" (arXiv:1804.06755) prescribes:
no host ever sees the full dataset; each process streams bounded-memory
chunks, folds them into mergeable per-feature quantile sketches
(:class:`binning.QuantileSketch`), and bin bounds come from the merged
sketches — arXiv:1611.01276's ship-summaries-not-samples argument
applied to binning.

Pipeline, per chunk (:class:`IngestRunner`):

1. **Resume probe** — if ``ingest_resume`` and the chunk's spool +
   manifest verify (manifest parses, spool sha256 matches), the spooled
   arrays are loaded and the source is never re-read: a killed or OOM'd
   loader resumes from the last COMPLETE chunk, byte-identically
   (tests/ingest_worker.py kills the loader between commits and the
   resumed model text equals the uninterrupted run's).
2. **Read + parse** under ``resilience.retry_call`` (jittered backoff,
   ``ingest_retries``) and a raise-mode ``resilience.Watchdog``
   (``ingest_read_timeout_s``): a reader wedged on a dead filesystem is
   abandoned at the deadline and the WatchdogTimeout — like any
   transient read error — is retried; exhaustion raises
   ``ElasticFailure("ingest", ...)`` so the elastic recovery ladder
   classifies it instead of inheriting a stuck process.  Fault sites
   ``ingest_read`` / ``ingest_hang`` (utils/faultinject.py) fire here.
3. **Validate** — parse failure, row-count drift against the plan, and
   the ``ingest_checksum`` fault site classify the chunk CORRUPT (not
   transient): it is quarantined with a flight-recorder dump and the
   run either fails fast (``ingest_bad_chunk=raise``, default) or
   degrades with a dropped-row accounting (``skip``).
4. **Commit** — the parsed arrays spool to a DETERMINISTIC container
   (``.lgc`` — raw ``.npy`` segments, no zip timestamps, so the spool
   sha256 is reproducible) via ``resilience.atomic_write``, then the
   chunk manifest (sha256s, row span, byte offsets) is written LAST in
   the snapshot.py mold: its presence marks a complete chunk.
5. **Sketch** — each feature column folds into its QuantileSketch;
   after the last chunk ``binning.fit_mappers_from_sketches`` turns
   them into BinMappers in one pass, and :func:`ingest_dataset` hands
   a :class:`SpooledChunkSequence` (a ``dataset.Sequence``) plus the
   mappers to ``Dataset`` — construction bins chunk-by-chunk and the
   full raw matrix never exists in memory.

Liveness: when an elastic context is installed
(``parallel/elastic.install``) the per-process heartbeat thread keeps
beating through ingest and every chunk boundary calls
``elastic.check_peers()`` — a peer that died mid-ingest surfaces as a
classified ``host_loss`` at the next boundary, not at first collective.

Metrics (``metrics_snapshot()``): ``ingest.chunks{outcome=...}``,
``ingest.rows``, ``ingest.rows_dropped``, ``ingest.retries``,
``ingest.bytes_read``, ``ingest.chunk_s``.
"""

from __future__ import annotations

import dataclasses
import io
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .binning import BinMapper, QuantileSketch, fit_mappers_from_sketches
from .data_io import (_clean_line, detect_format, parse_csv_block,
                      parse_libsvm_block)
from .dataset import Sequence as DatasetSequence
from .obs import blackbox
from .obs.metrics import MetricsRegistry
from .utils import faultinject
from .utils.log import Log
from .utils.resilience import (RetryPolicy, Watchdog, atomic_write,
                               is_retryable_device_error, retry_call)

_FORMAT = 1
_SPOOL_MAGIC = b"LGIC\x01"

# module-level ingest metrics, the elastic.py registry pattern:
# always-on, host-side counter bumps per CHUNK (never per row).
# Lock contract (tools/analyze/check_races.py): _REGISTRY_LOCK guards:
# _REGISTRY.
_REGISTRY = MetricsRegistry()
_REGISTRY_LOCK = threading.Lock()


def metrics_snapshot() -> dict:
    """Deterministic dict snapshot of the ``ingest.*`` metrics."""
    return _REGISTRY.snapshot()


def reset_metrics() -> None:
    """Test hook: drop all ``ingest.*`` metric state."""
    global _REGISTRY
    with _REGISTRY_LOCK:
        _REGISTRY = MetricsRegistry()


def _metrics() -> MetricsRegistry:
    with _REGISTRY_LOCK:
        return _REGISTRY


class IngestError(RuntimeError):
    """Unrecoverable ingest failure (corrupt chunk under
    ``ingest_bad_chunk=raise``, malformed source).  Deliberately NOT
    classified retryable: bad data does not become good by waiting."""


class ChunkCorrupt(IngestError):
    """One chunk failed validation (sha mismatch, parse failure,
    row-count drift) — quarantine material, never retried."""

    def __init__(self, index: int, reason: str):
        self.index = index
        self.reason = reason
        super().__init__(f"chunk {index} corrupt: {reason}")


@dataclasses.dataclass
class ChunkPlan:
    """One chunk's slice of the source, fixed at plan time."""
    index: int
    path: str
    byte_start: int
    byte_end: int
    row_start: int
    rows: int            # data (non-blank) lines; -1 = unknown until read


@dataclasses.dataclass
class ChunkReport:
    """Per-chunk outcome for the run report / soak assertions."""
    index: int
    rows: int
    outcome: str          # "ok" | "resumed" | "quarantined"
    retries: int = 0
    reason: str = ""


@dataclasses.dataclass
class IngestResult:
    """Everything dataset construction needs, without the raw matrix."""
    sketches: List[QuantileSketch]
    sequence: "SpooledChunkSequence"
    label: Optional[np.ndarray]
    num_rows: int
    num_features: int
    dropped_rows: int
    reports: List[ChunkReport]
    spool_dir: str
    resumed_chunks: int

    def fit_bin_mappers(self, cfg, cat_idx: Optional[set] = None
                        ) -> List[BinMapper]:
        return fit_mappers_from_sketches(self.sketches, cfg, cat_idx)


# ---------------------------------------------------------------------------
# Planning: source -> chunk spans (bounded-memory scan)
# ---------------------------------------------------------------------------

def _scan_line_offsets(path: str, scan_libsvm_width: bool
                       ) -> Tuple[List[int], int, int]:
    """Stream the file once in 1 MiB blocks -> (offsets of each
    non-blank data line, total byte size, libsvm max feature index or
    -1).  Never holds more than one block; the scan is the one
    whole-file pass planning needs (the libsvm feature-space width must
    be global before any chunk densifies)."""
    offsets: List[int] = []
    max_feat = -1
    pos = 0
    carry = b""
    carry_off = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(1 << 20)
            if not block:
                break
            data = carry + block
            start = 0
            while True:
                nl = data.find(b"\n", start)
                if nl < 0:
                    break
                line = data[start:nl]
                if line.strip(b"\r \t\xef\xbb\xbf"):
                    offsets.append(carry_off + start)
                    if scan_libsvm_width:
                        for tok in line.split()[1:]:
                            col, sep, _ = tok.partition(b":")
                            if sep:
                                try:
                                    max_feat = max(max_feat, int(col))
                                except ValueError:
                                    pass  # parse stage reports lineno
                start = nl + 1
            pos = carry_off + len(data)
            carry = data[start:]
            carry_off = pos - len(carry)
    if carry.strip(b"\r \t\xef\xbb\xbf"):
        offsets.append(carry_off)
    size = pos
    return offsets, size, max_feat


def _is_chunk_file(name: str) -> bool:
    return (not name.startswith(".") and not name.endswith(".tmp")
            and not name.endswith(".json"))


@dataclasses.dataclass
class IngestPlan:
    """The run-scoped chunking decision, persisted to ``run.json`` so a
    resumed loader can tell whether its spool is still valid."""
    source: str
    fmt: str
    has_header: bool
    label_column: str
    chunk_rows: int
    n_cols: int                    # libsvm feature-space width; -1 n/a
    header_line: str
    chunks: List[ChunkPlan]
    source_sizes: Dict[str, int]

    def signature(self) -> Dict[str, Any]:
        return {"format": _FORMAT, "source": os.path.abspath(self.source),
                "fmt": self.fmt, "has_header": self.has_header,
                "label_column": self.label_column,
                "chunk_rows": self.chunk_rows, "n_cols": self.n_cols,
                "num_chunks": len(self.chunks),
                "source_sizes": self.source_sizes}


def plan_chunks(source: str, chunk_rows: int, has_header: bool = False,
                fmt: Optional[str] = None,
                label_column: str = "") -> IngestPlan:
    """Chunk a source into bounded spans.  A directory is one chunk per
    (sorted) file — the sharded-dataset layout; a single file is split
    every ``chunk_rows`` data lines via a streaming offset scan."""
    if os.path.isdir(source):
        files = sorted(f for f in os.listdir(source) if _is_chunk_file(f))
        if not files:
            raise IngestError(f"ingest source dir {source!r} has no "
                              "chunk files")
        first = os.path.join(source, files[0])
        fmt = fmt or detect_format(first, has_header)
        n_cols = -1
        if fmt == "libsvm":
            n_cols = 0
            for fn in files:
                _, _, mf = _scan_line_offsets(os.path.join(source, fn),
                                              True)
                n_cols = max(n_cols, mf + 1)
        header_line = ""
        if has_header:
            with open(first, encoding="utf-8-sig") as f:
                header_line = _clean_line(f.readline())
        chunks, sizes = [], {}
        for i, fn in enumerate(files):
            p = os.path.join(source, fn)
            sz = os.path.getsize(p)
            sizes[fn] = sz
            chunks.append(ChunkPlan(i, p, 0, sz, -1, -1))
        return IngestPlan(source, fmt, has_header, label_column,
                          chunk_rows, n_cols, header_line, chunks, sizes)

    fmt = fmt or detect_format(source, has_header)
    offsets, size, max_feat = _scan_line_offsets(source, fmt == "libsvm")
    header_line = ""
    if has_header and offsets:
        with open(source, encoding="utf-8-sig") as f:
            header_line = _clean_line(f.readline())
        offsets = offsets[1:]
    chunks = []
    for i, lo in enumerate(range(0, len(offsets), chunk_rows)):
        rows = min(chunk_rows, len(offsets) - lo)
        end = (offsets[lo + rows] if lo + rows < len(offsets) else size)
        chunks.append(ChunkPlan(i, source, offsets[lo], end, lo, rows))
    if not chunks:
        raise IngestError(f"ingest source {source!r} has no data rows")
    return IngestPlan(source, fmt, has_header, label_column, chunk_rows,
                      max_feat + 1 if fmt == "libsvm" else -1,
                      header_line, chunks,
                      {os.path.basename(source): size})


# ---------------------------------------------------------------------------
# Deterministic spool container (.lgc): no zip timestamps -> stable sha
# ---------------------------------------------------------------------------

def _spool_encode(x: np.ndarray, y: Optional[np.ndarray]) -> bytes:
    segs = []
    for arr in (x, y):
        if arr is None:
            segs.append(b"")
            continue
        buf = io.BytesIO()
        np.save(buf, np.ascontiguousarray(arr), allow_pickle=False)
        segs.append(buf.getvalue())
    out = [_SPOOL_MAGIC]
    for s in segs:
        out.append(len(s).to_bytes(8, "little"))
        out.append(s)
    return b"".join(out)


def _spool_decode(blob: bytes) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    if blob[:len(_SPOOL_MAGIC)] != _SPOOL_MAGIC:
        raise IngestError("spool container magic mismatch")
    pos = len(_SPOOL_MAGIC)
    arrs: List[Optional[np.ndarray]] = []
    for _ in range(2):
        n = int.from_bytes(blob[pos:pos + 8], "little")
        pos += 8
        if n == 0:
            arrs.append(None)
        else:
            arrs.append(np.load(io.BytesIO(blob[pos:pos + n]),
                                allow_pickle=False))
            pos += n
    assert arrs[0] is not None
    return arrs[0], arrs[1]


# ---------------------------------------------------------------------------
# The runner
# ---------------------------------------------------------------------------

def _sha256(data: bytes) -> str:
    from .snapshot import sha256_hex
    return sha256_hex(data)


class IngestRunner:
    """Drives one source through the chunk pipeline (module docstring).

    ``cfg`` is duck-typed on the ``ingest_*`` config params plus the
    binning surface ``fit_bin_mappers`` needs; ``tracer`` (obs/trace)
    adds ``ingest.chunk`` spans when telemetry is on."""

    def __init__(self, source: str, cfg, spool_dir: str = "",
                 has_header: bool = False, label_column: str = "",
                 tracer=None):
        self.source = source
        self.cfg = cfg
        self.has_header = has_header
        self.label_column = label_column
        self.tracer = tracer
        self.spool_dir = (spool_dir or getattr(cfg, "ingest_dir", "")
                          or (source.rstrip("/\\") + ".ingest"))
        self._retry_policy = RetryPolicy(
            max_attempts=1 + int(cfg.ingest_retries),
            base_delay_s=float(cfg.ingest_retry_backoff_s),
            max_delay_s=max(1.0, float(cfg.ingest_retry_backoff_s) * 8))

    # -- paths -------------------------------------------------------------
    def _spool_path(self, i: int) -> str:
        return os.path.join(self.spool_dir, f"chunk_{i:06d}.lgc")

    def _manifest_path(self, i: int) -> str:
        return os.path.join(self.spool_dir, f"chunk_{i:06d}.manifest.json")

    def _run_manifest_path(self) -> str:
        return os.path.join(self.spool_dir, "run.json")

    # -- plan / resume ------------------------------------------------------
    def _load_or_make_plan(self) -> Tuple[IngestPlan, bool]:
        """(plan, resumable): the spool is resumable only when its
        ``run.json`` matches the freshly computed plan signature —
        changed chunking, source size or label column invalidates every
        spooled chunk (they were cut along different byte spans)."""
        plan = plan_chunks(self.source, int(self.cfg.ingest_chunk_rows),
                           self.has_header, None, self.label_column)
        rm = self._run_manifest_path()
        resumable = False
        if bool(self.cfg.ingest_resume) and os.path.exists(rm):
            try:
                with open(rm, encoding="utf-8") as f:
                    old = json.load(f)
                resumable = old == plan.signature()
            except (OSError, ValueError):
                resumable = False
            if not resumable:
                Log.warning(
                    f"ingest: spool {self.spool_dir} belongs to a "
                    "different plan (source/params changed); re-ingesting")
        if not resumable:
            # stale spool entries must not satisfy a future resume probe
            if os.path.isdir(self.spool_dir):
                for fn in os.listdir(self.spool_dir):
                    if fn.startswith("chunk_"):
                        try:
                            os.unlink(os.path.join(self.spool_dir, fn))
                        except OSError:
                            pass
            atomic_write(self._run_manifest_path(),
                         json.dumps(plan.signature(), indent=1,
                                    sort_keys=True))
        return plan, resumable

    def _try_resume_chunk(self, plan: ChunkPlan
                          ) -> Optional[Tuple[np.ndarray,
                                              Optional[np.ndarray]]]:
        """Load a chunk from its verified spool, or None.  Trust order
        is manifest-last: no manifest (or an unparsable one) means the
        chunk never committed; a manifest whose spool sha disagrees
        means torn spool debris — both re-ingest from source."""
        mp, sp = self._manifest_path(plan.index), self._spool_path(plan.index)
        try:
            with open(mp, encoding="utf-8") as f:
                man = json.load(f)
            with open(sp, "rb") as f:
                blob = f.read()
        except (OSError, ValueError):
            return None
        if man.get("format") != _FORMAT \
                or man.get("spool_sha256") != _sha256(blob):
            Log.warning(f"ingest: chunk {plan.index} spool fails its "
                        "manifest checksum; re-reading from source")
            return None
        try:
            return _spool_decode(blob)
        except (IngestError, ValueError):
            return None

    # -- read + parse (the retried, deadline-guarded stage) ----------------
    def _read_raw(self, plan: ChunkPlan) -> bytes:
        faultinject.check("ingest_read")
        faultinject.check("ingest_hang")
        with open(plan.path, "rb") as f:
            f.seek(plan.byte_start)
            return f.read(plan.byte_end - plan.byte_start)

    def _read_and_parse(self, plan: IngestPlan, cp: ChunkPlan, label_idx
                        ) -> Tuple[np.ndarray, Optional[np.ndarray], bytes]:
        timeout = float(self.cfg.ingest_read_timeout_s)
        wd = Watchdog(timeout, label=f"ingest chunk {cp.index}",
                      on_timeout="raise")
        raw = wd.run(self._read_raw, cp)
        _metrics().counter("ingest.bytes_read").inc(len(raw))
        try:
            # ingest_checksum models DATA corruption, not infra flakiness:
            # surface it as ChunkCorrupt so the retry loop won't re-read
            # (re-reading corrupt bytes yields the same corrupt bytes)
            faultinject.check("ingest_checksum")
        except faultinject.InjectedFault as e:
            raise ChunkCorrupt(cp.index, str(e)) from None
        first_lineno = (cp.row_start + (2 if plan.has_header else 1)
                        if cp.row_start >= 0 else 1)
        text = raw.decode("utf-8-sig", errors="strict")
        lines = text.splitlines()
        if cp.byte_start == 0 and plan.has_header and cp.rows < 0:
            lines = lines[1:]       # directory chunk carrying a header
        if plan.fmt == "libsvm":
            x, y = parse_libsvm_block(
                lines, path=cp.path, first_lineno=first_lineno,
                n_cols=plan.n_cols if plan.n_cols > 0 else None)
            return x, y, raw
        delim = "\t" if plan.fmt == "tsv" else ","
        data = parse_csv_block(lines, delim, path=cp.path,
                               first_lineno=first_lineno)
        if data.shape[1] < 2 or label_idx is None:
            return data, None, raw
        y = data[:, label_idx].astype(np.float32)
        x = np.delete(data, label_idx, axis=1)
        return x, y, raw

    def _label_idx(self, plan: IngestPlan) -> Optional[int]:
        if plan.fmt == "libsvm":
            return None
        lc = plan.label_column
        if lc.startswith("name:"):
            if not plan.has_header:
                raise IngestError(
                    "label_column by name requires header=true")
            delim = "\t" if plan.fmt == "tsv" else ","
            names = plan.header_line.rstrip(delim).split(delim)
            return names.index(lc[5:])
        return int(lc) if lc else 0

    # -- quarantine --------------------------------------------------------
    def _quarantine(self, cp: ChunkPlan, raw: Optional[bytes],
                    reason: str) -> None:
        qdir = os.path.join(self.spool_dir, "quarantine")
        os.makedirs(qdir, exist_ok=True)
        meta = {"chunk": cp.index, "path": cp.path,
                "byte_start": cp.byte_start, "byte_end": cp.byte_end,
                "reason": reason}
        if raw is not None:
            atomic_write(os.path.join(qdir, f"chunk_{cp.index:06d}.bin"),
                         raw, binary=True)
        atomic_write(os.path.join(qdir, f"chunk_{cp.index:06d}.json"),
                     json.dumps(meta, indent=1, sort_keys=True))
        blackbox.dump_all(f"ingest:quarantine:chunk{cp.index}")
        _metrics().counter("ingest.chunks", outcome="quarantined").inc()
        Log.warning(f"ingest: chunk {cp.index} quarantined ({reason}) "
                    f"-> {qdir}")

    # -- the run -----------------------------------------------------------
    def run(self, categorical_idx: Optional[set] = None) -> IngestResult:
        t_run = time.monotonic()
        plan, resumable = self._load_or_make_plan()
        label_idx = self._label_idx(plan)
        cat_idx = categorical_idx or set()
        sketches: List[QuantileSketch] = []
        reports: List[ChunkReport] = []
        chunk_meta: List[Tuple[str, int]] = []   # (spool path, rows)
        dropped = resumed = 0
        n_features = -1
        bad_policy = str(self.cfg.ingest_bad_chunk)

        from .parallel import elastic

        for cp in plan.chunks:
            t0 = time.monotonic()
            if elastic.current() is not None:
                # a peer that died mid-ingest surfaces at the next
                # chunk boundary as a classified host_loss
                elastic.check_peers()
            x = y = raw = None
            outcome = "ok"
            retries = 0
            if resumable:
                loaded = self._try_resume_chunk(cp)
                if loaded is not None:
                    x, y = loaded
                    outcome = "resumed"
                    resumed += 1
            if x is None:
                def _on_retry(_a, _d, _e):
                    nonlocal retries
                    retries += 1
                    _metrics().counter("ingest.retries").inc()
                try:
                    x, y, raw = retry_call(
                        self._read_and_parse, plan, cp, label_idx,
                        policy=self._retry_policy,
                        # corruption is never transient, whatever its
                        # message says — only infra errors are retried
                        classify=lambda e: (
                            not isinstance(e, ChunkCorrupt)
                            and is_retryable_device_error(e)),
                        on_retry=_on_retry,
                        label=f"ingest chunk {cp.index}")
                except ChunkCorrupt as e:
                    x = e
                except ValueError as e:
                    # parse failure: corrupt, not transient
                    x = ChunkCorrupt(cp.index, f"parse failure: {e}")
                except faultinject.InjectedFault as e:
                    # retry budget exhausted on a transient-classified
                    # fault: infra failure, not data corruption
                    raise elastic.ElasticFailure(
                        "ingest", f"chunk {cp.index} read failed after "
                        f"{self._retry_policy.max_attempts} attempts: "
                        f"{e}") from e
                except Exception as e:
                    if is_retryable_device_error(e):
                        raise elastic.ElasticFailure(
                            "ingest", f"chunk {cp.index} read failed "
                            f"after {self._retry_policy.max_attempts} "
                            f"attempts: {e}") from e
                    x = ChunkCorrupt(cp.index, str(e))
                if not isinstance(x, ChunkCorrupt) \
                        and cp.rows >= 0 and len(x) != cp.rows:
                    x = ChunkCorrupt(
                        cp.index, f"row-count drift: plan {cp.rows}, "
                        f"parsed {len(x)}")
            if isinstance(x, ChunkCorrupt):
                self._quarantine(cp, raw, x.reason)
                reports.append(ChunkReport(cp.index, max(cp.rows, 0),
                                           "quarantined", retries,
                                           x.reason))
                if bad_policy == "raise":
                    raise x
                dropped += max(cp.rows, 0)
                _metrics().counter("ingest.rows_dropped").inc(
                    max(cp.rows, 0))
                continue
            if n_features < 0:
                n_features = x.shape[1]
                cap = int(self.cfg.ingest_sketch_size)
                sketches = [QuantileSketch(cap, categorical=(f in cat_idx))
                            for f in range(n_features)]
            elif x.shape[1] != n_features:
                self._quarantine(
                    cp, raw, f"feature-count drift: expected "
                    f"{n_features}, got {x.shape[1]}")
                reports.append(ChunkReport(cp.index, len(x),
                                           "quarantined", retries,
                                           "feature-count drift"))
                if bad_policy == "raise":
                    raise ChunkCorrupt(cp.index, "feature-count drift")
                dropped += len(x)
                _metrics().counter("ingest.rows_dropped").inc(len(x))
                continue
            if outcome != "resumed":
                blob = _spool_encode(x, y)
                atomic_write(self._spool_path(cp.index), blob,
                             binary=True)
                man = {"format": _FORMAT, "chunk": cp.index,
                       "source": cp.path, "byte_start": cp.byte_start,
                       "byte_end": cp.byte_end, "row_start": cp.row_start,
                       "rows": int(len(x)),
                       "raw_sha256": _sha256(raw),
                       "spool_sha256": _sha256(blob)}
                # manifest LAST: its presence marks a complete chunk
                atomic_write(self._manifest_path(cp.index),
                             json.dumps(man, indent=1, sort_keys=True))
            span = (self.tracer.span("ingest.chunk", index=cp.index,
                                     rows=len(x))
                    if self.tracer is not None else None)
            for f, sk in enumerate(sketches):
                sk.update(x[:, f])
            if span is not None:
                span.end()
            chunk_meta.append((self._spool_path(cp.index), int(len(x))))
            reports.append(ChunkReport(cp.index, int(len(x)), outcome,
                                       retries))
            _metrics().counter("ingest.chunks", outcome=outcome).inc()
            _metrics().counter("ingest.rows").inc(len(x))
            _metrics().histogram("ingest.chunk_s").observe(
                time.monotonic() - t0)

        if n_features < 0:
            raise IngestError(
                f"ingest of {self.source!r}: every chunk quarantined")
        seq = SpooledChunkSequence(chunk_meta)
        label = seq.gather_labels()
        total = sum(r for _, r in chunk_meta)
        _metrics().gauge("ingest.run_s").set(time.monotonic() - t_run)
        Log.info(f"ingest: {total} rows / {len(chunk_meta)} chunks from "
                 f"{self.source} ({resumed} resumed, {dropped} rows "
                 f"dropped)")
        return IngestResult(sketches, seq, label, total, n_features,
                            dropped, reports, self.spool_dir, resumed)


# ---------------------------------------------------------------------------
# Spooled chunks as a dataset.Sequence (streaming construction)
# ---------------------------------------------------------------------------

class SpooledChunkSequence(DatasetSequence):
    """Random row access over the spooled chunks — a
    ``dataset.Sequence``, so ``Dataset`` routes it through the
    streaming ``_construct_from_seqs`` path.  At most ONE decoded chunk
    is resident; sequential access (the construction scan) decodes each
    spool file exactly once."""

    def __init__(self, chunk_meta: List[Tuple[str, int]]):
        self._meta = list(chunk_meta)
        self._bounds = np.concatenate(
            [[0], np.cumsum([r for _, r in self._meta])]).astype(np.int64)
        self._cache_idx = -1
        self._cache: Optional[Tuple[np.ndarray, Optional[np.ndarray]]] = None
        self.batch_size = max(int(r) for _, r in self._meta) \
            if self._meta else 4096

    def __len__(self) -> int:
        return int(self._bounds[-1])

    def _chunk(self, ci: int) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        if ci != self._cache_idx:
            with open(self._meta[ci][0], "rb") as f:
                self._cache = _spool_decode(f.read())
            self._cache_idx = ci
        assert self._cache is not None
        return self._cache

    def _rows(self, gidx: np.ndarray) -> np.ndarray:
        ci = np.searchsorted(self._bounds, gidx, side="right") - 1
        out = None
        for c in np.unique(ci):
            x, _ = self._chunk(int(c))
            sel = ci == c
            if out is None:
                out = np.empty((len(gidx), x.shape[1]), np.float64)
            out[sel] = x[gidx[sel] - self._bounds[c]]
        assert out is not None
        return out

    def __getitem__(self, idx):
        if isinstance(idx, (int, np.integer)):
            return self._rows(np.asarray([int(idx)]))[0]
        if isinstance(idx, slice):
            gidx = np.arange(*idx.indices(len(self)))
        else:
            gidx = np.asarray(list(idx), dtype=np.int64)
        return self._rows(gidx)

    def gather_labels(self) -> Optional[np.ndarray]:
        """Concatenated per-chunk labels (float32 — tiny next to the
        raw features), or None when the source had no label column."""
        parts = []
        for ci in range(len(self._meta)):
            _, y = self._chunk(ci)
            if y is None:
                return None
            parts.append(y)
        return np.concatenate(parts) if parts else None


# ---------------------------------------------------------------------------
# Front door
# ---------------------------------------------------------------------------

def ingest_dataset(source: str, params: Optional[Dict[str, Any]] = None,
                   has_header: bool = False, label_column: str = "",
                   categorical_idx: Optional[set] = None,
                   spool_dir: str = "", tracer=None, reference=None):
    """Stream ``source`` (file or directory of chunks) into a
    ``Dataset``: chunked ingest -> merged sketches -> BinMappers ->
    streaming binned construction.  The full raw matrix never exists in
    memory; peak RSS is bounded by one chunk (bench.py's ``ingest``
    extras pin this).  With ``reference`` (a validation set binned
    against the training set) the reference's mappers are reused and no
    sketches are fitted."""
    from .config import Config
    from .dataset import Dataset
    cfg = Config(params or {})
    runner = IngestRunner(source, cfg, spool_dir=spool_dir,
                          has_header=has_header,
                          label_column=label_column, tracer=tracer)
    result = runner.run(categorical_idx=categorical_idx)
    mappers = (None if reference is not None
               else result.fit_bin_mappers(cfg, categorical_idx))
    ds = Dataset(result.sequence, label=result.label,
                 params=dict(params or {}), bin_mappers=mappers,
                 reference=reference)
    ds.ingest_report = {
        "num_rows": result.num_rows,
        "num_features": result.num_features,
        "dropped_rows": result.dropped_rows,
        "resumed_chunks": result.resumed_chunks,
        "quarantined": [dataclasses.asdict(r) for r in result.reports
                        if r.outcome == "quarantined"],
        "spool_dir": result.spool_dir,
    }
    return ds
