"""Serving circuit breaker: admission-time rejection while the device
side is failing.

Retry (``serve_retries``) protects ONE batch from a transient blip; the
breaker protects the SERVICE from a dependency that is actually down
(device wedged, backend gone — the round-5 outage shape).  Without it,
every incoming request queues, waits out the full retry schedule, and
fails — the bounded queue stays pinned at capacity doing work that
cannot succeed.  With it, ``serve_breaker_failures`` consecutive batch
failures open the circuit and submissions are rejected UP FRONT with
:class:`CircuitOpen` carrying a ``retry_after_ms`` hint (HTTP maps it
to 503 + ``Retry-After``); after ``serve_breaker_cooldown_ms`` the
circuit half-opens and admits probe traffic — one batch outcome decides
whether it closes or re-opens with a doubled cooldown (capped).

Only infrastructure-shaped failures count: a request's own bad input
(``ValueError`` family, ``LightGBMError`` shape checks, ``TypeError``)
fails that request alone and must never open the circuit for everyone
else.  The state machine itself is the generic
``utils/resilience.CircuitBreaker``; this module adds the serve
semantics — failure classification, metrics (``serve.breaker_state``
gauge: 0 closed / 1 half-open / 2 open, ``serve.breaker_opens`` /
``serve.breaker_rejected`` counters) and the typed admission error.
"""

from __future__ import annotations

from typing import Optional

from ..utils.resilience import CircuitBreaker

# failures that belong to one request, not to the serving substrate —
# they never move the breaker (LightGBMError subclasses ValueError)
_REQUEST_SCOPED = (ValueError, TypeError, KeyError, IndexError,
                   AttributeError, AssertionError, NotImplementedError)

_STATE_GAUGE = {CircuitBreaker.CLOSED: 0, CircuitBreaker.HALF_OPEN: 1,
                CircuitBreaker.OPEN: 2}


class CircuitOpen(RuntimeError):
    """Serving circuit is open; retry after ``retry_after_ms``."""

    def __init__(self, retry_after_ms: float, opens: int):
        super().__init__(
            f"serving circuit open (opened {opens}x); "
            f"retry in ~{retry_after_ms:.0f} ms")
        self.retry_after_ms = float(retry_after_ms)
        self.opens = int(opens)


class ServeBreaker:
    """The batcher-facing adapter around ``resilience.CircuitBreaker``.

    Lock contract (tools/analyze/check_races.py):
        _cb type: lightgbm_tpu/utils/resilience.py:CircuitBreaker

    Holds no lock of its own: every method is a pass-through to the
    breaker's internally-locked state machine (leaf-level — it never
    calls back into the batcher), plus ``_last_opens``, which only the
    worker thread's ``on_failure`` touches."""

    def __init__(self, failures: int = 5, cooldown_ms: float = 1000.0,
                 cooldown_max_ms: Optional[float] = None, metrics=None,
                 clock=None):
        if cooldown_max_ms is None:
            cooldown_max_ms = cooldown_ms * 16.0
        kw = {"clock": clock} if clock is not None else {}
        self._cb = CircuitBreaker(
            failure_threshold=failures,
            cooldown_s=cooldown_ms / 1e3,
            cooldown_max_s=cooldown_max_ms / 1e3, **kw)
        self.metrics = metrics
        self._last_opens = 0

    @property
    def enabled(self) -> bool:
        return self._cb.enabled

    def state(self) -> str:
        return self._cb.state()

    def check_admission(self) -> bool:
        """Raise :class:`CircuitOpen` while the circuit is open;
        otherwise admit, returning True when THIS request claimed the
        half-open probe slot (the batcher records it, and a probe that
        leaves the system without a batch outcome — deadline-shed,
        dropped at close — is handed back via :meth:`on_dropped` so the
        slot cannot wedge shut).  Called by ``MicroBatcher.submit`` as
        the LAST admission check before enqueue: still ahead of the
        queue (so rejected work never consumes capacity), but after
        every other rejection — a subsequent ``BacklogFull`` /
        ``DeadlineExceeded`` would leak the claimed probe.  The state
        gauge is updated only on rejections and batch outcomes (where
        transitions happen), keeping the common admitted path to one
        breaker lock acquisition."""
        admitted, probe = self._cb.try_acquire()
        if admitted:
            return probe
        if self.metrics is not None:
            self.metrics.counter("serve.breaker_rejected").inc()
        self._gauge()
        raise CircuitOpen(self._cb.retry_after_s() * 1e3, self._cb.opens)

    @staticmethod
    def counts(exc: BaseException) -> bool:
        """Whether a batch failure moves the breaker: infrastructure
        failures do, request-scoped input errors do not."""
        return not isinstance(exc, _REQUEST_SCOPED)

    def on_success(self) -> None:
        self._cb.record_success()
        self._gauge()

    def on_dropped(self) -> None:
        """An admitted probe request left the system without a batch
        outcome (deadline-shed before dispatch, dropped at close):
        release the slot so the next request probes immediately instead
        of a healthy device serving 503s for the whole abandoned-probe
        expiry."""
        self._cb.release_probe()
        self._gauge()

    def on_failure(self, exc: BaseException, probe: bool = False) -> None:
        if not self.counts(exc):
            # a request-scoped failure says nothing about the
            # infrastructure: a probe batch that dies of one must give
            # the slot back, not leave the circuit shut until expiry
            if probe:
                self.on_dropped()
            return
        self._cb.record_failure()
        if self.metrics is not None and self._cb.opens > self._last_opens:
            self.metrics.counter("serve.breaker_opens").inc(
                self._cb.opens - self._last_opens)
        self._last_opens = self._cb.opens
        self._gauge()

    def _gauge(self) -> None:
        if self.metrics is not None:
            self.metrics.gauge("serve.breaker_state").set(
                _STATE_GAUGE[self._cb.state()])

    def refresh_gauge(self) -> None:
        """Re-read the state into the gauge.  OPEN -> HALF_OPEN is a
        lazy clock transition with no event attached; a replica the LB
        stopped routing to would otherwise export ``open`` forever
        while /healthz (live describe) already says ``half_open`` —
        the metrics exporter calls this so dashboards and health can
        never disagree."""
        self._gauge()

    def describe(self) -> dict:
        return self._cb.describe()
