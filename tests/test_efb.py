"""EFB (exclusive feature bundling) tests — efb.py + dataset/grower wiring.

Mirrors the reference's EFB coverage (Dataset::FindGroups /
FastFeatureBundling, dataset.cpp:100, :239): bundling must be lossless at
max_conflict_rate=0, i.e. the trained model must match the unbundled run.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.efb import find_bundles, bin_grouped, unbundle


def _onehot_data(n=3000, n_dense=3, n_cats=12, seed=0):
    """Dense features + a mutually-exclusive one-hot block."""
    rs = np.random.RandomState(seed)
    dense = rs.randn(n, n_dense)
    cat = rs.randint(0, n_cats, size=n)
    onehot = np.zeros((n, n_cats))
    onehot[np.arange(n), cat] = 1.0
    x = np.column_stack([dense, onehot])
    y = (dense[:, 0] + (cat % 3 == 0) + 0.2 * rs.randn(n) > 0.5)
    return x, y.astype(np.float32)


class TestFindBundles:
    def test_exclusive_block_bundles(self):
        rs = np.random.RandomState(1)
        n, k = 500, 8
        cat = rs.randint(0, k, size=n)
        bins = np.zeros((n, k), np.int64)
        bins[np.arange(n), cat] = 1  # bin 1 = "one", bin 0 default
        efb = find_bundles(bins, np.full(k, 2), np.zeros(k, bool),
                           np.zeros(k, np.int64))
        assert efb.num_groups == 1
        assert efb.any_bundled
        # group bins: 1 + k * (2-1)
        assert efb.group_num_bin[0] == 1 + k

    def test_conflicting_features_not_bundled(self):
        rs = np.random.RandomState(2)
        bins = rs.randint(1, 5, size=(200, 3))  # dense, always non-default
        efb = find_bundles(bins, np.full(3, 5), np.zeros(3, bool),
                           np.zeros(3, np.int64))
        assert not efb.any_bundled

    def test_roundtrip_unbundle(self):
        rs = np.random.RandomState(3)
        n, k = 400, 6
        cat = rs.randint(0, k, size=n)
        bins = np.zeros((n, k), np.int64)
        bins[np.arange(n), cat] = 1 + (cat % 1)
        nb = np.full(k, 2)
        efb = find_bundles(bins, nb, np.zeros(k, bool), np.zeros(k, np.int64))
        grouped = bin_grouped(lambda j: bins[:, j], efb, n)
        back = unbundle(grouped, efb, nb)
        np.testing.assert_array_equal(back, bins)


class TestEFBTraining:
    def test_dataset_narrows(self):
        x, y = _onehot_data()
        ds = lgb.Dataset(x, label=y).construct()
        assert ds.efb is not None
        assert ds.binned.shape[1] < ds.num_features

    def test_lossless_vs_unbundled(self):
        x, y = _onehot_data()
        params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
                  "min_data_in_leaf": 20, "num_boost_round": 10}
        b1 = lgb.train({**params, "enable_bundle": True},
                       lgb.Dataset(x, label=y), num_boost_round=10)
        b2 = lgb.train({**params, "enable_bundle": False},
                       lgb.Dataset(x, label=y), num_boost_round=10)
        p1, p2 = b1.predict(x), b2.predict(x)
        np.testing.assert_allclose(p1, p2, rtol=1e-5, atol=1e-6)
        assert b1._model.train_set.efb is not None
        assert b2._model.train_set.efb is None

    def test_valid_and_early_stopping(self):
        x, y = _onehot_data(seed=5)
        ntr = 2400
        dtr = lgb.Dataset(x[:ntr], label=y[:ntr])
        dva = dtr.create_valid(x[ntr:], label=y[ntr:])
        bst = lgb.train({"objective": "binary", "num_leaves": 15,
                         "metric": "binary_logloss", "verbosity": -1},
                        dtr, num_boost_round=30, valid_sets=[dva],
                        callbacks=[lgb.early_stopping(5, verbose=False)])
        assert bst.best_iteration >= 1
        auc_in = np.mean((bst.predict(x[ntr:]) > 0.5) == y[ntr:])
        assert auc_in > 0.8

    def test_binary_cache_roundtrip(self, tmp_path):
        x, y = _onehot_data(seed=7)
        ds = lgb.Dataset(x, label=y).construct()
        assert ds.efb is not None
        path = str(tmp_path / "cache.bin")
        ds.save_binary(path)
        ds2 = lgb.Dataset.load_binary(path)
        assert ds2.efb is not None
        np.testing.assert_array_equal(ds2.binned, ds.binned)
        np.testing.assert_array_equal(ds2.efb.group_of_feat,
                                      ds.efb.group_of_feat)
        b1 = lgb.train({"objective": "binary", "verbosity": -1}, ds,
                       num_boost_round=5)
        b2 = lgb.train({"objective": "binary", "verbosity": -1}, ds2,
                       num_boost_round=5)
        np.testing.assert_allclose(b1.predict(x), b2.predict(x), rtol=1e-5)


class TestEFBMaskedLearner:
    """EFB on the masked (TPU-default) learner: group-space histograms +
    search-time expansion must be lossless (VERDICT round-1 gap: EFB was
    partitioned-only, leaving wide sparse data uncompressed on TPU)."""

    def test_lossless_vs_unbundled_masked(self):
        x, y = _onehot_data()
        params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
                  "min_data_in_leaf": 20, "tpu_learner": "masked"}
        b1 = lgb.train({**params, "enable_bundle": True},
                       lgb.Dataset(x, label=y), num_boost_round=10)
        b2 = lgb.train({**params, "enable_bundle": False},
                       lgb.Dataset(x, label=y), num_boost_round=10)
        assert b1._model._use_efb
        # measured width reduction of the device-resident matrix
        assert b1._model.binned_dev.shape[1] < x.shape[1]
        np.testing.assert_allclose(b1.predict(x), b2.predict(x),
                                   rtol=1e-5, atol=1e-6)

    def test_masked_matches_partitioned_with_efb(self):
        x, y = _onehot_data(seed=9)
        x = x.copy()
        x[::17, 0] = np.nan   # exercise the NaN bin through bundle decode
        params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
                  "min_data_in_leaf": 20, "enable_bundle": True}
        bm = lgb.train({**params, "tpu_learner": "masked"},
                       lgb.Dataset(x, label=y), num_boost_round=10)
        bp = lgb.train({**params, "tpu_learner": "partitioned"},
                       lgb.Dataset(x, label=y), num_boost_round=10)
        assert bm._model._use_efb and bp._model._use_efb
        np.testing.assert_allclose(bm.predict(x), bp.predict(x),
                                   rtol=1e-4, atol=1e-5)


def test_pigeonhole_skip_uses_bin0_occupancy_not_value_share():
    """The dense-data EFB skip (dataset.py pigeonhole pre-check) must
    bound the non-default rate with the EXACT bin-0 occupancy
    (BinMapper.bin0_frac).  1 - sparse_rate (the most frequent VALUE's
    share) under-counts a zero bin that merged several distinct values
    and would silently disable real bundles (code-review r4)."""
    rng = np.random.RandomState(5)
    n = 6000
    a = np.zeros(n)
    b = np.zeros(n)
    half = n // 2
    a[:half] = rng.rand(half) + 0.5
    # extra near-zero distinct values so bin 0 merges several values and
    # the most-frequent-value share understates its occupancy
    a[half:half + 600] = rng.choice([1e-35, 0.0], 600)
    b[half:] = rng.rand(half) + 0.5
    x = np.column_stack([a, b, rng.randn(n)])
    y = (a + b > 1.0).astype(np.float32)
    ds = lgb.Dataset(x, label=y, params={"max_bin": 15, "verbosity": -1})
    ds.construct()
    assert ds.efb is not None
    assert any(len(g) == 2 for g in ds.efb.groups), \
        f"mutually exclusive pair must bundle: {ds.efb.groups}"


def test_pigeonhole_skip_fires_on_dense(monkeypatch):
    """Dense wide data provably cannot bundle: the pre-check must skip
    the whole conflict-sampling pass (no second value_to_bin sweep)."""
    import lightgbm_tpu.efb as efb_mod
    called = []
    orig = efb_mod.find_bundles
    monkeypatch.setattr(efb_mod, "find_bundles",
                        lambda *a, **k: called.append(1) or orig(*a, **k))
    import lightgbm_tpu.dataset as ds_mod
    monkeypatch.setattr(ds_mod, "find_bundles", efb_mod.find_bundles)
    rng = np.random.RandomState(6)
    x = rng.standard_normal((3000, 20))
    ds = lgb.Dataset(x, label=(x[:, 0] > 0).astype(np.float32),
                     params={"max_bin": 31, "verbosity": -1})
    ds.construct()
    assert ds.efb is None
    assert not called, "conflict sampling ran despite the pigeonhole skip"
