"""Cluster orchestration: the reference's Dask-layer analog, TPU-shaped.

The reference orchestrates multi-machine training from Python with
dask.py (/root/reference/python-package/lightgbm/dask.py:393-810
``_train``: find each worker's data parts, allocate one port per worker
machine, build the ``machines=ip1:port1,ip2:port2`` parameter, then run
one trainer per worker wired through ``LGBM_NetworkInit``).  A TPU
cluster's unit of scheduling is a process per host over a device mesh,
so the analog here has two halves:

- :func:`run` — the *launcher* (dask._train's port-allocation and
  process bring-up role, shaped like torchrun): spawns N coordinated
  worker processes on this machine (or emits the per-host command lines
  for a real multi-host cluster), each bootstrapped through
  ``parallel.launch.init`` with the machines-parameter conventions.
- :func:`train` — the *per-worker trainer* (dask._train_part's role):
  an SPMD entry every process calls identically; it shards rows, fits
  globally-consistent bin mappers (sharded FindBin + allgather,
  parallel/dist_data.py), constructs the local Dataset and trains with
  ``tree_learner=data`` over the global mesh.  On a TPU pod slice, call
  :func:`train` directly from your per-host script — the JAX runtime is
  the launcher there.

Worker functions are addressed as ``"module:function"`` (the launcher
re-imports them in each spawned process), receive a
:class:`WorkerContext` and may return any picklable result;
:func:`run` returns the per-rank results rank-ordered.
"""

from __future__ import annotations

import os
import pickle
import socket
import subprocess
import sys
import tempfile
import time
from typing import Any, List, NamedTuple, Optional

import numpy as np


class WorkerContext(NamedTuple):
    """What every spawned worker receives (dask.py passes the same facts
    through its closure: rank via worker address, machines string,
    listen port)."""
    rank: int
    num_workers: int
    machines: str            # "host1:port1,host2:port2" (config.h machines)
    local_listen_port: int


def _free_ports(n: int) -> List[int]:
    """Allocate n distinct free localhost ports (dask.py:_find_n_open_ports
    role)."""
    socks, ports = [], []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
    finally:
        for s in socks:
            s.close()
    return ports


def build_machines(hosts: List[str], ports: List[int]) -> str:
    """The reference ``machines`` parameter (config.h; dask.py:700)."""
    return ",".join(f"{h}:{p}" for h, p in zip(hosts, ports))


def run(entry: str, num_workers: int = 2, *,
        hosts: Optional[List[str]] = None,
        base_port: Optional[int] = None,
        backend: str = "cpu",
        args: Any = None,
        timeout: int = 600,
        extra_pythonpath: Optional[List[str]] = None) -> List[Any]:
    """Spawn ``num_workers`` coordinated training processes on this
    machine and return their results rank-ordered.

    entry: ``"module:function"`` — imported in each worker; called as
      ``function(ctx)`` or ``function(ctx, args)`` when ``args`` given.
    hosts: one entry per worker for a REAL cluster (the function then
      only prints the per-host command lines — a cluster scheduler, not
      this process, must start them); default localhost spawning.
    backend: "cpu" pins workers to the CPU backend with gloo collectives
      (the test topology; also what the reference's distributed tests
      do over localhost sockets); "" leaves device selection to JAX
      (TPU pod workers).
    """
    if hosts is not None and set(hosts) - {"127.0.0.1", "localhost"}:
        ports = [base_port or 12400] * len(hosts)
        machines = build_machines(hosts, ports)
        lines = [
            f"{sys.executable} -m lightgbm_tpu.distributed "
            f"--entry {entry} --rank {i} --num-workers {len(hosts)} "
            f"--machines {machines}" for i in range(len(hosts))]
        raise SystemExit(
            "multi-host cluster: start one process per host:\n  "
            + "\n  ".join(lines))

    ports = _free_ports(num_workers)
    machines = build_machines(["127.0.0.1"] * num_workers, ports)
    tmp = tempfile.mkdtemp(prefix="lgbm_tpu_dist_")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)           # worker sets its own device count
    if extra_pythonpath:
        env["PYTHONPATH"] = os.pathsep.join(
            list(extra_pythonpath) + [env.get("PYTHONPATH", "")])
    args_path = ""
    if args is not None:
        args_path = os.path.join(tmp, "args.pkl")
        with open(args_path, "wb") as f:
            pickle.dump(args, f)

    # worker output goes to FILES, not pipes: the workers run coordinated
    # collectives, so blocking on one worker's full pipe buffer would
    # stall its collectives and deadlock the whole cluster
    procs, logs = [], []
    for rank in range(num_workers):
        cmd = [sys.executable, "-m", "lightgbm_tpu.distributed",
               "--entry", entry, "--rank", str(rank),
               "--num-workers", str(num_workers),
               "--machines", machines,
               "--result", os.path.join(tmp, f"r{rank}.pkl"),
               "--backend", backend]
        if args_path:
            cmd += ["--args", args_path]
        log = open(os.path.join(tmp, f"r{rank}.log"), "w+")
        logs.append(log)
        procs.append(subprocess.Popen(cmd, env=env, stdout=log,
                                      stderr=subprocess.STDOUT, text=True))
    deadline = time.monotonic() + timeout
    try:
        for p in procs:
            p.wait(timeout=max(deadline - time.monotonic(), 1.0))
    except subprocess.TimeoutExpired:
        for q in procs:
            q.kill()
        raise
    outs = []
    for log in logs:
        log.flush()
        log.seek(0)
        outs.append(log.read())
        log.close()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            raise RuntimeError(
                f"worker {rank} failed (rc={p.returncode}):\n{out[-3000:]}")
    results = []
    for rank in range(num_workers):
        with open(os.path.join(tmp, f"r{rank}.pkl"), "rb") as f:
            results.append(pickle.load(f))
    return results


def train(params: dict, x: np.ndarray, y: Optional[np.ndarray] = None, *,
          weight: Optional[np.ndarray] = None,
          num_boost_round: int = 100,
          shard_rows: bool = True,
          sample_count: int = 200_000,
          valid: Optional[tuple] = None):
    """SPMD per-worker trainer (dask.py:_train_part analog): every
    process calls this identically; returns the (replicated) Booster.

    params may carry the reference's network parameters — ``machines`` +
    ``local_listen_port`` (config.h) — in which case the network is
    initialized here exactly like ``LGBM_NetworkInit``; under :func:`run`
    or on an already-initialized pod that step is a no-op.

    shard_rows: x/y are the GLOBAL arrays and each process keeps its
    contiguous shard (dataset_loader.cpp:203-298 per-rank partition);
    pass False when each process loaded only its own rows already.
    """
    from . import Dataset, train as _engine_train
    from .config import Config
    from .parallel import launch

    p = dict(params)
    machines = str(p.pop("machines", "") or "")
    port = int(p.pop("local_listen_port", 12400) or 12400)
    if machines and not getattr(launch.init, "_done", False):
        launch.init(machines=machines, local_listen_port=port)

    import jax
    pc = jax.process_count()
    if pc > 1:
        p.setdefault("num_machines", pc)
        p.setdefault("tree_learner", "data")
        if shard_rows:
            sh = launch.row_shard(x, y)
            if weight is not None:
                # same deterministic contiguous partition as row_shard
                parts = np.array_split(np.arange(len(x)), pc)
                weight = np.asarray(weight)[parts[sh.process_index]]
        else:
            sh = launch.RowShard(x=x, y=y,
                                 process_index=jax.process_index(),
                                 process_count=pc)
        cfg = Config(dict(p, num_iterations=num_boost_round))
        cat_spec = str(getattr(cfg, "categorical_feature", "") or "")
        cat = {int(t) for t in cat_spec.split(",") if t.strip().isdigit()} \
            or None
        mappers = launch.global_bin_mappers(sh.sample(sample_count), cfg,
                                            cat_idx=cat)
        ds = Dataset(sh.x, label=sh.y, weight=weight, params=p,
                     bin_mappers=mappers)
    else:
        ds = Dataset(x, label=y, weight=weight, params=p)
    kw = {}
    if valid is not None:
        vx, vy = valid
        kw["valid_sets"] = [Dataset(vx, label=vy, params=p, reference=ds)]
    return _engine_train(p, ds, num_boost_round=num_boost_round, **kw)


def _main(argv: List[str]) -> None:
    """Worker bootstrap (what ``run`` spawns): init the collective
    runtime BEFORE any backend exists, then hand control to the entry."""
    import argparse
    ap = argparse.ArgumentParser(prog="python -m lightgbm_tpu.distributed")
    ap.add_argument("--entry", required=True)
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--num-workers", type=int, required=True)
    ap.add_argument("--machines", required=True)
    ap.add_argument("--result", default="")
    ap.add_argument("--args", default="")
    ap.add_argument("--backend", default="cpu")
    ns = ap.parse_args(argv)

    if ns.backend == "cpu":
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        import jax
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_cpu_collectives_implementation", "gloo")

    from .parallel import launch
    entries = [m for m in ns.machines.split(",") if m]
    launch.init(coordinator_address=entries[0],
                num_processes=ns.num_workers, process_id=ns.rank)

    mod_name, fn_name = ns.entry.split(":")
    import importlib
    fn = getattr(importlib.import_module(mod_name), fn_name)
    ctx = WorkerContext(rank=ns.rank, num_workers=ns.num_workers,
                        machines=ns.machines,
                        local_listen_port=int(
                            entries[ns.rank].rsplit(":", 1)[1]))
    if ns.args:
        with open(ns.args, "rb") as f:
            result = fn(ctx, pickle.load(f))
    else:
        result = fn(ctx)
    if ns.result:
        with open(ns.result, "wb") as f:
            pickle.dump(result, f)


if __name__ == "__main__":
    _main(sys.argv[1:])
