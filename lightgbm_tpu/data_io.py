"""Text data loading: CSV / TSV / LibSVM with auto-detection.

Analog of the reference Parser layer
(/root/reference/src/io/parser.hpp:18-93 CSVParser/TSVParser/LibSVMParser +
``Parser::CreateParser`` auto-detect, src/io/parser.cpp).  A native C++
fast path (lightgbm_tpu/native/parser.cpp, loaded via ctypes) accelerates
large files; this module is the API and NumPy fallback.

Real-world file tolerance (the reference's Atof/line handling is just as
forgiving): UTF-8 BOM prefixes, CRLF line endings and trailing-delimiter
rows all parse identically to their clean equivalents, and a malformed
line reports the FILE and 1-based LINE NUMBER instead of a bare numpy
conversion error.  The block parsers (:func:`parse_csv_block`,
:func:`parse_libsvm_block`) are the shared substrate: ``load_text`` runs
them over whole files, the streaming ingest pipeline
(lightgbm_tpu/ingest.py) runs them over byte-span chunks.
"""

from __future__ import annotations

import codecs
import os
from typing import List, Optional, Tuple

import numpy as np

from .native import native_parse_csv


_PARSER_REGISTRY = {}

# UTF-8 byte-order mark, both as bytes (sniffing) and decoded (lines)
_BOM_BYTES = codecs.BOM_UTF8
_BOM_CHAR = "﻿"


def register_parser(name: str, fn) -> None:
    """Pluggable custom parsers (``ParserFactory`` analog, parser.hpp:93 /
    dataset.h:304 ``CreateParser``): ``fn(path, has_header, label_column)``
    -> (features [N, F], label [N] or None).  Select with
    ``load_text(..., fmt=name)`` or the ``parser`` config key."""
    _PARSER_REGISTRY[name] = fn


def _clean_line(line: str, delim: Optional[str] = None) -> str:
    """One line as the parsers see it: newline (\\n or \\r\\n) stripped,
    BOM prefix dropped, trailing delimiters removed (the reference's
    CSV parser stops at end-of-line regardless of a dangling comma —
    ``1,2,3,`` must bin identically to ``1,2,3``)."""
    line = line.rstrip("\r\n")
    if line.startswith(_BOM_CHAR):
        line = line[len(_BOM_CHAR):]
    if delim:
        line = line.rstrip(delim)
    return line


def has_bom(path: str) -> bool:
    """Whether the file starts with a UTF-8 byte-order mark."""
    with open(path, "rb") as f:
        return f.read(len(_BOM_BYTES)) == _BOM_BYTES


def parse_csv_block(lines, delim: str, path: str = "<memory>",
                    first_lineno: int = 1,
                    n_cols: Optional[int] = None) -> np.ndarray:
    """Parse an iterable of CSV/TSV text lines -> float64 ``[n, F]``.

    Tolerates CRLF endings, a BOM on the first line and trailing
    delimiters; empty fields become NaN (the genfromtxt convention the
    previous fallback set).  Blank lines are skipped.  A malformed
    token or a row whose width disagrees with the block raises
    ``ValueError`` naming ``path`` and the 1-based line number
    (``first_lineno`` anchors blocks cut from mid-file by the streaming
    ingest reader)."""
    rows: List[List[float]] = []
    width = n_cols
    for off, raw in enumerate(lines):
        lineno = first_lineno + off
        line = _clean_line(raw, delim)
        if not line.strip():
            continue
        toks = line.split(delim)
        if width is None:
            width = len(toks)
        elif len(toks) != width:
            raise ValueError(
                f"{path}:{lineno}: expected {width} fields, got "
                f"{len(toks)}")
        vals = []
        for ci, t in enumerate(toks):
            t = t.strip()
            if not t or t.lower() in ("na", "nan", "null"):
                vals.append(np.nan)
                continue
            try:
                vals.append(float(t))
            except ValueError:
                raise ValueError(
                    f"{path}:{lineno}: malformed value {t!r} in column "
                    f"{ci}") from None
        rows.append(vals)
    if not rows:
        return np.empty((0, width or 0), np.float64)
    return np.asarray(rows, dtype=np.float64)


def parse_libsvm_block(lines, path: str = "<memory>",
                       first_lineno: int = 1,
                       n_cols: Optional[int] = None
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """Parse LibSVM text lines -> (dense features ``[n, F]``, labels
    ``[n]``).  ``n_cols`` forces the feature-space width (the streaming
    ingest reader pre-scans it so every chunk densifies congruently);
    None infers it from the block's max index.  Malformed tokens raise
    ``ValueError`` naming ``path`` and the 1-based line number."""
    labels, rows = [], []
    max_feat = (n_cols or 0) - 1
    for off, raw in enumerate(lines):
        lineno = first_lineno + off
        line = _clean_line(raw)
        toks = line.strip().split()
        if not toks:
            continue
        try:
            labels.append(float(toks[0]))
        except ValueError:
            raise ValueError(
                f"{path}:{lineno}: malformed label {toks[0]!r}") from None
        feats = {}
        for t in toks[1:]:
            if ":" not in t:
                continue
            k_s, v_s = t.split(":", 1)
            try:
                k, v = int(k_s), float(v_s)
            except ValueError:
                raise ValueError(
                    f"{path}:{lineno}: malformed feature {t!r}") from None
            if k < 0:
                raise ValueError(
                    f"{path}:{lineno}: negative feature index {k}")
            if n_cols is not None and k >= n_cols:
                raise ValueError(
                    f"{path}:{lineno}: feature index {k} >= declared "
                    f"width {n_cols}")
            feats[k] = v
            max_feat = max(max_feat, k)
        rows.append(feats)
    x = np.zeros((len(rows), max_feat + 1), np.float64)
    for i, feats in enumerate(rows):
        for k, v in feats.items():
            x[i, k] = v
    return x, np.asarray(labels, np.float32)


def detect_format(path: str, has_header: bool = False) -> str:
    """Sniff csv/tsv/libsvm from the first data line (parser.cpp
    auto-detect analog)."""
    with open(path, encoding="utf-8-sig") as f:
        line = f.readline()
        if has_header:
            line = f.readline()
    line = _clean_line(line)
    if ":" in line.split()[1] if len(line.split()) > 1 else False:
        return "libsvm"
    first_tokens = line.strip().split("\t")
    if len(first_tokens) > 1:
        return "tsv"
    if "," in line:
        return "csv"
    # space separated libsvm check: tokens after first contain ':'
    toks = line.strip().split()
    if len(toks) > 1 and all(":" in t for t in toks[1:3]):
        return "libsvm"
    return "csv"


def load_text(path: str, has_header: bool = False,
              label_column: str = "", fmt: Optional[str] = None
              ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Load a text data file -> (features [N, F], label [N] or None).

    Default label column is the first (reference convention,
    dataset_loader.cpp label_idx_=0).
    """
    fmt = fmt or detect_format(path, has_header)
    if fmt in _PARSER_REGISTRY:
        return _PARSER_REGISTRY[fmt](path, has_header, label_column)
    if fmt == "libsvm":
        return _load_libsvm(path)
    delim = "\t" if fmt == "tsv" else ","
    # the native fast path predates the BOM/CRLF/trailing-delimiter
    # tolerance contract — route marked files through the checked
    # Python parser so both paths produce identical arrays
    native = None if has_bom(path) else native_parse_csv(
        path, delim, has_header)
    if native is not None:
        data = native
        # the native parser maps UNPARSABLE tokens to NaN exactly like
        # legitimate missing values — audit NaN-bearing rows through the
        # strict parser so garbage reports path:lineno instead of
        # silently becoming missing data (dense files re-check nothing)
        nan_rows = np.unique(np.nonzero(np.isnan(data))[0])
        if nan_rows.size:
            with open(path, encoding="utf-8-sig") as f:
                lines = f.readlines()
            start = 1 if has_header else 0
            for r in nan_rows:
                parse_csv_block([lines[start + int(r)]], delim, path=path,
                                first_lineno=start + int(r) + 1)
    else:
        with open(path, encoding="utf-8-sig") as f:
            lines = f.readlines()
        start = 1 if has_header else 0
        data = parse_csv_block(lines[start:], delim, path=path,
                               first_lineno=start + 1)
        if data.ndim == 1:
            data = data.reshape(-1, 1)
    label_idx = 0
    if label_column.startswith("name:"):
        if not has_header:
            raise ValueError("label_column by name requires header=true")
        with open(path, encoding="utf-8-sig") as f:
            names = _clean_line(f.readline(), delim).split(delim)
        label_idx = names.index(label_column[5:])
    elif label_column:
        label_idx = int(label_column)
    if data.shape[1] < 2:
        return data, None
    y = data[:, label_idx].astype(np.float32)
    x = np.delete(data, label_idx, axis=1)
    return x, y


def _load_libsvm(path: str) -> Tuple[np.ndarray, np.ndarray]:
    with open(path, encoding="utf-8-sig") as f:
        return parse_libsvm_block(f.readlines(), path=path)
