"""Sync lint: flag raw host-sync calls in the library hot paths.

PROFILE.md measured ~67 ms per blocking host round trip on a tunneled
TPU — a stray ``jax.device_get`` / ``block_until_ready`` / ``.item()``
in the training path is a silent 60+ ms/iteration regression, and
``block_until_ready`` additionally *lies* on the axon backend (returns
with work still queued), so even intentional fences must go through
``obs.trace.fence``.  This lint keeps both properties true structurally:

- every raw sync call in ``lightgbm_tpu/`` (outside ``obs/trace.py``,
  the one module allowed to own the primitive) must be listed in
  ``tools/sync_allowlist.txt``;
- the allowlist pins (file, exact stripped source line), so MOVING a
  legitimate sync is cheap (re-pin) but ADDING one is a conscious act.

Comments and string literals are ignored (tokenize-based), so
documentation may mention the calls freely.

Run standalone (``python tools/check_syncs.py``; exit 1 on findings) or
via tier-1 (tests/test_observability.py calls ``find_raw_syncs``).
"""

from __future__ import annotations

import io
import os
import re
import sys
import tokenize
from typing import Dict, List, Set, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, "lightgbm_tpu")
ALLOWLIST = os.path.join(REPO, "tools", "sync_allowlist.txt")

# the module that owns the fence primitive; everything inside may sync
EXEMPT = {os.path.join("lightgbm_tpu", "obs", "trace.py")}

_SYNC_RE = re.compile(
    r"device_get\s*\(|block_until_ready\b|\.item\s*\(\s*\)")


def _code_lines(path: str) -> Dict[int, str]:
    """line number -> source line, with comment and string tokens
    blanked out so docs/docstrings never trigger the lint."""
    with open(path, "rb") as f:
        src = f.read()
    text = src.decode("utf-8")
    lines = text.splitlines()
    drop: List[Tuple[int, int, int, int]] = []
    try:
        for tok in tokenize.tokenize(io.BytesIO(src).readline):
            if tok.type in (tokenize.COMMENT, tokenize.STRING):
                drop.append((*tok.start, *tok.end))
    except tokenize.TokenError:
        pass                     # partial file: lint what parsed
    out = {i + 1: ln for i, ln in enumerate(lines)}
    for (r0, c0, r1, c1) in drop:
        for r in range(r0, r1 + 1):
            ln = out.get(r, "")
            a = c0 if r == r0 else 0
            b = c1 if r == r1 else len(ln)
            out[r] = ln[:a] + " " * (b - a) + ln[b:]
    return out


def load_allowlist(path: str = ALLOWLIST) -> Set[Tuple[str, str]]:
    """Entries are ``relative/path.py | exact stripped source line``."""
    out: Set[Tuple[str, str]] = set()
    try:
        with open(path) as f:
            for raw in f:
                raw = raw.strip()
                if not raw or raw.startswith("#"):
                    continue
                rel, _, line = raw.partition("|")
                out.add((rel.strip(), line.strip()))
    except OSError:
        pass
    return out


def find_raw_syncs(root: str = PACKAGE,
                   allowlist_path: str = ALLOWLIST) -> List[str]:
    """All unallowlisted raw sync call sites, as
    ``path:lineno: stripped line`` strings (empty list = lint green).
    Also reports allowlist entries that no longer match anything, so
    the list cannot rot."""
    allow = load_allowlist(allowlist_path)
    used: Set[Tuple[str, str]] = set()
    findings: List[str] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, REPO)
            if rel in EXEMPT:
                continue
            for lineno, code in sorted(_code_lines(path).items()):
                if not _SYNC_RE.search(code):
                    continue
                # the allowlist pins the ORIGINAL stripped line text
                with open(path) as f:
                    stripped = f.read().splitlines()[lineno - 1].strip()
                key = (rel, stripped)
                if key in allow:
                    used.add(key)
                    continue
                findings.append(f"{rel}:{lineno}: {stripped}")
    for key in sorted(allow - used):
        findings.append(f"stale allowlist entry (no matching line): "
                        f"{key[0]} | {key[1]}")
    return findings


def main() -> int:
    findings = find_raw_syncs()
    if findings:
        print("sync lint: raw device_get/block_until_ready/.item() "
              "outside obs.trace.fence:", file=sys.stderr)
        for f in findings:
            print(f"  {f}", file=sys.stderr)
        print(f"\n{len(findings)} finding(s).  Route fences through "
              "lightgbm_tpu.obs.trace.fence, or pin a genuinely "
              "necessary sync in tools/sync_allowlist.txt",
              file=sys.stderr)
        return 1
    print("sync lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
