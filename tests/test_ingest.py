"""Survivable out-of-core ingest (lightgbm_tpu/ingest.py) + the sketch
binning substrate (binning.QuantileSketch) + dist_data payload framing.

Pinned contracts:

- While a sketch never compacts (distinct values <= capacity) the
  sketch-fitted bin bounds are BYTE-IDENTICAL to in-memory FindBin over
  the same rows, and streaming-ingest training is byte-identical to
  in-memory training (the dense small-bin regime of docs/Ingest.md).
- After compaction each greedy boundary's rank displacement is bounded
  by 2*n*compactions/capacity (the documented sketch epsilon).
- A loader killed between chunk commits resumes from the manifests and
  trains a byte-identical model vs an uninterrupted run.
- Transient read errors retry; corrupt chunks quarantine per
  ``ingest_bad_chunk``; a wedged reader classifies as
  ``ElasticFailure("ingest")`` within the deadline; a torn allgather
  payload raises a classified PayloadIntegrityError, never raw
  unpickle behavior.

All fault specs go through ``faultinject.configure`` and are cleared by
the autouse fixture.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import ingest as ing
from lightgbm_tpu.binning import BinMapper, QuantileSketch
from lightgbm_tpu.config import Config
from lightgbm_tpu.data_io import load_text, parse_csv_block
from lightgbm_tpu.parallel import dist_data, elastic
from lightgbm_tpu.utils import faultinject
from lightgbm_tpu.utils.faultinject import InjectedKill

_WORKER = os.path.join(os.path.dirname(__file__), "ingest_worker.py")


@pytest.fixture(autouse=True)
def _clean_faults():
    faultinject.clear()
    ing.reset_metrics()
    yield
    faultinject.clear()


def _write_csv(path, x, y, fmt="%.6g"):
    with open(path, "w", encoding="utf-8") as f:
        for i in range(len(x)):
            f.write(",".join([f"{y[i]:g}"]
                             + [fmt % v for v in x[i]]) + "\n")


def _toy(n=1200, f=5, seed=3, decimals=None):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, f)
    x[::9, 1] = 0.0
    if decimals is not None:
        x = np.round(x, decimals)
    y = (x[:, 0] + 0.25 * rs.randn(n) > 0).astype(np.float64)
    return x, y


_PARAMS = {"objective": "binary", "num_leaves": 8, "max_bin": 31,
           "min_data_in_leaf": 5, "verbosity": -1,
           "ingest_chunk_rows": 200}


# ---------------------------------------------------------------------------
# QuantileSketch contracts
# ---------------------------------------------------------------------------

class TestSketch:
    def test_lossless_exact_vs_findbin(self):
        x, _ = _toy(n=3000)
        col = x[:, 0].copy()
        col[::11] = np.nan
        sk = QuantileSketch(4096)
        for i in range(0, len(col), 500):
            sk.update(col[i:i + 500])
        assert sk.compactions == 0
        exact = BinMapper()
        exact.find_bin(col, len(col), 255, 3)
        got = BinMapper()
        got.find_bin_from_sketch(sk, 255, 3)
        assert np.array_equal(exact.bin_upper_bound, got.bin_upper_bound)
        for attr in ("num_bin", "missing_type", "default_bin",
                     "most_freq_bin", "sparse_rate", "bin0_frac",
                     "is_trivial"):
            assert getattr(exact, attr) == getattr(got, attr), attr

    def test_compacted_rank_displacement_bound(self):
        rng = np.random.RandomState(7)
        n, cap = 30000, 512
        col = rng.lognormal(size=n)
        sk = QuantileSketch(cap)
        for i in range(0, n, 3000):
            sk.update(col[i:i + 3000])
        assert sk.compactions > 0
        exact = BinMapper()
        exact.find_bin(col, n, 63, 3)
        got = BinMapper()
        got.find_bin_from_sketch(sk, 63, 3)
        xs = np.sort(col)
        k = min(exact.num_bin, got.num_bin) - 1
        r_exact = np.searchsorted(xs, exact.bin_upper_bound[:k])
        r_got = np.searchsorted(xs, got.bin_upper_bound[:k])
        disp = int(np.abs(r_exact - r_got).max())
        # the documented epsilon (docs/Ingest.md): 2n/capacity rows per
        # compaction generation
        assert disp <= 2 * n * sk.compactions / cap

    def test_merge_equals_one_shot_and_is_deterministic(self):
        x, _ = _toy(n=4000)
        col = np.round(x[:, 2], 2)        # dense: stays lossless
        whole = QuantileSketch(2048).update(col)
        parts = [QuantileSketch(2048).update(c)
                 for c in np.array_split(col, 7)]
        merged = QuantileSketch(2048)
        for p in parts:
            merged.merge(p)
        assert np.array_equal(whole.values, merged.values)
        assert np.array_equal(whole.counts, merged.counts)
        assert whole.n == merged.n
        # deterministic under repetition (the fleet-wide rank-order
        # merge must be byte-stable)
        merged2 = QuantileSketch(2048)
        for p in parts:
            merged2.merge(p)
        assert np.array_equal(merged.values, merged2.values)
        assert np.array_equal(merged.counts, merged2.counts)

    def test_state_roundtrip_and_version_gate(self):
        sk = QuantileSketch(64).update(np.arange(200, dtype=np.float64))
        st = sk.to_state()
        back = QuantileSketch.from_state(st)
        assert np.array_equal(back.values, sk.values)
        assert back.compactions == sk.compactions
        st["version"] = 99
        with pytest.raises(ValueError, match="version"):
            QuantileSketch.from_state(st)

    def test_categorical_never_compacts(self):
        cats = np.repeat(np.arange(500, dtype=np.float64), 3)
        sk = QuantileSketch(64, categorical=True).update(cats)
        assert sk.compactions == 0
        uniq, counts = sk.categorical_counts()
        assert len(uniq) == 500 and counts.sum() == 1500


# ---------------------------------------------------------------------------
# Streaming ingest end-to-end
# ---------------------------------------------------------------------------

class TestIngestE2E:
    def test_dense_regime_byte_identical_model(self, tmp_path):
        x, y = _toy(decimals=1)
        path = str(tmp_path / "train.csv")
        _write_csv(path, x, y, fmt="%.1f")
        ds = lgb.ingest_dataset(path, _PARAMS)
        bst = lgb.train(_PARAMS, ds, num_boost_round=6)
        x2, y2 = load_text(path)
        bst2 = lgb.train(_PARAMS, lgb.Dataset(x2, label=y2,
                                              params=_PARAMS),
                         num_boost_round=6)
        assert bst.model_to_string() == bst2.model_to_string()
        assert ds.ingest_report["dropped_rows"] == 0
        snap = ing.metrics_snapshot()
        assert snap["ingest.chunks{outcome=ok}"]["value"] == 6

    def test_directory_of_chunks_source(self, tmp_path):
        x, y = _toy(n=900, decimals=1)
        d = tmp_path / "shards"
        d.mkdir()
        for i, (xc, yc) in enumerate(zip(np.array_split(x, 3),
                                         np.array_split(y, 3))):
            _write_csv(str(d / f"part-{i:03d}.csv"), xc, yc, fmt="%.1f")
        ds = lgb.Dataset.from_ingest(str(d), _PARAMS)
        bst = lgb.train(_PARAMS, ds, num_boost_round=4)
        x2, y2 = load_text(str(d / "part-000.csv"))
        assert bst.num_trees() == 4
        assert ds.ingest_report["num_rows"] == 900
        assert x2.shape[1] == x.shape[1]

    def test_in_process_resume_after_kill(self, tmp_path):
        x, y = _toy(decimals=1)
        path = str(tmp_path / "train.csv")
        _write_csv(path, x, y, fmt="%.1f")
        spool = str(tmp_path / "spool")
        # die at the 4th chunk read: 3 chunks committed manifest-last
        faultinject.configure("ingest_read:4:kill")
        with pytest.raises(InjectedKill):
            lgb.ingest_dataset(path, _PARAMS, spool_dir=spool)
        committed = [f for f in os.listdir(spool)
                     if f.endswith(".manifest.json")]
        assert len(committed) == 3
        faultinject.clear()
        ds = lgb.ingest_dataset(path, _PARAMS, spool_dir=spool)
        assert ds.ingest_report["resumed_chunks"] == 3
        bst = lgb.train(_PARAMS, ds, num_boost_round=5)
        clean = lgb.ingest_dataset(path, _PARAMS,
                                   spool_dir=str(tmp_path / "spool2"))
        bst2 = lgb.train(_PARAMS, clean, num_boost_round=5)
        assert bst.model_to_string() == bst2.model_to_string()

    def test_bounded_residency_one_chunk_in_flight(self, tmp_path):
        # the bounded-memory contract, structurally: however many chunks
        # the spool holds, the sequence keeps at most ONE decoded — RSS
        # cannot scale with chunk count (bench.py gates the measured MB)
        x, y = _toy(n=2000, decimals=1)
        path = str(tmp_path / "train.csv")
        _write_csv(path, x, y, fmt="%.1f")
        res = ing.IngestRunner(
            path, Config(dict(_PARAMS, ingest_chunk_rows=100))).run()
        seq = res.sequence
        assert len(seq._meta) == 20
        for gidx in (0, 150, 1999, 42):
            seq[gidx]
            assert seq._cache is not None
            assert len(seq._cache[0]) == 100     # one chunk, not the file
        # a cross-chunk slice still leaves a single chunk resident
        seq[180:220]
        assert len(seq._cache[0]) == 100

    def test_plan_change_invalidates_spool(self, tmp_path):
        x, y = _toy(n=600, decimals=1)
        path = str(tmp_path / "train.csv")
        _write_csv(path, x, y, fmt="%.1f")
        spool = str(tmp_path / "spool")
        lgb.ingest_dataset(path, _PARAMS, spool_dir=spool)
        p2 = dict(_PARAMS, ingest_chunk_rows=100)
        ds = lgb.ingest_dataset(path, p2, spool_dir=spool)
        # different chunking cuts different byte spans: nothing resumes
        assert ds.ingest_report["resumed_chunks"] == 0


# ---------------------------------------------------------------------------
# Failure policy: retry / quarantine / hang
# ---------------------------------------------------------------------------

class TestIngestFaults:
    def test_transient_read_error_retries(self, tmp_path):
        x, y = _toy(n=600, decimals=1)
        path = str(tmp_path / "train.csv")
        _write_csv(path, x, y, fmt="%.1f")
        faultinject.configure("ingest_read:2")   # 2nd read raises once
        ds = lgb.ingest_dataset(path, dict(_PARAMS, ingest_retries=2,
                                           ingest_retry_backoff_s=0.01),
                                spool_dir=str(tmp_path / "s"))
        assert ds.ingest_report["num_rows"] == 600
        assert ds.ingest_report["dropped_rows"] == 0
        snap = ing.metrics_snapshot()
        assert snap["ingest.retries"]["value"] >= 1

    def test_retry_exhaustion_classifies_as_elastic_ingest(self, tmp_path):
        x, y = _toy(n=600, decimals=1)
        path = str(tmp_path / "train.csv")
        _write_csv(path, x, y, fmt="%.1f")
        faultinject.configure("ingest_read:1-")   # every read fails
        with pytest.raises(elastic.ElasticFailure) as ei:
            lgb.ingest_dataset(path, dict(_PARAMS, ingest_retries=1,
                                          ingest_retry_backoff_s=0.01),
                               spool_dir=str(tmp_path / "s"))
        assert ei.value.kind == "ingest"
        assert elastic.failure_kind(ei.value) == "ingest"

    def test_corrupt_chunk_raise_policy(self, tmp_path):
        x, y = _toy(n=600, decimals=1)
        path = str(tmp_path / "train.csv")
        _write_csv(path, x, y, fmt="%.1f")
        faultinject.configure("ingest_checksum:2")
        with pytest.raises(ing.ChunkCorrupt):
            lgb.ingest_dataset(path, _PARAMS,
                               spool_dir=str(tmp_path / "s"))

    def test_corrupt_chunk_skip_policy_accounts_dropped_rows(
            self, tmp_path):
        x, y = _toy(n=600, decimals=1)
        path = str(tmp_path / "train.csv")
        _write_csv(path, x, y, fmt="%.1f")
        spool = str(tmp_path / "s")
        faultinject.configure("ingest_checksum:2")
        ds = lgb.ingest_dataset(path, dict(_PARAMS,
                                           ingest_bad_chunk="skip"),
                                spool_dir=spool)
        rep = ds.ingest_report
        assert rep["dropped_rows"] == 200          # one full chunk
        assert rep["num_rows"] == 400
        assert len(rep["quarantined"]) == 1
        assert rep["quarantined"][0]["index"] == 1
        qdir = os.path.join(spool, "quarantine")
        assert os.path.exists(
            os.path.join(qdir, "chunk_000001.json"))
        with open(os.path.join(qdir, "chunk_000001.json"),
                  encoding="utf-8") as f:
            assert "injected fault" in json.load(f)["reason"]
        # the degraded dataset still trains
        bst = lgb.train(_PARAMS, ds, num_boost_round=3)
        assert bst.num_trees() == 3

    def test_malformed_chunk_quarantines_not_retries(self, tmp_path):
        x, y = _toy(n=600, decimals=1)
        path = str(tmp_path / "train.csv")
        _write_csv(path, x, y, fmt="%.1f")
        with open(path, "a", encoding="utf-8") as f:
            f.write("1.0,not_a_number,0.1,0.2,0.3,0.4\n")
        with pytest.raises(ing.ChunkCorrupt, match="malformed"):
            lgb.ingest_dataset(path, _PARAMS,
                               spool_dir=str(tmp_path / "s"))

    def test_hang_classifies_within_deadline(self, tmp_path, monkeypatch):
        x, y = _toy(n=600, decimals=1)
        path = str(tmp_path / "train.csv")
        _write_csv(path, x, y, fmt="%.1f")
        monkeypatch.setenv(faultinject.HANG_ENV_VAR, "20")
        faultinject.configure("ingest_hang:1-")
        t0 = time.monotonic()
        with pytest.raises(elastic.ElasticFailure) as ei:
            lgb.ingest_dataset(
                path, dict(_PARAMS, ingest_read_timeout_s=0.5,
                           ingest_retries=1,
                           ingest_retry_backoff_s=0.01),
                spool_dir=str(tmp_path / "s"))
        wall = time.monotonic() - t0
        assert ei.value.kind == "ingest"
        # two 0.5 s deadlines + backoff, NOT the 20 s hang
        assert wall < 10.0


# ---------------------------------------------------------------------------
# kill -9 between chunk commits (subprocess, the real os._exit death)
# ---------------------------------------------------------------------------

class TestKillResume:
    def test_kill9_mid_ingest_resume_byte_identical(self, tmp_path):
        x, y = _toy(n=900, decimals=1)
        _write_csv(str(tmp_path / "train.csv"), x, y, fmt="%.1f")
        env = dict(os.environ, LGBM_TPU_FAULTS="ingest_read:4:exit")
        p = subprocess.run(
            [sys.executable, _WORKER, str(tmp_path), "spool", "dead"],
            env=env, capture_output=True, text=True, timeout=240)
        assert p.returncode == 23, p.stderr[-2000:]
        committed = [f for f in os.listdir(tmp_path / "spool")
                     if f.endswith(".manifest.json")]
        assert len(committed) == 3          # chunks 1-3 landed
        env.pop("LGBM_TPU_FAULTS")
        p2 = subprocess.run(
            [sys.executable, _WORKER, str(tmp_path), "spool", "resumed"],
            env=env, capture_output=True, text=True, timeout=240)
        assert p2.returncode == 0, p2.stderr[-2000:]
        assert "WORKER_DONE resumed=3" in p2.stdout
        p3 = subprocess.run(
            [sys.executable, _WORKER, str(tmp_path), "spool_clean",
             "clean"],
            env=env, capture_output=True, text=True, timeout=240)
        assert p3.returncode == 0, p3.stderr[-2000:]
        assert "WORKER_DONE resumed=0" in p3.stdout
        resumed = (tmp_path / "model_resumed.txt").read_text("utf-8")
        clean = (tmp_path / "model_clean.txt").read_text("utf-8")
        assert resumed == clean and len(resumed) > 100


# ---------------------------------------------------------------------------
# dist_data framing + sketch allgather
# ---------------------------------------------------------------------------

class TestDistFraming:
    def test_frame_roundtrip(self):
        body = b"x" * 1000
        assert dist_data.unframe_payload(
            dist_data.frame_payload(body)) == body

    @pytest.mark.parametrize("mutate", [
        lambda b: b[:-3],                               # truncated body
        lambda b: b[:20],                               # truncated header
        lambda b: b"XXXX" + b[4:],                      # bad magic
        lambda b: b[:50] + bytes([b[50] ^ 0xFF]) + b[51:],  # bit flip
        lambda b: b[:4] + (9).to_bytes(2, "little") + b[6:],  # version
    ])
    def test_tamper_raises_classified(self, mutate):
        blob = mutate(dist_data.frame_payload(b"payload" * 100))
        with pytest.raises(dist_data.PayloadIntegrityError) as ei:
            dist_data.unframe_payload(blob)
        # classifiable by the elastic ladder, not a crash
        assert elastic.failure_kind(ei.value) is not None

    def test_sketch_allgather_matches_in_memory_findbin(self):
        x, _ = _toy(n=2000, decimals=1)
        cfg = Config({"max_bin": 31, "min_data_in_leaf": 5})
        mappers = dist_data.distributed_bin_mappers(
            x, cfg, process_index=0, process_count=1,
            allgather=lambda b: [b])
        for f in range(x.shape[1]):
            exact = BinMapper()
            exact.find_bin(x[:, f], len(x), 31, cfg.min_data_in_bin,
                           min_split_data=cfg.min_data_in_leaf)
            assert np.array_equal(exact.bin_upper_bound,
                                  mappers[f].bin_upper_bound), f

    def test_wire_bytes_accounting(self):
        x, _ = _toy(n=500, decimals=1)
        cfg = Config({"max_bin": 31})
        dist_data.reset_wire_bytes()
        dist_data.distributed_bin_mappers(
            x, cfg, process_index=0, process_count=1,
            allgather=lambda b: [b])
        assert dist_data.wire_bytes_sent() > 0


# ---------------------------------------------------------------------------
# data_io hardening (satellite: BOM / CRLF / trailing delimiters)
# ---------------------------------------------------------------------------

class TestDataIOHardening:
    def _clean_and_dirty(self, tmp_path):
        rows = ["1,2.5,3", "0,1.5,4", "1,0.5,5"]
        clean = tmp_path / "clean.csv"
        clean.write_text("\n".join(rows) + "\n", encoding="utf-8")
        dirty = tmp_path / "dirty.csv"
        dirty.write_bytes(
            b"\xef\xbb\xbf" + "\r\n".join(r + "," for r in rows).encode()
            + b"\r\n")
        return str(clean), str(dirty)

    def test_bom_crlf_trailing_delim_parse_identically(self, tmp_path):
        clean, dirty = self._clean_and_dirty(tmp_path)
        xc, yc = load_text(clean)
        xd, yd = load_text(dirty)
        assert np.array_equal(xc, xd) and np.array_equal(yc, yd)

    def test_malformed_line_reports_path_and_lineno(self, tmp_path):
        p = tmp_path / "bad.csv"
        p.write_text("1,2,3\n1,zap,3\n", encoding="utf-8")
        with pytest.raises(ValueError, match=r"bad\.csv:2.*'zap'"):
            load_text(str(p))

    def test_width_drift_reports_lineno(self, tmp_path):
        with pytest.raises(ValueError, match=r"w\.csv:3"):
            parse_csv_block(["1,2", "3,4", "5,6,7"], ",",
                            path="w.csv")

    def test_empty_fields_are_nan(self):
        out = parse_csv_block(["1,,3"], ",")
        assert np.isnan(out[0, 1]) and out[0, 2] == 3.0

    def test_libsvm_malformed_reports_lineno(self, tmp_path):
        p = tmp_path / "bad.svm"
        p.write_text("1 0:1.5 1:2.0\n0 0:x\n", encoding="utf-8")
        with pytest.raises(ValueError, match=r"bad\.svm:2"):
            load_text(str(p), fmt="libsvm")

    def test_libsvm_ingest_matches_load_text(self, tmp_path):
        rng = np.random.RandomState(5)
        lines = []
        for i in range(400):
            feats = sorted(rng.choice(8, size=4, replace=False))
            lines.append(f"{i % 2} " + " ".join(
                f"{k}:{round(float(rng.randn()), 1)}" for k in feats))
        p = tmp_path / "t.svm"
        p.write_text("\n".join(lines) + "\n", encoding="utf-8")
        ds = lgb.ingest_dataset(str(p), dict(_PARAMS,
                                             ingest_chunk_rows=150),
                                spool_dir=str(tmp_path / "s"))
        x2, y2 = load_text(str(p), fmt="libsvm")
        assert ds.ingest_report["num_rows"] == 400
        assert ds.ingest_report["num_features"] == x2.shape[1]
        bst = lgb.train(_PARAMS, ds, num_boost_round=3)
        bst2 = lgb.train(_PARAMS, lgb.Dataset(x2, label=y2,
                                              params=_PARAMS),
                         num_boost_round=3)
        assert bst.model_to_string() == bst2.model_to_string()
