"""Deterministic fault injection for the fault-tolerance test suite.

Named injection sites are compiled into the hot paths as ONE dict-empty
check (zero cost when inactive) and fire according to a spec from the
``LGBM_TPU_FAULTS`` environment variable or :func:`configure`::

    LGBM_TPU_FAULTS="device_claim:1-2,nan_grads:3"

Spec grammar — comma-separated ``site:hits[:action]`` entries:

- ``hits``: which occurrences of the site fire, counted from 1 —
  ``3`` (exactly the 3rd hit), ``1-2`` (hits 1 and 2), ``4-`` (hit 4
  onward).  For per-iteration sites (``nan_grads``) the hit index IS the
  iteration number.
- ``action`` (optional): ``raise`` (default — :class:`InjectedFault`, a
  RuntimeError whose message matches the resilience layer's retryable
  patterns), ``kill`` (:class:`InjectedKill`, a BaseException that
  normal ``except Exception`` recovery cannot swallow — simulates the
  process dying at the site), ``exit`` (``os._exit(23)``, a REAL
  death for subprocess tests), or ``hang`` (the site blocks for
  ``LGBM_TPU_FAULT_HANG_S`` seconds, default 30 — the wedged-collective
  / wedged-claim simulation the elastic deadline layer exists to
  bound; the sleeping thread is abandoned by the watchdog exactly like
  a real wedge), or ``bitflip`` (one deterministic bit of the named
  device array flips at the site — only meaningful at the SDC sites
  wired through :func:`maybe_bitflip`).  Site ``snapshot_kill``
  defaults to ``kill``; sites ``collective_hang`` and ``claim_wedge``
  default to ``hang``; sites ``hist_sdc`` and ``score_sdc`` default to
  ``bitflip``.

Sites wired into the codebase:

==================  ========================================================
``device_claim``    device/backend bring-up (``GBDTModel._resolve_mesh``,
                    ``parallel/launch.init``, ``parallel/mesh
                    .init_distributed``) — exercises retry/backoff and
                    ``dist_fallback_serial``
``collective``      data-parallel grower dispatch
                    (``parallel/data_parallel.make_dp_grower``)
``snapshot_write``  entry of ``utils/resilience.atomic_write`` (every
                    model/binary/manifest write)
``snapshot_kill``   after the temp file is durable, before ``os.replace``
                    — the kill-before-rename crash window
``nan_grads``       gradient poisoning at iteration k
                    (``models/gbdt.GBDTModel.train_one_iter``) —
                    exercises ``finite_check_policy``
``serve_batch``     serve batch execution (``serve/server.Server
                    ._predict_batch``) — exercises the batcher's
                    transient-retry path and the serving circuit
                    breaker (tools/soak_serve.py chaos windows)
``serve_reload``    model load/hot-swap entry (``serve/registry
                    .ModelRegistry.load``) — a failed reload must leave
                    the current version serving
``continual_*``     the continual-boosting pipeline's stage boundaries
                    (``pipeline/continual.py``): ``continual_append``
                    (data-chunk ingest), ``continual_boost`` (boost k
                    rounds from the newest snapshot),
                    ``continual_publish`` (SHA-pinned artifact write),
                    ``continual_promote`` (gated registry promotion) —
                    each stage retries transients and rolls back to the
                    incumbent on exhaustion
``shadow_probe``    inside the shadow-traffic parity probe
                    (``pipeline/continual.py shadow_parity_probe``) —
                    a firing probe is a GATE FAILURE: the candidate is
                    quarantined, the incumbent keeps serving
``collective_hang`` inside the elastic collective-deadline fetch
                    (``parallel/elastic.guarded_get`` worker, i.e. the
                    training loop's one per-iteration host sync) —
                    default action ``hang``: the fetch wedges and the
                    deadline must classify + abandon it
``host_loss``       the elastic per-iteration liveness check
                    (``parallel/elastic.check_peers``) — a firing site
                    simulates a peer process's heartbeat going stale
                    (the kill -9 subprocess tests exercise the real
                    stale-file detection)
``claim_wedge``     device claim under elastic
                    (``models/gbdt.GBDTModel._resolve_mesh``) —
                    default action ``hang``: the claim wedges and the
                    bring-up deadline must turn it into a classified
                    ``ElasticFailure`` instead of a silent hang
``ingest_read``     chunk read+parse entry of the streaming ingest
                    pipeline (``ingest.IngestRunner``) — exercises the
                    per-chunk retry/backoff; ``exit`` between chunk
                    commits is the kill -9 resume test
``ingest_checksum`` chunk validation (``ingest.IngestRunner``) — a
                    firing site simulates a CORRUPT chunk (sha
                    mismatch class, not transient): quarantined per
                    ``ingest_bad_chunk``, never retried
``ingest_hang``     inside the chunk read (``ingest.IngestRunner``) —
                    default action ``hang``: a reader wedged on a dead
                    filesystem; the ``ingest_read_timeout_s`` watchdog
                    must abandon + classify it
``hist_sdc``        silent-data-corruption injection into the grower's
                    histogram-derived output (``models/gbdt
                    .GBDTModel.train_one_iter`` via
                    :func:`maybe_bitflip`) — default action
                    ``bitflip``: ONE deterministic bit of the new
                    tree's leaf-count array flips, simulating a
                    marginal chip; exercises the integrity layer's
                    detect / transient-absorb / rewind / quarantine
                    ladder (lightgbm_tpu/integrity.py)
``score_sdc``       silent-data-corruption injection into the
                    per-iteration score-update delta (``models/gbdt
                    .GBDTModel.train_one_iter``) — default action
                    ``bitflip``; exercises the integrity layer's
                    score-path verification
==================  ========================================================

Also exercisable from ``tools/tpu_watch.py`` probes: export
``LGBM_TPU_FAULTS`` before starting the watcher and the probe child
inherits it (its retry/backoff attempts are logged to the watch log).
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Tuple

ENV_VAR = "LGBM_TPU_FAULTS"

KNOWN_SITES = ("device_claim", "collective", "snapshot_write",
               "snapshot_kill", "nan_grads", "serve_batch",
               "serve_reload", "serve_self_check", "continual_append",
               "continual_boost", "continual_publish",
               "continual_promote", "shadow_probe", "collective_hang",
               "host_loss", "claim_wedge", "ingest_read",
               "ingest_checksum", "ingest_hang", "hist_sdc",
               "score_sdc")

# sites whose realistic failure mode is a WEDGE, not an error
_HANG_DEFAULT_SITES = ("collective_hang", "claim_wedge", "ingest_hang")

# sites whose realistic failure mode is SILENT data corruption — the
# chip keeps running and hands back a wrong number (maybe_bitflip)
_BITFLIP_DEFAULT_SITES = ("hist_sdc", "score_sdc")

# how long a firing ``hang`` action blocks: long enough that any sane
# deadline fires first, short enough that an abandoned daemon thread
# does not outlive a test session
HANG_ENV_VAR = "LGBM_TPU_FAULT_HANG_S"


def _hang_seconds() -> float:
    try:
        return float(os.environ.get(HANG_ENV_VAR, "") or 30.0)
    except ValueError:
        return 30.0


class InjectedFault(RuntimeError):
    """Raised by a firing site.  The message deliberately matches the
    resilience classifier's retryable patterns (UNAVAILABLE / claim) so
    injected bring-up failures exercise the REAL retry path."""

    def __init__(self, site: str, hit: int):
        self.site = site
        self.hit = hit
        super().__init__(
            f"injected fault at site '{site}' (hit {hit}): UNAVAILABLE: "
            "simulated device claim/backend failure")


class InjectedKill(BaseException):
    """Simulated process death at a site.  Derives from BaseException so
    ``except Exception`` recovery paths (snapshot skip-and-warn) cannot
    swallow it — only the test harness catches it."""

    def __init__(self, site: str, hit: int):
        self.site = site
        self.hit = hit
        super().__init__(f"injected kill at site '{site}' (hit {hit})")


# site -> (first_hit, last_hit_or_None_for_open_end, action)
_spec: Dict[str, Tuple[int, Optional[int], str]] = {}
_hits: Dict[str, int] = {}


def configure(spec: Optional[str]) -> None:
    """Install a fault spec (replacing any active one) and reset all hit
    counters.  ``None``/empty disables injection entirely."""
    _spec.clear()
    _hits.clear()
    if not spec:
        return
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) not in (2, 3):
            raise ValueError(f"bad fault spec entry {entry!r} "
                             "(want site:hits[:action])")
        site, hits = parts[0].strip(), parts[1].strip()
        if len(parts) == 3:
            action = parts[2].strip()
        elif site == "snapshot_kill":
            action = "kill"
        elif site in _HANG_DEFAULT_SITES:
            action = "hang"
        elif site in _BITFLIP_DEFAULT_SITES:
            action = "bitflip"
        else:
            action = "raise"
        if site not in KNOWN_SITES:
            raise ValueError(f"unknown fault site {site!r} "
                             f"(known: {', '.join(KNOWN_SITES)})")
        if action not in ("raise", "kill", "exit", "hang", "bitflip"):
            raise ValueError(f"unknown fault action {action!r}")
        if "-" in hits:
            lo_s, hi_s = hits.split("-", 1)
            lo = int(lo_s)
            hi = int(hi_s) if hi_s else None
        else:
            lo = hi = int(hits)
        if lo < 1 or (hi is not None and hi < lo):
            raise ValueError(f"bad hit range in {entry!r}")
        _spec[site] = (lo, hi, action)


def clear() -> None:
    """Disable injection and reset counters (test teardown)."""
    configure(None)


def enabled() -> bool:
    """Whether ANY site is armed (used to gate zero-cost fast paths,
    e.g. the fused-chunk program which cannot host per-iteration
    injection)."""
    return bool(_spec)


def hits(site: str) -> int:
    """How many times ``site`` was reached since configure()."""
    return _hits.get(site, 0)


def _advance(site: str) -> Tuple[bool, int, str]:
    """Count a hit; return (fires, hit_index, action)."""
    if site not in _spec:
        return False, 0, "raise"
    n = _hits.get(site, 0) + 1
    _hits[site] = n
    lo, hi, action = _spec[site]
    return (n >= lo and (hi is None or n <= hi)), n, action


def check(site: str) -> None:
    """Raise/exit/hang if ``site`` fires on this hit; no-op otherwise."""
    if not _spec:
        return
    fire, n, action = _advance(site)
    if not fire:
        return
    if action == "exit":
        os._exit(23)
    if action == "kill":
        raise InjectedKill(site, n)
    if action == "hang":
        # the wedge simulation: block like a hung collective/claim
        # would.  Bounded (HANG_ENV_VAR) so an abandoned thread cannot
        # outlive the test session; any sane deadline fires well before
        import time
        time.sleep(_hang_seconds())
        return
    raise InjectedFault(site, n)


def fires(site: str) -> bool:
    """Non-raising variant for corruption sites (``nan_grads``): counts
    the hit and reports whether it fires, leaving the action to the call
    site (e.g. writing NaN into the gradient array)."""
    if not _spec:
        return False
    fire, _n, _action = _advance(site)
    return fire


def maybe_bitflip(site: str, arr, index: Optional[int] = None):
    """SDC injection: count a hit at ``site``; when it fires with action
    ``bitflip``, return ``arr`` with exactly ONE bit flipped.  Element
    and bit are chosen deterministically from ``crc32(site:hit)`` so a
    given spec replays the identical corruption run to run; ``index``
    pins the element instead (e.g. ``hist_sdc`` flips leaf 0's count —
    a slot that is always live).  For int32 operands the bit is drawn
    from [0, 31); for float32 from [8, 31) — at least 256 ulps, so a
    flip is never hidden inside ``integrity_ulp_tol`` — and the sign
    bit is left alone either way so a float flip stays a plausible
    wrong *number*, not a sign glitch.

    Returns ``arr`` unchanged — the SAME object, no device work — when
    injection is off, the site is unarmed, or this hit does not fire.
    A non-``bitflip`` action on an armed SDC site still applies (e.g.
    ``hist_sdc:3:kill`` dies at the site instead of corrupting it).
    """
    if site not in _spec:
        return arr
    fire, n, action = _advance(site)
    if not fire:
        return arr
    if action != "bitflip":
        if action == "exit":
            os._exit(23)
        if action == "kill":
            raise InjectedKill(site, n)
        if action == "hang":
            import time
            time.sleep(_hang_seconds())
            return arr
        raise InjectedFault(site, n)
    import zlib

    import jax
    import jax.numpy as jnp
    seed = zlib.crc32(f"{site}:{n}".encode())
    flat = jnp.ravel(arr)
    size = max(int(flat.shape[0]), 1)
    idx = (seed if index is None else int(index)) % size
    bit = (seed >> 8) % 31
    if jnp.issubdtype(flat.dtype, jnp.floating):
        bit = 8 + (seed >> 8) % 23      # >= 256 ulps: never tol-masked
    mask = jnp.int32(1 << bit)
    if jnp.issubdtype(flat.dtype, jnp.floating):
        iv = jax.lax.bitcast_convert_type(
            flat.astype(jnp.float32), jnp.int32)
        iv = iv.at[idx].set(iv[idx] ^ mask)
        flat = jax.lax.bitcast_convert_type(
            iv, jnp.float32).astype(arr.dtype)
    elif jnp.issubdtype(flat.dtype, jnp.integer):
        flat = flat.at[idx].set(flat[idx] ^ mask.astype(flat.dtype))
    else:
        raise TypeError(f"maybe_bitflip: unsupported dtype "
                        f"{flat.dtype} at site '{site}'")
    return flat.reshape(jnp.shape(arr))


# arm from the environment at import (subprocess tests / tpu_watch probes)
configure(os.environ.get(ENV_VAR))
