"""Device-resident serving fast path (ISSUE 10, docs/Serving.md
"Device-resident fast path").

The acceptance bar: the fused one-jit bin->traverse->accumulate->
transform program (``PredictorEngine.fused_predict``,
``predict_device.fused_forest_predict``) does EXACTLY one host<->device
sync per serve batch (counted-device_get test), its scores byte-match
the host replay of the same f32 tree-order ops
(``engine._fused_reference``) on rows where f32 and f64 binning
provably agree — across the regression/binary/multiclass/categorical/
EFB/DART/RF matrix — and a failed engine self-check DEMOTES the model
to the always-correct host walk (``serve.host_fallback_batches``)
instead of refusing traffic.  Satellites: packed uint8/uint16 node
tables vs int32 equivalence, zero-row batches, multi-model co-hosting
(shared traces + residency cap).
"""

import json
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.predict_device import forest_trace_count, fused_trace_count
from lightgbm_tpu.serve import PredictorEngine, Server, start_http
from lightgbm_tpu.serve.engine import EngineUnsupported
from lightgbm_tpu.serve.registry import ModelRegistry
from lightgbm_tpu.utils import faultinject


def _data(n=450, f=6, seed=0, nan_frac=0.05, cat_col=None):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, f)
    if cat_col is not None:
        x[:, cat_col] = rs.randint(0, 12, n)
    x[rs.rand(n, f) < nan_frac] = np.nan
    if cat_col is not None:
        c = x[:, cat_col]
        x[:, cat_col] = np.where(np.isnan(c), np.nan, np.abs(c))
    return x


def _train(params, x, y, rounds=6, **kw):
    ds = lgb.Dataset(x, label=y, **kw)
    return lgb.train({"verbosity": -1, "num_leaves": 8, **params}, ds,
                     num_boost_round=rounds)


def _fused_matrix():
    """(tag, booster, test rows) across the fused parity matrix —
    every objective/feature family the ISSUE names."""
    rs = np.random.RandomState(7)
    out = []

    x = _data(seed=1)
    y = np.where(np.isnan(x[:, 0]), 0.3, x[:, 0] + 0.5 * x[:, 1])
    out.append(("regression", _train({"objective": "regression"}, x, y),
                _data(120, seed=11)))

    x = _data(seed=2)
    y = (np.nan_to_num(x[:, 0]) > 0).astype(np.float64)
    out.append(("binary", _train({"objective": "binary"}, x, y),
                _data(120, seed=12)))

    x = _data(seed=3)
    y = rs.randint(0, 3, len(x)).astype(np.float64)
    out.append(("multiclass",
                _train({"objective": "multiclass", "num_class": 3}, x, y),
                _data(120, seed=13)))

    x = _data(seed=4, cat_col=2)
    y = (np.nan_to_num(x[:, 2]) % 3 == 0).astype(np.float64)
    xt = _data(120, seed=14)
    xt[:, 2] = rs.randint(-2, 16, len(xt)).astype(np.float64)
    out.append(("categorical",
                _train({"objective": "binary"}, x, y,
                       categorical_feature=[2]), xt))

    x = _data(seed=5)
    y = (np.nan_to_num(x[:, 0]) > 0).astype(np.float64)
    out.append(("dart", _train({"objective": "binary",
                                "boosting": "dart"}, x, y),
                _data(120, seed=15)))

    x = _data(seed=6, nan_frac=0.0)
    out.append(("rf", _train({"objective": "regression", "boosting": "rf",
                              "bagging_fraction": 0.7,
                              "bagging_freq": 1}, x, x[:, 0]),
                _data(120, seed=16, nan_frac=0.0)))

    # EFB-bundled model (training-side bundling; serving bins raw
    # features from the model's own thresholds, so EFB must be
    # invisible to the fused path)
    n, n_cats = 700, 12
    dense = rs.randn(n, 3)
    cat = rs.randint(0, n_cats, n)
    onehot = np.zeros((n, n_cats))
    onehot[np.arange(n), cat] = 1.0
    x = np.column_stack([dense, onehot])
    y = (dense[:, 0] + (cat % 3 == 0) > 0.5).astype(np.float64)
    bst = _train({"objective": "binary"}, x, y)
    assert bst._model.train_set.efb is not None, "EFB did not trigger"
    d2 = rs.randn(120, 3)
    c2 = rs.randint(0, n_cats, 120)
    oh2 = np.zeros((120, n_cats))
    oh2[np.arange(120), c2] = 1.0
    out.append(("efb", bst, np.column_stack([d2, oh2])))
    return out


@pytest.fixture(scope="module")
def fused_matrix():
    return _fused_matrix()


# ---------------------------------------------------------------------------
# fused parity (acceptance criterion)
# ---------------------------------------------------------------------------

class TestFusedParity:
    def test_fused_matches_f32_reference_and_host_walk(self, fused_matrix):
        """On f32==f64-consensus rows the fused scores byte-match the
        host replay of the same f32 ops, and track the exact f64 host
        walk to f32 accumulation rounding."""
        for tag, bst, xt in fused_matrix:
            eng = PredictorEngine.from_booster(bst)
            assert eng.fused_ok, (tag, eng.fused_reason)
            mask = eng._f32_consensus_mask(np.asarray(xt, np.float64))
            assert mask.any(), tag
            rows = xt[mask]
            got = eng.fused_predict(rows)
            ref = eng._fused_reference(rows)
            assert np.array_equal(got, ref), tag
            host = np.asarray(bst.predict(rows), np.float64)
            assert np.allclose(np.asarray(got, np.float64), host,
                               rtol=1e-5, atol=1e-6), tag
            assert got.dtype == np.float32, tag

    def test_self_check_gates_fused_path(self, fused_matrix):
        for tag, bst, _ in fused_matrix:
            eng = PredictorEngine.from_booster(bst)
            assert eng.self_check(device_binning=True), tag

    def test_raw_score_mode(self):
        x = _data(seed=21)
        y = (np.nan_to_num(x[:, 0]) > 0).astype(np.float64)
        bst = _train({"objective": "binary"}, x, y)
        eng = PredictorEngine.from_booster(bst)
        xt = _data(40, seed=22)
        raw = eng.fused_predict(xt, raw_score=True)
        ref = eng._fused_reference(xt, raw_score=True)
        assert np.array_equal(raw, ref)
        host = bst.predict(xt, raw_score=True)
        assert np.allclose(np.asarray(raw, np.float64), host,
                           rtol=1e-5, atol=1e-6)

    def test_linear_trees_fall_back_counted(self):
        """Linear-leaf models cannot ride the fused program (raw-feature
        host math): the engine refuses, the server serves the exact
        host path and counts serve.host_fallback_batches."""
        x = _data(seed=23, nan_frac=0.0)
        bst = _train({"objective": "regression", "linear_tree": True},
                     x, x[:, 0])
        eng = PredictorEngine.from_booster(bst)
        assert not eng.fused_ok
        assert "linear" in eng.fused_reason
        with pytest.raises(EngineUnsupported):
            eng.fused_predict(x[:4])
        srv = Server({"serve_device_binning": True,
                      "serve_max_wait_ms": 0.0}, booster=bst)
        try:
            xt = _data(10, seed=24, nan_frac=0.0)
            out = srv.predict(xt)
            assert np.array_equal(out, bst.predict(xt))
            snap = srv.metrics_snapshot()
            assert snap["serve.host_fallback_batches"]["value"] >= 1
            assert "serve.fused_batches" not in snap
        finally:
            srv.close()

    def test_default_serving_unchanged_byte_identical(self, fused_matrix):
        """Without serve_device_binning nothing changes: serve results
        stay byte-identical to Booster.predict."""
        tag, bst, xt = fused_matrix[1]
        srv = Server({"serve_max_wait_ms": 0.0}, booster=bst)
        try:
            out = srv.predict(xt)
            assert np.array_equal(out, bst.predict(xt)), tag
            snap = srv.metrics_snapshot()
            assert "serve.fused_batches" not in snap
            assert "serve.host_fallback_batches" not in snap
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# packed tables (satellite)
# ---------------------------------------------------------------------------

class TestPackedTables:
    def test_uint8_tables_for_small_models(self):
        x = _data(seed=31)
        bst = _train({"objective": "regression"}, x,
                     np.nan_to_num(x[:, 0]))
        eng = PredictorEngine.from_booster(bst)
        stats = eng.compile_stats()
        assert stats["packed"] is True
        assert stats["threshold_dtype"] == "uint8"
        assert stats["child_dtype"] == "int8"
        assert eng._bin_dtype == np.uint8

    def test_packed_vs_int32_equivalence(self):
        """Packed narrow tables must route and score EXACTLY like the
        int32 build — fused path, host-binned leaf path and predict."""
        x = _data(500, seed=32, cat_col=3)
        y = (np.nan_to_num(x[:, 0]) + (np.nan_to_num(x[:, 3]) % 2)
             > 0.5).astype(np.float64)
        bst = _train({"objective": "binary"}, x, y, rounds=8,
                     categorical_feature=[3])
        packed = PredictorEngine.from_booster(bst, packed=True)
        plain = PredictorEngine.from_booster(bst, packed=False)
        assert plain.compile_stats()["threshold_dtype"] == "int32"
        xt = _data(90, seed=33, cat_col=3)
        assert np.array_equal(packed.leaf_ids(xt), plain.leaf_ids(xt))
        assert np.array_equal(packed.predict(xt), plain.predict(xt))
        assert np.array_equal(packed.predict(xt), bst.predict(xt))
        assert np.array_equal(packed.fused_predict(xt),
                              plain.fused_predict(xt))
        assert packed.table_bytes < plain.table_bytes

    def test_uint16_when_bins_outgrow_uint8(self):
        rs = np.random.RandomState(34)
        x = rs.randn(1500, 2)
        y = x[:, 0] + np.sin(3 * x[:, 0]) + 0.1 * x[:, 1]
        bst = _train({"objective": "regression", "num_leaves": 31,
                      "max_bin": 1023}, x, y, rounds=30)
        eng = PredictorEngine.from_booster(bst)
        max_bins = max(t.num_bins for t in eng.tables)
        if max_bins <= 255:
            pytest.skip(f"model too small to outgrow uint8 ({max_bins})")
        assert eng.compile_stats()["threshold_dtype"] == "uint16"
        xt = rs.randn(50, 2)
        plain = PredictorEngine.from_booster(bst, packed=False)
        assert np.array_equal(eng.fused_predict(xt),
                              plain.fused_predict(xt))
        assert np.array_equal(eng.predict(xt), bst.predict(xt))


# ---------------------------------------------------------------------------
# sync count (satellite: the re-pinned serve hot-path sync)
# ---------------------------------------------------------------------------

class TestSyncCount:
    def _counting(self, monkeypatch):
        import jax
        calls = []
        real = jax.device_get

        def counting(*a, **kw):
            calls.append(1)
            return real(*a, **kw)

        monkeypatch.setattr(jax, "device_get", counting)
        return calls

    def test_exactly_one_sync_per_fused_batch(self, monkeypatch):
        x = _data(seed=41)
        y = (np.nan_to_num(x[:, 0]) > 0).astype(np.float64)
        bst = _train({"objective": "binary"}, x, y, rounds=11)
        eng = PredictorEngine.from_booster(bst, max_batch=256)
        eng.fused_predict(x[:50])              # warm the bucket
        calls = self._counting(monkeypatch)
        out = eng.fused_predict(x[:50])
        assert len(calls) == 1, "fused batch must sync exactly once"
        assert out.shape == (50,)
        # above the bucket cap: one sync per max-bucket chunk, never
        # per row or per tree
        calls.clear()
        eng.fused_predict(_data(300, seed=42))
        assert len(calls) == 2                 # 256 + 44 -> two chunks

    def test_fused_serve_batch_single_sync_e2e(self, monkeypatch):
        """Through the whole serve stack (batcher worker included): a
        served batch on the fused path costs exactly one device_get."""
        x = _data(seed=43)
        y = np.nan_to_num(x[:, 1])
        bst = _train({"objective": "regression"}, x, y, rounds=7)
        srv = Server({"serve_device_binning": True,
                      "serve_max_wait_ms": 0.0}, booster=bst)
        try:
            srv.predict(x[:20])                # warm
            calls = self._counting(monkeypatch)
            srv.predict(x[:20])
            assert len(calls) == 1
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# zero rows (satellite)
# ---------------------------------------------------------------------------

class TestZeroRowFused:
    def test_zero_rows_no_device_work(self, monkeypatch):
        x = _data(seed=51)
        bst = _train({"objective": "multiclass", "num_class": 3}, x,
                     np.random.RandomState(0).randint(0, 3, len(x))
                     .astype(np.float64))
        eng = PredictorEngine.from_booster(bst)
        calls = self._count(monkeypatch)
        before = fused_trace_count()
        out = eng.fused_predict(np.empty((0, x.shape[1])))
        assert out.shape == (0, 3)
        assert out.dtype == np.float32
        assert fused_trace_count() == before
        assert not calls
        single = _data(1, seed=52)
        assert eng.fused_predict(single).shape == (1, 3)

    def _count(self, monkeypatch):
        import jax
        calls = []
        real = jax.device_get
        monkeypatch.setattr(
            jax, "device_get",
            lambda *a, **kw: (calls.append(1), real(*a, **kw))[1])
        return calls

    def test_zero_rows_through_fused_server(self):
        x = _data(seed=53)
        y = (np.nan_to_num(x[:, 0]) > 0).astype(float)
        bst = _train({"objective": "binary"}, x, y)
        srv = Server({"serve_device_binning": True}, booster=bst)
        try:
            out = srv.predict(np.empty((0, x.shape[1])))
        finally:
            srv.close()
        assert out.shape == (0,)


# ---------------------------------------------------------------------------
# demotion (satellite: failed self-check -> host walk, counted)
# ---------------------------------------------------------------------------

class TestDemotion:
    def test_self_check_fault_demotes_to_host_walk(self):
        x = _data(seed=61)
        y = (np.nan_to_num(x[:, 0]) > 0).astype(float)
        bst = _train({"objective": "binary"}, x, y)
        faultinject.configure("serve_self_check:1")
        try:
            srv = Server({"serve_device_binning": True,
                          "serve_max_wait_ms": 0.0}, booster=bst)
        finally:
            faultinject.clear()
        try:
            assert srv.registry.current().engine is None
            xt = _data(15, seed=62)
            out = srv.predict(xt)
            # demoted = the EXACT host walk, byte for byte
            assert np.array_equal(out, bst.predict(xt))
            snap = srv.metrics_snapshot()
            assert snap["serve.host_fallback_batches"]["value"] >= 1
        finally:
            srv.close()

    def test_registry_discards_engine_on_failed_check(self):
        x = _data(seed=63)
        bst = _train({"objective": "regression"}, x,
                     np.nan_to_num(x[:, 0]))
        reg = ModelRegistry(device_binning=True)
        faultinject.configure("serve_self_check:1")
        try:
            v = reg.load(booster=bst)
        finally:
            faultinject.clear()
        assert reg.get(v).engine is None
        # a later load without the fault builds the engine again
        v2 = reg.load(booster=bst)
        assert reg.get(v2).engine is not None

    @pytest.mark.slow
    def test_soak_demotion_never_drops_requests(self):
        """tools/soak_serve.py chaos window with a failing self-check
        under serve_device_binning: every request answers (fused or
        demoted host walk), zero invariant violations."""
        import os
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools"))
        import soak_serve
        report = soak_serve.run_soak(
            duration_s=1.5, clients=3, pool_size=8, max_rows=24,
            device_binning=True,
            chaos_spec="serve_self_check:1,serve_batch:1-3")
        assert report["violations"] == [], report["violations"]
        assert report["counts"].get("ok", 0) > 0


# ---------------------------------------------------------------------------
# co-hosting (tentpole: N resident versions share traces + bounded HBM)
# ---------------------------------------------------------------------------

class TestCoHosting:
    def test_second_version_shares_all_fused_traces(self):
        """Two versions of one model family land on identical padded
        SoA shapes (utils/shapes.py) — the second serves a mixed batch
        storm with ZERO fresh fused traces."""
        x = _data(500, seed=71)
        y = (np.nan_to_num(x[:, 0]) > 0).astype(float)
        b1 = _train({"objective": "binary", "max_depth": 4}, x, y,
                    rounds=9)                  # distinctive T=9 shape
        b2 = _train({"objective": "binary", "max_depth": 4,
                     "learning_rate": 0.2}, x, y, rounds=9)
        reg = ModelRegistry(max_batch=64, device_binning=True)
        v1 = reg.load(booster=b1)
        e1 = reg.get(v1).engine
        for n in (3, 17, 40, 64, 100):
            # warm every serve program variant over the bucket set:
            # fused, host-binned traversal (packed-uint8 bins) and
            # device-binned traversal — b2's load-time self-check may
            # probe any of them at any bucket
            e1.fused_predict(x[:n])
            e1.predict(x[:n])
            e1.leaf_ids(x[:n], device_binning=True)
        before = fused_trace_count(), forest_trace_count()
        v2 = reg.load(booster=b2)
        e2 = reg.get(v2).engine
        for n in (3, 17, 40, 64, 100):
            e2.fused_predict(x[:n])
        assert (fused_trace_count(), forest_trace_count()) == before, \
            "co-hosted same-family version must share every serve trace"
        # both stay resident and serve independently
        xt = x[:30]
        assert np.array_equal(e1.fused_predict(xt),
                              e1._fused_reference(xt))
        assert np.array_equal(e2.fused_predict(xt),
                              e2._fused_reference(xt))

    def test_max_resident_evicts_oldest_non_current(self):
        x = _data(seed=72)
        y = np.nan_to_num(x[:, 0])
        boosters = [_train({"objective": "regression",
                            "learning_rate": 0.1 + 0.05 * i}, x, y,
                           rounds=3) for i in range(4)]
        reg = ModelRegistry(max_resident=2, build_engine=False)
        for i, b in enumerate(boosters):
            reg.load(booster=b, version=f"v{i + 1}")
        vs = [v["version"] for v in reg.versions()]
        assert len(vs) == 2
        assert "v4" in vs                      # current always kept
        # a shadow load (activate=False) at the cap displaces an OLDER
        # version, never itself — the returned id must stay resident
        shadow = reg.load(booster=boosters[0], version="shadow",
                          activate=False)
        assert reg.get(shadow) is not None
        assert reg.current().version == "v4"
        assert len(reg.versions()) == 2
        srv = Server({"serve_max_resident": 2}, booster=boosters[0])
        try:
            srv.reload(booster=boosters[1])
            srv.reload(booster=boosters[2])
            assert len(srv.registry.versions()) == 2
        finally:
            srv.close()

    def test_config_validation(self):
        from lightgbm_tpu.config import Config
        assert Config({}).serve_packed_tables is True
        assert Config({}).serve_max_resident == 0
        with pytest.raises(ValueError):
            Config({"serve_max_resident": -1})


# ---------------------------------------------------------------------------
# serve stack e2e on the fused path
# ---------------------------------------------------------------------------

class TestServerFused:
    def test_fused_serving_in_process_and_http(self):
        x = _data(seed=81)
        y = (np.nan_to_num(x[:, 0]) > 0).astype(float)
        bst = _train({"objective": "binary"}, x, y)
        srv = Server({"serve_device_binning": True,
                      "serve_max_wait_ms": 1.0}, booster=bst)
        eng = srv.registry.current().engine
        fe = start_http(srv, port=0)
        try:
            xt = _data(37, seed=82)
            expect = eng.fused_predict(xt)
            got = srv.predict(xt)
            assert np.array_equal(got, expect)
            req = urllib.request.Request(
                f"http://127.0.0.1:{fe.port}/predict",
                data=json.dumps({"rows": xt.tolist()}).encode(),
                headers={"Content-Type": "application/json"})
            resp = json.loads(urllib.request.urlopen(req).read())
            assert np.array_equal(
                np.asarray(resp["predictions"], np.float32), expect)
            snap = srv.metrics_snapshot()
            assert snap["serve.fused_batches"]["value"] >= 2
            assert snap["serve.engine"]["fused"] is True
            assert snap["serve.engine"]["fused_buckets"]
            assert snap["serve.engine"]["table_bytes"] > 0
            assert snap["perf.forest.flops_per_row"] > 0
        finally:
            fe.close()
            srv.close()

    def test_perf_forest_keys_track_path(self):
        """perf.forest.* must reflect the path that actually serves:
        the fused formula covers binning+accumulate+transform, so its
        per-row flops exceed the traversal-only host accounting."""
        x = _data(seed=83)
        bst = _train({"objective": "regression"}, x,
                     np.nan_to_num(x[:, 0]))
        eng = PredictorEngine.from_booster(bst)
        fl_fused, hb_fused = eng.per_row_flops_bytes(fused=True)
        fl_host, hb_host = eng.per_row_flops_bytes(fused=False)
        assert fl_fused > fl_host
        assert hb_fused != hb_host
