"""Process-level memo of jitted programs: get-or-build with LRU
eviction.

The distributed grower builders (parallel/voting_parallel.py,
parallel/feature_parallel.py) memoize their jitted shard_map programs
process-wide so a leaf sweep inside one padded bucket shares ONE trace
across Boosters (the role grower.py's ``_SHARED_GROWERS`` plays for the
serial grower).  Each module keeps its own store/lock; this helper owns
the get/move-to-end/insert/evict discipline so the three copies cannot
drift.

``build`` runs OUTSIDE the lock — tracing can take seconds and must not
serialize unrelated Boosters.  A concurrent duplicate build is benign:
last writer wins the store slot, both handles stay live (eviction only
drops the shared handle, never a Booster's own reference).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, TypeVar

T = TypeVar("T")


def memo_get_or_build(store: "OrderedDict",
                      lock: threading.Lock,
                      max_entries: int,
                      key,
                      build: Callable[[], T]) -> T:
    with lock:
        hit = store.get(key)
        if hit is not None:
            store.move_to_end(key)
            return hit
    out = build()
    with lock:
        store[key] = out
        while len(store) > max_entries:
            store.popitem(last=False)
    return out
