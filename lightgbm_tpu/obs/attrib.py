"""Roofline attribution: join the static FLOP/byte ledger with the
fenced phase spans and a per-device peak table.

The per-phase question ROADMAP's perf frontier needs answered
continuously — "is this phase compute- or memory-bound, and how far
from peak?" — computed as ``perf.*`` keys from three ingredients that
already exist separately:

- ``flops.total`` / ``flops.hbm_bytes`` counters (obs/flops.py ledger,
  recorded per iteration by ``ObsSession.record_flops``),
- ``train.phase_seconds{phase=...}`` histograms (the fenced spans
  PROFILE.md's methodology mandates — wall time attributed to the
  phase that queued the work),
- the peak table below (extending the one bench.py used to carry
  privately, with HBM bandwidth added so the roofline has both axes).

``perf_summary`` is a pure function of a metrics snapshot, so the
static keys (flops, hbm_bytes) inherit the snapshot's dp == serial
determinism and the whole join is unit-testable without a device.
Surfaced in ``Booster.telemetry_snapshot()``, the serve ``/metrics``
endpoint and bench points.
"""

from __future__ import annotations

import re
from typing import Dict, Optional, Tuple

# bf16/f32 MXU peak FLOP/s and HBM bandwidth (bytes/s) per chip, by
# device-kind substring.  FLOP/s column == the table bench.py shipped;
# bandwidth from the public TPU system specs (v4 1228 GB/s, v5e
# 819 GB/s, v5p 2765 GB/s, v6e 1640 GB/s).  Unknown kinds report raw
# FLOP/s with no MFU/verdict — or the caller pins peaks via the
# ``telemetry_peak_flops`` / ``telemetry_peak_hbm_gbs`` params.
PEAKS: Dict[str, Tuple[float, float]] = {
    "v5lite": (197e12, 819e9), "v5e": (197e12, 819e9),
    "v5p": (459e12, 2765e9),
    "v4": (275e12, 1228e9),
    "v6e": (918e12, 1640e9), "v6lite": (918e12, 1640e9),
}


def device_peaks(devices=None) -> Tuple[Optional[float], Optional[float]]:
    """(peak FLOP/s, peak HBM bytes/s) for the first visible device,
    (None, None) when the kind is unknown (CPU, new TPU gens)."""
    if devices is None:
        try:
            import jax
            devices = jax.devices()
        except Exception:
            return None, None
    if not devices:
        return None, None
    kind = getattr(devices[0], "device_kind", "").lower().replace(" ", "")
    for key, peaks in PEAKS.items():
        if key in kind:
            return peaks
    return None, None


def config_peaks(config) -> Tuple[Optional[float], Optional[float]]:
    """Peaks from the ``telemetry_peak_flops`` / ``telemetry_peak_hbm_gbs``
    params (0 = auto), falling back to :func:`device_peaks` — the
    escape hatch for device kinds the table does not know."""
    pf = float(getattr(config, "telemetry_peak_flops", 0.0) or 0.0) or None
    pb = float(getattr(config, "telemetry_peak_hbm_gbs", 0.0) or 0.0)
    pb = pb * 1e9 if pb else None
    if pf is None or pb is None:
        dpf, dpb = device_peaks()
        pf = pf if pf is not None else dpf
        pb = pb if pb is not None else dpb
    return pf, pb


def roofline(flops: float, hbm_bytes: float, seconds: float,
             peak_flops: Optional[float] = None,
             peak_bw: Optional[float] = None) -> Dict[str, object]:
    """Achieved rates + roofline verdict for one phase.

    ``bound`` compares the workload's arithmetic intensity (FLOPs per
    HBM byte) against the machine's ridge point (peak FLOP/s / peak
    bytes/s): above the ridge the phase can saturate the MXU before
    the memory system (compute-bound), below it HBM bandwidth is the
    ceiling (memory-bound).  Requires both peaks; ``mfu`` requires the
    FLOP peak; achieved rates require measured seconds."""
    out: Dict[str, object] = {}
    if seconds and seconds > 0:
        out["flops_per_s"] = flops / seconds
        out["hbm_bytes_per_s"] = hbm_bytes / seconds
        if peak_flops:
            out["mfu"] = flops / seconds / peak_flops
        if peak_bw:
            out["hbm_util"] = hbm_bytes / seconds / peak_bw
    if hbm_bytes and hbm_bytes > 0:
        intensity = flops / hbm_bytes
        out["intensity_flops_per_byte"] = round(intensity, 3)
        if peak_flops and peak_bw:
            out["bound"] = ("compute" if intensity >= peak_flops / peak_bw
                            else "memory")
    return out


_FLOPS_KEY = re.compile(r"^flops\.(total|hbm_bytes)\{(.*)\}$")


def _labels(body: str) -> Dict[str, str]:
    return dict(p.split("=", 1) for p in body.split(",") if "=" in p)


def perf_summary(snap: Dict[str, dict],
                 peaks: Tuple[Optional[float], Optional[float]]
                 = (None, None)) -> Dict[str, object]:
    """Derive the ``perf.*`` key block from a metrics snapshot.

    Reads the ``flops.total{phase=..,site=..}`` /
    ``flops.hbm_bytes{...}`` counters and the
    ``train.phase_seconds{phase=..}`` histograms; emits, per phase and
    for the total:

    - ``perf.<phase>.flops`` / ``.hbm_bytes`` — cumulative static
      accounting (deterministic, dp == serial),
    - ``.seconds`` — fenced wall time from the phase spans,
    - ``.flops_per_s`` / ``.hbm_bytes_per_s`` / ``.mfu`` /
      ``.hbm_util`` / ``.intensity_flops_per_byte`` /
      ``.bound`` (compute|memory) — the roofline join (present when
      the required timing/peaks exist).
    """
    pf, pb = peaks or (None, None)
    phases: Dict[str, Dict[str, float]] = {}
    sites: Dict[str, Dict[str, float]] = {}
    for key, rec in snap.items():
        m = _FLOPS_KEY.match(key)
        if not m or not isinstance(rec, dict):
            continue
        labels = _labels(m.group(2))
        ph = labels.get("phase", "other")
        kind = "flops" if m.group(1) == "total" else "hbm_bytes"
        site = labels.get("site")
        if site:
            ds = sites.setdefault(site, {"flops": 0.0, "hbm_bytes": 0.0})
            ds[kind] += float(rec.get("value", 0.0))
        if ph == "pad":
            # MXU lane-pad MACs (obs/flops.hist_pad_flops_bytes): real
            # hardware cycles but not useful work — surfaced per-site
            # (perf.hist_pad.*) yet EXCLUDED from phase and total
            # aggregation so perf.*.mfu never counts channel padding
            # as achieved FLOPs
            continue
        d = phases.setdefault(ph, {"flops": 0.0, "hbm_bytes": 0.0})
        d[kind] += float(rec.get("value", 0.0))
    if not phases:
        return {}
    out: Dict[str, object] = {}
    # per-SITE keys (perf.hist.*, perf.split_scan.*, ...): no fenced
    # wall time exists at site granularity (spans are per phase), so
    # only the static accounting + the timing-free roofline verdict —
    # intensity and bound are exactly what the quantized-training
    # acceptance instrument reads to show the histogram's memory bound
    # moving (docs/Quantized-Training.md)
    for site in sorted(sites):
        d = sites[site]
        pre = f"perf.{site}."
        out[pre + "flops"] = d["flops"]
        out[pre + "hbm_bytes"] = d["hbm_bytes"]
        for k, v in roofline(d["flops"], d["hbm_bytes"], 0.0,
                             pf, pb).items():
            out[pre + k] = v
    tot = {"flops": 0.0, "hbm_bytes": 0.0, "seconds": 0.0}
    for ph in sorted(phases):
        d = phases[ph]
        ph_hist = snap.get(f"train.phase_seconds{{phase={ph}}}")
        sec = float(ph_hist.get("sum", 0.0)) \
            if isinstance(ph_hist, dict) else 0.0
        pre = f"perf.{ph}."
        out[pre + "flops"] = d["flops"]
        out[pre + "hbm_bytes"] = d["hbm_bytes"]
        out[pre + "seconds"] = round(sec, 6)
        for k, v in roofline(d["flops"], d["hbm_bytes"], sec,
                             pf, pb).items():
            out[pre + k] = v
        tot["flops"] += d["flops"]
        tot["hbm_bytes"] += d["hbm_bytes"]
        tot["seconds"] += sec
    out["perf.total.flops"] = tot["flops"]
    out["perf.total.hbm_bytes"] = tot["hbm_bytes"]
    out["perf.total.seconds"] = round(tot["seconds"], 6)
    for k, v in roofline(tot["flops"], tot["hbm_bytes"], tot["seconds"],
                         pf, pb).items():
        out["perf.total." + k] = v
    if pf:
        out["perf.device.peak_flops_per_s"] = pf
    if pb:
        out["perf.device.peak_hbm_bytes_per_s"] = pb
    return out
