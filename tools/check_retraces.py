"""Retrace-budget lint: pin the number of jit traces for a canonical
config matrix so retrace regressions fail CI instead of silently
costing 73 s of compile on device.

The sibling of tools/check_syncs.py for the OTHER silent perf tax:
BENCH_r02 paid 73.4 s of XLA trace+compile before the first training
iteration vs 84 s of steady state for 99 iterations (ROADMAP item 4).
The shape-bucketing layer (utils/shapes.py: leaf-budget padding,
pinned split_batch widths, row-bucketed valid sets, pow2 serve
batches) bounds the trace family; this lint keeps that bound true
structurally:

- every library jit entry point records a ``jax.monitoring`` event
  (``/lgbtpu/trace/<name>``, utils/compile_cache.trace_event) at TRACE
  time — cache-state-independent, so the counts are deterministic for
  a fixed code + config matrix;
- the canonical matrix below (leaf-budget sweep, bagging/GOSS
  sampling, two valid-set sizes, fused chunks, serve batch mix) runs
  on CPU and the per-scenario counts must EXACTLY match
  ``tools/retrace_budget.txt``;
- entries in the budget file that the matrix no longer produces are
  reported as stale, so the file cannot rot;
- a deliberately unbucketed negative control (``trace_buckets=false``
  leaf sweep) must EXCEED the bucketed budget — proving the lint
  would catch a bucketing regression, not just rubber-stamp it.

Run via the unified driver (``python tools/lint.py``; tier-1) or
standalone (``python tools/check_retraces.py``; exit 1 on findings;
``--update`` rewrites the budget file).  Budget parsing and stale-entry
detection live in ``tools/analyze/lintlib.py``, shared with the
sync/race/purity lints.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Dict, List

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from analyze import lintlib                              # noqa: E402

REPO = lintlib.REPO
BUDGET = os.path.join(REPO, "tools", "retrace_budget.txt")
sys.path.insert(0, REPO)

_TRACE_PREFIX = "/lgbtpu/trace/"

# live monitoring-counted totals (event name -> count)
_counts: Dict[str, int] = {}


def _install_listener() -> None:
    from jax import monitoring

    def _on_event(event: str, **kw) -> None:
        if event.startswith(_TRACE_PREFIX):
            name = event[len(_TRACE_PREFIX):]
            _counts[name] = _counts.get(name, 0) + 1

    monitoring.register_event_listener(_on_event)


class _Scope:
    """Delta of the monitoring-counted traces over a scenario."""

    def __init__(self, scenario: str, into: Dict[str, int]):
        self.scenario = scenario
        self.into = into

    def __enter__(self):
        self.t0 = dict(_counts)
        return self

    def __exit__(self, *exc):
        for name, v in _counts.items():
            d = v - self.t0.get(name, 0)
            if d:
                self.into[f"{self.scenario}.{name}"] = \
                    self.into.get(f"{self.scenario}.{name}", 0) + d
        return False


def _data(n: int = 600, f: int = 12, seed: int = 0):
    import numpy as np
    rs = np.random.RandomState(seed)
    x = rs.randn(n, f)
    y = (x[:, 0] * 1.5 - x[:, 1] + 0.3 * rs.randn(n) > 0)
    return x, y.astype("float32")


def _base_params(**over):
    p = {"objective": "binary", "verbosity": 0, "min_data_in_leaf": 5,
         "max_bin": 31, "tpu_learner": "masked", "fused_chunk": 0,
         "num_leaves": 40}
    p.update(over)
    return p


def _train(lgb, x, y, rounds: int = 2, valid=None, **over):
    p = _base_params(**over)
    ds = lgb.Dataset(x, label=y, params=p)
    vs = None
    if valid:
        vs = [lgb.Dataset(vx, label=vy, params=p, reference=ds)
              for vx, vy in valid]
    return lgb.train(p, ds, num_boost_round=rounds, valid_sets=vs)


def run_matrix() -> Dict[str, int]:
    """Run the canonical scenarios; returns {scenario.counter: traces}."""
    import lightgbm_tpu as lgb
    measured: Dict[str, int] = {}
    x, y = _data()

    # 1. leaf-budget sweep: 31/40/63 bucket onto ONE L=64 grower trace
    #    (the headline of the bucketing layer)
    with _Scope("leaf_sweep", measured):
        for nl in (31, 40, 63):
            _train(lgb, x, y, num_leaves=nl)

    # 2. sampling variants re-use the sweep's trace: bagging and GOSS
    #    change VALUES (the in-bag weight column), never shapes, and
    #    the process-level grower memo must recognize the config
    with _Scope("sampling", measured):
        _train(lgb, x, y, bagging_fraction=0.7, bagging_freq=1)
        _train(lgb, x, y, data_sample_strategy="goss")

    # 2b. wide super-step (ISSUE 15): a num_leaves sweep at K=32 stays
    #    ONE grower trace — both budgets bucket onto L=64 and the
    #    lane-padded C=96->128 channel axis is a structural constant,
    #    so the wide trace family is exactly as closed as the shipped
    #    K<=16 one (33, not 31: at 31 leaves K=32 fits DOWN to 16 by
    #    utils/shapes.fit_split_batch, which is the other half of the
    #    width contract)
    with _Scope("hist_k32", measured):
        for nl in (33, 63):
            _train(lgb, x, y, num_leaves=nl, split_batch=32)

    # 3. two valid-set sizes row-bucket onto one traversal shape, so
    #    early stopping over mixed valid sets stops re-tracing
    with _Scope("valid_sizes", measured):
        _train(lgb, x, y, rounds=3, num_leaves=15,
               valid=[(x[:200], y[:200]), (x[200:430], y[200:430])],
               metric=["binary_logloss"])

    # 4. fused chunks: one chunk trace per booster today (the chunk
    #    closes over the objective), but the leaf budget rides as an
    #    argument so the HLO — and the persistent-cache key — is shared
    #    across the bucket
    with _Scope("fused", measured):
        for nl in (31, 40):
            _train(lgb, x, y, num_leaves=nl, fused_chunk=2)

    # 4b. super-epoch scan (ISSUE 16): a num_leaves sweep at k=8 with a
    #    valid set + traced metric stays ONE scan trace — the leaf
    #    budget pads 31/63 onto L=64 and `_superepoch_key` carries only
    #    bucketed shapes, so the whole-run scan (k grows + k traced
    #    evals + the ES vote) compiles once per bucket, not per config.
    #    split_batch is pinned so the grower width doesn't fork the key.
    with _Scope("superepoch", measured):
        for nl in (31, 63):
            _train(lgb, x, y, rounds=8, num_leaves=nl, superepoch=8,
                   fused_chunk=8, split_batch=1,
                   valid=[(x[:200], y[:200])],
                   metric=["binary_logloss"])

    # 4c. fleet training (ISSUE 19): an N=8 member roster mixing
    #    num_leaves 31/63 and a learning-rate grid trains through ONE
    #    vmapped super-epoch scan trace — the leaf budget pads every
    #    member onto L=64, per-member lr/seeds ride as batched operands,
    #    and `fleet_superepoch_fn` keys the program on bucketed shapes
    #    only, so the whole fleet compiles once, not once per member
    with _Scope("fleet", measured):
        from lightgbm_tpu.fleet import fleet_train
        fp = _base_params(num_leaves=31, superepoch=8, fused_chunk=8,
                          split_batch=1, metric=["binary_logloss"],
                          fused_eval=True, padded_leaves=True,
                          deterministic=True, verbosity=-1)
        mem = [{"num_leaves": 31 if j % 2 == 0 else 63,
                "learning_rate": 0.05 + 0.02 * j} for j in range(8)]
        ds = lgb.Dataset(x, label=y, params=fp)
        va = lgb.Dataset(x[:200], label=y[:200], params=fp,
                         reference=ds)
        fleet_train(fp, ds, num_boost_round=8, valid_sets=[va],
                    members=mem)

    # 5. serve batch mix: pow2-bucketed engine bounds forest traces
    with _Scope("serve_buckets", measured):
        from lightgbm_tpu.serve.engine import PredictorEngine
        bst = _train(lgb, x, y)
        eng = PredictorEngine.from_booster(bst, max_batch=64)
        for n in (3, 5, 17, 30, 64, 100):
            eng.predict(x[:n])

    # 6. fused device-resident serve path (ISSUE 10): ONE jitted
    #    bin->traverse->accumulate->transform program per (model,
    #    row-bucket) — a mixed-size batch storm (self-check probe
    #    included, registry.load runs it) must stay within the pow2
    #    bucket bound ceil(log2(serve_max_batch)) + 1
    bf1 = _train(lgb, x, y, num_leaves=8, max_depth=4)
    bf2 = _train(lgb, x, y, num_leaves=8, max_depth=4,
                 learning_rate=0.2)
    from lightgbm_tpu.serve.registry import ModelRegistry
    reg = ModelRegistry(max_batch=64, device_binning=True)
    with _Scope("serve_fused", measured):
        v1 = reg.load(booster=bf1)
        e1 = reg.get(v1).engine
        assert e1 is not None and e1.fused_reason is None
        for n in (3, 5, 17, 30, 64, 100):
            e1.fused_predict(x[:n])

    # 7. co-hosted second version of the SAME model family: the pow2
    #    SoA padding (utils/shapes.py bucket_nodes/leaf_slots/steps)
    #    lands it on identical shapes, so EVERY serve trace — fused
    #    program, traversal, self-check probe — is already cached.
    #    check() enforces zero traces here; the budget file carries no
    #    serve_cohost pins by construction
    with _Scope("serve_cohost", measured):
        v2 = reg.load(booster=bf2)
        e2 = reg.get(v2).engine
        assert e2 is not None and e2.fused_reason is None
        for n in (3, 5, 17, 30, 64, 100):
            e2.fused_predict(x[:n])

    # 7b. fleet serving (ISSUE 19): a segment-routed request mix across
    #    the co-resident versions — per-segment assignments, an unknown
    #    key falling back to default, pow2 batch sizes — must serve
    #    with ZERO forest traces: routing only picks WHICH cached
    #    engine runs, and same-family versions share every serve trace
    #    (scenario 7).  check() enforces zero like serve_cohost; the
    #    budget file carries no fleet_serve pins by construction
    with _Scope("fleet_serve", measured):
        from lightgbm_tpu.fleet import SegmentRouter
        router = SegmentRouter()
        router.assign(router.default_segment, v1)
        router.assign("eu", v2)
        router.assign("us", v1)
        for seg in ("eu", "us", "unknown-key", None):
            ver, _fb = router.resolve(seg)
            eng = reg.get(ver).engine
            for n in (3, 17, 64, 100):
                eng.fused_predict(x[:n])

    # 8. distributed leaf sweep (ROADMAP item-1 remainder): the padded
    #    leaf budget + the process-level shard_map memo in the voting
    #    and feature-parallel builders collapse a num_leaves sweep onto
    #    ONE grower trace per learner (the serial leaf_sweep guarantee,
    #    extended).  Needs >= 2 devices (run_lint arranges the virtual
    #    CPU mesh before the backend initializes).
    import jax as _jax
    if len(_jax.devices()) >= 2:
        with _Scope("dist_leaf_sweep", measured):
            for nl in (31, 63):
                _train(lgb, x, y, tree_learner="voting", num_leaves=nl)
            for nl in (31, 63):
                _train(lgb, x, y, tree_learner="feature", num_leaves=nl)

    # 9. elastic recovery ladder (ISSUE 14): the shrink path rebuilds a
    #    Booster per rung — full mesh, shrunk mesh, serial.  The
    #    process-level dp-grower memo (parallel/data_parallel._SHARED)
    #    + the padded leaf budget must give ONE grower trace per
    #    TOPOLOGY for a 31/63 sweep (not one per Booster or per
    #    num_leaves), and the serial rung re-uses scenario 1's trace —
    #    so a recovery retries rungs for free and the whole ladder
    #    costs a bounded trace family.  Needs >= 4 devices.
    if len(_jax.devices()) >= 4:
        with _Scope("elastic_ladder", measured):
            for mesh_n in (4, 2):
                for nl in (31, 63):
                    _train(lgb, x, y, tree_learner="data",
                           mesh_shape=[mesh_n], num_leaves=nl)
            for nl in (31, 63):     # the serial rung: already traced
                _train(lgb, x, y, num_leaves=nl)

    # negative control: the SAME sweep unbucketed must blow the budget
    with _Scope("negative_unbucketed", measured):
        for nl in (31, 40, 63):
            _train(lgb, x, y, num_leaves=nl, trace_buckets=False)

    return measured


def load_budget(path: str = BUDGET) -> Dict[str, int]:
    return lintlib.load_kv_int(path)


def write_budget(measured: Dict[str, int], path: str = BUDGET) -> None:
    lintlib.write_kv_int(measured, path, [
        "# Retrace budget (tools/check_retraces.py): EXACT number of",
        "# library jit traces per canonical scenario, counted via",
        "# jax.monitoring /lgbtpu/trace/* events on CPU.  A failing",
        "# entry means a retrace regression (or an intentional trace-",
        "# family change: re-pin with `python tools/check_retraces.py",
        "# --update` and justify the diff in review).",
    ])


def check(measured: Dict[str, int],
          budget: Dict[str, int]) -> List[str]:
    findings: List[str] = []
    for multidev in ("dist_leaf_sweep.", "elastic_ladder."):
        if not any(k.startswith(multidev) for k in measured):
            # multi-device scenario skipped (a backend was live before
            # run_lint could arrange the virtual mesh): its pins are not
            # stale, just unmeasurable here
            budget = {k: v for k, v in budget.items()
                      if not k.startswith(multidev)}
    for k in sorted(measured):
        if k not in budget:
            findings.append(f"unpinned counter: {k} = {measured[k]} "
                            "(add it to tools/retrace_budget.txt)")
        elif measured[k] != budget[k]:
            findings.append(
                f"trace budget violated: {k} = {measured[k]}, "
                f"pinned {budget[k]}")
    findings.extend(lintlib.stale_pins(
        {(k,) for k in budget},
        {(k,) for k in budget if k in measured}, "budget"))
    # co-hosting invariant (ISSUE 10): the second model version of one
    # family must hit the first one's compile-cache entries — ANY trace
    # during its storm is a shape-sharing regression
    for k in sorted(measured):
        if k.startswith("serve_cohost."):
            findings.append(
                f"co-hosted model re-traced: {k} = {measured[k]} "
                "(second version of one model family must share every "
                "serve trace via the pow2 SoA padding)")
        elif k.startswith("fleet_serve."):
            findings.append(
                f"segment-routed serving re-traced: {k} = {measured[k]} "
                "(the fleet router only selects which cached engine "
                "serves — a segment mix must not compile anything)")
    # the negative control must PROVE the lint catches unbucketed
    # regressions: the same sweep without bucketing has to exceed the
    # bucketed grower budget
    neg = measured.get("negative_unbucketed.grower", 0)
    pos = measured.get("leaf_sweep.grower", 0)
    if neg <= pos:
        findings.append(
            f"negative control failed: unbucketed sweep traced the "
            f"grower {neg}x, not more than the bucketed sweep's {pos}x "
            "— the lint would not catch a bucketing regression")
    return findings


def run_lint(budget_path: str = BUDGET, update: bool = False,
             verbose: bool = True) -> List[str]:
    """Measure the canonical matrix and check (or, with ``update``,
    re-pin) the budget; the driver-facing entry point.  Forces CPU the
    supported way (the axon sitecustomize freezes jax_platforms at
    interpreter start; the env var is too late — same pattern as
    bench.py / tests/conftest.py) unless LGBTPU_RETRACE_DEVICE says
    otherwise."""
    # the dist_leaf_sweep scenario needs a multi-device mesh: arrange
    # the virtual 8-device CPU topology BEFORE the backend initializes
    # (a bare `python tools/lint.py` shell has 1 CPU device; under
    # pytest the conftest already set this).  Too late if a backend is
    # live — the scenario then degrades to a skip, never a false red.
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8")
    import jax
    if os.environ.get("LGBTPU_RETRACE_DEVICE", "cpu") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    _install_listener()
    measured = run_matrix()
    if verbose:
        print("measured trace counters:")
        for k in sorted(measured):
            print(f"  {k} = {measured[k]}")
    if update:
        write_budget(measured, budget_path)
        print(f"pinned {len(measured)} counters to {budget_path}")
        return []
    return check(measured, load_budget(budget_path))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--update", action="store_true",
                    help="re-pin tools/retrace_budget.txt from this run")
    ap.add_argument("--budget", default=BUDGET,
                    help="budget file (tests point this at a temp copy)")
    args = ap.parse_args()
    findings = run_lint(args.budget, update=args.update)
    if findings:
        print("retrace lint: trace budget violations:", file=sys.stderr)
        for f in findings:
            print(f"  {f}", file=sys.stderr)
        print(f"\n{len(findings)} finding(s).  If the trace-family "
              "change is intentional, re-pin with `python "
              "tools/check_retraces.py --update`", file=sys.stderr)
        return 1
    print("retrace lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
