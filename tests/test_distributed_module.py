"""User-facing cluster orchestration e2e (VERDICT r3 task 9): >= 2 REAL
coordinated processes spawned THROUGH ``lightgbm_tpu.distributed.run``
(the dask.py:393-810 _train analog: port allocation, machines parameter,
one trainer per worker), each training via ``distributed.train`` with
row sharding + distributed binning + data-parallel growth, then the
replicated model must agree across ranks and match single-process
training quality."""

import os

import numpy as np
import pytest

from lightgbm_tpu import distributed

HERE = os.path.dirname(os.path.abspath(__file__))
PARAMS = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
          "min_data_in_leaf": 5, "verbosity": -1}
ROUNDS = 8


def test_run_spawns_coordinated_workers():
    results = distributed.run(
        "dist_worker:worker", num_workers=2,
        args={"params": PARAMS, "rounds": ROUNDS, "weighted": True},
        extra_pythonpath=[HERE], timeout=420)
    assert [r["rank"] for r in results] == [0, 1]
    # the machines parameter followed the reference conventions
    assert results[0]["machines"].count(",") == 1
    assert all(m.startswith("127.0.0.1:")
               for m in results[0]["machines"].split(","))
    # replicated model: byte-identical across ranks
    assert results[0]["model"] == results[1]["model"]
    np.testing.assert_allclose(results[0]["pred_head"],
                               results[1]["pred_head"], rtol=1e-6)

    # quality sanity vs a single-process run on the same global data
    from dist_worker import _global_data
    import sys
    sys.path.insert(0, HERE)
    import lightgbm_tpu as lgb
    from lightgbm_tpu.metrics import _auc
    x, y = _global_data()
    bst = lgb.train(dict(PARAMS), lgb.Dataset(x, label=y),
                    num_boost_round=ROUNDS)
    auc_single = _auc(y, bst.predict(x, raw_score=True), None)

    from lightgbm_tpu.booster import Booster
    dist_bst = Booster(model_str=results[0]["model"])
    auc_dist = _auc(y, dist_bst.predict(x, raw_score=True), None)
    assert auc_dist > 0.9
    assert abs(auc_single - auc_dist) < 0.05


def test_multi_host_emits_commands():
    with pytest.raises(SystemExit) as ei:
        distributed.run("dist_worker:worker", hosts=["10.0.0.1", "10.0.0.2"])
    msg = str(ei.value)
    assert "-m lightgbm_tpu.distributed" in msg
    assert "--machines 10.0.0.1:12400,10.0.0.2:12400" in msg


ESTIMATOR_PARAMS = dict(num_leaves=15, max_bin=63, min_data_in_leaf=5,
                        n_estimators=8, verbosity=-1)


def test_estimator_classifier_prepartitioned():
    """Estimator-level distributed API (VERDICT r4 task 9, the
    dask.py:1092-1417 DaskLGBMClassifier analog): fit on PRE-PARTITIONED
    per-worker data — one part per worker, never concatenated on any
    host — over 2 real coordinated processes; the fitted estimator then
    predicts locally and matches single-process quality."""
    rng = np.random.RandomState(6)
    n, f = 4000, 10
    x = rng.randn(n, f)
    y = np.where(x[:, 0] - 0.7 * x[:, 1] > 0, "pos", "neg")

    parts_x = [x[:n // 2], x[n // 2:]]
    parts_y = [y[:n // 2], y[n // 2:]]
    clf = distributed.DistributedLGBMClassifier(
        n_workers=2, timeout=420, **ESTIMATOR_PARAMS)
    # eval_set carries the RAW (string) labels — they must go through
    # the fitted class encoding, not a float cast
    clf.fit(parts_x, parts_y, eval_set=[(x[:400], y[:400])])

    assert "valid_0" in clf.evals_result_
    assert list(clf.classes_) == ["neg", "pos"]
    assert clf.n_features_ == f
    pred = clf.predict(x)
    acc = (pred == y).mean()
    assert acc > 0.93, acc
    proba = clf.predict_proba(x)
    assert proba.shape == (n, 2)

    # single-process reference point: same params, plain sklearn API
    from lightgbm_tpu.sklearn import LGBMClassifier
    ref = LGBMClassifier(**ESTIMATOR_PARAMS).fit(x, (y == "pos"))
    acc_ref = (ref.predict(x) == (y == "pos")).mean()
    assert abs(acc - acc_ref) < 0.03

    # to_local: the plain estimator carries the fitted model
    local = clf.to_local()
    assert type(local) is LGBMClassifier
    np.testing.assert_array_equal(local.predict(x), pred)


def test_estimator_regressor_global_with_eval():
    """Global-array input is partitioned for the caller; eval_set is
    replicated per worker and the metric history comes back."""
    rng = np.random.RandomState(7)
    x = rng.randn(3000, 8)
    y = 2.0 * x[:, 0] - x[:, 1] + 0.1 * rng.randn(3000)
    reg = distributed.DistributedLGBMRegressor(
        n_workers=2, timeout=420, **ESTIMATOR_PARAMS)
    reg.fit(x, y, eval_set=[(x[:500], y[:500])], eval_names=["held"])
    assert "held" in reg.evals_result_
    assert len(reg.evals_result_["held"]["l2"]) == 8
    r2 = 1.0 - np.mean((reg.predict(x) - y) ** 2) / np.var(y)
    assert r2 > 0.7, r2  # 8 rounds at lr 0.1 — fit quality, not convergence


def test_estimator_ranker_group_aligned():
    """Ranker partitioning respects query-group boundaries (dask requires
    group-aligned partitions the same way)."""
    rng = np.random.RandomState(8)
    n_q, qsize, f = 60, 25, 6
    n = n_q * qsize
    x = rng.randn(n, f)
    rel = (x[:, 0] + 0.3 * rng.randn(n) > 0.5).astype(np.float32)
    group = np.full(n_q, qsize)
    rk = distributed.DistributedLGBMRanker(
        n_workers=2, timeout=420, **ESTIMATOR_PARAMS)
    rk.fit(x, rel, group=group)
    s = rk.predict(x)
    # ranking signal present: relevant rows score higher on average
    assert s[rel > 0].mean() > s[rel == 0].mean() + 0.5


def test_estimator_rejects_feature_parallel():
    clf = distributed.DistributedLGBMClassifier(
        n_workers=2, tree_learner="feature")
    with pytest.raises(ValueError, match="tree_learner=feature"):
        clf.fit(np.zeros((10, 2)), np.zeros(10))


def test_estimator_sparse_input():
    """scipy-sparse global input rides the estimator layer row-sliced
    (never densified on the host), reaching the Dataset's native
    CSR/CSC binning — the wide-sparse path the k-hot storage exists
    for."""
    import scipy.sparse as sp
    rng = np.random.RandomState(9)
    n, f = 3000, 40
    dense = rng.randn(n, f) * (rng.rand(n, f) < 0.1)
    dense[:, 0] = rng.randn(n)                    # informative + dense
    y = (dense[:, 0] > 0).astype(np.float32)
    x = sp.csr_matrix(dense)
    clf = distributed.DistributedLGBMClassifier(
        n_workers=2, timeout=420, **ESTIMATOR_PARAMS)
    clf.fit(x, y)
    acc = (clf.predict(dense) == y).mean()
    assert acc > 0.9, acc
