"""Text data loading: CSV / TSV / LibSVM with auto-detection.

Analog of the reference Parser layer
(/root/reference/src/io/parser.hpp:18-93 CSVParser/TSVParser/LibSVMParser +
``Parser::CreateParser`` auto-detect, src/io/parser.cpp).  A native C++
fast path (lightgbm_tpu/native/parser.cpp, loaded via ctypes) accelerates
large files; this module is the API and NumPy fallback.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from .native import native_parse_csv


_PARSER_REGISTRY = {}


def register_parser(name: str, fn) -> None:
    """Pluggable custom parsers (``ParserFactory`` analog, parser.hpp:93 /
    dataset.h:304 ``CreateParser``): ``fn(path, has_header, label_column)``
    -> (features [N, F], label [N] or None).  Select with
    ``load_text(..., fmt=name)`` or the ``parser`` config key."""
    _PARSER_REGISTRY[name] = fn


def detect_format(path: str, has_header: bool = False) -> str:
    """Sniff csv/tsv/libsvm from the first data line (parser.cpp
    auto-detect analog)."""
    with open(path) as f:
        line = f.readline()
        if has_header:
            line = f.readline()
    if ":" in line.split()[1] if len(line.split()) > 1 else False:
        return "libsvm"
    first_tokens = line.strip().split("\t")
    if len(first_tokens) > 1:
        return "tsv"
    if "," in line:
        return "csv"
    # space separated libsvm check: tokens after first contain ':'
    toks = line.strip().split()
    if len(toks) > 1 and all(":" in t for t in toks[1:3]):
        return "libsvm"
    return "csv"


def load_text(path: str, has_header: bool = False,
              label_column: str = "", fmt: Optional[str] = None
              ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Load a text data file -> (features [N, F], label [N] or None).

    Default label column is the first (reference convention,
    dataset_loader.cpp label_idx_=0).
    """
    fmt = fmt or detect_format(path, has_header)
    if fmt in _PARSER_REGISTRY:
        return _PARSER_REGISTRY[fmt](path, has_header, label_column)
    if fmt == "libsvm":
        return _load_libsvm(path)
    delim = "\t" if fmt == "tsv" else ","
    native = native_parse_csv(path, delim, has_header)
    if native is not None:
        data = native
    else:
        data = np.genfromtxt(path, delimiter=delim,
                             skip_header=1 if has_header else 0,
                             dtype=np.float64)
        if data.ndim == 1:
            data = data.reshape(-1, 1)
    label_idx = 0
    if label_column.startswith("name:"):
        if not has_header:
            raise ValueError("label_column by name requires header=true")
        with open(path) as f:
            names = f.readline().strip().split(delim)
        label_idx = names.index(label_column[5:])
    elif label_column:
        label_idx = int(label_column)
    if data.shape[1] < 2:
        return data, None
    y = data[:, label_idx].astype(np.float32)
    x = np.delete(data, label_idx, axis=1)
    return x, y


def _load_libsvm(path: str) -> Tuple[np.ndarray, np.ndarray]:
    labels, rows, max_feat = [], [], -1
    with open(path) as f:
        for line in f:
            toks = line.strip().split()
            if not toks:
                continue
            labels.append(float(toks[0]))
            feats = {}
            for t in toks[1:]:
                if ":" not in t:
                    continue
                k, v = t.split(":", 1)
                k = int(k)
                feats[k] = float(v)
                max_feat = max(max_feat, k)
            rows.append(feats)
    x = np.zeros((len(rows), max_feat + 1), np.float64)
    for i, feats in enumerate(rows):
        for k, v in feats.items():
            x[i, k] = v
    return x, np.asarray(labels, np.float32)
