"""Reference ``lightgbm.basic`` compatibility surface.

The reference python-package keeps its ctypes plumbing and shared
helpers in ``basic.py`` and its OWN tests (and a fair amount of
third-party code) import from there: ``LightGBMError``,
``list_to_1d_numpy``, ``_choose_param_value``, ``_ConfigAliases``,
``_data_from_pandas`` (basic.py:391, :340, :82 in the reference).  This
module provides those names re-implemented over this framework's config
table so ``import lightgbm_tpu.basic as basic``-style code — including
the reference's own test-suite run by the parity tier
(tests/test_reference_pytests.py) — works unmodified.  There is no
ctypes plumbing here: the training core is a JAX program, not a
dynamic library.
"""

from __future__ import annotations

import warnings
from copy import deepcopy
from typing import Any, Dict, Set

import numpy as np

__all__ = ["LightGBMError", "list_to_1d_numpy", "_choose_param_value",
           "_ConfigAliases", "_data_from_pandas"]


class LightGBMError(ValueError):
    """User-input error (basic.py LightGBMError).  Subclasses ValueError
    so callers catching the generic Python error keep working while
    reference-API code catching LightGBMError gets the exact type."""


def _is_1d_collection(data) -> bool:
    return (isinstance(data, (list, tuple))
            or (isinstance(data, np.ndarray) and data.ndim == 1))


def list_to_1d_numpy(data, dtype=np.float32, name: str = "list"):
    """Coerce a 1-d collection to a numpy array (basic.py list_to_1d_numpy
    contract): column-vector ndarrays are accepted with a warning, nested
    lists are a TypeError, object Series a ValueError."""
    if isinstance(data, np.ndarray):
        if data.ndim == 2:
            if data.shape[1] != 1:
                raise ValueError(f"{name} must be 1-dimensional")
            warnings.warn(
                f"Converting column-vector {name} to 1d array", UserWarning)
            data = data.ravel()
        return data.astype(dtype=dtype, copy=False)
    if isinstance(data, (list, tuple)):
        if len(data) and isinstance(data[0], (list, tuple, np.ndarray)):
            raise TypeError(f"{name} must be a flat collection, got nested")
        return np.asarray(data, dtype=dtype)
    # pandas Series (duck-typed: no hard pandas dependency)
    if hasattr(data, "dtype") and hasattr(data, "to_numpy"):
        if data.dtype == object:
            raise ValueError(f"{name} of object dtype is not supported")
        return data.to_numpy().astype(dtype=dtype, copy=False)
    raise TypeError(f"cannot convert {type(data).__name__} to 1d numpy "
                    f"array for {name}")


class _ConfigAliases:
    """Canonical-name -> alias-set table (the reference builds this by
    calling LGBM_DumpParamAliases into a JSON buffer, basic.py:344; here
    the config table IS the source)."""

    aliases: Dict[str, Set[str]] = None

    @classmethod
    def _build(cls) -> None:
        if cls.aliases is not None:
            return
        from .config import _PARAMS
        cls.aliases = {name: set(al) | {name}
                       for name, (_t, _d, al) in _PARAMS.items()}

    @classmethod
    def get(cls, *args: str) -> Set[str]:
        cls._build()
        out: Set[str] = set()
        for name in args:
            out |= cls.aliases.get(name, {name})
        return out


def _choose_param_value(main_param_name: str, params: Dict[str, Any],
                        default_value: Any) -> Dict[str, Any]:
    """One value for ``main_param_name`` with every alias removed; the
    canonical spelling wins over aliases — by PRESENCE, so an explicit
    ``None`` under the canonical key is preserved rather than overridden
    by an alias (the reference returns immediately when the main name is
    in params) — and aliases win over the default (basic.py:391
    contract)."""
    params = deepcopy(params)
    found_main = main_param_name in params
    found = params.get(main_param_name)
    for alias in _ConfigAliases.get(main_param_name):
        val = params.pop(alias, None)
        if not found_main and found is None and val is not None:
            found = val
    if found_main:
        params[main_param_name] = found
    else:
        params[main_param_name] = default_value if found is None else found
    return params


def _data_from_pandas(data, feature_name=None, categorical_feature=None,
                      pandas_categorical=None):
    """DataFrame -> (float ndarray, feature_name, categorical_feature,
    pandas_categorical) — the reference's pandas ingestion contract
    (basic.py _data_from_pandas), including the no-copy fast path when
    every column already shares one float dtype."""
    if not (hasattr(data, "columns") and hasattr(data, "dtypes")):
        raise ValueError("data should be a pandas DataFrame")
    if feature_name in (None, "auto"):
        feature_name = [str(c) for c in data.columns]
    dtypes = {str(dt) for dt in data.dtypes}
    if dtypes == {"float64"}:
        arr = data.to_numpy(dtype=np.float64, copy=False)
    elif dtypes == {"float32"}:
        arr = data.to_numpy(dtype=np.float32, copy=False)
    else:
        arr = data.astype(np.float64).to_numpy()
    return arr, feature_name, categorical_feature, pandas_categorical
