"""Unit tests for histogram construction, split search and the tree grower."""

import jax.numpy as jnp
import numpy as np
import pytest

from lightgbm_tpu.ops.histogram import compute_histogram
from lightgbm_tpu.ops.split import SplitParams, find_best_split, leaf_output
from lightgbm_tpu.grower import make_grower


def _ref_hist(binned, vals, B):
    n, f = binned.shape
    ref = np.zeros((f, B, vals.shape[1]))
    for fi in range(f):
        for b in range(B):
            m = binned[:, fi] == b
            ref[fi, b] = vals[m].sum(axis=0)
    return ref


class TestHistogram:
    def test_matches_reference_loop(self):
        rng = np.random.RandomState(0)
        N, F, B = 2000, 5, 16
        binned = rng.randint(0, B, size=(N, F)).astype(np.uint8)
        g = rng.randn(N).astype(np.float32)
        vals = np.stack([g, np.abs(g), np.ones(N, np.float32)], axis=1)
        hist = np.array(compute_histogram(jnp.array(binned), jnp.array(vals), num_bins=B))
        ref = _ref_hist(binned, vals, B)
        np.testing.assert_allclose(hist, ref, rtol=1e-4, atol=1e-3)

    def test_masked_rows_excluded(self):
        rng = np.random.RandomState(1)
        N, F, B = 512, 3, 8
        binned = rng.randint(0, B, size=(N, F)).astype(np.uint8)
        vals = np.ones((N, 3), np.float32)
        mask = (rng.rand(N) < 0.5).astype(np.float32)
        hist = np.array(compute_histogram(
            jnp.array(binned), jnp.array(vals * mask[:, None]), num_bins=B))
        assert hist[0, :, 2].sum() == pytest.approx(mask.sum())

    def test_nonuniform_block(self):
        # N not divisible by block_rows exercises the padding path
        rng = np.random.RandomState(2)
        N, F, B = 1037, 4, 8
        binned = rng.randint(0, B, size=(N, F)).astype(np.uint8)
        vals = np.ones((N, 3), np.float32)
        hist = np.array(compute_histogram(jnp.array(binned), jnp.array(vals),
                                          num_bins=B, block_rows=256))
        assert hist[2, :, 2].sum() == pytest.approx(N)


class TestSplit:
    def _mk(self, binned, g, h, B):
        N, F = binned.shape
        vals = np.stack([g, h, np.ones(N, np.float32)], axis=1)
        hist = compute_histogram(jnp.array(binned), jnp.array(vals), num_bins=B)
        total = jnp.asarray(vals.sum(axis=0), dtype=jnp.float32)
        return hist, total

    def test_finds_informative_feature(self):
        rng = np.random.RandomState(0)
        N, F, B = 4000, 6, 16
        binned = rng.randint(0, B, size=(N, F)).astype(np.uint8)
        y = (binned[:, 2] >= 8).astype(np.float32)
        g = (0.5 - y).astype(np.float32)
        h = np.ones(N, np.float32)
        hist, total = self._mk(binned, g, h, B)
        res = find_best_split(hist, total, jnp.full(F, B, jnp.int32),
                              jnp.full(F, -1, jnp.int32), jnp.ones(F, bool),
                              SplitParams(min_data_in_leaf=5))
        assert int(res.feature) == 2
        assert int(res.threshold) == 7  # left = bins <= 7
        assert float(res.gain) > 0

    def test_gain_matches_closed_form(self):
        # two bins, exact gain formula: GL^2/HL + GR^2/HR - G^2/H
        binned = np.array([[0], [0], [1], [1]], dtype=np.uint8)
        g = np.array([-1.0, -1.0, 1.0, 2.0], np.float32)
        h = np.ones(4, np.float32)
        hist, total = self._mk(binned, g, h, 2)
        p = SplitParams(min_data_in_leaf=1, min_sum_hessian_in_leaf=0.0)
        res = find_best_split(hist, total, jnp.full(1, 2, jnp.int32),
                              jnp.full(1, -1, jnp.int32), jnp.ones(1, bool), p)
        expect = (-2.0) ** 2 / 2 + 3.0 ** 2 / 2 - 1.0 ** 2 / 4
        assert float(res.gain) == pytest.approx(expect, rel=1e-5)
        assert float(res.left_output) == pytest.approx(1.0)   # -(-2)/2
        assert float(res.right_output) == pytest.approx(-1.5)  # -(3)/2

    def test_min_data_constraint(self):
        binned = np.array([[0], [1], [1], [1]], dtype=np.uint8)
        g = np.array([-5.0, 1.0, 1.0, 1.0], np.float32)
        h = np.ones(4, np.float32)
        hist, total = self._mk(binned, g, h, 2)
        p = SplitParams(min_data_in_leaf=2, min_sum_hessian_in_leaf=0.0)
        res = find_best_split(hist, total, jnp.full(1, 2, jnp.int32),
                              jnp.full(1, -1, jnp.int32), jnp.ones(1, bool), p)
        assert float(res.gain) == -np.inf  # only split leaves 1 row left

    def test_lambda_l2_shrinks_output(self):
        binned = np.array([[0], [0], [1], [1]], dtype=np.uint8)
        g = np.array([-1.0, -1.0, 1.0, 1.0], np.float32)
        h = np.ones(4, np.float32)
        hist, total = self._mk(binned, g, h, 2)
        p = SplitParams(min_data_in_leaf=1, min_sum_hessian_in_leaf=0.0, lambda_l2=2.0)
        res = find_best_split(hist, total, jnp.full(1, 2, jnp.int32),
                              jnp.full(1, -1, jnp.int32), jnp.ones(1, bool), p)
        assert float(res.left_output) == pytest.approx(2.0 / 4.0)  # -(-2)/(2+2)

    def test_missing_direction(self):
        # feature with NaN bin: put strong negative grads in the NaN bin;
        # best dir should send missing left with the negative group
        B = 4
        binned = np.concatenate([
            np.zeros(50, np.uint8), np.ones(50, np.uint8) * 1,
            np.ones(30, np.uint8) * 3,  # na bin
        ]).reshape(-1, 1)
        g = np.concatenate([-np.ones(50), np.ones(50), -np.ones(30)]).astype(np.float32)
        h = np.ones(130, np.float32)
        hist, total = self._mk(binned, g, h, B)
        p = SplitParams(min_data_in_leaf=1, min_sum_hessian_in_leaf=0.0)
        res = find_best_split(hist, total, jnp.full(1, 4, jnp.int32),
                              jnp.full(1, 3, jnp.int32), jnp.ones(1, bool), p)
        assert bool(res.default_left)
        assert int(res.threshold) == 0


class TestGrower:
    def test_grows_and_partitions(self):
        rng = np.random.RandomState(0)
        N, F, B, L = 5000, 6, 16, 8
        binned = rng.randint(0, B, size=(N, F)).astype(np.uint8)
        y = (binned[:, 2] >= 8).astype(np.float32) + 0.1 * rng.randn(N).astype(np.float32)
        g = (0.5 - y).astype(np.float32)
        vals = np.stack([g, np.ones(N, np.float32), np.ones(N, np.float32)], axis=1)
        grow = make_grower(num_leaves=L, num_bins=B, params=SplitParams(min_data_in_leaf=5))
        tree = grow(jnp.array(binned), jnp.array(vals), jnp.ones(F, bool),
                    jnp.full(F, B, jnp.int32), jnp.full(F, -1, jnp.int32))
        nl = int(tree.num_leaves)
        assert 2 <= nl <= L
        # leaf counts of active leaves sum to N
        assert float(np.array(tree.leaf_count)[:nl].sum()) == pytest.approx(N)
        # row partition agrees with leaf counts
        bc = np.bincount(np.array(tree.leaf_of_row), minlength=L)
        np.testing.assert_allclose(bc[:nl], np.array(tree.leaf_count)[:nl])
        # first split must use the informative feature
        assert int(np.array(tree.split_feature)[0]) == 2

    def test_partition_consistent_with_tree(self):
        """Rows' final leaves must equal a traversal of the built tree."""
        rng = np.random.RandomState(3)
        N, F, B, L = 2000, 5, 8, 6
        binned = rng.randint(0, B, size=(N, F)).astype(np.uint8)
        g = rng.randn(N).astype(np.float32)
        vals = np.stack([g, np.ones(N, np.float32), np.ones(N, np.float32)], axis=1)
        grow = make_grower(num_leaves=L, num_bins=B, params=SplitParams(min_data_in_leaf=10))
        tree = grow(jnp.array(binned), jnp.array(vals), jnp.ones(F, bool),
                    jnp.full(F, B, jnp.int32), jnp.full(F, -1, jnp.int32))
        nl = int(tree.num_leaves)
        sf = np.array(tree.split_feature)
        th = np.array(tree.threshold_bin)
        lc = np.array(tree.left_child)
        rc = np.array(tree.right_child)
        leaves = np.array(tree.leaf_of_row)
        if nl < 2:
            pytest.skip("no split found")
        for i in rng.choice(N, 200, replace=False):
            node = 0
            while node >= 0:
                node = lc[node] if binned[i, sf[node]] <= th[node] else rc[node]
            assert ~node == leaves[i]

    def test_max_depth(self):
        rng = np.random.RandomState(4)
        N, F, B, L = 3000, 6, 16, 16
        binned = rng.randint(0, B, size=(N, F)).astype(np.uint8)
        g = rng.randn(N).astype(np.float32)
        vals = np.stack([g, np.ones(N, np.float32), np.ones(N, np.float32)], axis=1)
        grow = make_grower(num_leaves=L, num_bins=B,
                           params=SplitParams(min_data_in_leaf=5), max_depth=2)
        tree = grow(jnp.array(binned), jnp.array(vals), jnp.ones(F, bool),
                    jnp.full(F, B, jnp.int32), jnp.full(F, -1, jnp.int32))
        assert int(tree.num_leaves) <= 4  # depth-2 tree has at most 4 leaves
        assert int(np.array(tree.leaf_depth)[:int(tree.num_leaves)].max()) <= 2




class TestPathSmooth:
    """path_smooth parity with the reference formula
    (feature_histogram.hpp:742-764): the smoothing weight uses the leaf's
    DATA COUNT, not its hessian sum — they differ for every
    non-unit-hessian objective — and max_delta_step clamps BEFORE the
    smoothing blend."""

    @staticmethod
    def _ref_output(g, h, l1, l2, mds, smooth, n, parent):
        t = np.sign(g) * max(abs(g) - l1, 0.0) if l1 > 0 else g
        ret = -t / (h + l2)
        if mds > 0 and abs(ret) > mds:
            ret = np.sign(ret) * mds
        if smooth > 0:
            ret = (ret * (n / smooth) / (n / smooth + 1)
                   + parent / (n / smooth + 1))
        return ret

    def test_leaf_output_formula_weighted(self):
        # hessian sum deliberately != data count (binary-like hessians)
        cases = [
            (3.7, 12.4, 0.0, 1.0, 0.0, 5.0, 80.0, -0.3),
            (-2.1, 4.9, 0.5, 0.1, 0.0, 2.0, 33.0, 0.7),
            (9.0, 1.5, 0.0, 0.0, 0.5, 10.0, 400.0, 0.1),  # clamp then smooth
            (-6.2, 2.2, 1.0, 2.0, 0.3, 1.0, 7.0, -1.4),
        ]
        for g, h, l1, l2, mds, smooth, n, parent in cases:
            p = SplitParams(lambda_l1=l1, lambda_l2=l2, max_delta_step=mds,
                            path_smooth=smooth)
            got = float(leaf_output(jnp.float32(g), jnp.float32(h), p,
                                    jnp.float32(parent), jnp.float32(n)))
            want = self._ref_output(g, h, l1, l2, mds, smooth, n, parent)
            np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_grown_leaf_values_match_formula(self):
        # grow one 2-leaf tree with NON-UNIT hessians and check both leaf
        # values against the reference formula using per-leaf (g, h, count)
        # sums recomputed host-side
        rng = np.random.RandomState(5)
        N, B, smooth = 600, 16, 4.0
        binned = rng.randint(0, B, size=(N, 2)).astype(np.uint8)
        g = rng.randn(N).astype(np.float32)
        h = (0.05 + rng.rand(N) * 0.4).astype(np.float32)   # h != 1
        vals = jnp.asarray(np.stack([g, h, np.ones(N, np.float32)], axis=1))
        p = SplitParams(path_smooth=smooth, min_data_in_leaf=5)
        grow = make_grower(num_leaves=2, num_bins=B, params=p)
        tree = grow(jnp.asarray(binned), vals,
                    jnp.ones(2, bool), jnp.full(2, B, jnp.int32),
                    jnp.full(2, -1, jnp.int32))
        assert int(tree.num_leaves) == 2
        leaf_of_row = np.asarray(tree.leaf_of_row)
        root_parent = self._ref_output(g.sum(), h.sum(), 0, 0, 0, 0, N, 0)
        for leaf in (0, 1):
            m = leaf_of_row == leaf
            want = self._ref_output(g[m].sum(), h[m].sum(), 0.0, 0.0, 0.0,
                                    smooth, m.sum(), root_parent)
            np.testing.assert_allclose(float(tree.leaf_value[leaf]), want,
                                       rtol=2e-4)
            # the hessian-weight approximation would differ measurably here
            wrong = self._ref_output(g[m].sum(), h[m].sum(), 0.0, 0.0, 0.0,
                                     smooth, h[m].sum(), root_parent)
            assert abs(want - wrong) > 1e-3
