"""Static-analysis suite (docs/Static-Analysis.md).

AST-based lints in the check_syncs/check_retraces mold, run in tier-1
through the unified driver ``tools/lint.py``:

- ``check_races``  — lock-discipline race lint for the threaded
  serve/continual stack (guard-map inference, unguarded-access and
  multi-writer findings, static lock-order deadlock detection);
- ``check_purity`` — jit-purity lint for every function reachable
  inside a traced body (host side effects that would escape a tracer);
- ``lintlib``      — the shared allowlist/pin parser, stale-entry
  detection and finding plumbing the whole lint family
  (syncs, retraces, races, purity) is built on.
"""
