"""Crash-safe training snapshots + auto-resume.

The reference's ``snapshot_freq`` (gbdt.cpp:279-284) writes the model
text mid-training but never reads it back — resuming means the operator
hand-wiring ``input_model``.  After the round-5 outage (10 h tunnel
wedge, no way to continue the run) this module closes the loop:

- :func:`write_snapshot` — the model text, a ``.state.npz`` sidecar (the
  f32 training score, so a resumed run continues from the EXACT device
  state rather than a re-predicted approximation of it) and a
  ``.manifest.json`` sidecar (iteration, params signature, data
  fingerprint, SHA-256 checksums of the model and state bytes — readers
  verify the artifacts they find are the artifacts the manifest
  describes).  All three go through ``resilience.atomic_write``; the
  manifest is written LAST, so its presence marks a complete snapshot —
  a crash mid-snapshot leaves the previous snapshot as the newest valid
  one.  Old snapshots are pruned to ``snapshot_keep``.
- :func:`find_latest_snapshot` — newest snapshot whose manifest parses,
  whose params signature matches the current run (so a changed learning
  rate can't silently splice into an old model), and whose data
  fingerprint matches the current dataset.  Invalid/mismatched
  candidates are warned about and skipped in favor of older ones.
- :func:`params_signature` — canonicalized-params hash with
  resume-control keys (``resume``, ``snapshot_freq`` …) excluded, so
  toggling snapshot bookkeeping never invalidates a snapshot.

``engine.train`` consumes these when ``resume=true``: the found model
feeds the existing ``init_model`` continued-training path, the state
score becomes the dataset's init score, and the booster's
iteration-keyed RNG streams are fast-forwarded
(``GBDTModel.set_resume_state``) — train-straight and crash-then-resume
produce byte-identical model text (tests/test_fault_tolerance.py).
"""

from __future__ import annotations

import contextlib
import glob
import hashlib
import io
import json
import os
import re
import threading
from typing import Any, Dict, Optional, Set, Tuple

import numpy as np

from .utils.log import Log
from .utils.resilience import atomic_write

_FORMAT = 1

# params that control snapshot/resume bookkeeping rather than the trained
# model — excluded from the signature so (a) toggling them between runs
# never invalidates a snapshot and (b) resuming with a LARGER
# num_iterations ("train 1M more") is allowed
_VOLATILE = {
    "resume", "snapshot_freq", "snapshot_keep", "num_iterations",
    "output_model", "input_model", "verbosity", "task", "data", "valid",
    "config", "machines", "machine_list_filename",
    # bring-up resilience knobs never affect the trained model, and
    # raising them is the NATURAL response to the crash being resumed
    # from — they must not invalidate the snapshot
    "dist_init_retries", "dist_init_timeout_s", "dist_fallback_serial",
    # computation-integrity knobs (lightgbm_tpu/integrity.py): checks
    # and transient-absorbed re-runs are byte-identical to unchecked
    # training, and turning detection ON is the natural response to
    # the corruption being resumed from
    "integrity_check_freq", "integrity_policy", "integrity_ulp_tol",
}

# Topology keys, volatile ONLY under elastic training
# (elastic_enable=true): the recovery ladder's whole premise is that
# the data-parallel owner-shard reduce makes global histograms
# shard-count invariant (dp == serial), so a run that started on an
# 8-wide mesh may legitimately resume on 4, 2, or serially — the
# topology is where the run executes, not what it trains.  Outside
# elastic these keys stay signature-relevant (voting's per-shard
# votes, for one, are topology-dependent).
_TOPOLOGY_VOLATILE = {"tree_learner", "num_machines", "mesh_shape",
                      "dp_owner_shard"}


def params_signature(params: Dict[str, Any]) -> str:
    """Stable hash of the training-relevant parameter surface."""
    from .config import _coerce, canonical_params
    cp = canonical_params(params)
    elastic = bool(_coerce("elastic_enable", bool,
                           cp.get("elastic_enable", False)))
    for k in _VOLATILE:
        cp.pop(k, None)
    for k in list(cp):
        # every elastic_* knob is run control (deadlines, heartbeat
        # cadence, ladder budgets) — never the trained model
        if k.startswith("elastic_"):
            cp.pop(k)
    if elastic:
        for k in _TOPOLOGY_VOLATILE:
            cp.pop(k, None)
    blob = json.dumps(cp, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def sha256_hex(data) -> str:
    """SHA-256 of ``data`` (str encoded as UTF-8)."""
    if isinstance(data, str):
        data = data.encode()
    return hashlib.sha256(data).hexdigest()


def file_sha256(path) -> str:
    """Streamed SHA-256 of a file's bytes."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def verify_snapshot_artifacts(path: str, man: Dict[str, Any],
                              state: bool = True) -> Optional[str]:
    """Check the snapshot's files against the checksums its manifest
    recorded; returns an error string on mismatch/unreadable, None when
    everything matches.  Manifests written before checksums existed
    record none — they verify vacuously (presence of the
    manifest-written-last marker is still the completeness signal).
    ``state=False`` skips the ``.state.npz`` sidecar: serving never
    reads it, so a reader that only needs the model must neither pay
    its hashing I/O nor refuse an otherwise servable snapshot over it."""
    pairs = [("model_sha256", path)]
    if state:
        pairs.append(("state_sha256", path + ".state.npz"))
    for key, p in pairs:
        want = man.get(key)
        if not want:
            continue
        try:
            got = file_sha256(p)
        except OSError as e:
            return f"{os.path.basename(p)} unreadable ({e})"
        if got != want:
            return (f"{os.path.basename(p)} checksum mismatch "
                    f"(file {got[:12]}…, manifest {want[:12]}…)")
    return None


# -- reader pins: close the find->open TOCTOU window -----------------------
# A reader (serving hot-load, training resume) locates a snapshot with a
# finder and only then opens its files; a concurrent writer's
# prune_snapshots could delete that very generation in between (a
# continual pipeline publishes + prunes while a registry loads).  Readers
# pin the path for the duration; prune holds newest-N PLUS every pinned
# generation.
_pin_lock = threading.Lock()
_pinned: Dict[str, int] = {}


@contextlib.contextmanager
def pin_snapshot(path: str):
    """Hold ``path`` (a snapshot model file) against
    :func:`prune_snapshots` while a reader is between locating it and
    finishing reading its files.  Re-entrant across threads (counted)."""
    key = os.path.abspath(path)
    with _pin_lock:
        _pinned[key] = _pinned.get(key, 0) + 1
    try:
        yield path
    finally:
        with _pin_lock:
            n = _pinned.get(key, 0) - 1
            if n <= 0:
                _pinned.pop(key, None)
            else:
                _pinned[key] = n


def pinned_snapshots() -> Set[str]:
    """Absolute paths currently pinned by active readers."""
    with _pin_lock:
        return set(_pinned)


def _snapshot_path(output_model: str, iteration: int) -> str:
    return f"{output_model}.snapshot_iter_{iteration}"


def _list_snapshots(output_model: str):
    """[(iteration, model_path)] for existing snapshot MODEL files,
    newest first.  Sidecars and atomic-write temp debris are ignored."""
    pat = re.compile(re.escape(os.path.basename(output_model))
                     + r"\.snapshot_iter_(\d+)$")
    out = []
    for path in glob.glob(glob.escape(output_model) + ".snapshot_iter_*"):
        m = pat.match(os.path.basename(path))
        if m:
            out.append((int(m.group(1)), path))
    out.sort(reverse=True)
    return out


def write_snapshot(booster, prev_booster, cfg, iteration: int,
                   signature: str, train_set) -> None:
    """Persist one snapshot (model + state + manifest, in that order)
    and prune to ``cfg.snapshot_keep``.  ``prev_booster`` (continued
    training / an earlier resume) contributes its leading trees so the
    snapshot is the FULL model, not just this run's suffix."""
    base = _snapshot_path(cfg.output_model, iteration)
    trees, weights = booster.trees, booster.tree_weights
    if prev_booster is not None:
        booster.trees = prev_booster.trees + trees
        booster.tree_weights = list(prev_booster.tree_weights) + list(weights)
    try:
        text = booster.model_to_string()
    finally:
        booster.trees, booster.tree_weights = trees, weights
    # under elastic multi-process training the model supplies GLOBAL
    # state (all-process score in global row order + the full-data
    # fingerprint) so a shrunk — even single-process — relaunch can
    # resume this snapshot; everywhere else this is exactly the local
    # score and the train set's own fingerprint
    fp_override = None
    state_fn = getattr(booster._model, "snapshot_state", None)
    if state_fn is not None:
        score, fp_override = state_fn()
        score = np.asarray(score, np.float32)
    else:
        score = np.asarray(booster._model.score, np.float32)
    buf = io.BytesIO()
    np.savez_compressed(buf, score=score)
    # encode ONCE and write binary: the hashed bytes must be the
    # written bytes (text mode would re-encode under the locale's
    # charset / newline rules, desynchronizing the checksum)
    text_bytes = text.encode("utf-8")
    manifest = {
        "format": _FORMAT,
        "iteration": int(iteration),
        "params_signature": signature,
        "data_fingerprint": fp_override or train_set.fingerprint(),
        "num_data": int(score.shape[0]),
        "num_class": int(score.shape[1]) if score.ndim > 1 else 1,
        "model_file": os.path.basename(base),
        "state_file": os.path.basename(base) + ".state.npz",
        # artifact checksums, computed from the EXACT bytes written
        # below: a reader (training resume, serving hot-load) can prove
        # the files it found are the files this manifest describes —
        # bit rot and torn/foreign files are refused, not loaded
        "model_sha256": sha256_hex(text_bytes),
        "state_sha256": sha256_hex(buf.getvalue()),
    }
    # computation-integrity stamp (lightgbm_tpu/integrity.py): present
    # only when integrity_check_freq > 0, so manifests stay
    # byte-identical to pre-integrity ones with the layer off.
    # ``verified`` means the snapshot's newest tree passed a shadow
    # compare (engine runs integrity_boundary_check first) — the stamp
    # find_latest_snapshot prefers when choosing a rewind target
    int_fn = getattr(booster._model, "integrity_manifest", None)
    if int_fn is not None:
        stamp = int_fn(int(iteration))
        if stamp is not None:
            manifest["integrity"] = stamp
    atomic_write(base, text_bytes, binary=True)
    atomic_write(base + ".state.npz", buf.getvalue(), binary=True)
    # manifest last: its presence marks the snapshot complete
    atomic_write(base + ".manifest.json",
                 json.dumps(manifest, indent=1, sort_keys=True))
    prune_snapshots(cfg.output_model, cfg.snapshot_keep)


def prune_snapshots(output_model: str, keep: int) -> None:
    """Delete all but the ``keep`` newest snapshots (model + sidecars);
    ``keep <= 0`` keeps everything.  Generations pinned by an active
    reader (:func:`pin_snapshot` — a registry hot-load or resume that
    located the snapshot but has not finished reading it) are held
    regardless of age; they become prunable again at the next prune
    after the reader unpins."""
    if keep <= 0:
        return
    pinned = pinned_snapshots()
    for _it, path in _list_snapshots(output_model)[keep:]:
        if os.path.abspath(path) in pinned:
            continue
        for p in (path + ".manifest.json", path + ".state.npz", path):
            try:
                os.unlink(p)
            except OSError:
                pass


def find_latest_complete_snapshot(output_model: str, verify: bool = True
                                  ) -> Optional[Tuple[int, str]]:
    """Newest snapshot of ``output_model`` whose manifest is present,
    parseable and format-matching, as ``(iteration, model_path)`` — the
    SERVING-side lookup (serve/registry.py hot reload): unlike
    :func:`find_latest_snapshot`, no params-signature or
    data-fingerprint check applies because a serving process has
    neither; the manifest-written-last marker alone distinguishes a
    complete snapshot from an interrupted write.  ``verify`` gates the
    manifest-checksum pass over the candidate's MODEL file — the
    ``.state.npz`` training sidecar is never hashed here because
    serving never reads it (a bit-rotted state must not block serving
    an intact model).  ``serve_verify_artifacts=false`` skips the
    hashing to shave load latency — corrupt candidates are then only
    caught if they fail to parse.  The find-time hash selects a clean
    candidate (bit-rotted newest falls back to an older complete
    snapshot); the loader's pinned re-hash of the same file
    (registry.load ``expected_sha256``) is a different job — the
    TOCTOU guarantee that the bytes activated are the bytes verified."""
    for it, path in _list_snapshots(output_model):
        try:
            with open(path + ".manifest.json", encoding="utf-8") as f:
                man = json.load(f)
        except (OSError, ValueError) as e:
            Log.warning(f"snapshot {path} skipped: manifest unreadable "
                        f"({e})")
            continue
        if man.get("format") != _FORMAT:
            Log.warning(f"snapshot {path} skipped: unknown manifest "
                        f"format {man.get('format')!r}")
            continue
        if verify:
            err = verify_snapshot_artifacts(path, man, state=False)
            if err is not None:
                Log.warning(f"snapshot {path} skipped: {err}")
                continue
        return it, path
    return None


def find_latest_snapshot(output_model: str, signature: str,
                         train_set) -> Optional[Tuple[int, str, np.ndarray]]:
    """Newest VALID snapshot as ``(iteration, model_path, score)``, or
    None.  Valid = manifest present and parseable, params signature and
    data fingerprint match, state loads.  Invalid candidates are skipped
    with a warning (an interrupted snapshot write leaves a model file
    with no manifest — exactly the case this walks past).

    ``elastic_global_fingerprint`` on the train set (set by
    ``parallel/elastic.elastic_train`` on multi-process shard datasets)
    overrides the shard's own fingerprint: elastic multi-process
    manifests are stamped with the GLOBAL data fingerprint
    (``GBDTModel.snapshot_state``), which the shard hash would never
    match.

    Integrity preference (lightgbm_tpu/integrity.py): among valid
    candidates, the newest whose manifest carries an
    ``integrity.verified == true`` stamp wins over a NEWER valid but
    unverified one — an SDC rewind must never land on a snapshot whose
    history could itself be corrupt.  With no verified candidate (or
    no integrity stamps at all, the ``integrity_check_freq=0`` world)
    the newest valid snapshot is returned exactly as before."""
    fp = getattr(train_set, "elastic_global_fingerprint", None) \
        or train_set.fingerprint()
    fallback: Optional[Tuple[int, str, np.ndarray]] = None
    for it, path in _list_snapshots(output_model):
        man_path = path + ".manifest.json"
        try:
            with open(man_path, encoding="utf-8") as f:
                man = json.load(f)
        except (OSError, ValueError) as e:
            Log.warning(f"snapshot {path} skipped: manifest unreadable "
                        f"({e})")
            continue
        if man.get("format") != _FORMAT:
            Log.warning(f"snapshot {path} skipped: unknown manifest "
                        f"format {man.get('format')!r}")
            continue
        if man.get("params_signature") != signature:
            Log.warning(f"snapshot {path} skipped: training parameters "
                        "differ from the run that wrote it")
            continue
        if man.get("data_fingerprint") != fp:
            Log.warning(f"snapshot {path} skipped: dataset fingerprint "
                        "differs from the run that wrote it")
            continue
        err = verify_snapshot_artifacts(path, man)
        if err is not None:
            Log.warning(f"snapshot {path} skipped: {err}")
            continue
        try:
            with np.load(path + ".state.npz") as z:
                score = np.asarray(z["score"], np.float32)
        except (OSError, ValueError, KeyError) as e:
            Log.warning(f"snapshot {path} skipped: state sidecar "
                        f"unreadable ({e})")
            continue
        if int(man.get("iteration", -1)) != it:
            Log.warning(f"snapshot {path} skipped: manifest iteration "
                        f"{man.get('iteration')} != filename {it}")
            continue
        stamp = man.get("integrity")
        if isinstance(stamp, dict) and not stamp.get("verified", False):
            # valid but integrity-UNVERIFIED: hold as the fallback and
            # keep walking for an older verified snapshot
            if fallback is None:
                fallback = (it, path, score)
            Log.warning(f"snapshot {path} is not integrity-verified; "
                        "looking for an older verified snapshot")
            continue
        if fallback is not None:
            Log.warning(
                f"resuming from integrity-verified snapshot iter {it} "
                f"instead of newer unverified iter {fallback[0]}")
        return it, path, score
    if fallback is not None:
        Log.warning(f"no integrity-verified snapshot found; resuming "
                    f"from unverified iter {fallback[0]}")
    return fallback
