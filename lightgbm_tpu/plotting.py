"""Plotting utilities (reference: python-package/lightgbm/plotting.py).

``plot_importance`` / ``plot_split_value_histogram`` / ``plot_metric`` /
``plot_tree`` / ``create_tree_digraph`` with matplotlib / graphviz gated at
call time like the reference (plotting.py _check_not_tuple_of_2_elements
import pattern).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

import numpy as np

from .booster import Booster
from .sklearn import LGBMModel


def _to_booster(model) -> Booster:
    if isinstance(model, LGBMModel):
        return model.booster_
    if isinstance(model, Booster):
        return model
    raise TypeError("model must be a Booster or LGBMModel")


def _import_matplotlib():
    try:
        import matplotlib.pyplot as plt
        return plt
    except ImportError as e:
        raise ImportError("matplotlib is required for plotting") from e


def plot_importance(model, ax=None, height: float = 0.2, xlim=None, ylim=None,
                    title: str = "Feature importance",
                    xlabel: str = "Feature importance",
                    ylabel: str = "Features",
                    importance_type: str = "split",
                    max_num_features: Optional[int] = None,
                    ignore_zero: bool = True, figsize=None, dpi=None,
                    grid: bool = True, precision: int = 3, **kwargs):
    plt = _import_matplotlib()
    booster = _to_booster(model)
    imp = booster.feature_importance(importance_type)
    names = booster.feature_names or [f"Column_{i}" for i in range(len(imp))]
    tuples = sorted(zip(names, imp), key=lambda t: t[1])
    if ignore_zero:
        tuples = [t for t in tuples if t[1] > 0]
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    if not tuples:
        raise ValueError("no features with importance > 0")
    labels, values = zip(*tuples)
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        ax.text(x + 1, y, f"{x:.{precision}g}" if isinstance(x, float)
                else str(x), va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_split_value_histogram(model, feature, bins=None, ax=None,
                               width_coef: float = 0.8, xlim=None, ylim=None,
                               title="Split value histogram for feature with "
                                     "@feature@ @index/name@",
                               xlabel="Feature split value", ylabel="Count",
                               figsize=None, dpi=None, grid=True, **kwargs):
    plt = _import_matplotlib()
    booster = _to_booster(model)
    if isinstance(feature, str):
        feature = booster.feature_names.index(feature)
    values = []
    for t in booster.trees:
        for i in range(t.num_nodes()):
            if t.split_feature[i] == feature and not (t.decision_type[i] & 1):
                values.append(t.threshold[i])
    if not values:
        raise ValueError(f"feature {feature} was not used in any split")
    values = np.asarray(values)
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    hist, edges = np.histogram(values, bins=bins or min(len(values), 20))
    centers = (edges[:-1] + edges[1:]) / 2
    ax.bar(centers, hist, width=width_coef * (edges[1] - edges[0]), **kwargs)
    ax.set_title(title.replace("@feature@", "feature")
                 .replace("@index/name@", str(feature)))
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster_or_record, metric: Optional[str] = None,
                dataset_names=None, ax=None, xlim=None, ylim=None,
                title="Metric during training", xlabel="Iterations",
                ylabel="@metric@", figsize=None, dpi=None, grid=True):
    plt = _import_matplotlib()
    if isinstance(booster_or_record, dict):
        record = booster_or_record
    else:
        raise TypeError("pass the dict from lgb.record_evaluation()")
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    names = dataset_names or list(record.keys())
    for name in names:
        metrics = record[name]
        mname = metric or next(iter(metrics))
        ax.plot(metrics[mname], label=name)
    ax.legend()
    ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel.replace("@metric@", metric or ""))
    ax.grid(grid)
    return ax


def create_tree_digraph(model, tree_index: int = 0, show_info=None,
                        precision: int = 3, orientation: str = "horizontal",
                        **kwargs):
    """Graphviz Digraph of one tree (plotting.py create_tree_digraph)."""
    try:
        import graphviz
    except ImportError as e:
        raise ImportError("graphviz is required for tree plotting") from e
    booster = _to_booster(model)
    t = booster.trees[tree_index]
    names = booster.feature_names
    graph = graphviz.Digraph(**kwargs)
    graph.attr(rankdir="LR" if orientation == "horizontal" else "TB")

    def node_name(node):
        return f"split{node}" if node >= 0 else f"leaf{~node}"

    for i in range(t.num_nodes()):
        fname = names[t.split_feature[i]] if names else str(t.split_feature[i])
        if t.decision_type[i] & 1:
            label = f"{fname} in set"
        else:
            label = f"{fname} <= {t.threshold[i]:.{precision}g}"
        label += f"\\ngain: {t.split_gain[i]:.{precision}g}"
        graph.node(node_name(i), label=label, shape="rectangle")
        for child, tag in ((t.left_child[i], "yes"), (t.right_child[i], "no")):
            graph.edge(node_name(i), node_name(child), label=tag)
    for leaf in range(t.num_leaves):
        graph.node(f"leaf{leaf}",
                   label=f"leaf {leaf}: {t.leaf_value[leaf]:.{precision}g}\\n"
                         f"count: {t.leaf_count[leaf]}",
                   shape="ellipse")
    return graph


def plot_tree(model, ax=None, tree_index: int = 0, figsize=None, dpi=None,
              **kwargs):
    plt = _import_matplotlib()
    graph = create_tree_digraph(model, tree_index=tree_index, **kwargs)
    import io
    try:
        s = graph.pipe(format="png")
    except Exception as e:
        raise RuntimeError("graphviz executable required to render") from e
    import matplotlib.image as mpimg
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    img = mpimg.imread(io.BytesIO(s))
    ax.imshow(img)
    ax.axis("off")
    return ax
