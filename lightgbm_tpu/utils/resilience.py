"""Fault-tolerance primitives: retry/backoff, watchdogs, atomic writes.

Round-5 VERDICT.md recorded the failure mode this module exists for: the
exclusive TPU tunnel wedged for ~10 hours and every probe died in ``claim
hung`` or backend setup/compile errors — with no retry, no traceback from
the hung call, and snapshots that were written non-atomically and never
read back.  The reference hardens the same surface piecemeal (network
retry in the socket learner, ``snapshot_freq`` in gbdt.cpp, continued
training via ``init_model``); here it is one layer:

- :class:`RetryPolicy` / :func:`retry_call` / :func:`retry` — jittered
  exponential backoff with a hard deadline and an exception CLASSIFIER
  (:func:`is_retryable_device_error`): transient device-claim /
  backend-bring-up errors are retried, programming errors are not.
- :class:`Watchdog` — arms ``faulthandler`` stack dumps while a blocking
  device call (claim, compile, collective bring-up) is in flight, so a
  wedge produces a traceback instead of silence.
- :func:`atomic_write` — temp file in the target directory +
  ``os.replace``, so a crash mid-write can never leave a truncated model
  or binary cache behind.  Hosts the ``snapshot_write`` /
  ``snapshot_kill`` fault-injection sites (utils/faultinject.py).

Consumers: ``parallel/launch.py`` / ``parallel/mesh.py`` /
``models/gbdt.py`` device bring-up, ``booster.py`` / ``dataset.py`` /
``snapshot.py`` persistence, ``tools/tpu_watch.py`` claim probes.
"""

from __future__ import annotations

import dataclasses
import faulthandler
import functools
import os
import random
import sys
import tempfile
import time
from typing import Callable, Optional


# ---------------------------------------------------------------------------
# Exception classification
# ---------------------------------------------------------------------------

# Message fragments of transient device-claim / backend-init / network
# failures (the axon relay's "claim hung", jax.distributed heartbeats,
# gRPC status strings).  Matched case-insensitively against str(exc).
_RETRYABLE_PATTERNS = (
    "unavailable",
    "deadline exceeded",
    "deadline_exceeded",
    "timed out",
    "timeout",
    "connection refused",
    "connection reset",
    "connection closed",
    "failed to connect",
    "socket closed",
    "stream removed",
    "resource exhausted",
    "aborted",
    "claim",
    "heartbeat",
    "coordination service",
    "barrier",
    "backend setup",
    "initialization failed",
)

# Never retried regardless of message: programming / environment errors a
# second attempt cannot fix, and control-flow exceptions.
_FATAL_TYPES = (KeyboardInterrupt, SystemExit, GeneratorExit, MemoryError,
                NotImplementedError, AssertionError, TypeError,
                AttributeError, KeyError, IndexError, ImportError,
                SyntaxError)


def is_retryable_device_error(exc: BaseException) -> bool:
    """Default classifier: True for transient device-claim / backend-init
    shaped failures, False for programming errors.  ValueError is fatal
    (bad arguments don't become good by waiting) EXCEPT LightGBMError
    subclasses are still checked by message — they wrap device errors."""
    if isinstance(exc, _FATAL_TYPES):
        return False
    if type(exc) is ValueError:
        return False
    msg = str(exc).lower()
    return any(p in msg for p in _RETRYABLE_PATTERNS)


# ---------------------------------------------------------------------------
# Retry with jittered exponential backoff + hard deadline
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class RetryPolicy:
    """Backoff schedule for :func:`retry_call`.

    max_attempts: total tries (1 = no retry).
    base_delay_s: backoff before the 2nd attempt; doubles per attempt.
    max_delay_s:  backoff cap.
    deadline_s:   hard wall-clock budget across ALL attempts (0 = none);
                  a retry that could not even START before the deadline
                  re-raises instead of sleeping.
    jitter:       fraction of each delay randomized (0..1): the slept
                  delay is uniform in [d*(1-jitter/2), d*(1+jitter/2)],
                  de-synchronizing a fleet of workers hammering one relay.
    """
    max_attempts: int = 3
    base_delay_s: float = 1.0
    max_delay_s: float = 30.0
    deadline_s: float = 0.0
    jitter: float = 0.5

    @classmethod
    def for_bringup(cls, retries: int, timeout_s: float) -> "RetryPolicy":
        """The device/distributed bring-up schedule shared by
        ``gbdt._resolve_mesh``, ``launch.init`` and
        ``mesh.init_distributed``: ``retries`` re-attempts after the
        first, a base delay scaled to 1% of the deadline (capped at
        1 s), and the deadline itself as the hard budget."""
        return cls(
            max_attempts=max(1, int(retries) + 1),
            base_delay_s=min(1.0, timeout_s / 100.0) if timeout_s > 0
            else 1.0,
            deadline_s=timeout_s)


def retry_call(fn: Callable, *args, policy: Optional[RetryPolicy] = None,
               classify: Optional[Callable[[BaseException], bool]] = None,
               on_retry: Optional[Callable[[int, float, BaseException],
                                           None]] = None,
               label: str = "", **kwargs):
    """Call ``fn(*args, **kwargs)``, retrying classified-transient
    failures under ``policy``.  ``on_retry(attempt, delay_s, exc)`` is
    invoked before each backoff sleep (tools/tpu_watch.py logs these).
    The final failure is re-raised unmodified."""
    policy = policy or RetryPolicy()
    classify = classify or is_retryable_device_error
    name = label or getattr(fn, "__name__", "call")
    t0 = time.monotonic()
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn(*args, **kwargs)
        except BaseException as e:
            if attempt >= max(1, policy.max_attempts) or not classify(e):
                raise
            delay = min(policy.max_delay_s,
                        policy.base_delay_s * (2.0 ** (attempt - 1)))
            if policy.jitter > 0:
                delay *= 1.0 + policy.jitter * (random.random() - 0.5)
            if policy.deadline_s > 0 and \
                    time.monotonic() - t0 + delay > policy.deadline_s:
                from .log import Log
                Log.warning(
                    f"{name}: retry deadline ({policy.deadline_s:g}s) "
                    f"exhausted after attempt {attempt}; giving up")
                raise
            from .log import Log
            Log.warning(
                f"{name}: attempt {attempt}/{policy.max_attempts} failed "
                f"({e}); retrying in {delay:.1f}s")
            if on_retry is not None:
                on_retry(attempt, delay, e)
            time.sleep(delay)


def retry(policy: Optional[RetryPolicy] = None, **retry_kwargs):
    """Decorator form of :func:`retry_call`::

        @retry(RetryPolicy(max_attempts=4))
        def claim(): ...
    """
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return retry_call(fn, *args, policy=policy, **retry_kwargs,
                              **kwargs)
        return wrapper
    return deco


# ---------------------------------------------------------------------------
# Watchdog: faulthandler stack dumps for wedged blocking calls
# ---------------------------------------------------------------------------

class Watchdog:
    """Context manager arming periodic ``faulthandler`` stack dumps while
    a blocking device call is in flight::

        with Watchdog(cfg.dist_init_timeout_s, label="device claim"):
            devs = jax.devices()

    If the call exceeds ``timeout_s`` the interpreter dumps every
    thread's stack to stderr (repeating each ``timeout_s``) — the
    round-5 wedge produced NO traceback for 10 hours; this makes the
    hang loud and attributable.  ``timeout_s <= 0`` disables.

    ``faulthandler``'s later-dump timer is process-global: nesting
    Watchdogs (or combining with pytest's per-test dump) leaves the
    innermost exit having cancelled the outer timer.  Acceptable for the
    bring-up call sites this guards — they do not nest.
    """

    def __init__(self, timeout_s: float, label: str = "",
                 file=None) -> None:
        self.timeout_s = float(timeout_s)
        self.label = label
        self.file = file

    def __enter__(self) -> "Watchdog":
        if self.timeout_s > 0:
            faulthandler.dump_traceback_later(
                self.timeout_s, repeat=True,
                file=self.file if self.file is not None else sys.stderr)
            from .log import Log
            Log.debug(f"watchdog armed ({self.timeout_s:g}s) around "
                      f"{self.label or 'blocking call'}")
        return self

    def __exit__(self, *exc) -> None:
        if self.timeout_s > 0:
            faulthandler.cancel_dump_traceback_later()


# ---------------------------------------------------------------------------
# Atomic file writes (temp + os.replace)
# ---------------------------------------------------------------------------

def atomic_write(path, data, binary: bool = False) -> None:
    """Write ``data`` to ``path`` atomically: temp file in the TARGET
    directory (``os.replace`` requires same-filesystem), fsync, rename.
    A crash at any point leaves either the old file or the new file —
    never a truncated hybrid.  Creates missing parent directories (a
    relative ``output_model`` in a fresh working dir used to make every
    snapshot write raise).

    Fault-injection sites (utils/faultinject.py): ``snapshot_write``
    fires before anything is written; ``snapshot_kill`` fires after the
    temp file is durable but BEFORE the rename — the kill-before-rename
    crash window.  An injected kill deliberately leaves the temp file
    behind, like a real crash would."""
    from . import faultinject
    faultinject.check("snapshot_write")
    path = os.fspath(path)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d,
                               prefix=os.path.basename(path) + ".",
                               suffix=".tmp")
    try:
        with os.fdopen(fd, "wb" if binary else "w") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    # kill-before-rename window: InjectedKill is a BaseException and the
    # cleanup above only catches Exception, so the temp file survives —
    # exactly the debris a real crash leaves (readers must ignore *.tmp)
    faultinject.check("snapshot_kill")
    os.replace(tmp, path)
