"""Fault-site coverage lint: every declared injection site is wired and
exercised.

``utils/faultinject.KNOWN_SITES`` is the registry of chaos-injection
points (docs/Fault-Tolerance.md).  A site that exists in the registry
but is never reached by a test or soak is worse than no site at all:
the fault-tolerance story CLAIMS coverage the suite does not deliver,
and the site's wiring silently rots.  This lint keeps the registry
honest, grep-verifiably:

- **unwired**  — the site name never appears in a string literal of
  any package module besides ``utils/faultinject.py`` itself: nothing
  can ever fire it;
- **unexercised** — the site name never appears in a string literal
  under ``tests/`` or ``tools/`` (spec strings like ``"hist_sdc:3-5"``
  count — that is exactly how sites are armed), so no test or soak
  drives it.  Pinnable in ``tools/faultsite_allowlist.txt`` with a
  MANDATORY rationale;
- **stale pins** — allowlist entries for sites that are now exercised
  (or no longer declared) are findings, so the allowlist cannot rot.

Matching is over tokenized STRING literals only (site names live in
strings: configure specs, ``fires(...)``/``maybe_bitflip(...)`` calls),
so comments never satisfy the lint.  Run via the unified driver
(``python tools/lint.py``; tier-1) or standalone
(``python tools/analyze/check_faultsites.py``; exit 1 on findings).
"""

from __future__ import annotations

import ast
import io
import os
import re
import sys
import tokenize
from typing import Iterator, List, Set, Tuple

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import lintlib                                           # noqa: E402

REPO = lintlib.REPO
ALLOWLIST = os.path.join(REPO, "tools", "faultsite_allowlist.txt")
_REGISTRY_REL = os.path.join("utils", "faultinject.py")


def declared_sites(package_root: str = lintlib.PACKAGE) -> Tuple[str, ...]:
    """``KNOWN_SITES`` parsed out of the package's faultinject module —
    textually (AST + literal_eval), so a ``--package-root`` copy is
    linted without importing it."""
    path = os.path.join(package_root, _REGISTRY_REL)
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "KNOWN_SITES"
                for t in node.targets):
            return tuple(ast.literal_eval(node.value))
    raise ValueError(f"{path}: no KNOWN_SITES assignment found")


def _string_literals(path: str) -> Iterator[str]:
    with open(path, "rb") as f:
        src = f.read()
    try:
        for tok in tokenize.tokenize(io.BytesIO(src).readline):
            if tok.type == tokenize.STRING:
                yield tok.string
    except tokenize.TokenError:
        pass                     # partial file: lint what parsed


def _sites_in_tree(roots: List[str], sites: Tuple[str, ...],
                   skip: Set[str]) -> Set[str]:
    pats = {s: re.compile(rf"\b{re.escape(s)}\b") for s in sites}
    found: Set[str] = set()
    for root in roots:
        for path in lintlib.iter_py(root):
            if os.path.abspath(path) in skip:
                continue
            for lit in _string_literals(path):
                for s, pat in pats.items():
                    if s not in found and pat.search(lit):
                        found.add(s)
            if len(found) == len(sites):
                return found
    return found


def run(package_root: str = lintlib.PACKAGE,
        allowlist_path: str = ALLOWLIST) -> List[str]:
    """All coverage findings (empty list = lint green)."""
    sites = declared_sites(package_root)
    findings: List[str] = []
    dupes = sorted({s for s in sites if sites.count(s) > 1})
    if dupes:
        findings.append("duplicate KNOWN_SITES entries: "
                        + ", ".join(dupes))

    registry = os.path.abspath(
        os.path.join(package_root, _REGISTRY_REL))
    wired = _sites_in_tree([package_root], sites, skip={registry})
    # this lint (site names in its own docstring/strings) and its
    # allowlist never count as exercise
    me = os.path.abspath(__file__)
    exercised = _sites_in_tree(
        [os.path.join(REPO, "tests"), os.path.join(REPO, "tools")],
        sites, skip={me})

    allow = {key[0] for key, _ in lintlib.parse_pins(
        allowlist_path, 1, require_rationale=True)}
    used: Set[str] = set()
    for s in sites:
        if s not in wired:
            findings.append(
                f"declared but UNWIRED site '{s}': no package module "
                "references it (utils/faultinject.py aside) — nothing "
                "can ever fire it")
        if s not in exercised:
            if s in allow:
                used.add(s)
            else:
                findings.append(
                    f"declared but UNEXERCISED site '{s}': no test or "
                    "soak under tests/ or tools/ arms it")
    for s in sorted(allow - set(sites)):
        findings.append(f"stale allowlist entry: site '{s}' is no "
                        "longer declared in KNOWN_SITES")
    findings.extend(lintlib.stale_pins(
        {(s,) for s in allow & set(sites)}, {(s,) for s in used},
        "faultsite allowlist"))
    return findings


def main() -> int:
    findings = run()
    if findings:
        print(f"{len(findings)} fault-site coverage finding(s):",
              file=sys.stderr)
        for f in findings:
            print(f"  {f}", file=sys.stderr)
        return 1
    print("fault-site coverage clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
