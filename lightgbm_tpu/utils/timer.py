"""Tracing / profiling (reference: include/LightGBM/utils/common.h:978-1056).

``FunctionTimer`` RAII scopes accumulating into a ``global_timer`` registry
printed at exit (``Timer::Print``), plus integration with ``jax.profiler``
traces: when profiling is enabled the same scopes emit
``jax.profiler.TraceAnnotation`` ranges so device timelines carry the
reference's phase names (SURVEY.md §5 tracing mapping).
"""

from __future__ import annotations

import atexit
import collections
import time
from typing import Dict, Optional


class Timer:
    """Accumulating named-scope timer (Common::Timer analog).

    The exit-time summary is registered LAZILY — on the first recorded
    stat while enabled — so merely importing this module (or running
    with telemetry off) never prints at interpreter exit; ``enabled``
    is switched on by the obs subsystem (obs.ObsSession) or manually."""

    def __init__(self):
        self.stats: Dict[str, float] = collections.defaultdict(float)
        self.counts: Dict[str, int] = collections.defaultdict(int)
        self.enabled = False
        self._atexit_armed = False

    def start(self, name: str) -> float:
        return time.perf_counter()

    def stop(self, name: str, t0: float) -> None:
        self.stats[name] += time.perf_counter() - t0
        self.counts[name] += 1
        if self.enabled and not self._atexit_armed:
            self._atexit_armed = True
            atexit.register(self.print_summary)

    def print_summary(self) -> None:
        if not self.enabled or not self.stats:
            return
        print("LightGBM-TPU timers:")
        for name, total in sorted(self.stats.items(), key=lambda kv: -kv[1]):
            print(f"  {name}: {total:.3f}s ({self.counts[name]} calls)")


global_timer = Timer()


class FunctionTimer:
    """RAII/context scope (Common::FunctionTimer analog); doubles as a
    jax.profiler trace annotation for device timelines."""

    def __init__(self, name: str, timer: Optional[Timer] = None):
        self.name = name
        self.timer = timer or global_timer
        self._t0 = 0.0
        self._annotation = None

    def __enter__(self):
        self._t0 = self.timer.start(self.name)
        if self.timer.enabled:
            try:
                import jax.profiler
                self._annotation = jax.profiler.TraceAnnotation(self.name)
                self._annotation.__enter__()
            except Exception:
                self._annotation = None
        return self

    def __exit__(self, *exc):
        if self._annotation is not None:
            self._annotation.__exit__(*exc)
        self.timer.stop(self.name, self._t0)
        return False
