"""Dataset: the binned training matrix + metadata.

TPU-native analog of the reference Dataset/DatasetLoader/Metadata
(/root/reference/include/LightGBM/dataset.h:45-849, src/io/dataset.cpp,
src/io/dataset_loader.cpp).  Instead of per-group packed ``Bin`` storage the
binned matrix is ONE dense uint8/uint16 ``[num_data, num_features]`` array
(SURVEY.md §7 design translation) handed to the device learner; bin offsets
per feature index into a concatenated histogram axis.

Supports: numpy / pandas construction, sampled bin-mapper fitting
(bin_construct_sample_cnt, dataset_loader.cpp:961), categorical features,
validation-set alignment to a reference Dataset (dataset.h ``CreateValid``),
and a binary cache file (save_binary, dataset.cpp ``SaveBinaryFile`` analog).
"""

from __future__ import annotations

import io
import os
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from .binning import BinMapper, BinType, MissingType
from .config import Config
from .efb import EFBInfo, bin_grouped, find_bundles, unbundle


class Metadata:
    """Label / weight / query-boundary / init-score storage
    (dataset.h:45-265, src/io/metadata.cpp analog)."""

    def __init__(self, num_data: int):
        self.num_data = num_data
        self.label: Optional[np.ndarray] = None
        self.weight: Optional[np.ndarray] = None
        self.query_boundaries: Optional[np.ndarray] = None  # [num_queries+1]
        self.init_score: Optional[np.ndarray] = None

    @staticmethod
    def _avoid_inf(arr: np.ndarray, f32: bool = True) -> np.ndarray:
        """Metadata fields sanitize NaN->0 and clamp +-inf to a large
        finite value (Common::AvoidInf, common.h:658/670: 1e38 for
        float fields, 1e300 for double) — the reference applies this on
        every SetField so downstream math never sees non-finite
        metadata."""
        lim = 1e38 if f32 else 1e300
        return np.nan_to_num(arr, nan=0.0, posinf=lim, neginf=-lim)

    def set_label(self, label) -> None:
        label = np.asarray(label, dtype=np.float32).reshape(-1)
        if len(label) != self.num_data:
            raise ValueError(f"label length {len(label)} != num_data {self.num_data}")
        self.label = self._avoid_inf(label)

    def set_weight(self, weight) -> None:
        if weight is None:
            self.weight = None
            return
        weight = np.asarray(weight, dtype=np.float32).reshape(-1)
        if len(weight) != self.num_data:
            raise ValueError("weight length mismatch")
        weight = self._avoid_inf(weight)
        if (weight < 0).any():
            raise ValueError("weights must be non-negative")
        self.weight = weight

    def set_group(self, group) -> None:
        """``group`` is per-query sizes (python API convention); converted to
        boundaries like Metadata::SetQuery (metadata.cpp)."""
        if group is None:
            self.query_boundaries = None
            return
        group = np.asarray(group, dtype=np.int64).reshape(-1)
        bounds = np.concatenate([[0], np.cumsum(group)])
        if bounds[-1] != self.num_data:
            raise ValueError(f"sum(group)={bounds[-1]} != num_data {self.num_data}")
        self.query_boundaries = bounds.astype(np.int32)

    def set_init_score(self, init_score) -> None:
        if init_score is None:
            self.init_score = None
            return
        s = self._avoid_inf(np.asarray(init_score, dtype=np.float64),
                            f32=False)
        if s.size % self.num_data != 0:
            raise ValueError("init_score size must be num_data * num_class")
        self.init_score = s.reshape(self.num_data, -1) if s.ndim > 1 or s.size != self.num_data \
            else s.reshape(-1)

    @property
    def num_queries(self) -> int:
        if self.query_boundaries is None:
            return 0
        return len(self.query_boundaries) - 1


def fingerprint_arrays(label, weight=None) -> str:
    """The snapshot data fingerprint as a pure function of label/weight
    arrays — shared by :meth:`Dataset.fingerprint` and the elastic
    multi-process snapshot writer (``GBDTModel.snapshot_state``), which
    must stamp the GLOBAL gathered arrays with byte-identical hashing
    so a shrunk relaunch over the full data matches the manifest."""
    import hashlib
    h = hashlib.sha256()
    if label is None:
        h.update(b"unlabeled")
    else:
        lab = np.asarray(label, np.float32).reshape(-1)
        h.update(str(len(lab)).encode())
        h.update(lab.tobytes())
    if weight is not None:
        h.update(np.asarray(weight, np.float32).reshape(-1).tobytes())
    return h.hexdigest()[:16]


def _is_scipy_sparse(data) -> bool:
    return hasattr(data, "tocsc") and hasattr(data, "nnz")


def _sample_rows(rng, n: int, cnt: int) -> np.ndarray:
    """cnt sorted unique row indices, unbiased, in O(cnt) memory (choice
    without replacement builds an O(n) permutation — fatal for out-of-core
    n when cnt << n)."""
    if cnt >= n:
        return np.arange(n, dtype=np.int64)
    if 2 * cnt >= n:  # dense sampling: O(n) = O(2 cnt), permutation is fine
        return np.sort(rng.permutation(n)[:cnt]).astype(np.int64)
    u = np.unique(rng.randint(0, n, size=int(cnt * 1.3) + 16).astype(np.int64))
    while len(u) < cnt:  # collision top-up; cnt < n/2 so this converges fast
        more = rng.randint(0, n, size=cnt).astype(np.int64)
        u = np.unique(np.concatenate([u, more]))
    if len(u) > cnt:  # drop uniformly, NOT from the tail (index bias)
        u = np.sort(rng.choice(u, size=cnt, replace=False))
    return u


class Sequence:
    """Generic batched row-access object for out-of-core construction
    (basic.py:621 ``Sequence`` analog).

    Subclasses implement ``__getitem__`` (int -> 1-D row; slice/list ->
    2-D rows) and ``__len__``.  ``batch_size`` controls how many rows are
    materialized at a time while binning.
    """

    batch_size = 4096

    def __getitem__(self, idx):
        raise NotImplementedError(
            "Sub-classes of lightgbm_tpu.Sequence must implement __getitem__()")

    def __len__(self) -> int:
        raise NotImplementedError(
            "Sub-classes of lightgbm_tpu.Sequence must implement __len__()")


def _is_seq_input(data) -> bool:
    if isinstance(data, Sequence):
        return True
    return (isinstance(data, (list, tuple)) and len(data) > 0
            and all(isinstance(s, Sequence) for s in data))


def _to_numpy_2d(data) -> tuple:
    """Accept numpy / pandas / scipy-sparse / list-of-lists; return
    (float64 2-D array, names, cat_cols)."""
    feature_names = None
    pandas_categorical: List[int] = []
    if _is_scipy_sparse(data):  # CSR/CSC/COO... (LGBM_*FromCSR/CSC analog)
        arr = np.asarray(data.todense(), dtype=np.float64)
        return np.ascontiguousarray(arr), None, []
    if hasattr(data, "values") and hasattr(data, "columns"):  # pandas DataFrame
        feature_names = [str(c) for c in data.columns]
        cols = []
        for i, c in enumerate(data.columns):
            col = data[c]
            if str(col.dtype) == "category":
                cols.append(col.cat.codes.to_numpy().astype(np.float64))
                pandas_categorical.append(i)
            else:
                cols.append(col.to_numpy().astype(np.float64))
        arr = np.column_stack(cols) if cols else np.empty((len(data), 0))
    elif (isinstance(data, (list, tuple)) and len(data)
          and all(isinstance(c, np.ndarray) and c.ndim == 2
                  for c in data)):
        # list of 2-D row chunks (LGBM_DatasetCreateFromMats semantics —
        # the reference's chunked-dataset path vstacks row blocks)
        arr = np.vstack([np.asarray(c, np.float64) for c in data])
    else:
        arr = np.asarray(data, dtype=np.float64)
        if arr.ndim == 1:
            arr = arr.reshape(-1, 1)
    return np.ascontiguousarray(arr), feature_names, pandas_categorical


class Dataset:
    """Binned dataset (dataset.h:355 analog).

    Lazily constructed like the python-package Dataset (basic.py:1135): raw
    data + params are held until ``construct()`` fits bin mappers and
    produces the packed binned matrix.
    """

    def __init__(self, data, label=None, weight=None, group=None, init_score=None,
                 feature_name: Union[str, List[str]] = "auto",
                 categorical_feature: Union[str, List] = "auto",
                 reference: Optional["Dataset"] = None,
                 params: Optional[Dict[str, Any]] = None,
                 free_raw_data: bool = False,
                 bin_mappers: Optional[List["BinMapper"]] = None):
        self._raw_input = data
        self._label_in, self._weight_in = label, weight
        self._group_in, self._init_score_in = group, init_score
        self._feature_name_in = feature_name
        self._categorical_in = categorical_feature
        self.reference = reference
        self.params: Dict[str, Any] = dict(params or {})
        self.free_raw_data = free_raw_data
        # externally-fitted mappers (distributed binning,
        # parallel/dist_data.py — dataset_loader.cpp:1104-1186 analog)
        self._preset_mappers = bin_mappers

        self._constructed = False
        self.used_indices = None       # set by subset()
        # filled by construct():
        self.num_data: int = 0
        self.num_total_features: int = 0
        self.bin_mappers: List[BinMapper] = []
        self.used_features: List[int] = []      # indices of non-trivial features
        self.binned: Optional[np.ndarray] = None  # [N, num_used] uint8/uint16
        # k-hot sparse binned storage (sparse_data.py, the sparse_bin.hpp
        # analog) — set INSTEAD of ``binned`` when it is smaller
        self.binned_sparse = None
        self.bin_offsets: Optional[np.ndarray] = None  # [num_used+1] cumulative bins
        self.metadata: Optional[Metadata] = None
        self.feature_names: List[str] = []
        self.raw_data: Optional[np.ndarray] = None
        self.max_bin: int = 255
        self.efb: Optional[EFBInfo] = None  # set when bundling merged columns

    # ------------------------------------------------------------------
    @staticmethod
    def _bin_signature(cfg: Config) -> dict:
        """The config fields that shape binning — a mismatch after
        construction means training would silently use stale bins
        (round-2's bench measured 255-bin histograms while reporting 63)."""
        return {
            "max_bin": cfg.max_bin,
            "min_data_in_bin": cfg.min_data_in_bin,
            "bin_construct_sample_cnt": cfg.bin_construct_sample_cnt,
            "max_bin_by_feature": tuple(cfg.max_bin_by_feature or ()),
            "enable_bundle": cfg.enable_bundle,
            "categorical_feature": cfg.categorical_feature,
            "use_missing": cfg.use_missing,
            "zero_as_missing": cfg.zero_as_missing,
            "forcedbins_filename": cfg.forcedbins_filename,
        }

    def construct(self, config: Optional[Config] = None) -> "Dataset":
        if self._constructed:
            # reference parity (basic.py "Ignoring params... dataset already
            # constructed"): binning params cannot change after construction
            # — warn loudly instead of silently training on the old bins
            built = getattr(self, "_built_bin_sig", None)
            if config is not None and built is not None \
                    and self._bin_signature(config) != built:
                # warn only about binning params the caller EXPLICITLY
                # passed (a booster config carries defaults for every
                # param — a dataset built with its own max_bin would
                # otherwise warn on every construct(self.config) touch)
                from .config import _ALIASES
                explicit = {_ALIASES.get(k, k) for k in config.raw_params}
                sig_now = self._bin_signature(config)
                conflict = {k for k, v in sig_now.items()
                            if k in explicit and built.get(k) != v}
                if conflict:
                    from .utils.log import Log
                    Log.warning(
                        "Ignoring binning params passed at train time "
                        f"({sorted(conflict)}): Dataset was already "
                        f"constructed with {built}; pass params to the "
                        "Dataset constructor instead")
            return self
        cfg = config or Config(self.params)
        self._built_bin_sig = self._bin_signature(cfg)
        if _is_seq_input(self._raw_input):
            return self._construct_from_seqs(cfg)
        sparse_in = _is_scipy_sparse(self._raw_input)
        if sparse_in:
            # CSR/CSC input (LGBM_DatasetCreateFromCSR/CSC, c_api.h:109-313
            # analog): bin column-at-a-time off the CSC layout — the only
            # dense product is the packed uint8 binned matrix.
            csc = self._raw_input.tocsc()
            if not csc.has_sorted_indices:
                # the sampled-column searchsorted path needs sorted
                # per-column indices; copy so the caller's matrix is untouched
                csc = csc.copy()
                csc.sort_indices()
            names, pandas_cat = None, []
            self.num_data, self.num_total_features = csc.shape

            def colfn(f: int) -> np.ndarray:
                out = np.zeros(self.num_data, np.float64)
                lo, hi = csc.indptr[f], csc.indptr[f + 1]
                out[csc.indices[lo:hi]] = csc.data[lo:hi]
                return out

            def sample_col_factory(rows: np.ndarray):
                # O(nnz_col)-per-column sampled access straight off the CSC
                # layout — no N-length dense intermediate
                def col(f: int) -> np.ndarray:
                    lo, hi = csc.indptr[f], csc.indptr[f + 1]
                    idx, dat = csc.indices[lo:hi], csc.data[lo:hi]
                    out = np.zeros(len(rows), np.float64)
                    if len(idx):
                        pos = np.minimum(np.searchsorted(idx, rows),
                                         len(idx) - 1)
                        hit = idx[pos] == rows
                        out[hit] = dat[pos[hit]]
                    return out
                return col

            arr = None
        else:
            arr, names, pandas_cat = _to_numpy_2d(self._raw_input)
            self.num_data, self.num_total_features = arr.shape

            def colfn(f: int) -> np.ndarray:
                return arr[:, f]

            sample_col_factory = None
        self._set_metadata_inputs()
        self._resolve_names(names)
        cat_idx = self._resolve_cats(cfg, pandas_cat)

        if self._preset_mappers is not None:
            self.bin_mappers = list(self._preset_mappers)
            self._finalize_mappers()
        elif self.reference is not None:
            # validation set: reuse the training set's bin mappers
            # (Dataset::CreateValid, dataset.cpp)
            ref = self.reference.construct(config)
            self.bin_mappers = ref.bin_mappers
            self.used_features = ref.used_features
            self.bin_offsets = ref.bin_offsets
            self.max_bin = ref.max_bin
            self.efb = ref.efb
        else:
            self._fit_bin_mappers(colfn, cfg, cat_idx,
                                  sample_col_factory=sample_col_factory)

        self._bin_data(colfn, cfg, csc if sparse_in else None)
        keep_raw = (not self.free_raw_data) or bool(cfg.linear_tree)
        self._built_linear_tree = bool(cfg.linear_tree)  # save_binary raw rule
        if sparse_in:
            if cfg.linear_tree and self.num_total_features:
                # linear trees need dense raw values (dataset.h:836 raw_data_)
                self.raw_data = np.column_stack(
                    [colfn(f) for f in range(self.num_total_features)])
            elif keep_raw:
                # keep the sparse matrix itself: predict() accepts CSR, so
                # init_model / refit paths keep working without densifying
                self.raw_data = csc.tocsr()
            else:
                self.raw_data = None
        else:
            self.raw_data = arr if keep_raw else None
        self._constructed = True
        self._raw_input = None
        return self

    def _set_metadata_inputs(self) -> None:
        self.metadata = Metadata(self.num_data)
        if self._label_in is not None:
            self.metadata.set_label(self._label_in)
        self.metadata.set_weight(self._weight_in)
        self.metadata.set_group(self._group_in)
        self.metadata.set_init_score(self._init_score_in)

    def _resolve_names(self, names) -> None:
        if self._feature_name_in != "auto" and self._feature_name_in is not None:
            self.feature_names = list(self._feature_name_in)
        elif names is not None:
            self.feature_names = names
        else:
            self.feature_names = [f"Column_{i}" for i in range(self.num_total_features)]

    def _resolve_cats(self, cfg: Config, pandas_cat) -> set:
        cat_idx = set(pandas_cat)
        if self._categorical_in != "auto" and self._categorical_in is not None:
            for c in self._categorical_in:
                if isinstance(c, str):
                    if c in self.feature_names:
                        cat_idx.add(self.feature_names.index(c))
                else:
                    cat_idx.add(int(c))
        elif isinstance(cfg.categorical_feature, str) and cfg.categorical_feature:
            for tok in cfg.categorical_feature.split(","):
                tok = tok.strip()
                if tok:
                    cat_idx.add(int(tok))
        return cat_idx

    def _construct_from_seqs(self, cfg: Config) -> "Dataset":
        """Out-of-core construction from ``Sequence`` objects
        (basic.py:1574 ``__init_from_seqs``): sample rows for bin-mapper
        fitting, then bin batch-by-batch — the full raw matrix is never
        materialized."""
        if cfg.linear_tree:
            raise ValueError("linear_tree requires in-memory raw data; "
                             "Sequence input is streaming-only")
        seqs = ([self._raw_input] if isinstance(self._raw_input, Sequence)
                else list(self._raw_input))
        lens = [len(s) for s in seqs]
        self.num_data = int(sum(lens))
        probe = np.asarray(seqs[0][0], dtype=np.float64).reshape(-1)
        self.num_total_features = probe.shape[0]
        self._set_metadata_inputs()
        self._resolve_names(None)
        cat_idx = self._resolve_cats(cfg, [])

        if self._preset_mappers is not None:
            # distributed binning handoff (parallel/dist_data.py) works for
            # streaming input too
            self.bin_mappers = list(self._preset_mappers)
            self._finalize_mappers()
        elif self.reference is not None:
            ref = self.reference.construct(cfg)
            self.bin_mappers = ref.bin_mappers
            self.used_features = ref.used_features
            self.bin_offsets = ref.bin_offsets
            self.max_bin = ref.max_bin
            self.efb = ref.efb
        else:
            sample_cnt = min(self.num_data, int(cfg.bin_construct_sample_cnt))
            rng = np.random.RandomState(cfg.data_random_seed)
            gidx = _sample_rows(rng, self.num_data, sample_cnt)
            bounds = np.concatenate([[0], np.cumsum(lens)])
            rows = []
            for si, s in enumerate(seqs):
                loc = gidx[(gidx >= bounds[si]) & (gidx < bounds[si + 1])] \
                    - bounds[si]
                if len(loc) == 0:
                    continue
                try:  # list indexing is optional in the Sequence protocol
                    rows.append(np.asarray(s[list(loc)], dtype=np.float64))
                except (TypeError, IndexError):
                    rows.append(np.asarray([s[int(i)] for i in loc],
                                           dtype=np.float64))
            sample = np.vstack(rows)
            # EFB bundling needs whole-column access; fresh streaming input
            # stays un-bundled (do_bundle=False skips the conflict-graph work)
            self._fit_bin_mappers(lambda f: sample[:, f], cfg, cat_idx,
                                  n=len(sample), do_bundle=False)

        dtype = np.uint8 if self.max_bin <= 256 else np.uint16
        nf = len(self.used_features)
        out = np.zeros((self.num_data, max(nf, 1)), dtype=dtype)
        row = 0
        for s in seqs:
            bs = int(getattr(s, "batch_size", None) or Sequence.batch_size)
            for i in range(0, len(s), bs):
                chunk = np.atleast_2d(np.asarray(s[i:min(i + bs, len(s))],
                                                 dtype=np.float64))
                for j, f in enumerate(self.used_features):
                    out[row:row + len(chunk), j] = \
                        self.bin_mappers[f].value_to_bin(chunk[:, f]).astype(dtype)
                row += len(chunk)
        if self.efb is not None:
            # a bundled reference set: regroup the per-feature bins into the
            # EFB-grouped layout consumers read (models/gbdt.py)
            self.binned = bin_grouped(lambda j: out[:, j].astype(np.int64),
                                      self.efb, self.num_data)
        else:
            self.binned = out
        self.raw_data = None
        self._constructed = True
        if self.free_raw_data:
            self._raw_input = None
        # else: keep the Sequence list — get_data() returns it (basic.py
        # keeps self.data = the sequences when free_raw_data=False)
        return self

    def _fit_bin_mappers(self, colfn, cfg: Config, cat_idx: set,
                         n: Optional[int] = None,
                         do_bundle: bool = True,
                         sample_col_factory=None) -> None:
        n = self.num_data if n is None else n
        sample_cnt = min(n, int(cfg.bin_construct_sample_cnt))
        # deterministic sampled rows (SampleTextDataFromFile analog,
        # dataset_loader.cpp:961) via data_random_seed
        if sample_cnt < n:
            rng = np.random.RandomState(cfg.data_random_seed)
            sample_rows = _sample_rows(rng, n, sample_cnt)
            if sample_col_factory is not None:
                sample_col = sample_col_factory(sample_rows)
            else:
                sample_col = lambda f: colfn(f)[sample_rows]  # noqa: E731
        elif sample_col_factory is not None:
            sample_col = sample_col_factory(np.arange(n, dtype=np.int64))
        else:
            sample_col = colfn
        # may arrive as list OR ndarray (the reference accepts both;
        # `if ndarray` would raise on truthiness)
        max_bin_by_feature = cfg.max_bin_by_feature
        if max_bin_by_feature is not None and len(max_bin_by_feature) == 0:
            max_bin_by_feature = None
        forced = {}
        if getattr(cfg, "forcedbins_filename", ""):
            # forced bin upper bounds (dataset_loader.cpp:519-524): JSON
            # list of {"feature": i, "bin_upper_bound": [...]}
            import json
            with open(cfg.forcedbins_filename) as fh:
                for entry in json.load(fh):
                    forced[int(entry["feature"])] = [
                        float(v) for v in entry.get("bin_upper_bound", [])]
        self.bin_mappers = []
        for f in range(self.num_total_features):
            m = BinMapper()
            mb = int(max_bin_by_feature[f]) if max_bin_by_feature is not None \
                else cfg.max_bin
            bt = BinType.CATEGORICAL if f in cat_idx else BinType.NUMERICAL
            m.find_bin(sample_col(f), sample_cnt, mb, cfg.min_data_in_bin,
                       # the reference scales the pre-filter threshold
                       # to the SAMPLE (dataset_loader.cpp:687:
                       # min_data_in_leaf * sample_size / num_data) —
                       # num_data is the true row count, NOT the n the
                       # streaming path passes (= its sample length)
                       min_split_data=int(cfg.min_data_in_leaf
                                          * sample_cnt
                                          / max(self.num_data, 1)),
                       pre_filter=cfg.feature_pre_filter, bin_type=bt,
                       use_missing=cfg.use_missing, zero_as_missing=cfg.zero_as_missing,
                       forced_bounds=forced.get(f))
            self.bin_mappers.append(m)
        self._finalize_mappers()

        if do_bundle and cfg.enable_bundle and len(self.used_features) > 1:
            # EFB over the fitting sample (FastFeatureBundling,
            # dataset.cpp:239; see efb.py)
            mappers = [self.bin_mappers[f] for f in self.used_features]
            # pigeonhole pre-check: a pair can bundle only if
            # nz_i + nz_j - S <= budget (their non-default rows can't
            # all avoid each other otherwise).  If even the two
            # sparsest features fail that bound, no bundle is possible
            # and the whole conflict-sampling pass — a second
            # value_to_bin over every feature, the dominant cost on
            # wide DENSE data like Epsilon — is provably a no-op.
            # nz comes from the mapper's EXACT bin-0 occupancy
            # (bin0_frac; NOT 1-sparse_rate, which is the single most
            # frequent VALUE's share and under-counts a bin 0 that
            # merged several values — that would disable real bundles).
            # Unknown occupancy (loaded mappers) is 1.0 -> nz 0 -> the
            # gate never fires and the full conflict count runs.
            nz_frac = np.sort([1.0 - m.bin0_frac for m in mappers])
            if nz_frac[0] + nz_frac[1] - 1.0 > cfg.max_conflict_rate:
                self.efb = None
                return
            sample_bins = np.column_stack(
                [m.value_to_bin(sample_col(f)) for m, f
                 in zip(mappers, self.used_features)])
            efb = find_bundles(
                sample_bins,
                np.asarray([m.num_bin for m in mappers]),
                np.asarray([m.bin_type == BinType.CATEGORICAL
                            for m in mappers]),
                np.asarray([m.most_freq_bin for m in mappers]),
                max_conflict_rate=cfg.max_conflict_rate)
            self.efb = efb if efb.any_bundled else None

    def _finalize_mappers(self) -> None:
        self.used_features = [f for f in range(self.num_total_features)
                              if not self.bin_mappers[f].is_trivial]
        if not self.used_features and self.num_total_features > 0:
            # ALL features trivial (constant data): keep one
            # unsplittable placeholder column so training degrades to
            # stump trees — predictions become the boosted average,
            # matching the reference, which happily trains on constant
            # data (test_engine.py check_constant_features) instead of
            # erroring out
            self.used_features = [0]
        nbins = [self.bin_mappers[f].num_bin for f in self.used_features]
        self.bin_offsets = np.concatenate([[0], np.cumsum(nbins)]).astype(np.int32)
        self.max_bin = max([2] + nbins)

    def _try_sparse_bin(self, cfg, csc) -> bool:
        """Sparse binned storage decision (sparse_bin.hpp:73 /
        multi_val_sparse_bin.hpp analog — see sparse_data.py).

        Taken only for scipy-sparse input with ``is_enable_sparse`` on:
        collect the non-default-bin entries O(nnz) off the CSC layout,
        then keep the padded k-hot layout iff it is smaller than the
        dense (post-EFB bundled) matrix it replaces — for Allstate-class
        width (13.2M x 4228, docs/Experiments.rst:32) that is ~4K bytes/row
        vs G bytes/row, the difference between fitting one chip's HBM or
        not.  Never chosen under linear_tree (needs dense raw values)."""
        nf = len(self.used_features)
        if (cfg is None or csc is None or not cfg.is_enable_sparse
                or cfg.linear_tree or nf == 0):
            return False
        from . import sparse_data as spd
        stride = self.max_bin
        rows, flat, default_bin = spd.collect_entries_csc(
            csc, self.bin_mappers, self.used_features, stride)
        counts = np.bincount(rows, minlength=self.num_data) if len(rows) \
            else np.zeros(self.num_data, np.int64)
        k = int(max(counts.max() if self.num_data else 0, 1))
        sparse_bytes = self.num_data * k * 4
        if self.efb is not None:
            g = len(self.efb.group_num_bin)
            # the grouped matrix's dtype follows the widest BUNDLE bin
            # axis, not max_bin (bin_grouped) — bundles may exceed 256
            elt = 1 if int(self.efb.group_num_bin.max()) <= 256 else 2
        else:
            g = nf
            elt = 1 if self.max_bin <= 256 else 2
        dense_bytes = self.num_data * g * elt
        if sparse_bytes >= dense_bytes:
            return False
        self.binned_sparse = spd.build_khot(rows, flat, default_bin,
                                            self.num_data, stride, nf,
                                            counts=counts)
        self.binned = None
        self.efb = None     # the k-hot layout replaces bundling outright
        from .utils.log import Log
        Log.info(f"sparse binned storage: [N={self.num_data}, K={k}] k-hot "
                 f"({sparse_bytes / 2**20:.1f} MB) chosen over dense "
                 f"[N, {g}] ({dense_bytes / 2**20:.1f} MB)")
        return True

    def _bin_data(self, colfn, cfg=None, csc=None) -> None:
        nf = len(self.used_features)
        if self._try_sparse_bin(cfg, csc):
            return
        if self.efb is not None:
            self.binned = bin_grouped(
                lambda j: self.bin_mappers[self.used_features[j]]
                .value_to_bin(colfn(self.used_features[j])),
                self.efb, self.num_data)
            return
        dtype = np.uint8 if self.max_bin <= 256 else np.uint16
        out = np.zeros((self.num_data, max(nf, 1)), dtype=dtype)
        for j, f in enumerate(self.used_features):
            out[:, j] = self.bin_mappers[f].value_to_bin(colfn(f)).astype(dtype)
        self.binned = out

    def feature_binned(self) -> np.ndarray:
        """Per-feature binned matrix [N, F] (ungrouping EFB bundles if
        present) — for learners that take the flat layout."""
        self.construct()
        if self.binned_sparse is not None:
            if self.binned_sparse.nbytes() > 2**28:
                from .utils.log import Log
                Log.warning("densifying a large sparse-binned dataset "
                            "([N, F] materialization) — prefer the serial/"
                            "data-parallel learners, which consume the "
                            "sparse layout directly")
            return self.binned_sparse.densify()
        if self.efb is None:
            return self.binned
        nb = np.asarray([self.bin_mappers[f].num_bin
                         for f in self.used_features])
        return unbundle(self.binned, self.efb, nb)

    # ------------------------------------------------------------------
    @property
    def num_features(self) -> int:
        """Number of used (non-trivial) features."""
        return len(self.used_features)

    @property
    def num_total_bins(self) -> int:
        return int(self.bin_offsets[-1]) if self.bin_offsets is not None else 0

    def get_label(self) -> np.ndarray:
        self.construct()
        return self.metadata.label

    def get_init_score(self):
        self.construct()
        return self.metadata.init_score

    def get_data(self):
        """Raw feature values (basic.py get_data).  Raises once the raw
        values were freed (free_raw_data=True after construction), like
        the reference, instead of silently returning None."""
        if self.raw_data is not None:
            return self.raw_data
        if self._raw_input is not None:
            return self._raw_input
        if self.used_indices is not None and self.reference is not None:
            # subset of a Sequence-backed parent: gather rows lazily
            # through the Sequence protocol only when actually asked
            rows = self.reference._raw_rows(self.used_indices)
            if rows is not None:
                return rows
        raise ValueError(
            "raw data was freed: construct the Dataset with "
            "free_raw_data=False to keep it available")

    def get_field(self, field_name: str):
        """Generic metadata accessor (basic.py get_field)."""
        self.construct()
        md = self.metadata
        if field_name == "label":
            return md.label
        if field_name == "weight":
            return md.weight
        if field_name in ("group", "query"):
            return md.query_boundaries
        if field_name == "init_score":
            return md.init_score
        raise ValueError(f"unknown field {field_name!r}")

    def set_field(self, field_name: str, data) -> "Dataset":
        """Generic metadata setter (basic.py set_field)."""
        if field_name == "label":
            return self.set_label(data)
        if field_name == "weight":
            return self.set_weight(data)
        if field_name in ("group", "query"):
            return self.set_group(data)
        if field_name == "init_score":
            return self.set_init_score(data)
        raise ValueError(f"unknown field {field_name!r}")

    def set_reference(self, reference: "Dataset") -> "Dataset":
        """Align binning with another dataset (basic.py set_reference);
        only valid before construction."""
        if self._constructed:
            raise ValueError(
                "cannot set reference after the dataset is constructed")
        self.reference = reference
        return self

    def set_categorical_feature(self, categorical_feature) -> "Dataset":
        if self._constructed:
            raise ValueError("cannot change categorical_feature after "
                             "construction")
        self._categorical_in = categorical_feature
        return self

    def set_feature_name(self, feature_name) -> "Dataset":
        names = list(feature_name)
        # validate against whatever width is known NOW — post-construct
        # the resolved names, pre-construct the raw input's column count
        # (a silently accepted wrong-sized list would only surface much
        # later as an IndexError inside plotting/dataframe helpers)
        nf = len(self.feature_names) if getattr(self, "feature_names",
                                                None) else 0
        if not nf:
            raw = getattr(self, "_raw_input", None)
            if raw is not None and hasattr(raw, "shape") \
                    and len(raw.shape) == 2:
                nf = raw.shape[1]
        if nf and len(names) != nf:
            raise ValueError(f"{len(names)} names for {nf} features")
        self._feature_name_in = names
        if getattr(self, "feature_names", None):
            self.feature_names = list(names)
        return self

    def feature_num_bin(self, feature: int) -> int:
        """Bin count of one feature (basic.py feature_num_bin);
        trivial/unused features report 0 like the reference's
        LGBM_DatasetGetFeatureNumBin."""
        self.construct()
        m = self.bin_mappers[int(feature)]
        return 0 if m.is_trivial else int(m.num_bin)

    def get_ref_chain(self, ref_limit: int = 100):
        """The reference chain (basic.py get_ref_chain)."""
        chain, seen = [], set()
        node = self
        while node is not None and id(node) not in seen \
                and len(chain) < ref_limit:
            chain.append(node)
            seen.add(id(node))
            node = node.reference
        return chain

    def add_features_from(self, other: "Dataset") -> "Dataset":
        """Append other's feature columns (Dataset::AddFeaturesFrom,
        LGBM_DatasetAddFeaturesFrom)."""
        from .basic import LightGBMError
        if not self._constructed or not other._constructed:
            # reference semantics: both handles must exist (basic.py
            # add_features_from raises before touching the C API)
            raise LightGBMError(
                "Both source and target Datasets must be constructed "
                "before adding features")
        if self.num_data != other.num_data:
            raise LightGBMError(
                f"Cannot add features from other Dataset with a "
                f"different number of rows ({other.num_data} vs "
                f"{self.num_data})")
        nt = self.num_total_features
        self.binned = np.concatenate(
            [self.feature_binned(), other.feature_binned()], axis=1)
        self.bin_offsets = None
        self.efb = None                # bundles no longer match columns
        self.binned_sparse = None      # merged matrix is dense flat layout
        self.bin_mappers = list(self.bin_mappers) + list(other.bin_mappers)
        self.used_features = list(self.used_features) + [
            nt + f for f in other.used_features]
        self.num_total_features = nt + other.num_total_features
        self.feature_names = (list(self.feature_names)
                              + list(other.feature_names))
        if self.raw_data is not None and other.raw_data is not None \
                and hasattr(self.raw_data, "shape") \
                and hasattr(other.raw_data, "shape"):
            self.raw_data = np.concatenate(
                [np.asarray(self.raw_data), np.asarray(other.raw_data)],
                axis=1)
        else:
            self.raw_data = None
        return self

    def get_weight(self):
        self.construct()
        return self.metadata.weight

    def get_group(self):
        self.construct()
        if self.metadata.query_boundaries is None:
            return None
        return np.diff(self.metadata.query_boundaries)

    def get_feature_name(self):
        self.construct()
        return list(self.feature_names)

    def _dump_text(self, path) -> "Dataset":
        """Deterministic text dump of the constructed dataset
        (LGBM_DatasetDumpText's debugging role, c_api.cpp DumpText):
        names, per-feature bin bounds, and the binned rows — two
        datasets with identical content dump identical text regardless
        of HOW they were built (direct construct vs add_features_from),
        which is exactly what the reference's add_features tests
        compare."""
        self.construct()
        flat = self.feature_binned()
        used = set(self.used_features)
        with open(path, "w") as f:
            f.write(f"num_data={self.num_data} "
                    f"num_features={self.num_total_features}\n")
            f.write("feature_names=" + ",".join(self.feature_names) + "\n")
            col = 0
            for j in range(self.num_total_features):
                m = self.bin_mappers[j]
                bounds = ",".join(f"{b:.17g}" for b in
                                  np.asarray(m.bin_upper_bound).ravel()) \
                    if m.bin_upper_bound is not None else ""
                f.write(f"feature {j} used={j in used} "
                        f"num_bin={int(m.num_bin)} bounds=[{bounds}]\n")
            for i in range(self.num_data):
                row = []
                col = 0
                for j in range(self.num_total_features):
                    if j in used:
                        row.append(str(int(flat[i, col])))
                        col += 1
                    else:
                        row.append("-")
                f.write(" ".join(row) + "\n")
        return self

    # -- reference attribute surface --------------------------------------
    # basic.py keeps label/weight/init_score/group/feature_name as plain
    # Dataset attributes refreshed from the C side on every set_field;
    # here they are live views of the same state (metadata once
    # constructed, the constructor inputs before), so
    # ``ds.label``/``ds.get_label()``/``ds.get_field('label')`` always
    # agree (test_basic.py::test_consistent_state_for_dataset_fields).
    @property
    def label(self):
        return self.metadata.label if self.metadata is not None \
            else self._label_in

    @label.setter
    def label(self, value):
        self.set_label(value)

    @property
    def weight(self):
        return self.metadata.weight if self.metadata is not None \
            else self._weight_in

    @weight.setter
    def weight(self, value):
        self.set_weight(value)

    @property
    def init_score(self):
        return self.metadata.init_score if self.metadata is not None \
            else self._init_score_in

    @init_score.setter
    def init_score(self, value):
        self.set_init_score(value)

    @property
    def group(self):
        if self.metadata is not None:
            if self.metadata.query_boundaries is None:
                return None
            return np.diff(self.metadata.query_boundaries)
        return self._group_in

    @group.setter
    def group(self, value):
        self.set_group(value)

    @property
    def feature_name(self):
        if getattr(self, "feature_names", None):
            return list(self.feature_names)
        return self._feature_name_in

    @feature_name.setter
    def feature_name(self, value):
        self.set_feature_name(value)

    def set_label(self, label):
        if self.metadata is None:
            self._label_in = label
        else:
            self.metadata.set_label(label)
        return self

    def set_weight(self, weight):
        if self.metadata is None:
            self._weight_in = weight
        else:
            self.metadata.set_weight(weight)
        return self

    def set_group(self, group):
        if self.metadata is None:
            self._group_in = group
        else:
            self.metadata.set_group(group)
        return self

    def set_init_score(self, init_score):
        if self.metadata is None:
            self._init_score_in = init_score
        else:
            self.metadata.set_init_score(init_score)
        return self

    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, params=None) -> "Dataset":
        return Dataset(data, label=label, weight=weight, group=group,
                       init_score=init_score, reference=self,
                       params=params or self.params)

    def _raw_rows(self, idx: np.ndarray):
        """Raw feature rows for ``idx``, from whichever raw source
        survives: the kept ndarray/CSR, or the kept Sequence list
        (gathered through the Sequence protocol)."""
        if self.raw_data is not None:
            return self.raw_data[idx]
        src = self._raw_input
        if src is None:
            return None
        if isinstance(src, Sequence) or (isinstance(src, (list, tuple))
                                         and len(src)
                                         and isinstance(src[0], Sequence)):
            seqs = [src] if isinstance(src, Sequence) else list(src)
            bounds = np.concatenate([[0], np.cumsum([len(s) for s in seqs])])
            rows = []
            for i in idx:
                si = int(np.searchsorted(bounds, i, side="right") - 1)
                rows.append(np.asarray(seqs[si][int(i - bounds[si])],
                                       np.float64).reshape(-1))
            return np.asarray(rows)
        if hasattr(src, "shape"):
            return np.asarray(src, np.float64)[idx]
        return None

    def subset(self, used_indices, params=None) -> "Dataset":
        """Row-subset copy (Dataset::CopySubrow, dataset.h:486 analog).
        Indices are SORTED like the reference python subset (basic.py
        used_indices sort) — rows keep their original relative order."""
        self.construct()
        idx = np.sort(np.asarray(used_indices, dtype=np.int64))
        sub = Dataset.__new__(Dataset)
        sub.__dict__.update({k: v for k, v in self.__dict__.items()})
        sub.num_data = len(idx)
        sub.binned = self.binned[idx] if self.binned is not None else None
        sub.binned_sparse = self.binned_sparse.subset_rows(idx) \
            if self.binned_sparse is not None else None
        # raw rows slice cheaply when the parent holds them in memory;
        # a Sequence-backed parent stays LAZY (get_data gathers through
        # the protocol on demand via used_indices + reference) — eager
        # gathering here would materialize dense row blocks for every
        # cv fold of an out-of-core dataset
        sub.raw_data = self.raw_data[idx] if self.raw_data is not None \
            else None
        sub._raw_input = None
        sub.used_indices = idx
        sub.metadata = Metadata(len(idx))
        if self.metadata.label is not None:
            sub.metadata.label = self.metadata.label[idx]
        if self.metadata.weight is not None:
            sub.metadata.weight = self.metadata.weight[idx]
        if self.metadata.init_score is not None:
            sub.metadata.init_score = self.metadata.init_score[idx]
        if self.metadata.query_boundaries is not None:
            # per-query counts of the selected rows, empty queries
            # dropped — partial queries shrink (Metadata::CopySubrow's
            # query handling; sorted idx keeps rows query-contiguous)
            qb = self.metadata.query_boundaries
            qidx = np.searchsorted(qb, idx, side="right") - 1
            sub.metadata.set_group(np.unique(qidx, return_counts=True)[1])
        sub.reference = self
        return sub

    def _group_from_parent(self, parent: "Dataset", idx: np.ndarray) -> None:
        """Reconstruct query boundaries for a row subset whose indices cover
        whole queries (cv fold construction)."""
        qb = parent.metadata.query_boundaries
        if qb is None:
            return
        qid = np.searchsorted(qb, np.asarray(idx), side="right") - 1
        # run-length encode consecutive query ids
        change = np.nonzero(np.diff(qid))[0] + 1
        starts = np.concatenate([[0], change, [len(qid)]])
        sizes = np.diff(starts)
        self.metadata.set_group(sizes)

    def fingerprint(self) -> str:
        """Cheap content fingerprint for snapshot manifests (snapshot.py):
        row count + f32 label/weight bytes, computed identically before
        and after ``construct()`` so the manifest written mid-training
        matches the check a resuming run performs on its yet-unbinned
        dataset.  A guard against resuming onto the wrong data — not a
        cryptographic identity of the feature matrix."""
        lab = wgt = None
        if self.metadata is not None:
            lab, wgt = self.metadata.label, self.metadata.weight
        if lab is None:
            lab = getattr(self, "_label_in", None)
        if wgt is None:
            wgt = getattr(self, "_weight_in", None)
        return fingerprint_arrays(lab, wgt)

    # -- binary cache ----------------------------------------------------
    def save_binary(self, path: str) -> None:
        """Binary dataset cache (dataset.cpp SaveBinaryFile analog)."""
        self.construct()
        payload: Dict[str, Any] = {
            "bin_offsets": self.bin_offsets,
            "used_features": np.asarray(self.used_features, dtype=np.int32),
            "num_total_features": self.num_total_features,
            "max_bin": self.max_bin,
            "feature_names": np.asarray(self.feature_names, dtype=object),
            "num_mappers": len(self.bin_mappers),
        }
        if self.binned_sparse is not None:
            payload["sparse_flat"] = self.binned_sparse.flat
            payload["sparse_default_bin"] = self.binned_sparse.default_bin
            payload["sparse_stride"] = self.binned_sparse.stride
        else:
            payload["binned"] = self.binned
        for i, m in enumerate(self.bin_mappers):
            for k, v in m.to_state().items():
                payload[f"mapper{i}_{k}"] = v
        if self.metadata.label is not None:
            payload["label"] = self.metadata.label
        if self.metadata.weight is not None:
            payload["weight"] = self.metadata.weight
        if self.metadata.query_boundaries is not None:
            payload["query_boundaries"] = self.metadata.query_boundaries
        if self.metadata.init_score is not None:
            payload["init_score"] = self.metadata.init_score
        # raw feature values are in the binary ONLY for linear-tree
        # datasets (the reference's SaveBinaryFile keeps raw values iff
        # has_raw_, i.e. linear_tree — a loaded dataset must still fit
        # linear leaves).  Otherwise the file stores just the binned
        # representation + metadata, making it a pure function of
        # dataset CONTENT: an ndarray-built and a Sequence-built
        # dataset with identical bins produce identical binaries
        # (test_basic.py::test_sequence's filecmp contract).
        if getattr(self, "_built_linear_tree", False) \
                and self.raw_data is not None:
            if isinstance(self.raw_data, np.ndarray):
                payload["raw_data"] = self.raw_data
            elif hasattr(self.raw_data, "tocsr"):
                csr = self.raw_data.tocsr()
                payload["raw_csr_data"] = csr.data
                payload["raw_csr_indices"] = csr.indices
                payload["raw_csr_indptr"] = csr.indptr
                payload["raw_csr_shape"] = np.asarray(csr.shape, np.int64)
        if self.efb is not None:
            payload["efb_group_of_feat"] = self.efb.group_of_feat
            payload["efb_off_of_feat"] = self.efb.off_of_feat
            payload["efb_group_num_bin"] = self.efb.group_num_bin
            payload["efb_group_sizes"] = np.asarray(
                [len(g) for g in self.efb.groups], np.int32)
            payload["efb_group_members"] = np.asarray(
                [j for g in self.efb.groups for j in g], np.int32)
        # write through a BYTES buffer so the EXACT requested filename is
        # honored (np.savez appends '.npz' to bare string paths — the
        # reference C API contract saves to the caller's name verbatim),
        # then atomically (temp + os.replace, utils/resilience.py): a
        # crash mid-save can never leave a truncated binary cache that a
        # later run would try to load
        import io as _io
        buf = _io.BytesIO()
        np.savez_compressed(buf, **payload)
        from .utils.resilience import atomic_write
        # getbuffer(): hand atomic_write a view, not a second full copy
        atomic_write(path, buf.getbuffer(), binary=True)

    @classmethod
    def from_ingest(cls, source: str, params: Optional[Dict[str, Any]] = None,
                    **kwargs) -> "Dataset":
        """Streaming out-of-core construction from a chunked text source
        (file or directory of chunks) via the survivable ingest pipeline
        (lightgbm_tpu/ingest.py): checkpointed chunk spool + manifest,
        retry/quarantine per chunk, bin mappers fitted from merged
        quantile sketches.  Keyword args pass through to
        ``ingest.ingest_dataset`` (``has_header``, ``label_column``,
        ``categorical_idx``, ``spool_dir``, ``reference``)."""
        from .ingest import ingest_dataset
        return ingest_dataset(source, params, **kwargs)

    @classmethod
    def load_binary(cls, path: str) -> "Dataset":
        if not os.path.exists(path) and os.path.exists(path + ".npz"):
            path = path + ".npz"
        z = np.load(path, allow_pickle=True)
        ds = cls.__new__(cls)
        ds.params = {}
        ds.reference = None
        ds.free_raw_data = False
        ds._constructed = True
        ds._raw_input = None
        ds.used_features = [int(x) for x in z["used_features"]]
        if "sparse_flat" in z.files:
            from .sparse_data import SparseBinnedHost
            ds.binned = None
            ds.binned_sparse = SparseBinnedHost(
                z["sparse_flat"], z["sparse_default_bin"],
                int(z["sparse_stride"]), len(ds.used_features))
            ds.num_data = ds.binned_sparse.flat.shape[0]
        else:
            ds.binned = z["binned"]
            ds.binned_sparse = None
            ds.num_data = ds.binned.shape[0]
        ds.bin_offsets = z["bin_offsets"]
        ds.num_total_features = int(z["num_total_features"])
        ds.max_bin = int(z["max_bin"])
        ds.feature_names = [str(x) for x in z["feature_names"]]
        n_mappers = int(z["num_mappers"])
        ds.bin_mappers = []
        for i in range(n_mappers):
            st = {k.split("_", 1)[1]: z[k] for k in z.files if k.startswith(f"mapper{i}_")}
            ds.bin_mappers.append(BinMapper.from_state(st))
        ds.metadata = Metadata(ds.num_data)
        if "label" in z.files:
            ds.metadata.label = z["label"]
        if "weight" in z.files:
            ds.metadata.weight = z["weight"]
        if "query_boundaries" in z.files:
            ds.metadata.query_boundaries = z["query_boundaries"]
        if "init_score" in z.files:
            ds.metadata.init_score = z["init_score"]
        if "raw_data" in z.files:
            ds.raw_data = z["raw_data"]
        elif "raw_csr_data" in z.files:
            import scipy.sparse as _sp
            ds.raw_data = _sp.csr_matrix(
                (z["raw_csr_data"], z["raw_csr_indices"], z["raw_csr_indptr"]),
                shape=tuple(z["raw_csr_shape"]))
        else:
            ds.raw_data = None
        ds.efb = None
        if "efb_group_of_feat" in z.files:
            sizes = z["efb_group_sizes"]
            members = [int(x) for x in z["efb_group_members"]]
            groups, pos = [], 0
            for sz in sizes:
                groups.append(members[pos:pos + int(sz)])
                pos += int(sz)
            ds.efb = EFBInfo(groups=groups,
                             group_of_feat=z["efb_group_of_feat"],
                             off_of_feat=z["efb_off_of_feat"],
                             group_num_bin=z["efb_group_num_bin"])
        return ds

    def num_bins_of(self, used_feature_slot: int) -> int:
        f = self.used_features[used_feature_slot]
        return self.bin_mappers[f].num_bin
