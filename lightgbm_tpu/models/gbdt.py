"""GBDT boosting driver.

TPU-native analog of the reference GBDT
(/root/reference/src/boosting/gbdt.cpp): iteration loop of
gradient computation -> (bagging | GOSS sampling) -> per-class tree growth
on device -> leaf renewal -> shrinkage -> score update (gbdt.cpp:371-449
``TrainOneIter``).  Scores for train data are updated via the grower's
row->leaf vector (no traversal); validation scores via device traversal
(predict_device.py).  Model state (host ``Tree`` list) is serialized in the
reference text format by the Booster layer.
"""

from __future__ import annotations

import copy
import math
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..dataset import Dataset
from ..grower import make_grower, TreeArrays
from ..objectives import ObjectiveFunction
from ..ops.split import SplitParams
from ..predict_device import add_tree_score, round_up_pow2, traverse_tree_binned
from ..tree_model import Tree

# finite_check_policy=clamp replaces non-finite gradients/hessians/leaf
# outputs with 0 (NaN) or ±this bound (infinities) — large enough not to
# distort healthy training, small enough that squares stay in f32 range
_FINITE_CLAMP = 1e30

# process-level super-epoch program sharing (the grower._SHARED_GROWERS
# pattern one layer up): the jitted k-iteration scan closes over NO
# data-derived device arrays — binned matrices, bin metadata, objective
# arrays and valid-set operands all ride in as ARGUMENTS — so two
# boosters whose configs match (31/63 num_leaves collapse onto one
# L=64 leaf bucket) reuse ONE compiled super-epoch.  Keyed on the full
# config plus every shape-/semantics-relevant static; any unkeyable
# state (EFB bundles, categorical flags, CEGB, monotone/interaction
# constraints, multi-process meshes) falls back to a private per-model
# jit in ``self._fused_cache`` — correct, just not shared.
_SE_CACHE: "OrderedDict[tuple, object]" = OrderedDict()
_SE_CACHE_MAX = 8
_SE_CACHE_LOCK = threading.Lock()


class _DeviceTree:
    """Per-tree device arrays for fast binned traversal."""

    __slots__ = ("split_feature", "threshold_bin", "default_left",
                 "left_child", "right_child", "is_cat_node", "cat_rank",
                 "leaf_value", "steps")

    def __init__(self, arrays: TreeArrays, leaf_value: np.ndarray, steps: int):
        self.split_feature = arrays.split_feature
        self.threshold_bin = arrays.threshold_bin
        self.default_left = arrays.default_left
        self.left_child = arrays.left_child
        self.right_child = arrays.right_child
        self.is_cat_node = arrays.is_cat_node
        self.cat_rank = arrays.cat_rank
        self.leaf_value = jnp.asarray(leaf_value, jnp.float32)
        self.steps = steps


def _apply_tree(score_vec, binned, dt: _DeviceTree, na_bin, weight: float,
                efb_maps=None):
    """score_vec += weight * tree(binned) — dense or sparse-binned rows."""
    from ..sparse_data import SparseBinned, add_tree_score_sparse
    if isinstance(binned, SparseBinned):
        return add_tree_score_sparse(
            score_vec, binned, dt.split_feature, dt.threshold_bin,
            dt.default_left, dt.left_child, dt.right_child, na_bin,
            dt.is_cat_node, dt.cat_rank, dt.leaf_value,
            jnp.float32(weight), steps=dt.steps)
    return add_tree_score(
        score_vec, binned, dt.split_feature, dt.threshold_bin,
        dt.default_left, dt.left_child, dt.right_child, na_bin,
        dt.is_cat_node, dt.cat_rank, dt.leaf_value, jnp.float32(weight),
        efb_maps, steps=dt.steps)


def _tree_leaves(binned, dt: _DeviceTree, na_bin, efb_maps=None):
    """Leaf id per row — dense or sparse-binned rows."""
    from ..sparse_data import SparseBinned, traverse_tree_sparse
    if isinstance(binned, SparseBinned):
        return traverse_tree_sparse(
            binned, dt.split_feature, dt.threshold_bin, dt.default_left,
            dt.left_child, dt.right_child, na_bin, dt.is_cat_node,
            dt.cat_rank, steps=dt.steps)
    return traverse_tree_binned(
        binned, dt.split_feature, dt.threshold_bin, dt.default_left,
        dt.left_child, dt.right_child, na_bin, dt.is_cat_node,
        dt.cat_rank, efb_maps, steps=dt.steps)


class GBDTModel:
    """Boosting state machine (boosting.h:27-319 interface analog)."""

    def __init__(self, config: Config, train_set: Dataset,
                 objective: Optional[ObjectiveFunction],
                 hist_reduce=None):
        self.config = config
        self.train_set = train_set.construct(config)
        self.objective = objective
        self.num_class = config.num_model_per_iteration
        self.learning_rate = config.learning_rate
        self.iter_ = 0
        # iteration-keyed RNG/guard streams (bagging epochs, GOSS keys,
        # extra_trees/bynode draws, finite-check cadence) run on
        # iter_ + this offset so a crash+resume run replays the SAME
        # per-iteration randomness as the straight run (snapshot resume,
        # engine.py; set via set_resume_state)
        self._iter_rng_offset = 0

        ds = self.train_set
        self.num_data = ds.num_data
        self.num_features = ds.num_features
        if self.num_features == 0:
            raise ValueError("Dataset has no usable (non-trivial) features")
        import jax as _jax
        self._pc = _jax.process_count()   # >1 = one controller per host

        # elastic liveness layer (parallel/elastic.py): when enabled,
        # the per-iteration host fetch runs under the collective
        # deadline and peers are heartbeat-checked each iteration.
        # Disabled (default) costs one None test per fetch — every
        # path stays byte-identical to before
        self._elastic = None
        self._elastic_timeout = 0.0
        if getattr(config, "elastic_enable", False):
            from ..parallel import elastic as _elastic
            self._elastic = _elastic
            self._elastic_timeout = float(
                config.elastic_collective_timeout_s)
        self._global_fp = None      # cached global data fingerprint

        # learner selection (the device_type axis, tree_learner.cpp:16-64):
        # - partitioned: host-orchestrated, histogram work ∝ smaller child —
        #   wins when dispatch is cheap (CPU) or trees are huge
        # - masked: ONE jitted program per tree (the cuda_exp stance,
        #   cuda_single_gpu_tree_learner.cpp) — wins on accelerators where
        #   per-split host round-trips dominate (esp. remote/tunneled chips)
        learner = config.tpu_learner
        if learner == "auto":
            import jax
            learner = "partitioned" if jax.default_backend() == "cpu" \
                else "masked"
        if ds.binned_sparse is not None:
            # sparse k-hot storage (sparse_data.py) is consumed by the
            # one-program masked grower; the partitioned learner works on
            # host-dense arrays, which would defeat the memory budget
            if learner == "partitioned" and config.tpu_learner != "auto":
                from ..utils.log import Log
                Log.warning(
                    "tpu_learner=partitioned overridden to masked: the "
                    "dataset chose sparse binned storage (pass "
                    "enable_sparse=false to keep the partitioned learner)")
            learner = "masked"

        self.split_params = SplitParams(
            lambda_l1=config.lambda_l1,
            lambda_l2=config.lambda_l2,
            min_data_in_leaf=config.min_data_in_leaf,
            min_sum_hessian_in_leaf=config.min_sum_hessian_in_leaf,
            min_gain_to_split=config.min_gain_to_split,
            max_delta_step=config.max_delta_step,
            path_smooth=config.path_smooth,
            cat_l2=config.cat_l2,
            cat_smooth=config.cat_smooth,
            max_cat_threshold=config.max_cat_threshold,
            max_cat_to_onehot=config.max_cat_to_onehot,
            min_data_per_group=config.min_data_per_group,
        )
        mono = None
        if config.monotone_constraints:
            mc_full = np.zeros(ds.num_total_features, np.int32)
            mc_in = np.asarray(config.monotone_constraints, np.int32)
            mc_full[:len(mc_in)] = mc_in
            mono = mc_full[np.asarray(ds.used_features)]
        inter = self._interaction_allow(config, ds)
        self._cegb_state = self._make_cegb(config, ds)
        self._forced_spec = self._load_forced(config, ds)
        # feature_contri: per-feature split-gain scale over used slots
        # (feature_histogram.hpp; config.h feature_contri)
        contri = None
        if config.feature_contri:
            fc = np.ones(ds.num_total_features, np.float32)
            vals_in = np.asarray(config.feature_contri, np.float32)
            fc[:len(vals_in)] = vals_in
            contri = fc[np.asarray(ds.used_features)]
        self._feature_contri = contri
        self._extra_trees = bool(config.extra_trees)
        mono_active = mono is not None and np.any(mono)
        # monotone 'basic' lives in the one-program masked grower too
        # (device-resident [L] lo/hi range vectors, grower.py), so it no
        # longer forces the host-orchestrated path and is supported under
        # the data-parallel learner like the reference's parallel learners
        # (monotone_constraints.hpp works under all of them).
        # 'intermediate'/'advanced' recompute the whole frontier's
        # intervals from sibling subtrees — still host bookkeeping.
        mono_masked_ok = mono_active \
            and config.monotone_constraints_method == "basic"
        self._mono = mono if mono_active else None
        self._inter = inter
        # interaction constraints and bynode sampling also run in the
        # masked grower now (per-leaf [L, F] feature-mask state / in-graph
        # subset draws, grower.py) — only CEGB, forced splits and the
        # non-basic monotone methods still need host orchestration
        self._bynode_masked = config.feature_fraction_bynode < 1.0
        has_node_controls = (mono_active and not mono_masked_ok) \
            or self._forced_spec is not None

        if has_node_controls and ds.binned_sparse is not None:
            raise ValueError(
                "forced splits and monotone intermediate/advanced need the "
                "host-orchestrated learner, which requires dense binned "
                "storage; construct the Dataset with enable_sparse=false")
        if has_node_controls and learner != "partitioned" \
                and config.tpu_learner == "auto":
            # node-level controls are host bookkeeping -> partitioned only
            # (auto falls back silently; explicit masked still errors below)
            learner = "partitioned"

        # distributed learner selection (tree_learner.cpp:16-64 factory;
        # config auto-promotes serial->data when num_machines>1).  The
        # distributed growers are shard_map wrappers around the masked
        # one-program grower (parallel/{data,feature,voting}_parallel.py).
        dist = config.tree_learner \
            if config.tree_learner in ("data", "feature", "voting") else None
        if dist is not None and hist_reduce is not None:
            # can't raise: num_machines>1 auto-promotes serial->data in
            # Config, so multi-host callers using the hook pattern never
            # asked for a distributed learner explicitly — warn and keep
            # the (previously silent) hook path
            from ..utils.log import Log
            Log.warning(
                f"ignoring tree_learner={dist}: a caller-supplied "
                "hist_reduce hook takes over cross-shard reduction")
        self._custom_hist_reduce = hist_reduce is not None
        self._fused_cache: Dict[str, object] = {}
        self._mesh = None
        self._row_pad = 0
        self._feat_pad = 0
        self._global_counts = None
        self._dist_axis = "feature" if dist == "feature" else "data"
        if dist is not None and hist_reduce is None:
            self._mesh = self._resolve_mesh(config, self._dist_axis)
            if self._mesh is None:
                dist = None             # single device -> serial (warned)
            elif has_node_controls or inter is not None \
                    or self._bynode_masked or self._cegb_state is not None:
                raise ValueError(
                    "monotone intermediate/advanced, interaction "
                    "constraints, CEGB, forced splits and "
                    "feature_fraction_bynode are not supported with "
                    f"tree_learner={dist} (they require a single-chip "
                    "learner); monotone basic IS supported")
            elif contri is not None or self._extra_trees:
                raise ValueError(
                    "feature_contri and extra_trees are not yet supported "
                    f"with tree_learner={dist}")
            elif mono_masked_ok and dist in ("feature", "voting"):
                raise ValueError(
                    f"monotone constraints with tree_learner={dist} are "
                    "not supported (the [F] constraint vector would need "
                    "feature-axis sharding); use tree_learner=data")
            else:
                learner = "masked"
        else:
            dist = None
        self._dist = dist
        self._learner_kind = learner

        # device-resident binned matrix + per-feature bin metadata.
        # EFB (efb.py): the grouped layout is used by the single-chip
        # learners AND the data-parallel learner, where it shrinks the
        # histogram reduce-scatter payload and the owner-shard chunk axis
        # (dataset.cpp:239 bundles before the reduce-scatter,
        # data_parallel_tree_learner.cpp:174-186).
        # Feature-parallel shards the feature axis (bundles would straddle
        # shards) and voting votes per feature, so both keep flat layout.
        self._use_efb = (ds.efb is not None and hist_reduce is None
                         and learner in ("partitioned", "masked")
                         and dist in (None, "data"))
        # sparse k-hot storage rides the masked serial/data-parallel paths
        # natively; feature/voting shard or vote per flat feature column,
        # so they fall back to densified flat layout (feature_binned warns)
        self._sparse = (ds.binned_sparse is not None and learner == "masked"
                        and dist in (None, "data"))
        if self._pc > 1 and dist == "data":
            # each process chose its binned layout (sparse k-hot vs
            # dense, EFB bundles vs flat, entry width K) from its LOCAL
            # rows; the jitted SPMD program needs ONE layout across the
            # pod.  Consensus: any dense rank demotes everyone to dense
            # (it means dense was viable there); dense ranks keep EFB
            # only when EVERY rank holds the IDENTICAL bundle structure
            # (bundles are fitted on per-rank samples, so shards can
            # disagree, and a sparse-chooser dropped its bundles
            # outright) — otherwise the whole pod uses the flat [N, F]
            # layout; all-sparse pods pad the entry axis to the max K.
            from jax.experimental import multihost_utils
            efb_sig = 0
            if self._use_efb:
                import hashlib
                hsh = hashlib.sha256()
                for a in (ds.efb.group_of_feat, ds.efb.off_of_feat,
                          ds.efb.group_num_bin,
                          [len(g) for g in ds.efb.groups],
                          [j for g in ds.efb.groups for j in g]):
                    hsh.update(np.asarray(a, np.int64).tobytes())
                efb_sig = int.from_bytes(hsh.digest()[:7], "big")
            mine = np.asarray([1 if self._sparse else 0,
                               ds.binned_sparse.k
                               if ds.binned_sparse is not None else 0,
                               efb_sig], np.int64)
            allinfo = np.asarray(multihost_utils.process_allgather(mine))
            if self._sparse and int(allinfo[:, 0].min()) == 0:
                from ..utils.log import Log
                Log.info("sparse binned storage demoted to dense: another "
                         "process's shard kept the dense layout")
                self._sparse = False
            elif self._sparse:
                kmax = int(allinfo[:, 1].max())
                sp = ds.binned_sparse
                if sp.k < kmax:
                    sp.flat = np.concatenate(
                        [sp.flat, np.full((sp.flat.shape[0],
                                           kmax - sp.k), -1, np.int32)],
                        axis=1)
            if not self._sparse:
                sigs = allinfo[:, 2]
                if self._use_efb and not (sigs == sigs[0]).all():
                    from ..utils.log import Log
                    Log.info("EFB bundles dropped pod-wide: processes "
                             "disagree on the bundle structure (per-rank "
                             "sample bundling); using the flat layout")
                if not (sigs == sigs[0]).all() or int(sigs[0]) == 0:
                    self._use_efb = False
        # quantized training (ROADMAP item 3, docs/Quantized-Training.md):
        # one QuantSpec threads through every learner family below —
        # masked (strict/batched/fused-chunk), partitioned, and all
        # three distributed growers
        self._quant = None
        if config.quant_train:
            if self._sparse:
                raise ValueError(
                    "quant_train requires dense binned storage (the "
                    "sparse k-hot segment-sum histogram has no integer "
                    "formulation yet); construct the Dataset with "
                    "enable_sparse=false")
            from ..ops.quantize import QuantSpec
            self._quant = QuantSpec(
                bits=int(config.quant_bits),
                stochastic=(config.quant_round == "stochastic"),
                seed=int(config.seed))

        if self._sparse:
            feat_binned = ds.binned_sparse.flat
        elif self._use_efb:
            feat_binned = ds.binned
        else:
            feat_binned = ds.feature_binned()
        num_bin = np.asarray([ds.bin_mappers[f].num_bin for f in ds.used_features],
                             np.int32)
        na_bin = np.asarray([ds.bin_mappers[f].na_bin for f in ds.used_features],
                            np.int32)
        self.num_bin_dev = jnp.asarray(num_bin)
        self.na_bin_dev = jnp.asarray(na_bin)
        from ..binning import BinType
        is_cat = np.asarray([ds.bin_mappers[f].bin_type == BinType.CATEGORICAL
                             for f in ds.used_features], bool)
        self.is_cat_dev = jnp.asarray(is_cat) if is_cat.any() else None
        self.max_bin = int(num_bin.max())
        if self._use_efb:
            from ..efb import make_device_efb
            self.efb_dev = make_device_efb(ds.efb, num_bin, self.max_bin)
            self.efb_maps = (self.efb_dev.group_of_feat,
                             jnp.asarray(ds.efb.off_of_feat),
                             jnp.asarray(num_bin - 1))
        else:
            self.efb_dev = None
            self.efb_maps = None

        # grower-facing bin metadata (== the user-facing arrays unless the
        # feature axis is padded for feature-parallel sharding)
        self._nb_grow = self.num_bin_dev
        self._na_grow = self.na_bin_dev
        self._ic_grow = self.is_cat_dev
        if dist in ("data", "voting"):
            from ..parallel.data_parallel import shard_rows
            n_sh = self._mesh.shape[self._dist_axis]
            if self._pc > 1:
                # multi-process (one controller per host): each process
                # holds only ITS rows; all processes must contribute the
                # same local row count to the global array, so pad to the
                # allgathered max rounded up to the local device count
                from jax.experimental import multihost_utils
                counts = np.asarray(multihost_utils.process_allgather(
                    np.asarray(self.num_data)))
                # unpadded per-process row counts: global GOSS needs the
                # true global N and this process's global row offset
                self._global_counts = counts
                ldev = max(n_sh // self._pc, 1)
                target = -(-int(counts.max()) // ldev) * ldev
                self._row_pad = target - self.num_data
            else:
                self._row_pad = (-self.num_data) % n_sh
            if self._row_pad:
                # sparse k-hot pads with -1 (no stored entries; the pad
                # rows' vals are zeroed so the default-bin fix adds 0)
                fill = -1 if self._sparse else 0
                feat_binned = np.concatenate(
                    [feat_binned, np.full((self._row_pad,
                                           feat_binned.shape[1]), fill,
                                          feat_binned.dtype)], axis=0)
            self.binned_dev = shard_rows(self._mesh, feat_binned,
                                         self._dist_axis)
        elif dist == "feature":
            n_sh = self._mesh.shape[self._dist_axis]
            self._feat_pad = (-self.num_features) % n_sh
            if self._feat_pad:
                feat_binned = np.concatenate(
                    [feat_binned, np.zeros((feat_binned.shape[0],
                                            self._feat_pad),
                                           feat_binned.dtype)], axis=1)
                pad_i = np.full(self._feat_pad, 2, np.int32)
                self._nb_grow = jnp.asarray(np.concatenate([num_bin, pad_i]))
                self._na_grow = jnp.asarray(np.concatenate(
                    [na_bin, np.full(self._feat_pad, -1, np.int32)]))
                if self.is_cat_dev is not None:
                    self._ic_grow = jnp.asarray(np.concatenate(
                        [is_cat, np.zeros(self._feat_pad, bool)]))
            self.binned_dev = jnp.asarray(feat_binned)
        else:
            self.binned_dev = jnp.asarray(feat_binned)
        if self._sparse:
            # wrap the (possibly sharded) flat entry matrix as the pytree
            # the grower/traversal paths dispatch on
            from ..sparse_data import SparseBinned
            self.binned_dev = SparseBinned(
                self.binned_dev, jnp.asarray(ds.binned_sparse.default_bin),
                ds.binned_sparse.stride, self.num_features)

        # split_batch resolution (config.py): 0 = auto -> strict leaf-wise
        # below 64 leaves, K-way super-steps above (PROFILE.md: the
        # histogram contraction is sublane-bound at M=3; batching K leaves
        # is the only way to raise that ceiling — M=3K of the MXU's 128
        # rows, so K=16 at 255 leaves lifts utilization to ~37% where K=8
        # sat at ~18%).  Voting stays strict: its per-split top-k feature
        # votes are per-histogram-pass.
        sb = config.split_batch
        self._split_batch = sb if sb >= 1 else \
            (16 if config.num_leaves >= 128 else
             8 if config.num_leaves >= 64 else 1)
        if dist == "voting":
            self._split_batch = 1
        if sb < 1 and self._split_batch > 1:
            from ..utils.log import Log
            Log.info(
                f"num_leaves={config.num_leaves} auto-selects "
                f"split_batch={self._split_batch} (top-K batched growth; "
                "trees differ slightly from strict leaf-wise order — set "
                "split_batch=1 for exact reference growth)")

        # on-device contraction autotuner (ops/hist_tune.py): under
        # hist_tune=on the FIRST fit per (platform, shape bucket)
        # sweeps the eligible (K, block_rows) grid by measured ms per
        # leaf slot and persists the winner next to the compile cache;
        # later fits — including other processes — reuse it (zero
        # re-tune, zero re-compile).  The tuner engages ONLY when
        # split_batch is on auto (an explicit width is the user's
        # choice, and applying the winner's paired block_rows to a
        # different K would both mis-tune and re-partition the f32
        # scan against the explicit-width byte pins); the tuned
        # block_rows fills rows_per_block=0.  Budgets that admit only
        # strict growth (num_leaves <= 8: no set width fits) have
        # nothing to tune and skip the sweep entirely.
        self._block_rows = config.rows_per_block
        self._hist_tuned = None
        if getattr(config, "hist_tune", "off") == "on" and sb < 1 \
                and learner == "masked" and dist != "voting" \
                and not self._sparse:
            from ..utils.shapes import SPLIT_BATCH_SET as _SBS
            from ..utils.shapes import fit_split_batch
            kmax = fit_split_batch(_SBS[-1], config.num_leaves)
            if kmax > 1:
                try:
                    from ..ops.hist_tune import ensure as _tune_ensure
                    # the contraction's column/bin axes: the binned
                    # matrix as built (EFB bundles -> group columns at
                    # group-bin width; dense otherwise)
                    t_cols = int(self.binned_dev.shape[1])
                    t_bins = (int(self.efb_dev.group_bins)
                              if self._use_efb else self.max_bin)
                    n_global = (int(self._global_counts.sum())
                                if self._global_counts is not None
                                else self.num_data)
                    rec = self._hist_tuned = _tune_ensure(
                        n_global, t_cols, t_bins,
                        itemsize=(self._quant.itemsize
                                  if self._quant is not None else 4),
                        kmax=kmax, config=config)
                    self._split_batch = rec["k"]
                    if config.rows_per_block <= 0:
                        self._block_rows = int(rec["block_rows"])
                    from ..utils.log import Log
                    Log.info(
                        f"hist_tune: measured choice K={rec['k']} "
                        f"block_rows={rec['block_rows']} "
                        f"({rec['ms_per_leaf']} ms/leaf-slot at "
                        f"{rec.get('sample_rows')} sampled rows)")
                except Exception as e:        # tuner is best-effort
                    from ..utils.log import Log
                    Log.warning(
                        f"hist_tune failed ({type(e).__name__}: {e}); "
                        "keeping untuned shapes")

        # trace-relevant static dims are bucketed (utils/shapes.py) so a
        # config sweep stays inside a bounded trace family; pinned by
        # tools/check_retraces.py.  trace_buckets=false restores exact
        # per-shape traces (A/B + escape hatch).
        from ..utils.shapes import (SPLIT_BATCH_SET, bucket_leaves,
                                    fit_split_batch, snap_split_batch)
        self._trace_buckets = bool(getattr(config, "trace_buckets", True))
        if self._trace_buckets and self._split_batch > 1:
            snapped = self._split_batch
            if snapped not in SPLIT_BATCH_SET:
                snapped = snap_split_batch(snapped)
            if snapped > 16:
                # the WIDE widths also fit under the leaf budget by
                # stepping DOWN the set (31 leaves at K=32 runs K=16)
                # so no off-set width ever opens a private trace
                # family; the shipped widths <= 16 keep their historic
                # clamp (grower.py K = min(K, num_leaves-1)) for
                # byte-identity with existing models
                snapped = fit_split_batch(snapped, config.num_leaves)
            if snapped != self._split_batch:
                from ..utils.log import Log
                Log.info(
                    f"split_batch={self._split_batch} snapped to the "
                    f"shipped super-step width {snapped} "
                    f"(trace_buckets=true pins the trace family to K in "
                    f"{SPLIT_BATCH_SET}, fitted under num_leaves="
                    f"{config.num_leaves}; set trace_buckets=false to "
                    "keep an off-set width)")
                self._split_batch = snapped
        # effective strict-overlap flag (grower.py hist_overlap):
        # masked growers only — voting keeps the masked pass (its
        # top-k vote is per histogram call either way), sparse-binned
        # data keeps its own total-reduction order, and the
        # partitioned learner has no slot path.  Threaded through the
        # serial, fused-chunk, data- and feature-parallel builders;
        # the flop ledger accounts the 1-slot mask as the masked pass
        # it is byte-identical to (obs/flops.hist_flops_bytes).
        self._hist_overlap = (bool(getattr(config, "hist_overlap", True))
                              and learner == "masked"
                              and dist != "voting" and not self._sparse)
        # leaf-budget bucketing: every one-program (masked) grower takes
        # a traced budget — serial, data, and (since the ROADMAP item-1
        # remainder closed) the voting/feature growers too; only the
        # host-orchestrated partitioned learner keeps exact shapes
        self._leaf_pad = None
        if self._trace_buckets and learner == "masked":
            lp = bucket_leaves(config.num_leaves)
            # inflation cap: the grower carries a [L, F, B, 3] histogram
            # per leaf slot, so padding a tiny budget to the 64 floor
            # (e.g. num_leaves=4 -> 16x) could blow HBM on wide data;
            # past 4x the trace consolidation isn't worth the state.
            # The common sweep (31/40/63 -> 64) stays well inside.
            if config.num_leaves < lp <= 4 * config.num_leaves:
                self._leaf_pad = lp

        if self._quant is not None:
            # int32 accumulator headroom: every row contributes at most
            # qmax per channel to its bin, and a degenerate (constant or
            # NA-heavy) feature can put EVERY row in one bin — past
            # rows * qmax > 2^31-1 the histogram (and the dp psum over
            # shards, which sums to the same global totals) wraps
            # silently.  Same quant_bits + log2(rows) arithmetic that
            # rejected the 16-bit wire format
            # (docs/Quantized-Training.md).
            n_global = (int(self._global_counts.sum())
                        if self._global_counts is not None
                        else self.num_data)
            if n_global * self._quant.qmax > 2 ** 31 - 1:
                cap = (2 ** 31 - 1) // self._quant.qmax
                hint = "quant_bits=8 (bound ~16.9M rows) or " \
                    if self._quant.bits == 16 else ""
                raise ValueError(
                    f"quant_bits={self._quant.bits} can overflow the "
                    f"int32 histogram accumulator at {n_global} rows: "
                    f"a single bin may collect every row, so rows * "
                    f"qmax ({self._quant.qmax}) must stay under 2^31 "
                    f"(at most {cap} rows).  Use {hint}quant_train="
                    "false.")

        mg_kwargs = None   # set on the masked-learner path (integrity shadow)
        if dist == "data":
            from ..parallel.data_parallel import make_dp_grower
            self.grower = make_dp_grower(
                self._mesh, num_leaves=config.num_leaves,
                num_bins=self.max_bin, params=self.split_params,
                max_depth=config.max_depth, block_rows=self._block_rows,
                efb=self.efb_dev if self._use_efb else None,
                split_batch=self._split_batch,
                hist_overlap=self._hist_overlap,
                mono=self._mono if mono_masked_ok else None,
                mono_penalty=config.monotone_penalty,
                sparse=self._sparse,
                padded_leaves=self._leaf_pad,
                quant=self._quant,
                # owner-shard reduce-scatter (dp_owner_shard=false falls
                # back to the full-psum reduction for A/B comparison)
                owner_shard=config.dp_owner_shard)
        elif dist == "voting":
            from ..parallel.voting_parallel import make_voting_grower
            self.grower = make_voting_grower(
                self._mesh, num_leaves=config.num_leaves,
                num_bins=self.max_bin, params=self.split_params,
                top_k=config.top_k, max_depth=config.max_depth,
                block_rows=self._block_rows,
                padded_leaves=self._leaf_pad, quant=self._quant)
        elif dist == "feature":
            from ..parallel.feature_parallel import make_fp_grower
            self.grower = make_fp_grower(
                self._mesh, num_features=self.num_features + self._feat_pad,
                num_leaves=config.num_leaves, num_bins=self.max_bin,
                params=self.split_params, max_depth=config.max_depth,
                block_rows=self._block_rows,
                split_batch=self._split_batch,
                hist_overlap=self._hist_overlap,
                padded_leaves=self._leaf_pad, quant=self._quant)
        elif hist_reduce is None and learner == "partitioned":
            # single-chip performance learner (grower_partitioned.py):
            # histogram work ∝ smaller child, like the reference
            from ..grower_partitioned import PartitionedGrower
            self.grower = PartitionedGrower(
                num_leaves=config.num_leaves, num_bins=self.max_bin,
                params=self.split_params, max_depth=config.max_depth,
                block_rows=self._block_rows, mono=mono,
                mono_method=config.monotone_constraints_method,
                mono_penalty=config.monotone_penalty,
                interaction_groups=inter,
                bynode_frac=config.feature_fraction_bynode,
                bynode_seed=config.feature_fraction_seed + 1,
                efb=self.efb_dev,
                pool_entries=self._pool_entries(config, ds),
                feature_contri=contri,
                extra_trees=self._extra_trees,
                extra_seed=config.extra_seed,
                quant=self._quant)
        else:
            if has_node_controls:
                raise ValueError(
                    "monotone intermediate/advanced and forced splits "
                    "currently require the partitioned learner "
                    "(tpu_learner=partitioned, single-chip); monotone "
                    "basic, interaction constraints, CEGB and "
                    "feature_fraction_bynode work on the masked learner")
            # a caller-supplied hist_reduce hook keeps its single-arg
            # contract; quantized growers call reduce hooks with the
            # iteration's scales as a second argument (grower.py _hist)
            if hist_reduce is not None and self._quant is not None:
                user_reduce = hist_reduce
                hist_reduce = lambda h, scales=None: user_reduce(h)  # noqa: E731
            # kwargs captured so the integrity layer can build an
            # independently-jitted shadow twin of this exact grower
            mg_kwargs = dict(
                num_leaves=config.num_leaves, num_bins=self.max_bin,
                params=self.split_params, max_depth=config.max_depth,
                block_rows=self._block_rows, hist_reduce=hist_reduce,
                quant=self._quant,
                efb=self.efb_dev if self._use_efb else None,
                gain_scale=contri, extra_trees=self._extra_trees,
                extra_seed=config.extra_seed,
                split_batch=self._split_batch,
                hist_overlap=self._hist_overlap,
                mono=self._mono if mono_masked_ok else None,
                mono_penalty=config.monotone_penalty,
                interaction_groups=inter,
                bynode_frac=config.feature_fraction_bynode,
                bynode_seed=config.feature_fraction_seed + 1,
                cegb=self._cegb_state,
                padded_leaves=self._leaf_pad)
            self.grower = make_grower(**mg_kwargs)

        if config.linear_tree and config.boosting not in ("gbdt", "gbrt"):
            raise ValueError("linear_tree requires boosting=gbdt")

        if self.objective is not None:
            self.objective.init(ds.metadata, self.num_data)

        # scores: [N, K] f32 on device
        init = np.zeros((self.num_data, self.num_class), np.float32)
        if ds.metadata.init_score is not None:
            s = np.asarray(ds.metadata.init_score, np.float32)
            init += s.reshape(self.num_data, -1)
        self.score = jnp.asarray(init)
        self._init_applied = ds.metadata.init_score is not None

        # validation sets: (dataset, device binned, score)
        self.valid_sets: List[Tuple[Dataset, jax.Array, jax.Array]] = []
        # super-epoch traced early-stop vote state, carried ON DEVICE
        # across epochs: (best [E] f32, best_iter [E] i32, has-best [E]
        # bool, stop scalar bool) — see train_superepoch
        self._es_dev = None
        self._se_valid_cache: Dict[int, Tuple[jax.Array, jax.Array]] = {}

        self.models: List[Tree] = []          # host trees, grouped per iter
        self.device_trees: List[_DeviceTree] = []
        self.tree_weights: List[float] = []   # DART/RF reweighting
        self.step_counts: List[int] = []      # grower loop steps per tree
        self._rng_feat = np.random.RandomState(config.feature_fraction_seed)
        self._goss = config.data_sample_strategy == "goss"
        self._last_iter_state: Optional[dict] = None

        # computation-integrity layer (lightgbm_tpu/integrity.py): None
        # unless integrity_check_freq > 0 — the hot paths only test for
        # None, so the default adds zero work and zero syncs
        self._integrity = None
        if config.integrity_check_freq > 0:
            from ..integrity import IntegrityChecker
            if mg_kwargs is not None:
                # masked learner: a second trace of the same logical
                # math — jax.jit over the unjitted grower, deliberately
                # bypassing the shared-grower memo
                from ..grower import make_shadow_grower
                shadow, independent = make_shadow_grower(**mg_kwargs), True
            elif dist in ("data", "voting", "feature"):
                # distributed growers are built per-topology around
                # collectives: the shadow is the SAME program re-run —
                # a full redundant recompute rather than a second
                # trace (manifest records independent_trace=false)
                shadow, independent = self.grower, False
            else:
                raise ValueError(
                    "integrity_check_freq > 0 is unsupported with "
                    "tpu_learner=partitioned: its grower keeps host-side "
                    "pool/RNG state, so a shadow re-execution is not a "
                    "pure recompute.  Use the masked learner")
            self._integrity = IntegrityChecker(config, shadow, independent)

        # telemetry (obs/): None when telemetry=false — the hot paths
        # below only ever test this for None, so the default adds zero
        # host syncs and no per-iteration allocation beyond the branch
        from ..obs import maybe_session
        self._obs = maybe_session(config)
        self._flops = None
        if self._obs is not None:
            ledger = getattr(self.grower, "comm", None)
            if ledger is not None:
                self._obs.attach_comm_sites(ledger)
            # static compute ledger (obs/flops.py) from LOGICAL GLOBAL
            # shapes — identical between tree_learner=data and serial,
            # independent of jit-cache state.  Attached on process 0
            # only: the ledger accounts the global work, so a
            # per-process attach would multiply it by the process
            # count when snapshots aggregate.
            # peaks are process-independent (config override or the
            # device-kind table) — attached everywhere so every
            # process's perf.* join carries the same mfu/bound keys
            from ..obs.attrib import config_peaks
            self._obs.attach_peaks(*config_peaks(config))
            if _jax.process_index() == 0:
                from ..obs.flops import FlopLedger
                n_global = (int(self._global_counts.sum())
                            if self._global_counts is not None
                            else self.num_data)
                if self._sparse:
                    hist_cols, itemsize = self.num_features, 4
                else:
                    hist_cols = int(self.binned_dev.shape[1])
                    itemsize = int(self.binned_dev.dtype.itemsize)
                self._flops = FlopLedger.for_training(
                    n_rows=n_global, n_feat=self.num_features,
                    num_bins=self.max_bin,
                    split_batch=self._split_batch,
                    hist_cols=hist_cols,
                    hist_bins=(int(self.efb_dev.group_bins)
                               if self.efb_dev is not None
                               else self.max_bin),
                    binned_itemsize=itemsize,
                    num_class=self.num_class,
                    # per-dtype HBM accounting: the quantized passes
                    # read int8/int16 accumulands, and the quantize/
                    # dequant sites join the perf.* roofline so
                    # perf.hist.* shows the memory bound moving
                    vals_itemsize=(self._quant.itemsize
                                   if self._quant is not None else 4),
                    quant=self._quant is not None)
                self._obs.attach_flop_sites(self._flops)
        # flight recorder (obs/blackbox.py): None unless
        # telemetry_blackbox=true — zero ring allocation, no file
        from ..obs.blackbox import maybe_recorder
        self._bbox = maybe_recorder(
            config,
            default_path=((config.output_model + ".blackbox.jsonl")
                          if getattr(config, "output_model", "")
                          else "lgbtpu_blackbox.jsonl"),
            meta={"surface": "train", "objective": config.objective,
                  "num_leaves": config.num_leaves,
                  "tree_learner": config.tree_learner,
                  "learner": self._learner_kind,
                  "split_batch": self._split_batch})

    def _fit_linear_leaves(self, arrays: TreeArrays, ht: Tree, g, h, w,
                           shrinkage: float, bias: float) -> None:
        """Per-leaf linear models (LinearTreeLearner::CalculateLinear,
        linear_tree_learner.cpp): Newton-step ridge regression of the
        gradients on the leaf's path features; coefficients shrunk by the
        learning rate; constant = fitted intercept (+ iteration-0 bias)."""
        nl = int(arrays.num_leaves)
        raw = self.train_set.raw_data
        if nl <= 1 or raw is None:
            return
        lc = np.asarray(arrays.left_child)[:nl - 1]
        rc = np.asarray(arrays.right_child)[:nl - 1]
        sf = np.asarray(arrays.split_feature)[:nl - 1]
        icn = np.asarray(arrays.is_cat_node)[:nl - 1]
        lor = np.asarray(arrays.leaf_of_row)
        used = self.train_set.used_features

        paths: Dict[int, List[int]] = {}
        stack = [(0, [])]
        while stack:
            node, feats = stack.pop()
            if node < 0:
                paths[~node] = feats
                continue
            nf = feats if icn[node] else feats + [int(used[sf[node]])]
            stack.append((int(lc[node]), nf))
            stack.append((int(rc[node]), nf))

        g_np = np.asarray(g, np.float64)
        h_np = np.asarray(h, np.float64)
        w_np = np.asarray(w, np.float64)
        lam = self.config.linear_lambda
        ht.is_linear = True
        for leaf in range(nl):
            feats = list(dict.fromkeys(paths.get(leaf, [])))
            rows = np.nonzero((lor == leaf) & (w_np > 0))[0]
            ht.leaf_const[leaf] = ht.leaf_value[leaf]
            ht.leaf_features[leaf], ht.leaf_coeff[leaf] = [], []
            if not feats or len(rows) < len(feats) + 2:
                continue
            X = raw[np.ix_(rows, feats)].astype(np.float64)
            ok = ~np.isnan(X).any(axis=1)
            if ok.sum() < len(feats) + 2:
                continue
            # bagging/GOSS amplification weights scale g and h exactly as
            # in the histogram path (goss.hpp weight amplification)
            ww = w_np[rows][ok]
            X, gg, hh = X[ok], g_np[rows][ok] * ww, h_np[rows][ok] * ww
            Xt = np.column_stack([X, np.ones(len(X))])
            A = Xt.T @ (hh[:, None] * Xt)
            A[np.arange(len(feats)), np.arange(len(feats))] += lam
            A[np.arange(len(A)), np.arange(len(A))] += 1e-10
            b = -Xt.T @ gg
            try:
                beta = np.linalg.solve(A, b)
            except np.linalg.LinAlgError:
                continue
            if not np.isfinite(beta).all():
                continue
            ht.leaf_features[leaf] = feats
            ht.leaf_coeff[leaf] = (beta[:-1] * shrinkage).tolist()
            ht.leaf_const[leaf] = float(beta[-1] * shrinkage) + bias

    @staticmethod
    def _linear_outputs(ht: Tree, leaves: np.ndarray,
                        raw: np.ndarray) -> np.ndarray:
        """Per-row outputs of a linear tree given row->leaf assignment."""
        return ht.linear_leaf_outputs(leaves, raw)

    @staticmethod
    def _make_cegb(config: Config, ds: Dataset):
        """CEGB penalties over used-feature slots
        (cost_effective_gradient_boosting.hpp)."""
        coupled_in = config.cegb_penalty_feature_coupled
        lazy_in = config.cegb_penalty_feature_lazy
        if config.cegb_penalty_split <= 0 and not coupled_in and not lazy_in:
            return None
        from ..grower_partitioned import CEGBState
        nf = len(ds.used_features)

        def slot_array(vals):
            if not vals:
                return None
            full = np.zeros(ds.num_total_features, np.float32)
            full[:len(vals)] = np.asarray(vals, np.float32)
            return full[np.asarray(ds.used_features)]

        return CEGBState(
            tradeoff=config.cegb_tradeoff,
            penalty_split=config.cegb_penalty_split,
            coupled=slot_array(coupled_in),
            lazy=slot_array(lazy_in),
            used=np.zeros(nf, bool))

    @staticmethod
    def _load_forced(config: Config, ds: Dataset):
        """Parse forcedsplits_filename JSON into slot/bin space
        (forced splits file, serial_tree_learner.cpp:455)."""
        if not config.forcedsplits_filename:
            return None
        import json
        with open(config.forcedsplits_filename) as f:
            spec = json.load(f)
        slot_of_orig = {f: i for i, f in enumerate(ds.used_features)}

        def conv(node):
            if not isinstance(node, dict) or "feature" not in node:
                return None
            orig = int(node["feature"])
            if orig not in slot_of_orig:
                return None
            mapper = ds.bin_mappers[orig]
            thr_bin = int(mapper.value_to_bin(
                np.asarray([float(node["threshold"])]))[0])
            out = {"feature": slot_of_orig[orig], "threshold_bin": thr_bin}
            for side in ("left", "right"):
                c = conv(node.get(side))
                if c is not None:
                    out[side] = c
            return out

        return conv(spec)

    def _pool_entries(self, config: Config, ds: Dataset) -> int:
        """histogram_pool_size (MB, config.h) -> max cached per-leaf
        histograms for the HistogramPool analog (feature_histogram.hpp:1095;
        sizing logic mirrors serial_tree_learner.cpp:33-46)."""
        if config.histogram_pool_size <= 0:
            return 0
        cols = self.efb_dev.group_bins if self.efb_dev is not None \
            else self.max_bin
        nf = (int(self.efb_dev.group_host.max()) + 1
              if self.efb_dev is not None else self.num_features)
        # grower histograms are [F, B, 3] f32; under EFB the bin axis is the
        # max group-bin count
        bytes_per_leaf = max(nf, 1) * max(cols, 2) * 3 * 4
        return max(2, int(config.histogram_pool_size * 1024 * 1024
                          / bytes_per_leaf))

    @staticmethod
    def _resolve_mesh(config: Config, axis: str):
        """Device mesh for tree_learner=data|feature|voting
        (tree_learner.cpp:16-64 factory dispatch; the mesh replaces the
        reference's machine list, SURVEY.md §2.5).  Size precedence:
        ``mesh_shape`` > ``num_machines`` > all visible devices.  Returns
        None (serial fallback, with a warning) on a single device —
        the reference's num_machines=1 degenerate case.

        The device claim itself (jax backend init — the call that wedged
        for ~10 h in round 5) runs under the resilience layer: watchdog
        stack dumps at ``dist_init_timeout_s``, ``dist_init_retries``
        jittered-backoff retries for classified-transient errors, and an
        optional graceful degradation to the serial learner
        (``dist_fallback_serial``) when bring-up exhausts its retries."""
        import jax
        from ..parallel import make_mesh
        from ..utils import faultinject
        from ..utils.log import Log
        from ..utils.resilience import (RetryPolicy, Watchdog,
                                        WatchdogTimeout, retry_call)

        def _claim():
            faultinject.check("device_claim")
            faultinject.check("claim_wedge")
            return jax.devices()

        timeout = config.dist_init_timeout_s
        elastic = bool(getattr(config, "elastic_enable", False))
        policy = RetryPolicy.for_bringup(config.dist_init_retries, timeout)
        try:
            if elastic:
                # cancel-and-raise: a WEDGED claim (the round-5 / bench
                # r03-r05 failure) is abandoned at its deadline slice
                # and becomes a retryable WatchdogTimeout.  The
                # per-attempt slice is timeout/attempts — a wedge
                # abandoned at the FULL timeout would exhaust
                # retry_call's deadline_s (== timeout) on the first
                # attempt and dist_init_retries would never fire.
                # Exhaustion surfaces as a classified ElasticFailure
                # for the recovery ladder
                per_attempt = timeout / max(1, policy.max_attempts)
                devs = retry_call(
                    lambda: Watchdog(per_attempt, label="device claim",
                                     on_timeout="raise").run(_claim),
                    policy=policy, label="device claim")
            else:
                with Watchdog(timeout, label="device claim"):
                    devs = retry_call(_claim, policy=policy,
                                      label="device claim")
        except Exception as e:
            fail = None
            if elastic and isinstance(e, WatchdogTimeout):
                # classify + record (elastic.* metrics, JSONL event,
                # blackbox dump) BEFORE the fallback decision — a wedge
                # must never be silent, even when dist_fallback_serial
                # then degrades it to the serial learner
                from ..parallel.elastic import ElasticFailure, _on_failure
                fail = ElasticFailure("claim_wedge", str(e))
                _on_failure(fail, site="device_claim")
            if config.dist_fallback_serial:
                Log.warning(
                    f"multi-chip bring-up failed after "
                    f"{policy.max_attempts} attempt(s) ({e}); falling back "
                    "to the serial learner (dist_fallback_serial=true)")
                return None
            if fail is not None:
                raise fail from e
            raise
        if elastic:
            # suspect-device quarantine (integrity.py sticky SDC): a
            # quarantined chip is excluded from the claimed list, so
            # the ladder's "sdc" rung runs mesh-minus-suspects.  Never
            # filter down to nothing — with every device suspect the
            # serial rung re-trusts the least-recently-accused
            from ..parallel import elastic as elastic_mod
            sus = elastic_mod.suspected_devices()
            if sus:
                keep = [d for d in devs
                        if getattr(d, "id", None) not in sus]
                if keep and len(keep) < len(devs):
                    Log.warning(
                        f"excluding {len(devs) - len(keep)} quarantined "
                        f"suspect device(s) {sorted(sus)} from the mesh")
                    devs = keep
        if config.mesh_shape and len(config.mesh_shape) > 1:
            # the tree learners shard exactly one axis (rows OR features);
            # a multi-dim mesh has no meaning here, so reject it loudly
            # rather than silently flattening
            raise ValueError(
                f"mesh_shape={config.mesh_shape}: tree_learner="
                f"{config.tree_learner} shards a single axis; pass a "
                "one-element mesh_shape (e.g. [8])")
        if config.mesh_shape:
            n = int(np.prod(config.mesh_shape))
        elif config.num_machines > 1:
            n = config.num_machines
        else:
            n = len(devs)
        if n > len(devs):
            raise ValueError(
                f"tree_learner={config.tree_learner} needs {n} devices "
                f"(mesh_shape/num_machines), only {len(devs)} visible")
        if n <= 1:
            Log.warning(
                f"tree_learner={config.tree_learner} requested but only one "
                "device is visible; training serially")
            return None
        return make_mesh((n,), (axis,), devs)

    def _eget(self, x, site: str = "fetch"):
        """The iteration's host fetch.  Under ``elastic_enable`` it runs
        inside the collective deadline (``parallel/elastic.guarded_get``:
        a wedged collective materializes at this blocking fetch, gets
        stack-dumped, abandoned, and classified as an ElasticFailure
        instead of hanging the run); otherwise a plain device fetch."""
        if self._elastic is not None and self._elastic_timeout > 0:
            return self._elastic.guarded_get(x, self._elastic_timeout,
                                             site=site)
        return jax.device_get(x)

    def integrity_boundary_check(self) -> None:
        """Shadow-verify the newest committed tree right before a
        snapshot is written (engine.py calls this ahead of
        ``write_snapshot``), so the manifest's ``integrity`` stamp means
        'last check clean AT this snapshot'.  No-op when the integrity
        layer is off or the newest tree already passed a check.  Raises
        ``IntegrityFailure`` on a sticky boundary mismatch."""
        if self._integrity is not None:
            self._integrity.boundary_check(self)

    def integrity_manifest(self, iteration: int):
        """The snapshot manifest's ``integrity`` stamp dict, or None
        when the integrity layer is off (manifests stay byte-identical
        to pre-integrity ones at ``integrity_check_freq=0``)."""
        if self._integrity is None:
            return None
        return self._integrity.manifest(iteration)

    def snapshot_state(self):
        """``(score, fingerprint_override)`` for snapshot.write_snapshot.

        Default: this process's score and no override.  Under elastic
        MULTI-PROCESS row-sharded training the snapshot must instead
        carry GLOBAL state — the all-process score in global row order
        and the full-data fingerprint — so a shrunk (even
        single-process) relaunch over the full data can locate and
        resume it (docs/Fault-Tolerance.md "Elastic training")."""
        if not (self._elastic is not None and self._pc > 1
                and self._dist in ("data", "voting")
                and self._global_counts is not None):
            return np.asarray(self.score, np.float32), None
        from jax.experimental import multihost_utils

        def _allgather(arr, site):
            # the allgather is itself a collective: a peer that died
            # between the iteration's liveness check and this snapshot
            # write would wedge it forever — bound it by the same
            # elastic deadline as the training fetch so a snapshot
            # boundary can never reopen the silent-hang class
            return np.asarray(self._elastic.guarded_call(
                lambda: multihost_utils.process_allgather(arr),
                self._elastic_timeout, site))

        counts = self._global_counts
        tmax = int(counts.max())
        sc = np.asarray(self.score, np.float32)
        if sc.shape[0] < tmax:
            sc = np.concatenate(
                [sc, np.zeros((tmax - sc.shape[0], sc.shape[1]),
                              np.float32)])
        allsc = _allgather(sc, "snapshot_allgather")
        gscore = np.concatenate(
            [allsc[p, :int(counts[p])] for p in range(len(counts))])
        if self._global_fp is None:
            lab = np.asarray(self.train_set.metadata.label, np.float32)
            w = self.train_set.metadata.weight
            pad = tmax - len(lab)
            cols = [np.pad(lab, (0, pad))]
            if w is not None:
                cols.append(np.pad(np.asarray(w, np.float32), (0, pad)))
            g = _allgather(np.stack(cols), "snapshot_fp_allgather")
            glab = np.concatenate(
                [g[p, 0, :int(counts[p])] for p in range(len(counts))])
            gw = None
            if w is not None:
                gw = np.concatenate(
                    [g[p, 1, :int(counts[p])] for p in range(len(counts))])
            from ..dataset import fingerprint_arrays
            self._global_fp = fingerprint_arrays(glab, gw)
        return gscore, self._global_fp

    def _prep_vals(self, vals: jax.Array) -> jax.Array:
        """Pad + row-shard the per-row (grad, hess, weight) stack for the
        row-sharded learners; identity otherwise.  Padded rows carry zero
        weight so they never contribute to histograms."""
        if self._dist not in ("data", "voting"):
            return vals
        if self._row_pad:
            vals = jnp.concatenate(
                [vals, jnp.zeros((self._row_pad, vals.shape[1]), vals.dtype)])
        from ..parallel.data_parallel import shard_rows
        return shard_rows(self._mesh, vals, self._dist_axis)

    def _boost_from_score(self, class_id: int) -> float:
        """BoostFromScore with reference multi-machine semantics: the
        initial score comes from the GLOBAL label/weight statistics
        (binary_objective.hpp BoostFromScore runs after a network
        allreduce of suml/sumw), not this process's shard."""
        if self._pc <= 1 or self._dist is None or self._dist == "feature" \
                or getattr(self.objective, "is_ranking", False):
            # feature-parallel replicates the data: every process already
            # holds the GLOBAL metadata, and gathering would only
            # duplicate each row process_count times.  Ranking objectives
            # boost from 0 regardless of data (rank_objective.hpp), so
            # the gathered metadata — which would also need global query
            # boundaries — is never consulted.
            return self.objective.boost_from_score(class_id)
        from jax.experimental import multihost_utils
        obj = self.objective
        lab = np.asarray(self.train_set.metadata.label, np.float64)
        w = self.train_set.metadata.weight
        w = np.ones_like(lab) if w is None else np.asarray(w, np.float64)
        pad = self.num_data + self._row_pad - len(lab)
        stacked = np.stack([np.pad(lab, (0, pad)), np.pad(w, (0, pad))])
        g = np.asarray(multihost_utils.process_allgather(stacked))
        glab = g[:, 0].reshape(-1)
        gw = g[:, 1].reshape(-1)
        keep = gw > 0.0            # padded rows carry zero weight
        # a fresh instance init'd on the GLOBAL metadata: objectives
        # derive their boost statistics (label counts, means) in init()
        from ..dataset import Metadata
        md = Metadata(int(keep.sum()))
        md.label = glab[keep].astype(np.float32)
        if self.train_set.metadata.weight is not None:
            md.weight = gw[keep].astype(np.float32)
        gobj = type(obj)(self.config)
        gobj.init(md, md.num_data)
        return gobj.boost_from_score(class_id)

    def _localize_rows(self, global_arr: jax.Array) -> jax.Array:
        """This process's rows of a row-sharded global array, pad dropped
        (multi-process only; shards ordered by global row offset)."""
        shards = sorted(global_arr.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        parts = [np.asarray(s.data) for s in shards]
        local = parts[0] if len(parts) == 1 else np.concatenate(parts)
        return jnp.asarray(local[:self.num_data])

    def _prep_fmask(self, fmask: jax.Array) -> jax.Array:
        if self._feat_pad:
            return jnp.concatenate([fmask, jnp.zeros(self._feat_pad, bool)])
        return fmask

    @staticmethod
    def _interaction_allow(config: Config, ds: Dataset):
        """Parse interaction_constraints ("[0,1],[2,3]" over original feature
        indices) into a [G, F] constraint-GROUP matrix over used-feature
        slots (ColSampler, col_sampler.hpp:91-111 GetByNode): a leaf's
        allowed features are its branch set plus the union of the groups
        that contain the WHOLE branch set — overlapping groups compose by
        subset containment, not by progressive intersection, and features
        in no group are unusable (an empty branch allows only the union
        of all groups)."""
        spec = config.interaction_constraints
        if not spec:
            return None
        groups: List[List[int]] = []
        for part in spec.replace(" ", "").strip("[]").split("],["):
            if part:
                groups.append([int(t) for t in part.split(",") if t != ""])
        if not groups:
            return None
        slot_of_orig = {f: i for i, f in enumerate(ds.used_features)}
        nf = len(ds.used_features)
        gm = np.zeros((len(groups), nf), bool)
        for gi, grp in enumerate(groups):
            for member in grp:
                if member in slot_of_orig:
                    gm[gi, slot_of_orig[member]] = True
        return gm

    # -- plumbing ----------------------------------------------------------
    def add_valid_set(self, valid: Dataset) -> None:
        valid.construct(self.config)
        nv = valid.num_data
        pad = 0
        if valid.binned_sparse is not None:
            binned = valid.binned_sparse.to_device()
        else:
            vb = valid.binned if self._use_efb else valid.feature_binned()
            if self._trace_buckets and nv <= (1 << 20):
                # row-bucket the valid set (utils/shapes.py pow2 policy)
                # so the per-iteration score-update traversal — and
                # therefore early stopping over differently-sized valid
                # sets — traces once per BUCKET, not once per size.
                # Padded rows are bin-0 and their scores are sliced off
                # in valid_score(); metrics are byte-identical.  Above
                # ~1M rows the up-to-2x recurring pad work outweighs the
                # one-time retrace, so huge valid sets keep exact shapes.
                from ..utils.shapes import bucket_rows
                pad = bucket_rows(nv, min_bucket=256) - nv
                if pad:
                    vb = np.concatenate(
                        [vb, np.zeros((pad, vb.shape[1]), vb.dtype)])
            binned = jnp.asarray(vb)
        init = np.zeros((nv + pad, self.num_class), np.float32)
        if valid.metadata.init_score is not None:
            init[:nv] += np.asarray(valid.metadata.init_score, np.float32) \
                .reshape(nv, -1)
        # models without device copies (reset_training_data installed an
        # existing ensemble): fold their contribution in by host
        # prediction on the raw values; device_trees always corresponds
        # to the TAIL of models
        n_host_only = len(self.models) - len(self.device_trees)
        if n_host_only > 0:
            if valid.raw_data is None:
                raise ValueError(
                    "validation after reset_training_data needs the valid "
                    "set's raw values (free_raw_data=False)")
            raw = np.asarray(valid.raw_data, np.float64)
            for ti in range(n_host_only):
                k = ti % self.num_class
                init[:nv, k] += (self.tree_weights[ti]
                                 * self.models[ti].predict(raw))
        score = jnp.asarray(init)
        # replay existing device trees (continued training)
        for ti, dt in enumerate(self.device_trees):
            mi = n_host_only + ti
            k = mi % self.num_class
            ht = self.models[mi] if mi < len(self.models) else None
            if ht is not None and ht.is_linear:
                leaves = np.asarray(_tree_leaves(
                    binned, dt, self.na_bin_dev, self.efb_maps))[:nv]
                delta = self._linear_outputs(ht, leaves, valid.raw_data)
                if pad:
                    delta = np.pad(np.asarray(delta, np.float32), (0, pad))
                score = score.at[:, k].add(
                    self.tree_weights[mi] * jnp.asarray(delta, jnp.float32))
            else:
                score = score.at[:, k].set(_apply_tree(
                    score[:, k], binned, dt, self.na_bin_dev,
                    self.tree_weights[mi], self.efb_maps))
        self.valid_sets.append((valid, binned, score))

    # -- sampling (gbdt.cpp:230 Bagging + goss.hpp) ------------------------
    @property
    def _bagging_active(self) -> bool:
        cfg = self.config
        return cfg.bagging_freq > 0 and (
            cfg.bagging_fraction < 1.0 or cfg.pos_bagging_fraction < 1.0
            or cfg.neg_bagging_fraction < 1.0)

    def _bagging_w(self, it, seed=None) -> jax.Array:
        """In-graph bagging mask (gbdt.cpp:230-264 Bagging): the draw is
        keyed by the iteration's refresh epoch ``(it // freq) * freq`` so
        the mask is identical for ``bagging_freq`` consecutive iterations
        and identical between the per-iteration and fused-chunk paths —
        ``it`` may be a traced scan index (the GOSS pattern).  Redrawing
        per iteration instead of caching costs one [N] uniform + compare,
        noise next to a histogram pass.  ``seed`` (optional, possibly a
        traced int32) overrides ``cfg.bagging_seed`` — the fleet trainer's
        per-member stream; PRNGKey on a traced seed stays in-graph."""
        cfg = self.config
        n = self.num_data
        epoch = (it // cfg.bagging_freq) * cfg.bagging_freq
        key = jax.random.fold_in(jax.random.PRNGKey(
            cfg.bagging_seed if seed is None else seed), epoch)
        if self._pc > 1 and self._dist != "feature":
            # per-host independent draws (the reference seeds its bagging
            # RNG per rank the same way, gbdt.cpp bagging_rand_).
            # feature-parallel replicates the rows, so every process MUST
            # draw the SAME mask or the pod's split statistics diverge.
            key = jax.random.fold_in(key, jax.process_index())
        u = jax.random.uniform(key, (n,))
        pos_f, neg_f = cfg.pos_bagging_fraction, cfg.neg_bagging_fraction
        if (pos_f < 1.0 or neg_f < 1.0) and self.objective is not None \
                and self.objective.name == "binary":
            lbl = jnp.asarray(
                np.asarray(self.train_set.metadata.label) > 0)
            mask = jnp.where(lbl, u < pos_f, u < neg_f)
        else:
            mask = u < cfg.bagging_fraction
        return mask.astype(jnp.float32)

    def _goss_vals(self, g: jax.Array, h: jax.Array,
                   it: Optional[jax.Array] = None,
                   seed=None) -> jax.Array:
        """GOSS (goss.hpp:20-188): keep top_rate by |grad|, sample
        other_rate of the rest, amplify their weight.  ``it`` may be a
        traced iteration index (fused-chunk path); defaults to the host
        counter so both paths draw identical per-iteration keys.
        ``seed`` (optional, possibly traced) overrides
        ``cfg.bagging_seed`` — the fleet trainer's per-member stream."""
        cfg = self.config
        multi = self._pc > 1 and self._global_counts is not None
        if multi:
            # GLOBAL semantics under multi-process data-parallel
            # (goss.hpp samples over the full data): the threshold is the
            # global top_k-th |g|h and the Bernoulli draw is keyed by the
            # row's GLOBAL index, so any process topology trains the same
            # trees as a single process over the concatenated rows.
            pidx = jax.process_index()
            n = int(self._global_counts.sum())
            offset = int(self._global_counts[:pidx].sum())
        else:
            n = self.num_data
            offset = 0
        top_k = max(1, int(n * cfg.top_rate))
        other_k = max(1, int(n * cfg.other_rate))
        amp = (1.0 - cfg.top_rate) / cfg.other_rate
        absg = jnp.abs(g) * h
        if multi:
            # the global top-k all lie inside the per-process local top-k:
            # allgather each process's top min(k, local_n) candidates and
            # take the k-th of the merged set
            from jax.experimental import multihost_utils
            cand = int(min(top_k, self.num_data))
            local_top = np.full(top_k, -np.inf, np.float32)
            local_top[:cand] = np.asarray(
                jax.lax.top_k(absg, cand)[0], np.float32)
            allc = np.asarray(multihost_utils.process_allgather(local_top))
            thresh = jnp.float32(np.partition(allc.ravel(), -top_k)[-top_k])
        else:
            thresh = -jnp.sort(-absg)[top_k - 1]
        is_top = absg >= thresh
        if it is None:
            it = self.iter_ + self._iter_rng_offset
        key = jax.random.PRNGKey(
            (cfg.bagging_seed if seed is None else seed) + it)
        if self._pc > 1 and not multi and self._dist != "feature":
            # multi-process WITHOUT the mesh data-parallel bookkeeping
            # (caller-supplied hist_reduce hook): keep per-rank independent
            # draws, matching _bagging_mask's fold-in.  feature-parallel
            # replicates the rows — identical draws on every process, so
            # the single-process sampling IS already global
            key = jax.random.fold_in(key, jax.process_index())
        u = jax.random.uniform(key, (n,))[offset:offset + self.num_data]
        p_other = other_k / jnp.maximum(n - top_k, 1)
        is_other = (~is_top) & (u < p_other)
        w = jnp.where(is_top, 1.0, jnp.where(is_other, amp, 0.0))
        return w.astype(jnp.float32)

    def _feature_mask(self) -> np.ndarray:
        frac = self.config.feature_fraction
        f = self.num_features
        if frac >= 1.0:
            return np.ones(f, bool)
        k = max(1, int(round(f * frac)))
        idx = self._rng_feat.choice(f, size=k, replace=False)
        mask = np.zeros(f, bool)
        mask[idx] = True
        return mask

    # -- training ----------------------------------------------------------
    _bias_in_every_tree = False   # RF overrides: init bias folded in each tree

    def _score_for_gradients(self) -> jax.Array:
        return self.score

    def set_resume_state(self, start_iteration: int) -> None:
        """Align all iteration-keyed state with a straight run that
        already trained ``start_iteration`` iterations (snapshot
        auto-resume, engine.py): iteration-indexed RNG keys (bagging
        epochs, GOSS, extra_trees/bynode, finite-check cadence) shift by
        the offset, and the stateful feature-fraction host RNG is
        fast-forwarded by redrawing the consumed masks — so crash+resume
        trains byte-identical trees to never-crashing."""
        self._iter_rng_offset = int(start_iteration)
        if self.config.feature_fraction < 1.0:
            for _ in range(int(start_iteration)):
                self._feature_mask()

    # -- fused multi-iteration path (the tunnel-latency killer) ------------
    def _fusable_config(self) -> bool:
        """Whether this model/objective/sampling combination has fused-path
        semantics (independent of whether fusion is enabled) — also gates
        the f32 leaf-shrinkage in train_one_iter so toggling ``fused_chunk``
        never changes the trained model."""
        cfg = self.config
        return (type(self) is GBDTModel
                and self.objective is not None
                and not self.objective.need_renew_tree_output
                and not self.objective.host_state_per_iter
                and self.num_class == 1
                and not cfg.linear_tree
                and self._learner_kind == "masked"
                and self._dist is None
                and not self._custom_hist_reduce
                and self._forced_spec is None)

    def supports_fused(self) -> bool:
        """True when whole iterations can run fused on device via
        ``lax.scan``: pure-JAX gradients -> grow -> leaf-gather score
        update, with ONE host round trip per chunk instead of ~5 per
        iteration.  PROFILE.md measured ~67 ms per blocking call on the
        tunneled chip, so the per-iteration path pays ~335 ms/iter of pure
        latency; the reference's cuda_exp learner syncs once per TREE
        (cuda_single_gpu_tree_learner.cpp:108-232) — this syncs once per
        CHUNK of trees.

        Active fault injection (utils/faultinject.py) forces the
        per-iteration path: host-side injection sites cannot fire inside
        a fused device program.  Path choice only — numerics are still
        governed by ``_fusable_config``, so injected and clean runs train
        identical models.  The integrity layer likewise forces the
        per-iteration path: its shadow compares and transient re-runs
        are host-driven."""
        return (self.config.fused_chunk > 1 and self._fusable_config()
                and not self._faults_active()
                and self._integrity is None)

    @staticmethod
    def _faults_active() -> bool:
        from ..utils import faultinject
        return faultinject.enabled()

    def fused_reasons(self) -> List[str]:
        """Every reason ``supports_fused()`` is False, as specific
        human-readable blockers — empty when the fused path is
        eligible.  The ``reasons()`` companion of ``supports_fused()``:
        consumed by the ``train_chunk`` errors (which must name the
        exact objective/sampling/config condition that failed, not just
        point back at the predicate) and recorded as provenance by the
        benches (tools/bench_fused.py, bench.py extras)."""
        cfg = self.config
        reasons: List[str] = []
        if type(self) is not GBDTModel:
            reasons.append(
                f"boosting={cfg.boosting}: DART/RF drive the iteration "
                "loop host-side (tree weights / bias folding)")
        if self.objective is None:
            reasons.append(
                "custom objective (fobj): gradients arrive from the host "
                "every iteration")
        else:
            if self.objective.need_renew_tree_output:
                reasons.append(
                    f"objective={self.objective.name} renews leaf outputs "
                    "host-side (RenewTreeOutput)")
            if self.objective.host_state_per_iter:
                reasons.append(
                    f"objective={self.objective.name} mutates host state "
                    "every iteration")
        if self.num_class != 1:
            reasons.append(
                f"num_class={self.num_class}: multiclass grows one tree "
                "per class per iteration through the host loop")
        if cfg.linear_tree:
            reasons.append("linear_tree fits per-leaf linear models "
                           "host-side")
        if self._learner_kind != "masked":
            reasons.append(
                f"tpu_learner={self._learner_kind}: only the one-program "
                "masked grower runs inside a fused scan")
        if self._dist is not None:
            reasons.append(
                f"tree_learner={self._dist}: distributed growers "
                "re-materialize tree arrays per iteration")
        if self._custom_hist_reduce:
            reasons.append("caller-supplied hist_reduce hook")
        if self._forced_spec is not None:
            reasons.append("forced_splits need host node bookkeeping")
        if cfg.fused_chunk <= 1:
            reasons.append(f"fused_chunk={cfg.fused_chunk} (set > 1 to "
                           "enable fusion)")
        if self._faults_active():
            reasons.append(
                "fault injection active: host-side injection sites "
                "cannot fire inside a fused device program")
        if self._integrity is not None:
            reasons.append(
                "integrity_check_freq > 0: the computation-integrity "
                "layer's shadow compares and transient re-runs are "
                "host-driven (docs/Fault-Tolerance.md layer 7)")
        return reasons

    def _fused_chunk_fn(self):
        fn = self._fused_cache.get("chunk")
        if fn is None:
            import functools
            cfg = self.config
            grow = make_grower(
                num_leaves=cfg.num_leaves, num_bins=self.max_bin,
                params=self.split_params, max_depth=cfg.max_depth,
                block_rows=self._block_rows,
                efb=self.efb_dev if self._use_efb else None,
                gain_scale=self._feature_contri,
                extra_trees=self._extra_trees, extra_seed=cfg.extra_seed,
                split_batch=self._split_batch,
                hist_overlap=self._hist_overlap,
                mono=self._mono if self._learner_kind == "masked" else None,
                mono_penalty=cfg.monotone_penalty,
                interaction_groups=self._inter,
                bynode_frac=cfg.feature_fraction_bynode,
                bynode_seed=cfg.feature_fraction_seed + 1,
                cegb=self._cegb_state,
                padded_leaves=self._leaf_pad,
                quant=self._quant,
                jit=False)
            obj = self.objective
            lr = jnp.float32(self.learning_rate)
            use_goss = self._goss
            use_bag = self._bagging_active and not use_goss
            ic = self._ic_grow
            fin_freq = cfg.finite_check_freq
            fin_policy = cfg.finite_check_policy

            use_cegb = self._cegb_state is not None
            nf = self.num_features

            leaf_padded = self._leaf_pad is not None

            def one_iter(carry, xs):
                score, dead, cuse, ml = carry
                fmask, it = xs
                g, h = obj.get_gradients(score[:, 0])
                if fin_freq > 0 and fin_policy == "clamp":
                    # clamp is sync-free, so it applies every iteration
                    g = jnp.nan_to_num(g, nan=0.0, posinf=_FINITE_CLAMP,
                                       neginf=-_FINITE_CLAMP)
                    h = jnp.nan_to_num(h, nan=0.0, posinf=_FINITE_CLAMP,
                                       neginf=0.0)
                if use_goss:
                    w = self._goss_vals(g, h, it)
                elif use_bag:
                    w = self._bagging_w(it)
                else:
                    w = jnp.ones_like(g)
                vals = jnp.stack([g * w, h * w, w], axis=1)
                kw = {"is_cat": ic} if ic is not None else {}
                if self._extra_trees or self._bynode_masked \
                        or self._quant is not None:
                    # quant: the scan's iteration index keys the
                    # stochastic-rounding stream, so fused and per-iter
                    # paths quantize identically
                    kw["rng_iter"] = it
                if use_cegb:
                    kw["cegb_used"] = cuse
                if leaf_padded:
                    # the actual budget is a chunk ARGUMENT (not a baked
                    # constant) so the fused-chunk HLO is identical
                    # across a num_leaves bucket — in-process the chunk
                    # still traces per booster, but the persistent cache
                    # recognizes the compile
                    kw["max_leaves"] = ml
                arrays = grow(self.binned_dev, vals, fmask,
                              self._nb_grow, self._na_grow, **kw)
                if use_cegb:
                    # fold this tree's split features into the CEGB
                    # cross-tree used set for the next scan iteration
                    node_on = (jnp.arange(arrays.split_feature.shape[0])
                               < arrays.num_leaves - 1)
                    marks = jnp.zeros(nf, jnp.int32) \
                        .at[arrays.split_feature].add(
                            node_on.astype(jnp.int32))
                    cuse = cuse | (marks > 0)
                if fin_freq > 0 and fin_policy == "clamp":
                    # clamp BEFORE shrinkage, exactly where the per-iter
                    # path clamps its host leaf_values — an inf leaf must
                    # become ±bound*lr on both paths
                    lv = jnp.nan_to_num(
                        arrays.leaf_value, nan=0.0, posinf=_FINITE_CLAMP,
                        neginf=-_FINITE_CLAMP) * lr
                else:
                    lv = arrays.leaf_value * lr
                # finite guard (fused form): ONE fused isfinite reduction
                # over grad/hess and the new tree's leaf outputs at check
                # iterations; the per-iteration flag ships with the tree
                # records, so the whole chunk still costs a single host
                # sync (the policy engages host-side in train_chunk)
                if fin_freq > 0 and fin_policy != "clamp":
                    check_now = ((it + 1) % fin_freq) == 0
                    fin = (jnp.isfinite(g).all() & jnp.isfinite(h).all()
                           & jnp.isfinite(lv).all())
                    bad = check_now & ~fin
                else:
                    bad = jnp.bool_(False)
                # per-iteration semantics stop training at the FIRST
                # no-split tree (gbdt.cpp "no more leaves..."); once dead,
                # later scan iterations must contribute nothing, even if a
                # different feature mask could have split (the host loop
                # discards their tree records)
                ok = jnp.where(dead | bad, 0.0,
                               (arrays.num_leaves > 1).astype(jnp.float32))
                if fin_freq > 0 and fin_policy == "raise":
                    # halt at the first tripped check: later iterations
                    # contribute nothing, so the host can raise at the
                    # flagged iteration with a consistent score/model
                    dead = dead | (arrays.num_leaves <= 1) | bad
                else:
                    # skip_iter: the flagged iteration contributes a zero
                    # stump; a NaN-induced natural stump must NOT end
                    # training
                    dead = dead | ((arrays.num_leaves <= 1) & ~bad)
                delta = jnp.where(ok > 0.0,
                                  jnp.take(lv, arrays.leaf_of_row), 0.0)
                from ..obs.flops import (note_traced,
                                         score_update_flops_bytes)
                note_traced("score",
                            *score_update_flops_bytes(score.shape[0]),
                            phase="score", cadence="iter")
                score = score.at[:, 0].add(delta)
                if fin_freq > 0 and fin_policy == "skip_iter":
                    # a tripped check heals the score carry too: a NaN
                    # that slipped in at an UNCHECKED iteration (freq>1)
                    # would otherwise re-poison every later gradient and
                    # the guard would skip forever
                    score = jnp.where(bad, jnp.nan_to_num(
                        score, nan=0.0, posinf=_FINITE_CLAMP,
                        neginf=-_FINITE_CLAMP), score)
                # keep the scan outputs tree-sized: drop the [N] row->leaf
                # vector, ship shrunk leaf values
                out = arrays._replace(leaf_of_row=jnp.zeros((), jnp.int32),
                                      leaf_value=lv)
                return (score, dead, cuse, ml), (out, bad)

            @functools.partial(jax.jit, donate_argnums=(0,))
            def chunk(score, fmasks, iters, cuse0, ml):
                (score, _, _, _), (out, bad) = jax.lax.scan(
                    one_iter, (score, jnp.bool_(False), cuse0, ml),
                    (fmasks, iters))
                return score, out, bad

            fn = self._fused_cache["chunk"] = chunk
        return fn

    def train_chunk(self, k: int) -> bool:
        """Run ``k`` boosting iterations as ONE device program + ONE host
        fetch of the k small tree records.  Semantically identical to k
        ``train_one_iter`` calls under ``supports_fused()`` (same RNG
        streams: feature masks are pre-drawn host-side, GOSS keys are
        seeded by iteration index in-graph).  Returns True when a
        no-split iteration occurred (trailing stump repeats discarded)."""
        if self._elastic is not None:
            self._elastic.check_peers()      # per-chunk liveness poll
        if self.valid_sets:
            raise ValueError(
                "train_chunk requires no validation sets: per-iteration "
                "eval/early-stop runs go through train_superepoch, which "
                "evaluates traced metrics inside the scan (engine.train "
                "routes there automatically)")
        if not self._fusable_config():
            raise ValueError(
                "train_chunk: config not fusable: "
                + "; ".join(r for r in self.fused_reasons()
                            if not r.startswith("fused_chunk=")))
        cfg = self.config
        start_iter = self.iter_
        init0 = 0.0
        if start_iter == 0 and self.objective is not None \
                and cfg.boost_from_average and not self._init_applied:
            init0 = self._boost_from_score(0)
            self._init_scores = [init0]
            if init0 != 0.0:
                self.score = self.score + jnp.float32(init0)

        obs = self._obs
        if obs is not None:
            _sp = obs.tracer.span("train_chunk", n_iters=k,
                                  iteration=start_iter)
            if obs.profiler is not None:
                # the chunk is ONE device program: the capture window
                # opens if any requested iteration falls inside it
                for it in range(start_iter, start_iter + k):
                    obs.profiler.on_iter_begin(it)

        chunk = self._fused_chunk_fn()
        if cfg.feature_fraction < 1.0:
            fmasks = jnp.asarray(
                np.stack([self._feature_mask() for _ in range(k)]))
        else:
            fmasks = jnp.ones((k, self.num_features), bool)
        it0 = start_iter + self._iter_rng_offset
        iters = jnp.arange(it0, it0 + k, dtype=jnp.int32)
        cuse0 = jnp.asarray(self._cegb_state.used) \
            if self._cegb_state is not None \
            else jnp.zeros(1, bool)
        self.score, stacked, bad_flags = chunk(self.score, fmasks, iters,
                                               cuse0,
                                               jnp.int32(cfg.num_leaves))
        # the one sync per chunk (tree records + finite-guard flags)
        host, bad_host = self._eget((stacked, bad_flags), "fused_fetch")
        if obs is not None:
            _sp.end()                  # device_get above already blocked
            if obs.profiler is not None:
                obs.profiler.on_iter_end(start_iter + k - 1)

        lr = self.learning_rate
        stopped = False
        for j in range(k):
            tj = TreeArrays(*(np.asarray(fld[j]) for fld in host))
            nl = int(tj.num_leaves)
            if bool(bad_host[j]):
                from ..utils.log import Log
                msg = ("non-finite gradient/hessian or leaf output "
                       f"detected at iteration {it0 + j + 1} "
                       f"(finite_check_freq={cfg.finite_check_freq})")
                if self._bbox is not None:
                    self._bbox.record(event="finite_check_trip",
                                      iteration=it0 + j + 1,
                                      policy=cfg.finite_check_policy,
                                      fused=True)
                    self._bbox.dump("finite_check")
                if cfg.finite_check_policy == "raise":
                    from ..basic import LightGBMError
                    raise LightGBMError(
                        msg + "; aborting (finite_check_policy=raise)")
                # skip_iter: the iteration already contributed nothing
                # in-graph; record a zero stump so iteration counts and
                # model text match the per-iteration path exactly
                Log.warning(msg + "; iteration contributes nothing "
                                  "(finite_check_policy=skip_iter)")
                self.step_counts.append(int(tj.n_steps))
                ht = Tree(1)
                ht.shrinkage = lr
                ht.leaf_value = np.asarray(
                    [init0 if (start_iter == 0 and j == 0) else 0.0],
                    np.float64)
                self.models.append(ht)
                dev_arrays = TreeArrays(*(fld[j] for fld in stacked))
                self.device_trees.append(_DeviceTree(
                    dev_arrays, jnp.zeros_like(dev_arrays.leaf_value), 1))
                self.tree_weights.append(1.0)
                self.iter_ += 1
                continue
            self.step_counts.append(int(tj.n_steps))
            lvj = np.asarray(tj.leaf_value, np.float64).copy()
            if self._cegb_state is not None and nl > 1:
                # mirror the in-graph CEGB used-set update on the host so
                # the NEXT chunk starts from the right cross-tree state
                self._cegb_state.used[
                    np.asarray(tj.split_feature)[:nl - 1]] = True
            if nl <= 1:
                stopped = True
                lvj[:] = 0.0
            ht = Tree.from_arrays(tj, self.train_set.used_features,
                                  self.train_set.bin_mappers)
            ht.internal_value = ht.internal_value * lr
            ht.shrinkage = lr
            bias = init0 if (start_iter == 0 and j == 0) else 0.0
            ht.leaf_value = lvj[:max(nl, 1)] + bias   # Tree::AddBias
            self.models.append(ht)

            dev_arrays = TreeArrays(*(fld[j] for fld in stacked))
            dev_lv = dev_arrays.leaf_value if nl > 1 else \
                jnp.zeros_like(dev_arrays.leaf_value)
            steps = round_up_pow2(max(ht.max_depth(), 1))
            self.device_trees.append(_DeviceTree(dev_arrays, dev_lv, steps))
            self.tree_weights.append(1.0)
            self.iter_ += 1
            if stopped:
                break
        if obs is not None:
            done = self.iter_ - start_iter
            obs.metrics.counter("train.iterations").inc(done)
            obs.metrics.counter("train.fused_chunks").inc()
            for s in self.step_counts[len(self.step_counts) - done:]:
                obs.metrics.histogram("train.steps_per_tree").observe(s)
                obs.record_flops(s)
        if self._bbox is not None:
            done = self.iter_ - start_iter
            rec = {"event": "fused_chunk", "iterations": done,
                   "first_iteration": start_iter + 1,
                   "steps": self.step_counts[len(self.step_counts)
                                             - done:]}
            if self._flops is not None:
                fl = hb = 0
                for s in rec["steps"]:
                    f_, b_ = self._flops.per_iteration(s)
                    fl, hb = fl + f_, hb + b_
                rec["flops"], rec["hbm_bytes"] = fl, hb
            self._bbox.record(**rec)
        self._last_iter_state = None    # rollback not supported past a chunk
        return stopped

    # -- super-epoch trainer: whole-run on-device boosting -----------------

    def _se_steps(self) -> int:
        """Static per-tree traversal budget for the in-scan valid-set
        scoring (utils/shapes.traversal_steps): the scan cannot size a
        fori_loop from a grown tree's ACTUAL depth (a traced value), so
        every tree in the epoch walks the config-derived worst case."""
        from ..utils.shapes import traversal_steps
        cfg = self.config
        return traversal_steps(cfg.max_depth,
                               self._leaf_pad or max(cfg.num_leaves, 2))

    def _se_valid_dev(self, vi: int) -> Tuple[jax.Array, jax.Array]:
        """Device (label, weight) operands of valid set ``vi``, padded to
        its bucketed score length — pad rows carry weight 0 so the traced
        weighted metrics reduce them away exactly."""
        cached = self._se_valid_cache.get(vi)
        if cached is not None:
            return cached
        vds, _, vscore = self.valid_sets[vi]
        rows, nv = vscore.shape[0], vds.num_data
        lbl = np.zeros(rows, np.float32)
        lbl[:nv] = np.asarray(vds.metadata.label, np.float32).reshape(-1)
        w = np.zeros(rows, np.float32)
        if vds.metadata.weight is not None:
            w[:nv] = np.asarray(vds.metadata.weight,
                                np.float32).reshape(-1)
        else:
            w[:nv] = 1.0
        out = (jnp.asarray(lbl), jnp.asarray(w))
        self._se_valid_cache[vi] = out
        return out

    def _teval_fn(self, eval_spec):
        """The shared traced-eval program for ``eval_spec`` (model-level
        cache; metrics.build_traced_eval).  Both the super-epoch replay
        rows and Booster.eval_valid_traced report through THIS program,
        which is what makes their values bit-identical."""
        key = ("teval", tuple(eval_spec))
        fn = self._fused_cache.get(key)
        if fn is None:
            from ..metrics import build_traced_eval
            fn = build_traced_eval(tuple(eval_spec), self.config)
            self._fused_cache[key] = fn
        return fn

    def _obj_array_attrs(self):
        """Partition the live objective's attributes into (array attr
        names, array values, scalar key parts) so the super-epoch program
        can bake a data-free objective template and receive the arrays as
        ARGUMENTS (process-level program sharing).  Returns None when an
        attribute defies classification — the caller then falls back to a
        private jit that closes over the objective whole."""
        names: List[str] = []
        vals: List[jax.Array] = []
        scal: List[Tuple[str, str]] = []
        for name in sorted(vars(self.objective)):
            if name == "config":
                continue            # keyed via Config.to_dict already
            v = getattr(self.objective, name)
            if isinstance(v, (jax.Array, np.ndarray)):
                names.append(name)
                vals.append(jnp.asarray(v))
            elif v is None or isinstance(v, (bool, int, float, str)):
                scal.append((name, repr(v)))
            elif isinstance(v, tuple) and all(
                    isinstance(t, (bool, int, float, str)) for t in v):
                scal.append((name, repr(v)))
            else:
                return None
        return tuple(names), tuple(vals), tuple(scal)

    def _superepoch_key(self, eval_spec, es_spec, obj_parts):
        """Process-level sharing key for the super-epoch program, or None
        when this model's state cannot ride as arguments (private jit in
        ``self._fused_cache`` instead).  ``num_leaves`` is deliberately
        REPLACED by the effective super-step width when the leaf budget
        is padded: with ``padded_leaves`` the budget is a traced argument
        and the only structural residue of ``num_leaves`` is the grower's
        K = min(split_batch, num_leaves - 1) — so a 31/63 leaf sweep at
        split_batch <= 30 shares ONE compiled scan (the check_retraces.py
        ``superepoch`` scenario pins exactly that)."""
        cfg = self.config
        if obj_parts is None:
            return None
        if (self._use_efb or self.efb_maps is not None
                or self._ic_grow is not None
                or self._cegb_state is not None
                or self._mono is not None or self._inter is not None
                or self._feature_contri is not None or self._pc > 1):
            return None
        if self._goss or self._bagging_active:
            return None     # sampling bakes bound methods (model state)
        from ..sparse_data import SparseBinned
        if isinstance(self.binned_dev, SparseBinned) or any(
                not isinstance(vb, jax.Array)
                for _, vb, _ in self.valid_sets):
            return None
        cfg_items = tuple(sorted(
            (k, repr(v)) for k, v in cfg.to_dict().items()
            if k != "num_leaves" or self._leaf_pad is None))
        k_eff = max(1, min(self._split_batch, cfg.num_leaves - 1)) \
            if cfg.num_leaves > 1 else 1
        names, _, scal = obj_parts
        return (cfg_items, k_eff, self._split_batch, self._block_rows,
                self._leaf_pad, self._hist_overlap, self._learner_kind,
                self._se_steps(), float(self.learning_rate), self.max_bin,
                type(self.objective).__name__, names, scal,
                len(self.valid_sets), tuple(eval_spec), repr(es_spec))

    def _build_superepoch_body(self, eval_spec, es_spec, obj_parts,
                               member_args=False):
        """Build the UNJITTED super-epoch scan body: ONE ``lax.scan``
        over k FULL boosting iterations — gradients, grow, score update,
        valid-set traversal+scoring, traced metric eval, early-stop vote
        — with zero host syncs inside.  The per-iteration tree math is
        the fused-chunk ``one_iter`` body verbatim (same RNG streams,
        same finite-guard policies, same dead-gating), extended with the
        traced eval tail; model data arrays ride as arguments so keyable
        configs share the compile process-wide (``_SE_CACHE``).

        ``member_args=True`` is the fleet trainer's form: the trailing
        ``mrng = (learning_rate, sampling_seed, quant_seed)`` operand
        replaces the corresponding baked constants so the SAME body can
        be ``jax.vmap``-ped over a member axis (fleet/trainer.py) with
        per-member streams.  Feeding a value as an argument instead of a
        closure constant does not change a single emitted arithmetic op,
        which is what keeps fleet members byte-identical to solo runs."""
        from ..metrics import traced_metric_fn
        from ..obs.flops import (eval_flops_bytes, note_traced,
                                 score_update_flops_bytes)

        cfg = self.config
        grow = make_grower(
            num_leaves=cfg.num_leaves, num_bins=self.max_bin,
            params=self.split_params, max_depth=cfg.max_depth,
            block_rows=self._block_rows,
            efb=self.efb_dev if self._use_efb else None,
            gain_scale=self._feature_contri,
            extra_trees=self._extra_trees, extra_seed=cfg.extra_seed,
            split_batch=self._split_batch,
            hist_overlap=self._hist_overlap,
            mono=self._mono if self._learner_kind == "masked" else None,
            mono_penalty=cfg.monotone_penalty,
            interaction_groups=self._inter,
            bynode_frac=cfg.feature_fraction_bynode,
            bynode_seed=cfg.feature_fraction_seed + 1,
            cegb=self._cegb_state,
            padded_leaves=self._leaf_pad,
            quant=self._quant,
            jit=False)
        if obj_parts is not None:
            arr_names = obj_parts[0]
            obj_template = copy.copy(self.objective)
            for nm in arr_names:
                setattr(obj_template, nm, None)   # arrays ride as args
        else:
            arr_names = ()
            obj_template = self.objective      # private jit: close over
        lr = jnp.float32(self.learning_rate)
        use_goss = self._goss
        use_bag = self._bagging_active and not use_goss
        # bound methods hold the model alive — only bake them when the
        # sampling mode actually uses them (sampling also excludes the
        # model from _SE_CACHE sharing, so a baked method never leaks
        # into another model's program)
        goss_vals = self._goss_vals if use_goss else None
        bagging_w = self._bagging_w if use_bag else None
        rng_iter_kw = (self._extra_trees or self._bynode_masked
                       or self._quant is not None)
        use_quant_seed = member_args and self._quant is not None
        ic = self._ic_grow
        fin_freq = cfg.finite_check_freq
        fin_policy = cfg.finite_check_policy
        use_cegb = self._cegb_state is not None
        nf = self.num_features
        leaf_padded = self._leaf_pad is not None
        steps = self._se_steps()
        efb_maps = self.efb_maps
        n_rows = self.num_data

        # eval plumbing: one traced metric per (valid set, metric) entry,
        # in booster.eval_valid() order.  The in-scan eval exists ONLY
        # to drive the early-stop vote (callback.early_stopping's
        # update-then-check at min_delta == 0): reported values are
        # recomputed post-scan through the shared teval program
        # (metrics.build_traced_eval) from the stacked per-iteration
        # valid scores the scan emits, because a reduction fused INTO
        # the scan body may round the last ulp differently than the
        # standalone program — bit-identity with the per-iteration
        # fused_eval path requires the same program shape
        n_entries = len(eval_spec)
        vote_eval = es_spec is not None and n_entries > 0
        metric_idx = tuple(
            (vi, traced_metric_fn(mname, cfg))
            for (vi, _sname, mname, _hib) in eval_spec) if vote_eval \
            else ()
        if es_spec is not None:
            es_rounds = int(es_spec["stopping_rounds"])
            es_elig = jnp.asarray(np.asarray(es_spec["eligible"], bool))
            es_hib = jnp.asarray(
                np.asarray([hib for (_, _, _, hib) in eval_spec], bool))

        # the scan body assembles the objective from the array arguments
        # (process-level program sharing keeps data out of the closure)
        def sepoch_body(score, vscores, es_state, fmasks, iters, eiters,
                        cuse0, ml, binned, nb, na, na_bin, obj_arrs,
                        valid_ops, mrng=None):
            if member_args:
                lr_, samp_seed, q_seed = mrng
            else:
                lr_, samp_seed, q_seed = lr, None, None
            obj = copy.copy(obj_template)
            for nm, arr in zip(arr_names, obj_arrs):
                setattr(obj, nm, arr)

            def one_iter(carry, xs):
                score, vsc, esb, esi, esh, stop, dead, cuse, ml = carry
                fmask, it, eit = xs
                blocked = dead | stop
                g, h = obj.get_gradients(score[:, 0])
                if fin_freq > 0 and fin_policy == "clamp":
                    g = jnp.nan_to_num(g, nan=0.0, posinf=_FINITE_CLAMP,
                                       neginf=-_FINITE_CLAMP)
                    h = jnp.nan_to_num(h, nan=0.0, posinf=_FINITE_CLAMP,
                                       neginf=0.0)
                if use_goss:
                    w = goss_vals(g, h, it, seed=samp_seed)
                elif use_bag:
                    w = bagging_w(it, seed=samp_seed)
                else:
                    w = jnp.ones_like(g)
                vals = jnp.stack([g * w, h * w, w], axis=1)
                kw = {"is_cat": ic} if ic is not None else {}
                if rng_iter_kw:
                    kw["rng_iter"] = it
                if use_quant_seed:
                    kw["quant_seed"] = q_seed
                if use_cegb:
                    kw["cegb_used"] = cuse
                if leaf_padded:
                    kw["max_leaves"] = ml
                arrays = grow(binned, vals, fmask, nb, na, **kw)
                if use_cegb:
                    node_on = (jnp.arange(arrays.split_feature.shape[0])
                               < arrays.num_leaves - 1)
                    marks = jnp.zeros(nf, jnp.int32) \
                        .at[arrays.split_feature].add(
                            node_on.astype(jnp.int32))
                    cuse = cuse | (marks > 0)
                if fin_freq > 0 and fin_policy == "clamp":
                    lv = jnp.nan_to_num(
                        arrays.leaf_value, nan=0.0, posinf=_FINITE_CLAMP,
                        neginf=-_FINITE_CLAMP) * lr_
                else:
                    lv = arrays.leaf_value * lr_
                if fin_freq > 0 and fin_policy != "clamp":
                    check_now = ((it + 1) % fin_freq) == 0
                    fin = (jnp.isfinite(g).all() & jnp.isfinite(h).all()
                           & jnp.isfinite(lv).all())
                    bad = check_now & ~fin
                else:
                    bad = jnp.bool_(False)
                ok = jnp.where(blocked | bad, 0.0,
                               (arrays.num_leaves > 1)
                               .astype(jnp.float32))
                if fin_freq > 0 and fin_policy == "raise":
                    dead = dead | (arrays.num_leaves <= 1) | bad
                else:
                    dead = dead | ((arrays.num_leaves <= 1) & ~bad)
                delta = jnp.where(ok > 0.0,
                                  jnp.take(lv, arrays.leaf_of_row), 0.0)
                note_traced("score",
                            *score_update_flops_bytes(score.shape[0]),
                            phase="score", cadence="iter")
                score = score.at[:, 0].add(delta)
                if fin_freq > 0 and fin_policy == "skip_iter":
                    score = jnp.where(bad, jnp.nan_to_num(
                        score, nan=0.0, posinf=_FINITE_CLAMP,
                        neginf=-_FINITE_CLAMP), score)
                # valid-set scoring: same traversal + leaf-gather the
                # per-iteration path runs (predict_device add_tree_score
                # at weight 1.0 == plain gather-add), under ONE static
                # step budget so every tree of the epoch shares the trace
                new_vsc = []
                for vi2 in range(len(valid_ops)):
                    leaf = traverse_tree_binned(
                        valid_ops[vi2][0], arrays.split_feature,
                        arrays.threshold_bin, arrays.default_left,
                        arrays.left_child, arrays.right_child, na_bin,
                        arrays.is_cat_node, arrays.cat_rank, efb_maps,
                        steps=steps)
                    vd = jnp.where(ok > 0.0, jnp.take(lv, leaf), 0.0)
                    new_vsc.append(vsc[vi2].at[:, 0].add(vd))
                vsc = tuple(new_vsc)
                # early-stop vote (callback.early_stopping traced form,
                # min_delta == 0): update-then-check exactly like the
                # host closure — non-eligible entries (training set /
                # first_metric_only filter) still update their best.
                # The vote's in-scan metric values may differ from the
                # reported teval values in the last ulp (fusion-order);
                # engine.train heals vote/replay disagreement either
                # way (drop_iterations / clear_es_stop), so the vote is
                # a work-bound, never the source of truth
                if vote_eval:
                    note_traced("fused_eval",
                                *eval_flops_bytes(n_rows, n_entries),
                                phase="eval", cadence="iter")
                    ev = jnp.stack([
                        fn_m(vsc[vi2][:, 0], valid_ops[vi2][1],
                             valid_ops[vi2][2])
                        for (vi2, fn_m) in metric_idx])
                    fin2 = jnp.isfinite(ev)
                    cmp2 = jnp.where(es_hib, ev > esb, ev < esb)
                    improved = fin2 & (~esh | cmp2) & ~blocked
                    esb = jnp.where(improved, ev, esb)
                    esi = jnp.where(improved, eit, esi)
                    esh = esh | improved
                    trip = (es_elig & ((eit - esi) >= es_rounds)
                            & ~blocked)
                    stop = stop | trip.any()
                out = arrays._replace(
                    leaf_of_row=jnp.zeros((), jnp.int32), leaf_value=lv)
                return ((score, vsc, esb, esi, esh, stop, dead, cuse,
                         ml), (out, bad, stop,
                               tuple(v[:, 0] for v in vsc)))

            esb, esi, esh, stop = es_state
            carry0 = (score, vscores, esb, esi, esh, stop,
                      jnp.bool_(False), cuse0, ml)
            (score, vscores, esb, esi, esh, stop, _, _, _), \
                (out, bad, stops, vstack) = jax.lax.scan(
                    one_iter, carry0, (fmasks, iters, eiters))
            return (score, vscores, (esb, esi, esh, stop), out, bad,
                    stops, vstack)

        return sepoch_body

    def _build_superepoch(self, eval_spec, es_spec, obj_parts):
        """Compile the (solo) super-epoch program: the scan body from
        ``_build_superepoch_body`` under one jit with donated carries."""
        import functools
        from ..utils.compile_cache import trace_event
        body = self._build_superepoch_body(eval_spec, es_spec, obj_parts)

        @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
        def sepoch(score, vscores, es_state, fmasks, iters, eiters,
                   cuse0, ml, binned, nb, na, na_bin, obj_arrs,
                   valid_ops):
            trace_event("superepoch")
            return body(score, vscores, es_state, fmasks, iters, eiters,
                        cuse0, ml, binned, nb, na, na_bin, obj_arrs,
                        valid_ops)

        return sepoch

    def build_fleet_superepoch(self, eval_spec, es_spec, obj_parts):
        """Compile the FLEET super-epoch program (fleet/trainer.py): the
        same scan body as ``_build_superepoch``, ``jax.vmap``-ped over a
        leading member axis of every member-varying operand — scores,
        valid scores, ES state, feature masks, iteration indices, leaf
        budgets, and the per-member ``(lr, sampling seed, quant seed)``
        stream block — while the binned matrix, NA table, objective
        arrays and valid-set operands stay shared (in_axes=None).  N
        forests grow inside ONE compiled program with ONE trace
        (``fleet_superepoch``); per-member early-stop flags mask (not
        branch) finished members, so lanes at different progress points
        coexist without retracing."""
        import functools
        from ..utils.compile_cache import trace_event
        body = self._build_superepoch_body(eval_spec, es_spec, obj_parts,
                                           member_args=True)
        vbody = jax.vmap(body, in_axes=(0, 0, 0, 0, 0, 0, None, 0,
                                        None, None, None, None, None,
                                        None, 0))

        @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
        def fleet_sepoch(score, vscores, es_state, fmasks, iters,
                         eiters, cuse0, ml, binned, nb, na, na_bin,
                         obj_arrs, valid_ops, mrng):
            trace_event("fleet_superepoch")
            return vbody(score, vscores, es_state, fmasks, iters,
                         eiters, cuse0, ml, binned, nb, na, na_bin,
                         obj_arrs, valid_ops, mrng)

        return fleet_sepoch

    def fleet_superepoch_fn(self, eval_spec, es_spec, obj_parts,
                            n_members: int):
        """The FLEET super-epoch program with process-level sharing
        (fleet/trainer.py): same ``_SE_CACHE`` discipline as the solo
        path, keyed by the solo sharing key plus the member count — a
        warmed-up process redispatches the same fleet shape without
        recompiling.  Unkeyable state (bagging/GOSS bound methods etc.)
        falls back to this model's private ``_fused_cache``."""
        key = self._superepoch_key(eval_spec, es_spec, obj_parts)
        if key is not None:
            key = ("fleet", int(n_members)) + key
            with _SE_CACHE_LOCK:
                fn = _SE_CACHE.get(key)
                if fn is not None:
                    _SE_CACHE.move_to_end(key)
            if fn is None:
                fn = self.build_fleet_superepoch(eval_spec, es_spec,
                                                 obj_parts)
                with _SE_CACHE_LOCK:
                    _SE_CACHE[key] = fn
                    while len(_SE_CACHE) > _SE_CACHE_MAX:
                        _SE_CACHE.popitem(last=False)
            return fn
        pk = ("fleet_superepoch", int(n_members), tuple(eval_spec),
              repr(es_spec))
        fn = self._fused_cache.get(pk)
        if fn is None:
            fn = self.build_fleet_superepoch(eval_spec, es_spec,
                                             obj_parts)
            self._fused_cache[pk] = fn
        return fn

    def train_superepoch(self, k: int, es_it0: int, eval_spec=(),
                         es_spec=None) -> dict:
        """Run ``k`` FULL boosting iterations — grow, score update,
        valid-set scoring, traced metric eval and the early-stop vote —
        as ONE device program with exactly ONE host fetch (stacked tree
        records + finite-guard flags + the [k, E] eval block + per-
        iteration stop flags).  ``engine.train`` replays the fetched
        block through the real host callbacks afterwards, so
        ``record_evals``/``early_stopping``/``best_iteration`` are
        byte-identical to the per-iteration path.

        ``es_it0`` is the absolute ``env.iteration`` of the epoch's
        first row (the PR 9 absolute best_iteration contract —
        resume-correct); ``eval_spec`` is a tuple of
        ``(valid_idx, set_name, metric_name, higher_better)`` entries in
        ``booster.eval_valid()`` order; ``es_spec`` (optional) is
        ``{"stopping_rounds", "first_metric_only", "eligible"}`` for the
        traced vote (scalar ``min_delta == 0`` only — engine gates).

        Returns ``{"evals": f32 [done, E], "done": int, "stump": bool,
        "stop_row": Optional[int]}``."""
        cfg = self.config
        start_iter = self.iter_
        init0, _sp = self._se_begin(k, len(eval_spec))
        obs = self._obs
        obj_parts = self._obj_array_attrs()
        key = self._superepoch_key(eval_spec, es_spec, obj_parts)
        fn = None
        if key is not None:
            with _SE_CACHE_LOCK:
                fn = _SE_CACHE.get(key)
                if fn is not None:
                    _SE_CACHE.move_to_end(key)
            if fn is None:
                fn = self._build_superepoch(eval_spec, es_spec, obj_parts)
                with _SE_CACHE_LOCK:
                    _SE_CACHE[key] = fn
                    while len(_SE_CACHE) > _SE_CACHE_MAX:
                        _SE_CACHE.popitem(last=False)
        else:
            pk = ("superepoch", tuple(eval_spec), repr(es_spec))
            fn = self._fused_cache.get(pk)
            if fn is None:
                fn = self._build_superepoch(eval_spec, es_spec, obj_parts)
                self._fused_cache[pk] = fn

        (fmasks, iters, eiters, cuse0, es_state, vscores,
         valid_ops) = self._se_operands(k, es_it0, len(eval_spec))
        obj_arrs = obj_parts[1] if obj_parts is not None else ()
        (self.score, new_vsc, es_out, stacked, bad_flags, stops_dev,
         vstack) = fn(self.score, vscores, es_state, fmasks, iters,
                      eiters, cuse0, jnp.int32(cfg.num_leaves),
                      self.binned_dev, self._nb_grow, self._na_grow,
                      self.na_bin_dev, obj_arrs, valid_ops)
        self._se_absorb(new_vsc, es_out)
        ev_dev = self._se_eval_block(vstack, eval_spec, k)
        # the one sync per super-epoch (tree records + finite-guard
        # flags + eval block + stop flags)
        host, bad_host, ev_host, stops_np = self._eget(
            (stacked, bad_flags, ev_dev, stops_dev), "fused_fetch")
        if obs is not None:
            _sp.end()
            if obs.profiler is not None:
                obs.profiler.on_iter_end(start_iter + k - 1)
        return self._se_ingest(host, stacked, bad_host, stops_np,
                               ev_host, k, start_iter, init0,
                               len(eval_spec))

    def _se_begin(self, k: int, n_entries: int):
        """Super-epoch prologue (shared with fleet/trainer.py): peer
        liveness, fusability guard, the first-iteration
        boost_from_average bias applied to train AND valid scores, and
        the obs span.  Returns ``(init0, span_or_None)``."""
        if self._elastic is not None:
            self._elastic.check_peers()
        if not self._fusable_config():
            raise ValueError(
                "train_superepoch: config not fusable: "
                + "; ".join(r for r in self.fused_reasons()
                            if not r.startswith("fused_chunk=")))
        cfg = self.config
        start_iter = self.iter_
        init0 = 0.0
        if start_iter == 0 and self.objective is not None \
                and cfg.boost_from_average and not self._init_applied:
            init0 = self._boost_from_score(0)
            self._init_scores = [init0]
            if init0 != 0.0:
                self.score = self.score + jnp.float32(init0)
                # valid scores carry the same bias (train_one_iter's
                # boost_from path does this per-set too)
                for vi in range(len(self.valid_sets)):
                    vds, vb, vs = self.valid_sets[vi]
                    self.valid_sets[vi] = (vds, vb,
                                           vs + jnp.float32(init0))
        obs = self._obs
        _sp = None
        if obs is not None:
            _sp = obs.tracer.span("train_superepoch", n_iters=k,
                                  iteration=start_iter,
                                  n_evals=n_entries)
            if obs.profiler is not None:
                for it in range(start_iter, start_iter + k):
                    obs.profiler.on_iter_begin(it)
        return init0, _sp

    def _se_operands(self, k: int, es_it0: int, n_entries: int):
        """The epoch's device operands (shared with fleet/trainer.py).
        Draws the k stateful feature-fraction masks — call EXACTLY once
        per dispatched epoch, in member order, or the host RNG stream
        diverges from the solo run."""
        cfg = self.config
        if cfg.feature_fraction < 1.0:
            fmasks = jnp.asarray(
                np.stack([self._feature_mask() for _ in range(k)]))
        else:
            fmasks = jnp.ones((k, self.num_features), bool)
        it0 = self.iter_ + self._iter_rng_offset
        iters = jnp.arange(it0, it0 + k, dtype=jnp.int32)
        eiters = jnp.arange(es_it0, es_it0 + k, dtype=jnp.int32)
        cuse0 = jnp.asarray(self._cegb_state.used) \
            if self._cegb_state is not None \
            else jnp.zeros(1, bool)
        es_state = self._es_dev
        if es_state is None:
            es_state = (jnp.zeros(n_entries, jnp.float32),
                        jnp.zeros(n_entries, jnp.int32),
                        jnp.zeros(n_entries, bool),
                        jnp.bool_(False))
        vscores = tuple(vs for _, _, vs in self.valid_sets)
        valid_ops = tuple(
            (self.valid_sets[vi][1],) + self._se_valid_dev(vi)
            for vi in range(len(self.valid_sets)))
        return (fmasks, iters, eiters, cuse0, es_state, vscores,
                valid_ops)

    def _se_absorb(self, new_vsc, es_out) -> None:
        """Store the epoch's updated valid scores + ES vote state."""
        for vi in range(len(self.valid_sets)):
            vds, vb, _ = self.valid_sets[vi]
            self.valid_sets[vi] = (vds, vb, new_vsc[vi])
        self._es_dev = es_out

    def _se_eval_block(self, vstack, eval_spec, k: int, teval=None):
        """Reported eval values: the SAME jitted program the
        per-iteration fused_eval path runs (metrics.build_traced_eval),
        applied to each iteration's stacked valid-score row — in-scan
        reductions can fuse (and round the last ulp) differently than
        the standalone program, so re-evaluating through the shared
        program is what makes super-epoch record_evals bit-identical to
        per-iteration.  The k dispatches are async; no host sync here.
        ``teval`` (optional) supplies the program — the fleet trainer
        passes member 0's so ALL members report through ONE trace."""
        if not len(eval_spec):
            return jnp.zeros((k, 0), jnp.float32)
        if teval is None:
            teval = self._teval_fn(eval_spec)
        t_ops = tuple(self._se_valid_dev(vi)
                      for vi in range(len(self.valid_sets)))
        return jnp.stack([
            teval(tuple(vstack[vi][j]
                        for vi in range(len(vstack))), t_ops)
            for j in range(k)])

    def _se_ingest(self, host, stacked, bad_host, stops_np, ev_host,
                   k: int, start_iter: int, init0: float,
                   n_entries: int) -> dict:
        """Replay the fetched epoch block into host/device tree state:
        one ``Tree.from_arrays`` + ``_DeviceTree`` per row, finite-guard
        stub handling, CEGB feature marking, and the obs/bbox epoch
        accounting.  Shared with fleet/trainer.py, which slices each
        member's rows out of the [N, k, ...] fleet fetch and ingests
        them through this exact path."""
        cfg = self.config
        obs = self._obs
        E = n_entries
        it0 = start_iter + self._iter_rng_offset
        lr = self.learning_rate
        stopped = False
        stop_row = None
        for j in range(k):
            tj = TreeArrays(*(np.asarray(fld[j]) for fld in host))
            nl = int(tj.num_leaves)
            if bool(bad_host[j]):
                from ..utils.log import Log
                msg = ("non-finite gradient/hessian or leaf output "
                       f"detected at iteration {it0 + j + 1} "
                       f"(finite_check_freq={cfg.finite_check_freq})")
                if self._bbox is not None:
                    self._bbox.record(event="finite_check_trip",
                                      iteration=it0 + j + 1,
                                      policy=cfg.finite_check_policy,
                                      fused=True)
                    self._bbox.dump("finite_check")
                if cfg.finite_check_policy == "raise":
                    from ..basic import LightGBMError
                    raise LightGBMError(
                        msg + "; aborting (finite_check_policy=raise)")
                Log.warning(msg + "; iteration contributes nothing "
                                  "(finite_check_policy=skip_iter)")
                self.step_counts.append(int(tj.n_steps))
                ht = Tree(1)
                ht.shrinkage = lr
                ht.leaf_value = np.asarray(
                    [init0 if (start_iter == 0 and j == 0) else 0.0],
                    np.float64)
                self.models.append(ht)
                dev_arrays = TreeArrays(*(fld[j] for fld in stacked))
                self.device_trees.append(_DeviceTree(
                    dev_arrays, jnp.zeros_like(dev_arrays.leaf_value),
                    1))
                self.tree_weights.append(1.0)
                self.iter_ += 1
                if bool(stops_np[j]):
                    stop_row = j
                    break
                continue
            self.step_counts.append(int(tj.n_steps))
            lvj = np.asarray(tj.leaf_value, np.float64).copy()
            if self._cegb_state is not None and nl > 1:
                self._cegb_state.used[
                    np.asarray(tj.split_feature)[:nl - 1]] = True
            if nl <= 1:
                stopped = True
                lvj[:] = 0.0
            ht = Tree.from_arrays(tj, self.train_set.used_features,
                                  self.train_set.bin_mappers)
            ht.internal_value = ht.internal_value * lr
            ht.shrinkage = lr
            bias = init0 if (start_iter == 0 and j == 0) else 0.0
            ht.leaf_value = lvj[:max(nl, 1)] + bias
            self.models.append(ht)

            dev_arrays = TreeArrays(*(fld[j] for fld in stacked))
            dev_lv = dev_arrays.leaf_value if nl > 1 else \
                jnp.zeros_like(dev_arrays.leaf_value)
            steps = round_up_pow2(max(ht.max_depth(), 1))
            self.device_trees.append(
                _DeviceTree(dev_arrays, dev_lv, steps))
            self.tree_weights.append(1.0)
            self.iter_ += 1
            if stopped or bool(stops_np[j]):
                if bool(stops_np[j]):
                    stop_row = j
                break
        done = self.iter_ - start_iter
        if obs is not None:
            obs.metrics.counter("train.iterations").inc(done)
            obs.metrics.counter("train.superepochs").inc()
            for s in self.step_counts[len(self.step_counts) - done:]:
                obs.metrics.histogram("train.steps_per_tree").observe(s)
                obs.record_flops(s)
        if self._bbox is not None:
            rec = {"event": "superepoch", "iterations": done,
                   "first_iteration": start_iter + 1,
                   "n_evals": E,
                   "steps": self.step_counts[len(self.step_counts)
                                             - done:]}
            if self._flops is not None:
                fl = hb = 0
                for s in rec["steps"]:
                    f_, b_ = self._flops.per_iteration(s)
                    fl, hb = fl + f_, hb + b_
                rec["flops"], rec["hbm_bytes"] = fl, hb
            self._bbox.record(**rec)
        self._last_iter_state = None
        return {"evals": np.asarray(ev_host, np.float32).reshape(k, E),
                "done": done, "stump": stopped, "stop_row": stop_row}

    def drop_iterations(self, n: int) -> None:
        """Host-slice the last ``n`` recorded iterations.  Super-epoch
        replay healing only: when the host callback replay stops earlier
        than the traced vote predicted (defensive — the vote consumes
        the same fetched values the replay does), training is over and
        the surplus trees must not appear in the saved model.  Scores
        are rebuilt by subtracting each dropped tree's contribution via
        device traversal (float add-then-subtract: not bit-perfect, but
        this path ends training — nothing trains on the healed score)."""
        n = int(n)
        if n <= 0:
            return
        nt = n * self.num_class
        for dt in self.device_trees[-nt:]:
            self.score = self.score.at[:, 0].add(
                -jnp.take(dt.leaf_value,
                          _tree_leaves(self.binned_dev, dt,
                                       self.na_bin_dev, self.efb_maps)))
            for vi in range(len(self.valid_sets)):
                vds, vb, vs = self.valid_sets[vi]
                vd = _apply_tree(jnp.zeros_like(vs[:, 0]), vb, dt,
                                 self.na_bin_dev, 1.0, self.efb_maps)
                self.valid_sets[vi] = (vds, vb, vs.at[:, 0].add(-vd))
        del self.models[-nt:]
        del self.device_trees[-nt:]
        del self.tree_weights[-nt:]
        del self.step_counts[-nt:]
        self.iter_ -= n
        self._last_iter_state = None

    def clear_es_stop(self) -> None:
        """Reset the traced early-stop vote's stop latch (defensive
        counterpart of drop_iterations: the vote tripped but the host
        replay did not raise — trust the host and keep training)."""
        if self._es_dev is not None:
            esb, esi, esh, _ = self._es_dev
            self._es_dev = (esb, esi, esh, jnp.bool_(False))

    def train_one_iter(self, grad: Optional[np.ndarray] = None,
                       hess: Optional[np.ndarray] = None) -> bool:
        """One boosting iteration (gbdt.cpp:371 TrainOneIter).
        Returns True if training should stop (no splits possible)."""
        if self._elastic is not None:
            # per-iteration liveness poll (parallel/elastic.py): a peer
            # whose heartbeat went stale becomes a classified
            # ElasticFailure BEFORE this iteration queues collectives
            # that would hang on the dead shard
            self._elastic.check_peers()
        cfg = self.config
        obs = self._obs
        t_iter0 = obs.iter_begin(self.iter_) if obs is not None else 0.0
        bbox = self._bbox
        if bbox is not None:
            import time as _time
            t_bb0 = _time.perf_counter()
        init_scores = [0.0] * self.num_class
        if self.iter_ == 0 and self.objective is not None \
                and cfg.boost_from_average and not self._init_applied:
            # BoostFromAverage (gbdt.cpp:346): add init to train+valid
            # scorers before gradient computation; the saved tree gets the
            # bias via AddBias AFTER UpdateScore (gbdt.cpp:416-418)
            for k in range(self.num_class):
                init_scores[k] = self._boost_from_score(k)
            self._init_scores = list(init_scores)
            if any(s != 0.0 for s in init_scores) and not self._bias_in_every_tree:
                bias = jnp.asarray(init_scores, jnp.float32)
                self.score = self.score + bias
                for vi, (vds, vb, vs) in enumerate(self.valid_sets):
                    self.valid_sets[vi] = (vds, vb, vs + bias)
        # gradients (GBDT::Boosting, gbdt.cpp:172)
        gscore = self._score_for_gradients()
        if self._bias_in_every_tree:
            init_scores = list(getattr(self, "_init_scores", init_scores))
        if obs is not None:
            _sp = obs.phase("grad", self.iter_)
        if grad is None:
            g_all, h_all = self.objective.get_gradients(
                gscore[:, 0] if self.num_class == 1 else gscore)
        else:
            g_all = jnp.asarray(grad, jnp.float32)
            h_all = jnp.asarray(hess, jnp.float32)
        if self.num_class == 1:
            g_all = g_all.reshape(self.num_data, 1)
            h_all = h_all.reshape(self.num_data, 1)
        else:
            g_all = g_all.reshape(self.num_data, self.num_class)
            h_all = h_all.reshape(self.num_data, self.num_class)
        if obs is not None:
            obs.phase_metric("grad", _sp.end((g_all, h_all)))

        it_global = self.iter_ + self._iter_rng_offset
        # fault injection: gradient poisoning at iteration k (the
        # 'nan_grads' site's hit index IS the iteration number)
        from ..utils import faultinject
        if faultinject.enabled() and faultinject.fires("nan_grads"):
            g_all = g_all.at[0].set(jnp.nan)
            h_all = h_all.at[0].set(jnp.nan)

        # finite guard (gbdt.cpp has none; one NaN batch silently poisons
        # a million-iteration model): every finite_check_freq iterations,
        # one fused isfinite scalar over grad/hess — fetched together
        # with this iteration's leaf-output check below, so the guard
        # costs a single amortized scalar sync.  clamp is sync-free and
        # therefore applies every iteration.
        fin_freq = cfg.finite_check_freq
        fin_policy = cfg.finite_check_policy
        fin_check = fin_freq > 0 and (it_global + 1) % fin_freq == 0
        gh_ok = None
        if fin_freq > 0 and fin_policy == "clamp":
            g_all = jnp.nan_to_num(g_all, nan=0.0, posinf=_FINITE_CLAMP,
                                   neginf=-_FINITE_CLAMP)
            h_all = jnp.nan_to_num(h_all, nan=0.0, posinf=_FINITE_CLAMP,
                                   neginf=0.0)
        elif fin_check:
            gh_ok = jnp.isfinite(g_all).all() & jnp.isfinite(h_all).all()

        bag = self._bagging_w(jnp.int32(it_global)) \
            if self._bagging_active and not self._goss else None
        fmask = jnp.asarray(self._feature_mask())

        stopped = True
        heal_score = False
        iter_trees: List[Tree] = []
        iter_state = {"leaf_of_rows": [], "leaf_values": [], "trees": [],
                      "train_deltas": [], "valid_deltas": []}
        for k in range(self.num_class):
            g, h = g_all[:, k], h_all[:, k]
            if self._goss:
                w = self._goss_vals(g, h)
            elif bag is not None:
                w = bag
            else:
                w = jnp.ones(self.num_data, jnp.float32)
            vals = jnp.stack([g * w, h * w, w], axis=1)
            gkw = {}
            if self._ic_grow is not None:
                gkw["is_cat"] = self._ic_grow
            from ..grower_partitioned import PartitionedGrower
            if self._quant is not None:
                # every learner family keys the quantizer's stochastic-
                # rounding stream by the global iteration index, so
                # resume replays the exact rounding of a straight run
                gkw["rng_iter"] = jnp.int32(it_global)
            if isinstance(self.grower, PartitionedGrower):
                if self._forced_spec is not None:
                    gkw["forced"] = self._forced_spec
                if self._cegb_state is not None:
                    gkw["cegb_state"] = self._cegb_state
            else:
                if (self._extra_trees or self._bynode_masked) \
                        and self._dist is None:
                    # per-iteration extra_trees/bynode key component (the
                    # partitioned learner's host RNG advances statefully)
                    gkw["rng_iter"] = jnp.int32(it_global)
                if self._cegb_state is not None and self._dist is None:
                    # CEGB on the masked grower: cross-tree used-feature
                    # state goes in as an argument; the in-tree updates
                    # happen in-graph and are folded back below from the
                    # fetched split records
                    gkw["cegb_used"] = jnp.asarray(self._cegb_state.used)
                if self._leaf_pad is not None:
                    # leaf-padded trace: the ACTUAL budget rides in as a
                    # traced scalar (the while_loop exit bound) so one
                    # padded trace serves the whole num_leaves bucket
                    gkw["max_leaves"] = jnp.int32(cfg.num_leaves)
            vals_g = self._prep_vals(vals)
            fmask_g = self._prep_fmask(fmask)

            def _run_grow(fn):
                if self._dist == "feature":
                    return fn(self.binned_dev, vals_g, fmask_g,
                              self._nb_grow, self._na_grow,
                              self._na_grow, **gkw)
                return fn(self.binned_dev, vals_g, fmask_g,
                          self._nb_grow, self._na_grow, **gkw)

            def _grow():
                a = _run_grow(self.grower)
                if faultinject.enabled():
                    # SDC chaos substrate (integrity.py tests/soak): one
                    # deterministic bit of the new tree's leaf-count
                    # array flips when hist_sdc fires (leaf 0: always a
                    # live slot)
                    a = a._replace(leaf_count=faultinject.maybe_bitflip(
                        "hist_sdc", a.leaf_count, index=0))
                if self._pc > 1 and self._dist is not None:
                    # multi-process: the grower returned GLOBAL arrays
                    # (tree fields replicated, leaf_of_row row-sharded).
                    # Mixing them into this process's local score/valid
                    # math would make every later eager op a
                    # cross-process collective, so re-materialize
                    # everything process-locally: tree fields via one
                    # replicated fetch, this process's leaf_of_row rows
                    # from its own addressable shards.
                    sm = a._replace(leaf_of_row=a.num_leaves)
                    host_g = self._eget(sm, "fetch")
                    a = jax.tree.map(jnp.asarray, host_g)._replace(
                        leaf_of_row=self._localize_rows(a.leaf_of_row))
                elif self._row_pad:
                    # drop padded rows before any host/score use of the
                    # row->leaf vector
                    a = a._replace(
                        leaf_of_row=a.leaf_of_row[:self.num_data])
                return a

            if obs is not None:
                _sp = obs.phase("grow", self.iter_)
            arrays = _grow()
            if obs is not None:
                obs.phase_metric("grow", _sp.end(arrays.num_leaves))
                _sp = obs.phase("fetch", self.iter_)
            # ONE batched host transfer of the tree-sized fields; the [N]
            # leaf_of_row stays on device (only pulled when renew/linear
            # paths need it) — matters when the chip is behind a tunnel
            ichk = self._integrity
            check_now = False
            small = arrays._replace(leaf_of_row=arrays.num_leaves)
            if ichk is None:
                host = self._eget(small, "fetch") \
                    ._replace(leaf_of_row=arrays.leaf_of_row)
            else:
                # integrity layer (lightgbm_tpu/integrity.py): the
                # traced invariant flag — and, on check iterations, the
                # independently-jitted shadow re-execution — rides the
                # SAME consolidated fetch, so steady state gains zero
                # extra host syncs
                from .. import integrity as integrity_mod
                check_now = ichk.should_check(it_global)
                shadow_small = None
                if check_now:
                    s = _run_grow(ichk.shadow_fn)
                    shadow_small = s._replace(leaf_of_row=s.num_leaves)
                inv_dev = integrity_mod.invariant_flags(arrays)
                host_small, inv_ok, shadow_host = self._eget(
                    (small, inv_dev, shadow_small), "fetch")
                arrays, host_small = ichk.verify_grow(
                    self, it_global, _grow, _run_grow, arrays,
                    host_small, bool(inv_ok), shadow_host)
                host = host_small._replace(leaf_of_row=arrays.leaf_of_row)
            if obs is not None:
                # device_get blocks by itself; no fence needed
                obs.phase_metric("fetch", _sp.end())
            nl = int(host.num_leaves)
            # perf observability: grower loop steps per tree (== splits
            # for strict leaf-wise; the super-step count for split_batch)
            self.step_counts.append(int(host.n_steps))
            if "cegb_used" in gkw and nl > 1:
                self._cegb_state.used[
                    np.asarray(host.split_feature)[:nl - 1]] = True
            leaf_values = np.asarray(host.leaf_value, np.float64).copy()
            skip_tree = False
            if fin_freq > 0 and fin_policy == "clamp":
                leaf_values = np.nan_to_num(
                    leaf_values, nan=0.0, posinf=_FINITE_CLAMP,
                    neginf=-_FINITE_CLAMP)
            elif fin_check:
                fin_ok = bool(np.isfinite(leaf_values[:max(nl, 1)]).all())
                if fin_ok and gh_ok is not None:
                    fin_ok = bool(self._eget(gh_ok, "finite_check"))
                    gh_ok = None      # the one scalar sync per check
                if not fin_ok:
                    msg = ("non-finite gradient/hessian or leaf output "
                           f"detected at iteration {it_global + 1} "
                           f"(finite_check_freq={fin_freq})")
                    if bbox is not None:
                        # the finite guard IS a flight-recorder trigger:
                        # dump the trailing ring before acting on the
                        # policy so the post-mortem survives a raise
                        bbox.record(event="finite_check_trip",
                                    iteration=it_global + 1,
                                    policy=fin_policy)
                        bbox.dump("finite_check")
                    if fin_policy == "raise":
                        from ..basic import LightGBMError
                        raise LightGBMError(
                            msg + "; aborting (finite_check_policy=raise)")
                    from ..utils.log import Log
                    Log.warning(msg + "; iteration contributes nothing "
                                      "(finite_check_policy=skip_iter)")
                    skip_tree = True
            if skip_tree:
                # the iteration contributes a zero stump; training
                # continues (a NaN-induced stump must not end the run)
                nl = 1
                host = host._replace(num_leaves=np.int32(1))
                leaf_values[:] = 0.0
                stopped = False
                heal_score = True
            elif nl <= 1:
                leaf_values[:] = 0.0  # stump contributes nothing (gbdt.cpp warn)
            else:
                stopped = False
                if self.objective is not None and \
                        self.objective.need_renew_tree_output:
                    # RenewTreeOutput (serial_tree_learner.cpp:717)
                    score_np = np.asarray(self.score[:, k])
                    leaf_values[:nl] = self.objective.renew_leaf_values(
                        score_np, np.asarray(arrays.leaf_of_row), nl,
                        leaf_values[:nl].copy())

            shrinkage = 1.0 if cfg.boosting == "rf" else self.learning_rate
            if self._fusable_config():
                # shrink with f32 semantics (an exact f64 product of f32
                # operands rounded back to f32 equals the hardware f32
                # multiply) so the fused-chunk path, which shrinks on
                # device, yields bit-identical leaf values and scores
                leaf_values = (leaf_values
                               * np.float64(np.float32(shrinkage))
                               ).astype(np.float32).astype(np.float64)
            else:
                # DART/RF/multiclass/renew configs can never fuse; keep
                # the reference's full f64 leaf outputs
                leaf_values *= shrinkage
            # device trees carry UNBIASED values when the bias was already
            # added to the scorers (gbdt); RF folds the bias into every tree
            # (rf.hpp:137) so its device values include it too
            bias = init_scores[k] if self._bias_in_every_tree else 0.0
            dev_values = leaf_values + bias
            host_values = leaf_values + init_scores[k]  # Tree::AddBias

            # host tree (from the already-fetched host copy — from_arrays
            # never reads leaf_of_row)
            ht = Tree.from_arrays(host, self.train_set.used_features,
                                  self.train_set.bin_mappers)
            if skip_tree:
                # the stump's leaf stats came from a NaN-poisoned pass —
                # zero them so the serialized tree is clean
                ht.leaf_weight[:] = 0.0
                ht.leaf_count[:] = 0
            ht.internal_value = ht.internal_value * shrinkage
            ht.shrinkage = shrinkage
            iter_trees.append(ht)

            if obs is not None:
                _sp = obs.phase("score", self.iter_)
            linear = cfg.linear_tree and nl > 1
            if linear:
                # fit per-leaf linear models on bias-free leaf values, then
                # fold the init bias in afterwards (score already has it)
                ht.leaf_value = leaf_values[:max(nl, 1)].copy()
                self._fit_linear_leaves(arrays, ht, g, h, w, shrinkage, 0.0)
                lor_np = np.asarray(arrays.leaf_of_row)
                delta = jnp.asarray(self._linear_outputs(
                    ht, lor_np, self.train_set.raw_data), jnp.float32)
                self.score = self.score.at[:, k].add(delta)
                if init_scores[k] != 0.0:
                    ht.leaf_value += init_scores[k]
                    ht.leaf_const += init_scores[k]
                lv_dev = jnp.asarray(dev_values, jnp.float32)
            else:
                ht.leaf_value = host_values[:max(nl, 1)].copy()
                # score update via row->leaf gather (no traversal needed)
                lv_dev = jnp.asarray(dev_values, jnp.float32)
                delta = jnp.take(lv_dev, arrays.leaf_of_row)
                if faultinject.enabled():
                    delta = faultinject.maybe_bitflip("score_sdc", delta)
                if check_now:
                    # covers the on-device row partition + gather that
                    # the tree-sized fetch can't see; one extra scalar
                    # sync on CHECK iterations only
                    delta = ichk.verify_score(
                        self, lv_dev, arrays.leaf_of_row, delta,
                        it_global)
                self.score = self.score.at[:, k].add(delta)
            if obs is not None:
                obs.phase_metric("score", _sp.end(self.score))
                # score-update site note (obs/flops.py) — host-side
                # arithmetic only, gated so the telemetry-off path
                # stays exactly one is-None branch
                from ..obs.flops import (note_traced,
                                         score_update_flops_bytes)
                note_traced("score",
                            *score_update_flops_bytes(self.num_data),
                            phase="score", cadence="iter")
            iter_state["train_deltas"].append(delta)

            steps = round_up_pow2(max(ht.max_depth(), 1))
            dt = _DeviceTree(arrays, dev_values, steps)
            self.device_trees.append(dt)
            self.tree_weights.append(1.0)
            iter_state["leaf_of_rows"].append(arrays.leaf_of_row)
            iter_state["leaf_values"].append(lv_dev)
            iter_state["trees"].append(dt)

            # validation score updates (per-set deltas kept so
            # rollback_one_iter removes exactly what was added, including
            # linear-leaf outputs)
            vdeltas = []
            for vi, (vds, vbinned, vscore) in enumerate(self.valid_sets):
                if linear:
                    vleaves = np.asarray(_tree_leaves(
                        vbinned, dt, self.na_bin_dev,
                        self.efb_maps))[:vds.num_data]
                    vdelta = self._linear_outputs(ht, vleaves, vds.raw_data) \
                        - (init_scores[k] if init_scores[k] != 0.0 else 0.0)
                    vdelta = np.asarray(vdelta, np.float32)
                    if len(vscore) > vds.num_data:   # row-bucketed pad
                        vdelta = np.pad(
                            vdelta, (0, len(vscore) - vds.num_data))
                    vd = jnp.asarray(vdelta, jnp.float32)
                else:
                    vd = _apply_tree(jnp.zeros_like(vscore[:, k]), vbinned,
                                     dt, self.na_bin_dev, 1.0, self.efb_maps)
                vdeltas.append(vd)
                self.valid_sets[vi] = (vds, vbinned,
                                       vscore.at[:, k].add(vd))
            iter_state["valid_deltas"].append(vdeltas)

        if heal_score:
            # a tripped skip_iter check heals the score carry too: a NaN
            # that slipped in at an UNCHECKED iteration (freq>1) would
            # otherwise re-poison every later gradient and the guard
            # would skip forever (same sanitization point as the fused
            # path — the two stay byte-identical)
            self.score = jnp.nan_to_num(self.score, nan=0.0,
                                        posinf=_FINITE_CLAMP,
                                        neginf=-_FINITE_CLAMP)
        self.models.extend(iter_trees)
        self._last_iter_state = iter_state
        self.iter_ += 1
        if obs is not None:
            # all of this iteration's trees (num_class of them) count
            # toward its step/comm accounting
            obs.iter_end(self.iter_ - 1, t_iter0,
                         sum(self.step_counts[-self.num_class:]))
        if bbox is not None:
            # one host-side record per iteration (no device syncs: all
            # fields are values the driver already holds)
            import time as _time
            steps = sum(self.step_counts[-self.num_class:])
            rec = {"iteration": self.iter_,
                   "dur_s": round(_time.perf_counter() - t_bb0, 6),
                   "steps": steps, "stopped": stopped,
                   "skipped": heal_score}
            if self._flops is not None:
                fl, hb = self._flops.per_iteration(steps)
                rec["flops"], rec["hbm_bytes"] = fl, hb
            comm = getattr(self.grower, "comm", None)
            if comm is not None:
                rec["comm_wire_bytes"] = comm.bytes_per_iteration(steps)
            bbox.record(**rec)
        return stopped

    def rollback_one_iter(self) -> None:
        """GBDT::RollbackOneIter (gbdt.cpp:451)."""
        if self.iter_ == 0 or self._last_iter_state is None:
            if self.iter_ > 0:
                from ..utils.log import Log
                Log.warning(
                    "rollback_one_iter: no per-iteration state to roll "
                    "back (last iterations ran as a fused chunk; set "
                    "fused_chunk=0 if rollback is needed)")
            return
        st = self._last_iter_state
        for k in range(self.num_class):
            self.score = self.score.at[:, k].add(-st["train_deltas"][k])
            for vi, (vds, vbinned, vscore) in enumerate(self.valid_sets):
                if vi < len(st["valid_deltas"][k]):
                    vscore = vscore.at[:, k].add(-st["valid_deltas"][k][vi])
                    self.valid_sets[vi] = (vds, vbinned, vscore)
        del self.models[-self.num_class:]
        del self.device_trees[-self.num_class:]
        del self.tree_weights[-self.num_class:]
        del self.step_counts[-self.num_class:]
        self.iter_ -= 1
        self._last_iter_state = None

    # -- scores ------------------------------------------------------------
    @property
    def num_iterations_trained(self) -> int:
        return self.iter_

    def train_score(self) -> np.ndarray:
        s = np.asarray(self.score)
        if self.config.boosting == "rf" and self.iter_ > 0:
            s = s / self.iter_
        return s

    def valid_score(self, i: int) -> np.ndarray:
        vds = self.valid_sets[i][0]
        # slice off the row-bucket padding (add_valid_set) before any
        # metric/consumer sees the scores
        s = np.asarray(self.valid_sets[i][2])[:vds.num_data]
        if self.config.boosting == "rf" and self.iter_ > 0:
            s = s / self.iter_
        return s


def create_boosting(config: Config, train_set: Dataset,
                    objective, hist_reduce=None) -> GBDTModel:
    """Boosting factory (boosting.cpp:35-68 CreateBoosting analog)."""
    if config.boosting in ("gbdt", "gbrt"):
        return GBDTModel(config, train_set, objective, hist_reduce)
    if config.boosting == "dart":
        from .dart import DARTModel
        return DARTModel(config, train_set, objective, hist_reduce)
    if config.boosting in ("rf", "random_forest"):
        from .rf import RFModel
        return RFModel(config, train_set, objective, hist_reduce)
    raise ValueError(f"Unknown boosting type: {config.boosting}")
