"""Perf ledger + flight recorder + bench regression gate (ISSUE 9).

- FlopLedger formulas vs brute-force op counts on tiny shapes;
- trace-time site registration (obs/flops.note_traced) agrees with the
  driver ledger's formulas for the shapes actually trained;
- telemetry_snapshot(): perf.* roofline keys (flops / hbm_bytes /
  achieved FLOP/s / mfu / bound), deep-copy isolation, dp == serial
  static identity, telemetry=false carries no perf keys;
- flight recorder: JSONL dump of the last-K ring on an injected
  nan_grads fault, watchdog-fire dump, serve batch-failure dump,
  zero-cost (no ring, no file) when disabled;
- tools/bench_diff.py: green on identical pairs, nonzero on a
  synthetically regressed pair, stale-pin detection, --update re-pin
  (subprocess, the test_zretrace lint mold);
- Prometheus text exposition of the metrics snapshot + the serve
  ``/metrics?format=prom`` endpoint.
"""

import json
import os
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs.flops import (FlopLedger, hist_flops_bytes,
                                    padded_bins, partition_flops_bytes,
                                    score_update_flops_bytes,
                                    split_scan_flops_bytes,
                                    traced_sites,
                                    train_hist_flops_per_iter)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH_DIFF = os.path.join(REPO, "tools", "bench_diff.py")

sys.path.insert(0, os.path.join(REPO, "tools"))


def _small_data(n=1200, f=8, seed=3):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, f)
    y = (x[:, 0] - 0.5 * x[:, 1] > 0).astype(np.float32)
    return x, y


def _train(params, n_iter=3, x=None, y=None):
    if x is None:
        x, y = _small_data()
    base = {"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 5,
            "verbosity": 0, "fused_chunk": 0, "max_bin": 31,
            "tpu_learner": "masked"}
    base.update(params)
    ds = lgb.Dataset(x, label=y, params=base)
    ds.construct()
    bst = lgb.Booster(params=base, train_set=ds)
    for _ in range(n_iter):
        bst.update()
    return bst


# -- formulas vs brute force -----------------------------------------------

class TestFlopFormulas:
    def test_padded_bins_matches_hist_kernel_policy(self):
        # ops/histogram.py pads the bin axis to max(64, ceil(B/64)*64)
        assert padded_bins(15) == 64
        assert padded_bins(63) == 64
        assert padded_bins(64) == 64
        assert padded_bins(65) == 128
        assert padded_bins(255) == 256

    def test_hist_flops_match_brute_force(self):
        n, f, b, c = 5, 3, 7, 3
        flops, hbm = hist_flops_bytes(n, f, b, channels=c,
                                      binned_itemsize=1)
        # the one-hot contraction is 2 FLOPs (mul + add) per
        # (row, column, padded bin, channel) cell
        count = 0
        for _ in range(n):
            for _ in range(f):
                for _ in range(padded_bins(b)):
                    for _ in range(c):
                        count += 2
        assert flops == count
        # bytes: binned read + (g, h, w) read + histogram write
        assert hbm == n * f * 1 + n * 3 * 4 + c * f * padded_bins(b) * 4

    def test_hist_slot_expansion_accounts_slot_vector(self):
        _, hbm3 = hist_flops_bytes(10, 2, 7, channels=3)
        _, hbm6 = hist_flops_bytes(10, 2, 7, channels=6)
        # the [N] int32 slot vector rides only the multi-slot pass
        assert hbm6 - hbm3 == 10 * 4 + 3 * 2 * padded_bins(7) * 4

    def test_score_and_partition_match_brute_force(self):
        n = 11
        flops, hbm = score_update_flops_bytes(n)
        count = sum(2 for _ in range(n))   # gather + add per row
        assert flops == count
        assert hbm == n * 4 + 2 * n * 4
        pf, pb = partition_flops_bytes(n, binned_itemsize=2)
        assert pf == 5 * n
        assert pb == n * 2 + 2 * n * 4

    def test_train_hist_flops_per_iter_is_the_bench_formula(self):
        # the formula bench.py used to carry privately:
        # 2 * 3 * n * F * Bp * (leaves - 1)
        assert train_hist_flops_per_iter(1000, 28, 63, 31) == \
            2.0 * 3 * 1000 * 28 * 64 * 30

    def test_ledger_per_iteration_and_share(self):
        led = FlopLedger.for_training(100, 4, 15, split_batch=2)
        sites = {s.site: s for s in led.sites()}
        assert set(sites) == {"hist", "hist_root", "split_scan",
                              "split_root", "partition", "score"}
        steps = 3
        f, b = led.per_iteration(steps)
        manual_f = sum(s.flops * (steps if s.cadence == "step" else 1)
                       for s in led.sites())
        assert f == manual_f and f > 0 and b > 0
        share = led.flop_share(steps)
        assert abs(sum(share.values()) - 1.0) < 0.01
        # the histogram contraction dominates by construction
        assert share["hist"] == max(share.values())


# -- trace-time registration agrees with the formulas ----------------------

class TestTracedSites:
    def test_call_sites_register_traced_shapes(self):
        # distinctive shapes force fresh traces even late in the suite
        x, y = _small_data(n=1237, f=9, seed=11)
        bst = _train({"num_leaves": 6, "max_bin": 37}, n_iter=1, x=x, y=y)
        m = bst._model
        ts = traced_sites()
        for site in ("hist", "split_scan", "partition"):
            assert site in ts, f"site {site!r} never registered"
        itemsize = int(m.binned_dev.dtype.itemsize)
        # the last-traced hist note is the smaller-child pass; under
        # the default hist_overlap its 1-slot mask is accounted as the
        # masked pass it is byte-identical to (num_slots == 1 adds no
        # slot-operand bytes — obs/flops.hist_flops_bytes convention)
        exp_f, exp_b = hist_flops_bytes(
            m.num_data, int(m.binned_dev.shape[1]), m.max_bin,
            channels=3, binned_itemsize=itemsize)
        assert ts["hist"].flops == exp_f
        assert ts["hist"].hbm_bytes == exp_b
        assert ts["partition"].flops == \
            partition_flops_bytes(m.num_data, itemsize)[0]
        assert ts["split_scan"].flops == \
            split_scan_flops_bytes(m.num_features, m.max_bin, 1)[0]
        # ...and they agree with the driver-side ledger formulas
        led = FlopLedger.for_training(
            m.num_data, m.num_features, m.max_bin, split_batch=1,
            binned_itemsize=itemsize)
        sites = {s.site: s for s in led.sites()}
        assert sites["hist_root"].flops == ts["hist"].flops
        assert sites["partition"].flops == ts["partition"].flops


# -- perf.* roofline keys ---------------------------------------------------

class TestPerfSnapshot:
    PEAKS = {"telemetry_peak_flops": 1e12, "telemetry_peak_hbm_gbs": 100.0}

    def test_perf_keys_with_explicit_peaks(self):
        bst = _train(dict(self.PEAKS, telemetry=True), n_iter=3)
        snap = bst.telemetry_snapshot()
        for ph in ("grow", "score", "total"):
            assert snap[f"perf.{ph}.flops"] > 0
            assert snap[f"perf.{ph}.hbm_bytes"] > 0
            assert snap[f"perf.{ph}.seconds"] > 0
            assert snap[f"perf.{ph}.flops_per_s"] > 0
            assert snap[f"perf.{ph}.mfu"] > 0
            assert snap[f"perf.{ph}.bound"] in ("compute", "memory")
        assert snap["perf.total.flops"] == \
            snap["perf.grow.flops"] + snap["perf.score.flops"]
        assert snap["perf.device.peak_flops_per_s"] == 1e12
        assert snap["perf.device.peak_hbm_bytes_per_s"] == 100e9
        # the flops.* counters backing the join are in the snapshot too
        assert any(k.startswith("flops.total{") for k in snap)

    def test_snapshot_is_a_deep_copy(self):
        bst = _train(dict(self.PEAKS, telemetry=True), n_iter=2)
        snap = bst.telemetry_snapshot()
        before = json.dumps(bst.telemetry_snapshot(), sort_keys=True)
        # mutate scalars, nested dicts and nested lists of the copy
        snap["train.iterations"]["value"] = 1e9
        snap["train.steps_per_tree"]["counts"][0] = 12345
        snap["perf.grow.flops"] = -1
        snap.clear()
        after = json.dumps(bst.telemetry_snapshot(), sort_keys=True)
        assert before == after

    def test_dp_equals_serial_static_perf(self):
        import jax
        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device CPU mesh")
        x, y = _small_data(1600)
        serial = _train(dict(self.PEAKS, telemetry=True), n_iter=3,
                        x=x, y=y)
        dp = _train(dict(self.PEAKS, telemetry=True, tree_learner="data",
                         split_batch=1), n_iter=3, x=x, y=y)
        s_snap, d_snap = (serial.telemetry_snapshot(),
                          dp.telemetry_snapshot())
        # static accounting (logical global shapes x identical trees)
        # must agree byte-for-byte; achieved rates legitimately differ
        static = [k for k in s_snap
                  if k.startswith("flops.")
                  or k.endswith((".flops", ".hbm_bytes"))]
        assert static
        for k in static:
            assert s_snap[k] == d_snap[k], k

    def test_telemetry_off_has_no_perf_keys(self):
        bst = _train({}, n_iter=1)
        snap = bst.telemetry_snapshot()
        assert not any(k.startswith(("perf.", "flops.")) for k in snap)


# -- flight recorder --------------------------------------------------------

class TestFlightRecorder:
    def test_nan_grads_fault_dumps_last_k(self, tmp_path):
        from lightgbm_tpu.obs.trace import read_jsonl
        from lightgbm_tpu.utils import faultinject
        path = str(tmp_path / "bb.jsonl")
        faultinject.configure("nan_grads:3")
        try:
            bst = _train({"finite_check_freq": 1,
                          "finite_check_policy": "skip_iter",
                          "telemetry_blackbox": True,
                          "telemetry_blackbox_path": path,
                          "telemetry_blackbox_last_k": 8}, n_iter=4)
        finally:
            faultinject.clear()
        assert bst.current_iteration == 4    # skip_iter keeps training
        assert os.path.exists(path)
        events = read_jsonl(path)
        header, records = events[0], events[1:]
        assert header["blackbox"] is True
        assert header["reason"] == "finite_check"
        assert header["n_records"] == len(records)
        # the ring held the two clean iterations plus the trip event
        assert [r.get("iteration") for r in records] == [1, 2, 3]
        assert records[-1]["event"] == "finite_check_trip"
        assert all("dur_s" in r for r in records[:-1])
        bst._model._bbox.close()

    def test_disabled_is_zero_cost(self, tmp_path):
        bst = _train({"output_model": str(tmp_path / "m.txt")}, n_iter=1)
        assert bst._model._bbox is None      # no ring allocation
        assert not os.path.exists(str(tmp_path / "m.txt.blackbox.jsonl"))

    def test_ring_is_bounded_to_last_k(self, tmp_path):
        from lightgbm_tpu.obs.blackbox import FlightRecorder
        from lightgbm_tpu.obs.trace import read_jsonl
        rec = FlightRecorder(str(tmp_path / "r.jsonl"), last_k=3)
        for i in range(10):
            rec.record(iteration=i)
        rec.dump("test")
        events = read_jsonl(str(tmp_path / "r.jsonl"))
        assert [e["iteration"] for e in events[1:]] == [7, 8, 9]
        rec.close()

    def test_watchdog_fire_dumps_live_recorders(self, tmp_path):
        from lightgbm_tpu.obs.blackbox import FlightRecorder
        from lightgbm_tpu.obs.trace import read_jsonl
        from lightgbm_tpu.utils.resilience import Watchdog
        rec = FlightRecorder(str(tmp_path / "w.jsonl"), last_k=4)
        rec.record(iteration=1)
        try:
            with open(os.devnull, "w") as devnull:
                with Watchdog(0.1, label="wedge-sim", file=devnull):
                    time.sleep(0.5)          # outlive the timeout
        finally:
            rec.close()
        assert os.path.exists(str(tmp_path / "w.jsonl"))
        header = read_jsonl(str(tmp_path / "w.jsonl"))[0]
        assert header["reason"].startswith("watchdog")

    def test_serve_batch_failure_dumps(self, tmp_path):
        from lightgbm_tpu.serve.server import Server
        from lightgbm_tpu.utils import faultinject
        path = str(tmp_path / "serve_bb.jsonl")
        bst = _train({}, n_iter=2)
        srv = Server(params={"verbosity": 0, "serve_retries": 0,
                             "serve_breaker_failures": 0,
                             "telemetry_blackbox": True,
                             "telemetry_blackbox_path": path},
                     booster=bst)
        x, _ = _small_data(4)
        try:
            assert len(srv.predict(x)) == 4   # healthy batch recorded
            faultinject.configure("serve_batch:1-10")
            with pytest.raises(Exception):
                srv.predict(x)
        finally:
            faultinject.clear()
            srv.close()
        assert os.path.exists(path)
        from lightgbm_tpu.obs.trace import read_jsonl
        events = read_jsonl(path)
        assert events[0]["reason"] == "serve_batch_failure"
        assert any(r.get("event") == "batch_error" for r in events[1:])


# -- bench_diff perf gate ---------------------------------------------------

def _bench_rec(value=100.0, extra=None):
    return {"metric": "higgs1m_binary_train_iters_per_sec",
            "value": value, "unit": "iters/s", "vs_baseline": 1.0,
            "extra": {"serve_p99_ms": 5.0} if extra is None else extra}


class TestBenchDiff:
    def _run(self, *args, timeout=120):
        return subprocess.run([sys.executable, BENCH_DIFF, *args],
                              capture_output=True, text=True,
                              timeout=timeout, cwd=REPO)

    def _files(self, tmp_path, old, new, budget_text):
        op, np_, bp = (str(tmp_path / n)
                       for n in ("old.json", "new.json", "budget.txt"))
        with open(op, "w") as f:
            json.dump(old, f)
        with open(np_, "w") as f:
            json.dump(new, f)
        with open(bp, "w") as f:
            f.write(budget_text)
        return op, np_, bp

    BUDGET = "value = higher 0.1\nserve_p99_ms = lower 0.2\n"

    def test_identical_pair_is_green(self, tmp_path):
        op, np_, bp = self._files(tmp_path, _bench_rec(), _bench_rec(),
                                  self.BUDGET)
        out = self._run(np_, op, "--budget", bp)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "perf gate: clean" in out.stdout

    def test_regressed_pair_exits_nonzero(self, tmp_path):
        op, np_, bp = self._files(
            tmp_path, _bench_rec(100.0),
            _bench_rec(80.0, extra={"serve_p99_ms": 9.0}), self.BUDGET)
        out = self._run(np_, op, "--budget", bp)
        assert out.returncode == 1
        assert "regression: value" in out.stderr
        assert "regression: serve_p99_ms" in out.stderr

    def test_within_tolerance_noise_passes(self, tmp_path):
        op, np_, bp = self._files(
            tmp_path, _bench_rec(100.0),
            _bench_rec(91.0, extra={"serve_p99_ms": 5.9}), self.BUDGET)
        out = self._run(np_, op, "--budget", bp)
        assert out.returncode == 0, out.stderr

    def test_stale_pin_and_disappeared_metric(self, tmp_path):
        op, np_, bp = self._files(
            tmp_path, _bench_rec(), _bench_rec(extra={}),
            self.BUDGET + "ghost_metric = higher 0.1\n")
        out = self._run(np_, op, "--budget", bp)
        assert out.returncode == 1
        assert "stale budget entry" in out.stderr
        assert "metric disappeared: serve_p99_ms" in out.stderr

    def test_update_repins_and_goes_green(self, tmp_path):
        rec = _bench_rec(
            120.0, extra={"serve_p99_ms": 4.0, "serve_rows_per_s": 9e4,
                          "higgs1m_255leaf_iters_per_sec": 2.5,
                          "higgs1m_255leaf_auc": 0.97})
        op, np_, bp = self._files(tmp_path, rec, rec, self.BUDGET)
        out = self._run(np_, "--budget", bp, "--update")
        assert out.returncode == 0, out.stderr
        from bench_diff import load_budget
        pins = load_budget(bp)
        assert pins["value"] == ("higher", 0.1)          # kept
        assert pins["serve_p99_ms"] == ("lower", 0.2)    # kept
        assert pins["serve_rows_per_s"][0] == "higher"   # auto-added
        assert pins["higgs1m_255leaf_iters_per_sec"][0] == "higher"
        assert "higgs1m_255leaf_auc" not in pins         # not gateable
        out = self._run(np_, op, "--budget", bp)
        assert out.returncode == 0, out.stderr

    def test_shipped_budget_parses_and_pins_the_primary(self):
        from bench_diff import BUDGET as REAL, load_budget
        pins = load_budget(REAL)
        assert pins.get("value", ("", 0))[0] == "higher"
        assert any(d == "lower" for d, _ in pins.values())


# -- Prometheus exposition --------------------------------------------------

class TestPrometheus:
    def test_prometheus_text_rendering(self):
        from lightgbm_tpu.obs.metrics import (MetricsRegistry,
                                              prometheus_text)
        r = MetricsRegistry()
        r.counter("serve.rows").inc(42)
        r.gauge("serve.breaker_state", state="closed").set(0)
        r.histogram("serve.latency", buckets=(0.1, 1.0)).observe(0.5)
        snap = dict(r.snapshot())
        snap["perf.grow.mfu"] = 0.25
        snap["perf.grow.bound"] = "memory"
        snap["compile.count"] = 3
        snap["serve.engine"] = {"steps": 4, "num_trees": 7, "sig": "ab"}
        text = prometheus_text(snap)
        assert "# TYPE serve_rows counter" in text
        assert "serve_rows 42.0" in text
        assert 'serve_breaker_state{state="closed"} 0.0' in text
        assert "# TYPE serve_latency histogram" in text
        assert 'serve_latency_bucket{le="0.1"} 0' in text
        assert 'serve_latency_bucket{le="1.0"} 1' in text
        assert 'serve_latency_bucket{le="+Inf"} 1' in text
        assert "serve_latency_sum 0.5" in text
        assert "serve_latency_count 1" in text
        assert "perf_grow_mfu 0.25" in text
        assert 'perf_grow_bound{value="memory"} 1.0' in text
        assert "compile_count 3.0" in text
        assert "serve_engine_steps 4.0" in text      # flattened dict
        assert "sig" not in text                      # non-numeric leaf

    def test_http_metrics_prom_endpoint(self):
        from lightgbm_tpu.serve.server import Server, start_http
        bst = _train({}, n_iter=2)
        srv = Server(params={"verbosity": 0}, booster=bst)
        http = start_http(srv, port=0)
        try:
            x, _ = _small_data(8)
            srv.predict(x)
            url = f"http://127.0.0.1:{http.port}/metrics?format=prom"
            with urllib.request.urlopen(url, timeout=10) as resp:
                ctype = resp.headers.get("Content-Type", "")
                body = resp.read().decode()
            assert ctype.startswith("text/plain")
            assert "# TYPE serve_rows counter" in body
            assert "serve_rows 8.0" in body
            assert "perf_forest_flops_per_row" in body
            # the JSON default is untouched
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{http.port}/metrics",
                    timeout=10) as resp:
                snap = json.loads(resp.read())
            assert "perf.forest.flops_per_row" in snap
        finally:
            http.close()
            srv.close()
