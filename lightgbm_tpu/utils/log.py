"""Logging (reference: include/LightGBM/utils/log.h:71-177).

Level-filtered logger with a pluggable sink callback
(``LGBM_RegisterLogCallback`` analog, c_api.h:71) — the python-package
redirects to the ``logging`` module (basic.py:49-110), which is the default
sink here.
"""

from __future__ import annotations

import logging
import sys
from typing import Callable, Optional

_logger = logging.getLogger("lightgbm_tpu")
_callback: Optional[Callable[[str], None]] = None


class Log:
    """Log::Debug/Info/Warning/Fatal (log.h)."""
    level: int = 1  # -1 fatal only, 0 +warning, 1 +info, 2 +debug

    @classmethod
    def set_verbosity(cls, verbosity: int) -> None:
        """Map a Config ``verbosity`` (alias ``verbose``) to the level,
        with reference semantics (config.h / Log::ResetLogLevel): <0
        fatal-only, 0 warnings, 1 info, >=2 debug."""
        v = int(verbosity)
        cls.level = -1 if v < 0 else min(v, 2)

    @staticmethod
    def _emit(msg: str, py_level: int) -> None:
        if _callback is not None:
            _callback(msg + "\n")
        else:
            _logger.log(py_level, msg)
            if not _logger.handlers and not logging.getLogger().handlers:
                print(msg, file=sys.stderr)

    @classmethod
    def debug(cls, msg: str) -> None:
        if cls.level >= 2:
            cls._emit(f"[LightGBM-TPU] [Debug] {msg}", logging.DEBUG)

    @classmethod
    def info(cls, msg: str) -> None:
        if cls.level >= 1:
            cls._emit(f"[LightGBM-TPU] [Info] {msg}", logging.INFO)

    @classmethod
    def warning(cls, msg: str) -> None:
        if cls.level >= 0:
            cls._emit(f"[LightGBM-TPU] [Warning] {msg}", logging.WARNING)

    @classmethod
    def fatal(cls, msg: str) -> None:
        cls._emit(f"[LightGBM-TPU] [Fatal] {msg}", logging.ERROR)
        raise RuntimeError(msg)


def register_log_callback(cb: Optional[Callable[[str], None]]) -> None:
    """LGBM_RegisterLogCallback analog."""
    global _callback
    _callback = cb


def register_logger(logger, info_method_name: str = "info",
                    warning_method_name: str = "warning") -> None:
    """Route log lines to a caller-supplied logger object
    (python-package basic.py:49 register_logger contract: Info-level
    lines go to ``info_method_name``, warnings to
    ``warning_method_name``)."""
    info = getattr(logger, info_method_name)
    warn = getattr(logger, warning_method_name)

    def _cb(msg: str) -> None:
        line = msg.rstrip("\n")
        if "[Warning]" in line or "[Fatal]" in line:
            warn(line)
        else:
            info(line)

    register_log_callback(_cb)
