from .histogram import compute_histogram, hist_block_rows, HIST_BLOCK_ROWS
from .split import find_best_split, SplitParams
