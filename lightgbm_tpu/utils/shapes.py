"""Shared shape-bucketing policy for trace-relevant static dimensions.

Every distinct static shape that reaches a jitted program is a fresh
XLA trace + compile — BENCH_r02 measured 73 s of compile before the
first training iteration, rivaling 99 iterations of steady state
(ROADMAP item 4).  ``serve/engine.py`` already proved the fix for the
serving batch axis: round the dimension up to a power-of-two bucket so
one trace covers a family of sizes.  This module is that policy
extracted so every layer buckets the same way:

- **rows** (serve batches, validation sets): power-of-two with a floor,
  so tiny sizes share one shape instead of one per pow2 below it
  (:func:`bucket_rows`).
- **leaf budget** (the grower's ``num_leaves``): power-of-two with a
  floor of ``LEAF_BUCKET_FLOOR`` — the grower's ``lax.while_loop``
  exits on the *actual* budget (a traced scalar), so ``num_leaves``
  31 / 40 / 63 all run the same ``L=64``-shaped program with
  bit-identical output (:func:`bucket_leaves`, grower.py).
- **split_batch**: pinned to the shipped ``{1, 8, 16, 32, 64}`` set
  (:func:`snap_split_batch`) — the auto-tuner (ops/hist_tune.py) only
  ever picks from it, and snapping explicit odd values keeps the
  super-step trace family closed (K is a structural constant of the
  trace, it cannot be made dynamic the way the leaf budget can).
- **histogram channel axis** (the contraction's slot-expanded C = 3·K
  channels): widths past the shipped C=48 ceiling pad to MXU lane
  multiples of 128 (:func:`bucket_channels`) so the ``[block, C]``
  accumuland operand lands on full 128-lane tiles — padded channels
  belong to slots no row carries, accumulate exact zeros, and are
  sliced off inside the kernel (ops/histogram.py), so the pad costs
  MXU cycles only, never numerics.
- **serve SoA dimensions** (node slots, leaf slots, traversal steps):
  power-of-two with floors (:func:`bucket_nodes`,
  :func:`bucket_leaf_slots`, :func:`bucket_steps`) so two co-hosted
  model versions of one family (hot-swap / shadow, serve/registry.py)
  land on IDENTICAL SoA shapes and share every compiled serve trace —
  a retrained model whose deepest tree moved from 13 to 15 nodes must
  not re-trace the fused serve program.  Node/leaf padding costs
  memory only (padded slots are never gathered); the steps floor costs
  up to ``floor - 1`` no-op level walks for very shallow forests
  (:func:`bucket_steps` documents the tradeoff).

The retrace-budget lint (tools/check_retraces.py) pins the trace
counts this policy produces; changing a bucket boundary is a conscious
act that updates tools/retrace_budget.txt.
"""

from __future__ import annotations

# floor of the leaf-budget bucket: the common LightGBM budgets 31..63
# (default 31) collapse onto one L=64 trace; 127 -> 128, 255 -> 256.
# Below the floor the padded state costs (hist [L, F, B, 3] carry) stay
# small in absolute terms while the trace family shrinks drastically.
LEAF_BUCKET_FLOOR = 64

# the shipped split_batch widths (grower super-step K): 1 = strict
# leaf-wise reference growth, 8/16 = the measured MXU-sublane sweet
# spots (PROFILE.md §2-6; models/gbdt.py auto-selection), 32/64 = the
# lane-padded wide widths (ROADMAP item 1: C = 3K channels bucket to
# 128-lane tiles, ops/histogram.py) the on-device autotuner
# (ops/hist_tune.py) selects from by measured ms/pass
SPLIT_BATCH_SET = (1, 8, 16, 32, 64)

# channel widths up to the pre-widening ceiling (C = 3·16 = 48, the
# largest shipped slot expansion before K ∈ {32, 64} existed) keep
# their exact un-padded shapes: their histograms are regression-pinned
# byte-identical, and at ≤ 48 channels the sublane mapping measured
# fine (ops/histogram.py orientation note)
HIST_CHANNEL_EXACT_MAX = 48
# MXU lane width the wide channel axis pads to
HIST_CHANNEL_LANE = 128


def round_up_pow2(x: int) -> int:
    """Smallest power of two >= x (>= 1)."""
    p = 1
    while p < x:
        p *= 2
    return p


def _pow2_floor(n: int, floor: int) -> int:
    """THE bucketing rule every dimension policy below delegates to:
    pow2 with a floor.  Change it here, nowhere else."""
    return max(int(floor), round_up_pow2(max(int(n), 1)))


def bucket_rows(n: int, min_bucket: int = 16, cap: int | None = None) -> int:
    """Pow2 row bucket with a floor (and an optional pow2'd cap) —
    the serve/engine.py batch policy, shared."""
    b = _pow2_floor(n, min_bucket)
    if cap is not None:
        b = min(b, round_up_pow2(int(cap)))
    return b


def bucket_leaves(num_leaves: int, floor: int = LEAF_BUCKET_FLOOR) -> int:
    """Padded leaf budget covering ``num_leaves``: pow2 with a floor.

    31 / 40 / 63 -> 64; 127 -> 128; 255 -> 256.  The grower exits its
    while_loop on the ACTUAL budget, so the padded slots only cost
    state memory, never semantics (grower.py ``max_leaves``)."""
    return _pow2_floor(num_leaves, floor)


def bucket_nodes(n: int, floor: int = 16) -> int:
    """Padded per-tree node-slot count for the serve SoA tables: pow2
    with a floor.  Padded node rows are never reached by traversal
    (children pad to -1), so the cost is table memory only."""
    return _pow2_floor(n, floor)


def bucket_leaf_slots(n: int, floor: int = 8) -> int:
    """Padded per-tree leaf-slot count for the serve leaf-value table:
    pow2 with a floor; padded slots hold 0.0 and are never gathered."""
    return _pow2_floor(n, floor)


def bucket_bins(n: int, floor: int = 16) -> int:
    """Padded device bin-table width (per-feature threshold slots /
    known-category slots, serve/engine.py ``_device_bin_tables``): pow2
    with a floor.  Pad slots hold +inf, so every comparison against
    them is false — a retrained co-hosted version whose threshold
    count moved from 40 to 55 must not re-trace the fused serve
    program."""
    return _pow2_floor(n, floor)


def bucket_steps(depth: int, floor: int = 8) -> int:
    """Padded traversal step count (forest max depth): pow2 with a
    floor.  Finished rows carry their leaf id unchanged through the
    padded levels, so extra steps change cost, never results.  The
    floor keeps co-hosted versions whose depths jitter in the common
    shallow range (3..8) on ONE trace; the price is up to ``floor - 1``
    no-op level walks for very shallow forests (a depth-2 forest walks
    8 levels instead of 2) — accepted because sub-floor forests are
    tiny workloads and the trace-sharing win compounds per version."""
    return _pow2_floor(depth, floor)


def traversal_steps(max_depth: int, leaf_budget: int) -> int:
    """Static per-tree traversal step budget for the fused super-epoch
    (models/gbdt.py train_superepoch): the in-scan valid-set traversal
    cannot size its fori_loop from the grown tree's ACTUAL depth (a
    traced value), so it walks a config-derived worst case — max_depth
    when bounded, else ``leaf_budget - 1`` (a leaf-wise tree with L
    leaves is at most L-1 deep).  Finished rows carry their leaf id
    unchanged through the surplus levels
    (predict_device.traverse_tree_binned), so padding costs cycles
    only, never numerics; bounding max_depth is the perf lever when
    the leaf budget is large."""
    cap = int(max_depth) if int(max_depth) > 0 else max(int(leaf_budget) - 1, 1)
    return round_up_pow2(max(cap, 1))


def bucket_channels(c: int) -> int:
    """Padded histogram-contraction channel width for a slot-expanded
    C = cv·K axis: exact up to ``HIST_CHANNEL_EXACT_MAX`` (the shipped
    pre-widening widths stay byte-identical down to the trace shape),
    then the next ``HIST_CHANNEL_LANE`` multiple — K=32 (C=96) pads to
    128, K=64 (C=192) to 256.  The pad columns are zero (no slot maps
    to them) and sliced off in-kernel; obs/flops.py excludes their
    FLOPs from MFU accounting (they are not useful work) while the
    autotuner measures their real cost."""
    c = int(c)
    if c <= HIST_CHANNEL_EXACT_MAX:
        return c
    return -(-c // HIST_CHANNEL_LANE) * HIST_CHANNEL_LANE


def snap_split_batch(k: int) -> int:
    """Nearest shipped super-step width >= the request (capped at the
    largest shipped width); 0/1 pass through untouched."""
    k = int(k)
    if k <= 1:
        return k
    for s in SPLIT_BATCH_SET:
        if k <= s:
            return s
    return SPLIT_BATCH_SET[-1]


def fit_split_batch(k: int, num_leaves: int) -> int:
    """Snap a super-step width into the shipped set AND under the leaf
    budget: the grower can never split more than ``num_leaves - 1``
    leaves in one step, so a width past the budget steps DOWN the set
    (num_leaves=31 at K=32 runs K=16) instead of clamping to an
    off-set width that would open a private trace family — K is a
    structural constant of the grower trace, and leaf-budget padding
    must never change it (padded and exact-shape growers of one config
    train byte-identical trees)."""
    k = snap_split_batch(k)
    cap = int(num_leaves) - 1
    if k <= cap:
        return k
    fit = 1
    for s in SPLIT_BATCH_SET:
        if s <= cap:
            fit = s
    return fit
