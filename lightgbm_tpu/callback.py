"""Training callbacks (reference: python-package/lightgbm/callback.py:15-356).

Same surface: ``log_evaluation``, ``record_evaluation``, ``reset_parameter``,
``early_stopping``; early stopping signals via ``EarlyStopException`` caught
by the train loop (engine.py:252 pattern).
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List

CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


class EarlyStopException(Exception):
    def __init__(self, best_iteration: int, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


def _fmt_eval(res, show_stdv: bool = True) -> str:
    if len(res) == 4:
        name, metric, value, _ = res
        return f"{name}'s {metric}: {value:g}"
    # cv 5-tuple (callback.py _format_eval_result cv branch)
    _, key, mean, _hib, stdv = res
    if show_stdv:
        return f"cv_agg's {key}: {mean:g} + {stdv:g}"
    return f"cv_agg's {key}: {mean:g}"


def log_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list \
                and (env.iteration + 1) % period == 0:
            msg = "\t".join(_fmt_eval(r, show_stdv)
                            for r in env.evaluation_result_list)
            print(f"[{env.iteration + 1}]\t{msg}")
    _callback.order = 10
    # pure function of the CallbackEnv — the super-epoch replay
    # (engine.py) can feed it fetched eval rows after the fact and the
    # output is identical to the per-iteration path
    _callback._replayable = True
    return _callback


def record_evaluation(eval_result: Dict) -> Callable:
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result must be a dict")

    def _callback(env: CallbackEnv) -> None:
        for item in env.evaluation_result_list:
            if len(item) == 4:
                name, metric, value = item[0], item[1], item[2]
                eval_result.setdefault(name, collections.OrderedDict())
                eval_result[name].setdefault(metric, []).append(value)
            else:
                # cv 5-tuple ('cv_agg', '<set> <metric>', mean, hib,
                # stdv) — recorded as {set: {metric-mean: [...],
                # metric-stdv: [...]}} (reference callback.py:111-136)
                dsname, metric = item[1].split(" ", 1)
                eval_result.setdefault(dsname, collections.OrderedDict())
                eval_result[dsname].setdefault(f"{metric}-mean",
                                               []).append(item[2])
                eval_result[dsname].setdefault(f"{metric}-stdv",
                                               []).append(item[4])
    _callback.order = 20
    # env-pure: replayable from a super-epoch's fetched eval block
    _callback._replayable = True
    return _callback


def log_telemetry(period: int = 10, collect: Dict = None) -> Callable:
    """Log (and optionally collect) obs metrics snapshots during
    training (docs/Observability.md).  Every ``period`` iterations the
    booster's aggregated snapshot is summarized via ``Log.info`` —
    iteration count, mean per-phase milliseconds, cumulative comm wire
    bytes — and, when ``collect`` is given, stored whole under the
    1-based iteration number.  A no-op unless ``telemetry=true``."""

    def _summary(snap: Dict) -> str:
        parts = []
        it = snap.get("train.iterations")
        if it:
            parts.append(f"iters={it['value']:g}")
        for key, rec in snap.items():
            if key.startswith("train.phase_seconds{") \
                    and rec.get("count"):
                phase = key.split("phase=", 1)[1].rstrip("}")
                parts.append(
                    f"{phase}={rec['sum'] / rec['count'] * 1e3:.1f}ms")
        wire = sum(rec["value"] for key, rec in snap.items()
                   if key.startswith("comm.wire_bytes{"))
        if wire:
            parts.append(f"comm={wire / 1e6:.2f}MB")
        return " ".join(parts) or "(no telemetry data)"

    def _callback(env: CallbackEnv) -> None:
        if period <= 0 or (env.iteration + 1) % period != 0:
            return
        boosters = getattr(env.model, "boosters", None) or [env.model]
        many = len(boosters) > 1          # cv: one snapshot per fold
        for bi, bst in enumerate(boosters):
            snap_fn = getattr(bst, "telemetry_snapshot", None)
            snap = snap_fn() if snap_fn is not None else {}
            if not snap or all(k.startswith("compile.") for k in snap):
                # telemetry=false: the snapshot still carries the
                # process-wide compile accounting (docs/Compile-Cache.md)
                # but there is nothing iteration-scoped to log
                continue
            if collect is not None:
                if many:
                    collect.setdefault(env.iteration + 1, []).append(snap)
                else:
                    collect[env.iteration + 1] = snap
            from .utils.log import Log
            tag = f" fold {bi}" if many else ""
            Log.info(f"[telemetry] [{env.iteration + 1}]{tag} "
                     f"{_summary(snap)}")
    _callback.order = 40
    return _callback


def reset_parameter(**kwargs) -> Callable:
    """Per-iteration parameter schedule; supports ``learning_rate`` as a
    list or ``f(iteration) -> value`` (callback.py reset_parameter)."""

    def _callback(env: CallbackEnv) -> None:
        it = env.iteration - env.begin_iteration
        # cv passes the CVBooster container — the schedule applies to
        # every fold (the reference's _reset_parameter_callback does the
        # same CVBooster fan-out)
        boosters = getattr(env.model, "boosters", None) or [env.model]
        for key, value in kwargs.items():
            new_val = value[it] if isinstance(value, list) else value(it)
            for bst in boosters:
                if key == "learning_rate":
                    bst._model.learning_rate = new_val
                else:
                    setattr(bst._model.config, key, new_val)
    _callback.before_iteration = True
    _callback.order = 10
    return _callback


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True, min_delta: float = 0.0) -> Callable:
    best_score: List[float] = []
    best_iter: List[int] = []
    best_score_list: List[list] = []
    cmp_op: List[Callable] = []
    enabled = [True]
    first_metric = [""]
    warned_nonfinite = [False]

    def _metric_of(item) -> str:
        # cv 5-tuples carry '<set> <metric>' as the key
        m = item[1]
        return m.split(" ", 1)[1] if item[0] == "cv_agg" and " " in m else m

    def _init(env: CallbackEnv) -> None:
        enabled[0] = bool(env.evaluation_result_list)
        if not enabled[0]:
            return
        best_score.clear(), best_iter.clear()
        best_score_list.clear(), cmp_op.clear()
        first_metric[0] = _metric_of(
            env.evaluation_result_list[0]).split("@")[0]
        # per-metric deltas (callback.py _EarlyStoppingCallback): a list
        # gives one delta per UNIQUE metric (broadcast over datasets),
        # a scalar applies everywhere; negatives are rejected
        uniq = []
        for item in env.evaluation_result_list:
            m = _metric_of(item)
            if m not in uniq:
                uniq.append(m)
        if isinstance(min_delta, (list, tuple)):
            deltas = [float(d) for d in min_delta]
            if any(d < 0 for d in deltas):
                raise ValueError("Values for early stopping min_delta "
                                 "must be non-negative.")
            if len(deltas) != len(uniq):
                raise ValueError("Must provide a single value for "
                                 "min_delta or as many as metrics.")
            delta_of = dict(zip(uniq, deltas))
        else:
            if float(min_delta) < 0:
                raise ValueError("Early stopping min_delta must be "
                                 "non-negative.")
            delta_of = {m: float(min_delta) for m in uniq}
        for item in env.evaluation_result_list:
            higher_better = item[3]
            d = delta_of[_metric_of(item)]
            best_iter.append(0)
            best_score_list.append(None)
            if higher_better:
                best_score.append(float("-inf"))
                cmp_op.append(
                    lambda new, best, _d=d: new > best + _d)
            else:
                best_score.append(float("inf"))
                cmp_op.append(
                    lambda new, best, _d=d: new < best - _d)

    def _callback(env: CallbackEnv) -> None:
        if not best_score:
            _init(env)
        if not enabled[0]:
            return
        import math
        for i, item in enumerate(env.evaluation_result_list):
            name, val = item[0], item[2]
            metric = _metric_of(item)
            # a non-finite metric is NEVER an improvement: the reference
            # (and this loop, before the fix) recorded the FIRST value
            # unconditionally, so an early NaN/Inf became an unbeatable
            # best score and poisoned the whole early-stopping run
            finite = val is not None and math.isfinite(val)
            if not finite and not warned_nonfinite[0]:
                warned_nonfinite[0] = True
                from .utils.log import Log
                Log.warning(
                    f"early stopping: non-finite value for {metric} "
                    f"({val}); treated as no improvement")
            if finite and (best_score_list[i] is None
                           or cmp_op[i](val, best_score[i])):
                best_score[i] = val
                best_iter[i] = env.iteration
                best_score_list[i] = list(env.evaluation_result_list)
            if first_metric_only and metric.split("@")[0] != first_metric[0]:
                continue
            if name == "training" \
                    or (name == "cv_agg" and item[1].startswith("train ")):
                continue
            # best_score_list[i] stays None while every value so far was
            # non-finite — report the current results in that case
            bsl = best_score_list[i] if best_score_list[i] is not None \
                else list(env.evaluation_result_list)
            if env.iteration - best_iter[i] >= stopping_rounds:
                if verbose:
                    print(f"Early stopping, best iteration is:\n"
                          f"[{best_iter[i] + 1}]\t" +
                          "\t".join(_fmt_eval(r) for r in bsl))
                raise EarlyStopException(best_iter[i], bsl)
            if env.iteration == env.end_iteration - 1:
                if verbose:
                    print(f"Did not meet early stopping. Best iteration is:\n"
                          f"[{best_iter[i] + 1}]\t" +
                          "\t".join(_fmt_eval(r) for r in bsl))
                raise EarlyStopException(best_iter[i], bsl)
    _callback.order = 30
    # env-pure state machine: the super-epoch replay (engine.py) feeds
    # it the SAME (iteration, evaluation_result_list) stream the
    # per-iteration path would, so best_iteration/best_score come out
    # byte-identical.  _es_spec lets the engine mirror the closure as a
    # traced in-scan vote (models/gbdt.py) that predicts the stop row —
    # only the scalar min_delta == 0 form is traced (engine gates)
    _callback._replayable = True
    _callback._es_spec = {"stopping_rounds": stopping_rounds,
                          "first_metric_only": first_metric_only,
                          "min_delta": min_delta}
    return _callback
