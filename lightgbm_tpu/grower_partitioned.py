"""Partitioned leaf-wise grower: the single-chip performance learner.

Where grower.py's fully-jitted program pays a full-N masked histogram pass
per split, this learner keeps the reference's work complexity — histogram
work proportional to the SMALLER child (serial_tree_learner.cpp:283-323
smaller/larger leaf logic + subtraction trick), via:

- a device-resident row-permutation ``order`` grouped by leaf — the
  ``DataPartition::indices_`` analog (data_partition.hpp:161), repartitioned
  in place per split with an O(P) cumsum scatter (the CUDA learner's
  prefix-sum pipeline, cuda_data_partition.cu:288);
- host-orchestrated per-split loop (one tiny D2H of the two child split
  records per split — the same sync the CUDA learner does,
  cuda_single_gpu_tree_learner.cpp:118-228) with power-of-2 size bucketing
  so every jitted kernel has a static shape (~log2(N) compile variants);
- gathered-row histogram construction on the MXU (ops/histogram.py).

Output matches grower.py's TreeArrays bit-for-bit in structure; tests
assert equivalence between the two learners.
"""

from __future__ import annotations

import functools
from typing import List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .grower import TreeArrays
from .ops.histogram import compute_histogram
from .ops.split import (SplitParams, SplitResult, dequantize_hist,
                        find_best_split, leaf_output,
                        monotone_penalty_factor)


def _quantize_vals(vals, rng_iter, *, spec):
    """Per-iteration quantization for the partitioned learner: shared
    per-channel scales + iteration-keyed stochastic rounding
    (ops/quantize.py; single-chip, so global row id == row index)."""
    from .ops.quantize import quant_scales, quantize_stack
    scales = quant_scales(vals, spec.qmax)
    return quantize_stack(vals, scales, spec, rng_iter, 0), scales


def _pow2(x: int) -> int:
    p = 1
    while p < x:
        p *= 2
    return p


@functools.partial(jax.jit, static_argnames=("p", "num_bins", "block_rows"))
def _hist_segment(order, binned, vals, begin, count, *, p, num_bins,
                  block_rows=0):
    """Histogram over rows order[begin:begin+count], padded to p."""
    n = order.shape[0]
    pos = begin + jnp.arange(p, dtype=jnp.int32)
    idx = order[jnp.clip(pos, 0, n - 1)]
    rows = jnp.take(binned, idx, axis=0)
    mask = (jnp.arange(p) < count).astype(vals.dtype)
    v = jnp.take(vals, idx, axis=0) * mask[:, None]
    return compute_histogram(rows, v, num_bins=num_bins,
                             block_rows=block_rows)


@functools.partial(jax.jit, static_argnames=("p",))
def _partition_segment(order, binned, col, nb, goff, nbm1, thr, dleft, icat,
                       rank_vec, begin, count, *, p):
    """Stable in-place partition of order[begin:begin+count] by the split
    predicate (left block first).  Returns (order, left_count).
    ``rank_vec`` [B] is the decision rank (iota for numerical splits);
    ``col`` is the binned-matrix column (the EFB group for bundled
    features), ``nb`` the feature's NaN bin, ``goff``/``nbm1`` the bundle
    offset (-1 = identity) and num_bin-1 for group-bin unmapping."""
    n = order.shape[0]
    pos = begin + jnp.arange(p, dtype=jnp.int32)
    cpos = jnp.clip(pos, 0, n - 1)
    idx = order[cpos]
    gcol = binned[idx, col].astype(jnp.int32)
    fcol = jnp.where(goff < 0, gcol,
                     jnp.where((gcol >= goff) & (gcol < goff + nbm1),
                               gcol - goff + 1, 0))
    is_na = (nb >= 0) & (fcol == nb) & (~icat)
    valid = jnp.arange(p) < count
    go_left = jnp.where(is_na, dleft, rank_vec[fcol] <= thr) & valid
    go_right = (~go_left) & valid
    cl = go_left.sum()
    # O(p) stable partition via cumsum ranks (no sort)
    left_rank = jnp.cumsum(go_left) - 1
    right_rank = cl + jnp.cumsum(go_right) - 1
    inv_rank = count + jnp.cumsum(~valid) - 1
    dest = jnp.where(go_left, left_rank,
                     jnp.where(go_right, right_rank, inv_rank))
    dest_pos = begin + dest.astype(jnp.int32)
    dest_pos = jnp.where(pos < n, dest_pos, n)  # out-of-range -> dropped
    new_order = order.at[dest_pos].set(idx, mode="drop")
    return new_order, cl


@functools.partial(jax.jit, static_argnames=("num_leaves",))
def _leaf_of_row(order, seg_begins, seg_leafs, *, num_leaves):
    """Reconstruct row->leaf from the order permutation + host segment map."""
    n = order.shape[0]
    seg = jnp.searchsorted(seg_begins, jnp.arange(n, dtype=jnp.int32),
                           side="right") - 1
    leaf_by_pos = seg_leafs[seg]
    return jnp.zeros(n, jnp.int32).at[order].set(leaf_by_pos)


class _HostSplit(NamedTuple):
    gain: float
    feature: int
    threshold: int
    default_left: bool
    left_sum: np.ndarray
    right_sum: np.ndarray
    left_output: float
    right_output: float
    is_cat: bool
    bin_rank: np.ndarray


def _pull(res: SplitResult) -> _HostSplit:
    """Convert a (device or already-fetched) SplitResult to host scalars.

    Callers batching several results should jax.device_get the whole tuple
    first — one transfer instead of ~10 blocking scalar reads per result,
    which matters when the chip is behind a network tunnel."""
    return _HostSplit(
        gain=float(res.gain), feature=int(res.feature),
        threshold=int(res.threshold), default_left=bool(res.default_left),
        left_sum=np.asarray(res.left_sum), right_sum=np.asarray(res.right_sum),
        left_output=float(res.left_output), right_output=float(res.right_output),
        is_cat=bool(res.is_cat), bin_rank=np.asarray(res.bin_rank))


class CEGBState(NamedTuple):
    """Cost-effective gradient boosting penalties
    (cost_effective_gradient_boosting.hpp:22-160): per-split data-acquisition
    cost + per-feature coupled (once per model) and lazy (per data point,
    approximated here by leaf size) penalties, scaled by cegb_tradeoff and
    subtracted from candidate gains.  ``used`` persists across trees."""
    tradeoff: float
    penalty_split: float
    coupled: Optional[np.ndarray]     # [F] or None
    lazy: Optional[np.ndarray]        # [F] or None
    used: np.ndarray                  # [F] bool, mutated in place

    def penalty_vector(self, num_data_in_leaf: float) -> np.ndarray:
        f = len(self.used)
        pen = np.full(f, self.tradeoff * self.penalty_split
                      * float(num_data_in_leaf), np.float32)
        if self.coupled is not None:
            pen += self.tradeoff * self.coupled * (~self.used)
        if self.lazy is not None:
            pen += self.tradeoff * self.lazy * float(num_data_in_leaf)
        return pen

    def mark_used(self, feature: int) -> None:
        self.used[feature] = True

    @property
    def active(self) -> bool:
        return (self.penalty_split > 0 or self.coupled is not None
                or self.lazy is not None)


class PartitionedGrower:
    """Host-orchestrated device-resident leaf-wise learner.

    Optional per-node controls (host bookkeeping, device search):
    - ``mono``: [F] -1/0/+1 monotone constraints ('basic' range method,
      monotone_constraints.hpp BasicLeafConstraints analog);
    - ``interaction_groups``: [G, F] bool constraint-group matrix — a leaf
      may split on its branch features plus the union of the groups that
      contain the WHOLE branch set (ColSampler GetByNode subset
      containment, col_sampler.hpp:91-111; overlapping groups make the
      progressive-intersection shortcut wrong), and the root is limited
      to the union of all groups;
    - ``bynode_frac`` < 1: feature_fraction_bynode re-sampling per node.
    """

    def __init__(self, *, num_leaves: int, num_bins: int, params: SplitParams,
                 max_depth: int = -1, block_rows: int = 0,
                 mono: Optional[np.ndarray] = None,
                 mono_method: str = "basic", mono_penalty: float = 0.0,
                 interaction_groups: Optional[np.ndarray] = None,
                 bynode_frac: float = 1.0, bynode_seed: int = 0,
                 efb=None, pool_entries: int = 0,
                 feature_contri: Optional[np.ndarray] = None,
                 extra_trees: bool = False, extra_seed: int = 6,
                 quant=None):
        self.L = int(num_leaves)
        self.B = int(num_bins)
        self.params = params
        self.max_depth = max_depth
        self.block_rows = block_rows
        self.mono = None if mono is None or not np.any(mono) else \
            jnp.asarray(mono, jnp.int32)
        # 'basic' = midpoint range splitting (BasicLeafConstraints);
        # 'intermediate' = constraints from actual opposite-subtree
        # outputs, refreshed across the whole frontier after each split
        # (IntermediateLeafConstraints, monotone_constraints.hpp:514);
        # 'advanced' = per-THRESHOLD constraint refinement
        # (AdvancedLeafConstraints, monotone_constraints.hpp:856): a
        # candidate split is only constrained by leaves whose region
        # actually overlaps the resulting child's region.  Implemented
        # from leaf bounding boxes (_leaf_boxes/_advanced_bounds): exact
        # per-(feature, bin) neighbor bounds rather than the reference's
        # incremental up-walk bookkeeping — at least as tight, and
        # recomputed per frontier refresh like the intermediate mode.
        self.mono_method = mono_method
        self.mono_penalty = float(mono_penalty)
        self.interaction_groups = None if interaction_groups is None \
            else np.asarray(interaction_groups, bool)
        self.bynode_frac = bynode_frac
        self._bynode_rng = np.random.RandomState(bynode_seed)
        # feature_contri (per-feature gain scale, feature_histogram.hpp) —
        # composed multiplicatively with the monotone penalty below
        self.feature_contri = None if feature_contri is None else \
            jnp.asarray(feature_contri, jnp.float32)
        self.extra_trees = bool(extra_trees)
        self._extra_rng = np.random.RandomState(extra_seed)
        # quantized training (ops/quantize.py): vals are packed once per
        # grow() call (= per iteration) on device, the per-segment
        # histograms accumulate exact int32 (subtraction included), and
        # _find_leaf dequantizes at scan time — the same contract as the
        # masked grower, on the host-orchestrated loop
        self.quant = quant
        if quant is not None:
            self._quantize = jax.jit(functools.partial(
                _quantize_vals, spec=quant))
        self._find = jax.jit(functools.partial(find_best_split, params=params))
        # HistogramPool analog (feature_histogram.hpp:1095,
        # histogram_pool_size): cap the number of device-resident per-leaf
        # histograms; evicted leaves are reconstructed on demand (the
        # reference recomputes on pool miss the same way,
        # serial_tree_learner.cpp:283-323 slot juggling).  0 = unbounded.
        self.pool_entries = max(2, int(pool_entries)) if pool_entries > 0 \
            else 0
        self.efb = efb  # EFBDevice (efb.py) or None
        # histogram axis: group bins when bundled, feature bins otherwise
        self.BH = efb.group_bins if efb is not None else self.B
        if efb is not None:
            from .efb import expand_group_hist
            self._expand = jax.jit(functools.partial(
                expand_group_hist, group_of_feat=efb.group_of_feat,
                col_idx=efb.col_idx, fix0=efb.fix0))

    def grow(self, binned, vals, feature_mask, num_bin, na_bin,
             is_cat=None, forced=None,
             cegb_state: Optional[CEGBState] = None,
             rng_iter=None) -> TreeArrays:
        L, B = self.L, self.B
        n = binned.shape[0]
        p_full = _pow2(n)
        order = jnp.arange(n, dtype=jnp.int32)
        nb_host = np.asarray(num_bin)
        na_host = np.asarray(na_bin)

        scales = None
        if self.quant is not None:
            # pack once per tree; every segment histogram below is then
            # an exact int32 accumulation, dequantized only at scan time
            vals, scales = self._quantize(
                jnp.asarray(vals),
                jnp.int32(0 if rng_iter is None else rng_iter))

        # root histogram + split (over EFB groups when bundled)
        hist0 = _hist_segment(order, binned, vals, jnp.int32(0), jnp.int32(n),
                              p=p_full, num_bins=self.BH,
                              block_rows=self.block_rows)
        total0_dev = hist0[0].sum(axis=0)
        if scales is not None:
            total0_dev = dequantize_hist(total0_dev, scales)
        root_out_dev = leaf_output(total0_dev[0], total0_dev[1], self.params)
        total0, root_out = jax.device_get((total0_dev, root_out_dev))
        total0 = np.asarray(total0)
        root_out = float(root_out)
        base_mask = np.asarray(feature_mask, bool)
        if self.interaction_groups is not None:
            # GetByNode (col_sampler.hpp:91-111): per-leaf branch sets;
            # allowed = branch ∪ (groups that contain the whole branch).
            # Root branch is empty -> union of all groups.
            def _inter_allowed(branch):
                g = self.interaction_groups
                contains = (g | ~branch[None, :]).all(axis=1)
                return (g & contains[:, None]).any(axis=0) | branch
            leaf_branch = {0: np.zeros(base_mask.shape[0], bool)}
            leaf_mask = {0: base_mask & _inter_allowed(leaf_branch[0])}
        else:
            leaf_mask = {0: base_mask}
        inf = np.float32(np.finfo(np.float32).max)
        leaf_lo = {0: -inf}
        leaf_hi = {0: inf}
        use_advanced = self.mono is not None \
            and self.mono_method == "advanced"
        adv_bounds: dict = {}
        adv_prev_boxes: list = [None]
        if use_advanced:
            nf_adv = len(np.asarray(num_bin))
            adv_bounds[0] = (np.full((nf_adv, B), -np.inf, np.float32),
                             np.full((nf_adv, B), np.inf, np.float32),
                             np.full((nf_adv, B), -np.inf, np.float32),
                             np.full((nf_adv, B), np.inf, np.float32))

        def _node_mask(mask: np.ndarray) -> jax.Array:
            if self.bynode_frac < 1.0:
                f_all = len(mask)
                k = max(1, int(round(mask.sum() * self.bynode_frac)))
                on = np.nonzero(mask)[0]
                keep = self._bynode_rng.choice(on, size=min(k, len(on)),
                                               replace=False)
                m = np.zeros(f_all, bool)
                m[keep] = True
                return jnp.asarray(m)
            return jnp.asarray(mask)

        def _find_leaf(hist, total, pout, leaf):
            if scales is not None:
                # quantized training: dequantize AT SCAN TIME only
                # (ops/split.py dequantize_hist) — int32 everywhere else
                hist = dequantize_hist(hist, scales)
            kw = {}
            if self.mono is not None:
                kw = dict(mono=self.mono,
                          out_lo=jnp.float32(leaf_lo[leaf]),
                          out_hi=jnp.float32(leaf_hi[leaf]))
                if use_advanced:
                    kw["mono_bounds"] = tuple(
                        jnp.asarray(a) for a in adv_bounds[leaf])
                if self.mono_penalty > 0.0:
                    factor = monotone_penalty_factor(self.mono_penalty,
                                                     depth.get(leaf, 0))
                    kw["gain_scale"] = jnp.where(
                        self.mono != 0, factor.astype(jnp.float32),
                        jnp.float32(1.0))
            if cegb_state is not None and cegb_state.active:
                kw["gain_penalty"] = jnp.asarray(
                    cegb_state.penalty_vector(total[2]))
            if self.feature_contri is not None:
                gs = kw.get("gain_scale")
                kw["gain_scale"] = self.feature_contri if gs is None \
                    else gs * self.feature_contri
            if self.extra_trees:
                # one random threshold bin per feature per candidate-leaf
                # evaluation (extremely randomized trees; host RNG since
                # this learner is host-orchestrated anyway)
                nb_host = np.asarray(num_bin)
                u = self._extra_rng.rand(len(nb_host))
                kw["rand_bin"] = jnp.asarray(
                    np.minimum((u * np.maximum(nb_host - 1, 1)).astype(np.int32),
                               nb_host - 2), jnp.int32)
            if self.efb is not None:
                hist = self._expand(hist, jnp.asarray(total, jnp.float32))
            return self._find(hist, jnp.asarray(total, jnp.float32),
                              num_bin, na_bin, _node_mask(leaf_mask[leaf]),
                              parent_output=jnp.float32(pout),
                              is_cat=is_cat, **kw)

        depth = {0: 0}
        hists = {0: hist0}
        lru: List[int] = [0]

        def _store(l: int, h) -> None:
            hists[l] = h
            if self.pool_entries <= 0:
                return
            if l in lru:
                lru.remove(l)
            lru.append(l)
            live = [k for k in lru if hists.get(k) is not None]
            while len(live) > self.pool_entries:
                victim = live.pop(0)
                hists[victim] = None
                lru.remove(victim)

        def _get_hist(l: int):
            """Pool fetch; evicted leaves rebuilt from their row segment."""
            h = hists.get(l)
            if h is None:
                p_l = min(_pow2(max(counts[l], 1)), p_full)
                h = _hist_segment(order_box[0], binned, vals,
                                  jnp.int32(begins[l]), jnp.int32(counts[l]),
                                  p=p_l, num_bins=self.BH,
                                  block_rows=self.block_rows)
            _store(l, h)
            return h

        cand = {0: _pull(_find_leaf(hist0, total0, root_out, 0))}
        totals = {0: total0}
        parent_out = {0: root_out}

        # host tree state
        begins = {0: 0}
        counts = {0: n}
        leaf_parent = {0: -1}
        split_feature = np.zeros(L - 1, np.int32)
        threshold_bin = np.zeros(L - 1, np.int32)
        default_left = np.zeros(L - 1, bool)
        left_child = np.zeros(L - 1, np.int32)
        right_child = np.zeros(L - 1, np.int32)
        split_gain = np.zeros(L - 1, np.float32)
        leaf_value = np.zeros(L, np.float32)
        leaf_weight = np.zeros(L, np.float32)
        leaf_count = np.zeros(L, np.float32)
        internal_value = np.zeros(L - 1, np.float32)
        internal_weight = np.zeros(L - 1, np.float32)
        internal_count = np.zeros(L - 1, np.float32)
        leaf_depth_arr = np.zeros(L, np.int32)
        is_cat_node = np.zeros(L - 1, bool)
        cat_rank = np.broadcast_to(np.arange(B, dtype=np.int32)[None],
                                   (L - 1, B)).copy()
        leaf_value[0] = root_out
        leaf_weight[0] = total0[1]
        leaf_count[0] = total0[2]

        num_leaves = 1
        order_box = [order]

        def apply_split(i: int, leaf: int, rec: _HostSplit) -> None:
            nonlocal num_leaves
            order = order_box[0]
            new = num_leaves

            # tree bookkeeping (Tree::Split)
            parent = leaf_parent[leaf]
            if parent >= 0:
                if left_child[parent] == ~leaf:
                    left_child[parent] = i
                else:
                    right_child[parent] = i
            left_child[i] = ~leaf
            right_child[i] = ~new
            split_feature[i] = rec.feature
            threshold_bin[i] = rec.threshold
            default_left[i] = rec.default_left
            split_gain[i] = rec.gain
            internal_value[i] = leaf_value[leaf]
            internal_weight[i] = leaf_weight[leaf]
            internal_count[i] = leaf_count[leaf]
            leaf_parent[leaf] = i
            leaf_parent[new] = i
            is_cat_node[i] = rec.is_cat
            cat_rank[i] = rec.bin_rank

            # partition the leaf's segment
            begin, cnt = begins[leaf], counts[leaf]
            p_seg = min(_pow2(max(cnt, 1)), p_full)
            if self.efb is not None:
                col = int(self.efb.group_host[rec.feature])
                goff = int(self.efb.off_host[rec.feature])
            else:
                col, goff = rec.feature, -1
            order, cl_dev = _partition_segment(
                order, binned, jnp.int32(col),
                jnp.int32(na_host[rec.feature]), jnp.int32(goff),
                jnp.int32(nb_host[rec.feature] - 1),
                jnp.int32(rec.threshold), jnp.bool_(rec.default_left),
                jnp.bool_(rec.is_cat), jnp.asarray(rec.bin_rank),
                jnp.int32(begin), jnp.int32(cnt), p=p_seg)
            # actual moved-row count (with bagging, out-of-bag rows follow
            # the split too, so segment size != in-bag left_sum count).
            # this is the split's one unavoidable host sync (the CUDA
            # learner's D2H of the split description,
            # cuda_single_gpu_tree_learner.cpp:118-228)
            cl = int(cl_dev)
            cr = cnt - cl
            begins[leaf], counts[leaf] = begin, cl
            begins[new], counts[new] = begin + cl, cr
            d = depth[leaf] + 1
            depth[leaf] = d
            depth[new] = d
            leaf_value[leaf] = rec.left_output
            leaf_value[new] = rec.right_output
            leaf_weight[leaf] = rec.left_sum[1]
            leaf_weight[new] = rec.right_sum[1]
            leaf_count[leaf] = rec.left_sum[2]
            leaf_count[new] = rec.right_sum[2]
            leaf_depth_arr[leaf] = d
            leaf_depth_arr[new] = d

            # histogram: smaller child constructed, larger by subtraction
            # (falls back to direct construction on a histogram-pool miss —
            # the parent's rows are already re-partitioned by now)
            sm, lg = (leaf, new) if cl <= cr else (new, leaf)
            parent_hist = hists.get(leaf)
            p_sm = min(_pow2(max(counts[sm], 1)), p_full)
            hist_sm = _hist_segment(order, binned, vals,
                                    jnp.int32(begins[sm]),
                                    jnp.int32(counts[sm]), p=p_sm,
                                    num_bins=self.BH,
                                    block_rows=self.block_rows)
            if parent_hist is not None:
                hist_lg = parent_hist - hist_sm
            else:
                p_lg = min(_pow2(max(counts[lg], 1)), p_full)
                hist_lg = _hist_segment(order, binned, vals,
                                        jnp.int32(begins[lg]),
                                        jnp.int32(counts[lg]), p=p_lg,
                                        num_bins=self.BH,
                                        block_rows=self.block_rows)
            _store(sm, hist_sm)
            _store(lg, hist_lg)
            totals[leaf] = rec.left_sum
            totals[new] = rec.right_sum
            parent_out[leaf] = rec.left_output
            parent_out[new] = rec.right_output

            # constraint propagation to children
            if self.interaction_groups is not None:
                child_branch = leaf_branch[leaf].copy()
                child_branch[rec.feature] = True
                leaf_branch[leaf] = leaf_branch[new] = child_branch
                child_mask = base_mask & _inter_allowed(child_branch)
            else:
                child_mask = leaf_mask[leaf]
            leaf_mask[leaf] = child_mask
            leaf_mask[new] = child_mask
            lo_p, hi_p = leaf_lo[leaf], leaf_hi[leaf]
            mc = 0 if self.mono is None else int(np.asarray(self.mono)[rec.feature])
            use_intermediate = (self.mono is not None
                                and self.mono_method == "intermediate")
            refresh = []
            if use_advanced:
                # recompute per-threshold bounds ONLY for leaves this
                # split can affect: a leaf's bounds depend on boxes and
                # outputs of its monotone neighbors, and the only changed
                # regions are the split leaf's old box and the two child
                # boxes — any other leaf keeps its cached bounds (the
                # AdvancedLeafConstraints GoUpToFindLeavesToUpdate role,
                # as a box-overlap filter instead of a tree up-walk)
                num_leaves_next = new + 1
                boxes_int, boxes_wide = self._leaf_boxes(
                    num_leaves_next, split_feature, threshold_bin,
                    left_child, right_child, is_cat_node,
                    np.asarray(num_bin), default_left=default_left,
                    na_host=na_host)
                mono_np = np.asarray(self.mono)
                cand_boxes = [boxes_wide[leaf], boxes_wide[new]]
                if adv_prev_boxes[0] is not None \
                        and leaf < len(adv_prev_boxes[0]):
                    cand_boxes.append(adv_prev_boxes[0][leaf])

                # a changed box can constrain leaf l iff l's box overlaps
                # it in every dim except possibly ONE monotone feature
                # (the neighbor relation AdvancedLeafConstraints walks).
                # Vectorized over all leaves at once: the old per-leaf
                # Python loop was O(M^2*F) per split and walled out at
                # 255 leaves (VERDICT r3 weak 6); this is O(M*F) numpy.
                mono_mask = mono_np != 0
                could = np.zeros(num_leaves_next, bool)
                bw = boxes_wide[:num_leaves_next]
                for cb in cand_boxes:
                    nonov = ~((cb[None, :, 0] <= bw[:, :, 1])
                              & (bw[:, :, 0] <= cb[None, :, 1]))  # [M, F]
                    cnt = nonov.sum(axis=1)
                    mono_nonov = (nonov & mono_mask[None, :]).sum(axis=1)
                    could |= (cnt == 0) | ((cnt == 1) & (mono_nonov == 1))

                for l in range(num_leaves_next):
                    if l in (leaf, new) or l not in adv_bounds \
                            or could[l]:
                        nbnd = self._advanced_bounds(
                            boxes_int, boxes_wide, leaf_value, l, B,
                            na_host=na_host)
                        old = adv_bounds.get(l)
                        if l not in (leaf, new) and (
                                old is None or any(
                                    not np.array_equal(a, b)
                                    for a, b in zip(old, nbnd))):
                            refresh.append(l)
                        adv_bounds[l] = nbnd
                    # scalar range is unused under advanced (the per-bin
                    # bounds replace it) but must exist for _find_leaf
                    leaf_lo.setdefault(l, -inf)
                    leaf_hi.setdefault(l, inf)
                adv_prev_boxes[0] = boxes_wide
            elif use_intermediate:
                # recompute the whole frontier's intervals from the actual
                # opposite-subtree outputs (IntermediateLeafConstraints
                # UpdateConstraintsWithOutputs + GoUpToFindLeavesToUpdate,
                # monotone_constraints.hpp:543-587 — here a full host-side
                # refresh instead of the reference's up-walk bookkeeping)
                num_leaves_next = new + 1
                iv = self._mono_intervals(
                    num_leaves_next, split_feature, left_child, right_child,
                    leaf_value, is_cat_node)
                for l in range(num_leaves_next):
                    lo2, hi2 = iv[l]
                    if l not in (leaf, new) and (
                            abs(lo2 - leaf_lo.get(l, -inf)) > 1e-12
                            or abs(hi2 - leaf_hi.get(l, inf)) > 1e-12):
                        refresh.append(l)
                    leaf_lo[l], leaf_hi[l] = lo2, hi2
            elif mc != 0 and not rec.is_cat:
                mid = 0.5 * (rec.left_output + rec.right_output)
                if mc > 0:   # left (smaller values) must output <= right
                    leaf_lo[leaf], leaf_hi[leaf] = lo_p, min(hi_p, mid)
                    leaf_lo[new], leaf_hi[new] = max(lo_p, mid), hi_p
                else:
                    leaf_lo[leaf], leaf_hi[leaf] = max(lo_p, mid), hi_p
                    leaf_lo[new], leaf_hi[new] = lo_p, min(hi_p, mid)
            else:
                leaf_lo[new], leaf_hi[new] = lo_p, hi_p

            # new candidates for both children; dispatches are async, then
            # ONE batched device_get for everything this split needs on host
            r_l = _find_leaf(hists[leaf], totals[leaf], parent_out[leaf], leaf)
            r_r = _find_leaf(hists[new], totals[new], parent_out[new], new)
            r_refresh = [_find_leaf(_get_hist(l), totals[l], parent_out[l], l)
                         for l in refresh]
            got = jax.device_get((r_l, r_r, r_refresh))
            cand[leaf] = _pull(got[0])
            cand[new] = _pull(got[1])
            for l, r in zip(refresh, got[2]):
                cand[l] = _pull(r)
            num_leaves = new + 1
            order_box[0] = order

        # forced splits pre-pass (ForceSplits, serial_tree_learner.cpp:455):
        # apply the forced tree top regardless of gain, in BFS order
        node_budget = L - 1
        next_node = 0
        if forced is not None:
            queue = [(forced, 0)]
            while queue and next_node < node_budget:
                spec, leaf = queue.pop(0)
                ph = _get_hist(leaf)
                if scales is not None:
                    ph = dequantize_hist(ph, scales)
                fh = ph if self.efb is None else self._expand(
                    ph, jnp.asarray(totals[leaf], jnp.float32))
                rec = self._forced_record(spec, fh, totals[leaf],
                                          parent_out[leaf], B)
                if rec is None:
                    continue
                new = num_leaves
                apply_split(next_node, leaf, rec)
                next_node += 1
                if isinstance(spec.get("left"), dict):
                    queue.append((spec["left"], leaf))
                if isinstance(spec.get("right"), dict):
                    queue.append((spec["right"], new))

        for i in range(next_node, L - 1):
            # pick best leaf (host argmax — the per-leaf candidates are here)
            ok = [l for l in range(num_leaves)
                  if cand[l].gain > 0
                  and (self.max_depth <= 0 or depth[l] < self.max_depth)]
            if not ok:
                break
            leaf = max(ok, key=lambda l: cand[l].gain)
            if cegb_state is not None:
                cegb_state.mark_used(cand[leaf].feature)
            apply_split(i, leaf, cand[leaf])

        order = order_box[0]
        # reconstruct leaf_of_row from segments
        seg = sorted(((begins[l], l) for l in range(num_leaves)))
        seg_begins = jnp.asarray([s[0] for s in seg], jnp.int32)
        seg_leafs = jnp.asarray([s[1] for s in seg], jnp.int32)
        lor = _leaf_of_row(order, seg_begins, seg_leafs, num_leaves=L)

        return TreeArrays(
            num_leaves=jnp.int32(num_leaves),
            split_feature=jnp.asarray(split_feature),
            threshold_bin=jnp.asarray(threshold_bin),
            default_left=jnp.asarray(default_left),
            left_child=jnp.asarray(left_child),
            right_child=jnp.asarray(right_child),
            split_gain=jnp.asarray(split_gain),
            leaf_value=jnp.asarray(leaf_value),
            leaf_weight=jnp.asarray(leaf_weight),
            leaf_count=jnp.asarray(leaf_count),
            internal_value=jnp.asarray(internal_value),
            internal_weight=jnp.asarray(internal_weight),
            internal_count=jnp.asarray(internal_count),
            leaf_depth=jnp.asarray(leaf_depth_arr),
            leaf_of_row=lor,
            is_cat_node=jnp.asarray(is_cat_node),
            cat_rank=jnp.asarray(cat_rank),
            n_steps=jnp.int32(num_leaves - 1),
        )

    @staticmethod
    def _leaf_boxes(num_leaves, split_feature, threshold_bin, left_child,
                    right_child, is_cat_node, nb_host, default_left=None,
                    na_host=None):
        """Per-leaf bin-range boxes from the numerical split structure,
        as TWO [M, F, 2] arrays:

        - ``box_int``: the pure interval part (may be empty, lo > hi, for
          a child whose only rows are NA-routed).  Used for ORDERING
          along a monotone feature — NaN values are unordered, so only
          interval parts create left-of/right-of relations.
        - ``box_wide``: widened over the NaN bin for the child that
          receives NA rows by default_left, and over the full range for
          categorical splits — used for region-OVERLAP tests, where
          over-approximation can only ADD constraints (safe)."""
        nf = len(nb_host)
        box_i = np.zeros((num_leaves, nf, 2), np.int32)
        box_w = np.zeros((num_leaves, nf, 2), np.int32)
        lo0 = np.zeros(nf, np.int32)
        hi0 = np.asarray(nb_host, np.int32) - 1
        if num_leaves <= 1:
            for b in (box_i, box_w):
                b[0, :, 0], b[0, :, 1] = lo0, hi0
            return box_i, box_w
        stack = [(0, lo0, hi0, lo0, hi0)]
        while stack:
            node, lo, hi, wlo, whi = stack.pop()
            f = int(split_feature[node])
            t = int(threshold_bin[node])
            na = -1 if na_host is None else int(na_host[f])
            dl = bool(default_left[node]) if default_left is not None \
                else False
            for child, is_left in ((int(left_child[node]), True),
                                   (int(right_child[node]), False)):
                l2, h2, wl2, wh2 = lo, hi, wlo, whi
                if not is_cat_node[node]:
                    if is_left:
                        h2, wh2 = hi.copy(), whi.copy()
                        h2[f] = min(h2[f], t)
                        wh2[f] = min(wh2[f], t)
                    else:
                        l2, wl2 = lo.copy(), wlo.copy()
                        l2[f] = max(l2[f], t + 1)
                        wl2[f] = max(wl2[f], t + 1)
                    if na >= 0 and (dl == is_left):
                        wl2 = wl2.copy()
                        wh2 = wh2.copy()
                        wl2[f] = min(wl2[f], na)
                        wh2[f] = max(wh2[f], na)
                if child < 0:
                    box_i[~child, :, 0], box_i[~child, :, 1] = l2, h2
                    box_w[~child, :, 0], box_w[~child, :, 1] = wl2, wh2
                else:
                    stack.append((child, l2, h2, wl2, wh2))
        return box_i, box_w

    def _advanced_bounds(self, boxes_int, boxes_wide, leaf_value, y,
                         num_bins_total, na_host=None):
        """Per-(candidate-feature s, threshold-bin b) allowed output
        ranges of the two children of leaf ``y`` ('advanced' method).

        A leaf L' constrains a child C through monotone feature f iff
        their regions overlap in every dim except f (then point pairs
        differing only in f exist across them).  C's box equals y's box
        except in the split feature s, so the qualification is
        b-dependent exactly when s != f; because tree leaves partition
        the space, qualifying leaves' interval parts are f-disjoint from
        y's, making the s == f contribution b-independent.

        Ordering along f uses INTERVAL boxes (NaN is unordered, so only
        finite f-ranges create left-of/right-of relations; leaves whose
        f-interval is empty impose nothing through f), while every
        overlap test uses the NA-WIDENED boxes, plus an escape that keeps
        a constraint active at all thresholds of s when both regions
        cover s's NaN bin (NA rows follow default_left regardless of the
        threshold).  MissingType.Zero gets the same treatment on purpose:
        the model ROUTES zeros by default_left exactly like NaN
        (tree.h NumericalDecision), so zeros sit outside the ordered
        threshold geometry — matching the reference, whose monotone
        constraints also do not order the missing-routed branch."""
        nf, B = boxes_int.shape[1], int(num_bins_total)
        mono_np = np.asarray(self.mono)
        neg, pos = -np.inf, np.inf
        lo_l = np.full((nf, B), neg, np.float32)
        lo_r = np.full((nf, B), neg, np.float32)
        hi_l = np.full((nf, B), pos, np.float32)
        hi_r = np.full((nf, B), pos, np.float32)
        m = boxes_int.shape[0]
        if m <= 1:
            return lo_l, hi_l, lo_r, hi_r
        ybi, ybw = boxes_int[y], boxes_wide[y]
        ov = (boxes_wide[:, :, 0] <= ybw[None, :, 1]) \
            & (ybw[None, :, 0] <= boxes_wide[:, :, 1])    # [M, F]
        ids = np.arange(m)
        bgrid = np.arange(B)
        vals_all = np.asarray(leaf_value[:m], np.float64)
        if na_host is not None:
            na_s = np.asarray(na_host)
            cov_nb = (na_s[None, :] >= 0) \
                & (boxes_wide[:, :, 0] <= na_s[None, :]) \
                & (na_s[None, :] <= boxes_wide[:, :, 1])  # [M, F]
            cov_y = (na_s >= 0) & (ybw[:, 0] <= na_s) & (na_s <= ybw[:, 1])
            na_escape = cov_nb & cov_y[None, :]
        else:
            na_escape = np.zeros((m, nf), bool)
        for f in np.nonzero(mono_np != 0)[0]:
            mc = int(mono_np[f])
            q = (ov | (np.arange(nf) == f)[None, :]).all(axis=1) \
                & (ids != y)
            nonempty = boxes_int[:, f, 0] <= boxes_int[:, f, 1]
            right_nb = q & nonempty & (boxes_int[:, f, 0] > ybi[f, 1])
            left_nb = q & nonempty & (boxes_int[:, f, 1] < ybi[f, 0])
            ub_nb, lb_nb = (right_nb, left_nb) if mc > 0 \
                else (left_nb, right_nb)
            for nb_mask, is_min in ((ub_nb, True), (lb_nb, False)):
                vals = vals_all[nb_mask]
                if vals.size == 0:
                    continue
                sb = boxes_wide[nb_mask]
                ext = vals.min() if is_min else vals.max()
                fill = pos if is_min else neg
                # broadcast pass over (s, b):
                # left child's s-range is [y.lo_s, b] -> L' overlaps iff
                # L'.lo_s <= b; right child's is [b+1, y.hi_s] -> iff
                # L'.hi_s >= b+1.  Masked extremum over the K neighbors,
                # chunked over the s axis so the [K, s_chunk, B]
                # temporaries stay bounded (~8 MB) at wide/high-bin
                # shapes instead of multi-GB churn.
                k_nb = len(vals)
                vb = vals.astype(np.float32)[:, None, None]
                esc_all = na_escape[nb_mask]
                c_l = np.empty((nf, B), np.float32)
                c_r = np.empty((nf, B), np.float32)
                s_chunk = max(1, (1 << 21) // max(k_nb * B, 1))
                for s0 in range(0, nf, s_chunk):
                    sl = slice(s0, min(s0 + s_chunk, nf))
                    m_l = sb[:, sl, 0][:, :, None] <= bgrid[None, None, :]
                    m_r = sb[:, sl, 1][:, :, None] \
                        >= (bgrid + 1)[None, None, :]
                    esc = esc_all[:, sl, None]
                    m_l = m_l | esc
                    m_r = m_r | esc
                    if is_min:
                        c_l[sl] = np.where(m_l, vb, fill).min(axis=0)
                        c_r[sl] = np.where(m_r, vb, fill).min(axis=0)
                    else:
                        c_l[sl] = np.where(m_l, vb, fill).max(axis=0)
                        c_r[sl] = np.where(m_r, vb, fill).max(axis=0)
                # splits ON f itself: qualifying leaves are f-disjoint
                # from y, so the bound is b-independent for both children
                c_l[f, :] = ext
                c_r[f, :] = ext
                if is_min:
                    hi_l = np.minimum(hi_l, c_l)
                    hi_r = np.minimum(hi_r, c_r)
                else:
                    lo_l = np.maximum(lo_l, c_l)
                    lo_r = np.maximum(lo_r, c_r)
        return lo_l, hi_l, lo_r, hi_r

    def _mono_intervals(self, num_leaves, split_feature, left_child,
                        right_child, leaf_value, is_cat_node):
        """Per-leaf allowed output intervals from the current tree shape
        ('intermediate' method): walking root->leaf, a monotone split bounds
        the leaf by the extremum of the *opposite* subtree's current leaf
        outputs (tighter than the 'basic' midpoint; the analog of
        IntermediateLeafConstraints keeping constraints equal to actual
        sibling outputs, monotone_constraints.hpp:543-556)."""
        inf = float(np.finfo(np.float32).max)
        mono_np = np.asarray(self.mono)
        iv = {l: (-inf, inf) for l in range(num_leaves)}
        if num_leaves <= 1:
            return iv
        minmax_cache = {}

        def subtree_minmax(child):
            if child in minmax_cache:
                return minmax_cache[child]
            if child < 0:
                v = float(leaf_value[~child])
                r = (v, v)
            else:
                l0, l1 = subtree_minmax(int(left_child[child]))
                r0, r1 = subtree_minmax(int(right_child[child]))
                r = (min(l0, r0), max(l1, r1))
            minmax_cache[child] = r
            return r

        stack = [(0, -inf, inf)]
        while stack:
            node, lo, hi = stack.pop()
            lc, rc = int(left_child[node]), int(right_child[node])
            mc = 0 if is_cat_node[node] else \
                int(mono_np[int(split_feature[node])])
            llo, lhi, rlo, rhi = lo, hi, lo, hi
            if mc > 0:
                lhi = min(lhi, subtree_minmax(rc)[0])
                rlo = max(rlo, subtree_minmax(lc)[1])
            elif mc < 0:
                llo = max(llo, subtree_minmax(rc)[1])
                rhi = min(rhi, subtree_minmax(lc)[0])
            for child, clo, chi in ((lc, llo, lhi), (rc, rlo, rhi)):
                if child < 0:
                    iv[~child] = (clo, chi)
                else:
                    stack.append((child, clo, chi))
        return iv

    def _forced_record(self, spec, hist, total, pout, B) -> Optional[_HostSplit]:
        """Build a split record for a forced (feature, threshold) node
        (forcedsplits_filename, serial_tree_learner.cpp ForceSplits)."""
        f = int(spec["feature"])
        t = int(spec["threshold_bin"])
        h = np.asarray(hist[f])                         # [B, 3]
        lsum = h[:t + 1].sum(axis=0)
        rsum = np.asarray(total, np.float64) - lsum
        if lsum[2] < 1 or rsum[2] < 1:
            return None
        p = self.params

        def out(s):
            g, hh = float(s[0]), float(s[1])
            tl1 = np.sign(g) * max(0.0, abs(g) - p.lambda_l1) \
                if p.lambda_l1 > 0 else g
            o = -tl1 / (hh + p.lambda_l2 + 1e-15)
            if p.max_delta_step > 0:
                o = float(np.clip(o, -p.max_delta_step, p.max_delta_step))
            return float(o)

        return _HostSplit(
            gain=0.0, feature=f, threshold=t, default_left=False,
            left_sum=lsum.astype(np.float32), right_sum=rsum.astype(np.float32),
            left_output=out(lsum), right_output=out(rsum),
            is_cat=False, bin_rank=np.arange(B, dtype=np.int32))

    def __call__(self, binned, vals, feature_mask, num_bin, na_bin,
                 is_cat=None, **kw):
        return self.grow(binned, vals, feature_mask, num_bin, na_bin,
                         is_cat, **kw)
