"""Shared plumbing for the lint family (tools/lint.py driver).

Every lint in the family (sync, retrace, race, purity) has the same
skeleton: measure the tree, compare against a PIN FILE, report
findings including entries that no longer match anything (stale pins —
the mechanism that keeps pin files from rotting), exit 1 on any
finding.  This module is that skeleton, factored out of
``check_syncs.py`` / ``check_retraces.py`` so the two new AST lints
(``check_races.py`` / ``check_purity.py``) don't grow a third and
fourth copy of the parsing:

- ``parse_pins``      — ``|``-separated pin entries with an optional
  MANDATORY-rationale tail field (race/purity allowlists demand a
  reason per pin; the sync allowlist carries reasons as comments);
- ``stale_pins``      — the shared stale-entry findings;
- ``load_kv_int`` / ``write_kv_int`` — ``key = int`` budget files
  (retrace budget) with ``--update`` re-pinning;
- ``code_lines``      — tokenize-based comment/string blanking so
  docs may mention linted constructs freely;
- ``iter_py`` / ``rel_to_root`` — tree walking with the path
  convention shared by every pass (paths relative to the PARENT of
  the scanned package root, so a package copied to a temp dir for a
  tamper test matches the same allowlist entries as the real tree).
"""

from __future__ import annotations

import io
import os
import tokenize
from typing import Dict, Iterator, List, Sequence, Set, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
PACKAGE = os.path.join(REPO, "lightgbm_tpu")


# ---------------------------------------------------------------------------
# pin files
# ---------------------------------------------------------------------------

def parse_pins(path: str, fields: int,
               require_rationale: bool = False
               ) -> List[Tuple[Tuple[str, ...], str]]:
    """Parse a ``|``-separated pin file: ``fields`` leading fields plus
    (when ``require_rationale``) one trailing rationale field.  Returns
    ``[(fields_tuple, rationale), ...]``; blank lines and ``#`` comments
    are skipped.  A rationale-bearing entry whose rationale is empty is
    a malformed pin and raises — an allowlist exists to record WHY each
    exemption is safe, and a bare pin defeats that."""
    out: List[Tuple[Tuple[str, ...], str]] = []
    try:
        f = open(path)
    except OSError:
        return out
    with f:
        for lineno, raw in enumerate(f, 1):
            raw = raw.strip()
            if not raw or raw.startswith("#"):
                continue
            want = fields + (1 if require_rationale else 0)
            parts = [p.strip() for p in raw.split("|", want - 1)]
            if len(parts) < want or (require_rationale
                                     and not parts[-1]):
                raise ValueError(
                    f"{path}:{lineno}: malformed pin (need {fields} "
                    f"'|'-separated fields"
                    + (" + a non-empty rationale" if require_rationale
                       else "") + f"): {raw!r}")
            key = tuple(parts[:fields])
            rationale = parts[fields] if require_rationale else ""
            out.append((key, rationale))
    return out


def load_pin_keys(path: str, fields: int = 3,
                  require_rationale: bool = True
                  ) -> Set[Tuple[str, ...]]:
    """The race/purity allowlist form of :func:`parse_pins`: keys only,
    rationale mandatory."""
    return {key for key, _ in parse_pins(
        path, fields, require_rationale=require_rationale)}


def stale_pins(allow: Set[Tuple[str, ...]], used: Set[Tuple[str, ...]],
               label: str) -> List[str]:
    """The shared stale-entry findings: every pin that suppressed
    nothing this run is reported, so pin files cannot rot."""
    return [f"stale {label} entry (no matching finding): "
            + " | ".join(key) for key in sorted(allow - used)]


# ---------------------------------------------------------------------------
# key = int budget files (retrace budget)
# ---------------------------------------------------------------------------

def load_kv_int(path: str) -> Dict[str, int]:
    out: Dict[str, int] = {}
    try:
        with open(path) as f:
            for raw in f:
                raw = raw.split("#")[0].strip()
                if not raw or "=" not in raw:
                    continue
                k, _, v = raw.partition("=")
                out[k.strip()] = int(v.strip())
    except OSError:
        pass
    return out


def write_kv_int(measured: Dict[str, int], path: str,
                 header: Sequence[str]) -> None:
    lines = list(header) + [""]
    for k in sorted(measured):
        lines.append(f"{k} = {measured[k]}")
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


# ---------------------------------------------------------------------------
# source walking
# ---------------------------------------------------------------------------

def iter_py(root: str) -> Iterator[str]:
    """Every ``.py`` under ``root``, ``__pycache__`` pruned, sorted for
    deterministic finding order."""
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def rel_to_root(path: str, root: str) -> str:
    """Path convention of the whole family: relative to the PARENT of
    the scanned package root.  For the real tree that is the repo root
    (``lightgbm_tpu/serve/batcher.py``); for a package copied to a temp
    dir (tamper tests) the SAME relative path comes out, so the real
    allowlists keep matching."""
    return os.path.relpath(path, os.path.dirname(os.path.abspath(root)))


def code_lines(path: str) -> Dict[int, str]:
    """line number -> source line, with comment and string tokens
    blanked out so docs/docstrings never trigger a text lint."""
    with open(path, "rb") as f:
        src = f.read()
    text = src.decode("utf-8")
    lines = text.splitlines()
    drop: List[Tuple[int, int, int, int]] = []
    try:
        for tok in tokenize.tokenize(io.BytesIO(src).readline):
            if tok.type in (tokenize.COMMENT, tokenize.STRING):
                drop.append((*tok.start, *tok.end))
    except tokenize.TokenError:
        pass                     # partial file: lint what parsed
    out = {i + 1: ln for i, ln in enumerate(lines)}
    for (r0, c0, r1, c1) in drop:
        for r in range(r0, r1 + 1):
            ln = out.get(r, "")
            a = c0 if r == r0 else 0
            b = c1 if r == r1 else len(ln)
            out[r] = ln[:a] + " " * (b - a) + ln[b:]
    return out
