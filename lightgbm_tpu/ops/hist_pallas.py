"""Pallas TPU histogram kernel.

The reference's histogram hot loop (``DenseBin::ConstructHistogram``
/root/reference/src/io/dense_bin.hpp; CUDA shared-memory atomics variant
/root/reference/src/treelearner/cuda/cuda_histogram_constructor.cu:18-70)
re-designed for TPU:

TPU has no fast scatter-add, so the histogram is a one-hot contraction.
NOTE: measured on TPU v5e this kernel is SLOWER than the XLA scan in
ops/histogram.py (8.2 ms vs 4.7 ms amortized, 1M x 28 x 64 bins) — XLA
fuses the iota-compare one-hot generation into the dot operand load, so
the assumed HBM-materialization penalty does not occur.  The kernel is
kept behind LGBM_TPU_HIST=pallas for experimentation.  Design:

  per row-block (sequential grid), per feature-chunk:
    VMEM: bins [blk, Fc]  (uint8 -> f32)
    rep  = bins @ E          MXU, E[f, f*B+b] = 1  (feature -> column expand)
    onehot = (rep == bid)    VPU compare against the bin-id pattern
    acc += valsT @ onehot    MXU, [C, blk] x [blk, Fc*B]

The accumulator lives in VMEM for the whole row pass (same output block at
every grid step), so HBM traffic is just the binned matrix + vals, i.e.
the streaming lower bound.  Bin count is padded to a multiple of 8 lanes;
columns past a feature's real bin count never match and read back as 0.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _hist_kernel(binned_ref, valsT_ref, e_ref, bid_ref, out_ref):
    i = pl.program_id(1)  # row-block index (inner, sequential)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    # Mosaic has no direct uint8->f32 cast; widen via int32 first.
    bins = binned_ref[:].astype(jnp.int32).astype(jnp.float32)  # [blk, Fc]
    rep = jnp.dot(bins, e_ref[:],
                  preferred_element_type=jnp.float32)   # [blk, Fc*B]
    onehot = (rep == bid_ref[:]).astype(jnp.float32)    # bid broadcast [1,:]
    out_ref[:] += jnp.dot(valsT_ref[:], onehot,
                          preferred_element_type=jnp.float32)


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "block_rows", "interpret"))
def compute_histogram_pallas(binned: jax.Array, vals: jax.Array, *,
                             num_bins: int, block_rows: int = 0,
                             interpret: bool = False) -> jax.Array:
    """Drop-in for ops.histogram.compute_histogram on TPU.

    binned: [N, F] integer bins; vals: [N, C] float32 (rows outside the
    target leaf already zeroed); returns [F, num_bins, C] float32.
    """
    n, f = binned.shape
    c = vals.shape[1]
    bpad = _round_up(max(num_bins, 8), 8)

    # feature chunking keeps the one-hot tile in VMEM
    fc = max(1, min(f, 2048 // bpad))
    n_fchunks = (f + fc - 1) // fc
    if f % fc:
        binned = jnp.pad(binned, ((0, 0), (0, n_fchunks * fc - f)),
                         constant_values=255)
    fb = fc * bpad

    if block_rows <= 0:
        # one-hot tile (f32) + rep tile budgeted at ~6 MB of VMEM
        block_rows = max(32, min(2048, (6 * 2 ** 20) // (8 * fb) // 32 * 32))
    blk = block_rows
    npad = _round_up(max(n, blk), blk)
    if npad != n:
        binned = jnp.pad(binned, ((0, npad - n), (0, 0)), constant_values=255)
        vals = jnp.pad(vals, ((0, npad - n), (0, 0)))
    valsT = vals.T  # [C, N]

    col = np.arange(fb, dtype=np.int64)
    e = jnp.asarray((col[None, :] // bpad == np.arange(fc)[:, None])
                    .astype(np.float32))                  # [Fc, fb]
    bid = jnp.asarray((col % bpad).astype(np.float32)[None, :])  # [1, fb]

    grid = (n_fchunks, npad // blk)
    out = pl.pallas_call(
        _hist_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((blk, fc), lambda j, i: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((c, blk), lambda j, i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((fc, fb), lambda j, i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, fb), lambda j, i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((c, fb), lambda j, i: (0, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((c, n_fchunks * fb), jnp.float32),
        interpret=interpret,
    )(binned, valsT, e, bid)

    # [C, n_fchunks*Fc*bpad] -> [F, num_bins, C]
    hist = out.T.reshape(n_fchunks * fc, bpad, c)[:f, :num_bins, :]
    return hist
