"""Elastic pod-scale training: liveness, hung-collective deadlines and
shrink-to-survive recovery (ROADMAP item 2, robustness half).

The distributed learners (``parallel/``) had no mid-run failure story:
a preempted host or wedged TPU claim — the failure that cost the TPU
claim in 4 of 5 bench rounds (r03–r05) — hangs every ``psum`` /
``psum_scatter`` forever, and the only resilience was bring-up retries
plus ``dist_fallback_serial`` BEFORE training starts.  At the scale the
distributed-GBDT literature assumes (arXiv:1804.06755 billions of
examples, PV-Tree 1611.01276) worker loss is routine; and because the
owner-shard reduce makes global histograms shard-count invariant
(PR 1; ``dp == serial`` bitwise on the int32 quantized path), GBDT can
uniquely **shrink the mesh and keep boosting deterministically**
instead of aborting.  Three layers:

**Liveness.**  :class:`Heartbeat` (a per-process thread stamping
``hb_<process>.json`` in a shared directory every
``elastic_heartbeat_interval_s``) + :class:`HeartbeatMonitor` (stale
mtime past ``elastic_heartbeat_timeout_s`` = the peer is gone), polled
once per boosting iteration from ``models/gbdt.py`` via
:func:`check_peers`.  A lost peer becomes a classified
:class:`ElasticFailure` — never a silent hang.

**Collective deadline.**  :func:`guarded_get` routes the training
loop's one per-iteration host fetch (the point where every queued
collective actually blocks — async dispatch means a hung ``psum``
materializes at the ``device_get``) through
``resilience.Watchdog(on_timeout="raise")``: past
``elastic_collective_timeout_s`` the wedged fetch is stack-dumped,
abandoned, and surfaced as ``ElasticFailure("collective_timeout")``.
The device claim gets the same treatment in
``GBDTModel._resolve_mesh`` (``claim_wedge``).

**Recovery ladder.**  :func:`elastic_train` wraps ``engine.train``
with snapshots + auto-resume and degrades rung by rung on classified
failures: full mesh -> shrunk mesh (devices halved, rows re-sharded,
``OwnerShardPlan`` re-derived by the dp grower for the new shard
count) -> serial — each failure episode bounded by
``elastic_recover_timeout_s`` with jittered-backoff retries, resuming
from the newest COMPLETE snapshot so at most one snapshot gap of
iterations is retrained.  Under multi-process training an in-process
shrink cannot rebuild ``jax.distributed`` around a dead peer, so the
ladder raises :class:`ElasticShrinkRequired` (after persisting the
failure record): the pod launcher — or the kill -9 subprocess test —
relaunches the survivors, and ``resume=true`` continues from the
snapshot's GLOBAL state (``GBDTModel.snapshot_state``).

Determinism contract: the shrink axis is ``tree_learner=data`` (or
serial); global histograms are shard-count invariant, so every rung
trains the SAME trees — bitwise on the int32 quantized-histogram path,
within float-reduction epsilons on the f32 path
(tests/test_zelastic.py).  ``voting``/``feature`` learners degrade
straight to serial (voting's per-shard top-k votes are
topology-dependent).  With ``elastic_enable=false`` (default) nothing
here is ever imported on the hot path and all training behavior is
byte-identical to before.

Observability: ``elastic.*`` metrics in a process-level registry
(:func:`metrics_snapshot`) — failures by kind, shrinks, recoveries,
recovery seconds, a mesh-size gauge — plus one JSONL event per
failure/recovery next to the model
(``<output_model>.elastic.jsonl``), recovery spans on the session
tracer when ``telemetry=true``, and a flight-recorder dump
(``obs/blackbox.dump_all``) at every classified failure.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..obs.metrics import MetricsRegistry

FAILURE_KINDS = ("collective_timeout", "host_loss", "claim_wedge",
                 "bringup", "ingest", "sdc")

# process-level elastic metrics: always-on and host-side only (a few
# counter bumps per failure — nothing per-iteration), so they need no
# telemetry gate; tools/soak_train.py and the serve /metrics-style
# consumers read them via metrics_snapshot()
_REGISTRY = MetricsRegistry()
_REGISTRY_LOCK = threading.Lock()


def metrics_snapshot() -> dict:
    """Deterministic dict snapshot of the ``elastic.*`` metrics."""
    return _REGISTRY.snapshot()


def reset_metrics() -> None:
    """Test hook: drop all ``elastic.*`` metric state."""
    global _REGISTRY
    with _REGISTRY_LOCK:
        _REGISTRY = MetricsRegistry()


def _metrics() -> MetricsRegistry:
    with _REGISTRY_LOCK:
        return _REGISTRY


class ElasticFailure(RuntimeError):
    """A classified mid-run distributed-training failure.

    ``kind`` is one of :data:`FAILURE_KINDS`.  The message carries the
    resilience classifier's retryable patterns (``unavailable``,
    ``deadline``, ``heartbeat``) so anything that re-enters
    ``retry_call`` treats it as transient."""

    def __init__(self, kind: str, detail: str = ""):
        assert kind in FAILURE_KINDS, kind
        self.kind = kind
        self.detail = detail
        super().__init__(
            f"elastic failure [{kind}]: "
            f"{detail or 'classified distributed-training failure'} "
            "(UNAVAILABLE: deadline/heartbeat)")


class ElasticShrinkRequired(RuntimeError):
    """Raised by :func:`elastic_train` under MULTI-PROCESS training when
    a peer is lost or a collective wedges: an in-process shrink cannot
    rebuild ``jax.distributed`` around a dead client, so the launcher
    must relaunch the survivors (``resume=true`` continues from the
    snapshot's global state).  Carries the classified kind, the
    survivor process indices the heartbeat directory still vouches
    for, and the wall seconds from the episode's first classified
    failure to the confirmed shrink request (which includes the one
    heartbeat-staleness window spent telling the dead from the
    living)."""

    def __init__(self, kind: str, survivors: List[int],
                 detect_s: float, detail: str = ""):
        self.kind = kind
        self.survivors = list(survivors)
        self.detect_s = float(detect_s)
        super().__init__(
            f"elastic shrink required [{kind}]: survivors="
            f"{self.survivors} detect_s={detect_s:.3f} {detail}")


def failure_kind(exc: BaseException) -> Optional[str]:
    """Classify an exception into a :data:`FAILURE_KINDS` entry, or
    None for errors the recovery ladder must NOT swallow (programming
    errors, data errors)."""
    from ..utils.resilience import (WatchdogTimeout,
                                    is_retryable_device_error)
    if isinstance(exc, ElasticFailure):
        return exc.kind
    if isinstance(exc, WatchdogTimeout):
        return "collective_timeout"
    if is_retryable_device_error(exc):
        return "bringup"
    return None


# ---------------------------------------------------------------------------
# Liveness: heartbeat writer + staleness monitor
# ---------------------------------------------------------------------------

def _hb_path(directory: str, process_index: int) -> str:
    return os.path.join(directory, f"hb_{process_index}.json")


class Heartbeat:
    """Per-process heartbeat writer thread.

    Stamps ``hb_<process_index>.json`` (temp + ``os.replace``, so a
    reader never sees a torn file) every ``interval_s`` into a shared
    directory; peers judge liveness by the file's mtime
    (:class:`HeartbeatMonitor`).  ``start``/``stop`` are idempotent.

    Lock contract (tools/analyze/check_races.py):
        _lock guards: _thread, beats
    """

    def __init__(self, directory: str, process_index: int,
                 interval_s: float = 1.0):
        self.directory = str(directory)
        self.process_index = int(process_index)
        self.interval_s = max(0.05, float(interval_s))
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self.beats = 0

    def _write(self) -> None:
        os.makedirs(self.directory, exist_ok=True)
        path = _hb_path(self.directory, self.process_index)
        tmp = f"{path}.{os.getpid()}.tmp"
        with self._lock:
            n = self.beats = self.beats + 1
        payload = json.dumps({"process_index": self.process_index,
                              "pid": os.getpid(), "seq": n,
                              "t": time.time()})
        # plain replace, NOT resilience.atomic_write: heartbeats must
        # keep flowing while fault-injection windows (snapshot_write)
        # are armed, and losing one beat to a crash is harmless
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(payload)
        os.replace(tmp, path)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self._write()
            except OSError:
                # a transiently unwritable shared dir must not kill the
                # writer — staleness is the monitor's job to call
                pass

    def start(self) -> "Heartbeat":
        with self._lock:
            if self._thread is not None:
                return self
            t = threading.Thread(target=self._run, daemon=True,
                                 name=f"elastic-hb-{self.process_index}")
            self._thread = t
        self._write()                   # first beat lands synchronously
        t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)


class HeartbeatMonitor:
    """Judge peer liveness from the heartbeat directory.

    A peer is REGISTERED the first time its ``hb_*.json`` looks alive
    and LOST once the monitor observes no mtime PROGRESS from it for
    ``timeout_s`` of its own monotonic clock.  Staleness is judged by
    observed change, not by ``now - mtime``: pod hosts (or an NFS
    server stamping the mtimes) can disagree with this host's
    wall clock by more than the deadline, and an absolute comparison
    would declare every healthy peer dead — or mask a real death —
    under that skew.  Absolute freshness is only a REGISTRATION fast
    path; an absolutely-stale file whose mtime is seen to advance
    registers too (a live peer behind skew), while one that never
    advances is a relic of a previous incarnation and names no peer.
    ``check()`` is called once per boosting iteration (models/gbdt.py)
    and rate-limits its own directory scan to half the heartbeat
    interval, so the per-iteration cost is usually one
    monotonic-clock read.

    Lock contract (tools/analyze/check_races.py):
        _lock guards: _peers, _cand, _last_scan
    """

    def __init__(self, directory: str, self_index: int,
                 timeout_s: float = 10.0, interval_s: float = 1.0):
        self.directory = str(directory)
        self.self_index = int(self_index)
        self.timeout_s = max(0.1, float(timeout_s))
        self.scan_every_s = max(0.02, float(interval_s) / 2.0)
        self._lock = threading.Lock()
        # index -> (last seen mtime, monotonic time of last PROGRESS)
        self._peers: Dict[int, Tuple[float, float]] = {}
        # unregistered relic candidates: index -> last seen mtime
        self._cand: Dict[int, float] = {}
        self._last_scan = 0.0

    def _scan(self) -> Tuple[List[int], List[int]]:
        """(fresh, lost) peer indices as of now."""
        now = time.time()
        mono = time.monotonic()
        seen: Dict[int, float] = {}
        try:
            names = os.listdir(self.directory)
        except OSError:
            names = []
        for name in names:
            if not (name.startswith("hb_") and name.endswith(".json")):
                continue
            try:
                idx = int(name[3:-5])
                mtime = os.stat(os.path.join(self.directory, name)).st_mtime
            except (ValueError, OSError):
                continue
            if idx != self.self_index:
                seen[idx] = mtime
        fresh, lost = [], []
        with self._lock:
            for idx, mtime in seen.items():
                if idx in self._peers:
                    if mtime != self._peers[idx][0]:
                        self._peers[idx] = (mtime, mono)   # progress
                elif now - mtime <= self.timeout_s:
                    # absolutely fresh: the no-skew registration path
                    self._peers[idx] = (mtime, mono)
                elif self._cand.get(idx, mtime) != mtime:
                    # ADVANCING despite an absolutely-stale mtime: a
                    # live peer behind cross-host clock skew
                    self._peers[idx] = (mtime, mono)
                else:
                    # a relic of a PREVIOUS incarnation (e.g. the peer
                    # this relaunch exists to replace): never fresh,
                    # never advancing — names no peer of ours
                    self._cand[idx] = mtime
            for idx, (_sig, t_prog) in sorted(self._peers.items()):
                if mono - t_prog > self.timeout_s:
                    lost.append(idx)
                else:
                    fresh.append(idx)
        return fresh, lost

    def peers(self) -> List[int]:
        with self._lock:
            return sorted(self._peers)

    def survivors(self) -> List[int]:
        fresh, _lost = self._scan()
        return sorted(fresh + [self.self_index])

    def check(self) -> None:
        """Raise ``ElasticFailure("host_loss")`` when any registered
        peer's heartbeat is stale past the deadline."""
        now = time.monotonic()
        with self._lock:
            if now - self._last_scan < self.scan_every_s:
                return
            self._last_scan = now
        _fresh, lost = self._scan()
        if lost:
            raise ElasticFailure(
                "host_loss",
                f"peer heartbeat(s) stale past {self.timeout_s:g}s: "
                f"process(es) {lost}")


# ---------------------------------------------------------------------------
# Process-wide elastic context (installed by elastic_train for gbdt.py)
# ---------------------------------------------------------------------------

class ElasticContext:
    """The ladder's per-run liveness bundle: heartbeat writer + monitor
    + the failure-event sink.  Installed process-wide for the duration
    of :func:`elastic_train` so the training loop's per-iteration
    :func:`check_peers` can reach the monitor without new plumbing
    through every learner.

    All attributes are frozen at construction; mutable state lives in
    the heartbeat/monitor objects behind their own locks
    (their classes declare the machine-checked contracts).
    """

    def __init__(self, heartbeat: Optional[Heartbeat],
                 monitor: Optional[HeartbeatMonitor],
                 events_path: str = ""):
        self.heartbeat = heartbeat
        self.monitor = monitor
        self.events_path = events_path

    def close(self) -> None:
        if self.heartbeat is not None:
            self.heartbeat.stop()


_ctx_lock = threading.Lock()
_ctx: Optional[ElasticContext] = None


def install(ctx: ElasticContext) -> None:
    global _ctx
    with _ctx_lock:
        _ctx = ctx


def uninstall(ctx: Optional[ElasticContext] = None) -> None:
    global _ctx
    with _ctx_lock:
        if ctx is None or _ctx is ctx:
            _ctx = None


def current() -> Optional[ElasticContext]:
    with _ctx_lock:
        return _ctx


# ---------------------------------------------------------------------------
# Suspect-device quarantine (lightgbm_tpu/integrity.py sticky SDC)
# ---------------------------------------------------------------------------
# Device ids attributed to a sticky silent-data-corruption failure.
# GBDTModel._resolve_mesh excludes them from the next claimed mesh and
# the ladder's "sdc" rung shrinks by exactly the suspect count (full
# mesh -> mesh-minus-suspects -> ... -> serial) instead of halving.
# Guarded by _suspect_lock; reads return an immutable copy.
_suspect_lock = threading.Lock()
_suspects: set = set()


def mark_suspect(device_ids) -> None:
    """Record devices attributed to a sticky SDC failure (quarantine)."""
    with _suspect_lock:
        for d in device_ids:
            _suspects.add(int(d))
        n = len(_suspects)
    _metrics().gauge("elastic.suspect_devices").set(n)


def suspected_devices() -> frozenset:
    """Immutable snapshot of the quarantined device ids."""
    with _suspect_lock:
        return frozenset(_suspects)


def clear_suspects() -> None:
    """Drop all quarantine state (fresh elastic_train run / tests)."""
    with _suspect_lock:
        _suspects.clear()
    _metrics().gauge("elastic.suspect_devices").set(0)


def sdc_shrunk(n: int) -> int:
    """Next data-parallel rung after a sticky-SDC failure: drop exactly
    the quarantined suspects (full mesh -> mesh-minus-suspects — the
    healthy chips keep their shards; ``GBDTModel._resolve_mesh`` picks
    WHICH ids go) and fall back to the ladder's usual halving when
    attribution produced no suspects (``integrity_policy`` raise/rewind,
    or a host-array divergence with no placement)."""
    sus = len(suspected_devices())
    if sus:
        return max(1, int(n) - sus)
    return max(1, int(n) // 2)


def _record_event(event: str, **fields) -> None:
    """One JSONL failure/recovery event + the elastic.* metric bump.
    Best-effort: observability must never turn a recoverable failure
    into an unrecoverable one."""
    reg = _metrics()
    if event in FAILURE_KINDS:
        reg.counter("elastic.failures", kind=event).inc()
    ctx = current()
    path = fields.pop("events_path", "") or \
        (ctx.events_path if ctx is not None else "")
    if not path:
        return
    rec = {"event": event, "t": round(time.time(), 3), **fields}
    try:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "a", encoding="utf-8") as f:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
    except OSError:
        pass


def check_peers() -> None:
    """Per-iteration liveness poll (models/gbdt.py calls this when
    ``elastic_enable``): the ``host_loss`` fault-injection site, then
    the installed monitor's staleness check.  No context installed =
    just the (usually disarmed) injection branch."""
    from ..utils import faultinject
    if faultinject.enabled() and faultinject.fires("host_loss"):
        fail = ElasticFailure("host_loss", "injected host loss")
        _on_failure(fail, site="faultinject")
        raise fail
    ctx = current()
    if ctx is not None and ctx.monitor is not None:
        try:
            ctx.monitor.check()
        except ElasticFailure as e:
            _on_failure(e, site="heartbeat")
            raise


def _on_failure(exc: ElasticFailure, site: str = "") -> None:
    """Classified-failure bookkeeping: metrics + JSONL + flight
    recorder.  Idempotence is the caller's job (each failure passes
    through here exactly once, where it is first classified)."""
    _record_event(exc.kind, site=site, detail=exc.detail)
    from ..obs import blackbox
    blackbox.dump_all(f"elastic_{exc.kind}")


def guarded_call(fn: Callable, timeout_s: float, site: str):
    """Run a blocking collective-backed call under the elastic
    deadline: past ``timeout_s`` the wedged call is stack-dumped,
    abandoned in its daemon worker, and re-raised in the caller as
    ``ElasticFailure("collective_timeout")``.  ``timeout_s <= 0`` runs
    plain.  Shared by :func:`guarded_get` (the per-iteration fetch) and
    the snapshot writer's multi-process allgather
    (``GBDTModel.snapshot_state``) — which would otherwise be an
    UNBOUNDED collective at every snapshot boundary, reopening exactly
    the hang class this module exists to close."""
    from ..utils.resilience import Watchdog, WatchdogTimeout
    if timeout_s <= 0:
        return fn()
    try:
        return Watchdog(timeout_s, label=f"collective:{site}",
                        on_timeout="raise").run(fn)
    except WatchdogTimeout as e:
        fail = ElasticFailure("collective_timeout", f"{site}: {e}")
        _on_failure(fail, site=site)
        raise fail from e


def guarded_get(x, timeout_s: float, site: str = "fetch"):
    """``jax.device_get(x)`` under the elastic collective deadline.

    The training loop's host fetch is where every queued collective
    actually blocks (async dispatch), so bounding it bounds the
    collectives.  Hosts the ``collective_hang`` fault-injection site.
    ``timeout_s <= 0`` is a plain fetch."""
    import jax

    from ..utils import faultinject

    def _fetch():
        faultinject.check("collective_hang")
        return jax.device_get(x)

    if timeout_s <= 0:
        return _fetch()
    return guarded_call(_fetch, timeout_s, site)


# ---------------------------------------------------------------------------
# Recovery ladder
# ---------------------------------------------------------------------------

def _truthy(v) -> bool:
    return str(v).strip().lower() not in ("", "0", "false", "none", "no")


def _requested_devices(cfg) -> Optional[int]:
    """The rung-0 mesh width implied by the config, or None for
    'all visible devices' (resolved lazily after the first claim)."""
    if cfg.mesh_shape:
        return int(np.prod(cfg.mesh_shape))
    if cfg.num_machines > 1:
        return int(cfg.num_machines)
    return None


def elastic_train(params: dict, x, y=None, *, weight=None,
                  num_boost_round: int = 100, bin_mappers=None,
                  callbacks: Optional[list] = None,
                  valid: Optional[tuple] = None):
    """Train with the shrink-to-survive recovery ladder.

    ``x``/``y`` are the FULL (global) arrays — the ladder re-shards
    them for whatever topology each rung uses, which is what makes a
    shrunk mesh able to carry the dead shard's rows.  Callers that
    must not materialize the full data per host should pass
    ``bin_mappers`` fitted once (e.g. the distributed quantile sketch,
    ``parallel/dist_data.py``) so binning stays topology-independent;
    by default the mappers are fitted on the full data exactly like a
    serial run, which is what makes the final model byte-comparable to
    one.

    Returns the trained Booster with an ``elastic_report`` attribute:
    ``{"attempts", "shrinks", "recoveries", "failures": [...],
    "rungs": [...]}``.  Raises :class:`ElasticShrinkRequired` under
    multi-process training when the pod must be relaunched smaller,
    and re-raises unclassified (non-transient) errors unchanged.
    """
    import jax

    from .. import engine
    from ..config import Config, canonical_params
    from ..dataset import Dataset

    base = dict(canonical_params(dict(params or {})))
    base["elastic_enable"] = True
    base.setdefault("resume", True)
    cfg0 = Config(dict(base))
    if cfg0.snapshot_freq <= 0:
        # recovery loses at most one snapshot gap of iterations —
        # without a user cadence, default to ~10 gaps per run
        base["snapshot_freq"] = max(1, int(num_boost_round) // 10 or 1)
        cfg0 = Config(dict(base))
    retries = max(0, int(cfg0.elastic_retries))
    recover_budget = float(cfg0.elastic_recover_timeout_s)

    pc = jax.process_count()
    reg = _metrics()
    tracer = None
    if cfg0.telemetry:
        from ..obs.trace import Tracer
        tracer = Tracer(sink_path=(cfg0.telemetry_trace_file + ".elastic")
                        if cfg0.telemetry_trace_file else None)

    heartbeat = monitor = None
    if cfg0.elastic_heartbeat_dir:
        heartbeat = Heartbeat(cfg0.elastic_heartbeat_dir,
                              jax.process_index(),
                              cfg0.elastic_heartbeat_interval_s).start()
        monitor = HeartbeatMonitor(cfg0.elastic_heartbeat_dir,
                                   jax.process_index(),
                                   cfg0.elastic_heartbeat_timeout_s,
                                   cfg0.elastic_heartbeat_interval_s)
    ctx = ElasticContext(heartbeat, monitor,
                         events_path=cfg0.output_model + ".elastic.jsonl")
    install(ctx)
    # quarantine state is per-run: a fresh ladder starts trusting every
    # device again (suspects re-earn their place or re-fail the check)
    clear_suspects()

    report = {"attempts": 0, "shrinks": 0, "recoveries": 0,
              "failures": [], "rungs": []}

    def _topo_params(topo: Optional[int]) -> dict:
        pp = dict(base)
        if topo is None:
            return pp
        if topo <= 1:
            pp["tree_learner"] = "serial"
            pp["num_machines"] = 1
            pp.pop("mesh_shape", None)
        else:
            pp["tree_learner"] = "data" \
                if cfg0.tree_learner in ("data", "serial") \
                else cfg0.tree_learner
            pp["mesh_shape"] = [int(topo)]
            pp.pop("num_machines", None)
        return pp

    mcache = {"mappers": bin_mappers}

    def _dataset(pp: dict):
        if pc > 1:
            from . import launch
            from ..dataset import fingerprint_arrays
            shard = launch.row_shard(x, y, weight=weight)
            if mcache["mappers"] is None:
                # full-data binning on every host: identical mappers
                # everywhere AND identical to a serial run over the
                # concatenated rows — the byte-parity anchor across
                # topologies (docstring tradeoff note).  Fitted ONCE
                # per elastic_train: the mappers are a pure function of
                # (x, params), so ladder retries must not re-pay the
                # global binning inside the recovery budget
                full = Dataset(x, label=y, params=dict(pp))
                full.construct(Config(dict(pp)))
                mcache["mappers"] = full.bin_mappers
            ds = Dataset(shard.x, label=shard.y, weight=shard.weight,
                         params=dict(pp), bin_mappers=mcache["mappers"])
            # elastic multi-process snapshots carry GLOBAL state
            # (GBDTModel.snapshot_state): hand the resume path the
            # global fingerprint (to match the manifest against this
            # process's SHARD dataset) and this shard's global row
            # range (to slice the global score back to local rows) —
            # without these, a survivors>1 relaunch would silently
            # restart from iteration 0 on a fingerprint mismatch
            ds.elastic_global_fingerprint = fingerprint_arrays(y, weight)
            ds.elastic_row_range = (shard.row_start, shard.row_stop)
            return ds
        return Dataset(x, label=y, weight=weight, params=dict(pp),
                       bin_mappers=mcache["mappers"])

    def _shrunk(topo: Optional[int], kind: Optional[str] = None) -> int:
        if cfg0.tree_learner != "data":
            # voting's per-shard top-k votes are topology-dependent and
            # a serial-learner run has no mesh to shrink — the only
            # rung below the requested one is serial for both
            return 1
        n = topo
        if n is None:
            try:
                n = len(jax.local_devices()) if pc > 1 else \
                    len(jax.devices())
            except Exception:   # noqa: BLE001 — a wedged claim: go serial
                return 1
            req = _requested_devices(cfg0)
            if req is not None:
                n = min(n, req)
        if kind == "sdc":
            return sdc_shrunk(n)
        return max(1, int(n) // 2)

    topo: Optional[int] = None       # None = as requested (rung 0)
    episode_t0: Optional[float] = None
    rung_attempts = 0

    try:
        while True:
            report["attempts"] += 1
            report["rungs"].append(1 if topo == 1 else
                                   (topo or "requested"))
            reg.gauge("elastic.mesh_devices").set(float(topo or 0))
            reg.counter("elastic.attempts").inc()
            pp = _topo_params(topo)
            span = tracer.span("elastic_attempt", topo=str(topo)) \
                if tracer is not None else None
            try:
                ds = _dataset(pp)
                bst = engine.train(pp, ds,
                                   num_boost_round=int(num_boost_round),
                                   callbacks=list(callbacks or []) or None,
                                   valid_sets=None if valid is None else
                                   [Dataset(valid[0], label=valid[1],
                                            params=dict(pp),
                                            reference=ds)])
            except BaseException as e:   # noqa: BLE001 — classified below
                if span is not None:
                    span.args["outcome"] = type(e).__name__
                    span.end()
                kind = failure_kind(e)
                if kind is None:
                    raise
                if not isinstance(e, ElasticFailure):
                    # first classification of a raw transient error
                    _on_failure(ElasticFailure(kind, str(e)[:200]),
                                site="ladder")
                now = time.monotonic()
                if episode_t0 is None:
                    episode_t0 = now
                report["failures"].append(
                    {"kind": kind, "topo": topo or "requested"})
                _record_event("ladder_failure", kind=kind,
                              topo=str(topo or "requested"),
                              detail=str(e)[:300])
                if pc > 1:
                    if monitor is not None:
                        # a peer killed an instant ago still has a
                        # fresh heartbeat file; only after one full
                        # staleness window does the directory tell the
                        # dead from the living
                        time.sleep(monitor.timeout_s)
                        survivors = monitor.survivors()
                    else:
                        survivors = [jax.process_index()]
                    # classification -> confirmed shrink request,
                    # including the one-staleness-window survivor
                    # confirmation above (episode_t0 stamps the first
                    # classified failure of this episode)
                    detect_s = time.monotonic() - episode_t0
                    _record_event("shrink_required", kind=kind,
                                  survivors=survivors,
                                  detect_s=round(detect_s, 3))
                    raise ElasticShrinkRequired(
                        kind, survivors, detect_s, str(e)[:200]) from e
                if recover_budget > 0 and \
                        now - episode_t0 > recover_budget:
                    from ..utils.log import Log
                    Log.warning(
                        f"elastic: recovery budget "
                        f"({recover_budget:g}s) exhausted; giving up")
                    raise
                rung_attempts += 1
                # host_loss and sticky SDC shrink immediately: retrying
                # the same topology re-runs on the dead/suspect device
                if kind in ("host_loss", "sdc") or rung_attempts > retries:
                    new_topo = _shrunk(topo, kind)
                    if topo is not None and new_topo >= topo:
                        raise     # serial rung failed: ladder exhausted
                    topo = new_topo
                    rung_attempts = 0
                    report["shrinks"] += 1
                    reg.counter("elastic.shrinks").inc()
                    _record_event("shrink", to_devices=topo, kind=kind)
                    from ..utils.log import Log
                    Log.warning(
                        f"elastic: shrinking to "
                        f"{'serial' if topo <= 1 else f'{topo} devices'} "
                        f"after [{kind}] and resuming from the newest "
                        "snapshot")
                # jittered backoff before the next attempt
                delay = min(2.0, 0.1 * (2 ** len(report["failures"])))
                time.sleep(delay * (0.75 + 0.5 * random.random()))
                continue
            if span is not None:
                span.args["outcome"] = "ok"
                span.end()
            if episode_t0 is not None:
                rec_s = time.monotonic() - episode_t0
                report["recoveries"] += 1
                reg.counter("elastic.recoveries").inc()
                reg.histogram("elastic.recovery_seconds").observe(rec_s)
                _record_event("recovered", seconds=round(rec_s, 3),
                              topo=str(topo or "requested"))
            bst.elastic_report = report
            return bst
    finally:
        uninstall(ctx)
        ctx.close()
        if tracer is not None:
            tracer.flush()
